"""Concurrency stress on the serving engine — the `-race`-style tier the
reference never had (SURVEY §5.2: its CI doesn't even run -race). Storm the
engine with concurrent submits, cancellations, timeouts, and a mid-traffic
stop; the invariants are: no deadlock, every request completes exactly once
(result or error), and non-cancelled greedy results stay token-exact."""

import random
import threading
import time

import jax
import jax.numpy as jnp
import pytest

from gofr_tpu.container import new_mock_container
from gofr_tpu.models import LlamaConfig, llama
from gofr_tpu.testutil import assert_paged_pool_consistent
from gofr_tpu.tpu.engine import GenerateEngine

# integration tier (CI `integration` job): multi-minute engine/process
# runs — excluded from the tier-1 gate via -m 'not slow' (docs/testing.md)
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def setup():
    cfg = LlamaConfig.tiny()
    params = llama.init(cfg, jax.random.key(7))

    def ref(prompt, n):
        seq = list(prompt)
        for _ in range(n):
            logits = llama.forward(cfg, params, jnp.asarray([seq], jnp.int32))
            seq.append(int(jnp.argmax(logits[0, -1])))
        return seq[len(prompt):]

    return cfg, params, ref


@pytest.mark.parametrize("kv_layout", ["slot", "paged"])
def test_submit_cancel_storm(setup, kv_layout):
    cfg, params, ref = setup
    kw = dict(slots=4, max_len=64, max_prefill_batch=2)
    if kv_layout == "paged":
        kw.update(kv_layout="paged", page_size=8, total_pages=20)
    eng = GenerateEngine(llama, cfg, params, new_mock_container(), **kw)
    rng = random.Random(0)
    n_req = 24
    prompts = [[rng.randrange(1, 200) for _ in range(rng.randrange(2, 6))]
               for _ in range(n_req)]
    want = {i: ref(p, 6) for i, p in enumerate(prompts)}
    outcomes: dict[int, object] = {}
    lock = threading.Lock()

    def client(i):
        req = eng.submit(prompts[i], max_new_tokens=6, timeout=120)
        if i % 5 == 0:
            time.sleep(rng.random() * 0.02)
            req.cancel()
        try:
            res = req.result(120)
        except Exception as e:  # noqa: BLE001
            res = e
        with lock:
            assert i not in outcomes, f"request {i} completed twice"
            outcomes[i] = res

    try:
        threads = [threading.Thread(target=client, args=(i,)) for i in range(n_req)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        assert len(outcomes) == n_req, "a request never completed (deadlock?)"
        for i, res in outcomes.items():
            if isinstance(res, dict):
                assert res["tokens"] == want[i], f"request {i} diverged under storm"
            else:
                assert i % 5 == 0, f"non-cancelled request {i} failed: {res}"
        if kv_layout == "paged":
            assert_paged_pool_consistent(eng, slots_empty=True)
    finally:
        eng.stop()


def test_stop_mid_traffic_fails_everything_and_frees_state(setup):
    cfg, params, _ = setup
    eng = GenerateEngine(llama, cfg, params, new_mock_container(),
                         slots=2, max_len=64, max_prefill_batch=2,
                         kv_layout="paged", page_size=8)
    reqs = [eng.submit([i + 1, i + 2], max_new_tokens=40, timeout=120)
            for i in range(12)]
    # gate on observed in-flight state, not a fixed sleep (fast machines
    # could otherwise finish everything before stop and flake the premise)
    deadline = time.time() + 10
    while time.time() < deadline and all(s is None for s in eng.slots):
        time.sleep(0.01)
    assert any(s is not None for s in eng.slots), "requests never admitted"
    eng.stop()
    finished = errored = hung = 0
    for r in reqs:
        try:
            r.result(10)
            finished += 1
        except Exception:  # noqa: BLE001
            # r._done distinguishes "engine completed it with an error"
            # from "result() wait timed out" — the latter is a real hang
            if r._done.is_set():
                errored += 1
            else:
                hung += 1
    assert hung == 0, f"{hung} request(s) hung across stop()"
    assert errored > 0, "stop() during load completed everything — premise broken"
    assert_paged_pool_consistent(eng, slots_empty=True)
    assert all(s is None for s in eng.slots)
