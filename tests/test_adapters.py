"""Multi-LoRA adapter multiplexing + live weight hot-swap (tentpole).

The load-bearing contracts, in order of how expensive they'd be to get
wrong in production:

1. ``adapter_id=None`` is TOKEN-EXACT against a pre-adapter engine — on
   both KV layouts and with spec decode on and off. Slot 0 of the device
   pool is the reserved all-zeros base adapter, so the base lane's logits
   delta is exactly 0.0 (ops/lora.py), not merely small.
2. A mixed-adapter batch is token-exact per request against each adapter
   served in isolation: the lm_head LoRA gather is lane-independent, so
   co-batching ≥3 adapters changes scheduling, never tokens.
3. The live hot-swap drill: adopt a full replacement weight tree under
   in-flight traffic with ZERO dropped or mis-answered requests, a
   strictly bumped weights epoch, and a strictly bumped router-gossip
   epoch (fleet.epoch_of).
4. Adapter-pool eviction under load never corrupts the KV page pool
   (assert_page_refs_consistent) — the two refcounted pools are disjoint.
"""

import threading
import time

import jax
import numpy as np
import pytest

from gofr_tpu.adapters import (
    AdapterPool,
    AdapterRegistry,
    AdapterSpec,
    random_adapter,
)
from gofr_tpu.container import new_mock_container
from gofr_tpu.http.errors import TooManyRequests
from gofr_tpu.models import LlamaConfig, llama
from gofr_tpu.testutil import (
    assert_page_refs_consistent,
    assert_paged_pool_consistent,
)
from gofr_tpu.tpu.engine import GenerateEngine

pytestmark = pytest.mark.quick


@pytest.fixture(scope="module")
def setup():
    cfg = LlamaConfig.tiny()
    params = llama.init(cfg, jax.random.key(7))
    return cfg, params


def make_engine(cfg, params, **kw):
    kw.setdefault("slots", 4)
    kw.setdefault("max_len", 64)
    kw.setdefault("max_prefill_batch", 2)
    return GenerateEngine(llama, cfg, params, new_mock_container(), **kw)


def adapters_for(cfg, n=3):
    return [random_adapter(f"ad{i}", cfg.hidden_size, cfg.vocab_size,
                           rank=2 + 2 * i, seed=10 + i)
            for i in range(n)]


PROMPTS = [[1, 2, 3, 4], [9, 8, 7], [5, 5, 5, 5, 5], [2, 4, 6, 8, 10, 12]]


# -- host/device tier units ----------------------------------------------------


class TestRegistryAndPool:
    def test_register_get_unregister_digest(self, setup):
        cfg, _ = setup
        reg = AdapterRegistry(host_budget_mb=64)
        a, b, c = adapters_for(cfg, 3)
        for s in (a, b, c):
            reg.register(s)
        assert reg.names() == ["ad0", "ad1", "ad2"]
        assert reg.get("ad1").rank == b.rank
        # order-independent digest: same set registered in another order
        reg2 = AdapterRegistry(host_budget_mb=64)
        for s in (c, a, b):
            reg2.register(s)
        assert reg.digest() == reg2.digest()
        reg.unregister("ad1")
        assert reg.digest() != reg2.digest()
        with pytest.raises(KeyError):
            reg.get("ad1")

    def test_host_budget_never_evicts(self, setup):
        cfg, _ = setup
        reg = AdapterRegistry(host_budget_mb=0.001)  # ~1 KiB
        with pytest.raises(ValueError, match="ADAPTER_HOST_MB"):
            reg.register(adapters_for(cfg, 1)[0])
        assert reg.names() == []

    def test_per_adapter_concurrency_cap(self, setup):
        cfg, _ = setup
        reg = AdapterRegistry()
        spec = random_adapter("capped", cfg.hidden_size, cfg.vocab_size,
                              max_concurrency=2)
        reg.register(spec)
        reg.admit("capped")
        reg.admit("capped")
        with pytest.raises(TooManyRequests):
            reg.admit("capped")
        reg.release("capped")
        reg.admit("capped")  # a release frees a share

    def test_pool_refcounted_lru(self, setup):
        cfg, _ = setup
        specs = adapters_for(cfg, 3)
        pool = AdapterPool(3, cfg.hidden_size, cfg.vocab_size, rank=8)
        s0 = pool.acquire(specs[0])
        s1 = pool.acquire(specs[1])
        assert s0 != s1 and 0 not in (s0, s1)  # slot 0 = reserved base
        assert pool.acquire(specs[0]) == s0    # resident hit, refcount 2
        # both referenced, 3 slots = base + 2 -> third adapter must wait
        assert pool.acquire(specs[2]) is None
        pool.release(s1)
        s2 = pool.acquire(specs[2])            # evicts the unreferenced LRU
        assert s2 == s1
        assert pool.evictions == 1
        pool.release(s0)
        pool.release(s0)
        pool.release(s2)

    def test_slots_for_budget(self, setup):
        cfg, _ = setup
        per = 4 * (cfg.hidden_size * 8 + 8 * cfg.vocab_size)
        n = AdapterPool.slots_for_budget(per * 5 / (1 << 20),
                                         cfg.hidden_size, cfg.vocab_size, 8)
        assert n == 5
        # floor of 2: slot 0 (base) + at least one real adapter
        assert AdapterPool.slots_for_budget(0.0000001, cfg.hidden_size,
                                            cfg.vocab_size, 8) == 2

    def test_zero_padded_rank_upload_exact(self, setup):
        """A rank-r adapter in a rank-R pool (r < R) computes the exact
        rank-r delta: the padded tail rows/cols are zero."""
        cfg, _ = setup
        from gofr_tpu.ops.lora import lora_logits_delta
        import jax.numpy as jnp

        spec = adapters_for(cfg, 1)[0]  # rank 2
        pool = AdapterPool(2, cfg.hidden_size, cfg.vocab_size, rank=8)
        slot = pool.acquire(spec)
        x = jnp.asarray(np.random.default_rng(0).standard_normal(
            (3, cfg.hidden_size)), jnp.float32)
        sel = jnp.asarray([slot] * 3, jnp.int32)
        got = np.asarray(lora_logits_delta(
            x, (sel, pool.a, pool.b, pool.scale)))
        want = (np.asarray(x) @ spec.a @ spec.b) * spec.scale
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)
        # and the base slot's delta is EXACTLY zero, not epsilon
        base = np.asarray(lora_logits_delta(
            x, (jnp.zeros((3,), jnp.int32), pool.a, pool.b, pool.scale)))
        assert not base.any()


# -- bit-exactness of the adapter_id=None path ---------------------------------


@pytest.fixture(scope="module")
def base_tokens(setup):
    """Reference tokens from PRE-adapter engines, one per KV layout.
    Spec decode is token-exact vs non-spec by its own contract
    (tests/test_spec_decode.py), so the non-spec reference also judges
    the spec-enabled adapter engines below."""
    cfg, params = setup
    out = {}
    for layout, kw in (("slot", dict(kv_layout="slot")),
                       ("paged", dict(kv_layout="paged", page_size=8))):
        ref_eng = make_engine(cfg, params, **kw)
        ref_eng.start()
        try:
            out[layout] = [ref_eng.generate(p, max_new_tokens=8)["tokens"]
                           for p in PROMPTS]
        finally:
            ref_eng.stop()
    return out


class TestBaseExactness:
    @pytest.mark.parametrize("kw", [
        dict(kv_layout="slot"),
        dict(kv_layout="paged", page_size=8),
        dict(kv_layout="slot", spec_tokens=2, decode_chunk=2),
        dict(kv_layout="paged", page_size=8, spec_tokens=2, decode_chunk=2),
    ], ids=["slot", "paged", "slot-spec", "paged-spec"])
    def test_none_lane_token_exact(self, setup, base_tokens, kw):
        """adapter_id=None through an adapter-enabled engine produces the
        exact tokens of a pre-adapter engine — both layouts, spec on/off."""
        cfg, params = setup
        want = base_tokens[kw["kv_layout"]]

        eng = make_engine(cfg, params, adapter_slots=3, adapter_rank=8, **kw)
        eng.start()
        try:
            # a registered (and exercised) adapter must not disturb base lanes
            eng.register_adapter(adapters_for(cfg, 1)[0])
            eng.generate(PROMPTS[0], max_new_tokens=4, adapter_id="ad0")
            got = [eng.generate(p, max_new_tokens=8)["tokens"]
                   for p in PROMPTS]
        finally:
            eng.stop()
        assert got == want

    def test_unknown_adapter_rejected_at_submit(self, setup):
        cfg, params = setup
        eng = make_engine(cfg, params, adapter_slots=2)
        eng.start()
        try:
            with pytest.raises(ValueError, match="unknown adapter"):
                eng.generate(PROMPTS[0], max_new_tokens=4, adapter_id="nope")
        finally:
            eng.stop()

    def test_adapter_without_plane_rejected(self, setup):
        cfg, params = setup
        eng = make_engine(cfg, params)
        eng.start()
        try:
            with pytest.raises(ValueError, match="adapter plane"):
                eng.generate(PROMPTS[0], max_new_tokens=4, adapter_id="x")
        finally:
            eng.stop()


# -- batched mixed-adapter decode ----------------------------------------------


class TestMixedBatch:
    @pytest.mark.parametrize("kw", [
        dict(kv_layout="slot"),
        dict(kv_layout="paged", page_size=8),
    ], ids=["slot", "paged"])
    def test_mixed_batch_matches_isolation(self, setup, kw):
        """≥3 adapters co-batched in one engine: every request's tokens
        equal the same request served on an engine holding only its
        adapter. One device call serves many adapters, token-exactly."""
        cfg, params = setup
        specs = adapters_for(cfg, 3)
        jobs = [(p, specs[i % 3].name) for i, p in enumerate(PROMPTS * 2)]

        # isolation arm: ONE engine, one adapter registered at a time —
        # each request is served with no other adapter in the batch
        isolated = {}
        eng = make_engine(cfg, params, adapter_slots=2, adapter_rank=8, **kw)
        eng.start()
        try:
            for spec in specs:
                eng.register_adapter(spec)
                for p, name in jobs:
                    if name == spec.name:
                        isolated[(tuple(p), name)] = eng.generate(
                            p, max_new_tokens=8, adapter_id=name)["tokens"]
                eng.unregister_adapter(spec.name)
        finally:
            eng.stop()

        eng = make_engine(cfg, params, adapter_slots=4, adapter_rank=8, **kw)
        eng.start()
        try:
            for spec in specs:
                eng.register_adapter(spec)
            reqs = [eng.submit(p, max_new_tokens=8, adapter_id=name)
                    for p, name in jobs]
            got = [r.result(60.0)["tokens"] for r in reqs]
        finally:
            eng.stop()
        for (p, name), tokens in zip(jobs, got):
            assert tokens == isolated[(tuple(p), name)], (p, name)
        # distinct adapters actually produce distinct streams on the
        # shared prompt (the multiplexing isn't vacuously the base model)
        by_adapter = {name: tokens for (p, name), tokens
                      in zip(jobs, got) if p == PROMPTS[0]}
        assert len(set(map(tuple, by_adapter.values()))) > 1 or len(by_adapter) <= 1

    def test_pool_exhaustion_requeues_not_fails(self, setup):
        """More simultaneous adapters than device pool slots: the surplus
        request WAITS for a slot (like KV page exhaustion) and completes
        once one frees — never an error, never the wrong adapter."""
        cfg, params = setup
        specs = adapters_for(cfg, 3)
        # pool of 3 = base + 2 real: the third adapter must wait its turn
        eng = make_engine(cfg, params, adapter_slots=3, adapter_rank=8)
        eng.start()
        try:
            for spec in specs:
                eng.register_adapter(spec)
            reqs = [eng.submit(PROMPTS[i % len(PROMPTS)], max_new_tokens=6,
                               adapter_id=specs[i % 3].name)
                    for i in range(9)]
            outs = [r.result(60.0) for r in reqs]
            assert all(o["finish_reason"] == "length" for o in outs)
            stats = eng.adapter_stats()
            assert stats["pool"]["evictions"] >= 1  # slots actually cycled
        finally:
            eng.stop()


# -- per-adapter attribution ---------------------------------------------------


class TestAttribution:
    def test_flight_recorder_carries_adapter_and_epoch(self, setup):
        cfg, params = setup
        eng = make_engine(cfg, params, adapter_slots=2, adapter_rank=8)
        eng.start()
        try:
            eng.register_adapter(adapters_for(cfg, 1)[0])
            eng.generate(PROMPTS[0], max_new_tokens=4, adapter_id="ad0")
            eng.generate(PROMPTS[1], max_new_tokens=4)
            entries = eng.flight.requests(limit=2)
            by_adapter = {e.get("adapter"): e for e in entries}
            assert "ad0" in by_adapter
            assert by_adapter["ad0"]["weights_epoch"] == 0
            assert by_adapter.get(None, {}).get("adapter") is None
        finally:
            eng.stop()

    def test_perf_plane_partitions_by_adapter(self, setup):
        """Adapter rows are an exact partition of the step totals, and
        device-seconds accrue to the adapters that were actually served
        (the per-tenant COGS meter)."""
        cfg, params = setup
        eng = make_engine(cfg, params, adapter_slots=3, adapter_rank=8)
        if eng.perf is None:
            pytest.skip("no perf plane on this container")
        eng.start()
        try:
            eng.register_adapter(adapters_for(cfg, 1)[0])
            eng.generate(PROMPTS[0], max_new_tokens=6, adapter_id="ad0")
            eng.generate(PROMPTS[1], max_new_tokens=6)
            totals = eng.perf.window_totals(time.monotonic())
            ads = totals["adapters"]
            assert "ad0" in ads and "base" in ads
            assert ads["ad0"]["device_s"] > 0
            # exact partition: adapter rows sum to the kind rows
            for field in ("flops", "bytes", "device_s"):
                part = sum(rec[field] for rec in ads.values())
                whole = sum(rec[field] for rec in totals["kinds"].values())
                assert part == pytest.approx(whole, rel=1e-9)
        finally:
            eng.stop()


# -- live weight hot-swap ------------------------------------------------------


class TestHotSwap:
    def test_swap_is_tokenwise_real_and_reversible(self, setup):
        cfg, params = setup
        params2 = llama.init(cfg, jax.random.key(99))
        eng = make_engine(cfg, params, adapter_slots=2, adapter_rank=8)
        eng.start()
        try:
            base = eng.generate(PROMPTS[0], max_new_tokens=8)["tokens"]
            assert eng.adopt_weights(params2) == 1
            swapped = eng.generate(PROMPTS[0], max_new_tokens=8)["tokens"]
            assert swapped != base  # genuinely new weights
            assert eng.adopt_weights(params) == 2
            back = eng.generate(PROMPTS[0], max_new_tokens=8)["tokens"]
            assert back == base     # and exactly restorable
        finally:
            eng.stop()

    def test_swap_rejects_mismatched_tree(self, setup):
        cfg, params = setup
        eng = make_engine(cfg, params)
        bad = llama.init(LlamaConfig.tiny(num_layers=1), jax.random.key(0))
        with pytest.raises(ValueError, match="adopt_weights"):
            eng.adopt_weights(bad)
        eng.stop()

    def test_hot_swap_drill_zero_drop(self, setup):
        """The acceptance drill: swap under live traffic. Every in-flight
        and queued request completes (no drops, no errors); requests are
        answered by exactly one weight tree or requeued whole onto the new
        one (never mixed — asserted as: every answer is a full-length
        generation and the engine epoch/gossip epoch strictly bumped)."""
        from gofr_tpu.fleet import epoch_of

        cfg, params = setup
        params2 = llama.init(cfg, jax.random.key(99))
        eng = make_engine(cfg, params, adapter_slots=3, adapter_rank=8,
                          kv_layout="paged", page_size=8, slots=4)
        eng.start()
        results, errors = [], []
        stop_feed = threading.Event()

        def feeder():
            i = 0
            while not stop_feed.is_set():
                p = PROMPTS[i % len(PROMPTS)]
                try:
                    out = eng.generate(p, max_new_tokens=6, timeout=30.0)
                    results.append(out)
                except Exception as e:  # noqa: BLE001 - the drill counts every failure
                    errors.append(e)
                i += 1

        threads = [threading.Thread(target=feeder) for _ in range(3)]
        try:
            epoch0 = epoch_of(eng)
            for t in threads:
                t.start()
            time.sleep(0.3)  # traffic in flight
            new_epoch = eng.adopt_weights(params2, timeout_s=30.0)
            time.sleep(0.3)  # traffic continues on the new weights
            stop_feed.set()
            for t in threads:
                t.join(timeout=30.0)
            assert not errors, errors
            assert results
            # zero-drop: every answer is a complete 6-token generation
            assert all(len(r["tokens"]) == 6 for r in results)
            assert all(r["finish_reason"] == "length" for r in results)
            assert new_epoch == 1 and eng.weights_epoch == 1
            # the router's gossip epoch strictly bumped with the adoption
            assert epoch_of(eng) > epoch0
            assert_paged_pool_consistent(eng)
        finally:
            stop_feed.set()
            eng.stop()

    def test_checkpoint_adoption(self, setup, tmp_path):
        from gofr_tpu.train.checkpoint import save_params

        cfg, params = setup
        params2 = llama.init(cfg, jax.random.key(42))
        save_params(str(tmp_path / "ckpt"), params2)
        eng = make_engine(cfg, params)
        eng.start()
        try:
            direct = None
            eng.adopt_weights(params2)
            direct = eng.generate(PROMPTS[0], max_new_tokens=8)["tokens"]
            eng.adopt_weights(params)
            eng.adopt_checkpoint(str(tmp_path / "ckpt"))
            via_ckpt = eng.generate(PROMPTS[0], max_new_tokens=8)["tokens"]
            assert via_ckpt == direct
        finally:
            eng.stop()

    def test_lockstep_rejects_hot_swap(self, setup):
        cfg, params = setup
        eng = GenerateEngine(llama, cfg, params, new_mock_container(),
                             slots=2, max_len=64, lockstep_role="leader")
        with pytest.raises(RuntimeError, match="lockstep"):
            eng.adopt_weights(params)


# -- adapter cache eviction vs KV page pool ------------------------------------


class TestEvictionDrill:
    def test_page_refs_consistent_after_adapter_churn(self, setup):
        """Adapter-pool eviction under paged load: cycling many adapters
        through a tiny device pool churns uploads/evictions while KV pages
        allocate and free — the two refcounted pools must stay disjoint
        and the page pool exactly consistent afterwards."""
        cfg, params = setup
        specs = adapters_for(cfg, 5)
        eng = make_engine(cfg, params, adapter_slots=3, adapter_rank=12,
                          kv_layout="paged", page_size=8, slots=4)
        eng.start()
        try:
            for spec in specs:
                eng.register_adapter(spec)
            reqs = [eng.submit(PROMPTS[i % len(PROMPTS)], max_new_tokens=5,
                               adapter_id=specs[i % 5].name)
                    for i in range(15)]
            for r in reqs:
                assert r.result(60.0)["finish_reason"] == "length"
            stats = eng.adapter_stats()
            assert stats["pool"]["evictions"] >= 1
            assert_page_refs_consistent(eng)
            assert_paged_pool_consistent(eng)
            # all pool references drained with the traffic
            assert stats["pool"]["referenced"] == 0
        finally:
            eng.stop()


# -- config / build_engine wiring ----------------------------------------------


class TestBuildWiring:
    def test_adapter_pool_mb_derives_slots(self, setup):
        cfg, params = setup
        per = 4 * (cfg.hidden_size * 16 + 16 * cfg.vocab_size)
        eng = make_engine(cfg, params,
                          adapter_pool_mb=per * 4 / (1 << 20))
        try:
            assert eng._adapters_enabled
            assert eng._adapter_pool.slots == 4
        finally:
            eng.stop()

    def test_lockstep_disables_adapter_plane(self, setup):
        cfg, params = setup
        container = new_mock_container()
        eng = GenerateEngine(llama, cfg, params, container, slots=2,
                             max_len=64, adapter_slots=4,
                             lockstep_role="leader")
        assert not eng._adapters_enabled
        assert any("ADAPTER_* ignored under lockstep" in line
                   for line in container.logger.lines)

    def test_rank_above_pool_rejected(self, setup):
        cfg, params = setup
        eng = make_engine(cfg, params, adapter_slots=2, adapter_rank=4)
        try:
            with pytest.raises(ValueError, match="rank"):
                eng.register_adapter(random_adapter(
                    "big", cfg.hidden_size, cfg.vocab_size, rank=8))
        finally:
            eng.stop()
