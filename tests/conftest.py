"""Test harness: force JAX onto CPU with 8 virtual devices BEFORE jax imports.

This is the analog of the reference's MockContainer strategy (SURVEY.md §4): unit
tests run hermetically against a fake 8-chip mesh so every sharding/collective
path is exercised without TPU hardware.
"""

import os

# Force CPU. Env vars alone are too late here: the image's sitecustomize
# imports jax at interpreter startup (registering a real-TPU backend), so
# JAX_PLATFORMS is already captured. jax.config.update still works because
# no backend has been *initialized* yet — but XLA_FLAGS must be in the env
# before the CPU client is created, so set both.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture
def mock_logger():
    from gofr_tpu.logging import MockLogger

    return MockLogger()
