"""Test harness: force JAX onto CPU with 8 virtual devices BEFORE jax imports.

This is the analog of the reference's MockContainer strategy (SURVEY.md §4): unit
tests run hermetically against a fake 8-chip mesh so every sharding/collective
path is exercised without TPU hardware.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import pytest  # noqa: E402


@pytest.fixture
def mock_logger():
    from gofr_tpu.logging import MockLogger

    return MockLogger()
