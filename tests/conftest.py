"""Test harness: force JAX onto CPU with 8 virtual devices BEFORE jax use.

This is the analog of the reference's MockContainer strategy (SURVEY.md §4): unit
tests run hermetically against a fake 8-chip mesh so every sharding/collective
path is exercised without TPU hardware. The pin discipline itself lives in one
place — repo-root ``jaxpin.py`` (see its docstring for the sitecustomize/axon
constraints) — shared with bench.py and __graft_entry__.py.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from jaxpin import pin_cpu  # noqa: E402

pin_cpu(8)

import jax  # noqa: E402

# Persistent XLA compile cache: the suite builds dozens of engines whose
# tiny-config programs compile identically across test modules (and the
# fleet/lockstep drills recompile them again in subprocesses). Caching the
# compiled executables on disk dedups those repeats — including within a
# single cold run, since each GenerateEngine re-jits its own function
# objects — which is what keeps tier-1 inside its wall-clock budget on
# 1–2 vCPU CI hosts. Semantically neutral: a cache miss just compiles.
jax.config.update(
    "jax_compilation_cache_dir",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 ".cache", "jax"),
)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.3)

import pytest  # noqa: E402


@pytest.fixture
def mock_logger():
    from gofr_tpu.logging import MockLogger

    return MockLogger()
