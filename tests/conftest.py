"""Test harness: force JAX onto CPU with 8 virtual devices BEFORE jax use.

This is the analog of the reference's MockContainer strategy (SURVEY.md §4): unit
tests run hermetically against a fake 8-chip mesh so every sharding/collective
path is exercised without TPU hardware. The pin discipline itself lives in one
place — repo-root ``jaxpin.py`` (see its docstring for the sitecustomize/axon
constraints) — shared with bench.py and __graft_entry__.py.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from jaxpin import pin_cpu  # noqa: E402

pin_cpu(8)

import pytest  # noqa: E402


@pytest.fixture
def mock_logger():
    from gofr_tpu.logging import MockLogger

    return MockLogger()
