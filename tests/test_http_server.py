"""End-to-end HTTP tests: real aiohttp server on an ephemeral port, real client
requests (the analog of the reference's example integration tests, SURVEY.md §4)."""

import asyncio
import json
import threading
import time
from dataclasses import dataclass

import httpx
import pytest

import gofr_tpu.app as appmod
from gofr_tpu.config import DictConfig
from gofr_tpu.container import new_mock_container
from gofr_tpu.http.errors import EntityNotFound


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class AppHarness:
    """Runs an App's asyncio loop on a background thread."""

    def __init__(self, app):
        self.app = app
        self._thread = None
        self._loop = None

    def __enter__(self):
        started = threading.Event()

        def run():
            self._loop = asyncio.new_event_loop()
            asyncio.set_event_loop(self._loop)
            ready = asyncio.Event()

            async def main():
                task = asyncio.ensure_future(self.app.arun(ready=ready))
                await ready.wait()
                started.set()
                await task

            try:
                self._loop.run_until_complete(main())
            finally:
                self._loop.close()

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()
        assert started.wait(timeout=10), "app failed to start"
        return self

    def __exit__(self, *exc):
        self._loop.call_soon_threadsafe(self.app.stop)
        self._thread.join(timeout=10)

    @property
    def base(self) -> str:
        return f"http://127.0.0.1:{self.app.http_port}"


def make_app(extra_config=None, **kw):
    config = {
        "HTTP_PORT": str(_free_port()),
        "METRICS_PORT": str(_free_port()),
        **(extra_config or {}),
    }
    app = appmod.App(config=DictConfig(config), container=new_mock_container(config))
    return app


def test_end_to_end_routes_and_envelope():
    app = make_app()

    def greet(ctx):
        return f"Hello {ctx.param('name') or 'World'}!"

    def create_thing(ctx):
        body = ctx.bind(dict)
        return {"received": body}

    def boom(ctx):
        raise EntityNotFound("id", ctx.path_param("id"))

    def crash(ctx):
        raise RuntimeError("secret internals")

    app.get("/greet", greet)
    app.post("/things", create_thing)
    app.get("/things/{id}", boom)
    app.get("/crash", crash)

    with AppHarness(app) as h, httpx.Client(base_url=h.base) as client:
        r = client.get("/greet", params={"name": "gofr"})
        assert r.status_code == 200
        assert r.json() == {"data": "Hello gofr!"}
        assert "X-Correlation-ID" in r.headers

        r = client.post("/things", json={"a": 1})
        assert r.status_code == 201  # POST → 201
        assert r.json() == {"data": {"received": {"a": 1}}}

        r = client.get("/things/42")
        assert r.status_code == 404
        assert r.json() == {"error": {"message": "No entity found with id: 42"}}

        r = client.get("/crash")
        assert r.status_code == 500
        assert "secret internals" not in r.text  # no leak

        r = client.get("/no/such/route")
        assert r.status_code == 404
        assert r.json() == {"error": {"message": "route not registered"}}

        r = client.get("/.well-known/health")
        assert r.status_code == 200
        body = r.json()["data"]
        assert body["status"] == "UP"

        r = client.get("/.well-known/alive")
        assert r.json() == {"data": {"status": "UP"}}

        # metrics on the separate port
        m = httpx.get(f"http://127.0.0.1:{app.metrics_port}/metrics")
        assert m.status_code == 200
        assert "app_http_response" in m.text
        assert 'path="/greet"' in m.text


def test_swagger_docs_offline_by_default():
    """VERDICT r3 missing #2 analog: the reference embeds the Swagger-UI
    bundle (swagger.go:13-14) so docs work air-gapped; the default docs
    page must reference NO external assets, and the spec must list the
    registered routes. SWAGGER_UI=cdn opts into the unpkg bundle."""
    app = make_app()
    app.get("/greet", lambda ctx: "hi")
    app.post("/things/{id}", lambda ctx: {"ok": True})

    with AppHarness(app) as h, httpx.Client(base_url=h.base) as client:
        r = client.get("/.well-known/swagger")
        assert r.status_code == 200
        assert "unpkg.com" not in r.text and "https://" not in r.text, (
            "offline docs page references external assets"
        )
        assert "/.well-known/openapi.json" in r.text

        spec = client.get("/.well-known/openapi.json").json()
        assert "/greet" in spec["paths"]
        assert "post" in spec["paths"]["/things/{id}"]

    cdn_app = make_app(extra_config={"SWAGGER_UI": "cdn"})
    cdn_app.get("/greet", lambda ctx: "hi")  # no routes -> no HTTP server
    with AppHarness(cdn_app) as h, httpx.Client(base_url=h.base) as client:
        assert "unpkg.com" in client.get("/.well-known/swagger").text


def test_request_timeout_yields_408():
    app = make_app({"REQUEST_TIMEOUT": "0.3"})

    def slow(ctx):
        time.sleep(2)
        return "late"

    app.get("/slow", slow)
    with AppHarness(app) as h, httpx.Client(base_url=h.base) as client:
        r = client.get("/slow", timeout=5)
        assert r.status_code == 408
        assert r.json()["error"]["message"] == "request timed out"


def test_bind_dataclass_and_async_handler():
    app = make_app()

    @dataclass
    class Order:
        id: int
        item: str
        qty: int = 1

    def create(ctx):
        order = ctx.bind(Order)
        return {"id": order.id, "item": order.item, "qty": order.qty}

    async def async_route(ctx):
        await asyncio.sleep(0.01)
        return "async-ok"

    app.post("/orders", create)
    app.get("/async", async_route)
    with AppHarness(app) as h, httpx.Client(base_url=h.base) as client:
        r = client.post("/orders", json={"id": "7", "item": "tpu", "qty": 3})
        assert r.status_code == 201
        assert r.json()["data"] == {"id": 7, "item": "tpu", "qty": 3}

        r = client.post("/orders", json={"item": "x"})
        assert r.status_code == 400  # missing required field

        r = client.get("/async")
        assert r.json()["data"] == "async-ok"


def test_basic_auth_and_apikey():
    app = make_app()
    app.enable_basic_auth({"admin": "secret"})
    app.get("/private", lambda ctx: f"hi {ctx.auth_user}")
    with AppHarness(app) as h, httpx.Client(base_url=h.base) as client:
        assert client.get("/private").status_code == 401
        r = client.get("/private", auth=("admin", "secret"))
        assert r.status_code == 200
        assert r.json()["data"] == "hi admin"
        assert client.get("/private", auth=("admin", "wrong")).status_code == 401
        # well-known endpoints skip auth
        assert client.get("/.well-known/alive").status_code == 200


def test_cors_preflight():
    app = make_app()
    app.get("/x", lambda ctx: "x")
    with AppHarness(app) as h, httpx.Client(base_url=h.base) as client:
        r = client.options("/x")
        assert r.status_code == 200
        assert r.headers["Access-Control-Allow-Origin"] == "*"
        assert "GET" in r.headers["Access-Control-Allow-Methods"]


def test_crud_generator_end_to_end():
    @dataclass
    class Book:
        isbn: int
        title: str = ""

    app = make_app()
    # wire a real sqlite datasource into the mock container
    from gofr_tpu.datasource.sql import connect_sql

    app.container.sql = connect_sql(DictConfig({"DB_DIALECT": "sqlite"}), app.logger, app.container.metrics)
    app.add_rest_handlers(Book)

    with AppHarness(app) as h, httpx.Client(base_url=h.base) as client:
        r = client.post("/book", json={"isbn": 1, "title": "JAX"})
        assert r.status_code == 201, r.text
        r = client.get("/book/1")
        assert r.json()["data"] == {"isbn": 1, "title": "JAX"}
        r = client.put("/book/1", json={"isbn": 1, "title": "Pallas"})
        assert r.status_code == 200
        r = client.get("/book")
        assert r.json()["data"] == [{"isbn": 1, "title": "Pallas"}]
        r = client.delete("/book/1")
        assert r.status_code == 204
        assert client.get("/book/1").status_code == 404


def test_websocket_roundtrip():
    app = make_app()

    def ws_handler(ctx):
        data = ctx.bind(dict)
        return {"echo": data.get("msg", "")}

    app.websocket("/ws", ws_handler)

    with AppHarness(app) as h:
        async def talk():
            import aiohttp

            async with aiohttp.ClientSession() as session:
                async with session.ws_connect(f"{h.base}/ws") as ws:
                    await ws.send_str(json.dumps({"msg": "ping"}))
                    reply = await ws.receive_json(timeout=5)
                    return reply

        reply = asyncio.run(talk())
        assert reply == {"echo": "ping"}


def test_pubsub_subscribe_commit_flow():
    app = make_app()
    received = []
    done = threading.Event()

    def on_msg(ctx):
        received.append(ctx.bind(dict))
        done.set()

    app.subscribe("orders", on_msg)
    with AppHarness(app):
        app.container.publish("orders", {"id": 1})
        assert done.wait(timeout=5)
    assert received == [{"id": 1}]
    # committed offset advanced (at-least-once: commit happened after success)
    broker = app.container.pubsub
    assert broker._offsets[("orders", app.container.app_name)] == 1


def test_profile_route_gated_on_debug_env():
    """SURVEY §5.1 parity with pprof gating (http_server.go:53-60): the
    trace-capture route exists only under APP_ENV=DEBUG, and a capture
    produces an xplane trace dir on disk."""
    import glob
    import os
    import shutil
    import tempfile

    # without DEBUG: route absent → enveloped 404
    app = make_app()
    app.get("/ping", lambda ctx: "pong")
    with AppHarness(app) as h, httpx.Client(base_url=h.base) as c:
        assert c.get("/debug/profile").status_code == 404

    out_dir = tempfile.mkdtemp(prefix="gofr_profile_test_")
    try:
        app = make_app({"APP_ENV": "DEBUG", "PROFILER_PORT": "0",
                        "PROFILER_DIR": out_dir})
        with AppHarness(app) as h, httpx.Client(base_url=h.base, timeout=120) as c:
            r = c.get("/debug/profile", params={"seconds": "0.3"})
            assert r.status_code == 200, r.text
            trace_dir = r.json()["data"]["trace_dir"]
            assert trace_dir.startswith(out_dir)
            produced = glob.glob(os.path.join(trace_dir, "**", "*"), recursive=True)
            assert produced, "profiler produced no trace files"
            assert c.get("/debug/profile", params={"seconds": "nan3"}).status_code == 400
            # absurd N is rejected outright (400), not silently clamped
            assert c.get("/debug/profile", params={"seconds": "1e9"}).status_code == 400
            assert c.get("/debug/profile", params={"seconds": "0"}).status_code == 400
            assert c.get("/debug/profile", params={"seconds": "-5"}).status_code == 400
            # one capture at a time: 409 while another is running
            assert app._profile_busy.acquire(blocking=False)
            try:
                r = c.get("/debug/profile", params={"seconds": "0.3"})
                assert r.status_code == 409, r.text
            finally:
                app._profile_busy.release()
            r = c.get("/debug/profile", params={"seconds": "0.2"})
            assert r.status_code == 200, r.text  # lock released after capture
    finally:
        shutil.rmtree(out_dir, ignore_errors=True)


def test_subscriber_workers_parallel_consumption():
    """SUBSCRIBER_WORKERS=N runs N consumer threads per topic (consumer-group
    partition parallelism analog); every message is processed exactly once."""
    app = make_app({"SUBSCRIBER_WORKERS": "4"})
    seen, lock = [], threading.Lock()

    def handler(ctx):
        body = ctx.bind(dict)
        time.sleep(0.05)  # hold the worker so parallelism matters
        with lock:
            seen.append(body["n"])

    app.subscribe("jobs", handler)
    with AppHarness(app):
        names = [t.name for t in app._sub_threads]
        assert len([n for n in names if n.startswith("gofr-sub-jobs")]) == 4
        for i in range(12):
            app.container.pubsub.publish("jobs", {"n": i})
        deadline = time.time() + 15
        while time.time() < deadline and len(seen) < 12:
            time.sleep(0.02)
    assert sorted(seen) == list(range(12)), seen


def test_cors_preflight_variants():
    """Preflight edge cases the reference's middleware tier covers: custom
    env-configured origin/headers/methods win; preflight succeeds on any
    path (including unregistered); actual responses carry the headers too
    without clobbering handler-set values."""
    app = make_app({
        "ACCESS_CONTROL_ALLOW_ORIGIN": "https://app.example",
        "ACCESS_CONTROL_ALLOW_HEADERS": "X-Custom, Authorization",
        "ACCESS_CONTROL_ALLOW_METHODS": "GET, PATCH",
    })
    app.get("/y", lambda ctx: "y")
    with AppHarness(app) as h, httpx.Client(base_url=h.base) as client:
        r = client.options("/y", headers={
            "Origin": "https://app.example",
            "Access-Control-Request-Method": "PATCH",
            "Access-Control-Request-Headers": "X-Custom",
        })
        assert r.status_code == 200
        assert r.headers["Access-Control-Allow-Origin"] == "https://app.example"
        assert r.headers["Access-Control-Allow-Methods"] == "GET, PATCH"
        assert "X-Custom" in r.headers["Access-Control-Allow-Headers"]
        # preflight for a path with no registered handler still answers
        # (the reference registers OPTIONS at the router level)
        r2 = client.options("/never-registered")
        assert r2.status_code == 200
        assert r2.headers["Access-Control-Allow-Origin"] == "https://app.example"
        # non-preflight responses carry CORS headers as well
        r3 = client.get("/y")
        assert r3.status_code == 200
        assert r3.headers["Access-Control-Allow-Origin"] == "https://app.example"


def test_multipart_malformed_bodies():
    """Malformed multipart bodies must produce clean BindErrors or safe
    degradation — never a 500 from an uncaught parser crash."""
    import dataclasses

    from gofr_tpu.utils.bind import BindError
    from gofr_tpu.http.multipart import bind_multipart, parse_multipart

    # no boundary parameter at all
    with pytest.raises(BindError, match="boundary"):
        parse_multipart("multipart/form-data", b"--x\r\n\r\nhi\r\n--x--")

    b = "multipart/form-data; boundary=BB"
    # part without a content-disposition name is skipped, not fatal
    body = (b"--BB\r\ncontent-type: text/plain\r\n\r\norphan\r\n"
            b"--BB\r\ncontent-disposition: form-data; name=\"a\"\r\n\r\nva\r\n--BB--")
    parts = parse_multipart(b, body)
    assert [p[0] for p in parts] == ["a"] and parts[0][3] == b"va"

    # headers but no blank line: data degrades to empty, no crash
    parts = parse_multipart(b, b"--BB\r\ncontent-disposition: form-data; name=\"h\"\r\n--BB--")
    assert parts == [("h", None, "application/octet-stream", b"")]

    # trailing CRLF inside the content is PRESERVED (only delimiter CRLFs
    # stripped) and binary bytes pass through undecoded
    payload = b"\x00\x01\r\n"
    body = (b"--BB\r\ncontent-disposition: form-data; name=\"f\"; filename=\"x.bin\"\r\n"
            b"content-type: application/octet-stream\r\n\r\n" + payload + b"\r\n--BB--")
    (name, fname, ctype, data), = parse_multipart(b, body)
    assert (name, fname, ctype, data) == ("f", "x.bin", "application/octet-stream", payload)

    # dataclass bind: unknown fields ignored, missing field -> None default
    @dataclasses.dataclass
    class Form:
        a: str = ""
        missing: str | None = None

    bound = bind_multipart(
        b,
        b"--BB\r\ncontent-disposition: form-data; name=\"a\"\r\n\r\nhello\r\n"
        b"--BB\r\ncontent-disposition: form-data; name=\"zzz\"\r\n\r\nskip\r\n--BB--",
        Form,
    )
    assert bound.a == "hello" and bound.missing is None

    # bind target that is neither dataclass nor dict is a BindError
    with pytest.raises(BindError):
        bind_multipart(b, b"--BB--", object)


def test_websocket_edge_cases():
    """Binary/str/malformed frames, handler errors, hub lifecycle, and
    server-push broadcast — the reference's websocket tier behaviors
    (websocket.go:63-137) beyond the happy roundtrip."""
    app = make_app()
    seen = []

    def ws_handler(ctx):
        raw = ctx.bind(str)
        seen.append(raw)
        if raw == "boom":
            raise RuntimeError("handler exploded")
        if raw == "types":
            assert isinstance(ctx.bind(bytes), bytes)
            from gofr_tpu.utils.bind import BindError
            try:
                ctx.bind(dict)  # not JSON
                return {"bound": True}
            except BindError:
                return {"bound": False}
        return {"echo": raw}

    app.websocket("/ws", ws_handler)

    with AppHarness(app) as h:
        async def talk():
            import aiohttp

            out = {}
            async with aiohttp.ClientSession() as session:
                async with session.ws_connect(f"{h.base}/ws") as ws:
                    # non-JSON text frame: bind(str/bytes) works, bind(dict) errors cleanly
                    await ws.send_str("types")
                    out["types"] = await ws.receive_json(timeout=5)
                    # hub registered the live connection (checked after the
                    # first roundtrip — registration happens server-side on
                    # upgrade, which may trail the client handshake)
                    out["hub_size_live"] = len(app.ws_hub)
                    # handler exception must NOT kill the connection loop:
                    # the client gets an error envelope, then the next
                    # frame is served normally
                    await ws.send_str("boom")
                    out["boom"] = await ws.receive_json(timeout=5)
                    await ws.send_str("after-boom")
                    out["after"] = await ws.receive_json(timeout=5)
                    # server push through the hub reaches the client. The
                    # broadcast must run ON THE SERVER LOOP (transports are
                    # not thread-safe; cross-loop awaits raise) — the same
                    # run_coroutine_threadsafe pattern WSConnection.send uses
                    import asyncio as aio

                    fut = aio.run_coroutine_threadsafe(
                        app.ws_hub.broadcast({"push": 1}), h._loop)
                    await aio.get_event_loop().run_in_executor(
                        None, fut.result, 5)
                    out["push"] = await ws.receive_json(timeout=5)
                return out

        out = asyncio.run(talk())
        assert out["hub_size_live"] == 1
        assert out["types"] == {"bound": False}
        assert "error" in out["boom"]
        assert out["after"] == {"echo": "after-boom"}
        assert out["push"] == {"push": 1}
    # connection unregistered after close
    assert len(app.ws_hub) == 0


def test_static_files_served_but_openapi_forbidden(tmp_path):
    """Static-route hardening (reference `http/router.go:62-82`): a static
    mount serves its files, but `openapi.json` — at any depth — returns
    403 (the spec is served at /.well-known/openapi.json only), and path
    traversal out of the mount resolves to 404, never a file."""
    import httpx

    (tmp_path / "index.html").write_text("<h1>hi</h1>")
    (tmp_path / "openapi.json").write_text("{}")
    sub = tmp_path / "sub"
    sub.mkdir()
    (sub / "openapi.json").write_text("{}")
    (sub / "ok.txt").write_text("fine")
    outside = tmp_path.parent / "outside-secret.txt"
    outside.write_text("secret")

    app = make_app()
    app.add_static_files("/static", str(tmp_path))
    with AppHarness(app) as h, httpx.Client(base_url=h.base) as c:
        assert c.get("/static/index.html").text == "<h1>hi</h1>"
        assert c.get("/static/sub/ok.txt").text == "fine"
        r = c.get("/static/openapi.json")
        assert r.status_code == 403
        assert "well-known" in r.json()["error"]["message"]
        assert c.get("/static/sub/openapi.json").status_code == 403
        assert c.get("/static/missing.txt").status_code == 404
        # traversal: %2E%2E decodes to ".." after routing; must not escape
        r = c.get(f"{h.base}/static/%2E%2E/outside-secret.txt")
        assert r.status_code == 404
        assert "secret" not in r.text
