import io
import time

import pytest

from gofr_tpu.cli import CmdApp, CmdRequest
from gofr_tpu.container import new_mock_container
from gofr_tpu.cron import CronParseError, Crontab, Schedule

pytestmark = pytest.mark.quick


# -- cron parser (gofr cron.go:86-224 semantics) -------------------------------


def test_schedule_parse_star():
    s = Schedule.parse("* * * * *")
    assert len(s.minutes) == 60 and len(s.hours) == 24


def test_schedule_parse_step_range_list():
    s = Schedule.parse("*/15 1-5 1,15 */3 0-6/2")
    assert s.minutes == frozenset({0, 15, 30, 45})
    assert s.hours == frozenset({1, 2, 3, 4, 5})
    assert s.days == frozenset({1, 15})
    assert s.months == frozenset({1, 4, 7, 10})
    assert s.weekdays == frozenset({0, 2, 4, 6})


@pytest.mark.parametrize("bad", ["* * * *", "60 * * * *", "* 24 * * *", "x * * * *",
                                 "*/0 * * * *", "5-1 * * * *", "* * 0 * *"])
def test_schedule_parse_rejects(bad):
    with pytest.raises(CronParseError):
        Schedule.parse(bad)


def test_schedule_matches():
    s = Schedule.parse("30 14 * * *")
    t = time.struct_time((2026, 7, 29, 14, 30, 0, 2, 210, -1))
    assert s.matches(t)
    t2 = time.struct_time((2026, 7, 29, 14, 31, 0, 2, 210, -1))
    assert not s.matches(t2)


def test_schedule_dom_dow_vixie_or_rule():
    """When BOTH day-of-month and day-of-week are restricted, a day
    matching either fires (standard cron; reference cron.go:273-277)."""
    s = Schedule.parse("0 0 1 * 1")  # 1st of month OR Mondays
    # 2026-06-01 is a Monday AND the 1st
    assert s.matches(time.struct_time((2026, 6, 1, 0, 0, 0, 0, 152, -1)))
    # 2026-06-08 is a Monday but not the 1st → still fires
    assert s.matches(time.struct_time((2026, 6, 8, 0, 0, 0, 0, 159, -1)))
    # 2026-07-01 is a Wednesday, the 1st → still fires
    assert s.matches(time.struct_time((2026, 7, 1, 0, 0, 0, 2, 182, -1)))
    # 2026-06-09 Tuesday, not the 1st → no fire
    assert not s.matches(time.struct_time((2026, 6, 9, 0, 0, 0, 1, 160, -1)))
    # only dow restricted → AND semantics as usual
    s2 = Schedule.parse("0 0 * * 1")
    assert not s2.matches(time.struct_time((2026, 7, 1, 0, 0, 0, 2, 182, -1)))
    assert s2.matches(time.struct_time((2026, 6, 8, 0, 0, 0, 0, 159, -1)))
    # only dom restricted
    s3 = Schedule.parse("0 0 1 * *")
    assert s3.matches(time.struct_time((2026, 7, 1, 0, 0, 0, 2, 182, -1)))
    assert not s3.matches(time.struct_time((2026, 6, 8, 0, 0, 0, 0, 159, -1)))


def test_crontab_fires_matching_jobs():
    c = new_mock_container()
    cron = Crontab(c)
    fired = []
    cron.add_job("* * * * *", "always", lambda ctx: fired.append("always"))
    cron.add_job("59 23 31 12 *", "never-today", lambda ctx: fired.append("nope"))
    names = cron.tick(time.mktime((2026, 7, 29, 10, 0, 0, 0, 0, -1)))
    assert names == ["always"]
    # same minute → no double fire
    assert cron.tick(time.mktime((2026, 7, 29, 10, 0, 30, 0, 0, -1))) == []
    time.sleep(0.1)
    assert fired == ["always"]


def test_cron_job_failure_recovered():
    c = new_mock_container()
    cron = Crontab(c)

    def bad(ctx):
        raise RuntimeError("cron boom")

    cron.add_job("* * * * *", "bad", bad)
    cron.tick(time.time())
    time.sleep(0.2)
    assert any("cron job bad failed" in r.get("message", "") for r in c.logger.records)


# -- CLI runtime ---------------------------------------------------------------


def test_cmd_request_flag_parsing():
    r = CmdRequest(["migrate", "-v", "--env=prod", "-n", "5", "extra"])
    assert r.subcommand == "migrate"
    assert r.param("v") == "true"
    assert r.param("env") == "prod"
    assert r.param("n") == "5"
    assert r.positional == ["extra"]


def test_cmd_app_routes_and_output():
    app = CmdApp(new_mock_container())
    app.sub_command("hello", lambda ctx: f"hi {ctx.param('name')}", description="greets")
    out, err = io.StringIO(), io.StringIO()
    code = app.run(["hello", "--name=x"], out=out, err=err)
    assert code == 0
    assert out.getvalue().strip() == "hi x"


def test_cmd_app_unknown_subcommand():
    app = CmdApp(new_mock_container())
    app.sub_command("known", lambda ctx: "ok")
    out, err = io.StringIO(), io.StringIO()
    code = app.run(["nope"], out=out, err=err)
    assert code == 1
    assert "unknown subcommand" in err.getvalue()
    assert "known" in err.getvalue()  # help listed


def test_cmd_app_help():
    app = CmdApp(new_mock_container())
    app.sub_command("job", lambda ctx: "ok", description="runs the job")
    out = io.StringIO()
    assert app.run(["-h"], out=out) == 0
    assert "runs the job" in out.getvalue()


def test_cmd_app_error_exit_code():
    app = CmdApp(new_mock_container())

    def failing(ctx):
        raise ValueError("bad input")

    app.sub_command("fail", failing)
    out, err = io.StringIO(), io.StringIO()
    assert app.run(["fail"], out=out, err=err) == 1
    assert "bad input" in err.getvalue()


def test_cmd_regex_route():
    app = CmdApp(new_mock_container())
    app.sub_command("run-[0-9]+", lambda ctx: ctx.path_param("subcommand"))
    out = io.StringIO()
    app.run(["run-42"], out=out)
    assert out.getvalue().strip() == "run-42"
