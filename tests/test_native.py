"""Native (C++) runtime core vs the pure-Python fallback: identical
semantics for the prefill planner and the token loader."""

import os

import numpy as np
import pytest

from gofr_tpu.native import (
    TokenLoader,
    _plan_prefill_py,
    native_available,
    plan_prefill,
)

BUCKETS = [16, 32, 64, 128]


def _rand_case(rng):
    n = rng.integers(1, 12)
    lens = rng.integers(1, 128, n).tolist()
    deadlines = [int(d) if rng.random() < 0.5 else 0 for d in rng.integers(1, 2000, n)]
    now = int(rng.integers(0, 2000))
    free = int(rng.integers(0, 8))
    maxb = int(rng.integers(1, 8))
    return lens, deadlines, now, free, maxb


def test_native_compiles():
    assert native_available(), "g++ is in the image; the native core must build"


def test_planner_native_matches_python():
    rng = np.random.default_rng(0)
    for _ in range(200):
        lens, deadlines, now, free, maxb = _rand_case(rng)
        a = plan_prefill(lens, deadlines, now, free, maxb, BUCKETS)
        b = _plan_prefill_py(lens, deadlines, now, free, maxb, BUCKETS)
        assert (a.chosen, sorted(a.expired), a.len_bucket, a.batch_bucket) == (
            b.chosen, sorted(b.expired), b.len_bucket, b.batch_bucket,
        ), (lens, deadlines, now, free, maxb)


def test_planner_edf_and_bucket_affinity():
    # r1 has the earliest deadline and a short prompt → leads, bucket 16;
    # the huge r0 must NOT join (it would inflate padding), r2 fits.
    lens = [120, 10, 14]
    deadlines = [0, 100, 0]
    plan = plan_prefill(lens, deadlines, now_us=0, free_slots=4, max_batch=4,
                        len_buckets=BUCKETS)
    assert plan.chosen == [1, 2]
    assert plan.len_bucket == 16
    assert plan.batch_bucket == 2
    # next round the long prompt leads its own batch
    plan2 = plan_prefill([120], [0], 0, 4, 4, BUCKETS)
    assert plan2.chosen == [0] and plan2.len_bucket == 128


def test_planner_expiry():
    plan = plan_prefill([5, 5], [10, 0], now_us=50, free_slots=2, max_batch=2,
                        len_buckets=BUCKETS)
    assert plan.expired == [0] and plan.chosen == [1]


@pytest.fixture
def corpus(tmp_path):
    path = os.path.join(tmp_path, "tokens.bin")
    np.arange(10_000, dtype=np.int32).tofile(path)
    return path


def test_loader_yields_contiguous_crops(corpus):
    with TokenLoader(corpus, batch=4, seqlen=32, seed=7) as dl:
        assert dl.num_tokens == 10_000
        for _ in range(5):
            batch = dl.next()
            assert batch.shape == (4, 33) and batch.dtype == np.int32
            # corpus is arange → every crop is consecutive ints
            diffs = np.diff(batch, axis=1)
            assert (diffs == 1).all()


def test_loader_native_matches_fallback(corpus, monkeypatch):
    with TokenLoader(corpus, batch=2, seqlen=16, seed=42) as dl_native:
        assert dl_native._handle is not None, "native loader should engage"
        native_batches = [dl_native.next().copy() for _ in range(4)]

    monkeypatch.setenv("GOFR_NATIVE", "0")
    import gofr_tpu.native as gn

    monkeypatch.setattr(gn, "_lib", None)
    dl_py = TokenLoader(corpus, batch=2, seqlen=16, seed=42)
    assert dl_py._handle is None
    for nb in native_batches:
        np.testing.assert_array_equal(nb, dl_py.next())


def test_loader_deterministic_per_seed(corpus):
    with TokenLoader(corpus, batch=2, seqlen=8, seed=1) as a, \
         TokenLoader(corpus, batch=2, seqlen=8, seed=1) as b:
        np.testing.assert_array_equal(a.next(), b.next())
    with TokenLoader(corpus, batch=2, seqlen=8, seed=1) as a, \
         TokenLoader(corpus, batch=2, seqlen=8, seed=2) as c:
        assert not np.array_equal(a.next(), c.next())


def test_loader_feeds_train_step(corpus):
    """End-to-end: native loader batches drive a train step."""
    import jax
    import jax.numpy as jnp

    from gofr_tpu.models import LlamaConfig, llama
    from gofr_tpu.parallel import build_mesh
    from gofr_tpu.train import make_train_step

    cfg = LlamaConfig.tiny(vocab_size=16384)
    mesh = build_mesh("dp:8")
    init_fn, step_fn = make_train_step(cfg, llama, mesh)
    state = init_fn(jax.random.key(0))
    with TokenLoader(corpus, batch=8, seqlen=16, seed=3) as dl:
        batch = dl.next()
        tokens = jnp.asarray(batch[:, :-1])
        lengths = jnp.full((8,), 16, jnp.int32)
        state, metrics = step_fn(state, tokens, lengths)
    assert np.isfinite(float(metrics["loss"]))


def test_planner_oversize_prompt_reported_not_starved():
    # longer than every bucket → unschedulable: reported in expired, and the
    # rest of the queue still schedules
    plan = plan_prefill([500, 10], [100, 0], now_us=0, free_slots=4, max_batch=4,
                        len_buckets=BUCKETS)
    assert plan.expired == [0] and plan.chosen == [1]
