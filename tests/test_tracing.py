import pytest

from gofr_tpu.config import DictConfig
from gofr_tpu.logging import MockLogger
from gofr_tpu.tracing import (
    MemoryExporter,
    NoopExporter,
    Tracer,
    current_span,
    parse_traceparent,
    tracer_from_config,
)

pytestmark = pytest.mark.quick


def test_span_parenting():
    exp = MemoryExporter()
    tracer = Tracer(exp)
    with tracer.span("parent") as p:
        with tracer.span("child") as c:
            assert c.trace_id == p.trace_id
            assert c.parent_id == p.span_id
    assert len(exp.spans) == 2
    assert current_span() is None


def test_traceparent_roundtrip():
    tracer = Tracer(MemoryExporter())
    s = tracer.start_span("server", traceparent="00-" + "a" * 32 + "-" + "b" * 16 + "-01")
    assert s.trace_id == "a" * 32
    assert s.parent_id == "b" * 16
    header = s.traceparent()
    parsed = parse_traceparent(header)
    assert parsed == (s.trace_id, s.span_id, True)
    s.finish()


def test_unsampled_flag_preserved():
    tracer = Tracer(MemoryExporter())
    s = tracer.start_span("server", traceparent="00-" + "a" * 32 + "-" + "b" * 16 + "-00")
    assert s.sampled is False
    assert s.traceparent().endswith("-00")
    child = tracer.start_span("child", parent=s)
    assert child.sampled is False
    child.finish()
    s.finish()


def test_faulty_exporter_does_not_kill_worker():
    from gofr_tpu.tracing import SpanExporter

    class FlakyExporter(SpanExporter):
        def __init__(self):
            self.calls = 0

        def export(self, spans):
            self.calls += 1
            if self.calls == 1:
                raise RuntimeError("transient")

    exp = FlakyExporter()
    tracer = Tracer(exp, batch_size=1, flush_interval=0.01)
    tracer.start_span("a").finish()
    import time

    time.sleep(0.1)
    tracer.start_span("b").finish()
    tracer.shutdown()
    assert exp.calls >= 2  # worker survived the first raise


def test_parse_traceparent_rejects_garbage():
    assert parse_traceparent("") is None
    assert parse_traceparent("00-short-short-01") is None
    assert parse_traceparent("00-" + "z" * 32 + "-" + "b" * 16 + "-01") is None


def test_span_error_status():
    exp = MemoryExporter()
    tracer = Tracer(exp)
    try:
        with tracer.span("failing"):
            raise RuntimeError("x")
    except RuntimeError:
        pass
    assert exp.spans[0].status == "ERROR"


def test_tracer_from_config_none():
    t = tracer_from_config(DictConfig({}), MockLogger(), "svc")
    assert isinstance(t._exporter, NoopExporter)


def test_tracer_from_config_zipkin_requires_url():
    log = MockLogger()
    t = tracer_from_config(DictConfig({"TRACE_EXPORTER": "zipkin"}), log, "svc")
    assert isinstance(t._exporter, NoopExporter)
    assert any("TRACER_URL" in r.get("message", "") for r in log.records)
