"""BASELINE row 4 scaffolding: Llama-3-70B sharded over a virtual v5e-64
mesh. No 70B weights exist in this sandbox, so the provable claim is that
the FULL sharded programs (train step; serving prefill + decode) trace and
lower with real dp/fsdp/tp shardings over 64 devices using abstract arrays
only — the exact artifacts a v5e-64 deployment would compile. Runs in a
subprocess so the 64-device CPU platform doesn't leak into other tests."""

import os
import subprocess
import sys
import textwrap

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from jaxpin import child_env  # noqa: E402
import pytest

# integration tier (CI `integration` job): multi-minute engine/process
# runs — excluded from the tier-1 gate via -m 'not slow' (docs/testing.md)
pytestmark = pytest.mark.slow

_WORKER = textwrap.dedent("""
    import os, sys
    sys.path.insert(0, "@REPO@")
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=64"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from functools import partial
    from jax.sharding import NamedSharding, PartitionSpec as P

    from gofr_tpu.models import LlamaConfig, llama
    from gofr_tpu.parallel import build_mesh
    from gofr_tpu.parallel.sharding import fsdp_rules, sharding_tree

    cfg = LlamaConfig.llama3_70b()
    assert cfg.num_layers == 80 and cfg.hidden_size == 8192, cfg
    mesh = build_mesh("dp:2,fsdp:4,tp:8", devices=jax.devices("cpu")[:64])

    # abstract params with REAL shardings attached — nothing materializes
    shapes = jax.eval_shape(lambda: llama.init(cfg, jax.random.key(0)))
    rules = fsdp_rules()
    shardings = sharding_tree(llama.param_axes(cfg), rules, mesh)
    params_abs = jax.tree.map(
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
        shapes, shardings,
    )

    SLOTS, SEQ = 64, 2048
    cache_abs = jax.eval_shape(lambda: llama.make_cache(cfg, SLOTS, SEQ))

    def prefill(params, tokens, lengths, cache, slots):
        return llama.prefill(cfg, params, tokens, lengths, cache, slots)

    lowered = jax.jit(prefill).lower(
        params_abs,
        jax.ShapeDtypeStruct((8, 512), jnp.int32),
        jax.ShapeDtypeStruct((8,), jnp.int32),
        cache_abs,
        jax.ShapeDtypeStruct((8,), jnp.int32),
    )
    text = lowered.as_text()
    assert "mhlo.sharding" in text or "sdy.sharding" in text, (
        "no sharding annotations in the lowered 70B prefill")
    print("PREFILL_LOWERED bytes:", len(text))
    hlo_p = lowered.compile().as_text()
    assert "all-reduce" in hlo_p, "compiled 70B prefill has no tp all-reduce"
    print("PREFILL_COMPILED collectives:", hlo_p.count("all-reduce"))

    def decode(params, tokens, positions, cache):
        return llama.decode_step(cfg, params, tokens, positions, cache)

    lowered_d = jax.jit(decode).lower(
        params_abs,
        jax.ShapeDtypeStruct((SLOTS,), jnp.int32),
        jax.ShapeDtypeStruct((SLOTS,), jnp.int32),
        cache_abs,
    )
    print("DECODE_LOWERED bytes:", len(lowered_d.as_text()))
    # full GSPMD partition + compile: the all-reduces the tp sharding implies
    # must appear in the compiled module (this IS the v5e-64 program)
    compiled = lowered_d.compile()
    hlo = compiled.as_text()
    assert "all-reduce" in hlo, "compiled 70B decode has no tp all-reduce"
    print("DECODE_COMPILED collectives:", hlo.count("all-reduce"))

    from gofr_tpu.train import make_train_step
    init_fn, step_fn = make_train_step(cfg, llama, mesh, rules=rules, remat=True)
    state_abs = jax.eval_shape(init_fn, jax.random.key(0))
    lowered_t = jax.jit(step_fn).lower(
        state_abs,
        jax.ShapeDtypeStruct((8, 1024), jnp.int32),
        jax.ShapeDtypeStruct((8,), jnp.int32),
    )
    print("TRAIN_LOWERED bytes:", len(lowered_t.as_text()))
    hlo_t = lowered_t.compile().as_text()
    assert "all-reduce" in hlo_t, "compiled 70B train step has no collectives"
    print("TRAIN_COMPILED collectives:", hlo_t.count("all-reduce"))
    # pipeline-parallel SERVING at 70B (BASELINE row 4's weight-fit
    # topology): blocks + slot KV cache layer-sharded over pp:8, heads
    # over tp:8 — the GPipe decode program a v5e-64 deployment compiles
    # (models/llama_pp.py). ppermute must survive into the compiled HLO.
    from gofr_tpu.models.llama_pp import PPLlamaFamily
    from gofr_tpu.parallel.sharding import ShardingRules

    mesh_pp = build_mesh("pp:8,tp:8", devices=jax.devices("cpu")[:64])
    rules_pp = ShardingRules().with_overrides(layers="pp")
    fam = PPLlamaFamily(mesh_pp, microbatches=8, rules=rules_pp)
    shardings_pp = sharding_tree(llama.param_axes(cfg), rules_pp, mesh_pp)
    params_pp = jax.tree.map(
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
        shapes, shardings_pp,
    )
    cache_sh = NamedSharding(mesh_pp, fam._cache_spec())
    cache_pp = jax.tree.map(
        lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=cache_sh),
        jax.eval_shape(lambda: llama.make_cache(cfg, SLOTS, SEQ)),
    )

    def decode_pp(params, tokens, positions, cache):
        return fam.decode_step(cfg, params, tokens, positions, cache)

    lowered_pp = jax.jit(decode_pp).lower(
        params_pp,
        jax.ShapeDtypeStruct((SLOTS,), jnp.int32),
        jax.ShapeDtypeStruct((SLOTS,), jnp.int32),
        cache_pp,
    )
    hlo_pp = lowered_pp.compile().as_text()
    assert "collective-permute" in hlo_pp, (
        "compiled 70B pp decode has no stage-ring collective-permute")
    assert "all-reduce" in hlo_pp, "compiled 70B pp decode has no tp psum"
    print("PP_SERVE_COMPILED collective-permutes:",
          hlo_pp.count("collective-permute"), "all-reduces:", hlo_pp.count("all-reduce"))

    import math
    n_params = sum(math.prod(s.shape) for s in jax.tree.leaves(shapes))
    assert 6.5e10 < n_params < 7.5e10, f"not 70B-scale: {n_params}"
    print(f"SCALE_OK params={n_params/1e9:.1f}B mesh=dp:2,fsdp:4,tp:8 devices=64")
""")


def test_llama70b_sharded_programs_lower_on_v5e64_mesh():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = child_env()
    env.pop("XLA_FLAGS", None)
    out = subprocess.run(
        [sys.executable, "-c", _WORKER.replace("@REPO@", repo)],
        env=env, capture_output=True, text=True, timeout=900,
    )
    assert out.returncode == 0, out.stderr[-4000:]
    assert "SCALE_OK params=" in out.stdout, out.stdout
    assert "PREFILL_LOWERED" in out.stdout
    assert "TRAIN_LOWERED" in out.stdout
    assert "PP_SERVE_COMPILED" in out.stdout
