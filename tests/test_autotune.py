"""Fused int8-KV paged-decode kernel parity + the warmup backend autotuner.

Kernel parity runs under the Pallas interpreter on the CPU test mesh
(tests/test_pallas.py convention); the autotuner units inject fake timers
so no kernel is ever lowered — the whole module is CPU-safe and quick.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gofr_tpu.ops import autotune
from gofr_tpu.ops.attention import (
    decode_attention,
    paged_decode_attention_q,
    resolve_backend,
)

pytestmark = pytest.mark.quick


def _qpools(key, pool, hkv, page, d):
    """int8 K/V page pools with non-trivial, DISTINCT per-position scales —
    a wrong ks/vs fold cannot cancel out."""
    k1, k2, k3, k4 = jax.random.split(key, 4)
    kq = jax.random.randint(k1, (pool, hkv, page, d), -127, 128, jnp.int8)
    vq = jax.random.randint(k2, (pool, hkv, page, d), -127, 128, jnp.int8)
    ks = jax.random.uniform(k3, (pool, hkv, page), minval=0.005,
                            maxval=0.05).astype(jnp.bfloat16)
    vs = jax.random.uniform(k4, (pool, hkv, page), minval=0.02,
                            maxval=0.2).astype(jnp.bfloat16)
    return kq, vq, ks, vs


# -- fused int8 paged-decode kernel parity (interpreter mode) -------------------


@pytest.mark.parametrize("hq,hkv", [(4, 4), (8, 2)])
def test_paged_decode_q_kernel_matches_gather_path(monkeypatch, hq, hkv):
    """Fused kernel vs the XLA gather path: ragged lengths, a shuffled
    block table, an OOB-marked unallocated tail, and GQA group > 1."""
    n, d, maxp, pool, page = 3, 32, 4, 16, 16
    key = jax.random.key(0)
    q = jax.random.normal(jax.random.fold_in(key, 9), (n, hq, d))
    kq, vq, ks, vs = _qpools(key, pool, hkv, page, d)
    rng = np.random.RandomState(0)
    table = jnp.asarray(rng.permutation(pool)[: n * maxp].reshape(n, maxp), jnp.int32)
    table = table.at[2, 2:].set(pool)  # OOB unallocated tail
    lengths = jnp.array([page * maxp, 19, page + 3], jnp.int32)

    want = paged_decode_attention_q(q, kq, vq, ks, vs, table, lengths, backend="xla")
    monkeypatch.setenv("GOFR_PALLAS_INTERPRET", "1")
    got = paged_decode_attention_q(q, kq, vq, ks, vs, table, lengths, backend="pallas")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)


def test_paged_decode_q_empty_slot_zero_not_nan(monkeypatch):
    """A freshly-recycled slot (length 0) must emit zeros, never NaN."""
    n, hq, hkv, d, maxp, pool, page = 2, 4, 2, 16, 2, 6, 8
    key = jax.random.key(1)
    q = jax.random.normal(jax.random.fold_in(key, 9), (n, hq, d))
    kq, vq, ks, vs = _qpools(key, pool, hkv, page, d)
    table = jnp.arange(n * maxp, dtype=jnp.int32).reshape(n, maxp)
    lengths = jnp.array([0, 5], jnp.int32)

    monkeypatch.setenv("GOFR_PALLAS_INTERPRET", "1")
    got = np.asarray(paged_decode_attention_q(
        q, kq, vq, ks, vs, table, lengths, backend="pallas"))
    assert not np.isnan(got).any()
    np.testing.assert_allclose(got[0], np.zeros_like(got[0]), atol=1e-7)
    want = np.asarray(paged_decode_attention_q(
        q, kq, vq, ks, vs, table, lengths, backend="xla"))
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)


def test_paged_decode_q_scale_folds_match_dequantized_dense(monkeypatch):
    """Both in-kernel scale folds carry the dequant semantics exactly: the
    fused output equals dense decode over the explicitly dequantized
    (int8 * scale) logical views."""
    from gofr_tpu.ops.paged import gather_kv_q

    n, hq, hkv, d, maxp, pool, page = 2, 8, 2, 16, 3, 8, 8
    key = jax.random.key(2)
    q = jax.random.normal(jax.random.fold_in(key, 9), (n, hq, d))
    kq, vq, ks, vs = _qpools(key, pool, hkv, page, d)
    rng = np.random.RandomState(1)
    table = jnp.asarray(rng.permutation(pool)[: n * maxp].reshape(n, maxp), jnp.int32)
    lengths = jnp.array([maxp * page, 11], jnp.int32)

    gkq, gks = gather_kv_q(kq, ks, table)
    gvq, gvs = gather_kv_q(vq, vs, table)
    k_dense = gkq.astype(jnp.float32) * gks.astype(jnp.float32)[..., None]
    v_dense = gvq.astype(jnp.float32) * gvs.astype(jnp.float32)[..., None]
    want = decode_attention(q, k_dense, v_dense, lengths, backend="xla")

    monkeypatch.setenv("GOFR_PALLAS_INTERPRET", "1")
    got = paged_decode_attention_q(q, kq, vq, ks, vs, table, lengths, backend="pallas")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)


def test_fused_path_skips_gather(monkeypatch):
    """The acceptance-criterion proof: with the pallas backend the fused
    path never materializes a gathered logical view — gather_kv_q is not
    called at all."""
    import gofr_tpu.ops.paged as paged_mod

    def boom(*a, **k):
        raise AssertionError("gather_kv_q called on the fused path")

    monkeypatch.setenv("GOFR_PALLAS_INTERPRET", "1")
    monkeypatch.setattr(paged_mod, "gather_kv_q", boom)
    n, hq, hkv, d, maxp, pool, page = 2, 4, 2, 16, 2, 4, 8
    key = jax.random.key(3)
    q = jax.random.normal(jax.random.fold_in(key, 9), (n, hq, d))
    kq, vq, ks, vs = _qpools(key, pool, hkv, page, d)
    table = jnp.arange(n * maxp, dtype=jnp.int32).reshape(n, maxp)
    lengths = jnp.array([page, 3], jnp.int32)
    out = paged_decode_attention_q(q, kq, vq, ks, vs, table, lengths, backend="pallas")
    assert np.isfinite(np.asarray(out)).all()


def test_paged_decode_q_explicit_pallas_bad_page_raises(monkeypatch):
    """Explicit backend='pallas' with a page size the kernel cannot tile
    must raise, mirroring paged_decode_attention (ADVICE round 2)."""
    monkeypatch.setenv("GOFR_PALLAS_INTERPRET", "1")
    n, hq, hkv, d, maxp, pool, page = 2, 4, 2, 16, 2, 4, 12  # 12 % 8 != 0
    key = jax.random.key(4)
    q = jax.random.normal(jax.random.fold_in(key, 9), (n, hq, d))
    kq, vq, ks, vs = _qpools(key, pool, hkv, page, d)
    table = jnp.arange(n * maxp, dtype=jnp.int32).reshape(n, maxp)
    lengths = jnp.array([page, 3], jnp.int32)
    with pytest.raises(ValueError, match="backend='pallas'"):
        paged_decode_attention_q(q, kq, vq, ks, vs, table, lengths, backend="pallas")
    # 'auto' may degrade silently — and must agree with the explicit xla path
    got = paged_decode_attention_q(q, kq, vq, ks, vs, table, lengths, backend="auto")
    want = paged_decode_attention_q(q, kq, vq, ks, vs, table, lengths, backend="xla")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)


def test_decode_attention_explicit_pallas_bad_block_raises(monkeypatch):
    """Regression (ISSUE 6 satellite): decode_attention used to degrade an
    explicit backend='pallas' to XLA silently when the kv-block check
    failed, while paged_decode_attention raised for its analog."""
    monkeypatch.setenv("GOFR_PALLAS_INTERPRET", "1")
    b, hq, hkv, smax, d = 2, 4, 2, 97, 16  # prime Smax: block 97, not % 8
    key = jax.random.key(5)
    q = jax.random.normal(jax.random.fold_in(key, 1), (b, hq, d))
    kc = jax.random.normal(jax.random.fold_in(key, 2), (b, hkv, smax, d))
    vc = jax.random.normal(jax.random.fold_in(key, 3), (b, hkv, smax, d))
    lengths = jnp.array([smax, 11], jnp.int32)
    with pytest.raises(ValueError, match="backend='pallas'"):
        decode_attention(q, kc, vc, lengths, backend="pallas")
    # 'auto' still degrades silently to the XLA path
    got = decode_attention(q, kc, vc, lengths, backend="auto")
    want = decode_attention(q, kc, vc, lengths, backend="xla")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)


# -- autotuner units (fake timers; no kernel lowering) --------------------------


def _fake_timer(values):
    it = iter(values)

    def timer(fn):
        return next(it)

    return timer


def test_autotuner_pins_winner():
    tuner = autotune.Autotuner(device_kind="v5e", timer=_fake_timer([3e-3, 1e-3]))
    backend = tuner.measure("paged_decode_q", "8x16", "int8",
                            {"xla": lambda: None, "pallas": lambda: None})
    assert backend == "pallas"
    rec = tuner.decisions["paged_decode_q"]
    assert rec["source"] == "measured"
    assert rec["timings_ms"] == {"xla": 3.0, "pallas": 1.0}
    assert tuner.pins() == {"paged_decode_q": "pallas"}


def test_autotuner_failing_candidate_disqualified():
    def dies():
        raise RuntimeError("Mosaic rejected the shape")

    tuner = autotune.Autotuner(device_kind="v5e", timer=autotune._default_timer)
    backend = tuner.measure("decode", "4x97", "float32",
                            {"xla": lambda: jnp.zeros(()), "pallas": dies})
    assert backend == "xla"
    assert "pallas" in tuner.decisions["decode"]["errors"]


def test_pinned_decision_drives_auto_resolution(monkeypatch):
    monkeypatch.delenv("GOFR_PALLAS", raising=False)
    monkeypatch.setenv("GOFR_PALLAS_INTERPRET", "1")
    # interpreter default: 'auto' -> pallas ...
    assert resolve_backend("auto", op="paged_decode_q") == "pallas"
    with autotune.decision_scope({"paged_decode_q": "xla"}):
        # ... but a pinned decision for the op wins ...
        assert resolve_backend("auto", op="paged_decode_q") == "xla"
        # ... and ops without a decision keep the default
        assert resolve_backend("auto", op="decode") == "pallas"
    assert resolve_backend("auto", op="paged_decode_q") == "pallas"  # scope exited


def test_pinned_pallas_needs_kernel_platform(monkeypatch):
    """A 'pallas' pin from a TPU cache file must not make a CPU trace try
    to lower kernels."""
    monkeypatch.delenv("GOFR_PALLAS", raising=False)
    monkeypatch.delenv("GOFR_PALLAS_INTERPRET", raising=False)
    with autotune.decision_scope({"decode": "pallas"}):
        assert resolve_backend("auto", op="decode") == "xla"


def test_gofr_pallas_env_overrides_pin(monkeypatch):
    monkeypatch.setenv("GOFR_PALLAS_INTERPRET", "1")
    with autotune.decision_scope({"paged_decode_q": "xla"}):
        monkeypatch.setenv("GOFR_PALLAS", "1")
        assert resolve_backend("auto", op="paged_decode_q") == "pallas"
    with autotune.decision_scope({"decode": "pallas"}):
        monkeypatch.setenv("GOFR_PALLAS", "0")
        assert resolve_backend("auto", op="decode") == "xla"


def test_autotune_enabled_escape_hatches(monkeypatch):
    monkeypatch.delenv("GOFR_PALLAS", raising=False)
    monkeypatch.delenv("GOFR_PALLAS_INTERPRET", raising=False)
    monkeypatch.delenv("GOFR_AUTOTUNE", raising=False)
    assert autotune.enabled()
    monkeypatch.setenv("GOFR_AUTOTUNE", "0")
    assert not autotune.enabled()
    monkeypatch.delenv("GOFR_AUTOTUNE", raising=False)
    monkeypatch.setenv("GOFR_PALLAS", "1")  # operator override: nothing to tune
    assert not autotune.enabled()
    monkeypatch.delenv("GOFR_PALLAS", raising=False)
    monkeypatch.setenv("GOFR_PALLAS_INTERPRET", "1")  # timings meaningless
    assert not autotune.enabled()


def test_autotune_cache_round_trip(tmp_path):
    path = str(tmp_path / "autotune.json")
    t1 = autotune.Autotuner(device_kind="v5e", cache_file=path,
                            timer=_fake_timer([2e-3, 1e-3]))
    assert t1.measure("paged_decode_q", "8x16", "int8",
                      {"xla": lambda: None, "pallas": lambda: None}) == "pallas"
    doc = json.loads((tmp_path / "autotune.json").read_text())
    assert doc["version"] == autotune.FORMAT_VERSION
    key = autotune.entry_key("v5e", "paged_decode_q", "8x16", "int8")
    assert doc["entries"][key]["backend"] == "pallas"

    def no_timer(fn):
        raise AssertionError("re-timed despite a cache hit")

    t2 = autotune.Autotuner(device_kind="v5e", cache_file=path, timer=no_timer)
    assert t2.measure("paged_decode_q", "8x16", "int8",
                      {"xla": lambda: None, "pallas": lambda: None}) == "pallas"
    assert t2.decisions["paged_decode_q"]["source"] == "cache"
    # a different shape/device is a different key: measured fresh
    t3 = autotune.Autotuner(device_kind="v6e", cache_file=path,
                            timer=_fake_timer([1e-3, 2e-3]))
    assert t3.measure("paged_decode_q", "8x16", "int8",
                      {"xla": lambda: None, "pallas": lambda: None}) == "xla"


def test_sharding_key_isolates_pins_and_stays_read_compatible(tmp_path):
    """ISSUE 19 satellite: per-shard decode shapes change the winner, so a
    tp-sharded engine must never adopt an unsharded pin (or vice versa) —
    the ``|shard=`` suffix isolates them — while "" sharding keeps the
    exact pre-feature key so existing cache files stay valid."""
    # read-compat: no sharding -> the old key, byte for byte
    base = autotune.entry_key("v5e", "paged_decode", "8x16", "bf16")
    assert base == autotune.entry_key("v5e", "paged_decode", "8x16", "bf16",
                                      sharding="")
    assert "shard" not in base
    sharded = autotune.entry_key("v5e", "paged_decode", "8x16", "bf16",
                                 sharding="tp4")
    assert sharded == base + "|shard=tp4"

    # an unsharded engine's pin is STALE for a tp4 engine: same op/shape,
    # fresh measurement under the sharded key, both pins coexist on disk
    path = str(tmp_path / "autotune.json")
    t1 = autotune.Autotuner(device_kind="v5e", cache_file=path,
                            timer=_fake_timer([2e-3, 1e-3]))
    assert t1.measure("paged_decode", "8x16", "bf16",
                      {"xla": lambda: None, "pallas": lambda: None}) == "pallas"
    t2 = autotune.Autotuner(device_kind="v5e", cache_file=path,
                            sharding="tp4", timer=_fake_timer([1e-3, 2e-3]))
    assert t2.measure("paged_decode", "8x16", "bf16",
                      {"xla": lambda: None, "pallas": lambda: None}) == "xla"
    assert t2.decisions["paged_decode"]["source"] == "measured"
    assert t2.report()["sharding"] == "tp4"
    doc = json.loads((tmp_path / "autotune.json").read_text())
    assert doc["entries"][base]["backend"] == "pallas"
    assert doc["entries"][sharded]["backend"] == "xla"

    # and each geometry reloads its OWN pin from the shared file
    def no_timer(fn):
        raise AssertionError("re-timed despite a cache hit")

    for sh, want in (("", "pallas"), ("tp4", "xla")):
        t = autotune.Autotuner(device_kind="v5e", cache_file=path,
                               sharding=sh, timer=no_timer)
        assert t.measure("paged_decode", "8x16", "bf16",
                         {"xla": lambda: None, "pallas": lambda: None}) == want
        assert t.decisions["paged_decode"]["source"] == "cache"


@pytest.mark.parametrize("content", [
    "not json at all {",
    json.dumps({"version": 999, "entries": {"k": {"backend": "pallas"}}}),
    json.dumps({"version": autotune.FORMAT_VERSION, "entries": "nope"}),
    json.dumps({"version": autotune.FORMAT_VERSION,
                "entries": {"v5e|decode|8x16|int8": {"backend": "cuda"}}}),
])
def test_autotune_corrupt_or_stale_cache_ignored(tmp_path, content):
    path = tmp_path / "autotune.json"
    path.write_text(content)
    tuner = autotune.Autotuner(device_kind="v5e", cache_file=str(path),
                               timer=_fake_timer([2e-3, 1e-3]))
    assert tuner.measure("decode", "8x16", "int8",
                         {"xla": lambda: None, "pallas": lambda: None}) == "pallas"
    assert tuner.decisions["decode"]["source"] == "measured"
    # and the file is rewritten valid
    doc = json.loads(path.read_text())
    assert doc["version"] == autotune.FORMAT_VERSION


# -- engine wiring --------------------------------------------------------------


def _tiny_engine(container=None, **kw):
    from gofr_tpu.container import new_mock_container
    from gofr_tpu.models import LlamaConfig, llama
    from gofr_tpu.tpu.engine import GenerateEngine

    cfg = LlamaConfig.tiny()
    params = llama.init(cfg, jax.random.key(0))
    kwargs = dict(slots=2, max_len=32, kv_layout="paged", page_size=8,
                  kv_quantize="int8", prefill_buckets=[16])
    kwargs.update(kw)
    return GenerateEngine(llama, cfg, params, container or new_mock_container(),
                          **kwargs)


def test_engine_warmup_autotune_measures_pins_and_caches(tmp_path, monkeypatch):
    """warmup() times both backends on the engine's real shapes (fake timer
    here), pins the winner for its traces, exposes the report + info gauge,
    and a 'restarted' engine re-pins from the cache file without timing."""
    from gofr_tpu.container import new_mock_container

    monkeypatch.delenv("GOFR_PALLAS", raising=False)
    monkeypatch.delenv("GOFR_PALLAS_INTERPRET", raising=False)
    monkeypatch.delenv("GOFR_AUTOTUNE", raising=False)
    monkeypatch.setenv("GOFR_AUTOTUNE_CACHE", str(tmp_path / "autotune.json"))
    # pretend kernels can lower so BOTH candidates exist; the fake timings
    # make xla win, so no Pallas program is ever actually traced on CPU
    import gofr_tpu.ops.pallas as pallas_pkg

    monkeypatch.setattr(pallas_pkg, "kernel_platform", lambda: True)

    c = new_mock_container()
    eng = _tiny_engine(container=c)
    timed = []

    def fake_timer(fn):
        timed.append(fn)
        return [1e-3, 2e-3][len(timed) - 1]  # xla first (dict order), xla wins

    eng._autotune_timer = fake_timer
    try:
        eng.warmup()
    finally:
        eng.stop()
    assert len(timed) == 2
    assert eng._autotune_pins == {"paged_decode_q": "xla"}
    rep = eng.autotune_report()
    assert rep["decisions"]["paged_decode_q"]["source"] == "measured"
    assert rep["decisions"]["paged_decode_q"]["timings_ms"] == {
        "xla": 1.0, "pallas": 2.0}
    gauge = c.metrics.get("app_tpu_kernel_backend")
    vals = {dict(ls)["backend"]: v for ls, v in gauge._values.items()
            if dict(ls)["op"] == "paged_decode_q"}
    assert vals == {"xla": 1.0, "pallas": 0.0}

    # engine restart (PR5 epochs): the cache file answers, no re-timing
    eng2 = _tiny_engine()

    def no_timer(fn):
        raise AssertionError("re-timed despite the autotune cache")

    eng2._autotune_timer = no_timer
    try:
        eng2.warmup()
    finally:
        eng2.stop()
    assert eng2._autotune_pins == {"paged_decode_q": "xla"}
    assert eng2.autotune_report()["decisions"]["paged_decode_q"]["source"] == "cache"


def test_engine_autotune_escape_hatch_preserves_static_behavior(monkeypatch):
    """GOFR_AUTOTUNE=0 reproduces today's exact behavior: no pins, no
    report, resolution falls through to the static GOFR_PALLAS gate."""
    monkeypatch.setenv("GOFR_AUTOTUNE", "0")
    monkeypatch.delenv("GOFR_PALLAS", raising=False)
    eng = _tiny_engine()
    try:
        eng.warmup()
    finally:
        eng.stop()
    assert eng._autotune_pins == {}
    assert eng.autotune_report() is None


def test_engine_int8_paged_decode_token_exact_pallas_vs_xla(monkeypatch):
    """Acceptance criterion: serving through the engine, the fused int8
    kernel (pinned per op, exactly as the autotuner would pin it) emits
    TOKEN-IDENTICAL greedy output to the XLA gather path in interpreter
    mode. Prefill resolves identically in both runs (interpreter default),
    so the only difference between the two engines is the decode backend."""
    monkeypatch.setenv("GOFR_PALLAS_INTERPRET", "1")
    monkeypatch.delenv("GOFR_PALLAS", raising=False)
    prompts = [[5, 3, 9, 2, 7], [11, 4, 8]]
    tokens = {}
    for backend in ("xla", "pallas"):
        jax.clear_caches()  # backend resolution is a trace-time property
        eng = _tiny_engine(max_len=48)
        eng._autotune_pins = {"paged_decode_q": backend}
        try:
            eng.warmup()
            eng.start()
            tokens[backend] = [
                eng.generate(p, max_new_tokens=6, timeout=300)["tokens"]
                for p in prompts
            ]
        finally:
            eng.stop()
    assert tokens["pallas"] == tokens["xla"]
    jax.clear_caches()
