"""The unified async device pipeline (ISSUE 3 tentpole): batched and
chunked prefill dispatch onto the same bounded in-flight queue as decode,
with readback + slot bookkeeping at dequeue.

Load-bearing properties proven here:

- OVERLAP: decode chunks are dispatched between a prefill's dispatch and
  its readback (the device-idle bubble the synchronous paths had) — no
  synchronous ``np.asarray`` on a device result inside ``_admit`` or
  ``_advance_chunked`` (warmup excluded);
- EXACTNESS: mixed continuous arrivals (long chunked prompts against
  active decode slots) produce tokens identical to the sequential
  reference AND to the fully synchronous depth-1 engine, including under
  paged-pool preemption and stop()-mid-traffic;
- LOCKSTEP: the leader's announce stream, recorded under the async
  pipeline, replays through a follower to a bit-identical device state
  (announce order == dispatch order);
- BOOKKEEPING: the incrementally-maintained lane sets never drift from a
  rescan of ``engine.slots``.
"""

import threading
import time

import jax
import numpy as np
import pytest

from gofr_tpu.container import new_mock_container
from gofr_tpu.models import LlamaConfig, llama
from gofr_tpu.testutil import (
    assert_lane_sets_consistent,
    assert_page_refs_consistent,
    assert_paged_pool_consistent,
)
from gofr_tpu.tpu.engine import GenerateEngine


@pytest.fixture(scope="module")
def setup():
    cfg = LlamaConfig.tiny()
    params = llama.init(cfg, jax.random.key(7))

    def ref(prompt, n):
        import jax.numpy as jnp

        seq = list(prompt)
        for _ in range(n):
            logits = llama.forward(cfg, params, jnp.asarray([seq], jnp.int32))
            seq.append(int(jnp.argmax(logits[0, -1])))
        return seq[len(prompt):]

    return cfg, params, ref


def _teardown(eng):
    """Shared engine teardown: full page-refs/lane-set consistency
    (testutil.assert_page_refs_consistent) before stopping."""
    try:
        assert_page_refs_consistent(eng)
        assert_lane_sets_consistent(eng)
    finally:
        eng.stop()


def make_engine(cfg, params, **kw):
    kw.setdefault("slots", 4)
    kw.setdefault("max_len", 64)
    kw.setdefault("max_prefill_batch", 2)
    return GenerateEngine(llama, cfg, params, new_mock_container(), **kw)


class _TracedTokens:
    """Wraps a dispatched token future; records WHEN the host reads it
    back (process_decode's np.asarray) relative to other dispatches."""

    def __init__(self, dev, events, label):
        self._dev = dev
        self._events = events
        self._label = label

    def __array__(self, dtype=None, copy=None):
        self._events.append(self._label)
        out = np.asarray(self._dev)
        return out.astype(dtype) if dtype is not None else out


def _instrument(eng):
    """Wrap the engine's compiled handles so dispatches and readbacks
    append ordered events (device-thread only, so a plain list is safe)."""
    events: list[str] = []
    chunk_prefill = getattr(eng, "_chunk_prefill", None)
    prefill_sample = eng._prefill_sample
    decode_chunk = eng._decode_chunk

    def traced_chunk(params, key, cache, packed):
        events.append("chunk_dispatch")
        toks, cache = chunk_prefill(params, key, cache, packed)
        return _TracedTokens(toks, events, "chunk_readback"), cache

    def traced_prefill(params, key, cache, packed):
        events.append("prefill_dispatch")
        toks, cache = prefill_sample(params, key, cache, packed)
        return _TracedTokens(toks, events, "prefill_readback"), cache

    def traced_decode(params, key, cache, steps, packed, prev):
        events.append("decode_dispatch")
        return decode_chunk(params, key, cache, steps, packed, prev)

    if chunk_prefill is not None:
        eng._chunk_prefill = traced_chunk
    eng._prefill_sample = traced_prefill
    eng._decode_chunk = traced_decode
    return events


def _spin_up_decoder(eng, prompt=(3, 1, 4), max_new=48):
    """Get one slot actively decoding and keep it busy for many loop
    iterations (the 'active decode slots' half of the mixed workload)."""
    req = eng.submit(list(prompt), max_new_tokens=max_new, timeout=120)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and not eng._decode_lanes:
        time.sleep(0.005)
    assert eng._decode_lanes, "decoder slot never became active"
    return req


def _overlapped(events, dispatch, readback):
    """True if any decode_dispatch sits strictly between a ``dispatch``
    event and its matching (next) ``readback`` event."""
    for i, ev in enumerate(events):
        if ev != dispatch:
            continue
        for j in range(i + 1, len(events)):
            if events[j] == readback:
                if any(e == "decode_dispatch" for e in events[i + 1:j]):
                    return True
                break
    return False


@pytest.mark.quick
def test_decode_dispatched_between_chunk_prefill_dispatch_and_readback(setup):
    """The CI overlap guarantee: while a chunked prefill's readback is in
    flight, the loop keeps dispatching decode chunks for the active slots
    — i.e. _advance_chunked no longer blocks on np.asarray inline."""
    cfg, params, ref = setup
    eng = make_engine(cfg, params, prefill_buckets=[8], decode_chunk=1)
    events = _instrument(eng)
    long_prompt = [(7 * i) % 190 + 1 for i in range(21)]  # 3 chunks of ≤8
    try:
        dec = _spin_up_decoder(eng)
        out = eng.generate(long_prompt, max_new_tokens=4, timeout=120)
        assert out["tokens"] == ref(long_prompt, 4)
        dec.result(120)
        assert "chunk_dispatch" in events, "long prompt skipped the chunked path"
        assert _overlapped(events, "chunk_dispatch", "chunk_readback"), (
            "no decode chunk was dispatched between a chunked prefill's "
            f"dispatch and its readback: {events}"
        )
        assert_lane_sets_consistent(eng)
    finally:
        _teardown(eng)


@pytest.mark.quick
def test_decode_dispatched_between_prefill_dispatch_and_readback(setup):
    """Same guarantee for the BATCHED prefill path: an arriving batch's
    readback overlaps decode dispatch instead of stalling every slot."""
    cfg, params, ref = setup
    eng = make_engine(cfg, params, decode_chunk=1)
    events = _instrument(eng)
    try:
        dec = _spin_up_decoder(eng)
        out = eng.generate([5, 3, 9], max_new_tokens=4, timeout=120)
        assert out["tokens"] == ref([5, 3, 9], 4)
        dec.result(120)
        # two prefill dispatches happened (the decoder's own and the probe);
        # the probe's — arriving against an active decoder — must overlap
        assert events.count("prefill_dispatch") >= 2
        assert _overlapped(events, "prefill_dispatch", "prefill_readback"), (
            "no decode chunk was dispatched between a batched prefill's "
            f"dispatch and its readback: {events}"
        )
        assert_lane_sets_consistent(eng)
    finally:
        _teardown(eng)


@pytest.mark.parametrize("kv_layout", ["slot", "paged"])
def test_mixed_arrivals_token_exact(setup, kv_layout):
    """Continuous mixed arrivals — long chunked prompts landing while
    other slots decode — must be token-exact vs the sequential reference
    at the async depth AND at the synchronous depth 1 (the acceptance
    stress case: decode no longer collapses, correctness unchanged)."""
    cfg, params, ref = setup
    rngs = np.random.RandomState(11)
    prompts = []
    for i in range(10):
        if i % 3 == 2:  # every 3rd arrival is a long (chunked) prompt
            n = 17 + (i % 2) * 4
        else:
            n = 2 + i % 4
        prompts.append([int(x) for x in rngs.randint(1, 200, size=n)])
    # 16 new tokens: resident slots GROW past the minimum pool, so paged
    # runs are guaranteed to hit preemption-by-recompute mid-traffic
    want = [ref(p, 16) for p in prompts]

    for depth in (2, 1):
        kw = dict(slots=3, max_len=64, max_prefill_batch=2,
                  prefill_buckets=[8], decode_pipeline=depth)
        if kv_layout == "paged":
            # the minimum legal pool (== pages_per_slot): any two resident
            # requests contend, so preemption-by-recompute fires mid-traffic
            kw.update(kv_layout="paged", page_size=8, total_pages=9)
        eng = make_engine(cfg, params, **kw)
        results = [None] * len(prompts)

        def worker(i):
            time.sleep(0.01 * i)  # paced arrivals, not one up-front burst
            results[i] = eng.generate(prompts[i], max_new_tokens=16, timeout=300)

        try:
            threads = [threading.Thread(target=worker, args=(i,))
                       for i in range(len(prompts))]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=300)
            for i, r in enumerate(results):
                assert r is not None, f"depth={depth} request {i} never completed"
                assert r["tokens"] == want[i], (
                    f"depth={depth} {kv_layout} request {i} diverged"
                )
            assert_lane_sets_consistent(eng)
            if kv_layout == "paged":
                # the small pool forces preemption-by-recompute mid-traffic
                pre = eng.metrics.get("app_tpu_preemptions")
                assert pre is not None and sum(pre._values.values()) >= 1, (
                    "pool was not small enough to exercise preemption"
                )
                assert_paged_pool_consistent(eng, slots_empty=True)
        finally:
            _teardown(eng)


def test_depth4_token_exact(setup):
    """Deeper in-flight queues (the knob now allows up to 4) stay exact:
    the dead-lane masking bound is depth-generic."""
    cfg, params, ref = setup
    prompts = [[i + 2, (3 * i) % 190 + 1] for i in range(5)]
    want = [ref(p, 8) for p in prompts]
    eng = make_engine(cfg, params, pipeline_depth=4, decode_chunk=2)
    assert eng.pipeline_depth == 4
    try:
        reqs = [eng.submit(p, max_new_tokens=8, timeout=300) for p in prompts]
        got = [r.result(300)["tokens"] for r in reqs]
        assert got == want
        assert not eng._dq or len(eng._dq) <= 3
    finally:
        _teardown(eng)


def test_stop_mid_mixed_traffic_frees_all_state(setup):
    """stop() while prefills (batched AND chunked) are in flight on the
    queue: every request completes exactly once, claimed slots/pages are
    released through the slot sweep — never stranded on lanes whose fold
    never ran."""
    cfg, params, _ = setup
    eng = make_engine(cfg, params, slots=2, prefill_buckets=[8],
                      kv_layout="paged", page_size=8)
    long_prompt = [(3 * i) % 150 + 2 for i in range(25)]
    reqs = [eng.submit(long_prompt if i % 3 == 0 else [i + 1, i + 2],
                       max_new_tokens=30, timeout=120) for i in range(9)]
    deadline = time.time() + 10
    while time.time() < deadline and not (eng._prefill_lanes or eng._decode_lanes):
        time.sleep(0.01)
    assert eng._prefill_lanes or eng._decode_lanes, "nothing was ever admitted"
    eng.stop()
    hung = 0
    for r in reqs:
        try:
            r.result(10)
        except Exception:  # noqa: BLE001 - errors are the expected outcome
            if not r._done.is_set():
                hung += 1
    assert hung == 0, f"{hung} request(s) hung across stop()"
    assert all(s is None for s in eng.slots)
    assert_lane_sets_consistent(eng)
    assert_paged_pool_consistent(eng, slots_empty=True)


class _RecordingChannel:
    """Stands in for the announce transport (fleet/channel.py interface):
    captures the (header, payload) frame stream the leader would put on
    the fabric, then replays it through a same-config follower."""

    supports_rejoin = False

    def __init__(self):
        self.stream: list[tuple[np.ndarray, np.ndarray | None]] = []
        self._replay = None
        self._payload = None

    # leader side
    def send(self, header, payload):
        self.stream.append((
            np.array(header, np.int32, copy=True),
            None if payload is None else np.array(payload, np.int32, copy=True),
        ))

    def close(self):
        pass

    # follower side (consumes the recorded stream)
    def recv_header(self):
        header, self._payload = next(self._replay)
        return header

    def recv_payload(self, shape):
        payload, self._payload = self._payload, None
        assert payload is not None and payload.shape == tuple(shape), (
            "follower reconstructed a different payload shape than the "
            f"leader announced: {None if payload is None else payload.shape} "
            f"vs {shape}"
        )
        return payload

    def start_replay(self):
        self._replay = iter(self.stream)


@pytest.mark.quick
@pytest.mark.parametrize("kv_layout", ["slot", "paged"])
def test_lockstep_replay_reproduces_device_state(setup, kv_layout):
    """Leader/follower determinism under the async pipeline: the announce
    stream recorded while the leader serves overlapped mixed traffic must
    replay through LockstepFollower to a BIT-IDENTICAL final cache (and
    decode carry) on a same-config engine — announce order is dispatch
    order, and every header reconstructs the payload shape exactly."""
    from gofr_tpu.tpu import lockstep as ls_mod
    from gofr_tpu.tpu.lockstep import LockstepFollower

    cfg, params, ref = setup
    kw = dict(slots=2, max_len=48, max_prefill_batch=1, decode_chunk=2,
              prefill_buckets=[8], seed=5)
    if kv_layout == "paged":
        kw.update(kv_layout="paged", page_size=8, prefix_cache=False)
    leader = make_engine(cfg, params, **kw)
    chan = _RecordingChannel()
    leader._ls = ls_mod.LockstepLeader(channel=chan)
    long_prompt = [(5 * i) % 150 + 1 for i in range(13)]
    try:
        reqs = [leader.submit(p, max_new_tokens=5, timeout=120)
                for p in ([3, 7, 11], long_prompt, [9, 2])]
        outs = [r.result(120) for r in reqs]
        assert outs[1]["tokens"] == ref(long_prompt, 5)
    finally:
        leader.stop()
    assert chan.stream and int(chan.stream[-1][0][0]) == ls_mod.TAG_STOP

    chan.start_replay()
    follower = make_engine(cfg, params, **kw)
    try:
        LockstepFollower(follower, channel=chan).run()
        leader_leaves = jax.tree.leaves(leader.cache)
        follower_leaves = jax.tree.leaves(follower.cache)
        assert len(leader_leaves) == len(follower_leaves)
        for a, b in zip(leader_leaves, follower_leaves):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        if leader._prev_last is not None or follower._prev_last is not None:
            np.testing.assert_array_equal(
                np.asarray(leader._prev_last), np.asarray(follower._prev_last))
    finally:
        follower._poisoned = True  # never started a device thread; stop() noop
        follower._stop.set()
