"""Text serving path: ByteTokenizer + the engine's incremental stream
detokenization (VERDICT r3 weak #5 — string prompt in, valid UTF-8 text
out, even when multi-byte characters span token boundaries)."""

import jax
import jax.numpy as jnp
import pytest

from gofr_tpu.container import new_mock_container
from gofr_tpu.models import LlamaConfig, llama
from gofr_tpu.tpu.engine import GenerateEngine
from gofr_tpu.utils import ByteTokenizer


class TestByteTokenizer:
    def test_roundtrip_ascii_and_multibyte(self):
        t = ByteTokenizer()
        for s in ("hello", "héllo wörld", "日本語", "mixed ✓ text"):
            assert t.decode(t.encode(s)) == s

    def test_specials(self):
        t = ByteTokenizer()
        assert t.encode("hi", add_bos=True)[0] == t.bos_token_id
        assert t.decode([t.bos_token_id, t.eos_token_id]) == ""
        assert t.vocab_size == 259

    def test_partial_utf8_shows_replacement(self):
        t = ByteTokenizer()
        full = t.encode("é")  # 2 bytes
        assert t.decode(full[:1]) == "�"
        assert t.decode(full) == "é"


@pytest.fixture(scope="module")
def text_setup():
    cfg = LlamaConfig.tiny(vocab_size=300)  # covers the byte tokenizer's 259 ids
    params = llama.init(cfg, jax.random.key(11))

    def ref(prompt_ids, n_new):
        seq = list(prompt_ids)
        for _ in range(n_new):
            logits = llama.forward(cfg, params, jnp.asarray([seq], jnp.int32))
            seq.append(int(jnp.argmax(logits[0, -1])))
        return seq[len(prompt_ids):]

    return cfg, params, ref


def make_text_engine(cfg, params, **kw):
    kw.setdefault("slots", 4)
    kw.setdefault("max_len", 64)
    kw.setdefault("max_prefill_batch", 2)
    kw.setdefault("tokenizer", ByteTokenizer())
    return GenerateEngine(llama, cfg, params, new_mock_container(), **kw)


class TestEngineTextPath:
    def test_string_prompt_matches_token_ids(self, text_setup):
        cfg, params, ref = text_setup
        eng = make_text_engine(cfg, params)
        tok = ByteTokenizer()
        try:
            out = eng.generate("hello", max_new_tokens=5, timeout=120)
            assert out["tokens"] == ref(tok.encode("hello"), 5)
            assert out["text"] == tok.decode(out["tokens"])
        finally:
            eng.stop()

    def test_stream_pieces_join_to_final_text(self, text_setup):
        """Streamed pieces must be valid UTF-8 and concatenate to the final
        text, with no partial-character replacement glyphs leaking even
        when the (random) model emits split multi-byte sequences."""
        cfg, params, _ = text_setup
        eng = make_text_engine(cfg, params)
        try:
            it = eng.generate("héllo ✓", max_new_tokens=24, timeout=120, stream=True)
            pieces = list(it)
            # final result text for the same prompt (greedy, deterministic)
            out = eng.generate("héllo ✓", max_new_tokens=24, timeout=120)
            joined = "".join(pieces)
            assert all(isinstance(p, str) for p in pieces)
            # exact-join: nothing lost or duplicated, incomplete trailing
            # characters included (a random model emits invalid bytes, so
            # U+FFFD glyphs are legitimate content — equality is the
            # invariant; the split-character hold is proven deterministic
            # in test_split_character_held_until_complete)
            assert joined == out["text"], f"{joined!r} != {out['text']!r}"
        finally:
            eng.stop()

    def test_split_character_held_until_complete(self, text_setup):
        """Deterministic check of the stream-detokenizer hold: a 2-byte
        character arriving one byte-token at a time emits NOTHING until the
        second token completes it — driven through _emit directly (model
        outputs are random, so only a fabricated slot can pin this down)."""
        import queue

        from gofr_tpu.tpu.engine import Request, _Slot

        cfg, params, _ = text_setup
        eng = make_text_engine(cfg, params)
        tok = ByteTokenizer()
        try:
            req = Request([1], {}, None, stream=True)
            slot = _Slot(req, prompt_len=1, max_total=10, eos=None, first_token=None)
            ids = tok.encode("é")
            assert len(ids) == 2
            eng._emit(slot, ids[0])
            with pytest.raises(queue.Empty):
                req.stream_q.get_nowait()  # first byte held — incomplete char
            eng._emit(slot, ids[1])
            assert req.stream_q.get_nowait() == "é"
            eng._emit(slot, tok.encode("x")[0])
            assert req.stream_q.get_nowait() == "x"
        finally:
            eng.stop()

    def test_spec_decode_streams_text(self, text_setup):
        """Speculative rounds emit several tokens per device call; the
        stream detokenizer must still produce the exact final text."""
        cfg, params, _ = text_setup
        eng = make_text_engine(cfg, params, spec_tokens=3, decode_chunk=4,
                               kv_layout="slot")
        try:
            pieces = list(eng.generate("spec me", max_new_tokens=16,
                                       timeout=300, stream=True))
            out = eng.generate("spec me", max_new_tokens=16, timeout=300)
            assert "".join(pieces) == out["text"]
        finally:
            eng.stop()

    def test_no_tokenizer_streams_raw_ids(self, text_setup):
        cfg, params, ref = text_setup
        eng = make_text_engine(cfg, params, tokenizer=None)
        try:
            it = eng.generate([5, 9, 2], max_new_tokens=4, timeout=120, stream=True)
            toks = list(it)
            assert toks == ref([5, 9, 2], 4)
            with pytest.raises(ValueError, match="no tokenizer"):
                eng.generate("text prompt", max_new_tokens=2, timeout=120)
        finally:
            eng.stop()
