"""Prefix cache: token-addressed KV page reuse on the paged engine.

The unit tier exercises the chain/eviction bookkeeping of
``gofr_tpu.tpu.prefix.PrefixCache`` directly; the engine tier proves the
load-bearing property — a prefix HIT changes which pages feed attention but
never the tokens produced (greedy) — plus refcounted pool accounting with
shared pages and LRU eviction under pool pressure before preemption.
"""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gofr_tpu.container import new_mock_container
from gofr_tpu.models import LlamaConfig, llama
from gofr_tpu.testutil import assert_page_refs_consistent, assert_paged_pool_consistent
from gofr_tpu.tpu.engine import GenerateEngine
from gofr_tpu.tpu.prefix import PrefixCache


class TestPrefixCacheUnit:
    def test_insert_then_lookup_multi_page(self):
        c = PrefixCache(4)
        toks = np.arange(10)  # 2 full pages + a 2-token remainder
        assert c.insert(toks, [7, 3]) == [7, 3]
        assert c.lookup(toks) == [7, 3]
        assert c.lookup(np.arange(8)) == [7, 3]
        diverges = np.concatenate([np.arange(4), np.array([99, 98, 97, 96])])
        assert c.lookup(diverges) == [7]
        assert c.lookup(np.array([50, 51, 52, 53])) == []

    def test_insert_skips_existing_chain_positions(self):
        c = PrefixCache(4)
        c.insert(np.arange(8), [1, 2])
        # same first two pages from a different request's own pages: only the
        # extension page is newly retained — the existing pages hold
        # identical K/V and serve both chains
        assert c.insert(np.arange(12), [10, 11, 12]) == [12]
        assert c.lookup(np.arange(12)) == [1, 2, 12]
        assert len(c) == 3

    def test_evict_lru_takes_leaves_before_interior(self):
        c = PrefixCache(4)
        c.insert(np.arange(8), [1, 2])
        assert c.evict_lru() == 2  # leaf; evicting node 1 first would leak 2
        assert c.lookup(np.arange(8)) == [1]
        assert c.evict_lru() == 1
        assert c.evict_lru() is None

    def test_lookup_touch_protects_from_eviction(self):
        c = PrefixCache(2)
        c.insert(np.array([1, 1]), [5])
        c.insert(np.array([9, 9]), [6])
        c.lookup(np.array([1, 1]))  # chain A is now more recent than B
        assert c.evict_lru() == 6
        assert c.evict_lru() == 5

    def test_parent_chain_distinguishes_identical_pages(self):
        """Two chains whose second page holds identical tokens are distinct
        prefixes — ancestry must disambiguate (ADVICE r3)."""
        c = PrefixCache(2)
        a, b = np.array([1, 1, 7, 7]), np.array([2, 2, 7, 7])
        c.insert(a, [10, 11])
        c.insert(b, [20, 21])
        assert c.lookup(a) == [10, 11]
        assert c.lookup(b) == [20, 21]

    def test_interior_recency_survives_leaf_eviction(self):
        """An interior node touched while it had children must carry that
        recency when it becomes a leaf (lazy-heap staleness handling)."""
        c = PrefixCache(2)
        c.insert(np.array([1, 1, 2, 2]), [10, 11])  # chain A: 10 -> 11
        c.insert(np.array([3, 3]), [30])            # chain B
        c.lookup(np.array([1, 1]))                  # touch interior node 10
        assert c.evict_lru() == 11                  # only leaf of chain A
        # node 10 is now a leaf, touched AFTER 30 was created
        assert c.evict_lru() == 30
        assert c.evict_lru() == 10

    def test_clear_returns_all_pages(self):
        c = PrefixCache(2)
        c.insert(np.arange(4), [1, 2])
        assert sorted(c.clear()) == [1, 2]
        assert len(c) == 0
        assert c.lookup(np.arange(4)) == []
        assert c.evict_lru() is None


# -- engine integration (paged layout, CPU mesh) --------------------------------


@pytest.fixture(scope="module")
def setup():
    cfg = LlamaConfig.tiny()
    params = llama.init(cfg, jax.random.key(7))

    def ref(prompt, n_new):
        seq = list(prompt)
        for _ in range(n_new):
            logits = llama.forward(cfg, params, jnp.asarray([seq], jnp.int32))
            seq.append(int(jnp.argmax(logits[0, -1])))
        return seq[len(prompt):]

    return cfg, params, ref


def make_engine(cfg, params, **kw):
    kw.setdefault("slots", 4)
    kw.setdefault("max_len", 64)
    kw.setdefault("max_prefill_batch", 2)
    kw.setdefault("kv_layout", "paged")
    kw.setdefault("page_size", 8)
    return GenerateEngine(llama, cfg, params, new_mock_container(), **kw)


def _teardown(eng):
    """Shared engine teardown: full page-refs consistency
    (testutil.assert_page_refs_consistent) before stopping."""
    try:
        assert_page_refs_consistent(eng)
    finally:
        eng.stop()


def _counter_sum(eng, name):
    m = eng.metrics.get(name)
    return sum(m._values.values()) if m is not None else 0


class TestPrefixEngine:
    def test_hit_matches_cold_token_exact(self, setup):
        """Same prompt twice: the second run serves its prefix from cached
        pages (metrics prove it) and produces IDENTICAL greedy tokens."""
        cfg, params, ref = setup
        eng = make_engine(cfg, params)
        prompt = [(11 * i) % 190 + 1 for i in range(20)]  # 2 full pages @ 8
        want = ref(prompt, 6)
        try:
            cold = eng.generate(prompt, max_new_tokens=6, timeout=120)
            assert cold["tokens"] == want
            assert _counter_sum(eng, "app_tpu_prefix_hit_tokens") == 0
            assert len(eng._prefix) == 2  # both full prompt pages retained
            hot = eng.generate(prompt, max_new_tokens=6, timeout=120)
            assert hot["tokens"] == want, "prefix hit changed greedy tokens"
            assert _counter_sum(eng, "app_tpu_prefix_hit_tokens") == 16
            assert_paged_pool_consistent(eng, slots_empty=True)
        finally:
            _teardown(eng)

    def test_extension_chains_interleave(self, setup):
        """p2 extends p1's prefix; p1 re-issued after p2 still exact; the
        chain interleaves pages registered by different requests."""
        cfg, params, ref = setup
        base = [(7 * i) % 150 + 1 for i in range(28)]
        p1, p2 = base[:20], base  # share 2 full pages; p2 adds a 3rd
        cfg_, params_, _ = setup
        eng = make_engine(cfg, params)
        try:
            assert eng.generate(p1, max_new_tokens=4, timeout=120)["tokens"] == ref(p1, 4)
            assert eng.generate(p2, max_new_tokens=4, timeout=120)["tokens"] == ref(p2, 4)
            assert eng.generate(p1, max_new_tokens=4, timeout=120)["tokens"] == ref(p1, 4)
            assert len(eng._prefix) == 3  # 2 shared + 1 extension page
            assert _counter_sum(eng, "app_tpu_prefix_hit_tokens") > 0
            assert_paged_pool_consistent(eng, slots_empty=True)
        finally:
            _teardown(eng)

    def test_concurrent_shared_prefix(self, setup):
        """8 concurrent requests sharing a 16-token prefix with distinct
        suffixes all match the sequential reference."""
        cfg, params, ref = setup
        shared = [(5 * i) % 120 + 1 for i in range(16)]
        prompts = [shared + [i + 1, 2 * i + 1, (3 * i) % 90 + 1] for i in range(8)]
        want = [ref(p, 5) for p in prompts]
        eng = make_engine(cfg, params)
        results = [None] * len(prompts)

        def worker(i):
            results[i] = eng.generate(prompts[i], max_new_tokens=5, timeout=300)

        try:
            # seed the cache so the concurrent wave actually hits
            eng.generate(shared + [7], max_new_tokens=1, timeout=120)
            threads = [threading.Thread(target=worker, args=(i,)) for i in range(len(prompts))]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=300)
            for i, r in enumerate(results):
                assert r is not None, f"request {i} did not complete"
                assert r["tokens"] == want[i], f"request {i} diverged on shared prefix"
            assert _counter_sum(eng, "app_tpu_prefix_hit_tokens") >= 8 * 16
            assert_paged_pool_consistent(eng, slots_empty=True)
        finally:
            _teardown(eng)

    def test_eviction_under_pool_pressure(self, setup):
        """Distinct prompts fill the cache until pool pressure; LRU leaves
        are evicted (no preemption needed for sequential load) and every
        generation stays exact."""
        cfg, params, ref = setup
        # pages_per_slot = ceil((64+8)/8) = 9; pool of 12 pages forces
        # eviction once the cache holds more than 3 pages
        eng = make_engine(cfg, params, total_pages=12)
        try:
            for r in range(5):
                prompt = [(r * 37 + 13 * i) % 180 + 2 for i in range(18)]
                out = eng.generate(prompt, max_new_tokens=4, timeout=300)
                assert out["tokens"] == ref(prompt, 4), f"round {r} diverged"
            assert len(eng._prefix) <= 12
            assert _counter_sum(eng, "app_tpu_preemptions") == 0, (
                "sequential load should be absorbed by cache eviction, not preemption"
            )
            assert_paged_pool_consistent(eng, slots_empty=True)
        finally:
            _teardown(eng)

    def test_disabled_prefix_cache(self, setup):
        """prefix_cache=False: no retention, pool drains back to fully free."""
        cfg, params, ref = setup
        eng = make_engine(cfg, params, prefix_cache=False)
        prompt = [(11 * i) % 190 + 1 for i in range(20)]
        try:
            out = eng.generate(prompt, max_new_tokens=4, timeout=120)
            assert out["tokens"] == ref(prompt, 4)
            assert eng._prefix is None
            assert sorted(eng._free_pages) == list(range(eng.total_pages))
        finally:
            _teardown(eng)
