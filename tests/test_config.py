
import pytest

from gofr_tpu.config import DictConfig, EnvConfig, parse_dotenv

pytestmark = pytest.mark.quick


def test_parse_dotenv_basics():
    text = """
# comment
APP_NAME=svc
HTTP_PORT = 8000
QUOTED="hello world"
SINGLE='x y'
export EXPORTED=1
INLINE=value # trailing comment
EMPTY=
NOEQ
"""
    values = parse_dotenv(text)
    assert values["APP_NAME"] == "svc"
    assert values["HTTP_PORT"] == "8000"
    assert values["QUOTED"] == "hello world"
    assert values["SINGLE"] == "x y"
    assert values["EXPORTED"] == "1"
    assert values["INLINE"] == "value"
    assert values["EMPTY"] == ""
    assert "NOEQ" not in values


def test_parse_dotenv_quoted_with_inline_comment():
    values = parse_dotenv('PASS="p@ss word" # secret\nURL="http://x" #c\n')
    assert values["PASS"] == "p@ss word"
    assert values["URL"] == "http://x"


def test_env_file_layering(tmp_path):
    configs = tmp_path / "configs"
    configs.mkdir()
    (configs / ".env").write_text("A=base\nB=base\nAPP_ENV=stage\n")
    (configs / ".stage.env").write_text("B=stage\n")
    cfg = EnvConfig(folder=str(configs), environ={})
    assert cfg.get("A") == "base"
    assert cfg.get("B") == "stage"  # overlay wins


def test_local_overlay_when_no_app_env(tmp_path):
    configs = tmp_path / "configs"
    configs.mkdir()
    (configs / ".env").write_text("A=base\n")
    (configs / ".local.env").write_text("A=local\n")
    cfg = EnvConfig(folder=str(configs), environ={})
    assert cfg.get("A") == "local"


def test_real_environ_wins(tmp_path):
    configs = tmp_path / "configs"
    configs.mkdir()
    (configs / ".env").write_text("A=file\n")
    cfg = EnvConfig(folder=str(configs), environ={"A": "env"})
    assert cfg.get("A") == "env"


def test_typed_getters():
    cfg = DictConfig({"N": "5", "F": "2.5", "B": "true", "BAD": "x"})
    assert cfg.get_int("N", 0) == 5
    assert cfg.get_int("BAD", 7) == 7
    assert cfg.get_int("MISSING", 3) == 3
    assert cfg.get_float("F", 0.0) == 2.5
    assert cfg.get_bool("B") is True
    assert cfg.get_bool("MISSING", True) is True
    assert cfg.get_or_default("MISSING", "d") == "d"


def test_missing_folder_ok(tmp_path):
    cfg = EnvConfig(folder=str(tmp_path / "nope"), environ={})
    assert cfg.get("ANYTHING") is None


def test_every_knob_is_documented():
    """docs/configs.md must cover every ENGINE_*/GOFR_*/QOS_* knob in the source.

    Generated-from-grep so the catalog can't silently drift as knobs are
    added (the reference ships a complete configs catalog:
    docs/references/configs/page.md).
    """
    import pathlib
    import re

    root = pathlib.Path(__file__).resolve().parents[1]
    knobs: set = set()
    sources = [root / "bench.py", root / "__graft_entry__.py"]
    for base in (root / "gofr_tpu", root / "scripts", root / "examples"):
        sources.extend(p for p in base.rglob("*.py"))
        sources.extend(p for p in base.rglob("*.sh"))
    for path in sources:
        text = path.read_text(errors="ignore")
        knobs.update(re.findall(r"\b(?:ENGINE|GOFR|QOS)_[A-Z][A-Z0-9_]+", text))
    docs = (root / "docs" / "configs.md").read_text()
    missing = sorted(k for k in knobs if k not in docs)
    assert not missing, f"undocumented knobs (add to docs/configs.md): {missing}"
