"""Multi-host lockstep SERVING (tpu/lockstep.py): two REAL processes over a
localhost coordinator form a global tp:4 mesh (2 CPU devices each); process
0 runs the full engine and serves requests, process 1 executes the
announced programs. Tokens must match single-device greedy decoding — the
cross-process analog of test_mesh_serving, with the params genuinely
sharded across the process boundary (tp collectives ride the global mesh).
"""

import os
import socket
import subprocess
import sys
import textwrap

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from jaxpin import child_env  # noqa: E402

_WORKER = textwrap.dedent("""
    import faulthandler, os, sys
    faulthandler.dump_traceback_later(560, exit=True)  # post-mortem on hang
    sys.path.insert(0, {repo!r})
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from gofr_tpu.container import new_mock_container
    from gofr_tpu.models import ModelSpec
    from gofr_tpu.testutil import greedy_reference, tiny_f32_llama
    from gofr_tpu.tpu.engine import build_engine

    pid = int(sys.argv[1])
    c = new_mock_container({{
        "JAX_COORDINATOR": "127.0.0.1:{port}",
        "JAX_NUM_PROCESSES": "2",
        "JAX_PROCESS_ID": str(pid),
        "TPU_MESH": "tp:4",
        "ENGINE_KV_LAYOUT": "slot",
    }})
    assert c.tpu.distributed and jax.process_count() == 2

    cfg, params_unused = tiny_f32_llama()
    eng = build_engine(ModelSpec("llama", cfg, task="generate"), c, seed=3,
                       slots=2, max_len=64, max_prefill_batch=1,
                       prefill_buckets=[16], decode_chunk=4)
    assert eng.lockstep_role == ("leader" if pid == 0 else "follower"), eng.lockstep_role

    if pid == 0:
        # the engine's params are GLOBAL (tp-sharded across processes);
        # any jit over them from one process alone would hang waiting for
        # the other. The reference rebuilds them process-locally from the
        # same seed instead.
        from gofr_tpu.models import llama
        local_params = llama.init(cfg, jax.random.key(3))
        ref = greedy_reference(cfg, local_params)
        prompts = [[3, 7, 11], [5, 2, 9, 4]]
        try:
            outs = [eng.generate(p, max_new_tokens=5, timeout=240) for p in prompts]
            for p, o in zip(prompts, outs):
                want = ref(p, 5)
                assert o["tokens"] == want, (o["tokens"], want)
        finally:
            eng.stop()
        print("LOCKSTEP_OK leader served token-exact across 2 processes")
    else:
        eng.serve_follower()
        print("LOCKSTEP_OK follower drained and exited on stop")
""")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_lockstep_serving(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    port = _free_port()
    src = _WORKER.format(repo=repo, port=port)
    env = child_env()
    env.pop("XLA_FLAGS", None)

    logs = [open(tmp_path / f"worker{pid}.log", "w+") for pid in (0, 1)]
    procs = [
        subprocess.Popen([sys.executable, "-c", src, str(pid)],
                         env=env, stdout=logs[pid],
                         stderr=subprocess.STDOUT, text=True)
        for pid in (0, 1)
    ]

    def slurp():
        out = []
        for f in logs:
            f.flush()
            f.seek(0)
            out.append(f.read())
        return out

    try:
        for p in procs:
            p.wait(timeout=600)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail(f"lockstep workers hung:\n{chr(10).join(slurp())[-5000:]}")
    outs = slurp()
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out[-4000:]}"
        assert "LOCKSTEP_OK" in out, out[-4000:]


_KILL_WORKER = textwrap.dedent("""
    import faulthandler, os, signal, sys
    faulthandler.dump_traceback_later(560, exit=True)  # post-mortem on hang
    sys.path.insert(0, {repo!r})
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax
    jax.config.update("jax_platforms", "cpu")

    from gofr_tpu.container import new_mock_container
    from gofr_tpu.models import ModelSpec
    from gofr_tpu.testutil import tiny_f32_llama
    from gofr_tpu.tpu.engine import build_engine

    pid = int(sys.argv[1])
    c = new_mock_container({{
        "JAX_COORDINATOR": "127.0.0.1:{port}",
        "JAX_NUM_PROCESSES": "2",
        "JAX_PROCESS_ID": str(pid),
        "TPU_MESH": "tp:4",
        "ENGINE_KV_LAYOUT": "slot",
        "LOCKSTEP_DEADLINE_S": "8",
    }})
    # distributed init must precede ANY computation (it rides the lazy
    # c.tpu); tiny_f32_llama() below runs jax ops
    assert c.tpu.distributed and jax.process_count() == 2
    cfg, _ = tiny_f32_llama()
    eng = build_engine(ModelSpec("llama", cfg, task="generate"), c, seed=3,
                       slots=2, max_len=64, max_prefill_batch=1,
                       prefill_buckets=[16], decode_chunk=4)
    if pid == 0:
        out = eng.generate([3, 7, 11], max_new_tokens=4, timeout=240)
        assert out["tokens"], out
        print("LEADER_SERVED one request; now dying hard (no STOP broadcast)",
              flush=True)
        os.kill(os.getpid(), signal.SIGKILL)
    else:
        eng.serve_follower()
        print("FOLLOWER returned cleanly (unexpected for a killed leader)")
""")


def test_killed_leader_releases_follower(tmp_path):
    """A kill -9'd leader broadcasts nothing. With LOCKSTEP_DEADLINE_S set,
    the follower's watchdog must release the process (hard exit with the
    distinct LOCKSTEP_EXIT_CODE) within the deadline instead of blocking
    forever inside the dead collective (VERDICT r4 weak #5)."""
    import time as _time

    from gofr_tpu.tpu.lockstep import LOCKSTEP_EXIT_CODE

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    port = _free_port()
    src = _KILL_WORKER.format(repo=repo, port=port)
    env = child_env()
    env.pop("XLA_FLAGS", None)

    logs = [open(tmp_path / f"kill{pid}.log", "w+") for pid in (0, 1)]
    procs = [
        subprocess.Popen([sys.executable, "-c", src, str(pid)],
                         env=env, stdout=logs[pid],
                         stderr=subprocess.STDOUT, text=True)
        for pid in (0, 1)
    ]

    def slurp():
        out = []
        for f in logs:
            f.flush()
            f.seek(0)
            out.append(f.read())
        return out

    try:
        procs[0].wait(timeout=560)
        died_at = _time.monotonic()
        # follower must notice within the 8s deadline (+ watchdog poll +
        # teardown slack; far below the 560s hang budget)
        procs[1].wait(timeout=60)
        released_in = _time.monotonic() - died_at
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail(f"killed-leader workers hung:\n{chr(10).join(slurp())[-5000:]}")
    outs = slurp()
    assert procs[0].returncode == -9, (procs[0].returncode, outs[0][-2000:])
    assert "LEADER_SERVED" in outs[0], outs[0][-2000:]
    # watchdog exit is the designed path; a fast coordination-service error
    # unblocking the collective (also releasing the process) is acceptable
    assert procs[1].returncode != 0, (procs[1].returncode, outs[1][-2000:])
    if procs[1].returncode == LOCKSTEP_EXIT_CODE:
        assert "leader presumed dead" in outs[1], outs[1][-2000:]
    assert released_in < 60, released_in
