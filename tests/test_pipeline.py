"""Pipeline parallelism: GPipe-style SPMD schedule vs the dense forward on
the 8-device CPU mesh (gofr_tpu.parallel.pipeline)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gofr_tpu.models import LlamaConfig, llama
from gofr_tpu.parallel import ShardingRules, build_mesh, shard_pytree
from gofr_tpu.parallel.pipeline import make_pipeline_forward, spmd_pipeline
from gofr_tpu.train import make_train_step


def test_spmd_pipeline_identity_math():
    """Pipeline of per-stage 'add my slab sum' == sequential over all slabs."""
    mesh = build_mesh("pp:4,dp:2")
    weights = jnp.arange(8, dtype=jnp.float32).reshape(8, 1)  # 2 layers per stage
    x = jnp.arange(24, dtype=jnp.float32).reshape(6, 4)  # 6 microbatches

    def stage_fn(w_local, act):
        # each "layer" adds its weight; scan over the local slab
        def body(a, w):
            return a + w, None

        out, _ = jax.lax.scan(body, act, w_local)
        return out

    @jax.shard_map(mesh=mesh, in_specs=(jax.sharding.PartitionSpec("pp"),
                                        jax.sharding.PartitionSpec()),
                   out_specs=jax.sharding.PartitionSpec(), check_vma=False)
    def run(w, xm):
        return spmd_pipeline(stage_fn, w, xm, axis="pp", microbatches=6)

    got = run(weights, x)
    want = x + jnp.sum(weights)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want))


def test_llama_pipelined_matches_dense():
    mesh = build_mesh("pp:2,dp:4")
    cfg = LlamaConfig.tiny()  # 2 layers → 1 per stage
    params = llama.init(cfg, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (8, 16), 0, cfg.vocab_size)
    lengths = jnp.array([16, 12, 16, 9, 7, 16, 11, 16], jnp.int32)
    want = llama.forward(cfg, params, tokens, lengths)

    rules = ShardingRules().with_overrides(layers="pp")
    sharded = shard_pytree(params, llama.param_axes(cfg), rules, mesh)
    got = llama.forward_pipelined(cfg, sharded, tokens, lengths, mesh, 2)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-4, rtol=2e-4)


def test_pipeline_forward_validates():
    mesh = build_mesh("dp:8")
    with pytest.raises(ValueError, match="pp"):
        make_pipeline_forward(mesh)
    mesh = build_mesh("pp:2,dp:4")
    pp_forward = make_pipeline_forward(mesh, microbatches=3)
    with pytest.raises(ValueError, match="microbatches"):
        pp_forward(lambda p, x, l: x, jnp.zeros((2, 1)), jnp.zeros((4, 8, 16)),
                   jnp.zeros((4,), jnp.int32))


def test_train_step_pipeline():
    mesh = build_mesh("pp:2,dp:2,tp:2")
    cfg = LlamaConfig.tiny()
    init_fn, step_fn = make_train_step(cfg, llama, mesh, pipeline_microbatches=2)
    state = init_fn(jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (4, 16), 0, cfg.vocab_size)
    lengths = jnp.full((4,), 16, jnp.int32)
    state, metrics = step_fn(state, tokens, lengths)
    l0 = float(metrics["loss"])
    assert np.isfinite(l0)
    for _ in range(3):
        state, metrics = step_fn(state, tokens, lengths)
    assert float(metrics["loss"]) < l0


def test_train_step_pipeline_requires_pp():
    mesh = build_mesh("dp:8")
    with pytest.raises(ValueError, match="pp"):
        make_train_step(LlamaConfig.tiny(), llama, mesh, pipeline_microbatches=2)


def test_llama_pipelined_with_tp_matches_dense():
    """pp x tp: stage weights stay tp-sharded inside the region (manual
    psums after wo/w_down) — numerics must equal the dense forward."""
    mesh = build_mesh("pp:2,dp:2,tp:2")
    cfg = LlamaConfig.tiny()
    params = llama.init(cfg, jax.random.key(2))
    tokens = jax.random.randint(jax.random.key(3), (4, 16), 0, cfg.vocab_size)
    lengths = jnp.array([16, 10, 13, 16], jnp.int32)
    want = llama.forward(cfg, params, tokens, lengths)

    rules = ShardingRules().with_overrides(layers="pp")
    sharded = shard_pytree(params, llama.param_axes(cfg), rules, mesh)
    got = llama.forward_pipelined(cfg, sharded, tokens, lengths, mesh, 2)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-4, rtol=2e-4)
