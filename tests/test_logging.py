import io
import json

import pytest

from gofr_tpu.logging import Level, Logger, MockLogger

pytestmark = pytest.mark.quick


def test_level_filtering():
    log = MockLogger(level=Level.WARN)
    log.debug("d")
    log.info("i")
    log.warn("w")
    log.error("e")
    levels = [r["level"] for r in log.records]
    assert levels == ["WARN", "ERROR"]


def test_json_output_shape():
    log = MockLogger()
    log.infof("hello %s %d", "world", 42)
    rec = log.records[0]
    assert rec["level"] == "INFO"
    assert rec["message"] == "hello world 42"
    assert rec["time"].endswith("Z")


def test_structured_dict_merged():
    log = MockLogger()
    log.info({"method": "GET", "status": 200})
    rec = log.records[0]
    assert rec["method"] == "GET"
    assert rec["status"] == 200


def test_reserved_keys_not_overwritten():
    log = MockLogger()
    log.info("real message", {"level": "SPOOF", "time": "bad", "message": "spoof"})
    rec = log.records[0]
    assert rec["level"] == "INFO"
    assert rec["message"] == "real message"
    assert rec["time"].endswith("Z")


def test_change_level_live():
    log = MockLogger(level=Level.ERROR)
    log.info("hidden")
    log.change_level(Level.DEBUG)
    log.debug("visible")
    assert len(log.records) == 1
    assert log.records[0]["message"] == "visible"


def test_pretty_print_on_terminal():
    class Record:
        def pretty_print(self, w):
            w.write("CUSTOM-RENDER")

    out = io.StringIO()
    log = Logger(level=Level.DEBUG, out=out, err=out, terminal=True)
    log.info(Record())
    assert "CUSTOM-RENDER" in out.getvalue()


def test_errors_go_to_stderr():
    out, err = io.StringIO(), io.StringIO()
    log = Logger(level=Level.DEBUG, out=out, err=err, terminal=False)
    log.info("a")
    log.error("b")
    assert json.loads(out.getvalue())["message"] == "a"
    assert json.loads(err.getvalue())["message"] == "b"


def test_log_exception_includes_stack():
    log = MockLogger()
    try:
        raise ValueError("boom")
    except ValueError as e:
        log.log_exception(e, "handler panic")
    msg = log.records[0]["message"]
    assert "boom" in msg and "ValueError" in msg
