"""Fleet-wide SLO plane (ISSUE 9; docs/observability.md).

Quick tier, no TPU: window-ring arithmetic against hand-computed values,
burn-rate → DEGRADED health and recovery, config-driven objective parsing,
metrics federation merge semantics (counters summed, histogram buckets
merged only on identical ladders, percentiles NEVER averaged), the
token-bucket rate limit on trigger-fired anomaly capture, the router's
affinity/decision metrics, profiler-port collision handling, and the
acceptance drill: two in-process replicas gossiping digests to a router
whose /metrics and /debug/fleet views show per-replica AND exactly-merged
aggregate attainment, with a breach flipping health and firing exactly one
capture bundle.
"""

import json
import socket

import pytest

from gofr_tpu.config import DictConfig
from gofr_tpu.container import new_mock_container
from gofr_tpu.metrics import Registry, federation
from gofr_tpu.metrics.slo import (
    CaptureWatcher,
    Objective,
    SLOEngine,
    SLOTracker,
    _WindowRing,
)
from gofr_tpu.router import Router, RoutePlan, RouterPolicy
from gofr_tpu.router.gossip import GossipReporter


class _Clock:
    """Injectable monotonic clock: SLO windows and capture token buckets
    must be testable without sleeping."""

    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


# -- window math ---------------------------------------------------------------


@pytest.mark.quick
class TestWindowRing:
    def test_counts_match_hand_computed(self):
        ring = _WindowRing(60.0, buckets=6)
        clock = _Clock()
        for i in range(10):
            ring.observe(i < 8, clock())
            clock.advance(1.0)
        good, total = ring.stats(clock())
        assert (good, total) == (8, 10)

    def test_old_buckets_age_out_without_writes(self):
        ring = _WindowRing(60.0, buckets=6)
        clock = _Clock()
        for _ in range(10):
            ring.observe(True, clock())
        clock.advance(61.0)  # a full window later, with zero traffic
        assert ring.stats(clock()) == (0, 0)

    def test_memory_is_bounded_by_bucket_count(self):
        ring = _WindowRing(60.0, buckets=6)
        clock = _Clock()
        for _ in range(100_000):
            ring.observe(True, clock())
            clock.advance(0.001)
        assert len(ring._good) == 6 and len(ring._total) == 6

    def test_recycled_slot_resets(self):
        ring = _WindowRing(6.0, buckets=6)  # 1s-wide buckets
        clock = _Clock()
        ring.observe(False, clock())
        clock.advance(6.0)  # same slot index mod n, new epoch
        ring.observe(True, clock())
        good, total = ring.stats(clock())
        assert (good, total) == (1, 1)  # the old bad sample is gone


@pytest.mark.quick
class TestBurnArithmetic:
    def test_burn_rate_is_bad_fraction_over_budget(self):
        tr = SLOTracker(Objective("c", "ttft", 0.9, 1.0), 60.0, 3600.0)
        # 80/100 good against a 0.9 target: bad fraction 0.2, budget 0.1
        assert tr.burn(80, 100) == pytest.approx(2.0)
        assert tr.burn(100, 100) == pytest.approx(0.0)
        assert tr.burn(0, 0) is None  # no samples, no verdict
        degenerate = SLOTracker(Objective("c", "ttft", 1.0, 1.0), 60.0, 3600.0)
        assert degenerate.burn(1, 2) is None  # zero budget

    def test_budget_remaining_clamps_to_zero(self):
        clock = _Clock()
        eng = SLOEngine([Objective("c", "ttft", 0.9, 1.0)],
                        default_class="c", check_interval_s=0.0, now=clock)
        for _ in range(10):
            eng.observe("c", "ttft", 5.0)  # every sample blows the budget
        entry = eng.snapshot()["c"]["ttft"]
        assert entry["fast"]["attainment"] == 0.0
        assert entry["fast"]["burn_rate"] == pytest.approx(10.0)
        assert entry["budget_remaining"] == 0.0  # clamped, never negative


# -- the engine ----------------------------------------------------------------


def _engine(clock, **kw):
    kw.setdefault("min_samples", 10)
    kw.setdefault("burn_threshold", 2.0)
    kw.setdefault("check_interval_s", 0.0)
    objectives = [Objective("interactive", "ttft", 0.98, 0.25),
                  Objective("interactive", "availability", 0.99),
                  Objective("batch", "ttft", 0.98, 30.0)]
    rank = {"interactive": 0, "batch": 1}
    return SLOEngine(objectives, default_class="batch", rank=rank,
                     now=clock, **kw)


@pytest.mark.quick
class TestSLOEngine:
    def test_burn_flips_health_degraded_with_structured_reason_then_recovers(self):
        clock = _Clock()
        eng = _engine(clock)
        for _ in range(9):
            eng.observe("interactive", "ttft", 5.0)
        assert eng.health_check()["status"] == "UP"  # below min_samples
        eng.observe("interactive", "ttft", 5.0)
        h = eng.health_check()
        assert h["status"] == "DEGRADED"
        (b,) = [x for x in h["details"]["burning"] if x["objective"] == "ttft"]
        assert b["class"] == "interactive" and b["window"] == "fast"
        assert b["burn_rate"] == pytest.approx(50.0)  # 100% bad / 2% budget
        # recovery: enough good samples pull the fast burn under threshold
        for _ in range(490):
            eng.observe("interactive", "ttft", 0.01)
        entry = eng.snapshot()["interactive"]["ttft"]
        assert entry["fast"]["attainment"] == pytest.approx(0.98)
        assert entry["fast"]["burn_rate"] == pytest.approx(1.0)
        assert eng.health_check()["status"] == "UP"

    def test_a_single_slow_request_never_pages(self):
        clock = _Clock()
        eng = _engine(clock)
        eng.observe("interactive", "ttft", 99.0)
        assert eng.breaches() == []  # min_samples gates the alert

    def test_unknown_class_folds_into_default(self):
        clock = _Clock()
        eng = _engine(clock)
        eng.observe("mystery", "ttft", 1.0)
        eng.observe(None, "ttft", 1.0)
        assert eng.snapshot()["batch"]["ttft"]["fast"]["total"] == 2

    def test_should_shed_only_when_a_strictly_higher_class_burns(self):
        clock = _Clock()
        eng = _engine(clock)
        for _ in range(20):
            eng.observe("interactive", "ttft", 5.0)
        assert eng.burning_classes() == {"interactive"}
        assert eng.should_shed("batch")          # lower priority: shed
        assert not eng.should_shed("interactive")  # never shed by own burn

    def test_availability_objective_counts_outcomes(self):
        clock = _Clock()
        eng = _engine(clock)
        for i in range(20):
            eng.observe_outcome("interactive", i % 2 == 0)
        win = eng.snapshot()["interactive"]["availability"]["fast"]
        assert (win["good"], win["total"]) == (10, 20)
        assert eng.health_check()["status"] == "DEGRADED"

    def test_sample_gauges_exports_the_three_families(self):
        clock = _Clock()
        reg = Registry()
        reg.new_gauge("app_slo_attainment")
        reg.new_gauge("app_slo_burn_rate")
        reg.new_gauge("app_slo_budget_remaining")
        eng = _engine(clock, metrics=reg)
        for i in range(10):
            eng.observe("interactive", "ttft", 0.01 if i < 9 else 5.0)
        eng.sample_gauges(reg)
        labels = {"class": "interactive", "objective": "ttft"}
        att = reg.get("app_slo_attainment").value(window="fast", **labels)
        assert att == pytest.approx(0.9)
        burn = reg.get("app_slo_burn_rate").value(window="fast", **labels)
        assert burn == pytest.approx(5.0)
        assert reg.get("app_slo_budget_remaining").value(**labels) == 0.0
        # an idle class publishes nothing (not a fake 100%)
        assert reg.get("app_slo_attainment").value(
            window="fast", **{"class": "batch", "objective": "ttft"}) == 0.0

    def test_breach_listener_is_throttled_by_check_interval(self):
        clock = _Clock()
        calls = []
        eng = _engine(clock, check_interval_s=5.0)
        eng.add_breach_listener(calls.append)
        for _ in range(50):
            eng.observe("interactive", "ttft", 9.0)
        # the first observe ran a check below min_samples (no breach yet);
        # every later same-instant observe was throttled
        assert calls == []
        clock.advance(5.0)
        for _ in range(50):
            eng.observe("interactive", "ttft", 9.0)
        assert len(calls) == 1  # one notification despite 50 breaching observes
        clock.advance(5.0)
        eng.observe("interactive", "ttft", 9.0)
        assert len(calls) == 2

    def test_from_config_objective_parsing(self):
        conf = DictConfig({
            "SLO_TARGET": "0.9",
            "SLO_INTERACTIVE_TTFT_MS": "250",
            "SLO_INTERACTIVE_TPOT_MS": "0",     # 0 disables the pair
            "SLO_BATCH_TARGET": "0.5",
            "SLO_DEFAULT_AVAILABILITY": "0",    # out of (0,1): disabled
            "SLO_MIN_SAMPLES": "3",
        })
        eng = SLOEngine.from_config(conf)
        tr = eng._trackers[("interactive", "ttft")]
        assert tr.objective.threshold_s == pytest.approx(0.25)
        assert tr.objective.target == pytest.approx(0.9)
        assert ("interactive", "tpot") not in eng._trackers
        assert ("default", "availability") not in eng._trackers
        assert eng._trackers[("batch", "ttft")].objective.target == 0.5
        assert eng.min_samples == 3
        assert eng.default_class == "default"


# -- federation: the merges that must be done right ----------------------------


def _digest_pair(obs0, obs1, buckets=(0.01, 0.1, 1.0)):
    """Two single-histogram registries → digest dict keyed by replica."""
    digs = {}
    for name, obs in (("r0", obs0), ("r1", obs1)):
        reg = Registry()
        reg.new_counter("app_tpu_tokens_total")
        reg.new_histogram("app_tpu_ttft_seconds", buckets=buckets)
        for v in obs:
            reg.get("app_tpu_ttft_seconds").observe(v, model="m")
            reg.increment_counter("app_tpu_tokens_total", 1, model="m")
        digs[name] = federation.digest(reg)
    return digs


@pytest.mark.quick
class TestFederation:
    def test_counters_sum_and_keep_per_replica_series(self):
        digs = _digest_pair([0.005] * 3, [0.005] * 7)
        text = federation.fleet_text(digs)
        assert 'app_tpu_tokens_total{model="m"} 10' in text        # aggregate
        assert 'app_tpu_tokens_total{model="m",replica="r0"} 3' in text
        assert 'app_tpu_tokens_total{model="m",replica="r1"} 7' in text

    def test_histogram_buckets_merge_elementwise(self):
        digs = _digest_pair([0.005] * 4, [0.5] * 6)
        text = federation.fleet_text(digs)
        # aggregate cumulative buckets: 4 ≤ 0.01, 4 ≤ 0.1, 10 ≤ 1.0
        assert 'app_tpu_ttft_seconds_bucket{model="m",le="0.01"} 4' in text
        assert 'app_tpu_ttft_seconds_bucket{model="m",le="1"} 10' in text
        assert 'app_tpu_ttft_seconds_count{model="m"} 10' in text
        assert 'app_tpu_ttft_seconds_count{model="m",replica="r1"} 6' in text

    def test_mismatched_ladders_refuse_an_aggregate(self):
        d0 = _digest_pair([0.005], [], buckets=(0.01, 1.0))["r0"]
        d1 = _digest_pair([], [0.5], buckets=(0.25, 2.0))["r1"]
        text = federation.fleet_text({"r0": d0, "r1": d1})
        # per-replica series survive; no aggregate (unlabeled) series exists
        assert 'app_tpu_ttft_seconds_count{model="m",replica="r0"} 1' in text
        assert 'app_tpu_ttft_seconds_count{model="m"} ' not in text

    def test_percentiles_are_never_averaged(self):
        # r0: 100 fast requests (p50 = 0.005); r1: 100 slow (p50 = 1.0).
        # The fleet p50 read off the MERGED buckets is 0.005-bucket fast —
        # half the fleet's requests were fast. The average of per-replica
        # p50s (0.5025) is a number about nothing.
        buckets = (0.005, 0.1, 1.0)
        q = federation.histogram_quantile
        r0_counts, r1_counts = [100, 0, 0], [0, 0, 100]
        p50_r0 = q(buckets, r0_counts, 100, 0.5)
        p50_r1 = q(buckets, r1_counts, 100, 0.5)
        merged = [a + b for a, b in zip(r0_counts, r1_counts)]
        p50_fleet = q(buckets, merged, 200, 0.5)
        assert p50_fleet == pytest.approx(0.005)
        assert (p50_r0 + p50_r1) / 2 == pytest.approx(0.5025)
        assert p50_fleet != (p50_r0 + p50_r1) / 2
        # overflow tail: a rank above the last finite bucket reads +inf
        assert q(buckets, [0, 0, 0], 10, 0.5) == float("inf")
        assert q(buckets, [1, 0, 0], 0, 0.5) is None

    def test_aggregate_slo_merges_counts_not_ratios(self):
        clock = _Clock()
        e0, e1 = _engine(clock), _engine(clock)
        e0.observe("interactive", "ttft", 9.0)   # 1 bad of 2 → 0.5
        e0.observe("interactive", "ttft", 0.01)
        for _ in range(18):                       # 18 good → 1.0
            e1.observe("interactive", "ttft", 0.01)
        fleet = federation.aggregate_slo(
            {"r0": {"slo": e0.snapshot()}, "r1": {"slo": e1.snapshot()}})
        win = fleet["interactive"]["ttft"]["fast"]
        assert (win["good"], win["total"]) == (19, 20)
        assert win["attainment"] == pytest.approx(0.95)  # NOT (0.5+1.0)/2


# -- trigger-fired anomaly capture ---------------------------------------------


@pytest.mark.quick
class TestCaptureWatcher:
    def _watcher(self, tmp_path, clock, **kw):
        container = new_mock_container()
        eng = _engine(clock)
        kw.setdefault("min_interval_s", 600.0)
        w = CaptureWatcher(container, eng, out_dir=str(tmp_path),
                           now=clock, clock=clock, **kw)
        return container, eng, w

    def test_rate_limit_allows_one_then_suppresses_then_refills(self, tmp_path):
        clock = _Clock()
        container, _, w = self._watcher(tmp_path, clock)
        breach = [{"class": "interactive", "objective": "ttft",
                   "window": "fast", "burn_rate": 50.0}]
        path = w.on_breach(breach)
        assert path is not None
        assert w.on_breach(breach) is None  # bucket empty → suppressed
        assert w.on_breach(breach) is None
        taken = container.metrics.get("app_slo_captures_total")
        sup = container.metrics.get("app_slo_captures_suppressed_total")
        assert sum(v for _, v in taken.series()) == 1
        assert sum(v for _, v in sup.series()) == 2
        clock.advance(600.0)  # one token refilled
        assert w.on_breach(breach) is not None
        assert len(list(tmp_path.glob("slo-capture-*"))) == 2

    def test_burst_allows_consecutive_captures(self, tmp_path):
        clock = _Clock()
        _, _, w = self._watcher(tmp_path, clock, burst=2)
        breach = [{"class": "c", "objective": "ttft"}]
        assert w.on_breach(breach) is not None
        assert w.on_breach(breach) is not None
        assert w.on_breach(breach) is None

    def test_bundle_contains_reason_slo_and_flight_state(self, tmp_path):
        clock = _Clock()
        container, eng, w = self._watcher(tmp_path, clock)
        for _ in range(10):
            eng.observe("interactive", "ttft", 9.0)
        path = w.on_breach(eng.breaches())
        with open(f"{path}/bundle.json") as f:
            data = json.load(f)
        assert data["reason"][0]["class"] == "interactive"
        assert data["slo"]["interactive"]["ttft"]["fast"]["total"] == 10
        assert "requests" in data["flight"] and "steps" in data["flight"]
        assert "engines" in data

    def test_from_config_knobs(self, tmp_path):
        conf = DictConfig({"SLO_CAPTURE_DIR": str(tmp_path),
                           "SLO_CAPTURE_MIN_INTERVAL_S": "30",
                           "SLO_CAPTURE_BURST": "3"})
        clock = _Clock()
        w = CaptureWatcher.from_config(
            conf, new_mock_container(), _engine(clock), now=clock, clock=clock)
        assert w.out_dir == str(tmp_path)
        assert w.min_interval_s == 30.0 and w.burst == 3

    def test_capture_dir_falls_back_to_profiler_dir(self, tmp_path):
        conf = DictConfig({"PROFILER_DIR": str(tmp_path)})
        clock = _Clock()
        w = CaptureWatcher.from_config(
            conf, new_mock_container(), _engine(clock), now=clock, clock=clock)
        assert w.out_dir == str(tmp_path)


# -- router decision metrics (satellite 3) -------------------------------------


@pytest.mark.quick
def test_router_decision_counts_and_affinity_ratio_are_real_metrics():
    container = new_mock_container()
    router = Router(container, policy=RouterPolicy(
        page_size=4, jitter_s=0.0, replicas={"a": "http://a", "b": "http://b"}))
    p = RoutePlan(key=1, qos_class="default", spillable=True,
                  home="a", targets=[])
    for _ in range(3):
        with router._lock:
            router._stats["home"] += 1
        router._record(p, sent="a", outcome="200")
    with router._lock:
        router._stats["spill"] += 1
    router._record(p, sent="b", outcome="200")
    router._record(p, sent=None, outcome="shed:down")
    c = container.metrics.get("app_router_decisions_total")
    by = {ls: v for ls, v in c.series()}
    assert by[(("decision", "home"), ("replica", "a"))] == 3
    assert by[(("decision", "spill"), ("replica", "b"))] == 1
    # a shed never reached a replica: attributed to the planned home
    assert by[(("decision", "shed"), ("replica", "a"))] == 1
    g = container.metrics.get("app_router_affinity_hit_ratio")
    assert g.value() == pytest.approx(0.75)
    view = router.fleet_view()
    per = {d["name"]: d for d in view["replicas"]}
    assert per["a"]["decisions"]["home"] == 3
    assert per["a"]["affinity_hit_ratio"] == pytest.approx(1.0)
    assert view["stats"]["affinity_hit_ratio"] == pytest.approx(0.75)


# -- profiler port satellites --------------------------------------------------


@pytest.mark.quick
class TestProfilerPorts:
    def _app(self, **conf):
        from gofr_tpu import app as appmod

        config = {"APP_NAME": "t", **conf}
        return appmod.App(config=DictConfig(config),
                          container=new_mock_container(config))

    def test_auto_derives_from_http_port(self):
        app = self._app(PROFILER_PORT="auto", HTTP_PORT="8042")
        assert app._profiler_port_base() == 8042 + 1999

    def test_zero_and_garbage_disable(self):
        assert self._app(PROFILER_PORT="0")._profiler_port_base() is None
        assert self._app(PROFILER_PORT="-1")._profiler_port_base() is None
        assert self._app(PROFILER_PORT="teapot")._profiler_port_base() is None

    def test_bindable_port_walks_past_a_busy_one(self):
        from gofr_tpu.app import App

        with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.bind(("0.0.0.0", 0))
            s.listen(1)
            busy = s.getsockname()[1]
            port = App._bindable_port(busy, tries=4)
            assert port is not None and busy < port < busy + 4


# -- the acceptance drill: two replicas behind a router ------------------------


@pytest.mark.quick
def test_two_replica_federation_breach_capture_and_recovery(tmp_path):
    common = {"SLO_MIN_SAMPLES": "5", "SLO_BURN_THRESHOLD": "2",
              "SLO_CHECK_INTERVAL_S": "0"}
    r0 = new_mock_container({**common, "APP_NAME": "r0",
                             "SLO_CAPTURE": "true",
                             "SLO_CAPTURE_DIR": str(tmp_path),
                             "SLO_CAPTURE_MIN_INTERVAL_S": "3600"})
    r1 = new_mock_container({**common, "APP_NAME": "r1"})
    assert r0.slo is not None and r0.slo_capture is not None
    assert r1.slo_capture is None  # capture is strictly opt-in

    # asymmetric traffic so the exact merge is distinguishable from an
    # average of ratios: r0 1/2 good (0.5), r1 18/18 good (1.0)
    r0.slo.observe("interactive", "ttft", 10.0)  # > the 2s objective
    r0.slo.observe("interactive", "ttft", 0.01)
    for _ in range(18):
        r1.slo.observe("interactive", "ttft", 0.01)

    rep0 = GossipReporter(r0, name="r0", url="http://r0")
    rep1 = GossipReporter(r1, name="r1", url="http://r1")
    router = Router(new_mock_container(),
                    policy=RouterPolicy(page_size=4, jitter_s=0.0))
    router.registry.observe(rep0.snapshot())  # digest rides the snapshot
    router.registry.observe(rep1.snapshot())

    text = router.fleet_metrics_text()
    agg = ('app_slo_attainment{class="interactive",objective="ttft",'
           'window="fast"} 0.95')
    assert agg in text  # 19/20, NOT the 0.75 average of per-replica ratios
    assert ('app_slo_attainment{class="interactive",objective="ttft",'
            'replica="r0",window="fast"} 0.5') in text
    assert 'replica="r1"' in text
    assert 'app_fleet_replica_up{replica="r0"} 1' in text
    assert 'app_fleet_replica_inflight{replica="r0"} 0' in text

    view = router.fleet_view()
    win = view["classes"]["interactive"]["ttft"]["fast"]
    assert (win["good"], win["total"]) == (19, 20)
    per = {d["name"]: d for d in view["replicas"]}
    assert per["r0"]["slo"]["interactive"]["ttft"]["attainment"] == 0.5
    assert per["r1"]["slo"]["interactive"]["ttft"]["attainment"] == 1.0
    assert per["r1"]["inflight"] == 0

    # drive r0 past its TTFT objective: burn flips health DEGRADED with a
    # structured reason and fires exactly ONE rate-limited capture bundle
    for _ in range(10):
        r0.slo.observe("interactive", "ttft", 30.0)
    h = r0.health()["services"]["slo"]
    assert h["status"] == "DEGRADED"
    assert any(b["class"] == "interactive" for b in h["details"]["burning"])
    bundles = sorted(tmp_path.glob("slo-capture-*"))
    assert len(bundles) == 1, bundles
    bundle = json.loads((bundles[0] / "bundle.json").read_text())
    assert bundle["reason"] and "slo" in bundle and "flight" in bundle
    sup = r0.metrics.get("app_slo_captures_suppressed_total")
    assert sum(v for _, v in sup.series()) >= 1
    # the breach rides the next gossip into the router's fleet view
    router.registry.observe(rep0.snapshot())
    burn = (router.fleet_view()["classes"]["interactive"]["ttft"]
            ["fast"]["burn_rate"])
    assert burn is not None and burn >= 2.0

    # recovery: good traffic pulls the fast burn back under threshold,
    # health returns to UP, and the rate limit held at one bundle
    for _ in range(800):
        r0.slo.observe("interactive", "ttft", 0.01)
    assert r0.slo.health_check()["status"] == "UP"
    router.registry.observe(rep0.snapshot())
    att = (router.fleet_view()["classes"]["interactive"]["ttft"]
           ["fast"]["attainment"])
    assert att is not None and att > 0.97
    assert len(list(tmp_path.glob("slo-capture-*"))) == 1


@pytest.mark.quick
def test_gossip_digest_every_throttles_but_registry_keeps_last(tmp_path):
    r0 = new_mock_container({"ROUTER_GOSSIP_DIGEST_EVERY": "2"})
    rep = GossipReporter(r0, name="r0", url="http://r0")
    router = Router(new_mock_container(),
                    policy=RouterPolicy(page_size=4, jitter_s=0.0))
    s1 = rep.snapshot()
    assert "digest" not in s1  # seq 1 % 2 != 0
    s2 = rep.snapshot()
    assert "digest" in s2
    router.registry.observe(s2)
    router.registry.observe(rep.snapshot())  # seq 3: digest-less publish
    # the registry keeps the last digest across digest-less publishes
    assert router.registry.get("r0").digest is not None
    assert "r0" in router.digests()


# -- QoS shed-on-burn (pressure signal) ----------------------------------------


@pytest.mark.quick
def test_qos_sheds_lower_class_while_a_higher_class_burns():
    from gofr_tpu.http.errors import ServiceUnavailable
    from gofr_tpu.qos import AdmissionController, QoSPolicy

    container = new_mock_container({"QOS_ENABLED": "true",
                                    "QOS_SHED_ON_BURN": "true",
                                    "SLO_MIN_SAMPLES": "5",
                                    "SLO_BURN_THRESHOLD": "2",
                                    "SLO_CHECK_INTERVAL_S": "0"})
    policy = QoSPolicy.from_config(container.config)
    assert policy.shed_on_burn
    ctrl = AdmissionController(policy, container.metrics, container.logger)
    for _ in range(10):
        container.slo.observe("interactive", "ttft", 99.0)

    class _Eng:
        slo = container.slo
        _restarting = False

        def _backlog(self):
            return 0

    with pytest.raises(ServiceUnavailable):
        ctrl.admit_engine(_Eng(), "batch", None)
    # the burning class itself is never shed by its own burn
    ctrl.admit_engine(_Eng(), "interactive", None)
    c = container.metrics.get("app_qos_rejected_total")
    assert any(dict(ls).get("reason") == "slo_burn" and v == 1
               for ls, v in c.series())
