"""Weight-only int8 quantization (ops/quant.py): numerics stay close to the
fp reference, the QTensor pytree flows through jit/donation, and the engine
serves a quantized model end to end."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gofr_tpu.container import new_mock_container
from gofr_tpu.models import LlamaConfig, ModelSpec, llama
from gofr_tpu.ops.quant import QTensor, qdot, quantize, quantize_tree
from gofr_tpu.tpu.engine import build_engine


def test_quantize_roundtrip_error_bounded():
    w = jax.random.normal(jax.random.key(0), (128, 64), jnp.float32)
    qt = quantize(w)
    assert qt.q.dtype == jnp.int8 and qt.s.shape == (1, 64)
    deq = qt.q.astype(jnp.float32) * qt.s
    # symmetric per-channel int8: error <= scale/2 per element
    assert float(jnp.max(jnp.abs(deq - w) / jnp.squeeze(qt.s))) <= 0.5 + 1e-6


def test_qdot_matches_dense_within_quant_error():
    key = jax.random.key(1)
    x = jax.random.normal(key, (4, 128), jnp.float32)
    w = jax.random.normal(jax.random.key(2), (128, 64), jnp.float32)
    dense = x @ w
    quant_out = qdot(x, quantize(w))
    rel = float(jnp.max(jnp.abs(quant_out - dense)) / jnp.max(jnp.abs(dense)))
    assert rel < 0.05, rel
    # plain arrays pass through untouched
    assert jnp.allclose(qdot(x, w), dense)


def test_quantized_forward_mostly_agrees():
    cfg = LlamaConfig.tiny()
    params = llama.init(cfg, jax.random.key(7))
    qparams = quantize_tree(params)
    # stacked [L, in, out] block weights became QTensors; norms didn't
    assert isinstance(qparams["blocks"]["wq"], QTensor)
    assert not isinstance(qparams["blocks"]["attn_norm"], QTensor)

    tokens = jax.random.randint(jax.random.key(3), (2, 24), 1, cfg.vocab_size)
    dense = llama.forward(cfg, params, tokens)
    quant_logits = llama.forward(cfg, qparams, tokens)
    agree = float(jnp.mean(
        (jnp.argmax(dense, -1) == jnp.argmax(quant_logits, -1)).astype(jnp.float32)
    ))
    assert agree >= 0.8, f"top-1 agreement {agree} too low for weight-only int8"


def test_engine_serves_quantized_model():
    cfg = LlamaConfig.tiny()
    container = new_mock_container()
    spec = ModelSpec(family="llama", task="generate", config=cfg)
    eng = build_engine(spec, container, seed=7, slots=2, max_len=48,
                       max_prefill_batch=2, quantize="int8")
    try:
        assert isinstance(eng.params["blocks"]["wq"], QTensor)
        out = eng.generate([5, 3, 9], max_new_tokens=6, timeout=120)
        assert len(out["tokens"]) == 6
        assert all(0 <= t < cfg.vocab_size for t in out["tokens"])
    finally:
        eng.stop()


def test_engine_serves_quantized_model_on_mesh():
    """QTensor params flow through mesh sharding (quantize runs AFTER
    shard_pytree and inherits shardings from the computation)."""
    cfg = LlamaConfig(
        vocab_size=512, hidden_size=64, intermediate_size=160,
        num_layers=2, num_heads=8, num_kv_heads=4, max_seq_len=128,
        dtype=jnp.float32,
    )
    container = new_mock_container({"TPU_MESH": "dp:2,tp:4"})
    spec = ModelSpec(family="llama", task="generate", config=cfg)
    eng = build_engine(spec, container, seed=3, slots=2, max_len=48,
                       max_prefill_batch=2, quantize="int8")
    try:
        out = eng.generate([5, 3, 9], max_new_tokens=5, timeout=300)
        assert len(out["tokens"]) == 5
    finally:
        eng.stop()


def test_unknown_quantize_mode_rejected():
    cfg = LlamaConfig.tiny()
    spec = ModelSpec(family="llama", task="generate", config=cfg)
    with pytest.raises(ValueError, match="int8"):
        build_engine(spec, new_mock_container(), seed=0, quantize="fp4")


def test_unquantizable_family_explicit_request_errors_config_warns():
    from gofr_tpu.models import BertConfig

    spec = ModelSpec(family="bert", task="embed", config=BertConfig.tiny())
    # explicit per-model request: hard error
    with pytest.raises(ValueError, match="does not support"):
        build_engine(spec, new_mock_container(), quantize="int8")
    # process-wide config: warn and serve unquantized (the env may target a
    # different engine in the same app)
    container = new_mock_container({"ENGINE_QUANTIZE": "int8"})
    eng = build_engine(spec, container)
    try:
        out = eng.infer([1, 2, 3, 4], timeout=120)
        assert np.asarray(out).ndim >= 1
        assert any("ENGINE_QUANTIZE=int8 ignored" in r.get("message", "")
                   for r in container.logger.records), "no warning logged"
    finally:
        eng.stop()
