"""Multi-chip SERVING correctness (VERDICT r2 #2).

Training on a mesh was already exercised by test_train/test_pipeline; this
file proves the other half: `build_engine` on a tp(+dp) mesh container
serves concurrent requests with tokens identical to single-device greedy
decoding. The reference's scale-out analog is Kafka consumer groups
(`pkg/gofr/subscriber.go:27-60`); here scale-out is sharded serving.

The test model is f32: sharded matmul reduction order differs from the
dense single-device order, and on a random bf16 model near-tie argmaxes
flip, which would test numerics rather than the serving path.
"""

import pytest

from gofr_tpu.container import new_mock_container
from gofr_tpu.testutil import check_mesh_serving

# integration tier (CI `integration` job): multi-minute engine/process
# runs — excluded from the tier-1 gate via -m 'not slow' (docs/testing.md)
pytestmark = pytest.mark.slow


@pytest.mark.parametrize("config", [
    {"TPU_MESH": "dp:2,tp:4"},
    {"TPU_MESH": "tp:2", "TPU_DEVICES": "2"},
])
def test_engine_on_tp_mesh_matches_single_device(config):
    container = new_mock_container(config)
    mesh_axes = dict(zip(container.tpu.mesh.axis_names,
                         container.tpu.mesh.devices.shape))
    assert mesh_axes.get("tp", 1) > 1, "mesh has no tensor-parallel axis"
    check_mesh_serving(config)


def test_engine_on_mesh_slot_layout():
    """The slot (non-paged) KV layout must shard-serve identically too."""
    check_mesh_serving({"TPU_MESH": "dp:2,tp:4"}, kv_layout="slot")


@pytest.mark.parametrize("config", [
    {"TPU_MESH": "pp:2", "TPU_DEVICES": "2"},
    {"TPU_MESH": "dp:2,pp:2,tp:2"},
])
def test_engine_on_pp_mesh_matches_single_device(config):
    """VERDICT r3 #8: pipeline-parallel SERVING — build_engine wraps llama
    with the pp family (blocks + slot KV cache sharded over pp on the layer
    dim, GPipe microbatch schedule per device call, models/llama_pp.py) and
    must stay token-exact, tp psums and bubble-tick dropped writes included."""
    container = new_mock_container(config)
    assert dict(zip(container.tpu.mesh.axis_names,
                    container.tpu.mesh.devices.shape)).get("pp", 1) > 1
    check_mesh_serving(config)


def test_sp_mesh_ring_prefill_matches_single_device():
    """Sequence-parallel serving prefill: build_engine on an sp mesh swaps
    whole-prompt attention for ring attention (sequence sharded over sp,
    parallel/ring.py) and greedy tokens stay identical — the long-context
    prefill lever, proven token-exact at test scale."""
    config = {"TPU_MESH": "dp:2,sp:2,tp:2"}
    container = new_mock_container(config)
    assert dict(zip(container.tpu.mesh.axis_names,
                    container.tpu.mesh.devices.shape)).get("sp", 1) > 1
    # slot layout and prefix-cache-off paged: ring prefill active
    check_mesh_serving(config, kv_layout="slot")
    check_mesh_serving(config, prefix_cache=False)
    # default paged + prefix cache: ring prefill deliberately NOT wired
    # (cold/hit bit-identity) — serving stays correct, with a warning
    check_mesh_serving(config)


def test_sp_mesh_ulysses_strategy_and_bucket_guard():
    """ENGINE_SP_STRATEGY=ulysses swaps the sequence-parallel strategy and
    stays token-exact; buckets indivisible by sp are rejected at BUILD
    time (the top bucket is max_len itself, not a power of two)."""
    from gofr_tpu.models import ModelSpec
    from gofr_tpu.testutil import tiny_f32_llama
    from gofr_tpu.tpu.engine import build_engine

    check_mesh_serving({"TPU_MESH": "dp:2,sp:2,tp:2",
                        "ENGINE_SP_STRATEGY": "ulysses"},
                       kv_layout="slot", n_requests=3)

    cfg, _ = tiny_f32_llama()
    c = new_mock_container({"TPU_MESH": "dp:2,sp:2,tp:2", "ENGINE_KV_LAYOUT": "slot"})
    with pytest.raises(ValueError, match="divisible"):
        build_engine(ModelSpec("llama", cfg, task="generate"), c, seed=3,
                     slots=2, max_len=63, max_prefill_batch=1)


def test_int8_kv_and_spec_decode_on_tp_mesh():
    """Round-4 serving features under GSPMD: int8 KV (quantize/dequant
    folding must partition) and speculative decoding (verify_step +
    device-side lookup drafting) stay token-exact on a tp mesh."""
    check_mesh_serving({"TPU_MESH": "dp:2,tp:4"}, kv_layout="slot",
                       kv_quantize="int8")
    check_mesh_serving({"TPU_MESH": "dp:2,tp:4"}, kv_layout="slot",
                       spec_tokens=2, decode_chunk=4)


def test_pp_mesh_microbatch_override():
    """ENGINE_PP_MICROBATCHES > pp: deeper microbatching (smaller bubble
    fraction) must not change tokens."""
    check_mesh_serving({"TPU_MESH": "pp:2", "TPU_DEVICES": "2",
                        "ENGINE_PP_MICROBATCHES": "4"})


def test_pp_microbatches_must_divide_slots():
    """A non-dividing ENGINE_PP_MICROBATCHES would silently collapse to
    gcd(slots, m) microbatches (worst bubbles) — build_engine must reject
    it instead (ADVICE r4)."""
    from gofr_tpu.models import ModelSpec
    from gofr_tpu.testutil import tiny_f32_llama
    from gofr_tpu.tpu.engine import build_engine

    cfg, _ = tiny_f32_llama()
    c = new_mock_container({"TPU_MESH": "pp:2", "TPU_DEVICES": "2",
                            "ENGINE_PP_MICROBATCHES": "3"})
    with pytest.raises(ValueError, match="does not divide the slot count"):
        build_engine(ModelSpec("llama", cfg, task="generate"), c, seed=3,
                     slots=4, max_len=64, max_prefill_batch=1)


def test_draft_model_spec_on_tp_mesh():
    """Round-5 draft-model speculation under GSPMD: the draft's decode
    loop + the target verify must partition over tp and stay token-exact
    (self-draft, so acceptance also proves the sharded draft is coherent)."""
    check_mesh_serving({"TPU_MESH": "dp:2,tp:4"}, kv_layout="slot",
                       spec_tokens=2, decode_chunk=4, spec_self_draft=True)
