"""Tensor-parallel paged-KV pool (ISSUE 19 tentpole): the pool's K/V and
scale planes shard over the mesh's tp axis along the KV-head dimension,
and every consumer is shard-aware — decode attention runs per-shard under
shard_map with the reduce folded into the o-projection, writes/spec/
preemption/prefix swap-in operate on shard-local views, and the byte
accounting reports per-device numbers. The contract under test: a sharded
engine serves token-for-token what the SAME configuration serves on a
single device (the only valid comparison for int4, whose quantization
legitimately shifts greedy ties vs a full-precision reference), holds
1/tp of every plane per device, and keeps the page-refcount invariants
through spec rounds, preemption-by-recompute, and host-tier swap-in.
Runs on the conftest-forced 8-virtual-CPU-device mesh (jaxpin.pin_cpu)."""

import time

import jax
import numpy as np
import pytest

from gofr_tpu.container import new_mock_container
from gofr_tpu.models import ModelSpec
from gofr_tpu.ops.paged import kv_plane_bytes_per_position
from gofr_tpu.testutil import (
    assert_page_refs_consistent,
    assert_paged_pool_consistent,
    greedy_reference,
    tiny_f32_llama,
)
from gofr_tpu.tpu.engine import build_engine

pytestmark = pytest.mark.quick

MESH = "dp:2,tp:4"


@pytest.fixture(scope="module")
def setup():
    cfg, params = tiny_f32_llama()
    return cfg, params, greedy_reference(cfg, params)


def _build(cfg, config=None, **kw):
    kw.setdefault("slots", 4)
    kw.setdefault("max_len", 64)
    kw.setdefault("max_prefill_batch", 2)
    kw.setdefault("kv_layout", "paged")
    kw.setdefault("page_size", 8)
    container = new_mock_container(config)
    return build_engine(ModelSpec("llama", cfg, task="generate"),
                        container, seed=3, **kw)


def _sharded(cfg, **kw):
    return _build(cfg, {"TPU_MESH": MESH, "ENGINE_KV_SHARD": "tp"}, **kw)


def _prompts(n=4):
    return [[1 + (13 * i + j) % 200 for j in range(4 + i % 3)]
            for i in range(n)]


def _counter_sum(eng, name):
    m = eng.metrics.get(name)
    return sum(m._values.values()) if m is not None else 0


# -- token exactness vs single device, all three pool dtypes -------------------


@pytest.mark.parametrize("kvq", ["", "int8", "int4"])
def test_sharded_serving_token_exact_vs_single_device(setup, kvq):
    """The tentpole acceptance: for each KV dtype, the tp-sharded pool
    serves exactly the tokens the same engine produces on one device —
    the per-shard decode + o-projection psum changes nothing observable.
    The dense pool must additionally match the incremental f32 greedy
    reference (quantized pools compare same-dtype only)."""
    cfg, params, ref = setup
    prompts = _prompts()
    kw = {"kv_quantize": kvq} if kvq else {}
    ref_eng = _build(cfg, **kw)
    try:
        assert ref_eng.kv_shards == 1
        want = [ref_eng.generate(p, max_new_tokens=8, timeout=300)["tokens"]
                for p in prompts]
    finally:
        ref_eng.stop()
    if not kvq:
        assert want == [ref(p, 8) for p in prompts], (
            "single-device dense engine diverged from the greedy reference")
    eng = _sharded(cfg, **kw)
    try:
        assert eng.kv_shards == 4
        for i, p in enumerate(prompts):
            got = eng.generate(p, max_new_tokens=8, timeout=300)["tokens"]
            assert got == want[i], (
                f"request {i} diverged on the sharded {kvq or 'bf16'} pool: "
                f"{got} != {want[i]}")
        assert_page_refs_consistent(eng)
    finally:
        eng.stop()


def test_pool_planes_sharded_over_tp_and_stay_sharded(setup):
    """Every pool plane commits with the tp axis on the KV-head dim
    (axis 2) and each device holds exactly Hkv/tp heads — and serving
    must not silently reshard: donated step outputs keep the commitment,
    else the capacity win evaporates after the first decode."""
    cfg, params, _ = setup

    def check(eng):
        for leaf in jax.tree.leaves(eng.kv_cache):
            spec = tuple(leaf.sharding.spec)
            assert len(spec) > 2 and spec[2] == "tp", spec
            for sh in leaf.addressable_shards:
                assert sh.data.shape[2] == leaf.shape[2] // 4, (
                    leaf.shape, sh.data.shape)

    eng = _sharded(cfg)
    try:
        check(eng)
        eng.generate(_prompts(1)[0], max_new_tokens=4, timeout=300)
        check(eng)
    finally:
        eng.stop()


# -- spec rounds + preemption + prefix swap-in on the sharded pool -------------


def test_spec_and_preemption_on_sharded_pool(setup):
    """Speculative rounds and preemption-by-recompute on the sharded pool:
    spec writes and the requeued prompt's re-prefill both go through the
    shard-local write path, and under a minimum-legal pool contention must
    stay token-exact vs the greedy reference while the refcounts survive."""
    cfg, params, ref = setup
    rngs = np.random.RandomState(11)
    prompts = []
    for i in range(8):  # every 3rd arrival long enough to contend the pool
        n = 15 + (i % 2) * 4 if i % 3 == 2 else 2 + i % 4
        prompts.append([int(x) for x in rngs.randint(1, 200, size=n)])
    want = [ref(p, 12) for p in prompts]
    eng = _sharded(cfg, slots=3, total_pages=10, spec_tokens=2, decode_chunk=4)
    try:
        assert eng.kv_shards == 4 and eng.spec_tokens == 2
        reqs = []
        for p in prompts:  # paced arrivals, not one up-front burst
            time.sleep(0.01)
            reqs.append(eng.submit(p, max_new_tokens=12, timeout=300))
        results = [r.result(300) for r in reqs]
        assert _counter_sum(eng, "app_tpu_preemptions") >= 1, (
            "pool was not small enough to exercise preemption")
        for i, r in enumerate(results):
            assert r["tokens"] == want[i], (
                f"request {i} diverged under spec+preemption: "
                f"{r['tokens']} != {want[i]}")
        assert_page_refs_consistent(eng)
    finally:
        eng.stop()


def test_prefix_spill_swapin_on_sharded_pool(setup):
    """Host-tier spill and swap-in on the sharded pool: the spilled host
    copy and the device_put promoting it back must round-trip the
    SHARD-LOCAL views without ever materializing a replicated plane — a
    warm hit after forced spill replays token-exactly."""
    cfg, params, ref = setup
    prompt = [(11 * i) % 190 + 1 for i in range(20)]  # 2 full pages @ 8
    want = ref(prompt, 6)
    eng = _sharded(cfg, total_pages=12, prefix_host_mb=8.0)
    try:
        cold = eng.generate(prompt, max_new_tokens=6, timeout=300)
        assert cold["tokens"] == want, "cold sharded run diverged"
        for r in range(5):  # distinct prompts until pressure spills
            eng.generate([(r * 37 + 13 * i) % 180 + 2 for i in range(18)],
                         max_new_tokens=4, timeout=300)
        assert eng._prefix.host_pages > 0, "pool pressure never spilled"
        warm = eng.generate(prompt, max_new_tokens=6, timeout=300)
        assert warm["tokens"] == want, "host-tier swap-in changed tokens"
        assert _counter_sum(eng, "app_tpu_prefix_swapin_pages_total") >= 1
        assert_page_refs_consistent(eng)
        assert_paged_pool_consistent(eng, slots_empty=True)
    finally:
        eng.stop()


# -- per-device byte accounting ------------------------------------------------


def test_kv_plane_bytes_shard_divisor():
    """The analytic estimator's per-device mode: shards divides the head
    count exactly (never pads) and composes with every dtype contract."""
    for dt in ("bf16", "int8", "int4"):
        full = kv_plane_bytes_per_position(2, 4, 8, dt, dense_bytes=4)
        per = kv_plane_bytes_per_position(2, 4, 8, dt, dense_bytes=4, shards=4)
        assert per * 4 == full, (dt, per, full)
    with pytest.raises(ValueError, match="not divisible"):
        kv_plane_bytes_per_position(2, 4, 8, shards=3)


def test_page_pool_stats_report_shard_local_bytes(setup):
    """/debug/perf and the pool gauges ride page_pool_stats: byte fields
    must be SHARD-LOCAL (per-device) so a fleet rollup that sums parts
    sees parts — and they must equal what is actually resident per
    device, not a logical footprint divided on faith."""
    cfg, params, _ = setup
    eng = _sharded(cfg)
    try:
        stats = eng.page_pool_stats()
        assert stats["kv_shards"] == 4
        logical = sum(leaf.nbytes for leaf in jax.tree.leaves(eng.kv_cache))
        assert stats["pool_bytes_device"] == logical // 4
        assert stats["page_bytes_device"] == eng._page_bytes // 4
        dev0 = jax.devices()[0]
        resident = sum(
            sh.data.nbytes for leaf in jax.tree.leaves(eng.kv_cache)
            for sh in leaf.addressable_shards if sh.device == dev0)
        assert resident == stats["pool_bytes_device"], (
            "per-device gauge diverges from resident bytes")
        assert eng.replay_config()["engine"]["kv_shards"] == 4
        # and the DECLARED gauge actually reaches Prometheus exposition
        # (an undeclared name is silently dropped by the registry)
        cont = eng.container
        cont.register_engine("gen", eng)
        cont._sample_perf_metrics()
        line = next(
            ln for ln in cont.metrics.expose_text().splitlines()
            if ln.startswith("app_tpu_kv_pool_device_bytes{"))
        assert 'kv_shards="4"' in line and str(resident) in line, line
    finally:
        eng.stop()


def test_unsharded_stats_are_unchanged(setup):
    """ENGINE_KV_SHARD=off: kv_shards=1 and the per-device byte fields
    equal the logical footprint — today's accounting bit-for-bit."""
    cfg, params, _ = setup
    eng = _build(cfg, {"TPU_MESH": MESH, "ENGINE_KV_SHARD": "off"})
    try:
        assert eng.kv_shards == 1
        stats = eng.page_pool_stats()
        assert stats["kv_shards"] == 1
        assert stats["page_bytes_device"] == eng._page_bytes
        assert stats["pool_bytes_device"] == sum(
            leaf.nbytes for leaf in jax.tree.leaves(eng.kv_cache))
    finally:
        eng.stop()


# -- resolution gates ----------------------------------------------------------


def test_kv_shard_mode_gating(setup):
    """'auto' stands down silently when the geometry can't split; an
    explicit 'tp' request must raise instead of silently serving a
    replicated pool; unknown modes are config errors."""
    cfg, params, _ = setup
    # no tp axis at all: auto -> unsharded, explicit -> error
    eng = _build(cfg, {"TPU_MESH": "dp:2", "TPU_DEVICES": "2"})
    try:
        assert eng.kv_shards == 1
    finally:
        eng.stop()
    with pytest.raises(ValueError, match="ENGINE_KV_SHARD=tp impossible"):
        _build(cfg, {"TPU_MESH": "dp:2", "TPU_DEVICES": "2",
                     "ENGINE_KV_SHARD": "tp"})
    # tp=8 does not divide num_kv_heads=4: same split
    eng = _build(cfg, {"TPU_MESH": "tp:8"})
    try:
        assert eng.kv_shards == 1
    finally:
        eng.stop()
    with pytest.raises(ValueError, match="do not divide"):
        _build(cfg, {"TPU_MESH": "tp:8", "ENGINE_KV_SHARD": "tp"})
    with pytest.raises(ValueError, match="use 'auto', 'tp' or 'off'"):
        _build(cfg, {"TPU_MESH": MESH, "ENGINE_KV_SHARD": "sideways"})
