"""Full-stack serving integration: HTTP entrypoint → Context →
continuous-batching engine → model on the device mesh — the framework's
"minimum end-to-end slice" (SURVEY.md §7 stage 3), hermetic on CPU.
"""

import threading

import httpx
import jax.numpy as jnp
import pytest

from tests.test_http_server import AppHarness, make_app
from gofr_tpu.models import LlamaConfig, BertConfig, ModelSpec


@pytest.fixture
def lm_app():
    app = make_app()
    spec = ModelSpec("llama", LlamaConfig.tiny(), task="generate", dtype=jnp.float32)
    app.serve_model("lm", spec, slots=2, max_len=32)

    def generate(ctx):
        body = ctx.bind(dict)
        out = ctx.generate("lm", body["prompt"], max_new_tokens=int(body.get("max_new_tokens", 4)),
                           timeout=120)
        return out

    app.post("/generate", generate)
    return app


def test_generate_over_http(lm_app):
    with AppHarness(lm_app) as h, httpx.Client(base_url=h.base, timeout=180) as client:
        r = client.post("/generate", json={"prompt": [1, 2, 3], "max_new_tokens": 3})
        assert r.status_code == 201, r.text
        data = r.json()["data"]
        assert len(data["tokens"]) == 3
        assert data["finish_reason"] == "length"

        # concurrent requests batch through the slots
        results = []

        def call(i):
            rr = client.post("/generate", json={"prompt": [i + 1, 5], "max_new_tokens": 2})
            results.append(rr.status_code)

        threads = [threading.Thread(target=call, args=(i,)) for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert results == [201, 201, 201, 201]

        # engine surfaced in health
        r = client.get("/.well-known/health")
        services = r.json()["data"]["services"]
        assert services["model:lm"]["status"] == "UP"
        assert services["tpu"]["status"] == "UP"


def test_embed_over_http():
    app = make_app()
    spec = ModelSpec("bert", BertConfig.tiny(), task="embed", dtype=jnp.float32)
    app.serve_model("embedder", spec)

    def embed(ctx):
        body = ctx.bind(dict)
        vec = ctx.infer("embedder", body["tokens"], timeout=120)
        return {"embedding": [float(x) for x in vec], "dim": len(vec)}

    app.post("/embed", embed)

    with AppHarness(app) as h, httpx.Client(base_url=h.base, timeout=180) as client:
        r = client.post("/embed", json={"tokens": [4, 9, 2]})
        assert r.status_code == 201, r.text
        data = r.json()["data"]
        assert data["dim"] == 32
        norm = sum(x * x for x in data["embedding"]) ** 0.5
        assert abs(norm - 1.0) < 1e-4

        # serving metrics visible on the metrics port
        m = httpx.get(f"http://127.0.0.1:{app.metrics_port}/metrics")
        assert "app_tpu_step_seconds" in m.text
        assert "app_tpu_device_count" in m.text


def test_http_trace_stitches_engine_timeline_and_debug_endpoints():
    """Acceptance: a traced generate over HTTP yields ONE trace (server span
    + engine children), non-empty SLO histograms on /metrics, and the flight
    recorder's /debug endpoints serve the request's timeline."""
    from gofr_tpu.tracing import MemoryExporter, Tracer

    app = make_app({"APP_ENV": "DEBUG"})
    app.container.tracer = Tracer(MemoryExporter())
    spec = ModelSpec("llama", LlamaConfig.tiny(), task="generate", dtype=jnp.float32)
    app.serve_model("lm", spec, slots=2, max_len=32)
    app.post("/generate", lambda ctx: ctx.generate(
        "lm", ctx.bind(dict)["prompt"], max_new_tokens=3, timeout=120))

    inbound = "00-" + "a" * 32 + "-" + "b" * 16 + "-01"
    with AppHarness(app) as h, httpx.Client(base_url=h.base, timeout=180) as client:
        r = client.post("/generate", json={"prompt": [1, 2, 3]},
                        headers={"traceparent": inbound})
        assert r.status_code == 201, r.text
        assert r.headers["X-Trace-Id"] == "a" * 32

        spans = app.container.tracer._exporter.spans
        by_name = {s.name: s for s in spans}
        server = by_name["POST /generate"]
        assert server.trace_id == "a" * 32
        assert server.parent_id == "b" * 16
        for name in ("engine.queue_wait", "engine.prefill", "engine.decode"):
            assert by_name[name].trace_id == server.trace_id, name
            assert by_name[name].parent_id == server.span_id, name

        m = httpx.get(f"http://127.0.0.1:{app.metrics_port}/metrics").text
        for metric in ("app_tpu_ttft_seconds", "app_tpu_tpot_seconds",
                       "app_tpu_e2e_seconds"):
            counts = [line for line in m.splitlines()
                      if line.startswith(f"{metric}_count") and not line.endswith(" 0")]
            assert counts, f"{metric} empty in exposition"

        r = client.get("/debug/requests")
        assert r.status_code == 200
        reqs = r.json()["data"]["requests"]
        assert reqs and reqs[0]["finish_reason"] == "length"
        assert reqs[0]["trace_id"] == "a" * 32
        assert reqs[0]["ttft_s"] is not None

        r = client.get("/debug/engine")
        assert r.status_code == 200
        data = r.json()["data"]
        assert data["steps"], "no engine steps recorded"
        assert data["engines"]["lm"]["status"] in ("UP", "DEGRADED")


def test_router_hop_stitches_one_trace_across_replica():
    """ISSUE 7 satellite: a generate proxied through the data-plane router
    (gofr_tpu.router) yields ONE trace — the router forwards traceparent so
    the replica's server span (and its engine children) parent under the
    router's span, and the replica's X-Trace-Id survives the hop."""
    from gofr_tpu.router import Router, RouterPolicy
    from gofr_tpu.tracing import MemoryExporter, Tracer

    replica = make_app()
    replica.container.tracer = Tracer(MemoryExporter())
    spec = ModelSpec("llama", LlamaConfig.tiny(), task="generate", dtype=jnp.float32)
    replica.serve_model("lm", spec, slots=2, max_len=32)
    replica.post("/generate", lambda ctx: ctx.generate(
        "lm", ctx.bind(dict)["prompt"], max_new_tokens=2, timeout=120))

    rapp = make_app()
    rapp.container.tracer = Tracer(MemoryExporter())
    router = Router(rapp.container,
                    policy=RouterPolicy(page_size=16, jitter_s=0.0))
    router.bind(rapp)

    inbound = "00-" + "c" * 32 + "-" + "d" * 16 + "-01"
    with AppHarness(replica) as hrep, AppHarness(rapp) as hr:
        router.registry.add_static("lm0", hrep.base)
        with httpx.Client(base_url=hr.base, timeout=180) as client:
            r = client.post("/generate", json={"prompt": [1, 2, 3]},
                            headers={"traceparent": inbound})
        assert r.status_code == 201, r.text
        # the replica's X-Trace-Id passes through the proxy response
        assert r.headers["X-Trace-Id"] == "c" * 32

    router_spans = {s.name: s for s in rapp.container.tracer._exporter.spans}
    rspan = router_spans["POST /generate"]
    assert rspan.trace_id == "c" * 32 and rspan.parent_id == "d" * 16
    replica_spans = {s.name: s for s in replica.container.tracer._exporter.spans}
    pspan = replica_spans["POST /generate"]
    assert pspan.trace_id == "c" * 32
    assert pspan.parent_id == rspan.span_id  # replica parents under the hop
    for name in ("engine.queue_wait", "engine.prefill", "engine.decode"):
        assert replica_spans[name].trace_id == "c" * 32, name
    router.stop()


def test_debug_endpoints_gated_outside_debug_env(lm_app):
    with AppHarness(lm_app) as h, httpx.Client(base_url=h.base, timeout=60) as client:
        assert client.get("/debug/requests").status_code == 404
        assert client.get("/debug/engine").status_code == 404


def test_unknown_model_is_client_error(lm_app):
    def bad(ctx):
        return ctx.generate("nope", [1], timeout=5)

    lm_app.post("/bad", bad)
    with AppHarness(lm_app) as h, httpx.Client(base_url=h.base, timeout=60) as client:
        r = client.post("/bad", json={})
        assert r.status_code == 500


# -- app-tier failure contract under a chaos-killed device loop ------------------
#
# The fleet chaos layer (gofr_tpu/fleet/chaos.py; docs/testing.md) injects
# "kill the device loop once the step counter reaches N" into the SAME app
# that serves traffic, proving the contract VERDICT r5 #6 asked for: in-
# flight work fails fast (5xx / in-band SSE error), queued work survives the
# supervised restart, and /.well-known/health is DEGRADED exactly during the
# restart window (held open deterministically by a chaos latch — no sleeps
# as synchronization).


def _chaos_app():
    from gofr_tpu.http.streaming import StreamingResponse

    app = make_app()
    spec = ModelSpec("llama", LlamaConfig.tiny(), task="generate", dtype=jnp.float32)
    app.serve_model("lm", spec, slots=2, max_len=64, decode_chunk=2)

    def generate(ctx):
        body = ctx.bind(dict)
        return ctx.generate("lm", body["prompt"],
                            max_new_tokens=int(body.get("max_new_tokens", 4)),
                            timeout=120)

    def generate_stream(ctx):
        body = ctx.bind(dict)
        it = ctx.generate("lm", body["prompt"],
                          max_new_tokens=int(body.get("max_new_tokens", 8)),
                          stream=True, timeout=120)
        return StreamingResponse(it, event="token")

    app.post("/generate", generate)
    app.post("/generate/stream", generate_stream)
    return app


def test_device_loop_kill_midstream_sse_and_degraded_window(tmp_path):
    import time as _time

    from gofr_tpu.fleet import chaos

    latch = tmp_path / "release-restart"
    with chaos.override(
            f"engine.step:raise,at_step=3;engine.restart:hold,file={latch},timeout=120"):
        app = _chaos_app()
        with AppHarness(app) as h, httpx.Client(base_url=h.base, timeout=180) as client:
            # in-flight SSE stream: 40 tokens at decode_chunk=2 is ~20 device
            # steps, so the at_step=3 kill lands mid-stream by construction
            events = []
            with client.stream("POST", "/generate/stream",
                               json={"prompt": [1, 2, 3], "max_new_tokens": 40}) as r:
                assert r.status_code == 200
                for line in r.iter_lines():
                    if line.startswith("event: "):
                        events.append(line.split("event: ", 1)[1])
            assert "error" in events, events  # IN-BAND error, not a dropped conn
            assert "done" not in events       # the stream did not lie about finishing

            # restart window is latch-held open: health MUST be DEGRADED now
            deadline = _time.time() + 60
            while _time.time() < deadline:
                health = client.get("/.well-known/health").json()["data"]
                if health["status"] == "DEGRADED":
                    break
                _time.sleep(0.02)
            assert health["status"] == "DEGRADED", health
            assert health["services"]["model:lm"]["status"] == "DEGRADED"

            # a request arriving DURING the window queues up and must survive
            results: list = []
            t = threading.Thread(target=lambda: results.append(
                client.post("/generate", json={"prompt": [4, 5], "max_new_tokens": 3})))
            t.start()
            latch.write_text("")  # release the held restart
            t.join(timeout=150)
            assert results, "queued request never completed after the restart"
            assert results[0].status_code == 201, results[0].text
            assert len(results[0].json()["data"]["tokens"]) == 3

            health = client.get("/.well-known/health").json()["data"]
            assert health["status"] == "UP", health  # DEGRADED only during the window


def test_device_loop_kill_inflight_5xx_then_recovers():
    from gofr_tpu.fleet import chaos

    with chaos.override("engine.step:raise,at_step=3"):
        app = _chaos_app()
        with AppHarness(app) as h, httpx.Client(base_url=h.base, timeout=180) as client:
            r = client.post("/generate", json={"prompt": [1, 2, 3], "max_new_tokens": 40})
            assert r.status_code == 500, r.text  # in-flight work fails FAST, not by timeout
            assert "error" in r.json()  # envelope, with internals masked
            # supervised restart: the same engine serves again (queue survived)
            r2 = client.post("/generate", json={"prompt": [1, 2], "max_new_tokens": 3})
            assert r2.status_code == 201, r2.text
            assert len(r2.json()["data"]["tokens"]) == 3
