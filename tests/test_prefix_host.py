"""Hierarchical prefix cache (ISSUE 4): host-DRAM spill tier + async swap-in.

Unit tier: the two-tier chain bookkeeping of ``tpu.prefix.PrefixCache`` —
spill/commit/promote transitions, host-LRU budget enforcement, mixed-tier
chains, and the upload-pending guard. Engine tier proves the load-bearing
properties on the CPU mesh: a forced spill→swap-in round trip is token-exact
on BOTH paged KV layouts (bf16 and int8 scales), per-tier hit metrics and
the flight-recorder ``prefix`` field surface the win, refcounts survive one
chain feeding several concurrent slots mid-swap-in, and preemption/cancel
racing an in-flight swap-in leaves the pool consistent.
"""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gofr_tpu.container import new_mock_container
from gofr_tpu.models import LlamaConfig, ModelSpec, llama
from gofr_tpu.testutil import assert_page_refs_consistent, assert_paged_pool_consistent
from gofr_tpu.tpu.engine import GenerateEngine, build_engine
from gofr_tpu.tpu.prefix import PrefixCache

pytestmark = pytest.mark.quick


class TestTieredCacheUnit:
    def test_spill_then_tiered_lookup_then_promote(self):
        c = PrefixCache(4, host_budget_bytes=1 << 20)
        toks = np.arange(8)
        c.insert(toks, [1, 2])
        # LRU spill takes the leaf first (dev_children == 0 discipline)
        key2, p2 = c.spill_lru()
        assert p2 == 2
        c.commit_spill(key2, ("payload2",), 100)
        assert len(c) == 1 and c.host_pages == 1 and c.host_bytes == 100
        # single-tier lookup stops at the host node; tiered walks through it
        assert c.lookup(toks) == [1]
        chain = c.lookup_tiered(toks)
        assert [n.page_id for _, n in chain] == [1, -1]
        assert chain[1][1].host == ("payload2",)
        # now the parent is spillable too
        key1, p1 = c.spill_lru()
        assert p1 == 1
        c.commit_spill(key1, ("payload1",), 100)
        assert len(c) == 0 and c.host_pages == 2
        # promote the child back: mixed-tier chain (host parent, dev child)
        c.promote(key2, 7)
        assert c.host_bytes == 100 and c.host_pages == 1
        chain = c.lookup_tiered(toks)
        assert [n.page_id for _, n in chain] == [-1, 7]
        # pending until settled: not spillable even as the only device node
        assert c.spill_lru() is None
        c.settle(key2)
        assert c.spill_lru()[1] == 7

    def test_host_budget_drops_lru_leaves(self):
        c = PrefixCache(4, host_budget_bytes=200)
        c.insert(np.array([1, 1, 1, 1]), [1])
        c.insert(np.array([2, 2, 2, 2]), [2])
        c.insert(np.array([3, 3, 3, 3]), [3])
        dropped = 0
        for want in (1, 2, 3):  # LRU spill order == insertion order
            key, p = c.spill_lru()
            assert p == want
            dropped += c.commit_spill(key, (f"pl{want}",), 100)
        # third commit blew the 200-byte budget: the oldest host page dropped
        assert dropped == 1
        assert c.host_pages == 2 and c.host_bytes == 200
        assert c.lookup_tiered(np.array([1, 1, 1, 1])) == []
        assert len(c.lookup_tiered(np.array([2, 2, 2, 2]))) == 1

    def test_zero_budget_cannot_hold_spills(self):
        # commit under a too-small budget immediately drops the node: the
        # net effect is a plain eviction, never a budget breach
        c = PrefixCache(4, host_budget_bytes=50)
        c.insert(np.arange(4), [9])
        key, p = c.spill_lru()
        assert c.commit_spill(key, ("x",), 100) == 1
        assert c.host_pages == 0 and c.host_bytes == 0 and len(c) == 0

    def test_insert_promotes_host_node_for_free(self):
        # a slot that recomputed a host-resident page donates its device
        # copy: insert returns the id so the engine refs it for the cache
        c = PrefixCache(4, host_budget_bytes=1 << 20)
        toks = np.arange(4)
        c.insert(toks, [1])
        key, p = c.spill_lru()
        c.commit_spill(key, ("pl",), 100)
        assert c.insert(toks, [5]) == [5]
        assert c.lookup(toks) == [5]
        assert c.host_pages == 0 and c.host_bytes == 0

    def test_bytes_keys_dtype_stable(self):
        # int64 callers (tests) and int32 callers (the engine) must agree
        c = PrefixCache(4)
        c.insert(np.arange(8, dtype=np.int64), [1, 2])
        assert c.lookup(np.arange(8, dtype=np.int32)) == [1, 2]

    def test_clear_resets_both_tiers(self):
        c = PrefixCache(4, host_budget_bytes=1 << 20)
        c.insert(np.arange(8), [1, 2])
        key, _ = c.spill_lru()
        c.commit_spill(key, ("pl",), 100)
        assert sorted(c.clear()) == [1]  # host payloads hold no pool pages
        assert c.host_pages == 0 and c.host_bytes == 0 and len(c) == 0


# -- engine integration (paged layout, CPU mesh) --------------------------------


@pytest.fixture(scope="module")
def setup():
    cfg = LlamaConfig.tiny()
    params = llama.init(cfg, jax.random.key(7))

    def ref(prompt, n_new):
        seq = list(prompt)
        for _ in range(n_new):
            logits = llama.forward(cfg, params, jnp.asarray([seq], jnp.int32))
            seq.append(int(jnp.argmax(logits[0, -1])))
        return seq[len(prompt):]

    return cfg, params, ref


def make_engine(cfg, params, **kw):
    kw.setdefault("slots", 4)
    kw.setdefault("max_len", 64)
    kw.setdefault("max_prefill_batch", 2)
    kw.setdefault("kv_layout", "paged")
    kw.setdefault("page_size", 8)
    kw.setdefault("total_pages", 12)
    kw.setdefault("prefix_host_mb", 8.0)
    return GenerateEngine(llama, cfg, params, new_mock_container(), **kw)


def _tier_counts(eng, name):
    m = eng.metrics.get(name)
    if m is None:
        return {}
    out = {}
    for ls, v in m._values.items():
        out[dict(ls).get("tier", "")] = out.get(dict(ls).get("tier", ""), 0) + v
    return out


def _pressure_prompt(r):
    return [(r * 37 + 13 * i) % 180 + 2 for i in range(18)]


def _force_spill(eng, rounds=5):
    """Distinct prompts until pool pressure spills the earliest cached
    pages to the host tier (the existing eviction workload, now spilling).
    Returns the generated token lists for exactness checks."""
    out = []
    for r in range(rounds):
        out.append(eng.generate(_pressure_prompt(r),
                                max_new_tokens=4, timeout=300)["tokens"])
    return out


class TestHostTierEngine:
    def _spill_swapin_exact(self, setup, **engine_kw):
        """Acceptance shape: the forced-spill run must be token-exact vs the
        SAME engine configuration with the cache off — the comparison that
        isolates what caching changed (and the only valid one under int8 KV,
        whose quantized logits differ from the f32 incremental reference)."""
        cfg, params, _ = setup
        prompt = [(11 * i) % 190 + 1 for i in range(20)]  # 2 full pages @ 8
        ref_eng = make_engine(cfg, params, prefix_cache=False, **engine_kw)
        try:
            want = ref_eng.generate(prompt, max_new_tokens=6, timeout=300)["tokens"]
            want_rounds = _force_spill(ref_eng)
        finally:
            ref_eng.stop()
        eng = make_engine(cfg, params, **engine_kw)
        try:
            cold = eng.generate(prompt, max_new_tokens=6, timeout=300)
            assert cold["tokens"] == want, "cold run diverged from cache-off"
            assert _force_spill(eng) == want_rounds, "pressure rounds diverged"
            assert eng._prefix.host_pages > 0, "pool pressure never spilled"
            spilled_bytes = eng._prefix.host_bytes
            assert spilled_bytes == eng._prefix.host_pages * eng._page_bytes
            warm = eng.generate(prompt, max_new_tokens=6, timeout=300)
            assert warm["tokens"] == want, "host-tier swap-in changed greedy tokens"
            hits = _tier_counts(eng, "app_tpu_prefix_hit_tokens")
            assert hits.get("host", 0) == 16, hits  # both pages rode the host tier
            swapped = eng.metrics.get("app_tpu_prefix_swapin_pages_total")
            assert swapped is not None and sum(swapped._values.values()) == 2
            lat = eng.metrics.get("app_tpu_prefix_swapin_seconds")
            assert lat is not None and lat.count() >= 1
            # hit rate is computable: lookups and misses both counted
            assert sum(eng.metrics.get(
                "app_tpu_prefix_lookup_total")._values.values()) > 0
            entry = next(e for e in eng.flight.requests()
                         if e.get("prefix", {}).get("host_tokens"))
            assert entry["prefix"]["swapin_pages"] == 2
            assert_page_refs_consistent(eng)
            assert_paged_pool_consistent(eng, slots_empty=True)
        finally:
            eng.stop()

    def test_spill_swapin_token_exact_bf16(self, setup):
        self._spill_swapin_exact(setup)

    def test_spill_swapin_token_exact_int8(self, setup):
        self._spill_swapin_exact(setup, kv_quantize="int8")

    def test_host_mb_zero_is_single_tier(self, setup):
        """ENGINE_PREFIX_HOST_MB=0 (the default): pressure evicts outright —
        no host pages, no swap-ins, the pre-tier behavior exactly."""
        cfg, params, _ = setup
        eng = make_engine(cfg, params, prefix_host_mb=0.0)
        try:
            _force_spill(eng, rounds=6)
            assert eng._prefix.host_pages == 0 and eng._prefix.host_bytes == 0
            swapped = eng.metrics.get("app_tpu_prefix_swapin_pages_total")
            assert sum(swapped._values.values()) == 0
            evicted = _tier_counts(eng, "app_tpu_prefix_evicted_pages_total")
            assert evicted.get("hbm", 0) > 0 and "host" not in evicted
            assert_page_refs_consistent(eng)
        finally:
            eng.stop()

    def test_concurrent_slots_share_chain_mid_swapin(self, setup):
        """One spilled chain feeds several concurrent slots: the first hit
        swaps the pages in (promoting the nodes), later hits ref the same
        device pages — refcounts and tokens must both survive."""
        cfg, params, ref = setup
        shared = [(5 * i) % 120 + 1 for i in range(16)]  # 2 full pages
        prompts = [shared + [i + 1, 2 * i + 1, (3 * i) % 90 + 1] for i in range(6)]
        want = [ref(p, 5) for p in prompts]
        eng = make_engine(cfg, params)
        results = [None] * len(prompts)

        def worker(i):
            results[i] = eng.generate(prompts[i], max_new_tokens=5, timeout=300)

        try:
            eng.generate(shared + [7], max_new_tokens=1, timeout=300)  # seed
            _force_spill(eng)
            assert eng._prefix.host_pages > 0
            threads = [threading.Thread(target=worker, args=(i,))
                       for i in range(len(prompts))]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=300)
            for i, r in enumerate(results):
                assert r is not None, f"request {i} did not complete"
                assert r["tokens"] == want[i], f"request {i} diverged mid-swap-in"
            hits = _tier_counts(eng, "app_tpu_prefix_hit_tokens")
            assert hits.get("host", 0) >= 16, hits
            assert_page_refs_consistent(eng)
            assert_paged_pool_consistent(eng, slots_empty=True)
        finally:
            eng.stop()

    def test_cancel_racing_inflight_swapin(self, setup):
        """Cancel fired right at submission races the swap-in dispatch/fold;
        whichever side wins, the pool stays consistent, the upload (if it
        ran) left valid cache-owned content, and later traffic is exact."""
        cfg, params, ref = setup
        prompt = [(11 * i) % 190 + 1 for i in range(20)]
        eng = make_engine(cfg, params)
        try:
            eng.generate(prompt, max_new_tokens=4, timeout=300)
            _force_spill(eng)
            assert eng._prefix.host_pages > 0
            req = eng.submit(prompt, max_new_tokens=6, timeout=300)
            req.cancel()
            try:
                req.result(300)
            except Exception:  # noqa: BLE001 - RequestTimeout (cancel) or a result: both fine
                pass
            out = eng.generate(prompt, max_new_tokens=6, timeout=300)
            assert out["tokens"] == ref(prompt, 6)
            assert_page_refs_consistent(eng)
            assert_paged_pool_consistent(eng, slots_empty=True)
        finally:
            eng.stop()

    def test_lockstep_disables_host_tier(self, setup):
        """Swap-in payloads are host-local and cannot be announced to
        followers — under lockstep the knob degrades to single-tier with a
        warning instead of desynchronizing the fleet."""
        cfg, params, _ = setup
        eng = GenerateEngine(
            llama, cfg, params, new_mock_container(), slots=2, max_len=32,
            kv_layout="paged", page_size=8, prefix_host_mb=4.0,
            lockstep_role="leader")
        assert eng._prefix is not None and eng._prefix.host_budget == 0

    def test_build_engine_knob_plumbing(self, setup):
        cfg, params, _ = setup
        container = new_mock_container({"ENGINE_PREFIX_HOST_MB": "2"})
        eng = build_engine(
            ModelSpec(family="llama", task="generate", config=cfg), container,
            kv_layout="paged", slots=2, max_len=32, page_size=8)
        try:
            assert eng._prefix is not None
            assert eng._prefix.host_budget == 2 * (1 << 20)
        finally:
            eng.stop()
