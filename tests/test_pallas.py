"""Pallas kernels vs the XLA reference path, run under the Pallas
interpreter on the CPU test mesh (SURVEY.md §4 analog: hermetic device
tests without TPU hardware)."""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gofr_tpu.ops.attention import decode_attention, mha_attention
from gofr_tpu.ops.pallas.decode_attention import decode_attention as pallas_decode
from gofr_tpu.ops.pallas.flash_attention import flash_attention


def _qkv(key, b, sq, skv, hq, hkv, d, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, sq, hq, d), dtype)
    k = jax.random.normal(kk, (b, skv, hkv, d), dtype)
    v = jax.random.normal(kv, (b, skv, hkv, d), dtype)
    return q, k, v


@pytest.mark.parametrize("hq,hkv", [(4, 4), (8, 2)])
def test_flash_matches_xla_causal(hq, hkv):
    q, k, v = _qkv(jax.random.key(0), 2, 64, 64, hq, hkv, 32)
    want = mha_attention(q, k, v, causal=True, backend="xla")
    got = flash_attention(q, k, v, causal=True, interpret=True)
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)


def test_flash_kv_lengths_and_offset():
    b, sq, skv = 3, 24, 48
    q, k, v = _qkv(jax.random.key(1), b, sq, skv, 4, 2, 16)
    lengths = jnp.array([48, 17, 1], jnp.int32)
    offset = jnp.array([24, 5, 0], jnp.int32)
    want = mha_attention(
        q, k, v, causal=True, q_offset=offset, kv_lengths=lengths, backend="xla"
    )
    got = flash_attention(
        q, k, v, causal=True, q_offset=offset, kv_lengths=lengths, interpret=True
    )
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)


def test_flash_non_causal_padded_blocks():
    # seq lengths that don't divide the block size exercise the pad path
    q, k, v = _qkv(jax.random.key(2), 2, 9, 21, 2, 2, 8)
    lengths = jnp.array([21, 13], jnp.int32)
    want = mha_attention(q, k, v, causal=False, kv_lengths=lengths, backend="xla")
    got = flash_attention(q, k, v, causal=False, kv_lengths=lengths, interpret=True)
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)


def test_flash_fully_masked_rows_zero():
    q, k, v = _qkv(jax.random.key(3), 1, 8, 8, 2, 2, 8)
    lengths = jnp.array([0], jnp.int32)  # nothing visible
    got = flash_attention(q, k, v, causal=False, kv_lengths=lengths, interpret=True)
    assert not np.isnan(np.asarray(got)).any()
    np.testing.assert_allclose(got, jnp.zeros_like(got), atol=1e-7)


@pytest.mark.parametrize("hq,hkv,smax", [(4, 2, 64), (8, 8, 96)])
def test_decode_matches_xla(hq, hkv, smax):
    b, d = 4, 16
    key = jax.random.key(4)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, hq, d))
    k_cache = jax.random.normal(kk, (b, hkv, smax, d))
    v_cache = jax.random.normal(kv, (b, hkv, smax, d))
    lengths = jnp.array([1, 7, smax, smax // 2], jnp.int32)
    want = decode_attention(q, k_cache, v_cache, lengths, backend="xla")
    got = pallas_decode(q, k_cache, v_cache, lengths, interpret=True)
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)


def test_auto_backend_dispatches_interpret(monkeypatch):
    monkeypatch.setenv("GOFR_PALLAS_INTERPRET", "1")
    q, k, v = _qkv(jax.random.key(5), 1, 16, 16, 2, 2, 8)
    want = mha_attention(q, k, v, causal=True, backend="xla")
    got = mha_attention(q, k, v, causal=True, backend="auto")
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)


def test_llama_forward_with_pallas_backend(monkeypatch):
    """Whole-model parity: tiny Llama forward, XLA vs Pallas-interpret."""
    monkeypatch.setenv("GOFR_PALLAS", "0")
    from gofr_tpu.models import llama

    cfg = llama.LlamaConfig.tiny()
    params = llama.init(cfg, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (2, 32), 0, cfg.vocab_size)
    lengths = jnp.array([32, 20], jnp.int32)
    want = llama.forward(cfg, params, tokens, lengths)

    monkeypatch.setenv("GOFR_PALLAS", "1")
    monkeypatch.setenv("GOFR_PALLAS_INTERPRET", "1")
    jax.clear_caches()  # backend resolution happens at trace time
    got = llama.forward(cfg, params, tokens, lengths)
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=2e-4)
    jax.clear_caches()


def test_flash_grad_matches_xla(monkeypatch):
    """Training routes gradients through the _flash_mha custom_vjp when
    backend='auto' resolves to pallas — the backward pass must match XLA,
    including the kv_lengths/q_offset chunked-prefill arguments (ADVICE.md)."""
    b, sq, skv = 2, 16, 32
    q, k, v = _qkv(jax.random.key(7), b, sq, skv, 4, 2, 16)
    lengths = jnp.array([32, 11], jnp.int32)
    offset = jnp.array([16, 3], jnp.int32)

    def loss(q, k, v, backend):
        out = mha_attention(
            q, k, v, causal=True, q_offset=offset, kv_lengths=lengths, backend=backend
        )
        # non-uniform weighting so every output element contributes distinctly
        w = jnp.arange(out.size, dtype=out.dtype).reshape(out.shape)
        return jnp.sum(out * w)

    want = jax.grad(partial(loss, backend="xla"), argnums=(0, 1, 2))(q, k, v)
    monkeypatch.setenv("GOFR_PALLAS_INTERPRET", "1")
    got = jax.grad(partial(loss, backend="auto"), argnums=(0, 1, 2))(q, k, v)
    for g, w_ in zip(got, want):
        np.testing.assert_allclose(g, w_, atol=2e-3, rtol=2e-3)


def test_flash_grad_matches_xla_plain_causal(monkeypatch):
    q, k, v = _qkv(jax.random.key(8), 2, 32, 32, 8, 2, 32)

    def loss(q, k, v, backend):
        return jnp.sum(mha_attention(q, k, v, causal=True, backend=backend) ** 2)

    want = jax.grad(partial(loss, backend="xla"), argnums=(0, 1, 2))(q, k, v)
    monkeypatch.setenv("GOFR_PALLAS_INTERPRET", "1")
    got = jax.grad(partial(loss, backend="auto"), argnums=(0, 1, 2))(q, k, v)
    for g, w_ in zip(got, want):
        np.testing.assert_allclose(g, w_, atol=2e-3, rtol=2e-3)


# -- in-place KV append kernels (ops/pallas/kv_append) --------------------------


def test_append_inplace_matches_select(monkeypatch):
    """Slot-cache in-place append == the masked-select path, including
    dropped OOB writes for padding rows."""
    import numpy as np

    from gofr_tpu.ops.kvcache import append_tokens
    from gofr_tpu.ops.pallas.kv_append import append_tokens_inplace

    n, hkv, smax, d = 4, 2, 32, 16
    key = jax.random.key(0)
    k_layer = jax.random.normal(jax.random.fold_in(key, 1), (n, hkv, smax, d))
    v_layer = jax.random.normal(jax.random.fold_in(key, 2), (n, hkv, smax, d))
    k_new = jax.random.normal(jax.random.fold_in(key, 3), (n, hkv, d))
    v_new = jax.random.normal(jax.random.fold_in(key, 4), (n, hkv, d))
    # one row per tile-boundary case + one OOB (dropped) row
    positions = jnp.array([0, 7, 8, smax], jnp.int32)

    want_k, want_v = append_tokens(k_layer, v_layer, positions, k_new, v_new)
    got_k, got_v = append_tokens_inplace(
        k_layer, v_layer, positions, k_new, v_new, block_s=8, interpret=True)
    np.testing.assert_allclose(np.asarray(got_k), np.asarray(want_k), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(got_v), np.asarray(want_v), rtol=1e-6)


def test_append_paged_inplace_matches_select():
    """Paged-pool in-place append == the select path through a shuffled
    block table, OOB table rows dropped."""
    import numpy as np

    from gofr_tpu.ops.paged import append_tokens_paged
    from gofr_tpu.ops.pallas.kv_append import append_tokens_paged_inplace

    n, hkv, d, page, maxp = 3, 2, 16, 8, 3
    pool = 10
    key = jax.random.key(5)
    k_pool = jax.random.normal(jax.random.fold_in(key, 1), (pool, hkv, page, d))
    v_pool = jax.random.normal(jax.random.fold_in(key, 2), (pool, hkv, page, d))
    k_new = jax.random.normal(jax.random.fold_in(key, 3), (n, hkv, d))
    v_new = jax.random.normal(jax.random.fold_in(key, 4), (n, hkv, d))
    # page 0 is the reserved OOB sink under this lowering (the engine
    # never allocates it) — real rows use pages >= 1. The pre-fix clamp
    # (OOB -> pool-1) demonstrably LOSES a real write to the shared tile
    # even in interpreter mode, which is why the sink exists (ADVICE r4).
    table = jnp.array([[7, 2, 9], [4, 5, 3], [pool, pool, pool]], jnp.int32)
    positions = jnp.array([page + 3, 0, 5], jnp.int32)  # row 2 = OOB table

    want_k, want_v = append_tokens_paged(k_pool, v_pool, table, positions, k_new, v_new)
    got_k, got_v = append_tokens_paged_inplace(
        k_pool, v_pool, table, positions, k_new, v_new, interpret=True)
    np.testing.assert_allclose(np.asarray(got_k), np.asarray(want_k), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(got_v), np.asarray(want_v), rtol=1e-6)


def test_kv_write_env_dispatch(monkeypatch):
    """GOFR_KV_WRITE=pallas routes append_tokens through the kernel (under
    the interpreter here) with identical results to select."""
    import numpy as np

    from gofr_tpu.ops.kvcache import append_tokens

    n, hkv, smax, d = 2, 2, 16, 8
    key = jax.random.key(9)
    k_layer = jax.random.normal(jax.random.fold_in(key, 1), (n, hkv, smax, d))
    v_layer = k_layer + 1
    k_new = jax.random.normal(jax.random.fold_in(key, 2), (n, hkv, d))
    v_new = k_new + 1
    positions = jnp.array([3, smax], jnp.int32)

    want = append_tokens(k_layer, v_layer, positions, k_new, v_new)
    monkeypatch.setenv("GOFR_KV_WRITE", "pallas")
    monkeypatch.setenv("GOFR_PALLAS_INTERPRET", "1")
    got = append_tokens(k_layer, v_layer, positions, k_new, v_new)
    np.testing.assert_allclose(np.asarray(got[0]), np.asarray(want[0]), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(got[1]), np.asarray(want[1]), rtol=1e-6)


def test_append_paged_oob_redirects_to_sink_page():
    """ADVICE r4: an OOB row must route its aliased tile fetch to the
    RESERVED sink page 0 — never clamp onto a page a real row writes in
    the same call (under Mosaic pipelining a stale copy-through could
    overwrite the real write). Here a real row writes the pool's LAST
    page while another row is OOB; the write must land and page 0 must
    be byte-identical."""
    import numpy as np

    from gofr_tpu.ops.pallas.kv_append import append_tokens_paged_inplace

    n, hkv, d, page, maxp = 2, 2, 16, 8, 2
    pool = 4
    key = jax.random.key(7)
    k_pool = jax.random.normal(jax.random.fold_in(key, 1), (pool, hkv, page, d))
    v_pool = jax.random.normal(jax.random.fold_in(key, 2), (pool, hkv, page, d))
    k_new = jax.random.normal(jax.random.fold_in(key, 3), (n, hkv, d))
    v_new = jax.random.normal(jax.random.fold_in(key, 4), (n, hkv, d))
    # row 0 writes the LAST page (the pre-fix clamp target); row 1 is OOB
    table = jnp.array([[pool - 1, 1], [pool, pool]], jnp.int32)
    positions = jnp.array([3, 0], jnp.int32)

    got_k, got_v = append_tokens_paged_inplace(
        k_pool, v_pool, table, positions, k_new, v_new, interpret=True)
    np.testing.assert_allclose(np.asarray(got_k[pool - 1, :, 3, :]),
                               np.asarray(k_new[0]), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(got_v[pool - 1, :, 3, :]),
                               np.asarray(v_new[0]), rtol=1e-6)
    # sink page 0 untouched by the OOB copy-through
    np.testing.assert_array_equal(np.asarray(got_k[0]), np.asarray(k_pool[0]))
    np.testing.assert_array_equal(np.asarray(got_v[0]), np.asarray(v_pool[0]))


def test_engine_reserves_sink_page_under_pallas_paged_write(monkeypatch):
    """With GOFR_PAGED_KV_WRITE=pallas the engine must never allocate
    page 0 (the kernel's OOB sink)."""
    from gofr_tpu.container import new_mock_container
    from gofr_tpu.models import LlamaConfig, llama
    from gofr_tpu.tpu.engine import GenerateEngine

    monkeypatch.setenv("GOFR_PAGED_KV_WRITE", "pallas")
    monkeypatch.setenv("GOFR_PALLAS_INTERPRET", "1")
    cfg = LlamaConfig.tiny()
    params = llama.init(cfg, jax.random.key(7))
    eng = GenerateEngine(llama, cfg, params, new_mock_container(),
                         slots=2, max_len=64, kv_layout="paged", page_size=8)
    try:
        assert eng._page_sink == 1
        assert 0 not in eng._free_pages
        out = eng.generate([5, 3, 9], max_new_tokens=4, timeout=300)
        assert len(out["tokens"]) == 4
        assert 0 not in [p for pages in eng._slot_pages for p in pages]
    finally:
        eng.stop()
