"""Integration tests over the runnable examples (examples/*) — the
reference's example-tier test strategy (SURVEY.md §4 tier 2): start the
real app, hit it over real HTTP, assert the JSON envelope."""

import asyncio
import importlib.util
import io
import os
import time

import httpx

from tests.test_http_server import AppHarness
import pytest

# integration tier (CI `integration` job): multi-minute engine/process
# runs — excluded from the tier-1 gate via -m 'not slow' (docs/testing.md)
pytestmark = pytest.mark.slow

EXAMPLES = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "examples")


def load_example(name: str):
    path = os.path.join(EXAMPLES, name, "main.py")
    spec = importlib.util.spec_from_file_location(f"example_{name.replace('-', '_')}", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_http_server_example():
    app = load_example("http-server").build_app()
    with AppHarness(app) as h, httpx.Client(base_url=h.base) as c:
        r = c.get("/greet", params={"name": "Ada"})
        assert r.status_code == 200 and r.json()["data"] == "Hello Ada!"
        r = c.post("/person", json={"name": "ada", "age": 36})
        assert r.status_code == 201
        r = c.get("/person/ada")
        assert r.json()["data"] == {"name": "ada", "age": 36}
        r = c.get("/person/nobody")
        assert r.status_code == 404 and "error" in r.json()
        assert c.get("/.well-known/health").json()["data"]["status"] == "UP"


def test_serving_llm_example():
    app = load_example("serving-llm").build_app()
    with AppHarness(app) as h, httpx.Client(base_url=h.base, timeout=300) as c:
        r = c.post("/generate", json={"prompt": [1, 2, 3], "max_new_tokens": 4})
        assert r.status_code == 201, r.text
        data = r.json()["data"]
        assert len(data["tokens"]) == 4 and data["finish_reason"] == "length"
        # text path (VERDICT r3 weak #5): string prompt in, decoded text out
        r = c.post("/generate", json={"prompt": "hello tpu", "max_new_tokens": 4})
        assert r.status_code == 201, r.text
        data = r.json()["data"]
        assert len(data["tokens"]) == 4
        assert isinstance(data["text"], str)
        # string prompt must tokenize to the same ids the tokenizer yields
        from gofr_tpu.utils import ByteTokenizer

        want = c.post("/generate", json={
            "prompt": ByteTokenizer().encode("hello tpu"), "max_new_tokens": 4,
        }).json()["data"]
        assert want["tokens"] == data["tokens"]


def test_serving_llm_sse_streaming():
    """Text pieces arrive as individual SSE events over the open connection
    and concatenate to exactly the non-streaming greedy result's decoded
    text (VERDICT r2 #7; r3 weak #5 — the engine streams TEXT when a
    tokenizer is attached, incremental detokenization included)."""
    import json

    app = load_example("serving-llm").build_app()
    with AppHarness(app) as h, httpx.Client(base_url=h.base, timeout=300) as c:
        want = c.post("/generate", json={"prompt": "stream me", "max_new_tokens": 6})
        want_text = want.json()["data"]["text"]

        pieces, saw_done = [], False
        with c.stream("POST", "/generate/stream",
                      json={"prompt": "stream me", "max_new_tokens": 6}) as r:
            assert r.status_code == 200
            assert r.headers["content-type"].startswith("text/event-stream")
            assert "content-length" not in r.headers  # chunked: truly streaming
            cur = None
            for line in r.iter_lines():
                if line.startswith("event: "):
                    cur = line[len("event: "):]
                elif line.startswith("data: "):
                    if cur == "token":
                        pieces.append(json.loads(line[len("data: "):]))
                    elif cur == "done":
                        saw_done = True
        assert saw_done, "stream ended without a done event"
        assert all(isinstance(p, str) for p in pieces), pieces
        # exact-join: nothing lost or duplicated across SSE events (a random
        # model emits invalid byte sequences, so U+FFFD replacement glyphs
        # are legitimate CONTENT here — equality is the real invariant)
        assert "".join(pieces) == want_text, f"streamed {pieces!r} != unary {want_text!r}"


def test_serving_llm_sse_disconnect_frees_slot():
    """After a client drops the SSE connection mid-stream, the engine's
    slot must come free (via cancellation or completion — no ghost slot)."""
    app = load_example("serving-llm").build_app()
    with AppHarness(app) as h, httpx.Client(base_url=h.base, timeout=300) as c:
        engine = app.container.engines["lm"]
        with c.stream("POST", "/generate/stream",
                      json={"prompt": [1, 2, 3], "max_new_tokens": 50,
                            "timeout": 300}) as r:
            assert r.status_code == 200
            for line in r.iter_lines():
                if line.startswith("data: "):
                    break  # first token arrived; drop the connection
        deadline = time.time() + 30
        while time.time() < deadline:
            if all(s is None for s in engine.slots) and not engine._pending:
                break
            time.sleep(0.1)
        assert all(s is None for s in engine.slots), (
            "slot still occupied long after the client disconnected"
        )


def test_serving_llm_websocket_streaming():
    """One websocket message per token (reference websocket.go:37-53 parity,
    but token-granular), terminated by a done frame."""
    import json

    import aiohttp

    app = load_example("serving-llm").build_app()
    with AppHarness(app) as h:
        async def drive():
            async with aiohttp.ClientSession() as session:
                async with session.ws_connect(f"{h.base}/ws/generate") as ws:
                    await ws.send_str(json.dumps({"prompt": "ws me", "max_new_tokens": 5}))
                    pieces = []
                    while True:
                        msg = await asyncio.wait_for(ws.receive(), timeout=120)
                        if msg.type != aiohttp.WSMsgType.TEXT:
                            break
                        # transport contract: every frame is JSON — text
                        # pieces are JSON strings, the terminal control
                        # frame is the object {"done": true}
                        payload = json.loads(msg.data)
                        if isinstance(payload, dict) and payload.get("done"):
                            return pieces
                        pieces.append(payload)

        pieces = asyncio.run(drive())
        assert pieces is not None and pieces, pieces
        assert all(isinstance(p, str) for p in pieces), pieces


def test_using_qos_example():
    """QoS example: interactive traffic serves, the batch class hits its
    concurrency cap under a flood (429 + Retry-After), counters move."""
    import threading

    app = load_example("using-qos").build_app()
    assert app.container.qos is not None  # QOS_ENABLED=true from configs/.env
    statuses = []
    lock = threading.Lock()

    def flood(i):
        with httpx.Client(timeout=300) as c:
            r = c.post(f"http://127.0.0.1:{app.http_port}/generate",
                       json={"prompt": [i + 1, 2, 3], "max_new_tokens": 24},
                       headers={"X-QoS-Class": "batch"})
            with lock:
                statuses.append(r)

    with AppHarness(app) as h, httpx.Client(base_url=h.base, timeout=300) as c:
        threads = [threading.Thread(target=flood, args=(i,)) for i in range(10)]
        for t in threads:
            t.start()
        r = c.post("/generate", json={"prompt": "hi", "max_new_tokens": 2,
                                      "timeout": 120},
                   headers={"X-QoS-Class": "interactive"})
        assert r.status_code == 201, r.text
        for t in threads:
            t.join(timeout=300)
        rejected = [r for r in statuses if r.status_code == 429]
        assert rejected, "batch flood never hit the class concurrency cap"
        for r in rejected:
            assert "Retry-After" in r.headers
        assert all(r.status_code in (201, 429, 503) for r in statuses)
        m = httpx.get(f"http://127.0.0.1:{app.metrics_port}/metrics").text
        assert "app_qos_rejected_total{" in m


def test_rest_handlers_example():
    app = load_example("using-add-rest-handlers").build_app()
    with AppHarness(app) as h, httpx.Client(base_url=h.base) as c:
        r = c.post("/book", json={"id": 1, "title": "SICP", "year": 1985})
        assert r.status_code == 201, r.text
        assert c.get("/book/1").json()["data"]["title"] == "SICP"
        c.put("/book/1", json={"id": 1, "title": "SICP", "year": 1996})
        assert c.get("/book/1").json()["data"]["year"] == 1996
        assert len(c.get("/book").json()["data"]) == 1
        assert c.delete("/book/1").status_code == 204
        assert c.get("/book/1").status_code == 404


def test_publisher_subscriber_examples_two_process(tmp_path):
    """The split pub/sub pair (reference `using-publisher`/`using-subscriber`):
    the SUBSCRIBER runs as a real separate process, the publisher in-process,
    and an order published over HTTP crosses the process boundary through the
    file-transport broker's shared log (pubsub/file.py) with at-least-once
    commit semantics — verified over the subscriber's own HTTP surface."""
    import subprocess
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from jaxpin import child_env
    from tests.test_http_server import _free_port

    from gofr_tpu.config import DictConfig

    sub_port = _free_port()
    env = child_env()
    env.update({
        "HTTP_PORT": str(sub_port), "METRICS_PORT": str(_free_port()),
        "PUBSUB_BACKEND": "file", "PUBSUB_DIR": str(tmp_path),
    })
    sub_main = os.path.join(EXAMPLES, "using-subscriber", "main.py")
    log = open(tmp_path / "subscriber.log", "w+")
    proc = subprocess.Popen([sys.executable, sub_main], env=env,
                            stdout=log, stderr=subprocess.STDOUT, text=True)
    try:
        pub = load_example("using-publisher").build_app(config=DictConfig({
            "HTTP_PORT": str(_free_port()), "METRICS_PORT": str(_free_port()),
            "PUBSUB_BACKEND": "file", "PUBSUB_DIR": str(tmp_path),
        }))
        with AppHarness(pub) as h, httpx.Client(base_url=h.base) as c:
            # subscriber process up?
            sub = httpx.Client(base_url=f"http://127.0.0.1:{sub_port}", timeout=5)
            deadline = time.time() + 60
            while time.time() < deadline:
                try:
                    if sub.get("/.well-known/health").status_code == 200:
                        break
                except httpx.TransportError:
                    time.sleep(0.1)
            else:
                log.flush(); log.seek(0)
                raise AssertionError(f"subscriber never came up:\n{log.read()[-3000:]}")

            r = c.post("/order", json={"id": 42, "qty": 2})
            assert r.status_code == 201 and r.json()["data"]["published"] is True
            # duplicate publish: the subscriber's idempotent handler applies
            # the effect once (at-least-once delivery, exactly-once effect)
            assert c.post("/order", json={"id": 42, "qty": 2}).status_code == 201

            deadline = time.time() + 30
            got: list = []
            while time.time() < deadline:
                got = sub.get("/processed").json()["data"]
                if got:
                    break
                time.sleep(0.1)
            assert got == [{"id": 42, "qty": 2}], got
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
        log.close()


def test_cron_example():
    mod = load_example("using-cron-jobs")
    mod.RUNS.clear()
    app = mod.build_app()
    assert [j.name for j in app.cron.jobs] == ["heartbeat"]
    app.cron.tick(time.time())  # fire synchronously instead of waiting a minute
    deadline = time.time() + 5
    while time.time() < deadline and not mod.RUNS:
        time.sleep(0.05)
    assert len(mod.RUNS) >= 1


def test_sample_cmd_example():
    mod = load_example("sample-cmd")
    app = mod.build_app()
    out, err = io.StringIO(), io.StringIO()
    code = app.run(["hello", "-name=Ada"], out=out, err=err)
    assert code == 0 and "Hello Ada!" in out.getvalue()
    out2 = io.StringIO()
    assert app.run(["hello", "-name=Ada", "-shout"], out=out2, err=err) == 0
    assert "HELLO ADA!" in out2.getvalue()
    outh = io.StringIO()
    app.run(["--help"], out=outh, err=err)
    assert "hello" in outh.getvalue() and "version" in outh.getvalue()


def test_migrations_example():
    app = load_example("using-migrations").build_app()
    rows = app.container.sql.query("SELECT version FROM gofr_migrations ORDER BY version")
    assert len(rows) == 2
    with AppHarness(app) as h, httpx.Client(base_url=h.base) as c:
        r = c.post("/user", json={"name": "ada", "email": "ada@x.io"})
        assert r.status_code == 201
        users = c.get("/user").json()["data"]
        assert users == [{"name": "ada", "email": "ada@x.io"}]


def test_web_socket_example():
    import aiohttp

    app = load_example("using-web-socket").build_app()
    with AppHarness(app) as h:
        async def roundtrip():
            async with aiohttp.ClientSession() as session:
                async with session.ws_connect(f"{h.base}/ws") as ws:
                    await ws.send_json({"n": 1})
                    return await ws.receive_json(timeout=10)

        got = asyncio.run(roundtrip())
        assert got == {"echo": {"n": 1}, "via": "gofr-tpu"}


def test_http_service_example():
    # downstream app the example's service client calls
    from gofr_tpu.config import DictConfig
    from gofr_tpu import App

    down = App(config=DictConfig({"HTTP_PORT": "8819", "METRICS_PORT": "9819",
                                  "LOG_LEVEL": "ERROR"}))
    down.get("/item", lambda ctx: {"sku": "tpu-v5e", "stock": 8})
    with AppHarness(down) as hd:
        app = load_example("using-http-service").build_app(hd.base)
        with AppHarness(app) as h, httpx.Client(base_url=h.base) as c:
            r = c.get("/fetch")
            assert r.status_code == 200, r.text
            body = r.json()["data"]
            assert body["status"] == 200
            assert body["downstream"]["data"]["sku"] == "tpu-v5e"


def test_custom_metrics_example():
    app = load_example("using-custom-metrics").build_app()
    with AppHarness(app) as h, httpx.Client(base_url=h.base) as c:
        assert c.post("/transaction", json={}).status_code == 201
        assert c.post("/transaction", json={}).status_code == 201
        assert c.post("/return", json={}).status_code == 201
        m = httpx.get(f"http://127.0.0.1:{app.metrics_port}/metrics").text
        assert "transaction_success 2" in m
        assert 'total_credit_day_sale{sale_type="credit"} 2000' in m
        assert 'total_credit_day_sale{sale_type="credit_return"} -1000' in m
        assert "product_stock 50" in m
        assert "transaction_time_bucket" in m


def test_file_bind_example():
    import io
    import zipfile

    app = load_example("using-file-bind").build_app()
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w") as zf:
        zf.writestr("a.txt", "alpha")
        zf.writestr("dir/b.txt", "beta!")
    with AppHarness(app) as h, httpx.Client(base_url=h.base) as c:
        r = c.post("/upload",
                   data={"name": "bundle"},
                   files={"upload": ("arch.zip", buf.getvalue(), "application/zip"),
                          "a": ("notes.md", b"# hi", "text/markdown")})
        assert r.status_code == 201, r.text
        data = r.json()["data"]
        assert data["name"] == "bundle"
        assert data["zip_files"] == ["a.txt", "dir/b.txt"]
        assert data["zip_bytes"] == len("alpha") + len("beta!")
        assert data["file"] == {"filename": "notes.md", "size": 4}


def test_grpc_server_example():
    """Drives the framework gRPC server end to end (interceptor chain,
    current_grpc_context, panic recovery) — no generated stubs needed."""
    import json as _json

    import grpc

    mod = load_example("grpc-server")
    app = mod.build_app()
    with AppHarness(app):
        with grpc.insecure_channel(f"127.0.0.1:{app.grpc_port}") as channel:
            say_hello = channel.unary_unary(
                f"/{mod.SERVICE}/SayHello",
                request_serializer=lambda o: _json.dumps(o).encode(),
                response_deserializer=lambda b: _json.loads(b.decode()),
            )
            assert say_hello({"name": "Ada"}, timeout=10) == {"message": "Hello Ada!"}

            boom = channel.unary_unary(
                f"/{mod.SERVICE}/Boom",
                request_serializer=lambda o: _json.dumps(o).encode(),
                response_deserializer=lambda b: _json.loads(b.decode()),
            )
            try:
                boom({}, timeout=10)
                raise AssertionError("panic was not surfaced as an RPC error")
            except grpc.RpcError as e:
                assert e.code() in (grpc.StatusCode.INTERNAL, grpc.StatusCode.UNKNOWN)

            # server survived the panic
            assert say_hello({"name": "Bob"}, timeout=10) == {"message": "Hello Bob!"}

            # server-streaming RPC through the interceptor
            countdown = channel.unary_stream(
                f"/{mod.SERVICE}/Countdown",
                request_serializer=lambda o: _json.dumps(o).encode(),
                response_deserializer=lambda b: _json.loads(b.decode()),
            )
            ticks = [m["tick"] for m in countdown({"from": 3}, timeout=10)]
            assert ticks == [3, 2, 1]

            # streaming handler crash → INTERNAL, not a connection reset
            try:
                list(countdown({"from": 1000}, timeout=10))
                raise AssertionError("stream error was not surfaced")
            except grpc.RpcError as e:
                assert e.code() in (grpc.StatusCode.INTERNAL, grpc.StatusCode.UNKNOWN)
            # and the server still serves
            assert say_hello({"name": "Eve"}, timeout=10) == {"message": "Hello Eve!"}


class MiniRedisServer:
    """A minimal in-process RESP server (SET/GET/DEL/PING/EXPIRE + inline
    pipelining) so the example's REAL wire-protocol client paths execute —
    the sandbox stand-in for the reference CI's Redis service container."""

    def __init__(self):
        import socket
        import threading

        self.store = {}
        self._srv = socket.create_server(("127.0.0.1", 0))
        self.port = self._srv.getsockname()[1]
        self._stop = False
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    def _serve(self):
        import threading

        while not self._stop:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(target=self._client, args=(conn,), daemon=True).start()

    def _client(self, conn):
        f = conn.makefile("rwb")
        try:
            while True:
                line = f.readline()
                if not line:
                    return
                if not line.startswith(b"*"):
                    continue
                n = int(line[1:].strip())
                parts = []
                for _ in range(n):
                    ln = f.readline()  # $<len>
                    size = int(ln[1:].strip())
                    parts.append(f.read(size))
                    f.read(2)  # trailing CRLF
                self._dispatch(parts, f)
                f.flush()
        except Exception:  # noqa: BLE001 - test server: drop the connection
            pass
        finally:
            conn.close()

    def _dispatch(self, parts, f):
        cmd = parts[0].upper()
        if cmd == b"PING":
            f.write(b"+PONG\r\n")
        elif cmd == b"SELECT" or cmd == b"AUTH":
            f.write(b"+OK\r\n")
        elif cmd == b"SET":
            self.store[parts[1]] = parts[2]
            f.write(b"+OK\r\n")
        elif cmd == b"GET":
            v = self.store.get(parts[1])
            if v is None:
                f.write(b"$-1\r\n")
            else:
                f.write(b"$%d\r\n%s\r\n" % (len(v), v))
        elif cmd == b"DEL":
            n = sum(1 for k in parts[1:] if self.store.pop(k, None) is not None)
            f.write(b":%d\r\n" % n)
        elif cmd == b"EXPIRE":
            f.write(b":1\r\n")
        else:
            f.write(b"-ERR unknown command\r\n")

    def close(self):
        self._stop = True
        self._srv.close()


def test_redis_example():
    from gofr_tpu.config import DictConfig

    srv = MiniRedisServer()
    try:
        config = DictConfig({
            "APP_NAME": "http-server-using-redis",
            "HTTP_PORT": "8818", "METRICS_PORT": "2818",
            "REDIS_HOST": "127.0.0.1", "REDIS_PORT": str(srv.port),
        })
        app = load_example("http-server-using-redis").build_app(config)
        with AppHarness(app) as h, httpx.Client(base_url=h.base) as c:
            assert c.post("/redis", json={"greeting": "hello"}).status_code == 201
            assert c.get("/redis/greeting").json()["data"] == "hello"
            assert c.get("/redis/absent").status_code == 404
            assert c.get("/redis-pipeline").json()["data"] == ["OK", "pipe-value"]
            health = c.get("/.well-known/health").json()["data"]
            assert health["services"]["redis"]["status"] == "UP"
    finally:
        srv.close()


def test_using_adapters_example():
    """Adapter multiplexing example: base and adapter requests co-serve
    on one engine — the base answer is unchanged by adapter traffic, the
    X-Adapter-ID header spells the same routing input as the body field,
    and the per-adapter perf meter shows up on /metrics."""
    app = load_example("using-adapters").build_app()
    eng = app.container.engine("lm")
    assert eng._adapters_enabled  # ADAPTER_SLOTS=4 from configs/.env
    with AppHarness(app) as h, httpx.Client(base_url=h.base, timeout=300) as c:
        base = c.post("/generate", json={"prompt": [1, 2, 3],
                                         "max_new_tokens": 6})
        assert base.status_code == 201, base.text
        fr = c.post("/generate", json={"prompt": [1, 2, 3],
                                       "max_new_tokens": 6,
                                       "adapter_id": "fr"})
        assert fr.status_code == 201, fr.text
        # header spelling reaches the same adapter as the body field
        fr_hdr = c.post("/generate", json={"prompt": [1, 2, 3],
                                           "max_new_tokens": 6},
                        headers={"X-Adapter-ID": "fr"})
        assert fr_hdr.status_code == 201, fr_hdr.text
        assert fr_hdr.json()["data"]["tokens"] == fr.json()["data"]["tokens"]
        # base lanes are unperturbed by the adapter traffic around them
        base2 = c.post("/generate", json={"prompt": [1, 2, 3],
                                          "max_new_tokens": 6})
        assert base2.json()["data"]["tokens"] == base.json()["data"]["tokens"]
        # an unknown adapter is a 400 client error, not an engine wedge
        bad = c.post("/generate", json={"prompt": [1, 2, 3],
                                        "max_new_tokens": 4,
                                        "adapter_id": "nope"})
        assert bad.status_code == 400, bad.text
        # ...and the engine still serves afterwards
        again = c.post("/generate", json={"prompt": [1, 2, 3],
                                          "max_new_tokens": 6})
        assert again.status_code == 201
        stats = c.get("/adapters").json()["data"]
        assert stats["enabled"] and stats["registry"]["registered"] == 2
        assert stats["pool"]["resident"] >= 1  # "fr" was uploaded on use
        m = httpx.get(f"http://127.0.0.1:{app.metrics_port}/metrics").text
        assert "app_tpu_adapters_registered" in m
        assert "app_tpu_adapter_device_seconds" in m
