"""Serve a REAL trained checkpoint end to end (VERDICT r4 #6): train a
small llama on real English text (a frozen snapshot of this repo's README,
tests/data/corpus.txt — frozen so doc edits can't move the measured
acceptance rates) with the train/
subsystem, checkpoint it with orbax, rebuild the serving stack from the
checkpoint DIRECTORY through the public ModelSpec path, and serve coherent
text with the real tokenizer — stream == result, detokenization
round-trips, prefix cache warm, speculative decoding on. Also produces the
honest speculative-acceptance numbers on NON-cyclic text that random-
weight benches cannot (VERDICT r4 #4): prompt-lookup vs a trained draft
model.

No network: the corpus is in-tree text, the tokenizer is the reversible
ByteTokenizer, training runs on the virtual CPU mesh in ~a minute.
"""

import pathlib

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from gofr_tpu.container import new_mock_container
from gofr_tpu.models import LlamaConfig, ModelSpec, llama
from gofr_tpu.parallel import build_mesh
from gofr_tpu.train import TrainState, cross_entropy_loss, make_train_step
from gofr_tpu.train.checkpoint import is_checkpoint_dir, save_params
from gofr_tpu.utils.tokenizer import ByteTokenizer

# integration tier (CI `integration` job): multi-minute engine/process
# runs — excluded from the tier-1 gate via -m 'not slow' (docs/testing.md)
pytestmark = pytest.mark.slow

SEQ = 128


def _corpus_ids(tok: ByteTokenizer, limit: int = 2048) -> np.ndarray:
    # small on purpose: a ~1M-param model memorizes it hard in a few
    # hundred steps, giving deterministic, *predictable* text — exactly
    # the regime where speculative acceptance can be measured honestly.
    # FROZEN snapshot (tests/data/corpus.txt), not the live README: the
    # measured rates below are corpus-dependent, and a doc edit must not
    # silently change what the model memorizes
    text = (pathlib.Path(__file__).resolve().parent / "data" / "corpus.txt").read_text()
    return np.asarray(tok.encode(text[:limit]), np.int32)


def _train(cfg: LlamaConfig, ids: np.ndarray, steps: int, seed: int):
    mesh = build_mesh(f"dp:{len(jax.devices())}")
    init_fn, step_fn = make_train_step(
        cfg, llama, mesh, optimizer=optax.adamw(1e-3, weight_decay=0.0))
    state = init_fn(jax.random.key(seed))
    # fixed windows, full batch every step — memorization, not generalization
    # stride == SEQ so 16 windows cover the WHOLE corpus — every
    # prompt position used below is trained
    starts = np.arange(0, ids.shape[0] - SEQ - 1, SEQ)[:16]
    tokens = np.stack([ids[s:s + SEQ + 1] for s in starts])
    lengths = np.full((tokens.shape[0],), SEQ + 1, np.int32)
    loss0 = loss = None
    for _ in range(steps):
        state, metrics = step_fn(state, jnp.asarray(tokens), jnp.asarray(lengths))
        loss = float(metrics["loss"])
        loss0 = loss if loss0 is None else loss0
    return state.params, loss0, loss


@pytest.fixture(scope="module")
def trained(tmp_path_factory):
    tok = ByteTokenizer()
    ids = _corpus_ids(tok)
    # vocab covers the ByteTokenizer's 259 ids; shapes stay MXU-friendly
    cfg = LlamaConfig(vocab_size=272, hidden_size=128, intermediate_size=352,
                      num_layers=3, num_heads=4, num_kv_heads=4,
                      max_seq_len=256, dtype=jnp.float32)
    params, loss0, loss = _train(cfg, ids, steps=700, seed=11)
    assert loss0 > 3.0, f"untrained loss suspiciously low: {loss0}"
    assert loss < 0.05, f"did not memorize the corpus: loss {loss0} -> {loss}"
    ckpt = tmp_path_factory.mktemp("ckpt") / "llama-readme"
    save_params(str(ckpt), params)
    assert is_checkpoint_dir(str(ckpt))

    # a genuinely SMALLER draft trained on the same text (different seed)
    dcfg = LlamaConfig(vocab_size=272, hidden_size=64, intermediate_size=176,
                       num_layers=2, num_heads=2, num_kv_heads=2,
                       max_seq_len=256, dtype=jnp.float32)
    dparams, _, dloss = _train(dcfg, ids, steps=500, seed=23)
    assert dloss < 1.0, f"draft did not learn the corpus: {dloss}"
    text = tok.decode(ids)
    return cfg, str(ckpt), dcfg, dparams, tok, text


def _engine_from_checkpoint(cfg, ckpt, tok, **kw):
    spec = ModelSpec("llama", cfg, task="generate", weights=ckpt,
                     tokenizer=tok, dtype=jnp.float32)
    from gofr_tpu.tpu.engine import build_engine

    return build_engine(spec, new_mock_container(), **kw)


def test_checkpoint_serves_coherent_text(trained):
    """The full loop: orbax checkpoint dir -> build_engine -> string prompt
    -> streamed text == result text == the memorized continuation."""
    cfg, ckpt, _, _, tok, text = trained
    eng = _engine_from_checkpoint(cfg, ckpt, tok, slots=2, max_len=192,
                                  decode_chunk=8, kv_layout="slot")
    try:
        prompt = text[256:288]          # mid-corpus slice, 32 chars
        expect = text[288:288 + 48]     # its true continuation
        req = eng.submit(prompt, max_new_tokens=48, stream=True)
        pieces = list(eng._stream_iter(req, timeout=600))
        out = req.result(timeout=60)
        assert out["text"] == "".join(pieces)  # stream == result, exactly
        # checkpoint-load fidelity: the SERVED tokens equal the trained
        # model's own free-run greedy continuation computed directly from
        # the restored engine params — the engine adds nothing and loses
        # nothing on the way from checkpoint dir to tokens
        params = eng.params
        seq = list(tok.encode(prompt))
        for _ in range(48):
            lg = llama.forward(cfg, params, jnp.asarray([seq], jnp.int32))
            seq.append(int(jnp.argmax(lg[0, -1])))
        assert out["tokens"] == seq[-48:]
        # coherence: free-run text tracks the memorized corpus. Byte-exact
        # reproduction is NOT guaranteed (locally-ambiguous patterns can
        # fork even at train loss <0.05), so the bar is a strong majority
        got = out["text"]
        match = sum(a == b for a, b in zip(got, expect))
        assert match >= 0.5 * min(len(got), len(expect)), (got, expect)
        # reversible tokenizer: result tokens decode to result text
        assert tok.decode(out["tokens"]) == out["text"]
    finally:
        eng.stop()


def test_spec_acceptance_on_real_text(trained):
    """The honest acceptance numbers: prompt-lookup vs a trained draft
    model, same trained target, same real-text prompts. On memorized text
    the draft should accept well; lookup depends on literal repetition."""
    cfg, ckpt, dcfg, dparams, tok, text = trained
    # WINDOW-ALIGNED offsets (training windows start at multiples of SEQ):
    # a prompt served from position 0 must have been TRAINED at position 0,
    # or both models extrapolate out-of-distribution at shifted rope
    # positions and their agreement — hence acceptance — collapses to
    # noise (measured: 0.05 at unaligned offsets vs near-perfect
    # teacher-forced agreement at aligned ones)
    prompts = [text[i:i + 24] for i in (128, 384, 768, 1280)]
    rates = {}
    for name, kw in (
        ("lookup", dict(spec_tokens=3)),
        ("draft", dict(spec_tokens=3, spec_draft=(llama, dcfg, dparams))),
    ):
        eng = _engine_from_checkpoint(cfg, ckpt, tok, slots=4, max_len=192,
                                      decode_chunk=4, kv_layout="slot", **kw)
        try:
            outs = [eng.submit(p, max_new_tokens=32) for p in prompts]
            for o in outs:
                assert o.result(timeout=600)["text"]
            prop = sum(eng.metrics.get("app_tpu_spec_proposed")._values.values())
            acc = sum(eng.metrics.get("app_tpu_spec_accepted")._values.values())
            rates[name] = acc / max(prop, 1)
        finally:
            eng.stop()
    # Measured on this harness (CPU, frozen corpus, 4 aligned prompts,
    # 32 new tokens): draft 0.14 vs lookup 0.05. The absolute rate is
    # DILUTED by design: `proposed` counts pipelined over-dispatched
    # rounds whose results are discarded at EOS/budget, and the rollout
    # leaves the reliably-memorized stretch partway (where two
    # independently-trained models diverge from each other). The robust
    # invariants: the trained draft lands REAL acceptance, and beats
    # prompt-lookup by a clear factor on non-cyclic text (VERDICT r4
    # #4's premise, confirmed).
    assert rates["draft"] > 0.08, rates
    assert rates["draft"] > 2 * max(rates["lookup"], 1e-9), rates


def test_prefix_cache_warm_with_spec_on_real_text(trained):
    """Paged + prefix + spec + real checkpoint: a shared system prompt is
    served twice; the warm pass must hit the prefix cache and produce the
    identical text."""
    cfg, ckpt, _, _, tok, text = trained
    eng = _engine_from_checkpoint(cfg, ckpt, tok, slots=4, max_len=192,
                                  decode_chunk=4, kv_layout="paged",
                                  page_size=16, spec_tokens=2,
                                  prefix_cache=True)
    try:
        prompt = text[512:568]  # 56 chars -> several full pages
        cold = eng.generate(prompt, max_new_tokens=24, timeout=600)
        warm = eng.generate(prompt, max_new_tokens=24, timeout=600)
        assert cold["text"] == warm["text"]
        hits = eng.metrics.get("app_tpu_prefix_hit_tokens")
        assert hits is not None and sum(hits._values.values()) > 0
    finally:
        eng.stop()
