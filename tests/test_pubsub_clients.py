"""Real-client pub/sub wrapper coverage (VERDICT r3 weak #9): the Kafka,
MQTT, and Google broker classes execute nowhere in CI because their client
libraries aren't installed. The reference's CI runs real brokers
(.github/workflows/go.yml:25-57); the hermetic sandbox equivalent drives
each wrapper against its injectable fake — MQTT and Google ship in-tree
fakes, Kafka gets a module-level stand-in via sys.modules — so the
wrapper logic (payload encoding, per-thread consumer keying, commit
plumbing, topic admin, health) actually runs."""

import sys
import threading
import types

import pytest

from gofr_tpu.config import DictConfig
from gofr_tpu.logging import MockLogger


# -- kafka ----------------------------------------------------------------------


class _FakeRecord:
    def __init__(self, value, offset, partition=0, headers=None):
        self.value = value
        self.offset = offset
        self.partition = partition
        self.headers = headers  # (str, bytes) pairs, like kafka-python


class _FakeKafkaState:
    """Topic log shared by producer and consumers, like one broker."""

    def __init__(self):
        self.topics: dict[str, list[bytes]] = {}
        self.commits: list[tuple[int, str]] = []
        self.cursors: dict[int, int] = {}  # consumer id -> next offset
        self.consumers_created = 0


def _install_fake_kafka(state: _FakeKafkaState):
    mod = types.ModuleType("kafka")

    class _Future:
        def get(self, timeout=None):
            return None

    class KafkaProducer:
        def __init__(self, bootstrap_servers=None, **kw):
            self.kw = kw

        def send(self, topic, value, headers=None):
            state.topics.setdefault(topic, []).append((value, headers))
            return _Future()

        def bootstrap_connected(self):
            return True

        def close(self):
            pass

    class KafkaConsumer:
        def __init__(self, topic, group_id=None, **kw):
            self.topic = topic
            self.group_id = group_id
            self.id = state.consumers_created
            state.consumers_created += 1
            state.cursors[self.id] = 0

        def poll(self, timeout_ms=1000, max_records=1):
            log = state.topics.get(self.topic, [])
            cur = state.cursors[self.id]
            if cur >= len(log):
                return {}
            state.cursors[self.id] = cur + 1
            value, headers = log[cur]
            return {("tp", 0): [_FakeRecord(value, cur, headers=headers)]}

        def commit(self):
            state.commits.append((self.id, self.topic))

        def close(self):
            pass

    mod.KafkaProducer = KafkaProducer
    mod.KafkaConsumer = KafkaConsumer
    sys.modules["kafka"] = mod
    return mod


@pytest.fixture
def kafka_broker():
    state = _FakeKafkaState()
    had = sys.modules.get("kafka")
    _install_fake_kafka(state)
    from gofr_tpu.pubsub.kafka import KafkaBroker

    broker = KafkaBroker(DictConfig({"PUBSUB_BROKER": "b1:9092,b2:9092"}),
                         MockLogger(), None)
    yield broker, state
    broker.close()
    if had is not None:
        sys.modules["kafka"] = had
    else:
        sys.modules.pop("kafka", None)


def test_kafka_publish_subscribe_commit(kafka_broker):
    broker, state = kafka_broker
    broker.publish("orders", {"n": 1})
    assert state.topics["orders"], "publish did not reach the producer"

    msg = broker.subscribe("orders", group="g1")
    assert msg is not None
    assert msg.bind(dict) == {"n": 1}
    assert msg.metadata["offset"] == 0
    msg.commit()
    assert len(state.commits) == 1

    assert broker.subscribe("orders", group="g1") is None  # log drained
    assert broker.health_check()["status"] == "UP"


def test_kafka_headers_round_trip(kafka_broker):
    """Trace context (traceparent) rides Kafka record headers and surfaces
    on the consumer Message's metadata (docs/observability.md)."""
    broker, state = kafka_broker
    broker.publish("traced", {"n": 2}, headers={"traceparent": "00-abc"})
    msg = broker.subscribe("traced", group="g1")
    assert msg is not None
    assert msg.param("traceparent") == "00-abc"
    # reserved metadata keys are never clobbered by a hostile header
    assert msg.metadata["offset"] == 0


def test_kafka_consumers_keyed_per_thread(kafka_broker):
    """SUBSCRIBER_WORKERS > 1 safety: each worker thread must join the
    group as its OWN consumer (kafka.py docstring), never share one."""
    broker, state = kafka_broker
    ids = []
    barrier = threading.Barrier(3)  # hold all threads alive together —
    # thread idents recycle once a thread exits, which would alias keys

    def worker():
        barrier.wait(timeout=10)
        c = broker._consumer("t", "g")
        ids.append(id(c))
        barrier.wait(timeout=10)

    threads = [threading.Thread(target=worker) for _ in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(set(ids)) == 3, "threads shared a KafkaConsumer"
    assert state.consumers_created == 3


# -- mqtt -----------------------------------------------------------------------


def make_mqtt():
    from gofr_tpu.pubsub.mqtt import FakeMqttClient, MqttBroker

    return MqttBroker(DictConfig({"MQTT_QOS": "1"}), MockLogger(), None,
                      client_factory=lambda cid: FakeMqttClient())


def test_mqtt_roundtrip_and_topic_admin():
    broker = make_mqtt()
    broker.create_topic("sensor")  # subscribes the loopback client
    broker.publish("sensor", {"temp": 21})
    msg = broker.subscribe("sensor", timeout=1.0)
    assert msg is not None and msg.bind(dict) == {"temp": 21}
    msg.commit()  # QoS redelivery is protocol-level; commit is a no-op
    assert broker.health_check()["status"] == "UP"
    broker.delete_topic("sensor")
    broker.publish("sensor", {"temp": 22})  # unsubscribed: dropped
    assert broker.subscribe("sensor", timeout=0.1) is None, (
        "message delivered to a deleted topic"
    )
    broker.close()


def test_mqtt_subscribe_with_function():
    broker = make_mqtt()
    broker.create_topic("cb")
    got = []
    done = threading.Event()

    def handler(msg):
        got.append(msg.bind(dict))
        done.set()

    broker.subscribe_with_function("cb", handler)
    broker.publish("cb", {"x": 1})
    assert done.wait(timeout=5), "callback never fired"
    assert got == [{"x": 1}]
    broker.close()


# -- google ---------------------------------------------------------------------


def make_google():
    from gofr_tpu.pubsub.google import FakeGooglePubSub, GooglePubSubBroker

    fake = FakeGooglePubSub()
    broker = GooglePubSubBroker(
        DictConfig({"GOOGLE_PROJECT_ID": "proj"}), MockLogger(), None,
        client_factory=lambda: (fake, fake),
    )
    return broker, fake


def test_google_publish_subscribe_ack():
    broker, fake = make_google()
    broker.create_topic("events")
    broker.publish("events", {"id": 7})
    msg = broker.subscribe("events", group="workers")
    assert msg is not None and msg.bind(dict) == {"id": 7}
    msg.commit()  # acknowledges through the subscriber client
    assert broker.subscribe("events", group="workers", timeout=0.1) is None
    assert broker.health_check()["status"] == "UP"
    broker.delete_topic("events")
    broker.close()


def test_google_requires_project_id():
    from gofr_tpu.pubsub.google import GooglePubSubBroker

    with pytest.raises(ValueError, match="GOOGLE_PROJECT_ID"):
        GooglePubSubBroker(DictConfig({}), MockLogger(), None,
                           client_factory=lambda: (None, None))
