"""Real-client pub/sub wrapper coverage (VERDICT r3 weak #9): the Kafka,
MQTT, and Google broker classes execute nowhere in CI because their client
libraries aren't installed. The reference's CI runs real brokers
(.github/workflows/go.yml:25-57); the hermetic sandbox equivalent drives
each wrapper against its injectable fake — MQTT and Google ship in-tree
fakes, Kafka gets a module-level stand-in via sys.modules — so the
wrapper logic (payload encoding, per-thread consumer keying, commit
plumbing, topic admin, health) actually runs."""

import sys
import threading
import types

import pytest

from gofr_tpu.config import DictConfig
from gofr_tpu.logging import MockLogger


# -- kafka ----------------------------------------------------------------------


class _FakeRecord:
    def __init__(self, value, offset, partition=0, headers=None):
        self.value = value
        self.offset = offset
        self.partition = partition
        self.headers = headers  # (str, bytes) pairs, like kafka-python


class _FakeKafkaState:
    """Topic log shared by producer and consumers, like one broker."""

    def __init__(self):
        self.topics: dict[str, list[bytes]] = {}
        self.commits: list[tuple[int, str]] = []
        self.cursors: dict[int, int] = {}  # consumer id -> next offset
        self.consumers_created = 0


def _install_fake_kafka(state: _FakeKafkaState):
    mod = types.ModuleType("kafka")

    class _Future:
        def get(self, timeout=None):
            return None

    class KafkaProducer:
        def __init__(self, bootstrap_servers=None, **kw):
            self.kw = kw

        def send(self, topic, value, headers=None):
            state.topics.setdefault(topic, []).append((value, headers))
            return _Future()

        def bootstrap_connected(self):
            return True

        def close(self):
            pass

    class KafkaConsumer:
        def __init__(self, topic, group_id=None, **kw):
            self.topic = topic
            self.group_id = group_id
            self.id = state.consumers_created
            state.consumers_created += 1
            state.cursors[self.id] = 0

        def poll(self, timeout_ms=1000, max_records=1):
            log = state.topics.get(self.topic, [])
            cur = state.cursors[self.id]
            if cur >= len(log):
                return {}
            state.cursors[self.id] = cur + 1
            value, headers = log[cur]
            return {("tp", 0): [_FakeRecord(value, cur, headers=headers)]}

        def commit(self):
            state.commits.append((self.id, self.topic))

        def close(self):
            pass

    mod.KafkaProducer = KafkaProducer
    mod.KafkaConsumer = KafkaConsumer
    sys.modules["kafka"] = mod
    return mod


@pytest.fixture
def kafka_broker():
    state = _FakeKafkaState()
    had = sys.modules.get("kafka")
    _install_fake_kafka(state)
    from gofr_tpu.pubsub.kafka import KafkaBroker

    broker = KafkaBroker(DictConfig({"PUBSUB_BROKER": "b1:9092,b2:9092"}),
                         MockLogger(), None)
    yield broker, state
    broker.close()
    if had is not None:
        sys.modules["kafka"] = had
    else:
        sys.modules.pop("kafka", None)


def test_kafka_publish_subscribe_commit(kafka_broker):
    broker, state = kafka_broker
    broker.publish("orders", {"n": 1})
    assert state.topics["orders"], "publish did not reach the producer"

    msg = broker.subscribe("orders", group="g1")
    assert msg is not None
    assert msg.bind(dict) == {"n": 1}
    assert msg.metadata["offset"] == 0
    msg.commit()
    assert len(state.commits) == 1

    assert broker.subscribe("orders", group="g1") is None  # log drained
    assert broker.health_check()["status"] == "UP"


def test_kafka_headers_round_trip(kafka_broker):
    """Trace context (traceparent) rides Kafka record headers and surfaces
    on the consumer Message's metadata (docs/observability.md)."""
    broker, state = kafka_broker
    broker.publish("traced", {"n": 2}, headers={"traceparent": "00-abc"})
    msg = broker.subscribe("traced", group="g1")
    assert msg is not None
    assert msg.param("traceparent") == "00-abc"
    # reserved metadata keys are never clobbered by a hostile header
    assert msg.metadata["offset"] == 0


def test_kafka_consumers_keyed_per_thread(kafka_broker):
    """SUBSCRIBER_WORKERS > 1 safety: each worker thread must join the
    group as its OWN consumer (kafka.py docstring), never share one."""
    broker, state = kafka_broker
    ids = []
    barrier = threading.Barrier(3)  # hold all threads alive together —
    # thread idents recycle once a thread exits, which would alias keys

    def worker():
        barrier.wait(timeout=10)
        c = broker._consumer("t", "g")
        ids.append(id(c))
        barrier.wait(timeout=10)

    threads = [threading.Thread(target=worker) for _ in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(set(ids)) == 3, "threads shared a KafkaConsumer"
    assert state.consumers_created == 3


# -- mqtt -----------------------------------------------------------------------


def make_mqtt():
    from gofr_tpu.pubsub.mqtt import FakeMqttClient, MqttBroker

    return MqttBroker(DictConfig({"MQTT_QOS": "1"}), MockLogger(), None,
                      client_factory=lambda cid: FakeMqttClient())


def test_mqtt_roundtrip_and_topic_admin():
    broker = make_mqtt()
    broker.create_topic("sensor")  # subscribes the loopback client
    broker.publish("sensor", {"temp": 21})
    msg = broker.subscribe("sensor", timeout=1.0)
    assert msg is not None and msg.bind(dict) == {"temp": 21}
    msg.commit()  # QoS redelivery is protocol-level; commit is a no-op
    assert broker.health_check()["status"] == "UP"
    broker.delete_topic("sensor")
    broker.publish("sensor", {"temp": 22})  # unsubscribed: dropped
    assert broker.subscribe("sensor", timeout=0.1) is None, (
        "message delivered to a deleted topic"
    )
    broker.close()


def test_mqtt_subscribe_with_function():
    broker = make_mqtt()
    broker.create_topic("cb")
    got = []
    done = threading.Event()

    def handler(msg):
        got.append(msg.bind(dict))
        done.set()

    broker.subscribe_with_function("cb", handler)
    broker.publish("cb", {"x": 1})
    assert done.wait(timeout=5), "callback never fired"
    assert got == [{"x": 1}]
    broker.close()


# -- google ---------------------------------------------------------------------


def make_google():
    from gofr_tpu.pubsub.google import FakeGooglePubSub, GooglePubSubBroker

    fake = FakeGooglePubSub()
    broker = GooglePubSubBroker(
        DictConfig({"GOOGLE_PROJECT_ID": "proj"}), MockLogger(), None,
        client_factory=lambda: (fake, fake),
    )
    return broker, fake


def test_google_publish_subscribe_ack():
    broker, fake = make_google()
    broker.create_topic("events")
    broker.publish("events", {"id": 7})
    msg = broker.subscribe("events", group="workers")
    assert msg is not None and msg.bind(dict) == {"id": 7}
    msg.commit()  # acknowledges through the subscriber client
    assert broker.subscribe("events", group="workers", timeout=0.1) is None
    assert broker.health_check()["status"] == "UP"
    broker.delete_topic("events")
    broker.close()


def test_google_requires_project_id():
    from gofr_tpu.pubsub.google import GooglePubSubBroker

    with pytest.raises(ValueError, match="GOOGLE_PROJECT_ID"):
        GooglePubSubBroker(DictConfig({}), MockLogger(), None,
                           client_factory=lambda: (None, None))


# -- file-transport broker (pubsub/file.py) --------------------------------------


def test_file_broker_cross_instance_roundtrip_and_restart(tmp_path):
    """Two FileBroker instances over one directory stand in for two
    PROCESSES (the using-publisher / using-subscriber pair): messages and
    headers cross the boundary, commits are durable, and a fresh instance
    (a restarted consumer process) resumes at the committed offset —
    redelivering the uncommitted suffix (at-least-once)."""
    from gofr_tpu.pubsub.file import FileBroker

    pub, sub = FileBroker(str(tmp_path)), FileBroker(str(tmp_path))
    pub.publish("orders", {"n": 1}, headers={"traceparent": "00-abc"})
    msg = sub.subscribe("orders", group="g", timeout=5)
    assert msg is not None and msg.bind(dict) == {"n": 1}
    assert msg.param("traceparent") == "00-abc"
    assert msg.metadata["offset"] == 0
    msg.commit()
    assert sub.subscribe("orders", group="g", timeout=0.1) is None  # drained

    # restarted consumer: starts from the durable committed offset
    pub.publish("orders", {"n": 2})
    sub2 = FileBroker(str(tmp_path))
    m2 = sub2.subscribe("orders", group="g", timeout=5)
    assert m2 is not None and m2.bind(dict) == {"n": 2} and m2.metadata["offset"] == 1
    # ...and m2 was never committed, so the NEXT restart redelivers it
    sub3 = FileBroker(str(tmp_path))
    m3 = sub3.subscribe("orders", group="g", timeout=5)
    assert m3 is not None and m3.bind(dict) == {"n": 2}
    m3.commit()
    assert sub3.subscribe("orders", group="g", timeout=0.1) is None
    assert pub.health_check()["status"] == "UP"
    assert "orders" in pub.topics()


def test_file_broker_never_delivers_torn_tail(tmp_path):
    """A publisher in another process can be observed mid-append: an
    unterminated trailing line is NOT a committed record and must not be
    delivered (it would hand the handler truncated bytes, and its commit
    would then skip the completed message). Only the newline lands it."""
    from gofr_tpu.pubsub.file import FileBroker

    b = FileBroker(str(tmp_path))
    b.publish("t", {"n": 0})
    full_line = open(b._log_path("t")).read()
    with open(b._log_path("t"), "a") as f:
        f.write(full_line.rstrip("\n"))  # mid-append snapshot: no newline yet
    m0 = b.subscribe("t", group="g", timeout=5)
    assert m0 is not None and m0.bind(dict) == {"n": 0}
    m0.commit()
    assert b.subscribe("t", group="g", timeout=0.2) is None  # torn tail invisible
    with open(b._log_path("t"), "a") as f:
        f.write("\n")  # the append completes
    m1 = b.subscribe("t", group="g", timeout=5)
    assert m1 is not None and m1.metadata["offset"] == 1


def test_file_broker_contiguous_prefix_commit(tmp_path):
    """Out-of-order commits advance the durable offset only across a
    contiguous prefix (the in-memory broker's at-least-once rule)."""
    from gofr_tpu.pubsub.file import FileBroker

    b = FileBroker(str(tmp_path))
    for n in range(3):
        b.publish("t", {"n": n})
    m0 = b.subscribe("t", group="g", timeout=5)
    m1 = b.subscribe("t", group="g", timeout=5)
    m2 = b.subscribe("t", group="g", timeout=5)
    m2.commit()  # gap at 0-1: offset must stay 0
    m1.commit()  # gap at 0: still 0
    assert b._read_offset("t", "g") == 0
    m0.commit()  # prefix complete -> 3
    assert b._read_offset("t", "g") == 3


# -- subscriber chaos: crash between handler and commit --------------------------


def test_subscriber_crash_between_handler_and_commit_redelivers():
    """The at-least-once hard case, driven by the chaos layer's
    ``pubsub.commit`` fault point (fleet/chaos.py): the handler runs, the
    injected crash lands BEFORE the offset commit, the broker redelivers,
    and the idempotent handler turns the duplicate delivery into an
    exactly-once EFFECT — after which the commit sticks and nothing is
    delivered again."""
    import time

    from gofr_tpu.app import new_testing
    from gofr_tpu.fleet import chaos

    app = new_testing({})
    broker = app.container.pubsub
    group = app.container.app_name
    deliveries: list = []
    effects: set = set()

    def handler(ctx):
        order = ctx.bind(dict)
        deliveries.append(order)
        effects.add(order["id"])  # set-add: idempotent effect

    app.subscribe("orders", handler)

    def wait_for(cond, what, timeout=10.0):
        deadline = time.monotonic() + timeout
        while not cond():
            assert time.monotonic() < deadline, f"timed out waiting for {what}"
            time.sleep(0.01)

    with chaos.override("pubsub.commit:raise,nth=1"):
        app._start_subscribers()
        try:
            broker.publish("orders", {"id": 7})
            wait_for(lambda: len(deliveries) == 1, "first delivery")
            # handler ran; the commit was killed -> offset NOT advanced
            wait_for(lambda: broker._cursor.get(("orders", group)) == 1,
                     "consume cursor")
            assert broker._offsets.get(("orders", group), 0) == 0
            # consumer crash/rebalance redelivers the uncommitted message
            broker.rewind_uncommitted("orders", group=group)
            wait_for(lambda: len(deliveries) == 2, "redelivery")
            wait_for(lambda: broker._offsets.get(("orders", group), 0) == 1,
                     "commit after retry")
            # exactly-once-after-retry EFFECT: applied once, committed once
            assert effects == {7}
            # nothing left to redeliver now that the commit stuck
            broker.rewind_uncommitted("orders", group=group)
            time.sleep(0.2)
            assert len(deliveries) == 2
        finally:
            app._sub_stop.set()
