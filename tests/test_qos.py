"""QoS subsystem tests (gofr_tpu.qos): rate limiting, weighted-fair
priority scheduling, admission control / load shedding, transport
integration (429/503 + Retry-After; gRPC RESOURCE_EXHAUSTED), and the
overload fault-injection case (VERDICT r5 #6).

The load-bearing properties:
- with QoS OFF the engine queue is byte-for-byte FIFO (the rest of the
  engine suite runs unmodified against it);
- under offered load >> capacity, interactive-class requests keep
  completing while excess traffic is rejected AT THE TRANSPORT with a
  Retry-After hint — never by burning a device slot until timeout.
"""

import queue
import threading
import time

import pytest

from gofr_tpu.config import DictConfig
from gofr_tpu.container import new_mock_container
from gofr_tpu.http.errors import (DeadlineExceeded, ServiceUnavailable,
                                  TooManyRequests)
from gofr_tpu.qos import (
    AdmissionController,
    PriorityClass,
    QoSPolicy,
    QoSQueue,
)
from gofr_tpu.qos.limiter import KeyedBuckets, TokenBucket


def make_policy(**kw):
    return QoSPolicy(**kw)


def make_controller(policy=None, container=None, **kw):
    c = container or new_mock_container()
    return AdmissionController(policy or make_policy(**kw), c.metrics), c


@pytest.mark.quick
class TestTokenBucket:
    def test_burst_then_throttle_then_refill(self):
        b = TokenBucket(rate=10.0, burst=2.0)
        now = time.monotonic()
        assert b.acquire(now=now) == 0.0
        assert b.acquire(now=now) == 0.0
        wait = b.acquire(now=now)  # burst exhausted
        assert wait == pytest.approx(0.1, abs=0.02)
        # after the hinted wait, one token exists again
        assert b.acquire(now=now + wait + 1e-6) == 0.0

    def test_zero_rate_disables(self):
        b = TokenBucket(rate=0.0)
        assert all(b.acquire() == 0.0 for _ in range(100))

    def test_tokens_cap_at_burst(self):
        b = TokenBucket(rate=100.0, burst=3.0)
        assert b.peek(now=time.monotonic() + 60) == 3.0

    def test_keyed_buckets_isolated_and_lru_bounded(self):
        kb = KeyedBuckets(rate=1.0, burst=1.0, max_keys=2)
        now_keys = ("a", "b")
        for k in now_keys:
            assert kb.acquire(k) == 0.0
        assert kb.acquire("a") > 0.0  # a's bucket is empty
        assert kb.acquire("c") == 0.0  # new key evicts LRU, stays bounded
        assert len(kb) == 2


@pytest.mark.quick
class TestQoSQueueFIFO:
    """QoS off: identical observable behavior to queue.Queue."""

    def test_fifo_order_and_empty(self):
        q = QoSQueue()
        for i in range(5):
            q.put(i)
        assert [q.get_nowait() for _ in range(5)] == list(range(5))
        with pytest.raises(queue.Empty):
            q.get_nowait()
        assert q.qsize() == 0

    def test_blocking_get_with_timeout(self):
        q = QoSQueue()
        t0 = time.monotonic()
        with pytest.raises(queue.Empty):
            q.get(timeout=0.05)
        assert time.monotonic() - t0 >= 0.05

    def test_blocking_get_wakes_on_put(self):
        q = QoSQueue()
        out = []

        def getter():
            out.append(q.get(timeout=5))

        t = threading.Thread(target=getter)
        t.start()
        q.put("x")
        t.join(timeout=5)
        assert out == ["x"]


class _Item:
    """Duck-typed engine Request: class on kw, deadline attribute."""

    def __init__(self, cls=None, deadline=None):
        self.kw = {"_qos_class": cls} if cls else {}
        self.deadline = deadline
        self.enqueued_at = time.monotonic()

    @property
    def cls(self):
        return self.kw.get("_qos_class", "default")


@pytest.mark.quick
class TestQoSQueuePriority:
    def test_interactive_overtakes_batch_backlog(self):
        q = QoSQueue(make_policy())
        for _ in range(4):
            q.put(_Item("batch"))
        q.put(_Item("interactive"))
        q.put(_Item("default"))
        first, second = q.get_nowait(), q.get_nowait()
        assert first.cls == "interactive"
        assert second.cls == "default"

    def test_weighted_fair_shares_under_saturation(self):
        """Saturated drain approximates the 8:4:1 class weights — batch is
        deprioritized but never starved."""
        q = QoSQueue(make_policy())
        for _ in range(80):
            q.put(_Item("interactive"))
            q.put(_Item("default"))
            q.put(_Item("batch"))
        drained = [q.get_nowait().cls for _ in range(13 * 4)]
        counts = {c: drained.count(c) for c in ("interactive", "default", "batch")}
        # one replenish cycle = 8 interactive + 4 default + 1 batch
        assert counts["interactive"] == 8 * 4
        assert counts["default"] == 4 * 4
        assert counts["batch"] == 1 * 4

    def test_edf_within_class(self):
        q = QoSQueue(make_policy())
        late = _Item("default", deadline=time.monotonic() + 60)
        soon = _Item("default", deadline=time.monotonic() + 1)
        never = _Item("default")  # no deadline sorts last
        q.put(never)
        q.put(late)
        q.put(soon)
        assert q.get_nowait() is soon
        assert q.get_nowait() is late
        assert q.get_nowait() is never

    def test_unknown_class_lands_in_default(self):
        q = QoSQueue(make_policy())
        q.put(_Item("no-such-class"))
        q.put(_Item("interactive"))
        assert q.get_nowait().cls == "interactive"
        assert q.get_nowait().cls == "no-such-class"  # scheduled as default

    def test_wait_nonempty_does_not_consume_or_bias(self):
        """The engine's idle poke must not pop (a get/put round trip would
        record fake wait samples, debit fair credits, and reorder)."""
        q = QoSQueue(make_policy())
        assert q.wait_nonempty(0.01) is False  # times out empty
        item = _Item("interactive")
        q.put(item)
        assert q.wait_nonempty(1.0) is True
        assert q.qsize() == 1  # nothing consumed
        assert q.get_nowait() is item

    def test_set_policy_migrates_fifo_backlog(self):
        q = QoSQueue()
        q.put(_Item("batch"))
        q.put(_Item("interactive"))
        q.set_policy(make_policy())
        assert q.qsize() == 2
        assert q.get_nowait().cls == "interactive"
        assert q.depths() == {"interactive": 0, "default": 0, "batch": 1}

    def test_set_policy_again_keeps_priority_backlog(self):
        """Re-registering a controller (QOS_ENABLED auto-enable followed by
        a programmatic enable_qos) swaps policies on a queue that already
        holds class-heap backlog — nothing may be dropped."""
        q = QoSQueue(make_policy())
        items = [_Item("batch"), _Item("interactive"), _Item("default")]
        for it in items:
            q.put(it)
        q.set_policy(make_policy(classes=[
            PriorityClass("interactive", 8.0),
            PriorityClass("default", 4.0),
            PriorityClass("batch", 1.0),
        ]))
        assert q.qsize() == 3
        drained = {q.get_nowait() for _ in range(3)}
        assert drained == set(items)


@pytest.mark.quick
class TestQoSPolicy:
    def test_from_config_full(self):
        p = QoSPolicy.from_config(DictConfig({
            "QOS_CLASSES": "gold:10:4,silver:3,bronze:1:16",
            "QOS_DEFAULT_CLASS": "silver",
            "QOS_RATE_RPS": "100",
            "QOS_MAX_QUEUE": "64",
            "QOS_CLASS_HEADER": "X-Tier",
        }))
        assert [c.name for c in p.classes] == ["gold", "silver", "bronze"]
        assert p.classes[0].max_concurrency == 4
        assert p.resolve("gold").weight == 10.0
        assert p.resolve(None).name == "silver"
        assert p.resolve("made-up").name == "silver"
        assert p.rate_rps == 100.0 and p.max_queue == 64
        assert p.class_header == "X-Tier"

    def test_defaults(self):
        p = QoSPolicy.from_config(DictConfig({}))
        assert [c.name for c in p.classes] == ["interactive", "default", "batch"]
        assert p.resolve(None).name == "default"

    def test_bad_default_class_rejected(self):
        with pytest.raises(ValueError, match="default class"):
            QoSPolicy(classes=[PriorityClass("a")], default_class="b")


@pytest.mark.quick
class TestAdmissionController:
    def test_rate_limit_rejects_with_retry_after(self):
        ctrl, c = make_controller(rate_rps=1.0, rate_burst=1.0)
        assert ctrl.admit_transport(route="/x").allowed
        d = ctrl.admit_transport(route="/x")
        assert not d.allowed and d.status == 429 and d.retry_after > 0
        assert c.metrics.get("app_qos_rejected_total").value(
            reason="rate", qos_class="default") == 1
        # rate rejections are NOT sheds: health stays UP
        assert ctrl.health_check()["status"] == "UP"

    def test_backlog_shed_and_degraded_health(self):
        ctrl, c = make_controller(max_queue=2, shed_window_s=60.0)

        class FakeEngine:
            num_slots = 2

            def _backlog(self):
                return 5

        ctrl.bind_engine("lm", FakeEngine())
        d = ctrl.admit_transport(route="/x")
        assert not d.allowed and d.status == 503
        assert ctrl.shedding
        assert ctrl.health_check()["status"] == "DEGRADED"
        assert c.metrics.get("app_qos_shed_total").value(reason="queue") == 1

    def test_engine_deadline_rejection(self):
        ctrl, _ = make_controller()

        class FakeEngine:
            num_slots = 2

            def _backlog(self):
                return 40

        eng = FakeEngine()
        ctrl.observe_step(1.0)  # EWMA: 1s/step, 40 queued / 2 lanes = ~20s wait
        with pytest.raises(DeadlineExceeded) as err:
            ctrl.admit_engine(eng, "interactive", timeout=5.0)
        # doomed work is a DEADLINE failure (504), not an overload (503):
        # waiting and retrying won't help THIS request, so no Retry-After
        assert err.value.status_code == 504
        # no deadline -> no deadline rejection
        assert ctrl.admit_engine(eng, "interactive", None).name == "interactive"

    def test_class_concurrency_cap_and_release(self):
        policy = make_policy(classes=[
            PriorityClass("interactive", 8.0),
            PriorityClass("default", 4.0),
            PriorityClass("batch", 1.0, max_concurrency=2),
        ])
        ctrl, _ = make_controller(policy=policy)

        class FakeEngine:
            num_slots = 4

            def _backlog(self):
                return 0

        class FakeReq:
            def __init__(self):
                self._cbs = []

            def add_done_callback(self, fn):
                self._cbs.append(fn)

            def finish(self):
                for fn in self._cbs:
                    fn(self)

        eng = FakeEngine()
        reqs = []
        for _ in range(2):
            cls = ctrl.admit_engine(eng, "batch", None)
            r = FakeReq()
            ctrl.track(r, cls)
            reqs.append(r)
        with pytest.raises(TooManyRequests) as err:
            ctrl.admit_engine(eng, "batch", None)
        assert err.value.status_code == 429
        reqs[0].finish()  # completion releases the share
        assert ctrl.admit_engine(eng, "batch", None).name == "batch"
        # uncapped class unaffected throughout
        assert ctrl.admit_engine(eng, "interactive", None).name == "interactive"

    def test_tenant_flood_does_not_drain_shared_buckets(self):
        """Limiters check most-specific first and short-circuit: a tenant
        rejected by its own bucket must not consume global tokens, so a
        well-behaved tenant keeps its full budget."""
        ctrl, _ = make_controller(rate_rps=100.0, rate_burst=100.0,
                                  tenant_rps=1.0)
        assert ctrl.admit_transport(tenant="flood").allowed
        for _ in range(20):  # rejected by the tenant bucket, global untouched
            d = ctrl.admit_transport(tenant="flood")
            assert not d.allowed and d.reason == "tenant_rate"
        # the global bucket paid only for the flood's single ADMIT — the 20
        # tenant-rejected requests consumed nothing shared
        assert ctrl._global.peek() >= 98.0
        assert ctrl.admit_transport(tenant="good").allowed

    def test_transport_backlog_gate_is_min_across_engines(self):
        """max_queue is per-engine: one full engine must not 503 traffic
        that could land on an idle one (admit_engine still guards the full
        engine itself)."""
        class FakeEngine:
            num_slots = 2

            def __init__(self, backlog):
                self._b = backlog

            def _backlog(self):
                return self._b

        ctrl, _ = make_controller(max_queue=4)
        ctrl.bind_engine("full", FakeEngine(10))
        ctrl.bind_engine("idle", FakeEngine(0))
        assert ctrl.admit_transport(route="/x").allowed
        ctrl2, _ = make_controller(max_queue=4)
        ctrl2.bind_engine("full", FakeEngine(10))
        assert not ctrl2.admit_transport(route="/x").allowed

    def test_reregister_replaces_scrape_hook(self):
        """QOS_ENABLED auto-enable followed by a programmatic enable_qos
        must not leave the stale controller's gauge sampler registered."""
        c = new_mock_container()
        first, _ = make_controller(container=c)
        second, _ = make_controller(container=c)
        c.register_qos(first)
        c.register_qos(second)
        assert c.qos is second
        hooks = c.metrics._collect_hooks
        assert sum(1 for h in hooks if getattr(h, "__self__", None) is first) == 0
        assert sum(1 for h in hooks if getattr(h, "__self__", None) is second) == 1

    def test_gauges_sampled_on_scrape(self):
        ctrl, c = make_controller()

        class FakeEngine:
            num_slots = 2
            _queue = QoSQueue(make_policy())

            def _backlog(self):
                return 3

        ctrl.bind_engine("lm", FakeEngine())
        c.metrics.add_collect_hook(ctrl.sample_gauges)
        text = c.metrics.expose_text()
        assert 'app_qos_queue_depth{qos_class="interactive"}' in text
        assert 'app_qos_predicted_wait_seconds{engine="lm"}' in text


@pytest.mark.quick
class TestGrpcInterceptor:
    def _details(self, metadata=()):
        class D:
            method = "/pkg.Svc/Do"
            invocation_metadata = metadata

        return D()

    def _handler(self, fn):
        import grpc

        return grpc.unary_unary_rpc_method_handler(fn)

    class _Ctx:
        def __init__(self):
            self.trailing = None
            self.aborted = None

        def set_trailing_metadata(self, md):
            self.trailing = md

        def abort(self, code, details):
            self.aborted = (code, details)
            raise RuntimeError(f"abort {code}")

    def test_rejection_aborts_resource_exhausted(self):
        import grpc

        from gofr_tpu.grpc.server import QoSGrpcInterceptor

        c = new_mock_container()
        c.register_qos(AdmissionController(
            make_policy(rate_rps=1.0, rate_burst=1.0), c.metrics))
        icpt = QoSGrpcInterceptor(c)
        inner_calls = []
        handler = icpt.intercept_service(
            lambda d: self._handler(lambda req, ctx: inner_calls.append(req) or "ok"),
            self._details(),
        )
        ctx = self._Ctx()
        assert handler.unary_unary("r1", ctx) == "ok"  # first passes
        ctx2 = self._Ctx()
        with pytest.raises(RuntimeError, match="abort"):
            handler.unary_unary("r2", ctx2)
        assert ctx2.aborted[0] == grpc.StatusCode.RESOURCE_EXHAUSTED
        assert dict(ctx2.trailing)["retry-after"]
        assert inner_calls == ["r1"]  # rejected RPC never reached the servicer

    def test_typed_engine_errors_map_to_grpc_codes(self):
        import grpc

        from gofr_tpu.grpc.server import _grpc_code_of

        assert _grpc_code_of(TooManyRequests()) == grpc.StatusCode.RESOURCE_EXHAUSTED
        assert _grpc_code_of(ServiceUnavailable()) == grpc.StatusCode.UNAVAILABLE
        assert _grpc_code_of(RuntimeError()) == grpc.StatusCode.INTERNAL


# -- engine + transport integration (tiny model on the CPU mesh) ----------------


@pytest.fixture(scope="module")
def tiny_llama():
    import jax

    from gofr_tpu.models import LlamaConfig, llama

    cfg = LlamaConfig.tiny()
    params = llama.init(cfg, jax.random.key(7))
    return cfg, params


def make_engine(cfg, params, container=None, **kw):
    from gofr_tpu.models import llama
    from gofr_tpu.tpu.engine import GenerateEngine

    kw.setdefault("slots", 2)
    kw.setdefault("max_len", 64)
    kw.setdefault("max_prefill_batch", 2)
    return GenerateEngine(llama, cfg, params, container or new_mock_container(), **kw)


class TestEngineQoSIntegration:
    def test_priority_class_rides_submit_kwargs(self, tiny_llama):
        cfg, params = tiny_llama
        c = new_mock_container()
        eng = make_engine(cfg, params, c)
        ctrl = AdmissionController(make_policy(), c.metrics)
        ctrl.bind_engine("lm", eng)
        try:
            out = eng.generate([1, 2, 3], max_new_tokens=2, timeout=120,
                               qos_class="interactive")
            assert len(out["tokens"]) == 2
            assert c.metrics.get("app_qos_admitted_total").value(
                qos_class="interactive") == 1
            # queue-wait histogram observed under the request's class
            assert c.metrics.get("app_qos_queue_wait_seconds").count(
                qos_class="interactive") >= 1
        finally:
            eng.stop()

    def test_deadline_hopeless_work_rejected_not_timed_out(self, tiny_llama):
        """The acceptance property: a request whose predicted wait exceeds
        its deadline is rejected AT SUBMIT with 504 deadline_exceeded — it
        never occupies a slot and never becomes a RequestTimeout."""
        cfg, params = tiny_llama
        c = new_mock_container()
        eng = make_engine(cfg, params, c)
        ctrl = AdmissionController(make_policy(), c.metrics)
        ctrl.bind_engine("lm", eng)
        ctrl._ewma_step = 30.0  # pretend steps take 30s
        eng._backlog = lambda: 10  # and 10 requests are already waiting
        try:
            t0 = time.monotonic()
            with pytest.raises(DeadlineExceeded) as err:
                eng.generate([1, 2, 3], max_new_tokens=2, timeout=2.0)
            assert time.monotonic() - t0 < 1.0, "rejection must be immediate"
            assert err.value.status_code == 504
            assert c.metrics.get("app_qos_rejected_total").value(
                reason="deadline_exceeded", qos_class="default") == 1
            assert c.metrics.get(
                "app_request_deadline_exceeded_total").value(where="qos") == 1
        finally:
            eng._backlog = lambda: 0
            eng.stop()

    def test_fifo_when_qos_disabled(self, tiny_llama):
        """No controller bound: the queue stays FIFO and nothing QoS-ish
        fires (the engine suite's byte-for-byte guarantee)."""
        cfg, params = tiny_llama
        c = new_mock_container()
        eng = make_engine(cfg, params, c)
        try:
            assert eng.qos is None
            assert eng._queue._policy is None
            out = eng.generate([1, 2, 3], max_new_tokens=2, timeout=120)
            assert len(out["tokens"]) == 2
            assert c.metrics.get("app_qos_admitted_total").value() == 0
        finally:
            eng.stop()


class TestOverloadEndToEnd:
    """Acceptance: offered load >> capacity over real HTTP — interactive
    completes, excess rejected at the transport with 429/503 + Retry-After,
    counters move, health reports DEGRADED while shedding."""

    def test_http_overload_shed_and_interactive_survival(self, tiny_llama):
        import httpx

        from tests.test_http_server import AppHarness, make_app

        app = make_app({
            "QOS_ENABLED": "true",
            # batch capped at 2 concurrent: the flood beyond that is
            # rejected at admission instead of queueing toward timeout
            "QOS_CLASSES": "interactive:8,default:4,batch:1:2",
        })
        cfg, params = tiny_llama
        eng = make_engine(cfg, params, app.container, slots=2)
        app.container.register_engine("lm", eng)

        async def generate(ctx):
            body = ctx.bind(dict)
            return await ctx.agenerate(
                "lm", body["prompt"],
                max_new_tokens=int(body.get("max_new_tokens", 4)),
                timeout=body.get("timeout", 120),
            )

        app.post("/generate", generate)
        statuses, lock = [], threading.Lock()

        def flood(i):
            with httpx.Client(base_url=h.base, timeout=120) as cl:
                r = cl.post("/generate", json={
                    "prompt": [i + 1, 2, 3], "max_new_tokens": 24,
                }, headers={"X-QoS-Class": "batch"})
                with lock:
                    statuses.append((r.status_code, dict(r.headers)))

        with AppHarness(app) as h:
            threads = [threading.Thread(target=flood, args=(i,)) for i in range(10)]
            for t in threads:
                t.start()
            # interactive traffic keeps completing while the flood runs
            with httpx.Client(base_url=h.base, timeout=120) as cl:
                for i in range(3):
                    r = cl.post("/generate", json={
                        "prompt": [50 + i, 1], "max_new_tokens": 2,
                        "timeout": 90,
                    }, headers={"X-QoS-Class": "interactive"})
                    assert r.status_code == 201, (
                        f"interactive request {i} failed under load: "
                        f"{r.status_code} {r.text}")
                health = cl.get("/.well-known/health")
            for t in threads:
                t.join(timeout=120)

            rejected = [(s, hd) for s, hd in statuses if s in (429, 503)]
            completed = [s for s, _ in statuses if s == 201]
            assert rejected, "flood never exceeded capacity — premise broken"
            assert completed, "admitted batch work must still finish"
            # never a slot-burning timeout
            assert all(s in (201, 429, 503) for s, _ in statuses), statuses
            for status, headers in rejected:
                # dict(httpx.Headers) lowercases keys
                assert "retry-after" in headers, (status, headers)
                assert int(headers["retry-after"]) >= 1
            # shedding flipped app health to DEGRADED (capacity sheds)
            assert health.status_code == 200
            assert health.json()["data"]["status"] == "DEGRADED"
            assert health.json()["data"]["services"]["qos"]["status"] == "DEGRADED"

            import re

            m = httpx.get(f"http://127.0.0.1:{app.metrics_port}/metrics").text
            counted = sum(
                float(line.rsplit(" ", 1)[1])
                for line in m.splitlines()
                if re.match(r"app_qos_rejected_total\{", line)
            )
            assert counted == len(rejected)

    def test_http_rate_limit_429(self):
        import httpx

        from tests.test_http_server import AppHarness, make_app

        app = make_app({
            "QOS_ENABLED": "true",
            "QOS_RATE_RPS": "1",
            "QOS_RATE_BURST": "2",
        })
        app.get("/ping", lambda ctx: "pong")
        with AppHarness(app) as h, httpx.Client(base_url=h.base) as cl:
            codes = [cl.get("/ping").status_code for _ in range(6)]
            assert 200 in codes and 429 in codes
            r = cl.get("/ping")
            if r.status_code == 429:
                assert "Retry-After" in r.headers
                assert r.json()["error"]["message"]
            # health/well-known bypass the limiter entirely
            for _ in range(5):
                assert cl.get("/.well-known/alive").status_code == 200


class TestOverloadFaultInjection:
    """VERDICT r5 #6: kill the device loop mid-stream under concurrent
    load — in-flight requests fail fast, queued requests survive the
    restart, health reports DEGRADED during the window."""

    def test_device_loop_crash_under_load(self, tiny_llama):
        cfg, params = tiny_llama
        c = new_mock_container()
        eng = make_engine(cfg, params, c, slots=1, decode_chunk=1,
                          max_restarts=10)
        # widen the DEGRADED window so the poller below cannot miss it:
        # pre-seeded restart count makes the next backoff sleep ~1.6s, and
        # a huge crash window stops the isolated-fault reset from undoing it
        eng.restart_window_s = 1e9
        eng._restarts = 3
        ctrl = AdmissionController(make_policy(), c.metrics)
        ctrl.bind_engine("lm", eng)

        armed = {"on": False}
        real = eng._decode_chunk

        def flaky(*a, **kw):
            if armed["on"]:
                armed["on"] = False
                raise RuntimeError("injected mid-stream device fault")
            return real(*a, **kw)

        eng._decode_chunk = flaky
        statuses, stop_poll = [], threading.Event()

        def poll_health():
            while not stop_poll.is_set():
                statuses.append(eng.health_check()["status"])
                time.sleep(0.005)

        poller = threading.Thread(target=poll_health, daemon=True)
        try:
            stream = eng.generate([5, 3, 9], max_new_tokens=400, timeout=300,
                                  stream=True)
            first = next(stream)  # the request is slot-resident and decoding
            assert isinstance(first, int)
            # queued-behind load: the single slot is held, so these wait
            queued = [eng.submit([i + 1, 2], max_new_tokens=3, timeout=300,
                                 qos_class="interactive") for i in range(2)]
            poller.start()
            armed["on"] = True

            # in-flight stream fails FAST (crash-recover, not timeout)
            t0 = time.monotonic()
            with pytest.raises(Exception) as err:
                for _ in stream:
                    pass
            assert time.monotonic() - t0 < 30
            assert "device fault" in str(err.value)

            # queued requests survive the restart and complete exactly
            for q in queued:
                out = q.result(timeout=300)
                assert len(out["tokens"]) == 3

            stop_poll.set()
            poller.join(timeout=5)
            assert "DEGRADED" in statuses, (
                "health never reported DEGRADED during the restart window")
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline and eng.health_check()["status"] != "UP":
                time.sleep(0.05)
            assert eng.health_check()["status"] == "UP"
            restarts = c.metrics.get("app_tpu_engine_restarts")
            assert restarts is not None and sum(restarts._values.values()) >= 1
        finally:
            stop_poll.set()
            eng.stop()


@pytest.mark.quick
class TestShedDuringRestart:
    def test_restarting_engine_sheds_new_work(self):
        """Shed-during-restart (docs/qos.md): while an engine's device loop
        is inside its crash-recovery backoff window, NEW submissions are
        rejected 503 + Retry-After (work already queued survives the
        restart; piling more on only deepens what the restarted loop must
        drain). Flips health to DEGRADED like every overload shed."""
        ctrl, c = make_controller(shed_window_s=60.0)

        class FakeEngine:
            num_slots = 2
            _restarting = True

            def _backlog(self):
                return 0

        eng = FakeEngine()
        with pytest.raises(ServiceUnavailable) as err:
            ctrl.admit_engine(eng, None, None)
        assert err.value.status_code == 503
        assert err.value.retry_after and err.value.retry_after > 0
        assert ctrl.shedding and ctrl.health_check()["status"] == "DEGRADED"
        assert c.metrics.get("app_qos_rejected_total").value(
            reason="restart", qos_class="default") == 1
        assert c.metrics.get("app_qos_shed_total").value(reason="restart") == 1

        # restart window over: admission resumes
        eng._restarting = False
        assert ctrl.admit_engine(eng, None, None).name == "default"
