"""Disaggregated prefill/decode serving (ISSUE 12): role-split engines
with paged-KV handoff over the fleet-style channel.

Unit tier: the KV wire codec (encode/decode round trip, bfloat16 planes,
frame-size cap). Engine tier proves the acceptance properties on the CPU
mesh: a prefill-role worker completes prompts with ``finish_reason=
"handoff"`` and ships bit-identical pages to a decode-role worker whose
generation is TOKEN-EXACT vs a colocated (``ENGINE_ROLE=both``) engine on
all three paged pool dtypes (bf16, int8, packed int4 — ISSUE 13), a
mismatched-dtype peer is rejected at JOIN; a stuck transfer is
shed by the PR 10 deadline plane as a 504 with ``where="handoff"``; and a
chaos-severed transfer (``kv.handoff``, either side) leaks zero pool
pages on BOTH workers (``assert_page_refs_consistent``).
"""

import socket
import time

import jax
import numpy as np
import pytest

from gofr_tpu.container import new_mock_container
from gofr_tpu.fleet import chaos
from gofr_tpu.http.errors import DeadlineExceeded
from gofr_tpu.models import LlamaConfig, llama
from gofr_tpu.testutil import assert_page_refs_consistent, assert_paged_pool_consistent
from gofr_tpu.tpu import handoff
from gofr_tpu.tpu.engine import GenerateEngine

pytestmark = pytest.mark.quick


# -- wire codec -----------------------------------------------------------------


def _roundtrip(payloads, toks, nbytes_page=64):
    """encode_frame → a real socket pair → decode_frame."""
    frame = handoff.encode_frame(np.asarray(toks, np.int32), payloads, nbytes_page)
    a, b = socket.socketpair()
    try:
        a.sendall(frame)
        return handoff.decode_frame(b)
    finally:
        a.close()
        b.close()


class TestWireCodec:
    def test_roundtrip_multi_plane(self):
        pages = [
            (np.arange(12, dtype=np.float32).reshape(3, 4),
             np.full((2, 2), i, np.int8))
            for i in range(3)
        ]
        toks, out, nbytes, _dt = _roundtrip(pages, [1, 2, 3, 4, 5])
        assert toks.tolist() == [1, 2, 3, 4, 5] and nbytes == 64
        assert len(out) == 3
        for want, got in zip(pages, out):
            for w, g in zip(want, got):
                assert w.dtype == g.dtype and (np.asarray(w) == np.asarray(g)).all()

    def test_roundtrip_bfloat16(self):
        import ml_dtypes

        page = (np.asarray([[1.5, -2.0]], ml_dtypes.bfloat16),)
        _, out, _, _ = _roundtrip([page], [7])
        assert out[0][0].dtype == ml_dtypes.bfloat16
        assert (np.asarray(out[0][0], np.float32) == [[1.5, -2.0]]).all()

    def test_frame_carries_kv_dtype_tag(self):
        page = (np.zeros((2, 2), np.uint8),)
        frame = handoff.encode_frame(np.asarray([3], np.int32), [page], 16,
                                     kv_dtype="int4")
        a, b = socket.socketpair()
        try:
            a.sendall(frame)
            _, _, _, dt = handoff.decode_frame(b)
            assert dt == "int4"
        finally:
            a.close()
            b.close()

    def test_encode_refuses_oversized_frame(self, monkeypatch):
        monkeypatch.setattr(handoff, "MAX_FRAME_BYTES", 64)
        big = [(np.zeros((64,), np.float32),)]
        with pytest.raises(ValueError, match="refusing to send"):
            handoff.encode_frame(np.asarray([1], np.int32), big, 256)

    def test_decode_rejects_lying_meta(self):
        import json
        import struct

        meta = json.dumps({
            "toks": [1], "n_pages": 1 << 30, "nbytes_page": 4,
            "planes": [{"dtype": "float32", "shape": [1024, 1024]}],
        }).encode()
        a, b = socket.socketpair()
        try:
            a.sendall(struct.pack("<i", len(meta)) + meta)
            with pytest.raises(ValueError, match="corrupt stream"):
                handoff.decode_frame(b)
        finally:
            a.close()
            b.close()


# -- engine tier (CPU mesh) ------------------------------------------------------


@pytest.fixture(scope="module")
def setup():
    cfg = LlamaConfig.tiny()
    params = llama.init(cfg, jax.random.key(7))
    return cfg, params


def make_engine(cfg, params, **kw):
    kw.setdefault("slots", 4)
    kw.setdefault("max_len", 64)
    kw.setdefault("max_prefill_batch", 2)
    kw.setdefault("kv_layout", "paged")
    kw.setdefault("page_size", 8)
    kw.setdefault("total_pages", 16)
    return GenerateEngine(llama, cfg, params, new_mock_container(), **kw)


PROMPT = [(11 * i) % 190 + 1 for i in range(20)]  # 2 full pages @ page_size 8


def _disagg_pair(cfg, params, **kw):
    dec = make_engine(cfg, params, role="decode", **kw)
    pre = make_engine(cfg, params, role="prefill",
                      handoff_target=dec.handoff_addr, **kw)
    return pre, dec


class TestDisaggServing:
    def _token_exact(self, setup, **engine_kw):
        """Acceptance: the P→D handoff run must be token-exact vs ONE
        colocated engine of the same configuration (the only valid
        comparison under int8 KV, whose quantized logits differ from an
        unquantized reference)."""
        cfg, params = setup
        colo = make_engine(cfg, params, **engine_kw)
        try:
            want = colo.generate(PROMPT, max_new_tokens=6, timeout=300)["tokens"]
        finally:
            colo.stop()
        pre, dec = _disagg_pair(cfg, params, **engine_kw)
        try:
            # 1) prefill worker: prompt prefill + KV export; the request
            # completes with exactly the first sampled token
            res = pre.generate(PROMPT, max_new_tokens=6, timeout=300)
            assert res["finish_reason"] == "handoff"
            assert res["tokens"] == [want[0]], "prefill first token diverged"
            assert res["ttft_s"] >= 0
            assert pre._handoff_exporter.stats()["exported"] == 1
            # 2) decode worker: the shipped chain is a host-tier prefix hit;
            # upload rides the swapin path and decode streams the rest
            assert dec._prefix.host_pages == 2, "import did not land both pages"
            assert dec._handoff_server.stats()["imported"] == 1
            out = dec.generate(PROMPT, max_new_tokens=6, timeout=300)
            assert out["tokens"] == want, "disagg decode diverged from colocated"
            swapped = dec.metrics.get("app_tpu_prefix_swapin_pages_total")
            assert swapped is not None and sum(swapped._values.values()) == 2
            # export-side transfer metrics (satellite: observability)
            pages = pre.metrics.get("app_tpu_kv_handoff_pages_total")
            assert pages is not None and sum(pages._values.values()) == 2
            lat = pre.metrics.get("app_tpu_kv_handoff_seconds")
            assert lat is not None and lat.count() == 1
            # zero-leak on BOTH sides (the acceptance drill)
            assert_page_refs_consistent(pre)
            assert_page_refs_consistent(dec)
            assert_paged_pool_consistent(dec, slots_empty=True)
        finally:
            pre.stop()
            dec.stop()

    def test_disagg_token_exact_bf16(self, setup):
        self._token_exact(setup)

    def test_disagg_token_exact_int8(self, setup):
        self._token_exact(setup, kv_quantize="int8")

    def test_disagg_token_exact_int4(self, setup):
        """ISSUE 13: the packed-int4 pool's nibble planes + per-position
        scale planes ship through the same frame codec, and disagg decode
        stays token-exact vs an int4 colocated engine."""
        self._token_exact(setup, kv_quantize="int4")

    def test_join_rejects_mismatched_kv_dtype(self, setup):
        """ISSUE 13 satellite: an int4 prefill worker dialing a bf16
        decode worker is rejected at JOIN (before any page frame moves)
        and the request is shed cleanly — no import, no page leak."""
        cfg, params = setup
        dec = make_engine(cfg, params, role="decode")  # bf16 pool
        pre = make_engine(cfg, params, role="prefill", kv_quantize="int4",
                          handoff_target=dec.handoff_addr,
                          handoff_timeout_s=1.0)
        try:
            with pytest.raises(DeadlineExceeded, match="handoff"):
                pre.generate(PROMPT, max_new_tokens=4, timeout=300)
            assert pre._handoff_exporter.stats()["failed"] == 1
            assert dec._handoff_server.stats()["imported"] == 0
            assert dec._handoff_server.stats()["rejected"] == 1
            assert dec._prefix.host_pages == 0
            assert_page_refs_consistent(pre)
            assert_page_refs_consistent(dec)
        finally:
            pre.stop()
            dec.stop()

    def test_role_validation(self, setup):
        cfg, params = setup
        with pytest.raises(ValueError, match="paged"):
            make_engine(cfg, params, kv_layout="slot", role="prefill",
                        page_size=None, total_pages=None)
        with pytest.raises(ValueError, match="ENGINE_ROLE"):
            make_engine(cfg, params, role="sidecar")
        with pytest.raises(ValueError, match="prefix cache"):
            make_engine(cfg, params, role="decode", prefix_cache=False)

    def test_prefill_without_target_falls_back_colocated(self, setup):
        """ENGINE_ROLE=prefill with no HANDOFF_TARGET: loud warn, prompts
        decode locally — bring-up must not brick a mis-wired worker."""
        cfg, params = setup
        eng = make_engine(cfg, params, role="prefill")
        try:
            res = eng.generate(PROMPT, max_new_tokens=4, timeout=300)
            assert res["finish_reason"] in ("stop", "length")
            assert len(res["tokens"]) == 4
        finally:
            eng.stop()

    def test_handoff_deadline_shed(self, setup):
        """A transfer that never ACKs (listener accepts, stays silent) is
        shed by the deadline plane: 504 DeadlineExceeded, where="handoff"
        counted, and the prefill side's pool stays consistent — the pages
        live on in its prefix cache, nothing leaks."""
        cfg, params = setup
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.bind(("127.0.0.1", 0))
        srv.listen(1)  # never accepted: connect+send buffer, the ACK never comes
        target = f"127.0.0.1:{srv.getsockname()[1]}"
        eng = make_engine(cfg, params, role="prefill", handoff_target=target,
                          handoff_timeout_s=0.5)
        try:
            t0 = time.monotonic()
            with pytest.raises(DeadlineExceeded, match="handoff"):
                eng.generate(PROMPT, max_new_tokens=4, timeout=300)
            assert time.monotonic() - t0 < 10.0, "shed was not deadline-bounded"
            shed = eng.metrics.get("app_request_deadline_exceeded_total")
            counts = {dict(ls).get("where"): v for ls, v in shed._values.items()}
            assert counts.get("handoff") == 1
            assert eng._handoff_exporter.stats()["failed"] == 1
            assert_page_refs_consistent(eng)
        finally:
            eng.stop()
            srv.close()

    def test_chaos_severed_transfer_leaks_nothing_either_side(self, setup):
        """kv.handoff chaos, both fire sites: an export-side sever (hit 1)
        and an import-side drop (hit 2 — frame arrives, dropped before
        import, connection closed with no ACK) each shed the request and
        leave BOTH pools consistent; the decode side holds zero imported
        pages. After the chaos window the same pair ships cleanly."""
        cfg, params = setup
        pre, dec = _disagg_pair(cfg, params)
        try:
            with chaos.override("kv.handoff:drop,nth=1"):
                with pytest.raises(DeadlineExceeded, match="handoff"):
                    pre.generate(PROMPT, max_new_tokens=4, timeout=300)
            assert dec._prefix.host_pages == 0
            assert_page_refs_consistent(pre)
            assert_page_refs_consistent(dec)

            prompt2 = [(13 * i) % 170 + 2 for i in range(20)]
            with chaos.override("kv.handoff:drop,nth=2"):
                with pytest.raises(DeadlineExceeded, match="handoff"):
                    pre.generate(prompt2, max_new_tokens=4, timeout=300)
            assert dec._prefix.host_pages == 0, "dropped frame was imported"
            assert dec._handoff_server.stats()["imported"] == 0
            assert_page_refs_consistent(pre)
            assert_page_refs_consistent(dec)

            # chaos cleared: the exporter re-dials and the path heals
            prompt3 = [(17 * i) % 150 + 3 for i in range(20)]
            res = pre.generate(prompt3, max_new_tokens=4, timeout=300)
            assert res["finish_reason"] == "handoff"
            assert dec._prefix.host_pages == 2
            assert_page_refs_consistent(pre)
            assert_page_refs_consistent(dec)
        finally:
            pre.stop()
            dec.stop()

    def test_handoff_stats_and_span(self, setup):
        """Role + transfer counters surface through engine.handoff_stats
        (what the gossip ships to /debug/fleet)."""
        cfg, params = setup
        pre, dec = _disagg_pair(cfg, params)
        try:
            pre.generate(PROMPT, max_new_tokens=4, timeout=300)
            ps = pre.handoff_stats()
            assert ps["role"] == "prefill" and ps["export"]["exported"] == 1
            ds = dec.handoff_stats()
            assert ds["role"] == "decode" and ds["import"]["imported"] == 1
            assert ds["addr"] == dec.handoff_addr
        finally:
            pre.stop()
            dec.stop()


class TestRouterRoleAwareness:
    def _registry(self):
        from gofr_tpu.router import RouterPolicy, Router

        container = new_mock_container()
        r = Router(container, RouterPolicy(ttl_s=0.0, jitter_s=0.0))
        return r

    def test_plan_filters_by_stage_when_role_split(self):
        r = self._registry()
        for name, role in (("p0", "prefill"), ("p1", "prefill"), ("d0", "decode")):
            r.registry.observe({"replica": name, "url": f"http://{name}",
                                "status": "UP", "role": role})
        for key in (1, 99, 12345, 999999):
            plan_p = r.plan(key, stage="prefill")
            assert plan_p.targets and all(
                r.registry.get(t.name).role == "prefill" for t in plan_p.targets)
            plan_d = r.plan(key, stage="decode")
            assert plan_d.targets and all(
                r.registry.get(t.name).role == "decode" for t in plan_d.targets)

    def test_plan_ignores_stage_for_colocated_fleet(self):
        r = self._registry()
        for name in ("r0", "r1"):
            r.registry.observe({"replica": name, "url": f"http://{name}",
                                "status": "UP"})
        p_any = r.plan(42)
        p_stage = r.plan(42, stage="decode")
        assert [t.name for t in p_any.targets] == [t.name for t in p_stage.targets]

    def test_stage_filter_stands_down_with_no_eligible_member(self):
        r = self._registry()
        r.registry.observe({"replica": "p0", "url": "http://p0",
                            "status": "UP", "role": "prefill"})
        plan = r.plan(7, stage="decode")  # no decode member: colocated fallback
        assert [t.name for t in plan.targets] == ["p0"]

    def test_replica_up_carries_role_label_only_when_split(self):
        from gofr_tpu.metrics import federation

        text = federation.fleet_text(
            {}, {"r0": {"status": "UP", "epoch": 0},
                 "d0": {"status": "UP", "epoch": 0, "role": "decode"}})
        assert 'app_fleet_replica_up{replica="r0"} 1' in text
        assert ('app_fleet_replica_up{replica="d0",role="decode"} 1' in text
                or 'app_fleet_replica_up{role="decode",replica="d0"} 1' in text)


class TestAdapterEraJoinGates:
    """PR 16 satellite: the JOIN hello now carries the adapter-set digest
    and the base-weight epoch. Mismatches are rejected BEFORE any page
    frame moves, with a distinct ACK code and a precise error both sides;
    a pre-adapter peer (hello without the fields) still joins."""

    def test_join_rejects_mismatched_adapter_set(self, setup):
        from gofr_tpu.adapters import random_adapter

        cfg, params = setup
        dec = make_engine(cfg, params, role="decode",
                          adapter_slots=2, adapter_rank=8)
        dec.register_adapter(random_adapter(
            "fr", cfg.hidden_size, cfg.vocab_size, rank=4, seed=1))
        # prefill side has the plane but NOT the adapter: digests differ
        pre = make_engine(cfg, params, role="prefill",
                          adapter_slots=2, adapter_rank=8,
                          handoff_target=dec.handoff_addr,
                          handoff_timeout_s=1.0)
        try:
            assert pre.adapters_digest() != dec.adapters_digest()
            with pytest.raises(DeadlineExceeded, match="handoff"):
                pre.generate(PROMPT, max_new_tokens=4, timeout=300)
            assert pre._handoff_exporter.stats()["failed"] == 1
            assert dec._handoff_server.stats()["imported"] == 0
            assert dec._handoff_server.stats()["rejected"] >= 1
            assert any("adapter set" in line
                       for line in dec.container.logger.lines)
            assert_page_refs_consistent(pre)
            assert_page_refs_consistent(dec)
        finally:
            pre.stop()
            dec.stop()

    def test_join_rejects_mismatched_kv_shards(self, setup):
        """ISSUE 19 satellite: page frames are SHARD-LOCAL views — a
        tp-sharded prefill worker's pages are 1/tp-width slices a
        replicated decode pool cannot splice. The tp-degree mismatch is
        rejected at JOIN (ACK_SHARD_MISMATCH, before any frame moves)
        with the sever leaking zero pages on either side."""
        from gofr_tpu.models import ModelSpec
        from gofr_tpu.tpu.engine import build_engine

        cfg, params = setup
        dec = make_engine(cfg, params, role="decode")  # kv_shards=1
        # honestly-sharded dialer: tiny() heads (Hq=4, Hkv=2) split over tp:2
        pre = build_engine(
            ModelSpec("llama", cfg, task="generate"),
            new_mock_container({"TPU_MESH": "tp:2", "TPU_DEVICES": "2",
                                "ENGINE_KV_SHARD": "tp"}),
            seed=7, slots=4, max_len=64, max_prefill_batch=2,
            kv_layout="paged", page_size=8, total_pages=16,
            role="prefill", handoff_target=dec.handoff_addr,
            handoff_timeout_s=1.0)
        try:
            assert pre.kv_shards == 2 and dec.kv_shards == 1
            with pytest.raises(DeadlineExceeded, match="handoff"):
                pre.generate(PROMPT, max_new_tokens=4, timeout=300)
            assert pre._handoff_exporter.stats()["failed"] == 1
            assert dec._handoff_server.stats()["imported"] == 0
            assert dec._handoff_server.stats()["rejected"] >= 1
            assert dec._prefix.host_pages == 0
            assert any("tp degree" in line
                       for line in dec.container.logger.lines)
            assert_page_refs_consistent(pre)
            assert_page_refs_consistent(dec)
        finally:
            pre.stop()
            dec.stop()

    def test_pre_shard_hello_is_wildcard_on_unsharded_peer(self, setup):
        """A pre-feature straggler whose hello omits kv_shards joins an
        UNSHARDED decode worker (absent = wildcard, the same rolling-
        upgrade contract the adapter/epoch gates follow)."""
        import json as _json

        cfg, params = setup
        dec = make_engine(cfg, params, role="decode")
        try:
            host, port = dec.handoff_addr.rsplit(":", 1)
            s = socket.create_connection((host, int(port)), timeout=5.0)
            try:
                hello = _json.dumps(
                    {"kv_dtype": handoff.engine_kv_dtype(dec)}).encode()
                s.sendall(handoff._MAGIC
                          + handoff._I32.pack(len(hello)) + hello)
                buf = b""
                while len(buf) < 4:
                    buf += s.recv(4 - len(buf))
                (status,) = handoff._I32.unpack(buf)
                assert status == handoff.ACK_OK
            finally:
                s.close()
            assert dec._handoff_server.stats().get("rejected", 0) == 0
        finally:
            dec.stop()

    def test_join_rejects_mismatched_weights_epoch(self, setup):
        cfg, params = setup
        dec = make_engine(cfg, params, role="decode")
        # a live hot-swap landed on the decode side only: same weights,
        # bumped epoch — pages from the stale prefill worker must bounce
        assert dec.adopt_weights(params) == 1
        pre = make_engine(cfg, params, role="prefill",
                          handoff_target=dec.handoff_addr,
                          handoff_timeout_s=1.0)
        try:
            with pytest.raises(DeadlineExceeded, match="handoff"):
                pre.generate(PROMPT, max_new_tokens=4, timeout=300)
            assert pre._handoff_exporter.stats()["failed"] == 1
            assert dec._handoff_server.stats()["imported"] == 0
            assert dec._handoff_server.stats()["rejected"] >= 1
        finally:
            pre.stop()
            dec.stop()

    def test_epoch_realignment_restores_the_path(self, setup):
        """After the SAME hot-swap lands on the prefill side too, the
        disagg path works again — the gate is about agreement, not age."""
        cfg, params = setup
        dec = make_engine(cfg, params, role="decode")
        dec.adopt_weights(params)
        pre = make_engine(cfg, params, role="prefill",
                          handoff_target=dec.handoff_addr)
        try:
            pre.adopt_weights(params)  # both at epoch 1 now
            res = pre.generate(PROMPT, max_new_tokens=4, timeout=300)
            assert res["finish_reason"] == "handoff"
            assert dec._handoff_server.stats()["imported"] == 1
        finally:
            pre.stop()
            dec.stop()

    def test_pre_adapter_hello_is_wildcard(self, setup):
        """A rolling upgrade straggler that sends neither field gates on
        neither: the decode worker ACKs OK even with adapters loaded."""
        import json as _json

        from gofr_tpu.adapters import random_adapter

        cfg, params = setup
        dec = make_engine(cfg, params, role="decode",
                          adapter_slots=2, adapter_rank=8)
        dec.register_adapter(random_adapter(
            "fr", cfg.hidden_size, cfg.vocab_size, rank=4, seed=1))
        try:
            host, port = dec.handoff_addr.rsplit(":", 1)
            s = socket.create_connection((host, int(port)), timeout=5.0)
            try:
                hello = _json.dumps(
                    {"kv_dtype": handoff.engine_kv_dtype(dec)}).encode()
                s.sendall(handoff._MAGIC
                          + handoff._I32.pack(len(hello)) + hello)
                buf = b""
                while len(buf) < 4:
                    buf += s.recv(4 - len(buf))
                (status,) = handoff._I32.unpack(buf)
                assert status == handoff.ACK_OK
            finally:
                s.close()
            assert dec._handoff_server.stats().get("rejected", 0) == 0
        finally:
            dec.stop()


# -- GOFR-HANDOFF2 streaming pipeline (ISSUE 18) ---------------------------------


LONG_PROMPT = [(7 * i) % 180 + 1 for i in range(40)]  # 5 pages @ page_size 8

# chunked prefill (prompt > top bucket) with one page per chunk, one page
# per wire chunk: five folds, each staging + shipping one page while the
# next chunk is still on the device
STREAM_KW = dict(prefill_buckets=[8], handoff_chunk_pages=1,
                 total_pages=32, max_len=128)


def _v2_dial(dec, streams=2):
    """One raw GOFR-HANDOFF2 stream connection: dial, hello with
    version=2, assert the server ACKs streaming; returns the socket."""
    import json as _json

    host, port = dec.handoff_addr.rsplit(":", 1)
    s = socket.create_connection((host, int(port)), timeout=5.0)
    hello = _json.dumps({
        "kv_dtype": handoff.engine_kv_dtype(dec),
        "version": handoff.PROTOCOL_VERSION, "streams": streams,
    }).encode()
    s.sendall(handoff._MAGIC + handoff._I32.pack(len(hello)) + hello)
    buf = b""
    while len(buf) < 4:
        buf += s.recv(4 - len(buf))
    (status,) = handoff._I32.unpack(buf)
    assert status == handoff.ACK_OK_STREAM
    return s


def _send_v2(sock, meta, payloads=()):
    """Ship one chunk the way the exporter frames it."""
    parts = [handoff._byte_view(np.ascontiguousarray(a))
             for page in payloads for a in page]
    for buf in handoff.chunk_parts(meta, parts):
        sock.sendall(bytes(buf) if isinstance(buf, memoryview) else buf)


def _pool_planes(dec, fill):
    """One hand-built page payload matching dec's pool plane geometry."""
    want = [((leaf.shape[0],) + tuple(leaf.shape[2:]), leaf.dtype)
            for leaf in jax.tree.leaves(dec.kv_cache)]
    return tuple(np.full(shape, fill).astype(dtype)
                 for shape, dtype in want)


class TestStreamingHandoff:
    def test_v2_hello_negotiates_streaming_ack(self, setup):
        """A version-2 hello gets ACK_OK_STREAM; the v1 hello (previous
        class) keeps getting plain ACK_OK — both generations JOIN through
        the same magic and the same dtype/adapter/epoch gates."""
        cfg, params = setup
        dec = make_engine(cfg, params, role="decode")
        try:
            _v2_dial(dec).close()
        finally:
            dec.stop()

    def test_streaming_chunked_prefill_token_exact(self, setup):
        """Tentpole acceptance: a chunked-prefill prompt streams page
        chunks DURING prefill, the decode side imports them incrementally,
        and the result is token-exact vs colocated. The exporter stats
        prove the negotiated mode and the per-stream accounting."""
        cfg, params = setup
        colo = make_engine(cfg, params, **STREAM_KW)
        try:
            want = colo.generate(LONG_PROMPT, max_new_tokens=6,
                                 timeout=300)["tokens"]
        finally:
            colo.stop()
        pre, dec = _disagg_pair(cfg, params, **STREAM_KW)
        try:
            res = pre.generate(LONG_PROMPT, max_new_tokens=6, timeout=300)
            assert res["finish_reason"] == "handoff"
            assert res["tokens"] == [want[0]]
            st = pre._handoff_exporter.stats()
            assert st["mode"] == "stream" and st["streams"] == 2
            assert st["exported"] == 1 and st["pages"] == 5
            # every stream carried bytes (round-robin chunk placement)
            assert len(st["stream_bytes"]) == 2
            assert sum(st["stream_bytes"]) >= st["bytes"]  # + begin/end framing
            assert dec._prefix.host_pages == 5
            assert dec._handoff_server.stats()["imported"] == 1
            out = dec.generate(LONG_PROMPT, max_new_tokens=6, timeout=300)
            assert out["tokens"] == want, "streamed decode diverged from colocated"
            assert_page_refs_consistent(pre)
            assert_page_refs_consistent(dec)
            assert_paged_pool_consistent(dec, slots_empty=True)
        finally:
            pre.stop()
            dec.stop()

    @pytest.mark.parametrize("kvq", ["int8", "int4"])
    def test_streaming_token_exact_quantized(self, setup, kvq):
        """Acceptance: streaming stays token-exact vs colocated on the
        quantized pools too (bf16 is the test above) — the chunk codec
        ships nibble planes and scale planes bit-identically."""
        cfg, params = setup
        kw = dict(STREAM_KW, kv_quantize=kvq)
        colo = make_engine(cfg, params, **kw)
        try:
            want = colo.generate(LONG_PROMPT, max_new_tokens=5,
                                 timeout=300)["tokens"]
        finally:
            colo.stop()
        pre, dec = _disagg_pair(cfg, params, **kw)
        try:
            res = pre.generate(LONG_PROMPT, max_new_tokens=5, timeout=300)
            assert res["tokens"] == [want[0]]
            out = dec.generate(LONG_PROMPT, max_new_tokens=5, timeout=300)
            assert out["tokens"] == want
            assert pre._handoff_exporter.stats()["mode"] == "stream"
        finally:
            pre.stop()
            dec.stop()

    def test_streaming_overlap_accounting(self, setup):
        """Overlap is counted deterministically at the exporter API level:
        pages staged and shipped BEFORE finish() count as overlap bytes,
        the tail after finish() does not; the overlap counter and gauge
        land in the registry."""
        from gofr_tpu.tpu.engine import Request

        cfg, params = setup
        dec = make_engine(cfg, params, role="decode")
        exp = None
        try:
            from gofr_tpu.metrics import Registry

            metrics = Registry()
            exp = handoff.HandoffExporter(
                dec.handoff_addr, engine=None, timeout_s=5.0, streams=2,
                chunk_pages=1, metrics=metrics)
            req = Request(list(PROMPT), {}, timeout=30.0)
            t = exp.begin_stream(req, np.asarray(PROMPT, np.int32),
                                 dec._page_bytes, time.monotonic())
            t.add([_pool_planes(dec, 0.25)])
            exp.kick(t)
            deadline = time.monotonic() + 5.0
            while t.sent_pages < 1 and time.monotonic() < deadline:
                time.sleep(0.01)  # first page must ship pre-finish
            assert t.sent_pages == 1
            assert dec._prefix.host_pages == 1, "first page not imported incrementally"
            t.add([_pool_planes(dec, 0.5)])
            exp.finish(t, first_token=3, now=time.monotonic())
            res = req.result(timeout=10.0)
            assert res["finish_reason"] == "handoff" and res["tokens"] == [3]
            st = exp.stats()
            assert st["exported"] == 1 and st["pages"] == 2
            assert 0 < st["overlap_bytes"] < st["bytes"]
            assert 0 < st["overlap_ratio"] < 1
            ovl = metrics.get("app_tpu_kv_handoff_overlap_bytes_total")
            assert ovl is not None and sum(ovl._values.values()) == st["overlap_bytes"]
            assert dec._prefix.host_pages == 2
            assert dec._handoff_server.stats()["imported"] == 1
        finally:
            if exp is not None:
                exp.close()
            dec.stop()

    def test_out_of_order_multistream_reassembly(self, setup):
        """Chunk seq/start_page sequencing: page 1 lands on stream B
        before page 0 lands on stream A (and before ``begin``!); the
        importer parks it, then registers the contiguous prefix once page
        0 arrives, and ACKs the ``end`` with everything imported."""
        cfg, params = setup
        dec = make_engine(cfg, params, role="decode")
        try:
            s0, s1 = _v2_dial(dec), _v2_dial(dec)
            try:
                pages = [_pool_planes(dec, 0.125), _pool_planes(dec, 0.375)]
                planes_meta = [{"dtype": str(a.dtype), "shape": list(a.shape)}
                               for a in pages[0]]
                xfer = "test:oOo"
                # page 1 first, on the OTHER stream, before begin
                _send_v2(s1, {"v": 2, "kind": "pages", "xfer": xfer, "seq": 1,
                              "start_page": 1, "n_pages": 1,
                              "planes": planes_meta}, [pages[1]])
                time.sleep(0.1)  # let it park (toks unknown: no import yet)
                assert dec._prefix.host_pages == 0
                _send_v2(s0, {"v": 2, "kind": "begin", "xfer": xfer,
                              "toks": [int(x) for x in PROMPT],
                              "nbytes_page": int(dec._page_bytes),
                              "kv_dtype": handoff.engine_kv_dtype(dec)})
                _send_v2(s0, {"v": 2, "kind": "pages", "xfer": xfer, "seq": 0,
                              "start_page": 0, "n_pages": 1,
                              "planes": planes_meta}, [pages[0]])
                _send_v2(s0, {"v": 2, "kind": "end", "xfer": xfer,
                              "total_pages": 2})
                s0.settimeout(10.0)
                (status,) = handoff._I32.unpack(s0.recv(4))
                assert status == handoff.ACK_OK
                assert dec._prefix.host_pages == 2
                assert dec._handoff_server.stats()["imported"] == 1
                assert dec._handoff_server.stats()["pages"] == 2
                assert_page_refs_consistent(dec)
            finally:
                s0.close()
                s1.close()
        finally:
            dec.stop()

    def test_mixed_version_pair_token_exact(self, setup):
        """Satellite: protocol compat across an in-place fleet upgrade,
        both directions. A v2 exporter against a HANDOFF1-only server
        negotiates DOWN to blob mode; a v1 exporter (streams=0) against a
        v2 server JOINs as blob. Both pairs serve token-exact."""
        cfg, params = setup
        colo = make_engine(cfg, params)
        try:
            want = colo.generate(PROMPT, max_new_tokens=5, timeout=300)["tokens"]
        finally:
            colo.stop()
        # new exporter → old server
        dec = make_engine(cfg, params, role="decode")
        dec._handoff_server.max_version = 1  # a pre-streaming build
        pre = make_engine(cfg, params, role="prefill",
                          handoff_target=dec.handoff_addr)
        try:
            res = pre.generate(PROMPT, max_new_tokens=5, timeout=300)
            assert res["finish_reason"] == "handoff"
            assert res["tokens"] == [want[0]]
            st = pre._handoff_exporter.stats()
            assert st["mode"] == "blob" and st["overlap_bytes"] == 0
            assert dec._prefix.host_pages == 2
            out = dec.generate(PROMPT, max_new_tokens=5, timeout=300)
            assert out["tokens"] == want, "down-negotiated pair diverged"
        finally:
            pre.stop()
            dec.stop()
        # old exporter (streams=0 → version-less hello) → new server
        dec = make_engine(cfg, params, role="decode")
        pre = make_engine(cfg, params, role="prefill",
                          handoff_target=dec.handoff_addr, handoff_streams=0)
        try:
            res = pre.generate(PROMPT, max_new_tokens=5, timeout=300)
            assert res["finish_reason"] == "handoff"
            assert res["tokens"] == [want[0]]
            assert pre._handoff_exporter.stats()["mode"] == "blob"
            out = dec.generate(PROMPT, max_new_tokens=5, timeout=300)
            assert out["tokens"] == want, "v1-exporter pair diverged"
        finally:
            pre.stop()
            dec.stop()

    def test_deadline_expiry_mid_stream_sheds_504(self, setup):
        """A peer that ACKs the streaming JOIN and then goes silent (never
        ACKs ``end``) is shed by the per-chunk deadline budget: 504
        DeadlineExceeded with where="handoff", bounded by
        HANDOFF_TIMEOUT_S, zero pages leaked on the prefill side."""
        import json as _json
        import threading

        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.bind(("127.0.0.1", 0))
        srv.listen(4)

        conns = []  # keep refs: GC closing a conn would mask the stall

        def _ack_stream_then_stall():
            while True:
                try:
                    conn, _ = srv.accept()
                except OSError:
                    return
                conns.append(conn)
                conn.recv(len(handoff._MAGIC))
                (n,) = handoff._I32.unpack(conn.recv(4))
                _json.loads(conn.recv(n))
                conn.sendall(handoff._I32.pack(handoff.ACK_OK_STREAM))
                # accept chunks into the TCP buffer, never ACK the end

        threading.Thread(target=_ack_stream_then_stall, daemon=True).start()
        cfg, params = setup
        eng = make_engine(cfg, params, role="prefill",
                          handoff_target=f"127.0.0.1:{srv.getsockname()[1]}",
                          handoff_timeout_s=0.5, **STREAM_KW)
        try:
            t0 = time.monotonic()
            with pytest.raises(DeadlineExceeded, match="handoff"):
                eng.generate(LONG_PROMPT, max_new_tokens=4, timeout=300)
            assert time.monotonic() - t0 < 10.0, "shed was not deadline-bounded"
            shed = eng.metrics.get("app_request_deadline_exceeded_total")
            counts = {dict(ls).get("where"): v for ls, v in shed._values.items()}
            assert counts.get("handoff") == 1
            assert_page_refs_consistent(eng)
        finally:
            eng.stop()
            srv.close()

    @pytest.mark.parametrize("spec", ["kv.handoff.hello:drop,nth=1",
                                      "kv.handoff.hello:drop,nth=2",
                                      "kv.handoff.chunk:drop,nth=1",
                                      "kv.handoff.chunk:drop,nth=2",
                                      "kv.handoff.midchunk:drop,nth=1"])
    def test_chaos_stream_sever_points_zero_leak(self, setup, spec):
        """Satellite: stream-granular sever drills. hello nth=1 severs the
        export-side JOIN, nth=2 the import side (gates passed, ACK never
        sent); chunk nth=1 severs at an export chunk boundary mid-prefill,
        nth=2 drops the first chunk on the import side before any page
        registers; midchunk tears the vectored write inside one frame.
        Every drill: clean 504, zero leaked pages BOTH sides, and the
        pair heals once chaos clears."""
        cfg, params = setup
        pre, dec = _disagg_pair(cfg, params, **STREAM_KW)
        try:
            with chaos.override(spec):
                with pytest.raises(DeadlineExceeded, match="handoff"):
                    pre.generate(LONG_PROMPT, max_new_tokens=4, timeout=300)
            if "hello" in spec:
                # the sever landed before ANY import could register
                assert dec._prefix.host_pages == 0
            # pages imported before a chunk-boundary sever are a valid
            # (shorter) host prefix — retained by design, not a leak; the
            # transfer itself never completes either way
            assert dec._handoff_server.stats()["imported"] == 0
            assert_page_refs_consistent(pre)
            assert_page_refs_consistent(dec)
            assert_paged_pool_consistent(dec, slots_empty=True)
            # chaos cleared: the exporter re-dials, re-negotiates, heals
            res = pre.generate(LONG_PROMPT, max_new_tokens=4, timeout=300)
            assert res["finish_reason"] == "handoff"
            assert dec._prefix.host_pages == 5
            assert_page_refs_consistent(pre)
            assert_page_refs_consistent(dec)
        finally:
            pre.stop()
            dec.stop()
