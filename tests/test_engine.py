"""Continuous-batching engine tests on the CPU mesh.

The load-bearing property: N requests served concurrently through the
slot-based engine must produce *identical* tokens to sequential
single-request generation with the same params (greedy), regardless of
arrival order, slot assignment, or padding.
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gofr_tpu.container import new_mock_container
from gofr_tpu.http.errors import RequestTimeout
from gofr_tpu.models import LlamaConfig, BertConfig, ViTConfig, ModelSpec, llama
from gofr_tpu.testutil import assert_paged_pool_consistent
from gofr_tpu.tpu.engine import (
    BatchEngine,
    GenerateEngine,
    Request,
    build_engine,
    next_bucket,
)


@pytest.fixture(scope="module")
def gen_setup():
    """Shared tiny llama + reference greedy generations."""
    cfg = LlamaConfig.tiny()
    params = llama.init(cfg, jax.random.key(7))

    def reference_generate(prompt, n_new):
        seq = list(prompt)
        for _ in range(n_new):
            logits = llama.forward(cfg, params, jnp.asarray([seq], jnp.int32))
            seq.append(int(jnp.argmax(logits[0, -1])))
        return seq[len(prompt):]

    return cfg, params, reference_generate


def make_container():
    return new_mock_container()


def make_gen_engine(cfg, params, container, **kw):
    kw.setdefault("slots", 4)
    kw.setdefault("max_len", 64)
    kw.setdefault("max_prefill_batch", 2)
    return GenerateEngine(llama, cfg, params, container, **kw)


def test_next_bucket():
    assert next_bucket(3, [4, 8, 16]) == 4
    assert next_bucket(4, [4, 8, 16]) == 4
    assert next_bucket(9, [4, 8, 16]) == 16
    with pytest.raises(ValueError):
        next_bucket(17, [4, 8, 16])


class TestGenerateEngine:
    def test_single_request_matches_reference(self, gen_setup):
        cfg, params, ref = gen_setup
        eng = make_gen_engine(cfg, params, make_container())
        try:
            out = eng.generate([5, 3, 9], max_new_tokens=6, timeout=60)
            assert out["finish_reason"] == "length"
            assert out["tokens"] == ref([5, 3, 9], 6)
        finally:
            eng.stop()

    def test_concurrent_requests_match_reference(self, gen_setup):
        """8 concurrent requests through 4 slots == sequential reference."""
        cfg, params, ref = gen_setup
        eng = make_gen_engine(cfg, params, make_container())
        prompts = [[i + 1, (2 * i) % 200 + 1, (7 * i) % 150] for i in range(8)]
        want = [ref(p, 5) for p in prompts]
        results = [None] * len(prompts)

        def worker(i):
            results[i] = eng.generate(prompts[i], max_new_tokens=5, timeout=120)

        try:
            threads = [threading.Thread(target=worker, args=(i,)) for i in range(len(prompts))]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            for i, r in enumerate(results):
                assert r is not None, f"request {i} did not complete"
                assert r["tokens"] == want[i], f"request {i} diverged"
        finally:
            eng.stop()

    def test_variable_prompt_lengths(self, gen_setup):
        cfg, params, ref = gen_setup
        eng = make_gen_engine(cfg, params, make_container())
        prompts = [[7], [1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13], [42, 17]]
        try:
            outs = [eng.generate(p, max_new_tokens=4, timeout=120) for p in prompts]
            for p, o in zip(prompts, outs):
                assert o["tokens"] == ref(p, 4)
        finally:
            eng.stop()

    def test_eos_stops_generation(self, gen_setup):
        cfg, params, ref = gen_setup
        # pick the greedy 3rd token as "eos" so generation stops there
        full = ref([11, 22, 33], 6)
        eos = full[2]
        eng = make_gen_engine(cfg, params, make_container(), eos_token_id=eos)
        try:
            out = eng.generate([11, 22, 33], max_new_tokens=6, timeout=60)
            assert out["finish_reason"] == "stop"
            assert out["tokens"] == full[:2]
        finally:
            eng.stop()

    def test_sampling_temperature(self, gen_setup):
        """temperature>0 samples (deterministic per engine seed), mixed
        greedy+sampled requests coexist in one batch."""
        cfg, params, ref = gen_setup
        eng = make_gen_engine(cfg, params, make_container(), seed=3)
        try:
            greedy = eng.generate([4, 4, 4], max_new_tokens=5, temperature=0.0, timeout=60)
            assert greedy["tokens"] == ref([4, 4, 4], 5)
            hot = eng.generate([4, 4, 4], max_new_tokens=5, temperature=5.0, timeout=60)
            assert len(hot["tokens"]) == 5
            assert all(0 <= t < cfg.vocab_size for t in hot["tokens"])
        finally:
            eng.stop()

    def test_streaming(self, gen_setup):
        cfg, params, ref = gen_setup
        eng = make_gen_engine(cfg, params, make_container())
        try:
            toks = list(eng.generate([9, 8, 7], max_new_tokens=4, stream=True, timeout=60))
            assert toks == ref([9, 8, 7], 4)
        finally:
            eng.stop()

    def test_prompt_too_long_rejected(self, gen_setup):
        cfg, params, _ = gen_setup
        eng = make_gen_engine(cfg, params, make_container())
        try:
            with pytest.raises(ValueError, match="max_len"):
                eng.generate(list(range(100)), max_new_tokens=2, timeout=60)
        finally:
            eng.stop()

    def test_stream_iterator_cancel_frees_slot(self, gen_setup):
        """Transports call stream.cancel() on client disconnect; the request
        must complete (as timeout) and the slot must come free without the
        engine decoding to max_new_tokens for a ghost client."""
        cfg, params, ref = gen_setup
        eng = make_gen_engine(cfg, params, make_container(), decode_chunk=1)
        try:
            it = eng.generate(list(range(1, 6)), max_new_tokens=400,
                              timeout=120, stream=True)
            first = next(it)
            assert isinstance(first, int)
            it.cancel()
            with pytest.raises(Exception):
                for _ in it:  # drains until the engine reports the timeout
                    pass
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline and any(s is not None for s in eng.slots):
                time.sleep(0.05)
            assert all(s is None for s in eng.slots), "cancel left a ghost slot"
        finally:
            eng.stop()

    def test_timeout_frees_slot(self, gen_setup):
        """A timed-out request raises AND its slot is reclaimed."""
        cfg, params, ref = gen_setup
        eng = make_gen_engine(cfg, params, make_container(), slots=2)
        try:
            with pytest.raises(RequestTimeout):
                eng.generate([1, 2], max_new_tokens=10_000_000 % 50, timeout=1e-9)
            # wait for the loop to notice and free the lane
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline and any(s is not None for s in eng.slots):
                time.sleep(0.05)
            assert all(s is None for s in eng.slots)
            # engine still serves
            out = eng.generate([5, 3, 9], max_new_tokens=3, timeout=60)
            assert out["tokens"] == ref([5, 3, 9], 3)
        finally:
            eng.stop()

    def test_more_requests_than_slots_all_complete(self, gen_setup):
        cfg, params, ref = gen_setup
        eng = make_gen_engine(cfg, params, make_container(), slots=2, max_prefill_batch=1)
        results = {}

        def worker(i):
            results[i] = eng.generate([i + 1], max_new_tokens=3, timeout=120)

        try:
            threads = [threading.Thread(target=worker, args=(i,)) for i in range(5)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            assert len(results) == 5
            for i in range(5):
                assert results[i]["tokens"] == ref([i + 1], 3)
        finally:
            eng.stop()

    def test_metrics_recorded(self, gen_setup):
        cfg, params, _ = gen_setup
        c = make_container()
        eng = make_gen_engine(cfg, params, c)
        try:
            eng.generate([1, 2, 3], max_new_tokens=4, timeout=60)
            text = c.metrics.expose_text()
            assert "app_tpu_step_seconds" in text
            assert "app_tpu_batch_occupancy" in text
            # prompt (3) + generated (4) tokens counted
            assert c.metrics.get("app_tpu_tokens_total").value() >= 7
            # compile happened at least twice (prefill + decode programs)
            assert c.metrics.get("app_tpu_compile_total").value() >= 2
        finally:
            eng.stop()

    def test_health_check(self, gen_setup):
        cfg, params, _ = gen_setup
        eng = make_gen_engine(cfg, params, make_container())
        try:
            eng.start()
            h = eng.health_check()
            assert h["status"] == "UP"
        finally:
            eng.stop()


class TestBatchEngine:
    def test_embed_batching_matches_single(self):
        from gofr_tpu.models import bert

        cfg = BertConfig.tiny()
        params = bert.init(cfg, jax.random.key(0))

        def apply(tokens, lengths):
            return bert.embed_pooled(cfg, params, tokens, lengths)

        eng = BatchEngine(apply, make_container(), max_batch=8, len_buckets=[8, 16])
        try:
            seqs = [list(range(1, 4)), list(range(5, 12)), [9]]
            outs = [eng.infer(s, timeout=60) for s in seqs]
            for s, o in zip(seqs, outs):
                want = bert.embed_pooled(
                    cfg, params,
                    jnp.asarray([s + [0] * (8 - len(s))], jnp.int32),
                    jnp.asarray([len(s)]),
                )
                np.testing.assert_allclose(np.asarray(o), np.asarray(want[0]), rtol=1e-4, atol=1e-5)
        finally:
            eng.stop()

    def test_concurrent_embeds_batched_together(self):
        from gofr_tpu.models import bert

        cfg = BertConfig.tiny()
        params = bert.init(cfg, jax.random.key(0))
        calls = []

        def apply(tokens, lengths):
            calls.append(int(tokens.shape[0]))
            return bert.embed_pooled(cfg, params, tokens, lengths)

        c = make_container()
        eng = BatchEngine(apply, c, max_batch=16, len_buckets=[8], max_wait_ms=200.0)
        results = [None] * 6

        def worker(i):
            results[i] = eng.infer([i + 1, i + 2], timeout=60)

        try:
            # warm up compile first so the batching window isn't dominated by it
            eng.infer([1, 2], timeout=60)
            threads = [threading.Thread(target=worker, args=(i,)) for i in range(6)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=60)
            assert all(r is not None for r in results)
        finally:
            eng.stop()

    def test_batch_engine_warmup_precompiles(self):
        """BatchEngine.warmup compiles every signature up front; the serving
        step then hits only the compile cache."""
        cfg = BertConfig.tiny()
        from gofr_tpu.models import bert

        params = bert.init(cfg, jax.random.key(0))
        container = make_container()
        traces = {"n": 0}

        @jax.jit
        def apply(tokens, lengths):
            traces["n"] += 1  # runs at TRACE time only: one per signature
            return bert.embed_pooled(cfg, params, tokens, lengths)

        eng = BatchEngine(apply, container, max_batch=4, len_buckets=[16, 32])
        try:
            n = eng.warmup([1, 2, 3])
            assert n == 2 * 3  # 2 len buckets x batch buckets {1,2,4}
            traces_after_warmup = traces["n"]
            assert traces_after_warmup == n
            out = eng.infer([5, 3, 9], timeout=120)
            assert np.asarray(out).ndim >= 1
            assert traces["n"] == traces_after_warmup, (
                "serving step traced a program warmup should have covered"
            )
        finally:
            eng.stop()

    def test_classify_images(self):
        from gofr_tpu.models import vit

        cfg = ViTConfig.tiny()
        params = vit.init(cfg, jax.random.key(0))

        def apply(images):
            return vit.forward(cfg, params, images)

        eng = BatchEngine(apply, make_container(), max_batch=4)
        try:
            img = np.random.RandomState(0).randn(32, 32, 3).astype(np.float32)
            out = eng.infer(img, timeout=60)
            want = vit.forward(cfg, params, jnp.asarray(img)[None])[0]
            np.testing.assert_allclose(np.asarray(out), np.asarray(want), rtol=1e-4, atol=1e-4)
        finally:
            eng.stop()

    def test_error_propagates_to_caller(self):
        def apply(tokens, lengths):
            raise RuntimeError("boom")

        eng = BatchEngine(apply, make_container(), max_batch=2)
        try:
            with pytest.raises(RuntimeError, match="boom"):
                eng.infer([1, 2, 3], timeout=60)
        finally:
            eng.stop()


class TestBuildEngine:
    def test_build_generate_engine_random_init(self):
        c = make_container()
        spec = ModelSpec("llama", LlamaConfig.tiny(), task="generate", dtype=jnp.float32)
        eng = build_engine(spec, c, slots=2, max_len=32)
        try:
            out = eng.generate([1, 2, 3], max_new_tokens=2, timeout=120)
            assert len(out["tokens"]) == 2
        finally:
            eng.stop()

    def test_build_embed_engine(self):
        c = make_container()
        spec = ModelSpec("bert", BertConfig.tiny(), task="embed", dtype=jnp.float32)
        eng = build_engine(spec, c)
        try:
            emb = eng.infer([4, 5, 6], timeout=120)
            assert emb.shape == (32,)
        finally:
            eng.stop()

    def test_weights_path_does_not_swallow_seed(self, monkeypatch):
        """ADVICE r5 regression: with ``spec.weights`` set (checkpoint/HF),
        a caller-supplied seed used to be popped for the random-init branch
        and silently dropped before reaching GenerateEngine — the engine's
        sampling RNG fell back to seed 0. The popped seed must be passed
        explicitly to ``GenerateEngine(seed=...)``."""
        from gofr_tpu.models import convert

        cfg = LlamaConfig.tiny()
        params = llama.init(cfg, jax.random.key(0))
        monkeypatch.setattr(convert, "llama_from_hf",
                            lambda path, dtype=None: (cfg, params),
                            raising=False)
        spec = ModelSpec("llama", task="generate", weights="hf-stub/tiny")
        eng = build_engine(spec, make_container(), seed=11, slots=2, max_len=32)
        try:
            assert (jax.random.key_data(eng._base_key)
                    == jax.random.key_data(jax.random.key(11))).all(), (
                "seed was dropped on the weights path before reaching the engine"
            )
        finally:
            eng.stop()

    def test_build_rejects_unknown_task(self):
        spec = ModelSpec("llama", LlamaConfig.tiny(), task="nonsense")
        with pytest.raises(ValueError, match="unknown task"):
            build_engine(spec, make_container())

    def test_container_integration(self):
        """serve_model → ctx-style container.generate round trip."""
        c = make_container()
        spec = ModelSpec("llama", LlamaConfig.tiny(), task="generate", dtype=jnp.float32)
        eng = build_engine(spec, c, slots=2, max_len=32)
        c.register_engine("lm", eng)
        try:
            out = c.generate("lm", [3, 1, 4], max_new_tokens=2, timeout=120)
            assert len(out["tokens"]) == 2
            health = c.health()
            assert "model:lm" in health["services"]
        finally:
            eng.stop()


class TestEngineSupervision:
    """SURVEY §5.3 / VERDICT r2 #4: a crashed device loop restarts with
    backoff instead of dying permanently (reference analog: the SQL driver's
    reconnect loop, sql.go:108-133)."""

    def test_engine_recovers_from_step_crash(self, gen_setup):
        cfg, params, ref = gen_setup
        eng = make_gen_engine(cfg, params, make_container())
        real = eng._decode_chunk
        boom = {"left": 1}

        def flaky(*a, **kw):
            if boom["left"] > 0:
                boom["left"] -= 1
                # simulate a fault AFTER buffer donation: the cache the
                # engine holds (arg 2: params, base_key, cache, ...) is
                # dead, recovery must rebuild it
                jax.tree.map(lambda x: x.delete(), a[2])
                raise RuntimeError("injected device fault")
            return real(*a, **kw)

        eng._decode_chunk = flaky
        try:
            # the in-flight request rides the crashed state and fails...
            with pytest.raises(Exception):
                eng.generate([5, 3, 9], max_new_tokens=6, timeout=60)
            # ...but the engine restarted: later requests succeed exactly
            out = eng.generate([5, 3, 9], max_new_tokens=6, timeout=60)
            assert out["tokens"] == ref([5, 3, 9], 6)
            restarts = eng.metrics.get("app_tpu_engine_restarts")
            assert restarts is not None and sum(restarts._values.values()) >= 1
            assert eng.health_check()["status"] == "UP"
            assert eng.health_check()["details"]["restarts"] >= 1
        finally:
            eng.stop()

    def test_engine_gives_up_after_max_restarts(self, gen_setup):
        cfg, params, _ = gen_setup
        eng = make_gen_engine(cfg, params, make_container(), max_restarts=1)

        def always_boom(*a, **kw):
            raise RuntimeError("permanent device fault")

        eng._decode_chunk = always_boom
        try:
            # crash #1 consumes the single restart; crash #2 exhausts the
            # budget and the engine goes DOWN permanently
            with pytest.raises(Exception):
                eng.generate([5, 3, 9], max_new_tokens=4, timeout=60)
            with pytest.raises(Exception):
                eng.generate([1, 2], max_new_tokens=2, timeout=60)
            deadline = time.monotonic() + 10
            while eng.health_check()["status"] != "DOWN" and time.monotonic() < deadline:
                time.sleep(0.05)
            assert eng.health_check()["status"] == "DOWN"
            with pytest.raises(Exception):
                eng.generate([7, 8], max_new_tokens=2, timeout=10)
        finally:
            eng.stop()


class TestSlotChunkedPrefill:
    """Chunked prefill on the SLOT layout: families whose prefill accepts
    offsets (SLOT_CHUNKED_PREFILL) stream long prompts in chunks without the
    paged cache."""

    def test_long_prompt_matches_reference_slot_layout(self, gen_setup):
        cfg, params, ref = gen_setup
        eng = make_gen_engine(cfg, params, make_container(), prefill_buckets=[8])
        assert eng.kv_layout == "slot" and eng._chunked_ok
        long_prompt = [(7 * i) % 190 + 1 for i in range(21)]
        short = [[i + 1, (2 * i) % 99 + 1] for i in range(2)]
        want_long = ref(long_prompt, 6)
        want_short = [ref(p, 4) for p in short]
        results = {"long": None, "short": [None, None]}

        def run_long():
            results["long"] = eng.generate(long_prompt, max_new_tokens=6, timeout=300)

        def run_short(i):
            results["short"][i] = eng.generate(short[i], max_new_tokens=4, timeout=300)

        try:
            threads = [threading.Thread(target=run_long)] + [
                threading.Thread(target=run_short, args=(i,)) for i in range(2)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=300)
            assert results["long"] is not None
            assert results["long"]["tokens"] == want_long, "slot chunked prefill diverged"
            assert [r["tokens"] for r in results["short"]] == want_short
        finally:
            eng.stop()

    def test_gpt2_long_prompt_slot_chunked(self):
        from gofr_tpu.models import GPT2Config, gpt2

        cfg = GPT2Config.tiny()
        params = gpt2.init(cfg, jax.random.key(5))

        def ref(prompt, n):
            seq = list(prompt)
            for _ in range(n):
                logits = gpt2.forward(cfg, params, jnp.asarray([seq], jnp.int32))
                seq.append(int(jnp.argmax(logits[0, -1])))
            return seq[len(prompt):]

        eng = GenerateEngine(gpt2, cfg, params, make_container(), slots=2,
                             max_len=64, max_prefill_batch=2, prefill_buckets=[8])
        long_prompt = [(3 * i) % 200 + 1 for i in range(19)]
        try:
            out = eng.generate(long_prompt, max_new_tokens=5, timeout=300)
            assert out["tokens"] == ref(long_prompt, 5), "gpt2 chunked diverged"
        finally:
            eng.stop()


class TestPagedGenerateEngine:
    """GenerateEngine on the paged KV cache (ops.paged): identical results
    to the sequential reference, page accounting, preemption-by-recompute."""

    def _engine(self, cfg, params, **kw):
        kw.setdefault("slots", 4)
        kw.setdefault("max_len", 64)
        kw.setdefault("max_prefill_batch", 2)
        kw.setdefault("kv_layout", "paged")
        kw.setdefault("page_size", 8)
        return GenerateEngine(llama, cfg, params, new_mock_container(), **kw)

    def test_single_request_matches_reference(self, gen_setup):
        cfg, params, ref = gen_setup
        eng = self._engine(cfg, params)
        try:
            out = eng.generate([5, 3, 9], max_new_tokens=6, timeout=60)
            assert out["finish_reason"] == "length"
            assert out["tokens"] == ref([5, 3, 9], 6)
        finally:
            eng.stop()

    def test_concurrent_requests_match_reference(self, gen_setup):
        cfg, params, ref = gen_setup
        eng = self._engine(cfg, params)
        prompts = [[i + 1, (2 * i) % 200 + 1, (7 * i) % 150] for i in range(8)]
        want = [ref(p, 5) for p in prompts]
        results = [None] * len(prompts)

        def worker(i):
            results[i] = eng.generate(prompts[i], max_new_tokens=5, timeout=120)

        try:
            threads = [threading.Thread(target=worker, args=(i,)) for i in range(len(prompts))]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            for i, r in enumerate(results):
                assert r is not None, f"request {i} did not complete"
                assert r["tokens"] == want[i], f"request {i} diverged"
        finally:
            eng.stop()

    def test_pages_released_on_completion(self, gen_setup):
        cfg, params, _ = gen_setup
        eng = self._engine(cfg, params)
        try:
            eng.generate([5, 3, 9], max_new_tokens=4, timeout=60)
            assert sorted(eng._free_pages) == list(range(eng.total_pages))
            assert (eng._table == eng.total_pages).all()
        finally:
            eng.stop()

    def test_preemption_under_pool_pressure(self, gen_setup):
        """A pool too small for every concurrent request forces LIFO
        preemption + recompute; greedy results must still be exact."""
        cfg, params, ref = gen_setup
        # pages_per_slot = ceil((64+8)/8) = 9; four 23-token sequences need
        # 3 pages each = 12 > 10 -> guaranteed preemption traffic
        eng = self._engine(cfg, params, total_pages=10)
        prompts = [[i + 1, (3 * i) % 200 + 1, (5 * i) % 150] for i in range(4)]
        want = [ref(p, 20) for p in prompts]
        results = [None] * len(prompts)

        def worker(i):
            results[i] = eng.generate(prompts[i], max_new_tokens=20, timeout=300)

        try:
            threads = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=300)
            for i, r in enumerate(results):
                assert r is not None, f"request {i} did not complete"
                assert r["tokens"] == want[i], f"request {i} diverged after preemption"
            preempts = eng.metrics.get("app_tpu_preemptions")
            assert preempts is not None and sum(preempts._values.values()) >= 1, (
                "pool pressure never forced a preemption — test premise broken"
            )
            assert_paged_pool_consistent(eng, slots_empty=True)
        finally:
            eng.stop()

    def test_pool_smaller_than_one_request_rejected(self, gen_setup):
        cfg, params, _ = gen_setup
        with pytest.raises(ValueError, match="total_pages"):
            self._engine(cfg, params, total_pages=4)

    def test_ensure_pages_rolls_back_partial_allocation(self, gen_setup):
        """ADVICE r2 (high): a failed _ensure_pages must not leave pages on
        a slot that stays unoccupied — they'd be invisible to preemption and
        permanently strand pool capacity."""
        cfg, params, _ = gen_setup
        eng = self._engine(cfg, params, total_pages=9)  # pages_per_slot = 9
        try:
            assert eng._ensure_pages(0, 7 * eng.page_size - 1)  # 7 of 9 pages
            free_before = sorted(eng._free_pages)
            assert not eng._ensure_pages(1, 3 * eng.page_size - 1)  # needs 3, 2 left
            assert sorted(eng._free_pages) == free_before, "partial alloc leaked"
            assert eng._slot_pages[1] == []
            assert (eng._table[1] == eng.total_pages).all()
            # the slot that legitimately owns pages keeps them
            assert len(eng._slot_pages[0]) == 7
        finally:
            eng.stop()

    def test_preempted_regrown_prompt_exceeds_custom_bucket(self, gen_setup):
        """ADVICE r2 (medium): preemption folds generated tokens into the
        prompt; with a custom bucket ladder below max_len the regrown prompt
        must still be admittable, not spuriously expired."""
        cfg, params, ref = gen_setup
        eng = self._engine(cfg, params, total_pages=10, prefill_buckets=[4])
        prompts = [[i + 1, (3 * i) % 200 + 1, (5 * i) % 150] for i in range(4)]
        want = [ref(p, 20) for p in prompts]
        results = [None] * len(prompts)

        def worker(i):
            results[i] = eng.generate(prompts[i], max_new_tokens=20, timeout=300)

        try:
            threads = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=300)
            for i, r in enumerate(results):
                assert r is not None, f"request {i} did not complete"
                assert r["tokens"] == want[i], f"request {i} diverged after preemption"
            preempts = eng.metrics.get("app_tpu_preemptions")
            assert preempts is not None and sum(preempts._values.values()) >= 1
        finally:
            eng.stop()

    def test_chunked_prefill_long_prompt_matches_reference(self, gen_setup):
        """VERDICT r2 #3: a prompt longer than the largest prefill bucket is
        streamed into the cache in chunks and must decode identically to the
        dense reference, while short requests admitted alongside it still
        complete (decode interleaves with the chunks)."""
        cfg, params, ref = gen_setup
        eng = self._engine(cfg, params, prefill_buckets=[8])
        long_prompt = [(7 * i) % 190 + 1 for i in range(21)]  # 21 > bucket 8
        short_prompts = [[i + 1, (2 * i) % 99 + 1] for i in range(3)]
        want_long = ref(long_prompt, 6)
        want_short = [ref(p, 4) for p in short_prompts]
        results = {"long": None, "short": [None] * 3}

        def run_long():
            results["long"] = eng.generate(long_prompt, max_new_tokens=6, timeout=300)

        def run_short(i):
            results["short"][i] = eng.generate(short_prompts[i], max_new_tokens=4, timeout=300)

        try:
            threads = [threading.Thread(target=run_long)] + [
                threading.Thread(target=run_short, args=(i,)) for i in range(3)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=300)
            assert results["long"] is not None, "long prompt never completed"
            assert results["long"]["tokens"] == want_long, "chunked prefill diverged"
            assert [r["tokens"] for r in results["short"]] == want_short
            steps = eng.metrics.get("app_tpu_step_seconds")
            kinds = {k for k in steps._totals} if steps is not None else set()
            assert any("prefill_chunk" in str(k) for k in kinds), (
                "long prompt did not take the chunked path — test premise broken"
            )
        finally:
            eng.stop()

    def test_chunked_prefill_under_pool_pressure(self, gen_setup):
        """Chunked admission + preemption compose: a long prompt re-entering
        after preemption (regrown past the bucket ladder) still finishes
        with exact tokens."""
        cfg, params, ref = gen_setup
        eng = self._engine(cfg, params, prefill_buckets=[8], total_pages=12)
        long_prompt = [(3 * i) % 150 + 2 for i in range(17)]
        want = ref(long_prompt, 8)
        others = [[i + 1, i + 2] for i in range(3)]
        want_others = [ref(p, 12) for p in others]
        res = [None] * 4

        def w(i):
            if i == 0:
                res[0] = eng.generate(long_prompt, max_new_tokens=8, timeout=300)
            else:
                res[i] = eng.generate(others[i - 1], max_new_tokens=12, timeout=300)

        try:
            threads = [threading.Thread(target=w, args=(i,)) for i in range(4)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=300)
            assert all(r is not None for r in res)
            assert res[0]["tokens"] == want
            assert [r["tokens"] for r in res[1:]] == want_others
            assert_paged_pool_consistent(eng, slots_empty=True)
        finally:
            eng.stop()

    def test_more_slots_at_equal_hbm(self, gen_setup):
        """The headline arithmetic: at the slot cache's HBM budget, the paged
        engine serves MORE concurrent slots because short requests only hold
        the pages they use."""
        cfg, params, ref = gen_setup
        # slot cache for 4 slots x 72 positions = 288 position-rows of HBM;
        # paged pool of 36 8-token pages = the same 288 — but carries 8 slots
        eng = self._engine(cfg, params, slots=8, total_pages=36)
        prompts = [[i + 2, (4 * i) % 99 + 1] for i in range(8)]
        want = [ref(p, 4) for p in prompts]
        results = [None] * len(prompts)

        def worker(i):
            results[i] = eng.generate(prompts[i], max_new_tokens=4, timeout=120)

        try:
            threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            assert all(r is not None for r in results)
            assert [r["tokens"] for r in results] == want
        finally:
            eng.stop()


class TestPipelinedDecode:
    """Dispatch-pipelined decode (decode_pipeline=2, the default): chunk t+1
    is dispatched before chunk t is read back, with the input token carried
    on device. The load-bearing property: tokens are IDENTICAL to the fully
    synchronous depth-1 path (greedy), on both KV layouts, including under
    EOS, cancellation, and paged preemption pressure — the rest of the suite
    already runs depth 2 everywhere since it is the default."""

    @pytest.mark.parametrize("kv_layout", ["slot", "paged"])
    def test_depth1_and_depth2_match_reference(self, gen_setup, kv_layout):
        cfg, params, ref = gen_setup
        prompts = [[i + 2, (3 * i) % 190 + 1, (11 * i) % 140 + 1] for i in range(6)]
        want = [ref(p, 7) for p in prompts]
        for depth in (1, 2):
            kw = dict(slots=3, max_len=64, max_prefill_batch=2,
                      decode_pipeline=depth, kv_layout=kv_layout)
            if kv_layout == "paged":
                kw["page_size"] = 8
            eng = GenerateEngine(llama, cfg, params, new_mock_container(), **kw)
            results = [None] * len(prompts)

            def worker(i):
                results[i] = eng.generate(prompts[i], max_new_tokens=7, timeout=300)

            try:
                threads = [threading.Thread(target=worker, args=(i,)) for i in range(len(prompts))]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join(timeout=300)
                for i, r in enumerate(results):
                    assert r is not None, f"depth={depth} request {i} did not complete"
                    assert r["tokens"] == want[i], f"depth={depth} request {i} diverged"
            finally:
                eng.stop()

    def test_inflight_bookkeeping_drains(self, gen_setup):
        """After traffic fully drains, no slot is occupied and no dispatched
        chunk is left unprocessed — the speculative counters returned to
        rest state."""
        cfg, params, _ = gen_setup
        eng = make_gen_engine(cfg, params, make_container(), decode_pipeline=2)
        try:
            outs = [eng.generate([3, 1, 4], max_new_tokens=9, timeout=120) for _ in range(3)]
            assert all(len(o["tokens"]) == 9 for o in outs)
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and eng._dq:
                time.sleep(0.05)
            assert not eng._dq, "dispatched chunk never processed"
            assert all(s is None for s in eng.slots)
        finally:
            eng.stop()

    def test_pipelined_eos_discards_overshoot(self, gen_setup):
        """A lane that hits EOS while its successor chunk is already in
        flight must not leak the successor's tokens into the result."""
        cfg, params, ref = gen_setup
        want = ref([5, 3, 9], 24)
        # pick the token the reference emits mid-way and use it as EOS
        eos = want[10]
        eng = make_gen_engine(cfg, params, make_container(),
                              decode_pipeline=2, decode_chunk=4)
        try:
            out = eng.generate([5, 3, 9], max_new_tokens=24, timeout=120,
                               eos_token_id=eos)
            assert out["finish_reason"] == "stop"
            assert out["tokens"] == want[:10]
        finally:
            eng.stop()


class TestAsyncAwaitPath:
    """Request.add_done_callback + ctx.agenerate: the asyncio-native await
    path transports use (no thread parked per in-flight request)."""

    def test_done_callback_before_and_after_completion(self):
        calls = []
        req = Request([1], {}, timeout=None)
        req.add_done_callback(lambda r: calls.append(("pre", r.outcome())))
        req.complete(result={"ok": 1})
        assert calls == [("pre", ({"ok": 1}, None))]
        # already-done: fires immediately
        req.add_done_callback(lambda r: calls.append(("post", r.outcome())))
        assert calls[-1] == ("post", ({"ok": 1}, None))
        # idempotent complete must not re-fire callbacks
        req.complete(result={"ok": 2})
        assert len(calls) == 2

    def test_outcome_before_completion_raises(self):
        req = Request([1], {}, timeout=None)
        with pytest.raises(RuntimeError, match="not complete"):
            req.outcome()

    def test_callback_exception_does_not_break_completion(self, capsys):
        req = Request([1], {}, timeout=None)
        req.add_done_callback(lambda r: 1 / 0)
        seen = []
        req.add_done_callback(lambda r: seen.append(True))
        req.complete(result="x")
        assert seen == [True]  # later callbacks still ran
        assert "ZeroDivisionError" in capsys.readouterr().err

    def test_agenerate_roundtrip_and_error(self, gen_setup):
        import asyncio

        from gofr_tpu.context import Context

        cfg, params, ref = gen_setup
        container = make_container()
        eng = make_gen_engine(cfg, params, container)
        container.register_engine("lm", eng)
        ctx = Context(None, container)
        try:
            out = asyncio.run(ctx.agenerate("lm", [5, 3, 9], max_new_tokens=6,
                                            timeout=120))
            assert out["tokens"] == ref([5, 3, 9], 6)
            # errors propagate through the future
            with pytest.raises(ValueError, match="max_len"):
                asyncio.run(ctx.agenerate("lm", list(range(100)),
                                          max_new_tokens=2, timeout=60))
        finally:
            eng.stop()

    def test_agenerate_timeout_backstop_on_wedged_engine(self, gen_setup):
        """A wedged device thread never calls complete(); the async client
        must still time out instead of hanging the handler forever."""
        import asyncio

        from gofr_tpu.context import Context
        from gofr_tpu.http.errors import RequestTimeout

        cfg, params, _ = gen_setup
        container = make_container()
        eng = make_gen_engine(cfg, params, container)

        def wedge(*a, **kw):
            time.sleep(60)

        eng._prefill_sample = wedge
        container.register_engine("lm", eng)
        ctx = Context(None, container)
        try:
            t0 = time.monotonic()
            with pytest.raises(RequestTimeout):
                asyncio.run(ctx.agenerate("lm", [5, 3], max_new_tokens=2,
                                          timeout=1.5))
            assert time.monotonic() - t0 < 10
        finally:
            eng._poisoned = True  # don't wait for the wedge in stop()
            eng._stop.set()


def test_spec_engine_recovers_from_crash(gen_setup):
    """Crash-restart with SPECULATION on: the recovery path must rebuild
    the (kv, hist) tuple cache and reset the device-resident spec carry —
    a stale carry or half-rebuilt pytree would poison every later round.
    Post-restart greedy output must be exact."""
    cfg, params, ref = gen_setup
    eng = make_gen_engine(cfg, params, make_container(), spec_tokens=2,
                          decode_chunk=2)
    real = eng._spec_chunk_fn
    boom = {"left": 1}

    def flaky(*a, **kw):
        if boom["left"] > 0:
            boom["left"] -= 1
            # fault AFTER donation of the tuple cache (arg 2 of
            # (params, base_key, cache, steps, packed, carry))
            jax.tree.map(lambda x: x.delete(), a[2])
            raise RuntimeError("injected spec fault")
        return real(*a, **kw)

    eng._spec_chunk_fn = flaky
    try:
        with pytest.raises(Exception):
            eng.generate([5, 3, 9], max_new_tokens=6, timeout=60)
        out = eng.generate([5, 3, 9], max_new_tokens=6, timeout=120)
        assert out["tokens"] == ref([5, 3, 9], 6)
        restarts = eng.metrics.get("app_tpu_engine_restarts")
        assert restarts is not None and sum(restarts._values.values()) >= 1
        assert eng._spec_carry is not None or True  # carry rebuilt lazily
        # a second, sampled request also completes on the restarted engine
        out2 = eng.generate([5, 3, 9], max_new_tokens=5, temperature=0.9,
                            timeout=120)
        assert len(out2["tokens"]) == 5
    finally:
        eng.stop()
