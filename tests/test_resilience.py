"""End-to-end request-lifetime plane (ISSUE 10; docs/resilience.md).

Quick tier: deadline wire-form parsing and the per-hop shrink, the
Request future's constructed-deadline bound, Envoy-style retry-budget
math on a fake clock, Retry full-jitter/Retry-After/deadline interplay
against a stub transport, router-side deadline shed + budget-gated
spill, and hedged dispatch (first good responder wins, the loser is
closed so its replica cancels cooperatively). Engine tier (unmarked,
tier-1): cancel-mid-decode reclaims the slot AND every KV page
(testutil.assert_page_refs_consistent), and already-expired work is
shed pre-slot with 504/deadline_exceeded.
"""

import random
import time

import pytest

from gofr_tpu import deadline
from gofr_tpu.container import new_mock_container
from gofr_tpu.fleet import chaos
from gofr_tpu.http.errors import DeadlineExceeded, RequestTimeout, ServiceUnavailable
from gofr_tpu.http.request import HTTPRequest
from gofr_tpu.router import Router, RouterPolicy
from gofr_tpu.service import Retry, ServiceError
from gofr_tpu.service.budget import RetryBudget
from gofr_tpu.tpu import prefix
from gofr_tpu.tpu.engine import Request


class _Clock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t


# -- deadline wire form ---------------------------------------------------------


@pytest.mark.quick
class TestDeadlineWire:
    def test_garbage_degrades_to_no_deadline(self):
        """A malformed deadline must never 500 the request."""
        for junk in (None, "", "soon", "12,5", object()):
            assert deadline.parse_deadline_ms(junk) is None

    def test_roundtrip_preserves_remaining(self):
        at = time.monotonic() + 5.0
        back = deadline.parse_deadline_ms(deadline.header_value(at))
        assert abs(back - at) < 0.1

    def test_hop_margin_shrinks_the_budget(self):
        """The router's re-stamp: each hop hands down LESS time than it
        was given, so the replica finishes early enough to relay."""
        at = time.monotonic() + 5.0
        back = deadline.parse_deadline_ms(deadline.header_value(at, margin_s=1.0))
        assert abs((at - back) - 1.0) < 0.1

    def test_context_slot_helpers(self):
        ctx: dict = {}
        assert deadline.deadline_of(ctx) is None
        assert deadline.remaining(ctx) is None
        deadline.set_deadline(ctx, None)
        assert deadline.CTX_KEY not in ctx  # None never pollutes the ctx
        deadline.set_deadline(ctx, 100.0)
        assert deadline.deadline_of(ctx) == 100.0
        assert deadline.remaining(ctx, now=97.5) == 2.5


# -- the Request future honors its constructed deadline -------------------------


@pytest.mark.quick
class TestRequestFuture:
    def test_result_never_blocks_past_constructed_deadline(self):
        """The double-timeout fix: ``result(30)`` on a request built with
        ``timeout=0.15`` must raise at ~0.15s, not block for 30 — the
        engine-side deadline is the binding one."""
        req = Request([1], {}, timeout=0.15)
        t0 = time.monotonic()
        with pytest.raises(RequestTimeout):
            req.result(timeout=30.0)
        assert time.monotonic() - t0 < 5.0
        assert req.cancelled and req.cancel_reason == "timeout"

    def test_cancel_reason_first_caller_wins(self):
        req = Request([1], {}, timeout=None)
        req.cancel("client_disconnect")
        req.cancel("timeout")  # late caller must not relabel the cause
        assert req.cancel_reason == "client_disconnect"

    def test_explicit_wait_still_binds_when_tighter(self):
        req = Request([1], {}, timeout=60.0)
        t0 = time.monotonic()
        with pytest.raises(RequestTimeout):
            req.result(timeout=0.05)
        assert time.monotonic() - t0 < 5.0


# -- Envoy-style retry budget ---------------------------------------------------


@pytest.mark.quick
class TestRetryBudget:
    def test_min_retries_floor_on_idle_client(self):
        clk = _Clock()
        b = RetryBudget(fraction=0.2, min_retries=3, window_s=10.0, clock=clk)
        assert b.allowed() == 3  # near-idle clients can still retry at all
        assert [b.try_spend() for _ in range(4)] == [True, True, True, False]

    def test_fraction_caps_the_aggregate(self):
        clk = _Clock()
        b = RetryBudget(fraction=0.2, min_retries=3, window_s=10.0, clock=clk)
        for _ in range(100):
            b.note_request()
        assert b.allowed() == 20
        granted = sum(1 for _ in range(100) if b.try_spend())
        assert granted == 20  # amplification hard-capped at the fraction

    def test_window_slide_refills(self):
        clk = _Clock()
        b = RetryBudget(fraction=0.5, min_retries=0, window_s=10.0, clock=clk)
        b.note_request()
        b.note_request()
        assert b.try_spend() and not b.try_spend()
        clk.t = 11.0  # the old retries (and originals) age out
        b.note_request()
        b.note_request()
        assert b.try_spend()

    def test_metrics_and_snapshot(self):
        c = new_mock_container()
        clk = _Clock()
        b = RetryBudget(fraction=0.0, min_retries=1, window_s=10.0,
                        metrics=c.metrics, clock=clk)
        b.note_request()
        assert b.try_spend() and not b.try_spend()
        assert c.metrics.get("app_retry_budget_spent_total").value() == 1
        assert c.metrics.get("app_retry_budget_exhausted_total").value() == 1
        snap = b.snapshot()
        assert snap["window_requests"] == 1 and snap["window_retries"] == 1


# -- Retry middleware: jitter, Retry-After, deadline, budget --------------------


class _Resp:
    def __init__(self, status, headers=None):
        self.status_code = status
        self.headers = headers or {}
        self.closed = False

    def close(self):
        self.closed = True


class _StubInner:
    """Scripted transport: each entry is a response or an exception."""

    def __init__(self, script):
        self.script = list(script)
        self.calls = 0

    def request(self, method, path, **kw):
        self.calls += 1
        item = self.script.pop(0)
        if isinstance(item, Exception):
            raise item
        return item


@pytest.mark.quick
class TestRetryMiddleware:
    def _sleeps(self, monkeypatch):
        rec: list[float] = []
        monkeypatch.setattr(time, "sleep", rec.append)
        return rec

    def test_full_jitter_bounded_by_exponential_envelope(self, monkeypatch):
        sleeps = self._sleeps(monkeypatch)
        inner = _StubInner([ServiceError("x"), ServiceError("x"), _Resp(200)])
        client = Retry(max_retries=3, backoff=0.05,
                       rng=random.Random(7)).add_option(inner)
        assert client.request("GET", "/x").status_code == 200
        assert inner.calls == 3
        # uniform(0, backoff * 2**attempt): jittered, never the full wave
        for i, s in enumerate(sleeps):
            assert 0.0 <= s <= 0.05 * (2 ** i)

    def test_retry_after_overrides_backoff(self, monkeypatch):
        sleeps = self._sleeps(monkeypatch)
        inner = _StubInner([_Resp(503, {"Retry-After": "0.07"}), _Resp(200)])
        client = Retry(max_retries=2, backoff=5.0).add_option(inner)
        assert client.request("GET", "/x").status_code == 200
        assert sleeps == [0.07]  # the server's horizon, not our exponent

    def test_429_with_hint_retries_bare_429_returns(self, monkeypatch):
        self._sleeps(monkeypatch)
        hinted = _StubInner([_Resp(429, {"retry-after": "0.01"}), _Resp(200)])
        client = Retry(max_retries=2, backoff=0.01).add_option(hinted)
        assert client.request("GET", "/x").status_code == 200
        assert hinted.calls == 2
        bare = _StubInner([_Resp(429)])
        client = Retry(max_retries=2, backoff=0.01).add_option(bare)
        # no hint: the caller's rate budget, not ours — returned verbatim
        assert client.request("GET", "/x").status_code == 429
        assert bare.calls == 1

    def test_retry_after_capped_at_remaining_deadline(self, monkeypatch):
        sleeps = self._sleeps(monkeypatch)
        hdrs = {deadline.DEADLINE_HEADER:
                deadline.header_value(time.monotonic() + 0.05)}
        inner = _StubInner([_Resp(503, {"Retry-After": "9"}), _Resp(200)])
        client = Retry(max_retries=2, backoff=0.01).add_option(inner)
        client.request("GET", "/x", headers=hdrs)
        assert len(sleeps) == 1 and sleeps[0] <= 0.06

    def test_expired_deadline_stops_retrying(self, monkeypatch):
        self._sleeps(monkeypatch)
        hdrs = {deadline.DEADLINE_HEADER:
                deadline.header_value(time.monotonic() - 1.0)}
        inner = _StubInner([ServiceError("x"), _Resp(200)])
        client = Retry(max_retries=3, backoff=0.01).add_option(inner)
        with pytest.raises(ServiceError):
            client.request("GET", "/x", headers=hdrs)
        assert inner.calls == 1  # a retry nobody can wait for never fires

    def test_budget_gates_retries_and_counts_originals(self, monkeypatch):
        self._sleeps(monkeypatch)
        clk = _Clock()
        budget = RetryBudget(fraction=0.0, min_retries=1, window_s=10.0,
                             clock=clk)
        inner = _StubInner([ServiceError("x")] * 4)
        client = Retry(max_retries=3, backoff=0.01,
                       budget=budget).add_option(inner)
        with pytest.raises(ServiceError):
            client.request("GET", "/x")
        assert inner.calls == 2  # 1 original + the single budgeted retry
        assert budget.snapshot()["window_requests"] == 1


# -- router: hop shrink, deadline shed, budget-gated spill, hedging -------------


class _Ctx:
    span = None

    def __init__(self, req):
        self.request = req

    def header(self, name):
        return (self.request.headers or {}).get(name.lower())


def _http_req(headers=None, body=b"{}"):
    return HTTPRequest(method="POST", path="/generate", query_string="",
                       headers=headers or {}, body=body, path_params={},
                       remote="10.0.0.9")


class _ProxyResp:
    def __init__(self, status, headers=None, body=b"{}", delay=0.0):
        self.status_code = status
        self.headers = {"content-type": "application/json", **(headers or {})}
        self._body = body
        self.delay = delay
        self.closed = False

    def read(self):
        return self._body

    def close(self):
        self.closed = True


class _ProxyClient:
    def __init__(self, script):
        self.script = list(script)
        self.calls = 0

    def request(self, method, path, **kw):
        self.calls += 1
        item = self.script.pop(0)
        if isinstance(item, Exception):
            raise item
        if item.delay:
            time.sleep(item.delay)
        return item


@pytest.mark.quick
class TestRouterLifetimePlane:
    def _router(self, **kw):
        kw.setdefault("page_size", 4)
        kw.setdefault("jitter_s", 0.0)
        kw.setdefault("replicas", {"a": "http://a", "b": "http://b"})
        return Router(new_mock_container(), policy=RouterPolicy(**kw))

    def _key_homed(self, router, name):
        for i in range(512):
            key = prefix.chain_key(0, bytes([i % 251, i // 251]))
            if router.registry.full.lookup(key, 1)[0] == name:
                return key
        raise AssertionError(f"no key homed on {name}")

    def _stub_clients(self, router, scripts):
        clients = {name: _ProxyClient(script) for name, script in scripts.items()}
        router._client = lambda rep: clients[rep.name]
        return clients

    def test_hop_restamp_shrinks_the_header(self):
        router = self._router(hop_margin_ms=250.0)
        at = time.monotonic() + 5.0
        req = _http_req(headers={
            deadline.DEADLINE_HEADER.lower(): deadline.header_value(at)})
        out = router._forward_headers(req, None, deadline_at=at)
        keys = [k for k in out
                if k.lower() == deadline.DEADLINE_HEADER.lower()]
        assert keys == [deadline.DEADLINE_HEADER]  # replaced, not duplicated
        back = deadline.parse_deadline_ms(out[deadline.DEADLINE_HEADER])
        assert abs((at - back) - 0.25) < 0.1  # shrunk by the hop margin

    def test_expired_deadline_shed_at_router(self):
        router = self._router()
        req = _http_req()
        deadline.set_deadline(req.context(), time.monotonic() - 1.0)
        with pytest.raises(DeadlineExceeded):
            router.handle(_Ctx(req))
        m = router.container.metrics
        assert m.get("app_request_deadline_exceeded_total").value(
            where="router") == 1
        assert router.debug_view()["stats"]["shed"] == 1

    def test_budget_exhausted_spill_passes_replica_answer_through(self):
        """With the budget spent, the home's own 429/503 (Retry-After
        intact) goes back unspilled — no budget, no second attempt."""
        router = self._router()
        router.budget = RetryBudget(fraction=0.0, min_retries=0)
        key = self._key_homed(router, "a")
        clients = self._stub_clients(router, {
            "a": [_ProxyResp(503, {"retry-after": "3"})], "b": []})
        req = _http_req(body=b'{"prompt": "k%d"}' % key)
        router.request_key = lambda r: key
        out = router.handle(_Ctx(req))
        assert out.status_code == 503
        assert out.headers["retry-after"] == "3"
        assert clients["b"].calls == 0

    def test_transport_storm_without_budget_sheds_retry_budget(self):
        router = self._router()
        router.budget = RetryBudget(fraction=0.0, min_retries=0)
        key = self._key_homed(router, "a")
        clients = self._stub_clients(router, {
            "a": [ServiceError("conn refused")], "b": []})
        router.request_key = lambda r: key
        with pytest.raises(ServiceUnavailable):
            router.handle(_Ctx(_http_req()))
        assert clients["b"].calls == 0  # the spill was denied, not attempted
        m = router.container.metrics
        assert m.get("app_router_shed_total").value(
            qos_class="default", reason="retry_budget") == 1

    def test_budgeted_spill_still_works(self):
        router = self._router()
        key = self._key_homed(router, "a")
        clients = self._stub_clients(router, {
            "a": [ServiceError("conn refused")], "b": [_ProxyResp(200)]})
        router.request_key = lambda r: key
        out = router.handle(_Ctx(_http_req()))
        assert out.status_code == 200 and clients["b"].calls == 1

    def test_hedge_fires_after_silence_and_closes_the_loser(self):
        """Primary silent past the hedge window: the successor answers
        first and wins; the primary's late response is closed (aborting
        its upstream transfer = cooperative cancel at that replica)."""
        router = self._router(hedge_after_ms=20.0)
        key = self._key_homed(router, "a")
        slow = _ProxyResp(200, body=b"slow", delay=0.4)
        clients = self._stub_clients(router, {
            "a": [slow], "b": [_ProxyResp(200, body=b"fast")]})
        router.request_key = lambda r: key
        out = router.handle(_Ctx(_http_req()))
        assert out.body == b"fast"
        assert clients["a"].calls == 1 and clients["b"].calls == 1
        m = router.container.metrics
        assert m.get("app_router_hedged_total").value(winner="hedge") == 1
        t_end = time.monotonic() + 5.0
        while not slow.closed and time.monotonic() < t_end:
            time.sleep(0.01)
        assert slow.closed, "the losing response must be closed (cancelled)"

    def test_hedge_primary_fast_no_hedge_fired(self):
        router = self._router(hedge_after_ms=50.0)
        key = self._key_homed(router, "a")
        clients = self._stub_clients(router, {
            "a": [_ProxyResp(200, body=b"home")], "b": []})
        router.request_key = lambda r: key
        out = router.handle(_Ctx(_http_req()))
        assert out.body == b"home" and clients["b"].calls == 0
        m = router.container.metrics
        assert m.get("app_router_hedged_total").value() == 0

    def test_hedge_denied_by_budget_waits_for_primary(self):
        router = self._router(hedge_after_ms=10.0)
        router.budget = RetryBudget(fraction=0.0, min_retries=0)
        key = self._key_homed(router, "a")
        clients = self._stub_clients(router, {
            "a": [_ProxyResp(200, body=b"home", delay=0.15)], "b": []})
        router.request_key = lambda r: key
        out = router.handle(_Ctx(_http_req()))
        assert out.body == b"home"
        assert clients["b"].calls == 0  # a hedge is a retry: budget-gated


# -- chaos points + gRPC deadline ingress ---------------------------------------


@pytest.mark.quick
def test_client_disconnect_chaos_point_schedule():
    """The storm drill's deterministic hangup schedule: every 2nd fire."""
    with chaos.override("client.disconnect:drop,every=2"):
        assert [chaos.fire("client.disconnect") for _ in range(4)] == \
            [False, True, False, True]
        assert chaos.fire("replica.slow") is False  # unarmed point is free


@pytest.mark.quick
def test_grpc_deadline_joins_the_request_context():
    """The gRPC edge reads the client's RPC deadline off the servicer
    context into the same monotonic slot the HTTP header feeds."""
    from gofr_tpu.grpc import server as gsrv

    ic = gsrv.GofrGrpcInterceptor(new_mock_container())

    class _SC:
        def time_remaining(self):
            return 1.5

    span, token = ic._begin({}, "Svc/M", {}, _SC())
    try:
        ctx = gsrv.current_grpc_context()
        rem = deadline.remaining(ctx.request.context())
        assert rem is not None and 1.0 < rem <= 1.5
    finally:
        gsrv._grpc_ctx.reset(token)

    class _NoDeadline:
        def time_remaining(self):
            return None

    span, token = ic._begin({}, "Svc/M", {}, _NoDeadline())
    try:
        ctx = gsrv.current_grpc_context()
        assert deadline.remaining(ctx.request.context()) is None
    finally:
        gsrv._grpc_ctx.reset(token)


# -- engine integration (tiny model, paged layout; unmarked = tier-1) -----------


@pytest.fixture(scope="module")
def tiny_llama():
    import jax

    from gofr_tpu.models import LlamaConfig, llama

    cfg = LlamaConfig.tiny()
    params = llama.init(cfg, jax.random.key(7))
    return cfg, params


def _paged_engine(cfg, params, container, **kw):
    from gofr_tpu.models import llama
    from gofr_tpu.tpu.engine import GenerateEngine

    kw.setdefault("slots", 2)
    kw.setdefault("max_len", 64)
    kw.setdefault("max_prefill_batch", 2)
    kw.setdefault("kv_layout", "paged")
    kw.setdefault("page_size", 8)
    kw.setdefault("decode_chunk", 1)
    return GenerateEngine(llama, cfg, params, container, **kw)


class TestEngineCancellation:
    def test_cancel_mid_decode_frees_slot_and_pages(self, tiny_llama):
        """The disconnect-mid-SSE contract at the engine boundary: the
        transport's stream.cancel() must reclaim the slot AND every KV
        page — verified by the full paged-cache accounting cross-check."""
        from gofr_tpu.testutil import assert_page_refs_consistent

        cfg, params = tiny_llama
        c = new_mock_container()
        eng = _paged_engine(cfg, params, c)
        try:
            it = eng.generate(list(range(1, 6)), max_new_tokens=400,
                              timeout=120, stream=True)
            first = next(it)
            assert isinstance(first, int)
            it.cancel()  # what _stream_sse does on ConnectionResetError
            with pytest.raises(Exception):
                for _ in it:
                    pass
            t_end = time.monotonic() + 30
            while time.monotonic() < t_end and any(
                    s is not None for s in eng.slots):
                time.sleep(0.05)
            assert all(s is None for s in eng.slots)
            assert it._req.cancelled
            assert it._req.cancel_reason == "client_disconnect"
            assert_page_refs_consistent(eng)  # zero leaked pages
        finally:
            eng.stop()

    def test_expired_deadline_submit_sheds_pre_slot(self, tiny_llama):
        """Doomed work never takes a slot: an effective timeout <= 0 is a
        504 at submission, with the engine-side metric."""
        cfg, params = tiny_llama
        c = new_mock_container()
        eng = _paged_engine(cfg, params, c)
        try:
            with pytest.raises(DeadlineExceeded):
                eng.generate([1, 2, 3], max_new_tokens=2, timeout=0.0)
            assert all(s is None for s in eng.slots)
            assert c.metrics.get("app_request_deadline_exceeded_total").value(
                where="engine") == 1
        finally:
            eng.stop()

    def test_cancel_reason_reaches_the_flight_recorder(self, tiny_llama):
        """Observability satellite: a cancelled generation's reason rides
        the flight-recorder entry (the 'why did this request die' answer
        an incident wants first)."""
        cfg, params = tiny_llama
        c = new_mock_container()
        eng = _paged_engine(cfg, params, c)
        try:
            it = eng.generate(list(range(1, 6)), max_new_tokens=400,
                              timeout=120, stream=True)
            next(it)
            it.cancel()
            with pytest.raises(Exception):
                for _ in it:
                    pass
            t_end = time.monotonic() + 30
            entry = None
            while time.monotonic() < t_end and entry is None:
                for e in c.flight.requests():
                    if e.get("cancel_reason") == "client_disconnect":
                        entry = e
                        break
                time.sleep(0.05)
            assert entry is not None, "cancel_reason missing from recorder"
        finally:
            eng.stop()
