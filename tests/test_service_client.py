"""Inter-service client tests against a real local HTTP server."""

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from gofr_tpu.logging import MockLogger
from gofr_tpu.metrics import Registry
from gofr_tpu.service import (
    APIKeyOption,
    BasicAuthOption,
    CircuitBreaker,
    DefaultHeaders,
    Retry,
    ServiceError,
    new_http_service,
)


class Backend(BaseHTTPRequestHandler):
    fail_times = 0
    requests: list = []

    def do_GET(self):
        Backend.requests.append((self.path, dict(self.headers)))
        if self.path == "/.well-known/alive":
            self._json(200, {"data": {"status": "UP"}})
            return
        if Backend.fail_times > 0:
            Backend.fail_times -= 1
            self._json(500, {"error": {"message": "boom"}})
            return
        self._json(200, {"data": "ok"})

    def _json(self, status, payload):
        body = json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *a):
        pass


@pytest.fixture
def backend():
    Backend.fail_times = 0
    Backend.requests = []
    srv = HTTPServer(("127.0.0.1", 0), Backend)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield f"http://127.0.0.1:{srv.server_address[1]}"
    srv.shutdown()


def test_base_client_get_logs_and_metrics(backend):
    log, reg = MockLogger(), Registry()
    reg.new_histogram("app_http_service_response")
    client = new_http_service(backend, log, reg)
    resp = client.get("/data")
    assert resp.ok and resp.json() == {"data": "ok"}
    assert reg.get("app_http_service_response").count(service=backend, method="GET", status="200") == 1
    assert any(r.get("message") == "http service call" for r in log.records)


def test_retry_recovers_from_5xx(backend):
    Backend.fail_times = 2
    client = new_http_service(backend, None, None, Retry(max_retries=3, backoff=0.01))
    resp = client.get("/flaky")
    assert resp.status_code == 200


def test_retry_exhausted_raises(backend):
    Backend.fail_times = 10
    client = new_http_service(backend, None, None, Retry(max_retries=1, backoff=0.01))
    with pytest.raises(ServiceError):
        client.get("/flaky")


def test_circuit_breaker_opens_and_recovers(backend):
    Backend.fail_times = 3
    client = new_http_service(backend, None, None, CircuitBreaker(threshold=3, interval=0.1))
    for _ in range(3):
        r = client.get("/flaky")
        assert r.status_code == 500
    # breaker now open: requests rejected without hitting the backend
    n = len(Backend.requests)
    with pytest.raises(ServiceError, match="circuit breaker is open"):
        client.get("/flaky")
    assert len([r for r in Backend.requests[n:] if not r[0].startswith("/.well-known")]) == 0
    # health probe recovers it (backend is healthy again)
    import time

    deadline = time.time() + 3
    while client.is_open and time.time() < deadline:
        time.sleep(0.05)
    assert not client.is_open
    assert client.get("/data").status_code == 200


def test_auth_and_header_options_compose(backend):
    client = new_http_service(
        backend, None, None,
        BasicAuthOption("u", "p"), APIKeyOption("k123"), DefaultHeaders(X_Env="prod"),
    )
    client.get("/who")
    path, headers = Backend.requests[-1]
    assert headers["Authorization"].startswith("Basic ")
    assert headers["X-API-KEY"] == "k123"
    assert headers["X-Env"] == "prod"


def test_traceparent_propagation(backend):
    from gofr_tpu.tracing import MemoryExporter, Tracer

    tracer = Tracer(MemoryExporter())
    client = new_http_service(backend, None, None)
    with tracer.span("parent") as span:
        client.get("/traced")
    _, headers = Backend.requests[-1]
    assert headers["traceparent"].split("-")[1] == span.trace_id


def test_health_check(backend):
    client = new_http_service(backend, None, None)
    assert client.health_check()["status"] == "UP"


# -- streamed responses (ISSUE 7 satellite: SSE proxying needs body chunks
# as they arrive, and a client cancel must abort the upstream transfer) ---------


class StreamBackend(BaseHTTPRequestHandler):
    """Writes one SSE frame, BLOCKS on ``release``, then writes the rest —
    so a test can prove the client saw frame one while frame two did not
    yet exist (incremental delivery, not full-body buffering)."""

    release = threading.Event()
    write_error: list = []

    def do_GET(self):
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.end_headers()
        self.wfile.write(b"data: one\n\n")
        self.wfile.flush()
        StreamBackend.release.wait(timeout=10)
        try:
            self.wfile.write(b"data: two\n\n")
            self.wfile.flush()
            # keep writing: a closed peer RSTs and a later flush raises —
            # one buffered write could slip out before the RST lands
            for _ in range(50):
                self.wfile.write(b"x" * 65536)
                self.wfile.flush()
                time.sleep(0.01)
        except OSError as e:
            StreamBackend.write_error.append(repr(e))

    def log_message(self, *a):
        pass


@pytest.fixture
def stream_backend():
    StreamBackend.release = threading.Event()
    StreamBackend.write_error = []
    srv = HTTPServer(("127.0.0.1", 0), StreamBackend)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield f"http://127.0.0.1:{srv.server_address[1]}"
    StreamBackend.release.set()
    srv.shutdown()


def test_streamed_response_chunks_arrive_incrementally(stream_backend):
    client = new_http_service(stream_backend, None, None)
    resp = client.request("GET", "/stream", stream=True)
    assert resp.status_code == 200 and resp.ok
    assert resp.headers["content-type"] == "text/event-stream"
    it = resp.iter_content()
    got = b""
    while b"one" not in got:
        got += next(it)
    # the server has not produced frame two yet: seeing frame one NOW
    # proves request() returned headers-first instead of reading the body
    assert b"two" not in got
    StreamBackend.release.set()
    for chunk in it:
        got += chunk
    assert b"two" in got
    client.close()


def test_streamed_response_close_aborts_upstream(stream_backend):
    client = new_http_service(stream_backend, None, None)
    resp = client.request("GET", "/stream", stream=True)
    first = next(resp.iter_content())
    assert b"one" in first
    resp.close()  # client cancel mid-stream (idempotent; closes the conn)
    resp.close()
    StreamBackend.release.set()
    deadline = time.time() + 5
    while not StreamBackend.write_error and time.time() < deadline:
        time.sleep(0.02)
    # the server's next write hit a dead connection: the transfer was
    # aborted, not silently drained into a ghost
    assert StreamBackend.write_error
    client.close()
