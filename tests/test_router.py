"""Prefix-affinity data-plane router (ISSUE 7; docs/routing.md).

Quick tier: stable chain keys (subprocess regression across PYTHONHASHSEED
values — the tentpole-prerequisite bugfix), the consistent-hash ring, the
gossip-fed registry state machine, and QoS-aware routing plans. Process
tier: TWO real replica Apps (tiny llama, paged prefix cache) behind a
router App on one broker — affinity routing beats random routing on
prefix hit-token ratio, and a chaos-killed replica spills high classes /
sheds low classes at the router with zero failed high-class requests,
then re-enters the ring at its bumped epoch.
"""

import json
import os
import pathlib
import subprocess
import sys
import time

import numpy as np
import pytest

from gofr_tpu.container import new_mock_container
from gofr_tpu.pubsub.inmemory import InMemoryBroker
from gofr_tpu.router import Router, RouterPolicy
from gofr_tpu.router.registry import ReplicaRegistry
from gofr_tpu.router.ring import HashRing
from gofr_tpu.tpu import prefix
from gofr_tpu.tpu.prefix import PrefixCache

REPO = pathlib.Path(__file__).resolve().parents[1]


@pytest.mark.quick
class TestStableChainKeys:
    def test_keys_equal_across_processes_with_different_hash_seeds(self):
        """The ISSUE 7 prerequisite regression: chain keys derived in two
        interpreters with different PYTHONHASHSEED values must be equal —
        builtin ``hash(bytes)`` is seed-salted and was neither shardable
        nor restart-stable."""
        script = ("import numpy as np; from gofr_tpu.tpu import prefix; "
                  "print(prefix.chain_keys(np.arange(64), 16))")
        outs = []
        for seed in ("0", "424242"):
            env = {**os.environ, "PYTHONHASHSEED": seed}
            run = subprocess.run([sys.executable, "-c", script], cwd=REPO,
                                 env=env, capture_output=True, text=True, timeout=120)
            assert run.returncode == 0, run.stderr
            outs.append(run.stdout.strip())
        assert outs[0] and outs[0] == outs[1]

    def test_router_side_keys_match_the_cache_walk(self):
        """``chain_keys`` (router side) must produce the exact node keys the
        replica's PrefixCache stores — that identity IS the affinity."""
        c = PrefixCache(4)
        toks = np.arange(13)  # 3 full pages + a remainder the walk ignores
        c.insert(toks, [1, 2, 3])
        walked = [k for k, _ in c.lookup_tiered(toks)]
        assert walked == prefix.chain_keys(toks, 4)
        assert len(walked) == 3

    def test_ancestry_feeds_the_digest(self):
        # identical page tokens under different parents are distinct chains
        page = np.arange(4, dtype=np.int32).tobytes()
        assert prefix.chain_key(prefix._ROOT, page) != prefix.chain_key(1, page)
        # and the digest is a stable value, not an id()-flavored accident
        assert prefix.chain_key(0, b"") == prefix.chain_key(0, b"")


@pytest.mark.quick
class TestHashRing:
    def test_lookup_is_deterministic_and_home_first_distinct(self):
        r1, r2 = HashRing(16), HashRing(16)
        for n in ("a", "b", "c"):
            r1.add(n)
            r2.add(n)
        for key in range(0, 2**64, 2**60):
            order = r1.lookup(key)
            assert order == r2.lookup(key)
            assert sorted(order) == ["a", "b", "c"]  # distinct, all members
        assert r1.lookup(123, n=1) == r1.lookup(123)[:1]

    def test_removal_moves_only_the_removed_replicas_keys(self):
        ring = HashRing(32)
        for n in ("a", "b", "c"):
            ring.add(n)
        keys = [prefix.chain_key(0, bytes([i])) for i in range(200)]
        before = {k: ring.lookup(k, 1)[0] for k in keys}
        ring.remove("b")
        for k, home in before.items():
            if home != "b":
                assert ring.lookup(k, 1)[0] == home  # unaffected keys stay put
            else:
                assert ring.lookup(k, 1)[0] in ("a", "c")
        ring.add("b")  # re-adding restores the original assignment exactly
        assert {k: ring.lookup(k, 1)[0] for k in keys} == before

    def test_empty_and_single_member(self):
        ring = HashRing(8)
        assert ring.lookup(1) == []
        ring.add("only")
        assert ring.lookup(1) == ["only"]
        assert len(ring) == 1 and "only" in ring


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t


@pytest.mark.quick
class TestReplicaRegistry:
    def _reg(self, ttl_s=3.0, jitter_s=0.0):
        clock = _Clock()
        reg = ReplicaRegistry(HashRing(8), ttl_s=ttl_s, jitter_s=jitter_s, now=clock)
        return reg, clock

    def test_up_admits_and_shedding_keeps_membership(self):
        reg, _ = self._reg()
        reg.observe({"replica": "a", "url": "u", "status": "UP", "epoch": 0})
        assert "a" in reg.ring and "a" in reg.full
        reg.observe({"replica": "a", "status": "DEGRADED", "shedding": True})
        # shedding is a spillover signal, NOT a membership change: one
        # overloaded replica must not shift every key
        assert "a" in reg.ring and reg.get("a").shedding

    def test_restart_window_drops_and_requires_epoch_bump(self):
        # REAL engine timing: _restarts bumps BEFORE the window opens, so
        # the restarting gossip already carries the post-rebuild epoch —
        # the gate must compare against the last epoch seen HEALTHY
        reg, clock = self._reg()
        reg.observe({"replica": "a", "status": "UP", "epoch": 0})
        reg.observe({"replica": "a", "status": "DEGRADED", "restarting": True, "epoch": 1})
        assert "a" not in reg.ring
        assert "a" in reg.full  # restart-window member keeps its keys
        # UP at the last HEALTHY epoch (a dying gossip tick racing the
        # drop, or a replayed message): not proof of a finished rebuild
        clock.t += 0.1
        reg.observe({"replica": "a", "status": "UP", "epoch": 0})
        assert "a" not in reg.ring
        # UP at the bumped epoch the window gossiped: rebuild finished
        reg.observe({"replica": "a", "status": "UP", "epoch": 1})
        assert "a" in reg.ring

    def test_rejoin_jitter_delays_readmission(self):
        # long TTL: the clock jump below must exercise the jitter window,
        # not gossip-silence expiry
        reg, clock = self._reg(ttl_s=100.0, jitter_s=5.0)
        reg.observe({"replica": "a", "status": "UP", "epoch": 0})
        reg.observe({"replica": "a", "status": "DEGRADED", "restarting": True, "epoch": 0})
        delay = reg.get("a").readmit_at
        assert 0.0 <= delay <= 5.0
        reg.observe({"replica": "a", "status": "UP", "epoch": 1})
        if delay > 0:
            assert "a" not in reg.ring  # not yet: anti-stampede window
        clock.t = 5.0
        reg.sweep()
        assert "a" in reg.ring
        # deterministic per (name, epoch): a re-run computes the same delay
        assert reg._jitter(reg.get("a")) == reg._jitter(reg.get("a"))

    def test_gossip_silence_expires_membership_and_keys(self):
        reg, clock = self._reg(ttl_s=2.0)
        reg.observe({"replica": "a", "status": "UP"})
        clock.t = 5.0
        reg.sweep()
        assert "a" not in reg.ring
        assert "a" not in reg.full  # silent replicas give up their keys
        # fresh gossip re-admits without an epoch requirement
        reg.observe({"replica": "a", "status": "UP"})
        assert "a" in reg.ring and "a" in reg.full

    def test_terminal_down_leaves_both_rings(self):
        reg, _ = self._reg()
        reg.observe({"replica": "a", "status": "UP"})
        reg.observe({"replica": "a", "status": "DOWN"})
        assert "a" not in reg.ring and "a" not in reg.full

    def test_restart_window_ending_in_down_gives_up_keys(self):
        # engine exhausts its restart budget: the app stays alive and keeps
        # gossiping DOWN — the member must not hold its keys hostage
        reg, _ = self._reg()
        reg.observe({"replica": "a", "status": "UP", "epoch": 0})
        reg.observe({"replica": "a", "status": "DEGRADED", "restarting": True})
        assert "a" in reg.full  # transient window: keys kept
        reg.observe({"replica": "a", "status": "DOWN", "restarting": False})
        assert "a" not in reg.full  # persistent DOWN: keys move for good
        reg.observe({"replica": "a", "status": "UP", "epoch": 1})
        assert "a" in reg.ring and "a" in reg.full  # and it can come back

    def test_static_seed_is_ttl_exempt(self):
        reg, clock = self._reg(ttl_s=1.0)
        reg.add_static("s", "http://s")
        clock.t = 100.0
        reg.sweep()
        assert "s" in reg.ring


@pytest.mark.quick
class TestRoutePlans:
    def _router(self, **kw):
        container = new_mock_container()
        kw.setdefault("page_size", 4)
        kw.setdefault("jitter_s", 0.0)
        kw.setdefault("replicas", {"a": "http://a", "b": "http://b"})
        return Router(container, policy=RouterPolicy(**kw))

    def _key_homed(self, router, name):
        for i in range(512):
            key = prefix.chain_key(0, bytes([i % 251, i // 251]))
            if router.registry.full.lookup(key, 1)[0] == name:
                return key
        raise AssertionError(f"no key homed on {name}")

    def test_healthy_home_first_spillable_gets_successor(self):
        router = self._router()
        key = self._key_homed(router, "a")
        p = router.plan(key, "interactive")
        assert p.home == "a" and [t.name for t in p.targets] == ["a", "b"]
        p = router.plan(key, "batch")  # below ROUTER_SPILL_CLASSES: no spare
        assert [t.name for t in p.targets] == ["a"] and p.shed is None

    def test_restarting_home_spills_high_and_sheds_low(self):
        router = self._router()
        router.registry.observe({"replica": "a", "url": "http://a",
                                 "status": "DEGRADED", "restarting": True,
                                 "epoch": 0, "retry_after": 7.5})
        key = self._key_homed(router, "a")
        high = router.plan(key, "interactive")
        assert high.shed is None and [t.name for t in high.targets] == ["b"]
        low = router.plan(key, "batch")
        assert low.targets == [] and low.shed == ("restart", 7.5)

    def test_shedding_home_spills_high_and_sheds_low(self):
        router = self._router()
        router.registry.observe({"replica": "b", "url": "http://b",
                                 "status": "DEGRADED", "shedding": True,
                                 "retry_after": 2.0})
        key = self._key_homed(router, "b")
        assert [t.name for t in router.plan(key, "interactive").targets] == ["a"]
        assert router.plan(key, "batch").shed == ("shedding", 2.0)

    def test_empty_ring_sheds_everything(self):
        router = self._router(replicas={})
        p = router.plan(12345, "interactive")
        assert p.targets == [] and p.shed is not None

    def test_unknown_class_resolves_to_default_and_spills(self):
        router = self._router()
        key = self._key_homed(router, "a")
        p = router.plan(key, "no-such-class")
        assert p.qos_class == "default" and p.spillable

    def test_shard_key_hashes_only_the_keyed_prefix(self):
        # the shard key of a long prompt equals the key of its first
        # key_pages pages — deeper pages must not change (or cost) anything
        router = self._router()
        rng = np.random.RandomState(5)
        head = rng.randint(1, 99, size=4).tolist()
        long = head + rng.randint(1, 99, size=40).tolist()
        assert router.shard_key(long) == router.shard_key(head)
        assert router.shard_key(long) == prefix.chain_keys(np.asarray(head), 4)[0]

    def test_proxied_response_keeps_full_content_type(self):
        # Content-Type parameters (charset, multipart boundary) must survive
        # the hop verbatim in the passthrough headers
        router = self._router()
        key = self._key_homed(router, "a")
        p = router.plan(key, "interactive")

        class _Resp:
            status_code = 200
            headers = {"content-type": "text/plain; charset=latin-1",
                       "retry-after": "3", "transfer-encoding": "chunked"}

            def read(self):
                return b"\xe9"

            def close(self):
                pass

        out = router._finish(p, p.targets[0], _Resp())
        assert out.body == b"\xe9" and out.status_code == 200
        assert out.headers["content-type"] == "text/plain; charset=latin-1"
        assert out.headers["retry-after"] == "3"
        assert "transfer-encoding" not in out.headers  # hop-by-hop stripped

    def test_random_mode_is_seeded_and_ignores_affinity(self):
        r1 = self._router(mode="random", seed=11)
        r2 = self._router(mode="random", seed=11)
        keys = [prefix.chain_key(0, bytes([i])) for i in range(32)]
        picks1 = [r1.plan(k).targets[0].name for k in keys]
        picks2 = [r2.plan(k).targets[0].name for k in keys]
        assert picks1 == picks2
        assert set(picks1) == {"a", "b"}  # actually scatters


# -- process tier: two replica apps + a router app over one broker ---------------


def _hits(app) -> float:
    m = app.container.metrics.get("app_tpu_prefix_hit_tokens")
    return sum(m._values.values()) if m is not None else 0.0


def _make_replica(broker, name):
    import jax.numpy as jnp

    from gofr_tpu.http.streaming import StreamingResponse
    from gofr_tpu.models import LlamaConfig, ModelSpec
    from tests.test_http_server import make_app

    app = make_app()
    app.container.pubsub = broker
    spec = ModelSpec("llama", LlamaConfig.tiny(), task="generate", dtype=jnp.float32)
    app.serve_model("lm", spec, slots=2, max_len=64, decode_chunk=2,
                    kv_layout="paged", page_size=16, total_pages=20,
                    prefix_cache=True)
    app.enable_qos()  # restart windows answer 503 + Retry-After, not queue

    def generate(ctx):
        body = ctx.bind(dict)
        return ctx.generate("lm", body["prompt"],
                            max_new_tokens=int(body.get("max_new_tokens", 2)),
                            timeout=120)

    def generate_stream(ctx):
        body = ctx.bind(dict)
        it = ctx.generate("lm", body["prompt"],
                          max_new_tokens=int(body.get("max_new_tokens", 8)),
                          stream=True, timeout=120)
        return StreamingResponse(it, event="token")

    app.post("/generate", generate)
    app.post("/generate/stream", generate_stream)
    app.enable_router_gossip(name=name, interval_s=0.05)
    return app


def _make_router_app(broker, **policy_kw):
    from tests.test_http_server import make_app

    app = make_app({"APP_ENV": "DEBUG"})
    app.container.pubsub = broker
    policy_kw.setdefault("page_size", 16)
    policy_kw.setdefault("ttl_s", 2.0)
    policy_kw.setdefault("jitter_s", 0.0)
    router = Router(app.container, policy=RouterPolicy(**policy_kw))
    router.bind(app)
    return app, router


def _wait_ring(router, want, deadline_s=30.0):
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        router.registry.sweep()
        if sorted(router.ring.members()) == sorted(want):
            return
        time.sleep(0.02)
    raise AssertionError(f"ring never became {want}: {router.ring.members()}")


def _tenant_prompt(rng, shared):
    return shared + rng.randint(1, 500, size=5).tolist()


def test_two_replica_affinity_beats_random_hit_ratio():
    """Acceptance drill, arm 1: with affinity routing a repeat tenant's warm
    requests land on the replica holding their cached prefix — the
    hit-token ratio must be STRICTLY above the random-routing arm's."""
    import httpx

    from tests.test_http_server import AppHarness

    broker = InMemoryBroker()
    a, b = _make_replica(broker, "a"), _make_replica(broker, "b")
    rapp, router = _make_router_app(broker)
    with AppHarness(a), AppHarness(b), AppHarness(rapp) as hr:
        _wait_ring(router, ["a", "b"])
        rng = np.random.RandomState(0)
        with httpx.Client(base_url=hr.base, timeout=180) as client:

            def run_arm(mode):
                router.policy.mode = mode
                hit0 = _hits(a) + _hits(b)
                toks = 0
                for _tenant in range(4):
                    shared = rng.randint(1, 500, size=16).tolist()  # one page
                    for _rep in range(5):
                        p = _tenant_prompt(rng, shared)
                        toks += len(p)
                        r = client.post("/generate",
                                        json={"prompt": p, "max_new_tokens": 2})
                        assert r.status_code == 201, r.text
                return (_hits(a) + _hits(b) - hit0) / toks

            affinity_ratio = run_arm("affinity")
            random_ratio = run_arm("random")
        assert affinity_ratio > random_ratio, (affinity_ratio, random_ratio)
        assert affinity_ratio > 0.4  # 4 of 5 per tenant hit a 16/21 prefix
        view = router.debug_view()
        assert view["stats"]["requests"] == 40
        assert view["stats"]["shed"] == 0


def test_replica_kill_spills_high_sheds_low_then_epoch_readmits(tmp_path):
    """Acceptance drill, arm 2: chaos kills replica b's engine mid-stream;
    while b's restart window is latch-held open the router spills
    high-class b-homed traffic to a (zero failures) and sheds low-class at
    the router with Retry-After; releasing the latch restarts b, whose
    epoch-bumped gossip re-admits it to the ring."""
    import httpx

    from gofr_tpu.fleet import chaos
    from tests.test_http_server import AppHarness

    broker = InMemoryBroker()
    a = _make_replica(broker, "a")
    latch = tmp_path / "release-restart"
    with chaos.override(
            f"engine.step:raise,at_step=3;engine.restart:hold,file={latch},timeout=120"):
        b = _make_replica(broker, "b")  # chaos arms at engine build: only b
    rapp, router = _make_router_app(broker)
    rng = np.random.RandomState(1)

    def prompt_homed(name):
        while True:
            p = rng.randint(1, 500, size=21).tolist()
            if router.registry.full.lookup(router.shard_key(p), 1)[0] == name:
                return p

    with AppHarness(a), AppHarness(b), AppHarness(rapp) as hr:
        _wait_ring(router, ["a", "b"])
        with httpx.Client(base_url=hr.base, timeout=180) as client:
            pb = prompt_homed("b")
            # mid-traffic kill: a b-homed SSE stream long enough that the
            # at_step=3 raise lands inside it; the error arrives IN BAND
            # through the router's raw streaming passthrough
            events = []
            with client.stream("POST", "/generate/stream",
                               json={"prompt": pb, "max_new_tokens": 40}) as r:
                assert r.status_code == 200
                assert r.headers["content-type"].startswith("text/event-stream")
                for line in r.iter_lines():
                    if line.startswith("event: "):
                        events.append(line.split("event: ", 1)[1])
            assert "error" in events and "done" not in events

            # gossip flips b restarting → it leaves the ring (keys intact)
            deadline = time.time() + 30
            while time.time() < deadline and "b" in router.ring:
                time.sleep(0.02)
            assert "b" not in router.ring
            assert "b" in router.registry.full  # restart window keeps keys

            # high class homed on b: spilled to a, ZERO failures
            for _ in range(5):
                r = client.post("/generate",
                                json={"prompt": prompt_homed("b"), "max_new_tokens": 2},
                                headers={"X-QoS-Class": "interactive"})
                assert r.status_code == 201, r.text
            # low class homed on b: shed AT the router, Retry-After intact
            r = client.post("/generate",
                            json={"prompt": pb, "max_new_tokens": 2},
                            headers={"X-QoS-Class": "batch"})
            assert r.status_code == 503, r.text
            assert "Retry-After" in r.headers
            # a-homed traffic is untouched by b's window
            r = client.post("/generate",
                            json={"prompt": prompt_homed("a"), "max_new_tokens": 2},
                            headers={"X-QoS-Class": "batch"})
            assert r.status_code == 201, r.text

            # release the held restart: b rebuilds, bumps its epoch, and the
            # ring re-admits it at the bumped epoch
            latch.write_text("")
            deadline = time.time() + 60
            while time.time() < deadline and "b" not in router.ring:
                router.registry.sweep()
                time.sleep(0.02)
            assert "b" in router.ring
            assert router.registry.get("b").epoch >= 1

            # and b actually serves its home keys again
            r = client.post("/generate",
                            json={"prompt": pb, "max_new_tokens": 2},
                            headers={"X-QoS-Class": "interactive"})
            assert r.status_code == 201, r.text
        view = router.debug_view()
        assert any(d["outcome"].startswith("shed:") for d in view["decisions"])
        m = rapp.container.metrics.get("app_router_shed_total")
        assert m is not None and sum(m._values.values()) >= 1
        # the high-class b-homed wave was accounted as SPILL off b with the
        # restart-window reason (counted at the landing, labeled by home)
        sp = rapp.container.metrics.get("app_router_spilled_total")
        spills = {ls: v for ls, v in sp._values.items()}
        assert sum(v for ls, v in spills.items()
                   if dict(ls).get("replica") == "b"
                   and dict(ls).get("reason") == "restart") >= 5, spills


@pytest.mark.quick
def test_gossip_reporter_snapshot_tracks_engine_state():
    """Quick-adjacent sanity on the replica side of the drill: the reporter
    derives status/epoch/restarting from the engines it fronts."""
    from gofr_tpu.router.gossip import GossipReporter

    container = new_mock_container()

    class _Engine:
        _restarting = False
        _restarts = 0

        def health_check(self):
            return {"status": "UP"}

    eng = _Engine()
    container.register_engine("m", eng)
    rep = GossipReporter(container, name="r0", url="http://r0", interval_s=9.0)
    snap = rep.snapshot()
    assert snap["replica"] == "r0" and snap["status"] == "UP"
    assert snap["epoch"] == 0 and not snap["restarting"]
    eng._restarting = True
    eng._restarts = 2
    assert rep.snapshot()["restarting"] and rep.snapshot()["epoch"] == 2
    # published snapshots arrive on the broker for any subscribed router
    rep.publish_once()
    msg = container.pubsub.subscribe(rep.topic, group="t", timeout=1.0)
    assert msg is not None and json.loads(msg.value)["replica"] == "r0"


@pytest.mark.quick
def test_forwarded_headers_merge_xff_and_inject_traceparent():
    """The hop must MERGE the existing X-Forwarded-For chain (HTTPRequest
    stores lowercase header keys) and replace traceparent with the
    router's own span so the replica parents under this hop."""
    from gofr_tpu.http.request import HTTPRequest
    from gofr_tpu.tracing import MemoryExporter, Tracer

    container = new_mock_container()
    router = Router(container, policy=RouterPolicy(page_size=4))
    req = HTTPRequest(method="POST", path="/generate", query_string="debug=1",
                      headers={"X-Forwarded-For": "203.0.113.9", "Host": "edge",
                               "traceparent": "00-" + "9" * 32 + "-" + "8" * 16 + "-01",
                               "X-QoS-Class": "interactive"},
                      body=b"{}", path_params={}, remote="10.0.0.2")
    # the raw query string is forwardable (the proxy appends it verbatim)
    assert req.query_string == "debug=1"
    span = Tracer(MemoryExporter()).start_span("hop", set_current=False)
    out = router._forward_headers(req, span)
    xff = [v for k, v in out.items() if k.lower() == "x-forwarded-for"]
    assert xff == ["203.0.113.9, 10.0.0.2"]  # merged, no duplicate key
    assert out["traceparent"] == span.traceparent()  # router span wins
    assert not any(k.lower() == "host" for k in out)  # hop-by-hop stripped
    assert any(k.lower() == "x-qos-class" for k in out)  # QoS class rides on


@pytest.mark.quick
def test_replayed_stale_gossip_is_ignored_at_boot():
    """A durable broker (pubsub/file.py) replays topic history to a fresh
    router consumer group: snapshots older than any liveness window must
    not admit their (possibly dead) URLs — only fresh gossip counts."""
    broker = InMemoryBroker()
    container = new_mock_container()
    container.pubsub = broker
    router = Router(container, policy=RouterPolicy(page_size=4, jitter_s=0.0))
    broker.publish(router.policy.topic, {
        "replica": "dead", "url": "http://old:1", "status": "UP",
        "epoch": 0, "ts": time.time() - 3600})
    broker.publish(router.policy.topic, {
        "replica": "live", "url": "http://live:1", "status": "UP",
        "epoch": 0, "ts": time.time()})
    router.start()
    try:
        deadline = time.time() + 5
        while time.time() < deadline and "live" not in router.ring:
            time.sleep(0.01)
        assert "live" in router.ring
        assert "dead" not in router.ring and router.registry.get("dead") is None
    finally:
        router.stop()


@pytest.mark.quick
def test_router_metrics_and_debug_view_shapes():
    """The /debug/router payload and metric families the docs promise."""
    container = new_mock_container()
    router = Router(container, policy=RouterPolicy(
        page_size=4, jitter_s=0.0, replicas={"a": "http://a"}))
    view = router.debug_view()
    assert view["ring"] == ["a"] and view["ring_size"] == 1
    assert view["stats"]["affinity_hit_ratio"] is None
    assert view["replicas"][0]["name"] == "a"
    g = container.metrics.get("app_router_ring_size")
    assert g is not None


@pytest.mark.quick
class TestAdapterAffinity:
    """PR 16 satellite: requests naming an adapter mix it into the ring
    key, so affinity is effectively on (prefix, adapter) — one adapter's
    traffic converges on replicas whose device pool already holds it."""

    def _router(self):
        return Router(new_mock_container(), policy=RouterPolicy(page_size=4))

    def _req(self, body, headers=None):
        from gofr_tpu.http.request import HTTPRequest

        return HTTPRequest(method="POST", path="/generate", query_string="",
                           headers=headers or {}, body=body,
                           path_params={}, remote="10.0.0.1")

    def test_body_adapter_id_changes_the_key(self):
        r = self._router()
        base = r.request_key(self._req(b'{"prompt": [1, 2, 3, 4]}'))
        fr = r.request_key(self._req(b'{"prompt": [1, 2, 3, 4], "adapter_id": "fr"}'))
        de = r.request_key(self._req(b'{"prompt": [1, 2, 3, 4], "adapter_id": "de"}'))
        assert len({base, fr, de}) == 3
        # deterministic: the same (prefix, adapter) pair keys identically
        assert fr == r.request_key(
            self._req(b'{"prompt": [1, 2, 3, 4], "adapter_id": "fr"}'))

    def test_header_adapter_is_case_insensitive_and_matches_body(self):
        r = self._router()
        via_body = r.request_key(
            self._req(b'{"prompt": [1, 2, 3, 4], "adapter_id": "fr"}'))
        via_hdr = r.request_key(self._req(b'{"prompt": [1, 2, 3, 4]}',
                                          headers={"x-adapter-id": "fr"}))
        via_HDR = r.request_key(self._req(b'{"prompt": [1, 2, 3, 4]}',
                                          headers={"X-Adapter-ID": "fr"}))
        assert via_hdr == via_HDR
        # body and header spell the same routing input... but the body
        # bytes differ, so only the ids-keyed portion is shared; what
        # matters is that the ADAPTER component is identical: stripping
        # it must land both on their no-adapter keys
        no_ad_body = r.request_key(self._req(b'{"prompt": [1, 2, 3, 4]}'))
        from gofr_tpu.router.ring import hash_point
        mix = hash_point(b"adapter:fr")
        assert via_hdr == no_ad_body ^ mix
        assert via_body == no_ad_body ^ mix

    def test_body_field_wins_over_header(self):
        r = self._router()
        both = self._req(b'{"prompt": [1, 2], "adapter_id": "fr"}',
                         headers={"X-Adapter-ID": "de"})
        only_fr = self._req(b'{"prompt": [1, 2], "adapter_id": "fr"}')
        assert r.request_key(both) == r.request_key(only_fr)

    def test_same_adapter_same_prefix_is_sticky_on_the_ring(self):
        """The actual affinity property: identical (prefix, adapter)
        requests route to the same replica through the plan."""
        r = self._router()
        for name in ("r0", "r1", "r2"):
            r.registry.observe({"replica": name, "status": "UP",
                                "url": f"http://{name}", "epoch": 0})
        req = self._req(b'{"prompt": [1, 2, 3, 4], "adapter_id": "fr"}')
        picks = {r.plan(r.request_key(req)).targets[0].name for _ in range(5)}
        assert len(picks) == 1
