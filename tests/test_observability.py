"""Request-lifecycle observability: engine span timeline, SLO histograms,
flight recorder, OTLP export, and traceparent propagation across
transports (HTTP handled end-to-end in test_serve_integration.py).

Pure-CPU/no-sleep tests are marked ``quick``; the engine-timeline tests
compile a tiny llama and ride the unit tier instead.
"""

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from gofr_tpu.config import DictConfig
from gofr_tpu.container import new_mock_container
from gofr_tpu.logging import MockLogger
from gofr_tpu.metrics.flight import FlightRecorder
from gofr_tpu.tracing import (
    MemoryExporter,
    NoopExporter,
    OTLPExporter,
    RequestTrace,
    SpanExporter,
    Tracer,
    ZipkinExporter,
    _rand_hex,
    tracer_from_config,
)


# -- id generation (satellite: fork-safe, seed-independent ids) ----------------


@pytest.mark.quick
def test_rand_hex_shape_and_seed_independence():
    import random

    h = _rand_hex(16)
    assert len(h) == 32
    int(h, 16)  # valid hex
    # the global random module is seeded identically twice; os.urandom-backed
    # ids must NOT repeat (the old implementation drew from `random` and did)
    random.seed(1234)
    a = _rand_hex(8)
    random.seed(1234)
    b = _rand_hex(8)
    assert a != b


# -- exporters -----------------------------------------------------------------


def _finished_span(name="s", parent=None, kind="INTERNAL", tracer=None):
    t = tracer or Tracer(MemoryExporter())
    span = t.start_span(name, parent=parent, kind=kind, set_current=False)
    span.finish()
    return span


@pytest.mark.quick
def test_zipkin_omits_absent_fields():
    """Strict Zipkin collectors reject literal ``"kind": null`` /
    ``"parentId": null`` — absent fields must be omitted entirely."""
    exp = ZipkinExporter("http://unused:9411/api/v2/spans", "svc")
    root = _finished_span(kind="INTERNAL")
    z = exp._to_zipkin(root)
    assert "kind" not in z
    assert "parentId" not in z

    t = Tracer(MemoryExporter())
    parent = t.start_span("p", set_current=False)
    child = t.start_span("c", parent=parent, kind="SERVER", set_current=False)
    child.finish()
    z = exp._to_zipkin(child)
    assert z["kind"] == "SERVER"
    assert z["parentId"] == parent.span_id
    # the whole payload round-trips as JSON without nulls for these keys
    assert "null" not in json.dumps({k: v for k, v in z.items() if k in ("kind", "parentId")})


@pytest.mark.quick
def test_otlp_payload_shape():
    exp = OTLPExporter("http://unused:4318/v1/traces", "svc")
    t = Tracer(MemoryExporter())
    parent = t.start_span("server", kind="SERVER", set_current=False)
    child = t.start_span("engine.prefill", parent=parent, set_current=False)
    child.set_attribute("slot", 3)
    child.add_event("chunk", offset=0, tokens=128)
    child.finish()
    parent.finish()

    payload = exp.to_payload([parent, child])
    rs = payload["resourceSpans"][0]
    assert {"key": "service.name", "value": {"stringValue": "svc"}} in rs["resource"]["attributes"]
    spans = rs["scopeSpans"][0]["spans"]
    assert [s["name"] for s in spans] == ["server", "engine.prefill"]
    srv, pre = spans
    assert srv["kind"] == 2 and pre["kind"] == 1  # SERVER / INTERNAL
    assert "parentSpanId" not in srv
    assert pre["parentSpanId"] == parent.span_id
    assert pre["traceId"] == parent.trace_id
    # proto3 JSON: int64 nanos as strings, int attributes as strings
    assert pre["startTimeUnixNano"].isdigit()
    assert {"key": "slot", "value": {"intValue": "3"}} in pre["attributes"]
    ev = pre["events"][0]
    assert ev["name"] == "chunk" and ev["timeUnixNano"].isdigit()


class _StubCollector:
    """Minimal OTLP/HTTP collector: records every POSTed JSON body."""

    def __init__(self):
        self.bodies = []
        self.paths = []
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_POST(self):
                length = int(self.headers.get("Content-Length", "0"))
                outer.bodies.append(json.loads(self.rfile.read(length)))
                outer.paths.append(self.path)
                self.send_response(200)
                self.end_headers()

            def log_message(self, *a):  # quiet
                pass

        self.server = HTTPServer(("127.0.0.1", 0), Handler)
        self.port = self.server.server_address[1]
        self._thread = threading.Thread(target=self.server.serve_forever, daemon=True)
        self._thread.start()

    def close(self):
        self.server.shutdown()
        self.server.server_close()


@pytest.mark.quick
def test_otlp_round_trip_via_config():
    """TRACE_EXPORTER=otlp exports real OTLP/HTTP JSON a collector accepts
    (acceptance criterion: round-trip against a stub collector)."""
    collector = _StubCollector()
    try:
        tracer = tracer_from_config(
            DictConfig({"TRACE_EXPORTER": "otlp",
                        "TRACER_URL": f"http://127.0.0.1:{collector.port}"}),
            MockLogger(), "svc-otlp")
        assert tracer.enabled
        with tracer.span("outer"):
            with tracer.span("inner"):
                pass
        tracer.shutdown()  # flush the batch thread
        assert collector.paths and all(p == "/v1/traces" for p in collector.paths)
        spans = [s
                 for body in collector.bodies
                 for rs in body["resourceSpans"]
                 for ss in rs["scopeSpans"]
                 for s in ss["spans"]]
        names = {s["name"] for s in spans}
        assert names == {"outer", "inner"}
        inner = next(s for s in spans if s["name"] == "inner")
        outer = next(s for s in spans if s["name"] == "outer")
        assert inner["parentSpanId"] == outer["spanId"]
        assert inner["traceId"] == outer["traceId"]
    finally:
        collector.close()


@pytest.mark.quick
def test_tracer_from_config_otlp_requires_url():
    log = MockLogger()
    t = tracer_from_config(DictConfig({"TRACE_EXPORTER": "otlp"}), log, "svc")
    assert isinstance(t._exporter, NoopExporter)
    assert any("TRACER_URL" in r.get("message", "") for r in log.records)


@pytest.mark.quick
def test_tracer_from_config_memory_and_enabled():
    t = tracer_from_config(DictConfig({"TRACE_EXPORTER": "memory"}), MockLogger(), "svc")
    assert isinstance(t._exporter, MemoryExporter)
    assert t.enabled
    assert not Tracer(NoopExporter()).enabled
    assert not Tracer().enabled


@pytest.mark.quick
def test_tracer_flush_on_shutdown():
    """Batch-exported spans still in the queue must be flushed when the
    container closes (satellite: flush-on-shutdown)."""

    class Collecting(SpanExporter):
        def __init__(self):
            self.spans = []

        def export(self, spans):
            self.spans.extend(spans)

    exp = Collecting()  # not Memory/Console → batching worker path
    c = new_mock_container()
    c.tracer = Tracer(exp, batch_size=1000, flush_interval=60.0)
    for i in range(5):
        c.tracer.start_span(f"s{i}", set_current=False).finish()
    c.close()  # container shutdown flushes the tracer
    assert len(exp.spans) == 5


# -- RequestTrace (engine span bundle) -----------------------------------------


@pytest.mark.quick
def test_request_trace_parents_under_inbound_span():
    exp = MemoryExporter()
    tracer = Tracer(exp)
    server = tracer.start_span("server", kind="SERVER", set_current=False)
    rt = RequestTrace(tracer, server)
    rt.begin("engine.queue_wait")
    rt.end("engine.queue_wait")
    rt.begin("engine.decode")
    rt.close_all()
    server.finish()
    for s in exp.spans:
        assert s.trace_id == server.trace_id
        if s.name != "server":
            assert s.parent_id == server.span_id
    assert rt.trace_id == server.trace_id


@pytest.mark.quick
def test_request_trace_synthesizes_root_and_marks_errors():
    exp = MemoryExporter()
    tracer = Tracer(exp)
    rt = RequestTrace(tracer, None)  # direct engine.generate caller
    rt.begin("engine.queue_wait")
    rt.close_all(error=RuntimeError("boom"))
    by_name = {s.name: s for s in exp.spans}
    assert set(by_name) == {"engine.request", "engine.queue_wait"}
    assert by_name["engine.queue_wait"].parent_id == by_name["engine.request"].span_id
    assert by_name["engine.queue_wait"].status == "ERROR"
    assert by_name["engine.request"].status == "ERROR"
    # double-end and unknown-end are harmless no-ops
    rt.end("engine.queue_wait")
    rt.end("never-begun")


# -- flight recorder -----------------------------------------------------------


@pytest.mark.quick
def test_flight_recorder_rings_and_order():
    fr = FlightRecorder(max_requests=3, max_steps=2)
    for i in range(5):
        fr.record_request({"id": i})
        fr.record_step("decode", 0.01, 0.5, ("decode", 4, 8), backlog=i)
    reqs = fr.requests()
    assert [r["id"] for r in reqs] == [4, 3, 2]  # newest first, ring of 3
    assert [r["id"] for r in fr.requests(limit=1)] == [4]
    steps = fr.steps()
    assert len(steps) == 2
    assert steps[0]["backlog"] == 4
    assert steps[0]["signature"] == "('decode', 4, 8)"
    assert steps[0]["kind"] == "decode"


# -- propagation: gRPC metadata → span -----------------------------------------


@pytest.mark.quick
def test_grpc_interceptor_joins_inbound_trace():
    from gofr_tpu.grpc.server import GofrGrpcInterceptor

    c = new_mock_container()
    c.tracer = Tracer(MemoryExporter())
    interceptor = GofrGrpcInterceptor(c)
    traceparent = "00-" + "c" * 32 + "-" + "d" * 16 + "-01"
    span, token = interceptor._begin(
        object(), "/pkg.Svc/Generate", {"traceparent": traceparent})
    assert span.trace_id == "c" * 32
    assert span.parent_id == "d" * 16
    assert span.kind == "SERVER"
    assert span.attributes["rpc.method"] == "/pkg.Svc/Generate"
    interceptor._end(span, token, "/pkg.Svc/Generate", 0, time.perf_counter(), messages=7)
    exported = c.tracer._exporter.spans[0]
    assert exported.attributes["rpc.messages"] == 7


# -- propagation: pubsub publish/subscribe -------------------------------------


@pytest.mark.quick
def test_pubsub_carries_traceparent_end_to_end():
    """Context.publish stamps traceparent into broker headers; the app's
    subscriber loop starts its CONSUMER span inside the same trace."""
    import gofr_tpu.app as appmod
    from gofr_tpu.context import Context

    c = new_mock_container()
    c.tracer = Tracer(MemoryExporter())
    server = c.tracer.start_span("server", kind="SERVER", set_current=False)
    Context(None, c, span=server).publish("events", {"x": 1})
    server.finish()

    # broker side: the header rides the message metadata
    peek = c.pubsub.subscribe("events", group="peek", timeout=1.0)
    assert peek is not None
    assert peek.param("traceparent") == server.traceparent()

    # consumer side: App._subscribe_loop joins the publisher's trace
    app = appmod.App(config=DictConfig({}), container=c)
    seen = {}
    done = threading.Event()

    def handler(ctx):
        seen["trace_id"] = ctx.span.trace_id
        seen["parent_id"] = ctx.span.parent_id
        done.set()

    t = threading.Thread(target=app._subscribe_loop, args=("events", handler), daemon=True)
    t.start()
    assert done.wait(timeout=10), "subscriber never ran"
    app._sub_stop.set()
    t.join(timeout=5)
    assert seen["trace_id"] == server.trace_id
    assert seen["parent_id"] == server.span_id


@pytest.mark.quick
def test_inmemory_broker_headers_optional():
    from gofr_tpu.pubsub.inmemory import InMemoryBroker

    b = InMemoryBroker()
    b.publish("t", b"plain")  # header-less publish unchanged
    b.publish("t", b"tagged", headers={"traceparent": "00-x", "offset": "evil"})
    m1 = b.subscribe("t", timeout=1.0)
    m2 = b.subscribe("t", timeout=1.0)
    assert m1.param("traceparent") == ""
    assert m2.param("traceparent") == "00-x"
    assert m2.value == b"tagged"
    # reserved delivery keys are never clobbered by a hostile header
    assert m2.metadata["offset"] == 1


# -- engine span timeline (compiles a tiny llama: unit tier, not quick) --------


@pytest.fixture(scope="module")
def tiny_llama():
    import jax

    from gofr_tpu.models import LlamaConfig, llama

    cfg = LlamaConfig.tiny()
    return cfg, llama.init(cfg, jax.random.key(7)), llama


def _make_engine(tiny, container, **kw):
    from gofr_tpu.tpu.engine import GenerateEngine

    cfg, params, llama = tiny
    kw.setdefault("slots", 2)
    kw.setdefault("max_len", 64)
    kw.setdefault("max_prefill_batch", 2)
    return GenerateEngine(llama, cfg, params, container, **kw)


def test_engine_span_timeline_and_slo_metrics(tiny_llama):
    """Acceptance core: one generate request under a MemoryExporter tracer
    yields ONE trace — server parent + engine.queue_wait/prefill/decode/
    finish children — and the SLO histograms + flight timeline populate."""
    c = new_mock_container()
    c.tracer = Tracer(MemoryExporter())
    eng = _make_engine(tiny_llama, c)
    try:
        with c.tracer.span("server") as server:
            out = eng.generate([5, 3, 9], max_new_tokens=6, timeout=60,
                               _parent_span=server)
        assert out["finish_reason"] == "length"

        spans = c.tracer._exporter.spans
        by_name = {s.name: s for s in spans}
        for name in ("engine.queue_wait", "engine.prefill", "engine.decode",
                     "engine.finish"):
            assert name in by_name, f"missing {name} in {sorted(by_name)}"
            assert by_name[name].trace_id == server.trace_id
            assert by_name[name].parent_id == server.span_id
        assert len({s.trace_id for s in spans}) == 1  # a single trace
        assert by_name["engine.decode"].attributes["tokens"] == 6
        assert by_name["engine.decode"].attributes["finish.reason"] == "length"
        assert "slot" in by_name["engine.prefill"].attributes

        m = c.metrics
        assert m.get("app_tpu_queue_wait_seconds").count() == 1
        assert m.get("app_tpu_ttft_seconds").count() == 1
        assert m.get("app_tpu_tpot_seconds").count() == 1
        assert m.get("app_tpu_e2e_seconds").count(qos_class="none") == 1
        # the gauge is summed across registered engines at scrape time
        c.register_engine("lm", eng)
        m.expose_text()
        assert m.get("app_tpu_inflight_requests").value() == 0
        assert eng._inflight_requests == 0

        # exposition carries the family (what /metrics serves)
        text = m.expose_text()
        for name in ("app_tpu_ttft_seconds", "app_tpu_tpot_seconds",
                     "app_tpu_e2e_seconds", "app_tpu_queue_wait_seconds"):
            assert f"{name}_count" in text

        entry = c.flight.requests()[0]
        assert entry["finish_reason"] == "length"
        assert entry["new_tokens"] == 6
        assert entry["trace_id"] == server.trace_id
        assert entry["queue_wait_s"] is not None
        assert entry["ttft_s"] >= entry["queue_wait_s"]
        assert entry["slot"] is not None
        assert c.flight.steps(), "device steps not recorded"
    finally:
        eng.stop()


def test_engine_noop_tracer_allocates_no_spans(tiny_llama):
    """Acceptance guard-branch: with TRACE_EXPORTER=none the engine path
    never constructs a span (MemoryExporter absence is trivially true —
    assert the stronger property: zero start_span calls)."""
    import gofr_tpu.tracing as tracing

    calls = []
    orig = tracing.Tracer.start_span

    def counting(self, *a, **k):
        calls.append(a)
        return orig(self, *a, **k)

    c = new_mock_container()  # default tracer: NoopExporter
    eng = _make_engine(tiny_llama, c)
    tracing.Tracer.start_span = counting
    try:
        req = eng.submit([5, 3, 9], max_new_tokens=4)
        out = req.result(60)
        assert len(out["tokens"]) == 4
        assert not calls, "engine built spans despite TRACE_EXPORTER=none"
        assert "_rt" not in req.kw
        # flight recorder + SLO metrics stay live with tracing off
        assert c.flight.requests()[0]["trace_id"] is None
        assert c.metrics.get("app_tpu_ttft_seconds").count() == 1
    finally:
        tracing.Tracer.start_span = orig
        eng.stop()


def test_engine_failure_closes_spans_with_error(tiny_llama):
    """A failed request must not leak open spans: the done callback closes
    its timeline with status=ERROR and the flight entry records the error."""
    c = new_mock_container()
    c.tracer = Tracer(MemoryExporter())
    eng = _make_engine(tiny_llama, c)
    try:
        # an empty prompt fails validation inside the device loop — after
        # the queue_wait span opened, before any phase could close it
        req = eng.submit([], max_new_tokens=4, timeout=60)
        with pytest.raises(ValueError):
            req.result(60)
        failed = [s for s in c.tracer._exporter.spans
                  if s.status == "ERROR" and s.name == "engine.queue_wait"]
        assert failed, "failed request's queue_wait span was not closed with ERROR"
        errs = [e for e in c.flight.requests() if "error" in e]
        assert errs and errs[0]["error"] == "ValueError"
    finally:
        eng.stop()
