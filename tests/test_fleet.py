"""Elastic fleet subsystem (gofr_tpu/fleet; docs/parallelism.md):

- quick tier: chaos-injection determinism, Supervisor restart policy
  (including the sliding-window restart budget and FleetSupervisor's
  fleet-wide generation monotonicity, on fake clocks/procs), and the
  fleet announce channel's frame/handshake/rejoin protocol — pure
  host-side code, no jax;
- process tier: 4 REAL processes (1 leader + 3 followers, each with a
  process-local dp:2,tp:2 mesh over 4 virtual CPU devices) serving
  token-exact over the host-side announce channel, and the leader-kill
  drill — chaos kills the leader's device loop mid-generation, the
  engine's supervised restart recovers it, the follower rejoins at a new
  epoch (no exit-17 fleet death), queued requests finish token-exact, and
  health reports DEGRADED exactly during the restart window.
"""

import os
import socket
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from jaxpin import child_env  # noqa: E402

from gofr_tpu.fleet import (  # noqa: E402
    ChannelClosed,
    FleetFollowerChannel,
    FleetLeaderChannel,
    FleetProtocolError,
    FleetSupervisor,
    Supervisor,
    chaos,
)
from gofr_tpu.logging import MockLogger  # noqa: E402
from gofr_tpu.tpu.lockstep import TAG_EPOCH, TAG_PREFILL  # noqa: E402


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# -- chaos layer (quick) ---------------------------------------------------------


@pytest.mark.quick
class TestChaos:
    def test_disabled_is_zero_cost(self, monkeypatch):
        monkeypatch.delenv("GOFR_CHAOS", raising=False)
        chaos.reset()
        assert not chaos.active()
        assert chaos.hook("engine.step") is None  # call sites bind None → one branch
        assert chaos.fire("engine.step") is False
        chaos.reset()

    def test_nth_every_after_gates(self):
        with chaos.override("a:drop,nth=2;b:drop,every=3;c:drop,after=2"):
            a = chaos.hook("a")
            assert [a() for _ in range(4)] == [False, True, False, False]
            b = chaos.hook("b")
            assert [b() for _ in range(7)] == [False, False, True, False, False, True, False]
            c = chaos.hook("c")
            assert [c() for _ in range(5)] == [False, False, True, True, True]

    def test_at_step_fires_once_on_state(self):
        with chaos.override("engine.step:drop,at_step=5"):
            h = chaos.hook("engine.step")
            assert not h(step=1) and not h(step=4)
            assert h(step=7)       # first time the counter reaches the gate
            assert not h(step=8)   # once only
            assert not h(step=5)

    def test_raise_action_and_fire(self):
        with chaos.override("pubsub.commit:raise,nth=1"):
            with pytest.raises(chaos.ChaosFault):
                chaos.fire("pubsub.commit", topic="orders")
            assert chaos.fire("pubsub.commit") is False  # nth=1 consumed

    def test_seeded_probability_is_replayable(self):
        def schedule(seed):
            with chaos.override("x:drop,p=0.5", seed=seed):
                h = chaos.hook("x")
                return [h() for _ in range(32)]

        assert schedule(7) == schedule(7)
        assert schedule(7) != schedule(8)  # 2^-32 false-failure odds

    def test_delay_returns_false(self):
        with chaos.override("y:delay,ms=1"):
            t0 = time.monotonic()
            assert chaos.fire("y") is False
            assert time.monotonic() - t0 >= 0.001

    def test_hold_waits_for_latch(self, tmp_path):
        latch = tmp_path / "go"
        latch.write_text("")
        with chaos.override(f"z:hold,file={latch}"):
            assert chaos.fire("z") is False  # latch exists: no wait

    def test_override_restores(self):
        chaos.reset()
        with chaos.override("q:drop"):
            assert chaos.active()
        assert not chaos.active()


# -- supervisor (quick) ----------------------------------------------------------


class TestSupervisor:
    # not quick: spawns (tiny) real subprocesses — the quick tier's
    # no-process rule (docs/testing.md) stays honest
    @staticmethod
    def _spawn_codes(codes, seen):
        def spawn(gen):
            seen.append(gen)
            return subprocess.Popen(
                [sys.executable, "-c", f"import sys; sys.exit({codes[gen]})"])

        return spawn

    def test_exit17_restarts_into_rejoin_then_clean(self):
        seen: list = []
        sup = Supervisor(self._spawn_codes([17, 5, 0], seen), name="t",
                         max_restarts=5, backoff_s=0.01, logger=MockLogger())
        assert sup.run() == 0
        assert seen == [0, 1, 2]       # exit 17 AND the crash both restarted
        assert sup.restarts == 2 and sup.generation == 2

    def test_budget_exhaustion_gives_up(self):
        seen: list = []
        sup = Supervisor(self._spawn_codes([1] * 10, seen), name="t",
                         max_restarts=2, backoff_s=0.01, logger=MockLogger())
        assert sup.run() == 1
        assert seen == [0, 1, 2]  # initial + 2 budgeted restarts, then give up

    def test_restart_policy_hook(self):
        seen: list = []
        sup = Supervisor(self._spawn_codes([3, 0], seen), name="t",
                         max_restarts=5, backoff_s=0.01,
                         restart_on=lambda rc: rc == 17)
        assert sup.run() == 3  # policy: only leader-loss exits restart
        assert seen == [0]

    def test_stop_terminates_child(self):
        def spawn(gen):
            return subprocess.Popen([sys.executable, "-c", "import time; time.sleep(60)"])

        sup = Supervisor(spawn, name="t", backoff_s=0.01)
        t = sup.start()
        time.sleep(0.2)
        sup.stop()
        t.join(timeout=10)
        assert not t.is_alive()


# -- supervisor restart-budget window (quick: fake clocks, fake procs) -----------


class _FakeProc:
    """Popen-shaped stand-in that has already exited with ``rc``."""

    def __init__(self, rc: int):
        self.returncode = rc

    def poll(self):
        return self.returncode

    def wait(self, timeout=None):
        return self.returncode

    def terminate(self):
        pass

    def kill(self):
        pass


@pytest.mark.quick
class TestSupervisorWindow:
    """The restart budget is a TRUE sliding window over crash timestamps
    (a deque pruned to ``window_s``), not a reset-on-gap counter — the
    give-up exists for crash loops, not lifetime fault totals."""

    @staticmethod
    def _drip(codes, gap_s, **kw):
        t = {"now": 0.0}
        seen: list = []

        def spawn(gen):
            t["now"] = gap_s * gen
            seen.append(gen)
            return _FakeProc(codes[gen])

        sup = Supervisor(spawn, name="t", backoff_s=0.001,
                         logger=MockLogger(), now=lambda: t["now"], **kw)
        return sup, seen

    def test_slow_drip_never_exhausts(self):
        # isolated faults 250s apart against a 300s window: no single
        # window ever holds more than 2 crashes, so a budget of 2 is never
        # exhausted — the reset-on-gap counter this replaced accumulated
        # them (each gap < window_s) and gave up on the 3rd drip fault
        sup, seen = self._drip([1, 1, 1, 1, 0], 250.0,
                               max_restarts=2, window_s=300.0)
        assert sup.run() == 0
        assert seen == [0, 1, 2, 3, 4]

    def test_overlapping_windows_counted_exactly(self):
        # crashes at t=0/100/200 overlap pairwise: a 300s window holds all
        # three at once (crash loop — give up after the budgeted 2
        # restarts), while a 150s window holds at most two (keep serving)
        sup, seen = self._drip([1, 1, 1, 1, 0], 100.0,
                               max_restarts=2, window_s=300.0)
        assert sup.run() == 1
        assert seen == [0, 1, 2]
        sup, seen = self._drip([1, 1, 1, 1, 0], 100.0,
                               max_restarts=2, window_s=150.0)
        assert sup.run() == 0
        assert seen == [0, 1, 2, 3, 4]

    def test_restarts_attribute_tracks_window_occupancy(self):
        sup, _ = self._drip([1, 1, 1, 0], 250.0,
                            max_restarts=2, window_s=300.0)
        assert sup.run() == 0
        # last crash (t=500) shares its window only with t=250 — the
        # exported restart count is window occupancy, not a lifetime total
        assert sup.restarts == 2


@pytest.mark.quick
class TestFleetSupervisorGenerations:
    def test_generations_monotonic_under_rapid_kill_rejoin(self):
        """Rapid kill/rejoin across DIFFERENT members: every spawn —
        initial or respawn — draws from ONE fleet-wide counter, so the
        FLEET_EPOCH base derived from it is never reused and is strictly
        increasing per member (the ring's bumped-epoch re-admission gate
        stays sound across members)."""
        import threading as _threading

        lock = _threading.Lock()
        seen: list[tuple[str, int]] = []
        lives = {"a": 3, "b": 3}  # 2 crashes then a clean exit, each

        def spawn_member(name, gen):
            with lock:
                seen.append((name, gen))
                lives[name] -= 1
                rc = 1 if lives[name] > 0 else 0
            return _FakeProc(rc)

        fs = FleetSupervisor(spawn_member, members=["a", "b"],
                             max_restarts=10, backoff_s=0.001,
                             logger=MockLogger())
        threads = fs.start()
        for t in threads.values():
            t.join(timeout=30)
        assert not any(t.is_alive() for t in threads.values())
        assert len(seen) == 6
        gens = [g for _, g in seen]
        # unique and gapless from 1: no generation is ever reused, even
        # with both members respawning concurrently
        assert sorted(gens) == list(range(1, 7))
        per: dict[str, list[int]] = {}
        for name, g in seen:
            per.setdefault(name, []).append(g)
        for gs in per.values():
            assert gs == sorted(gs)  # strictly increasing per member
        assert fs.generation == 6


# -- announce channel (quick) ----------------------------------------------------


@pytest.mark.quick
class TestFleetChannel:
    def test_handshake_frames_and_follower_loss(self):
        gauges, counters = {}, {}

        class _Metrics:
            def set_gauge(self, name, value, **kw):
                gauges[name] = value

            def increment_counter(self, name, value=1, **kw):
                counters[name] = counters.get(name, 0) + value

        leader = FleetLeaderChannel(0, fingerprint="fp", host="127.0.0.1",
                                    metrics=_Metrics())
        try:
            fol = FleetFollowerChannel(f"127.0.0.1:{leader.port}", fingerprint="fp",
                                       connect_timeout_s=5, rejoin_timeout_s=2)
            fol.connect()
            leader.wait_ready(1, epoch=0, timeout_s=5)
            assert leader.follower_count() == 1
            h = fol.recv_header()
            assert (int(h[0]), int(h[3])) == (TAG_EPOCH, 0)

            payload = np.arange(12, dtype=np.int32).reshape(3, 4)
            leader.send(np.array([TAG_PREFILL, 4, 3, 0], np.int32), payload)
            h = fol.recv_header()
            assert [int(x) for x in h] == [TAG_PREFILL, 4, 3, 0]
            got = fol.recv_payload((3, 4))
            assert np.array_equal(got, payload)

            # follower dies: a subsequent fan-out drops it (TCP surfaces
            # the peer close on the first send AFTER the RST lands, so the
            # leader may need a couple of sends to observe it) and serving
            # continues
            fol.close()
            deadline = time.monotonic() + 5
            while leader.follower_count() and time.monotonic() < deadline:
                leader.send(np.array([TAG_PREFILL, 4, 3, 0], np.int32), payload)
                time.sleep(0.01)
            assert leader.follower_count() == 0
            # the drop path keeps the active-follower gauge truthful (a
            # for-good loss never reaches an epoch bump to refresh it)
            assert gauges.get("app_fleet_followers") == 0
            assert counters.get("app_fleet_followers_lost_total") == 1
        finally:
            leader.close()

    def test_rejoin_after_leader_restart_bumps_epoch(self):
        port = _free_port()
        leader1 = FleetLeaderChannel(port, fingerprint="fp", host="127.0.0.1")
        fol = FleetFollowerChannel(f"127.0.0.1:{port}", fingerprint="fp",
                                   connect_timeout_s=5, rejoin_timeout_s=10)
        fol.connect()
        leader1.wait_ready(1, epoch=0, timeout_s=5)
        assert int(fol.recv_header()[0]) == TAG_EPOCH
        # leader PROCESS dies and a new one binds the same endpoint. The
        # follower's redial starts first (its abort releases the old
        # connection — with a dead leader process the kernel would have
        # reset it already) and retries until the new leader is up.
        leader1.close()
        import threading

        joined = threading.Thread(target=fol.rejoin, daemon=True)
        joined.start()
        leader2 = FleetLeaderChannel(port, fingerprint="fp", host="127.0.0.1")
        try:
            joined.join(timeout=10)
            assert not joined.is_alive()
            deadline = time.monotonic() + 5
            while not leader2.has_pending() and time.monotonic() < deadline:
                time.sleep(0.01)
            assert leader2.has_pending()
            assert leader2.admit_pending(epoch=1) == 1
            h = fol.recv_header()
            assert (int(h[0]), int(h[3])) == (TAG_EPOCH, 1)  # the new epoch
        finally:
            fol.close()
            leader2.close()

    def test_torn_frame_and_abort_surface_channel_closed(self):
        """Leader death between a frame's header and payload — and the
        watchdog's abort() landing in the same window — must both surface
        the RECOVERABLE ChannelClosed from recv_payload (the follower loop
        discards the torn frame and redials), never some unrelated error
        that would kill the follower instead of rejoining it."""
        from gofr_tpu.fleet.channel import _HEADER, _NBYTES

        leader = FleetLeaderChannel(0, fingerprint="fp", host="127.0.0.1")
        try:
            fol = FleetFollowerChannel(f"127.0.0.1:{leader.port}",
                                       fingerprint="fp",
                                       connect_timeout_s=5, rejoin_timeout_s=1)
            fol.connect()
            leader.wait_ready(1, epoch=0, timeout_s=5)
            assert int(fol.recv_header()[0]) == TAG_EPOCH
            # header + nbytes promise 48 payload bytes that never arrive
            with leader._lock:
                conn = leader._active[0]
            conn.sendall(_HEADER.pack(TAG_PREFILL, 4, 3, 0) + _NBYTES.pack(48))
            assert [int(x) for x in fol.recv_header()] == [TAG_PREFILL, 4, 3, 0]
            leader.reset_connections()  # leader dies mid-frame
            with pytest.raises(ChannelClosed):
                fol.recv_payload((3, 4))
            # watchdog abort() between header and payload: same signal,
            # not an AttributeError on the nulled socket
            fol.abort()
            with pytest.raises(ChannelClosed):
                fol.recv_payload((3, 4))
            fol.close()
        finally:
            leader.close()

    def test_fingerprint_mismatch_rejected_at_the_door(self):
        leader = FleetLeaderChannel(0, fingerprint="right", host="127.0.0.1")
        try:
            fol = FleetFollowerChannel(f"127.0.0.1:{leader.port}",
                                       fingerprint="wrong",
                                       connect_timeout_s=5, rejoin_timeout_s=1)
            fol.connect()
            with pytest.raises(FleetProtocolError, match="fingerprint"):
                fol.recv_header()
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                if leader.follower_count() == 0 and not leader.has_pending():
                    break
                time.sleep(0.01)
            assert not leader.has_pending()  # never parked in pending
        finally:
            leader.close()


# -- 4-process token-exact serving ----------------------------------------------

_FLEET_WORKER = textwrap.dedent("""
    import faulthandler, os, sys
    faulthandler.dump_traceback_later(400, exit=True)  # post-mortem on hang
    sys.path.insert(0, {repo!r})
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from gofr_tpu.container import new_mock_container
    from gofr_tpu.models import ModelSpec
    from gofr_tpu.testutil import greedy_reference, tiny_f32_llama
    from gofr_tpu.tpu.engine import build_engine

    role = sys.argv[1]
    conf = {{"TPU_MESH": "dp:2,tp:2", "ENGINE_KV_LAYOUT": "slot"}}
    if role == "leader":
        conf["FLEET_LISTEN"] = "{port}"
        conf["FLEET_FOLLOWERS"] = "3"
    else:
        conf["FLEET_LEADER"] = "127.0.0.1:{port}"
    c = new_mock_container(conf)
    cfg, _ = tiny_f32_llama()
    eng = build_engine(ModelSpec("llama", cfg, task="generate"), c, seed=3,
                       slots=2, max_len=64, max_prefill_batch=1,
                       prefill_buckets=[16], decode_chunk=4)
    assert eng.lockstep_role == role, eng.lockstep_role

    if role == "leader":
        assert eng._ls.follower_count() == 3
        from gofr_tpu.models import llama
        ref = greedy_reference(cfg, llama.init(cfg, jax.random.key(3)))
        prompts = [[3, 7, 11], [5, 2, 9, 4]]
        try:
            outs = [eng.generate(p, max_new_tokens=5, timeout=240) for p in prompts]
            for p, o in zip(prompts, outs):
                want = ref(p, 5)
                assert o["tokens"] == want, (o["tokens"], want)
            prev = np.asarray(eng._prev_last).tolist()
        finally:
            eng.stop()
        print("FLEET_PREV", prev, flush=True)
        print("FLEET_OK leader served token-exact to 3 followers, epoch",
              eng._ls.epoch, flush=True)
    else:
        eng.serve_follower()
        assert eng._prev_last is not None, "follower never replayed a live decode"
        print("FLEET_PREV", np.asarray(eng._prev_last).tolist(), flush=True)
        print("FLEET_OK follower drained and exited on stop", flush=True)
""")


def _run_workers(src: str, roles: list[str], tmp_path, timeout: float,
                 extra_env: dict | None = None):
    env = child_env()
    env.pop("XLA_FLAGS", None)
    env.pop("GOFR_CHAOS", None)
    logs = [open(tmp_path / f"{role}{i}.log", "w+") for i, role in enumerate(roles)]
    procs = []
    for i, role in enumerate(roles):
        penv = dict(env)
        if extra_env and role in extra_env:
            penv.update(extra_env[role])
        procs.append(subprocess.Popen([sys.executable, "-c", src, role],
                                      env=penv, stdout=logs[i],
                                      stderr=subprocess.STDOUT, text=True))

    def slurp():
        out = []
        for f in logs:
            f.flush()
            f.seek(0)
            out.append(f.read())
        return out

    try:
        for p in procs:
            p.wait(timeout=timeout)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail(f"fleet workers hung:\n{chr(10).join(slurp())[-6000:]}")
    finally:
        outs = slurp()
        for f in logs:
            f.close()
    return procs, outs


def test_four_process_fleet_token_exact(tmp_path):
    """1 leader + 3 followers, each a full replica on its own 2-axis
    (dp:2,tp:2) virtual-CPU mesh, lockstepped over the host-side announce
    channel: the leader serves token-exact vs the single-device greedy
    reference, every follower replays to the IDENTICAL device-resident
    decode carry, and stop() drains the whole fleet cleanly."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    src = _FLEET_WORKER.format(repo=repo, port=_free_port())
    roles = ["leader", "follower", "follower", "follower"]
    procs, outs = _run_workers(src, roles, tmp_path, timeout=420)
    for role, p, out in zip(roles, procs, outs):
        assert p.returncode == 0, f"{role} failed:\n{out[-4000:]}"
        assert "FLEET_OK" in out, out[-4000:]
    prevs = {out.split("FLEET_PREV", 1)[1].splitlines()[0].strip() for out in outs}
    assert len(prevs) == 1, f"decode carries diverged across the fleet: {prevs}"


# -- leader kill → supervised restart → epoch rejoin -----------------------------

_KILL_LEADER = textwrap.dedent("""
    import faulthandler, os, sys, time
    faulthandler.dump_traceback_later(400, exit=True)  # post-mortem on hang
    sys.path.insert(0, {repo!r})
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from gofr_tpu.container import new_mock_container
    from gofr_tpu.models import ModelSpec
    from gofr_tpu.testutil import greedy_reference, tiny_f32_llama
    from gofr_tpu.tpu.engine import build_engine

    role = sys.argv[1]
    conf = {{"TPU_MESH": "dp:2,tp:2", "ENGINE_KV_LAYOUT": "slot"}}
    if role == "leader":
        conf["FLEET_LISTEN"] = "{port}"
        conf["FLEET_FOLLOWERS"] = "1"
    else:
        conf["FLEET_LEADER"] = "127.0.0.1:{port}"
    c = new_mock_container(conf)
    cfg, _ = tiny_f32_llama()
    eng = build_engine(ModelSpec("llama", cfg, task="generate"), c, seed=3,
                       slots=1, max_len=64, max_prefill_batch=1,
                       prefill_buckets=[16], decode_chunk=4)

    if role == "leader":
        # GOFR_CHAOS (set by the test): kill the device loop once the step
        # counter reaches 2 — request A is mid-decode (mid-STREAM), B and C
        # are still queued — and HOLD the restart window open on the latch
        # file so DEGRADED health and the follower rejoin are observable
        # without any sleep-based synchronization.
        prompts = [[3, 7, 11], [5, 2, 9, 4], [2, 8]]
        reqs = [eng.submit(p, max_new_tokens=6, timeout=240) for p in prompts]

        deadline = time.monotonic() + 120
        while eng.health_check()["status"] != "DEGRADED":
            assert time.monotonic() < deadline, "never saw DEGRADED"
            time.sleep(0.005)
        # the follower saw our dropped connection and redialed into the
        # pending set; only THEN release the restart hold, so the first
        # loop iteration of the new life admits it at the bumped epoch
        while not eng._ls.has_pending():
            assert time.monotonic() < deadline, "follower never redialed"
            time.sleep(0.005)
        assert eng.health_check()["status"] == "DEGRADED"
        open({latch!r}, "w").close()

        # in-flight request A rode the killed device loop: fails fast with
        # the injected fault; queued B and C survive the restart and
        # complete token-exact at the NEW epoch
        try:
            reqs[0].result(240)
            raise AssertionError("in-flight request survived the device-loop kill")
        except RuntimeError as e:
            assert type(e).__name__ == "ChaosFault", repr(e)
        from gofr_tpu.models import llama
        ref = greedy_reference(cfg, llama.init(cfg, jax.random.key(3)))
        for p, r in zip(prompts[1:], reqs[1:]):
            out = r.result(240)
            want = ref(p, 6)
            assert out["tokens"] == want, (out["tokens"], want)
        assert eng.health_check()["status"] == "UP"  # DEGRADED only during the window
        assert eng._ls.epoch == 1, eng._ls.epoch     # exactly one rejoin bump
        assert eng._ls.follower_count() == 1
        prev = np.asarray(eng._prev_last).tolist()
        eng.stop()
        print("FLEET_PREV", prev, flush=True)
        print("KILL_OK leader restarted, follower rejoined at epoch 1, "
              "queued requests finished token-exact", flush=True)
    else:
        eng.serve_follower()  # EOF -> redial -> TAG_EPOCH 1 -> replay -> STOP
        assert eng._prev_last is not None, "follower never replayed a live decode"
        print("FLEET_PREV", np.asarray(eng._prev_last).tolist(), flush=True)
        print("KILL_OK follower rejoined and drained cleanly", flush=True)
""")


def test_leader_kill_supervised_restart_epoch_rejoin(tmp_path):
    """The VERDICT #4 drill, as a test: chaos kills the leader's device
    loop mid-generation under load. The supervised restart recovers it —
    in-flight work fails fast, queued work survives and completes
    token-exact, health is DEGRADED exactly during the (latch-held)
    restart window — and the follower rejoins at a new fleet epoch instead
    of exiting 17 (no fleet death)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    latch = str(tmp_path / "release-restart")
    src = _KILL_LEADER.format(repo=repo, port=_free_port(), latch=latch)
    chaos_env = {"leader": {"GOFR_CHAOS":
                            f"engine.step:raise,at_step=2;engine.restart:hold,file={latch},timeout=120"}}
    procs, outs = _run_workers(src, ["leader", "follower"], tmp_path,
                               timeout=420, extra_env=chaos_env)
    for role, p, out in zip(["leader", "follower"], procs, outs):
        assert p.returncode == 0, f"{role} failed (exit {p.returncode}):\n{out[-4000:]}"
        assert "KILL_OK" in out, out[-4000:]
    prevs = {out.split("FLEET_PREV", 1)[1].splitlines()[0].strip() for out in outs}
    assert len(prevs) == 1, f"decode carries diverged after the rejoin: {prevs}"
