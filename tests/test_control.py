"""Online step-controller suite (gofr_tpu.control): the perf plane
closed into actuation.

Three layers, cheapest first:

- **hysteresis core** — the flap-damping state machine extracted from the
  PR 11 ScaleDecider, driven entirely on fake clocks (sustain, per-
  direction cooldown anchored on executed actions, band behavior, stale
  freeze), plus the structural proof that ScaleDecider now delegates to
  the SAME machine instead of a parallel reimplementation.
- **controller units** — StepController with injected windows/clock/
  knobs: the try→judge→commit trial loop, worsening-move revert with
  doubling backoff, a→b→a oscillation freeze, lockstep stand-down,
  evidence starvation accumulating across ticks, and the autotune-style
  pin persistence (versioned JSON, corrupt file tolerance, read-merge-
  write preserving foreign keys, resume-from-pin on restart).
- **engine seams** — the live-knob contract on a real (tiny, CPU)
  engine: request_knobs clamps to the boot envelope, spec_tokens swaps
  the per-g compiled handle, and — the drill that matters — flipping
  every knob MID-STREAM never changes a single emitted token versus an
  untouched engine, because knobs only move work placement, never the
  sampled distribution. CONTROL_ENABLE=0 constructs no controller at
  all (the quality-plane off-path discipline).

A metric-registration lint rides along (satellite): every literal metric
name the package records must be registered somewhere, so a typo'd
increment_counter can no longer vanish into the registry's silent-drop
path.
"""

import json
import re
import pathlib

import pytest

from gofr_tpu.control.controller import (
    ControlPolicy,
    FORMAT_VERSION,
    KnobSpec,
    StepController,
    entry_key,
)
from gofr_tpu.control.hysteresis import HysteresisGate

pytestmark = pytest.mark.quick

REPO = pathlib.Path(__file__).resolve().parents[1]


# -- hysteresis core -----------------------------------------------------------


def make_gate(**kw):
    kw.setdefault("sustain_s", 2.0)
    kw.setdefault("idle_s", 4.0)
    kw.setdefault("cooldown_hot_s", 3.0)
    kw.setdefault("cooldown_calm_s", 5.0)
    kw.setdefault("stale_s", 60.0)
    return HysteresisGate(**kw)


class TestHysteresisGate:
    def test_hot_requires_sustain(self):
        g = make_gate()
        assert g.decide(hot=True, calm=False, now=0.0) == "hold"
        assert g.decide(hot=True, calm=False, now=1.9) == "hold"
        assert g.decide(hot=True, calm=False, now=2.0) == "hot"

    def test_blip_resets_the_streak(self):
        g = make_gate()
        g.decide(hot=True, calm=False, now=0.0)
        # one calm reading restarts the pressure clock
        g.decide(hot=False, calm=True, now=1.0)
        assert g.decide(hot=True, calm=False, now=2.0) == "hold"
        assert g.decide(hot=True, calm=False, now=4.0) == "hot"

    def test_band_accumulates_neither(self):
        g = make_gate()
        g.decide(hot=True, calm=False, now=0.0)
        g.decide(hot=False, calm=False, now=1.0)  # inside the band
        # pressure restarted: 2s of fresh sustain needed again
        assert g.decide(hot=True, calm=False, now=2.5) == "hold"

    def test_cooldown_anchors_on_note_action(self):
        g = make_gate()
        g.decide(hot=True, calm=False, now=0.0)
        assert g.decide(hot=True, calm=False, now=2.0) == "hot"
        g.note_action(2.0)
        # sustained again, but inside the 3s cooldown from the ACTION
        assert g.decide(hot=True, calm=False, now=2.5) == "hold"
        assert g.decide(hot=True, calm=False, now=4.9) == "hold"
        assert g.decide(hot=True, calm=False, now=5.0) == "hot"

    def test_calm_uses_idle_and_its_own_cooldown(self):
        g = make_gate()
        g.note_action(0.0)
        g.decide(hot=False, calm=True, now=1.0)
        # idle satisfied at 5.0 but calm cooldown (5s) holds until then too
        assert g.decide(hot=False, calm=True, now=4.9) == "hold"
        assert g.decide(hot=False, calm=True, now=5.0) == "calm"

    def test_stale_freezes_and_forgets(self):
        g = make_gate()
        g.decide(hot=True, calm=False, now=0.0)
        assert g.decide(hot=True, calm=False, now=1.0, age_s=61.0) == "freeze"
        # the streak did not survive the signal gap
        assert g.decide(hot=True, calm=False, now=2.0) == "hold"
        assert g.decide(hot=True, calm=False, now=4.0) == "hot"

    def test_scale_decider_delegates_to_the_shared_gate(self):
        """PR 11's decider and the step controller must damp oscillation
        with ONE state machine — the extraction is only real if the
        decider actually holds a HysteresisGate."""
        from gofr_tpu.fleet.autoscaler import AutoscalePolicy, ScaleDecider

        d = ScaleDecider(AutoscalePolicy())
        assert isinstance(d._gate, HysteresisGate)
        src = (REPO / "gofr_tpu" / "fleet" / "autoscaler.py").read_text()
        assert "HysteresisGate" in src


# -- controller units ----------------------------------------------------------


def win(score: float, *, steps: int = 10, band: str = "hi",
        bubble_ratio: float = 0.0) -> dict:
    """A synthetic band_totals payload whose _summarize score is exactly
    ``score`` (attainment = score / (1 - bubble_ratio), caps = 1)."""
    attain = score / (1.0 - bubble_ratio)
    busy = 1.0
    bubble = bubble_ratio * busy / (1.0 - bubble_ratio)
    return {f"decode|bf16|{band}": {
        "flops": attain, "bytes": 0.0, "device_s": busy,
        "steps": float(steps), "bubble_s": bubble,
        "flops_cap": 1.0, "bytes_cap": 1.0,
    }}


class ValueKnob:
    def __init__(self, name, values, value):
        self.value = value
        self.applied = []
        self.spec = KnobSpec(name, tuple(values), self._read, self._apply)

    def _read(self):
        return self.value

    def _apply(self, v):
        self.value = v
        self.applied.append(v)


def make_ctl(windows, *, knob=None, standdown=None, cache="", **policy_kw):
    """Fake-clock controller: ``windows`` is a list consumed one per tick
    (the last entry repeats); tick it with explicit `now` values."""
    policy_kw.setdefault("interval_s", 1.0)
    policy_kw.setdefault("sustain_s", 1.0)
    policy_kw.setdefault("idle_s", 100.0)
    policy_kw.setdefault("cooldown_s", 1.0)
    policy_kw.setdefault("stale_s", 1000.0)
    policy_kw.setdefault("min_steps", 2)
    policy_kw.setdefault("backoff_s", 10.0)
    policy_kw.setdefault("cache_path", cache)
    policy_kw.setdefault("knobs", ("pipeline_depth",))
    knob = knob or ValueKnob("pipeline_depth", (1, 2, 3), 1)
    seen_since = []

    def window_fn(now, since):
        seen_since.append(since)
        w = windows.pop(0) if len(windows) > 1 else windows[0]
        return w

    ctl = StepController(
        ControlPolicy(**policy_kw), [knob.spec],
        window_fn=window_fn, standdown_fn=standdown, clock=lambda: 0.0)
    ctl._seen_since = seen_since  # test hook
    return ctl, knob


class TestStepController:
    def test_hot_window_proposes_then_commits_and_pins(self):
        ctl, knob = make_ctl([win(0.10), win(0.10), win(0.20)])
        assert ctl.maybe_tick(1.0) is None          # sustain pending
        d = ctl.maybe_tick(2.0)
        assert d.verdict == "try" and d.frm == 1 and d.to == 2
        assert knob.value == 2
        d = ctl.maybe_tick(3.0)                      # judged: 0.20 >= 0.10*1.03
        assert d.verdict == "commit" and d.score > d.baseline
        assert knob.value == 2
        assert ctl.pin_for("pipeline_depth", "hi") == 2

    def test_worsening_move_reverts_and_backs_off(self):
        ctl, knob = make_ctl([win(0.20), win(0.20), win(0.10)])
        ctl.maybe_tick(1.0)
        assert ctl.maybe_tick(2.0).verdict == "try"
        d = ctl.maybe_tick(3.0)                      # 0.10 < 0.20*1.03
        assert d.verdict == "revert"
        assert knob.value == 1                       # restored
        # +1 is backed off for backoff_s and -1 has no neighbor from the
        # bottom value: sustained pressure proposes NOTHING until 13.0
        for t in (5.0, 8.0, 12.0):
            assert ctl.maybe_tick(t) is None
        tries = [d for d in ctl.decisions if d.verdict == "try"]
        assert len(tries) == 1

    def test_backoff_doubles_per_direction(self):
        ctl, knob = make_ctl([win(0.20), win(0.20), win(0.10)],
                             backoff_s=2.0, backoff_cap_s=3.0)
        ctl.maybe_tick(1.0)
        ctl.maybe_tick(2.0)
        assert ctl.maybe_tick(3.0).verdict == "revert"
        until, delay = ctl._backoff[("pipeline_depth", 1)]
        assert until == 5.0 and delay == 3.0         # doubled 2->4, capped 3

    def test_oscillating_commits_freeze_the_knob(self):
        knob = ValueKnob("pipeline_depth", (1, 2), 1)
        # scores climb 4% (> epsilon) every window, so every trial commits:
        # the knob ping-pongs 1->2->1->2 and the a->b->a history freezes it
        scores = [win(0.10 * (1.04 ** i)) for i in range(12)]
        ctl, knob = make_ctl(scores, knob=knob)
        t = 0.0
        while not ctl.oscillating and t < 40.0:
            t += 1.0
            ctl.maybe_tick(t)
        assert ctl.oscillating, "a->b->a commits never flagged"
        assert "pipeline_depth" in ctl._frozen
        commits = [d.to for d in ctl.decisions if d.verdict == "commit"]
        assert commits[-3:] in ([2, 1, 2], [1, 2, 1])
        # frozen: sustained pressure proposes nothing ever again
        n_tries = sum(1 for d in ctl.decisions if d.verdict == "try")
        for dt in range(1, 10):
            ctl.maybe_tick(t + dt)
        assert sum(1 for d in ctl.decisions if d.verdict == "try") == n_tries

    def test_standdown_parks_with_one_decision(self):
        ctl, _ = make_ctl([win(0.10)], standdown=lambda: "lockstep")
        d = ctl.maybe_tick(1.0)
        assert d.verdict == "standdown" and d.reason == "lockstep"
        assert ctl.standdown == "lockstep"
        for t in (2.0, 3.0, 4.0):
            assert ctl.maybe_tick(t) is None         # parked, not spamming
        assert ctl.report()["standdown"] == "lockstep"

    def test_starved_window_accumulates_instead_of_discarding(self):
        ctl, _ = make_ctl([win(0.10, steps=1), win(0.10, steps=1),
                           win(0.10)], min_steps=5)
        assert ctl.maybe_tick(1.0) is None
        assert ctl.maybe_tick(2.0) is None
        ctl.maybe_tick(3.0)
        # every starved tick re-read from the ORIGINAL window start — the
        # evidence accumulated rather than being thrown away per tick
        assert ctl._seen_since == [0.0, 0.0, 0.0]

    def test_trial_without_evidence_reverts_unjudged(self):
        ctl, knob = make_ctl([win(0.10), win(0.10), win(0.10, steps=0)],
                             max_trial_ticks=2)
        ctl.maybe_tick(1.0)
        assert ctl.maybe_tick(2.0).verdict == "try"
        assert ctl.maybe_tick(3.0) is None           # starved tick 1
        d = ctl.maybe_tick(4.0)                      # starved tick 2: abort
        assert d.verdict == "revert" and d.reason == "no-evidence"
        assert knob.value == 1

    def test_persistence_roundtrip_resume_and_foreign_keys(self, tmp_path):
        cache = str(tmp_path / "control.json")
        # a foreign replica's pin must survive our read-merge-write
        foreign = entry_key("pipeline_depth", "hi", kv_dtype="int8",
                            device_kind="v5e", shard="tp4")
        (tmp_path / "control.json").write_text(json.dumps({
            "version": FORMAT_VERSION,
            "entries": {foreign: {"value": 3, "at": 0, "score": 0.5}}}))
        ctl, knob = make_ctl([win(0.10), win(0.10), win(0.20)], cache=cache)
        ctl.maybe_tick(1.0)
        ctl.maybe_tick(2.0)
        assert ctl.maybe_tick(3.0).verdict == "commit"
        data = json.loads((tmp_path / "control.json").read_text())
        assert data["version"] == FORMAT_VERSION
        assert data["entries"][foreign]["value"] == 3   # preserved
        ours = entry_key("pipeline_depth", "hi", kv_dtype="bf16",
                         device_kind="cpu", shard="tp1")
        assert data["entries"][ours]["value"] == 2
        # a fresh controller (restart) resumes from the pin without a trial
        knob2 = ValueKnob("pipeline_depth", (1, 2, 3), 1)
        ctl2, knob2 = make_ctl([win(0.10)], knob=knob2, cache=cache)
        d = ctl2.maybe_tick(1.0)
        assert d.verdict == "resume" and d.to == 2
        assert knob2.value == 2

    def test_corrupt_or_missing_cache_is_empty(self, tmp_path):
        bad = tmp_path / "bad.json"
        bad.write_text("{not json")
        ctl, _ = make_ctl([win(0.10)], cache=str(bad))
        assert ctl._pins == {}
        wrong = tmp_path / "wrong.json"
        wrong.write_text(json.dumps({"version": 999, "entries": {"k": 1}}))
        ctl, _ = make_ctl([win(0.10)], cache=str(wrong))
        assert ctl._pins == {}

    def test_summarize_math(self):
        ev = StepController._summarize({
            "decode|bf16|hi": {"flops": 3.0, "bytes": 1.0, "device_s": 2.0,
                               "steps": 4, "bubble_s": 0.5,
                               "flops_cap": 10.0, "bytes_cap": 10.0},
            "prefill|bf16|lo": {"flops": 1.0, "bytes": 7.0, "device_s": 0.5,
                                "steps": 2, "bubble_s": 0.0,
                                "flops_cap": 10.0, "bytes_cap": 10.0},
        })
        assert ev["steps"] == 6
        assert ev["attainment"] == pytest.approx(0.4)   # bytes side wins
        assert ev["bubble_ratio"] == pytest.approx(0.5 / 3.0)
        assert ev["band"] == "hi"                       # by device_s share
        assert ev["score"] == pytest.approx(0.4 * (1 - 0.5 / 3.0))

    def test_neighbor_snaps_out_of_range_current(self):
        spec = KnobSpec("k", (16, 32, 64), lambda: 0, lambda v: None)
        # a current value outside the list snaps to the nearest member —
        # the snap IS the proposed move, regardless of direction
        assert spec.neighbor(48, 1) in (32, 64)
        assert spec.neighbor(20, 1) == 16
        assert spec.neighbor(16, -1) is None
        assert spec.neighbor(64, 1) is None
        assert spec.neighbor(32, 1) == 64 and spec.neighbor(32, -1) == 16

    def test_policy_rejects_inverted_bands(self):
        with pytest.raises(ValueError):
            ControlPolicy(bubble_lo=0.5, bubble_hi=0.1)
        with pytest.raises(ValueError):
            ControlPolicy(attain_lo=0.8, attain_hi=0.4)
        with pytest.raises(ValueError):
            ControlPolicy(interval_s=0.0)


# -- band-labeled perf evidence ------------------------------------------------


class TestBandEvidence:
    def test_occupancy_band_edges(self):
        from gofr_tpu.metrics.perf import occupancy_band

        assert occupancy_band(None) == "lo"
        assert occupancy_band(0.0) == "lo"
        assert occupancy_band(0.34) == "lo"
        assert occupancy_band(0.35) == "mid"
        assert occupancy_band(0.69) == "mid"
        assert occupancy_band(0.70) == "hi"
        assert occupancy_band(1.0) == "hi"

    def test_band_totals_keys_and_since_delta(self):
        from gofr_tpu.metrics.perf import CostModel, PerfPlane

        plane = PerfPlane(CostModel(
            n_params=1e6, weight_bytes=2e6, kv_bytes_per_pos=16.0,
            page_bytes=0.0, page_size=0, kv_dtype="bf16", kv_shards=1),
            "cpu", window_s=60.0)
        s1 = plane.step("decode", 1e9, 1e6, 100.0)
        s1.t_ready = 100.5
        plane.note(s1, 100.5, band="hi")
        s2 = plane.step("decode", 2e9, 2e6, 101.0)
        s2.t_ready = 102.0
        plane.note(s2, 102.0, band="lo")
        bands = plane.band_totals(102.0)
        assert set(bands) == {"decode|bf16|hi", "decode|bf16|lo"}
        hi = bands["decode|bf16|hi"]
        assert hi["steps"] == 1 and hi["flops"] == pytest.approx(1e9)
        # capacity denominators priced from the device peaks x busy time
        assert hi["flops_cap"] > 0 and hi["bytes_cap"] > 0
        # `since` restricts to buckets after the cut: only s2 remains
        later = plane.band_totals(102.0, since=101.0)
        assert "decode|bf16|hi" not in later
        assert later["decode|bf16|lo"]["steps"] == 1
        # unbanded window_totals must not double-count the band rows
        kinds = plane.window_totals(102.0)["kinds"]
        assert kinds["decode|bf16"]["steps"] == 2
        assert not any(k.startswith("bd.") for k in kinds)


# -- engine seams (tiny CPU engine) --------------------------------------------


@pytest.fixture(scope="module")
def tiny():
    import jax

    from gofr_tpu.models import LlamaConfig, llama

    cfg = LlamaConfig.tiny()
    params = llama.init(cfg, jax.random.key(7))
    return cfg, params


def make_engine(tiny, **kw):
    from gofr_tpu.container import new_mock_container
    from gofr_tpu.models import llama
    from gofr_tpu.tpu.engine import GenerateEngine

    cfg, params = tiny
    conf = kw.pop("conf", None)
    kw.setdefault("slots", 4)
    kw.setdefault("max_len", 96)
    kw.setdefault("max_prefill_batch", 2)
    kw.setdefault("decode_chunk", 2)
    kw.setdefault("prefill_buckets", [16, 32, 48])
    return GenerateEngine(llama, cfg, params, new_mock_container(conf), **kw)


class TestEngineKnobSeams:
    def test_apply_clamps_to_boot_envelope(self, tiny):
        eng = make_engine(tiny, pipeline_depth=2, spec_tokens=2,
                          kv_layout="paged", page_size=8)
        try:
            assert eng.knob_vector() == {
                "pipeline_depth": 2, "prefill_chunk": 48,
                "prefill_batch": 2, "spec_tokens": 2}
            eng.request_knobs(pipeline_depth=4, prefill_batch=9,
                              spec_tokens=7)
            eng._apply_pending_knobs()
            # every move clamped to the operator's boot ceiling
            assert eng.pipeline_depth == 2
            assert eng.max_prefill_batch == 2
            assert eng.spec_tokens == 2
            # prefill_chunk snaps DOWN to a bucket member
            eng.request_knobs(prefill_chunk=40)
            eng._apply_pending_knobs()
            assert eng.prefill_chunk == 32
            eng.request_knobs(prefill_chunk=1)
            eng._apply_pending_knobs()
            assert eng.prefill_chunk == 16
            # an unknown knob is logged and dropped, never raises
            eng.request_knobs(warp_factor=9)
            eng._apply_pending_knobs()
        finally:
            eng.stop()

    def test_spec_g_change_swaps_compiled_handle(self, tiny):
        eng = make_engine(tiny, spec_tokens=2, kv_layout="paged",
                          page_size=8)
        try:
            boot_fn = eng._spec_chunk_fn
            assert set(eng._spec_fns) == {2}
            eng.request_knobs(spec_tokens=1)
            eng._apply_pending_knobs()
            assert eng.spec_tokens == 1
            assert eng._spec_chunk_fn is not boot_fn
            assert set(eng._spec_fns) == {1, 2}
            # back up: the per-g map caches, no rebuild
            fn1 = eng._spec_fns[1]
            eng.request_knobs(spec_tokens=2)
            eng._apply_pending_knobs()
            assert eng._spec_chunk_fn is boot_fn
            assert eng._spec_fns[1] is fn1
            # the cache-slack span stays at the BOOT worst case
            assert eng._chunk_span == eng.decode_chunk * 3 + 2
        finally:
            eng.stop()

    def test_spec_knob_rejected_when_spec_off_at_boot(self, tiny):
        eng = make_engine(tiny)
        try:
            eng.request_knobs(spec_tokens=2)
            eng._apply_pending_knobs()  # logged, not applied, not raised
            assert eng.spec_tokens == 0
            assert "spec_tokens" not in eng.knob_vector()
        finally:
            eng.stop()

    def test_control_enable_off_constructs_nothing(self, tiny):
        eng = make_engine(tiny)
        try:
            assert eng._control is None
            rep = eng.control_report()
            assert rep["enabled"] is False and "knobs" in rep
        finally:
            eng.stop()

    def test_control_enable_builds_wired_controller(self, tiny):
        eng = make_engine(tiny, control_enable=True, spec_tokens=2,
                          kv_layout="paged", page_size=8,
                          conf={"CONTROL_INTERVAL_S": "0.5"})
        try:
            assert eng._control is not None
            rep = eng.control_report()
            assert rep["enabled"] is True
            assert set(rep["knobs"]) == {"pipeline_depth", "prefill_chunk",
                                         "prefill_batch", "spec_tokens"}
            # allowed ranges are the boot envelope
            assert rep["knobs"]["pipeline_depth"]["allowed"] == [1, 2]
            assert rep["knobs"]["spec_tokens"]["allowed"] == [1, 2]
            assert rep["knobs"]["prefill_chunk"]["allowed"] == [16, 32, 48]
            assert eng._control.policy.interval_s == 0.5
        finally:
            eng.stop()

    def test_lockstep_role_stands_the_controller_down(self, tiny):
        eng = make_engine(tiny, control_enable=True)
        try:
            assert eng._control is not None
            eng.lockstep_role = "leader"  # runtime role flip
            d = eng._control.maybe_tick(100.0)
            assert d is not None and d.verdict == "standdown"
            assert eng._control.standdown == "lockstep"
        finally:
            eng.lockstep_role = None
            eng.stop()

    def test_midstream_knob_flips_are_token_exact(self, tiny):
        """THE drill: flip every live knob while requests are decoding and
        prefilling; the emitted tokens must be identical to an untouched
        engine's — knobs move work placement, never the distribution."""
        import numpy as np

        rng = np.random.RandomState(3)
        prompts = [rng.randint(1, tiny[0].vocab_size,
                               size=rng.randint(8, 40)).tolist()
                   for _ in range(10)]
        kw = dict(pipeline_depth=2, spec_tokens=2, kv_layout="paged",
                  page_size=8)

        def run(flip: bool) -> list:
            eng = make_engine(tiny, **kw)
            try:
                reqs = []
                for i, p in enumerate(prompts):
                    reqs.append(eng.submit(p, max_new_tokens=12, timeout=60))
                    if flip and i == 2:
                        eng.request_knobs(prefill_chunk=16, spec_tokens=1,
                                          pipeline_depth=1, prefill_batch=1)
                    if flip and i == 6:
                        eng.request_knobs(prefill_chunk=48, spec_tokens=2,
                                          pipeline_depth=2, prefill_batch=2)
                return [r.result(60)["tokens"] for r in reqs]
            finally:
                eng.stop()

        assert run(True) == run(False)


# -- metric-registration lint (satellite) --------------------------------------


def test_every_recorded_metric_literal_is_registered():
    """The registry silently drops writes to unregistered names — correct
    for optional planes, but it means a typo'd name vanishes without a
    trace. Lint the package: every literal name passed to a record call
    must appear in some registration call."""
    record_re = re.compile(
        r"(?:increment_counter|set_gauge|record_histogram)\(\s*\n?\s*"
        r"[\"']([a-z0-9_]+)[\"']")
    register_re = re.compile(
        r"(?:new_counter|new_updown_counter|new_gauge|new_histogram)\(\s*\n?\s*"
        r"[\"']([a-z0-9_]+)[\"']")
    recorded: dict[str, set] = {}
    registered: set = set()
    for p in (REPO / "gofr_tpu").rglob("*.py"):
        text = p.read_text(errors="ignore")
        for m in record_re.finditer(text):
            recorded.setdefault(m.group(1), set()).add(
                str(p.relative_to(REPO)))
        registered.update(m.group(1) for m in register_re.finditer(text))
    assert registered, "registration scan found nothing — regex rotted?"
    missing = {name: sorted(files) for name, files in sorted(recorded.items())
               if name not in registered}
    assert not missing, (
        f"metric names recorded but never registered (writes are silently "
        f"dropped): {missing}")
    # the controller family is registered (satellite acceptance)
    for name in ("app_tpu_control_decisions_total", "app_tpu_control_knob",
                 "app_tpu_control_active"):
        assert name in registered, f"{name} not registered in the container"
