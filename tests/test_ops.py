"""Numerics tests for gofr_tpu.ops against naive reference implementations."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gofr_tpu.ops import (
    SlotKVCache,
    apply_rope,
    decode_attention,
    layer_norm,
    mha_attention,
    rms_norm,
    rope_table,
    sample_token,
)
from gofr_tpu.ops.kvcache import append_tokens, write_prompt


def naive_attention(q, k, v, causal=True, kv_len=None, q_offset=0):
    """Slow per-head reference: q [S,H,D], k/v [T,Hkv,D]."""
    s, h, d = q.shape
    t, hkv, _ = k.shape
    group = h // hkv
    out = np.zeros((s, h, d), np.float32)
    for i in range(h):
        kk, vv = k[:, i // group].astype(np.float32), v[:, i // group].astype(np.float32)
        scores = q[:, i].astype(np.float32) @ kk.T / np.sqrt(d)
        for a in range(s):
            for b in range(t):
                if causal and a + q_offset < b:
                    scores[a, b] = -np.inf
                if kv_len is not None and b >= kv_len:
                    scores[a, b] = -np.inf
        probs = np.exp(scores - scores.max(-1, keepdims=True))
        probs /= probs.sum(-1, keepdims=True)
        out[:, i] = probs @ vv
    return out


class TestNorms:
    def test_rms_norm(self):
        x = jax.random.normal(jax.random.key(0), (2, 5, 16))
        w = jax.random.normal(jax.random.key(1), (16,)) + 1.0
        got = rms_norm(x, w)
        xf = np.asarray(x, np.float64)
        want = xf / np.sqrt((xf**2).mean(-1, keepdims=True) + 1e-6) * np.asarray(w)
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4, atol=1e-5)

    def test_rms_norm_bf16_computes_in_f32(self):
        x = (jax.random.normal(jax.random.key(0), (4, 64)) * 100).astype(jnp.bfloat16)
        w = jnp.ones((64,), jnp.bfloat16)
        got = rms_norm(x, w)
        assert got.dtype == jnp.bfloat16
        assert bool(jnp.all(jnp.isfinite(got.astype(jnp.float32))))

    def test_layer_norm(self):
        x = jax.random.normal(jax.random.key(0), (3, 8))
        w, b = jnp.ones((8,)) * 2, jnp.ones((8,)) * 0.5
        got = np.asarray(layer_norm(x, w, b))
        xf = np.asarray(x, np.float64)
        normed = (xf - xf.mean(-1, keepdims=True)) / np.sqrt(xf.var(-1, keepdims=True) + 1e-12)
        np.testing.assert_allclose(got, normed * 2 + 0.5, rtol=1e-4, atol=1e-5)


class TestRope:
    def test_table_shapes(self):
        cos, sin = rope_table(32, 8)
        assert cos.shape == (32, 4) and sin.shape == (32, 4)
        np.testing.assert_allclose(np.asarray(cos[0]), 1.0)
        np.testing.assert_allclose(np.asarray(sin[0]), 0.0)

    def test_rotation_preserves_norm(self):
        x = jax.random.normal(jax.random.key(0), (1, 6, 2, 8))
        cos, sin = rope_table(16, 8)
        pos = jnp.arange(6)[None]
        y = apply_rope(x, pos, cos, sin)
        np.testing.assert_allclose(
            np.linalg.norm(np.asarray(y), axis=-1),
            np.linalg.norm(np.asarray(x), axis=-1),
            rtol=1e-5,
        )

    def test_relative_property(self):
        """<rope(q,m), rope(k,n)> depends only on m-n."""
        cos, sin = rope_table(64, 8)
        q = jax.random.normal(jax.random.key(1), (1, 1, 1, 8))
        k = jax.random.normal(jax.random.key(2), (1, 1, 1, 8))

        def dot_at(m, n):
            qr = apply_rope(q, jnp.array([[m]]), cos, sin)
            kr = apply_rope(k, jnp.array([[n]]), cos, sin)
            return float(jnp.sum(qr * kr))

        assert dot_at(5, 3) == pytest.approx(dot_at(12, 10), rel=1e-5)
        assert dot_at(5, 3) != pytest.approx(dot_at(5, 4), rel=1e-3)

    def test_matches_hf_rotate_half(self):
        """Cross-check against the HF/Llama rotate_half formulation."""
        torch = pytest.importorskip("torch")
        head_dim, seq = 16, 7
        cos, sin = rope_table(32, head_dim)
        x = np.random.RandomState(0).randn(1, seq, 1, head_dim).astype(np.float32)
        got = apply_rope(jnp.asarray(x), jnp.arange(seq)[None], cos, sin)

        inv_freq = 1.0 / (10000 ** (np.arange(0, head_dim // 2) * 2 / head_dim))
        t = np.arange(seq)
        freqs = np.outer(t, inv_freq)
        emb = np.concatenate([freqs, freqs], -1)
        hf_cos, hf_sin = np.cos(emb), np.sin(emb)
        xt = x[0, :, 0]  # [seq, dim]
        rot = np.concatenate([-xt[:, head_dim // 2:], xt[:, : head_dim // 2]], -1)
        want = xt * hf_cos + rot * hf_sin
        np.testing.assert_allclose(np.asarray(got)[0, :, 0], want, rtol=1e-4, atol=1e-5)


class TestAttention:
    def test_causal_matches_naive(self):
        key = jax.random.key(0)
        q = jax.random.normal(key, (2, 5, 4, 8))
        k = jax.random.normal(jax.random.key(1), (2, 5, 4, 8))
        v = jax.random.normal(jax.random.key(2), (2, 5, 4, 8))
        got = np.asarray(mha_attention(q, k, v, causal=True))
        for b in range(2):
            want = naive_attention(np.asarray(q[b]), np.asarray(k[b]), np.asarray(v[b]))
            np.testing.assert_allclose(got[b], want, rtol=1e-4, atol=1e-5)

    def test_gqa_matches_naive(self):
        q = jax.random.normal(jax.random.key(0), (1, 6, 8, 4))
        k = jax.random.normal(jax.random.key(1), (1, 6, 2, 4))
        v = jax.random.normal(jax.random.key(2), (1, 6, 2, 4))
        got = np.asarray(mha_attention(q, k, v, causal=True))
        want = naive_attention(np.asarray(q[0]), np.asarray(k[0]), np.asarray(v[0]))
        np.testing.assert_allclose(got[0], want, rtol=1e-4, atol=1e-5)

    def test_kv_length_masking(self):
        q = jax.random.normal(jax.random.key(0), (2, 3, 2, 4))
        k = jax.random.normal(jax.random.key(1), (2, 8, 2, 4))
        v = jax.random.normal(jax.random.key(2), (2, 8, 2, 4))
        lengths = jnp.array([8, 4])
        got = np.asarray(mha_attention(q, k, v, causal=False, kv_lengths=lengths))
        # batch 1 must equal attention over only the first 4 kv positions
        want = naive_attention(
            np.asarray(q[1]), np.asarray(k[1]), np.asarray(v[1]), causal=False, kv_len=4
        )
        np.testing.assert_allclose(got[1], want, rtol=1e-4, atol=1e-5)

    def test_q_offset_chunked_prefill(self):
        """Attention over a chunk at offset t equals the tail of full attention."""
        q = jax.random.normal(jax.random.key(0), (1, 8, 2, 4))
        k = jax.random.normal(jax.random.key(1), (1, 8, 2, 4))
        v = jax.random.normal(jax.random.key(2), (1, 8, 2, 4))
        full = mha_attention(q, k, v, causal=True)
        chunk = mha_attention(q[:, 4:], k, v, causal=True, q_offset=4)
        np.testing.assert_allclose(np.asarray(chunk), np.asarray(full[:, 4:]), rtol=1e-4, atol=1e-5)
        # per-batch array offset too
        chunk2 = mha_attention(q[:, 4:], k, v, causal=True, q_offset=jnp.array([4]))
        np.testing.assert_allclose(np.asarray(chunk2), np.asarray(full[:, 4:]), rtol=1e-4, atol=1e-5)

    def test_fully_masked_rows_are_finite(self):
        q = jax.random.normal(jax.random.key(0), (1, 2, 1, 4))
        k = jax.random.normal(jax.random.key(1), (1, 4, 1, 4))
        v = jax.random.normal(jax.random.key(2), (1, 4, 1, 4))
        out = mha_attention(q, k, v, causal=False, kv_lengths=jnp.array([0]))
        assert bool(jnp.all(jnp.isfinite(out)))

    def test_decode_matches_prefill_last_row(self):
        s = 6
        q = jax.random.normal(jax.random.key(0), (1, s, 4, 8))
        k = jax.random.normal(jax.random.key(1), (1, s, 2, 8))
        v = jax.random.normal(jax.random.key(2), (1, s, 2, 8))
        full = mha_attention(q, k, v, causal=True)
        # head-major cache padded beyond the real length
        k_pad = jnp.pad(k.swapaxes(1, 2), ((0, 0), (0, 0), (0, 10), (0, 0)))
        v_pad = jnp.pad(v.swapaxes(1, 2), ((0, 0), (0, 0), (0, 10), (0, 0)))
        dec = decode_attention(q[:, -1], k_pad, v_pad, jnp.array([s]))
        np.testing.assert_allclose(np.asarray(dec), np.asarray(full[:, -1]), rtol=1e-4, atol=1e-5)


class TestKVCache:
    def test_create_shapes(self):
        c = SlotKVCache.create(layers=2, slots=3, max_len=16, kv_heads=2, head_dim=4)
        assert c.k.shape == (2, 3, 2, 16, 4)  # head-major: [L, B, Hkv, Smax, D]
        assert c.num_layers == 2 and c.num_slots == 3 and c.max_len == 16

    def test_write_prompt_and_append(self):
        c = SlotKVCache.create(1, 2, 8, 1, 4, dtype=jnp.float32)
        k_new = jnp.ones((3, 1, 4))  # [S, Hkv, D] activation layout
        v_new = jnp.full((3, 1, 4), 2.0)
        k_l, v_l = write_prompt(c.k[0], c.v[0], jnp.int32(1), k_new, v_new)
        np.testing.assert_array_equal(np.asarray(k_l[1, :, :3]), np.ones((1, 3, 4)))
        np.testing.assert_array_equal(np.asarray(k_l[0]), np.zeros((1, 8, 4)))
        # append one token per slot at different positions
        k_tok = jnp.full((2, 1, 4), 5.0)
        v_tok = jnp.full((2, 1, 4), 6.0)
        k_l, v_l = append_tokens(k_l, v_l, jnp.array([0, 3]), k_tok, v_tok)
        np.testing.assert_array_equal(np.asarray(k_l[0, :, 0]), np.full((1, 4), 5.0))
        np.testing.assert_array_equal(np.asarray(k_l[1, :, 3]), np.full((1, 4), 5.0))
        np.testing.assert_array_equal(np.asarray(v_l[1, :, 3]), np.full((1, 4), 6.0))


class TestSampling:
    def test_greedy(self):
        logits = jnp.array([[0.0, 5.0, 1.0], [9.0, 0.0, 0.0]])
        toks = sample_token(logits, jax.random.key(0), do_sample=False)
        np.testing.assert_array_equal(np.asarray(toks), [1, 0])

    def test_top_k_restricts_support(self):
        logits = jnp.array([[10.0, 9.0, -1.0, -2.0]] * 64)
        toks = sample_token(logits, jax.random.key(0), top_k=2, temperature=5.0)
        assert set(np.asarray(toks)) <= {0, 1}

    def test_top_p_restricts_support(self):
        logits = jnp.log(jnp.array([[0.6, 0.35, 0.04, 0.01]] * 64))
        toks = sample_token(logits, jax.random.key(1), top_p=0.9, temperature=1.0)
        assert set(np.asarray(toks)) <= {0, 1}

    def test_top_p_always_keeps_top1(self):
        logits = jnp.array([[3.0, 1.0, 0.0]] * 8)
        toks = sample_token(logits, jax.random.key(0), top_p=1e-9)
        np.testing.assert_array_equal(np.asarray(toks), [0] * 8)

    def test_scalar_zero_temperature_is_greedy(self):
        logits = jnp.array([[0.0, 5.0, 1.0], [9.0, 0.0, 0.0]])
        toks = sample_token(logits, jax.random.key(0), temperature=0.0)
        np.testing.assert_array_equal(np.asarray(toks), [1, 0])
        toks = sample_token(logits, jax.random.key(0), temperature=-1.0)
        np.testing.assert_array_equal(np.asarray(toks), [1, 0])

    def test_per_row_temperature_mixes_greedy_and_sampled(self):
        logits = jnp.array([[0.0, 5.0, 1.0]] * 4)
        temps = jnp.array([0.0, 0.0, 8.0, 8.0])
        toks = np.asarray(sample_token(logits, jax.random.key(2), temperature=temps))
        assert toks[0] == 1 and toks[1] == 1
        assert all(0 <= t < 3 for t in toks)

    def test_top_p_zero_degrades_to_greedy(self):
        logits = jnp.array([[3.0, 1.0, 0.0]] * 8)
        toks = sample_token(logits, jax.random.key(0), top_p=0.0)
        np.testing.assert_array_equal(np.asarray(toks), [0] * 8)

    def test_temperature_is_traced(self):
        """Same compiled fn serves different temperatures (no recompile)."""
        f = jax.jit(lambda lg, key, t: sample_token(lg, key, temperature=t))
        logits = jnp.array([[1.0, 2.0, 3.0]] * 4)
        _ = f(logits, jax.random.key(0), 1.0)
        n0 = f._cache_size()
        _ = f(logits, jax.random.key(0), 0.3)
        assert f._cache_size() == n0
