"""Packed-int4 KV pages (ISSUE 13 tentpole): the row primitives
(ops/quant.quantize_row_int4 / pack_int4 / unpack_int4 /
fake_quant_row_int4), the Q4PagedKVCache pool helpers, fused-kernel vs
gathered-XLA parity for ``paged_decode_attention_q4`` (interpret mode on
CPU), and engine-level plausibility: an int4 paged engine must serve
deterministically, keep its page accounting clean, and archive a pool
whose bytes-per-token are far below the int8 pool's. Token EXACTNESS vs
the dense reference is deliberately NOT asserted here — 4-bit KV error
flips greedy ties on tiny random-init models; exactness is the int8
suite's contract (tests/test_kv_quant.py) and int4-vs-int4 exactness is
the handoff suite's (tests/test_handoff.py::test_disagg_token_exact_int4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gofr_tpu.container import new_mock_container
from gofr_tpu.models import LlamaConfig, llama
from gofr_tpu.ops.paged import (
    Q4PagedKVCache,
    append_tokens_paged_q4,
    gather_kv_q4,
    write_prompts_paged_q4,
)
from gofr_tpu.ops.quant import (
    fake_quant_row_int4,
    pack_int4,
    quantize_row_int4,
    unpack_int4,
)
from gofr_tpu.tpu.engine import GenerateEngine

pytestmark = pytest.mark.quick


# -- row primitives ------------------------------------------------------------


def test_quantize_row_int4_bounds_and_error():
    """Symmetric per-row int4: levels stay in [-7, 7] and the round-trip
    error of every element is at most half a quantization step."""
    x = jax.random.normal(jax.random.key(0), (5, 3, 32), jnp.float32) * 4.0
    q, s = quantize_row_int4(x)
    assert q.dtype == jnp.int8 and s.shape == (5, 3)
    qn = np.asarray(q)
    assert qn.min() >= -7 and qn.max() <= 7
    err = np.abs(np.asarray(x) - qn * np.asarray(s)[..., None])
    assert (err <= np.asarray(s)[..., None] * 0.5 + 1e-6).all()


def test_pack_unpack_roundtrip_and_nibble_order():
    """pack_int4 is lossless over the full [-8, 7] range and uses the
    split-half order: byte j of a D-wide row holds elements j and
    j + D/2 (low/high nibble, +8 biased) — the layout the fused kernel's
    in-register unpack assumes."""
    q = jax.random.randint(jax.random.key(1), (4, 6, 16), -8, 8, jnp.int8)
    b = pack_int4(q)
    assert b.dtype == jnp.uint8 and b.shape == (4, 6, 8)
    np.testing.assert_array_equal(np.asarray(unpack_int4(b)), np.asarray(q))
    qn, bn = np.asarray(q), np.asarray(b)
    want = ((qn[..., :8] + 8) | ((qn[..., 8:] + 8) << 4)).astype(np.uint8)
    np.testing.assert_array_equal(bn, want)


def test_fake_quant_row_int4_matches_pool_roundtrip():
    """fake_quant_row_int4 IS the pool round-trip: quantize → pack →
    unpack → dequant with the pool's bf16 scale cast. The engine's
    reference paths (verify_step history re-reads) rely on this identity."""
    x = jax.random.normal(jax.random.key(2), (3, 2, 32), jnp.float32)
    q, s = quantize_row_int4(x)
    s = s.astype(jnp.bfloat16).astype(jnp.float32)
    want = unpack_int4(pack_int4(q)).astype(jnp.float32) * s[..., None]
    got = fake_quant_row_int4(x, scale_dtype=jnp.bfloat16)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-6, atol=1e-6)


# -- pool helpers --------------------------------------------------------------


def test_q4_pool_create_shapes_and_odd_head_dim_raises():
    pool = Q4PagedKVCache.create(2, 6, 8, 3, 32)
    assert pool.k.shape == (2, 6, 3, 8, 16) and pool.k.dtype == jnp.uint8
    assert pool.ks.shape == (2, 6, 3, 8) and pool.ks.dtype == jnp.bfloat16
    assert (pool.num_layers, pool.num_pages, pool.page_size) == (2, 6, 8)
    with pytest.raises(ValueError, match="even head_dim"):
        Q4PagedKVCache.create(2, 6, 8, 3, 31)


def test_write_append_gather_roundtrip():
    """write_prompts_paged_q4 + append_tokens_paged_q4 through a block
    table, read back via gather_kv_q4: every written position dequantizes
    to its own fake-quant round-trip; positions past the length are
    untouched (zero scale planes)."""
    page, hkv, d = 8, 2, 32
    kq = jnp.zeros((6, hkv, page, d // 2), jnp.uint8)
    ks = jnp.zeros((6, hkv, page), jnp.bfloat16)
    table = jnp.asarray([[0, 1], [3, 6]], jnp.int32)  # slot 1 page 1 is OOB
    prompt = jax.random.normal(jax.random.key(3), (2, 5, hkv, d), jnp.float32)
    kq, ks = write_prompts_paged_q4(kq, ks, table, prompt, jnp.asarray([0, 0]))
    step = jax.random.normal(jax.random.key(4), (2, hkv, d), jnp.float32)
    kq, ks = append_tokens_paged_q4(kq, ks, table, jnp.asarray([5, 5]), step)

    gq, gs = gather_kv_q4(kq, ks, table)  # [2, hkv, 16, d], [2, hkv, 16]
    view = gq.astype(jnp.float32) * gs.astype(jnp.float32)[..., None]
    full = jnp.concatenate([prompt, step[:, None]], axis=1)  # [2, 6, hkv, d]
    want = fake_quant_row_int4(full, scale_dtype=jnp.bfloat16)
    np.testing.assert_allclose(
        np.asarray(view[:, :, :6]),
        np.asarray(want).transpose(0, 2, 1, 3), rtol=1e-2, atol=1e-2)
    # untouched tail of slot 0's second page: zero scales → zero view
    assert not np.asarray(view[0, :, 6:]).any()


# -- fused kernel vs gathered-XLA parity ---------------------------------------


def _build_case(key, n, hq, hkv, d, page, max_pages, table):
    """Random q + a packed pool whose pages are filled through the same
    write helper the model uses (so parity covers the layout end to end)."""
    kq = vq = jnp.zeros((max_pages * n, hkv, page, d // 2), jnp.uint8)
    ks = vs = jnp.zeros((max_pages * n, hkv, page), jnp.bfloat16)
    ka, kb, kc = jax.random.split(key, 3)
    q = jax.random.normal(ka, (n, hq, d), jnp.float32)
    k = jax.random.normal(kb, (n, max_pages * page, hkv, d), jnp.float32)
    v = jax.random.normal(kc, (n, max_pages * page, hkv, d), jnp.float32)
    off = jnp.zeros((n,), jnp.int32)
    kq, ks = write_prompts_paged_q4(kq, ks, table, k, off)
    vq, vs = write_prompts_paged_q4(vq, vs, table, v, off)
    return q, kq, vq, ks, vs


@pytest.mark.parametrize("hq,hkv", [(4, 2), (2, 2)])
def test_paged_decode_q4_kernel_matches_gather(monkeypatch, hq, hkv):
    """The fused in-kernel unpack+dequant path (interpret mode) must match
    the gather-then-unpack XLA reference over ragged lengths, an empty
    slot, OOB table rows, and GQA head grouping."""
    monkeypatch.setenv("GOFR_PALLAS_INTERPRET", "1")
    from gofr_tpu.ops.attention import paged_decode_attention_q4

    n, d, page, maxp = 3, 32, 8, 4
    P = maxp * n  # OOB sentinel
    table = jnp.asarray(
        [[0, 1, 2, 3], [4, 5, P, P], [P, P, P, P]], jnp.int32)
    lengths = jnp.asarray([29, 13, 0], jnp.int32)
    q, kq, vq, ks, vs = _build_case(
        jax.random.key(7), n, hq, hkv, d, page, maxp, table)
    want = paged_decode_attention_q4(
        q, kq, vq, ks, vs, table, lengths, backend="xla")
    got = paged_decode_attention_q4(
        q, kq, vq, ks, vs, table, lengths, backend="pallas")
    np.testing.assert_allclose(
        np.asarray(got[:2]), np.asarray(want[:2]), rtol=2e-2, atol=2e-2)
    assert np.isfinite(np.asarray(got[:2])).all()


def test_paged_decode_q4_explicit_pallas_rejects_bad_page(monkeypatch):
    """Explicit backend='pallas' with a page size that breaks the f32
    sublane tile must raise, never silently degrade (ADVICE r2)."""
    monkeypatch.setenv("GOFR_PALLAS_INTERPRET", "1")
    from gofr_tpu.ops.attention import paged_decode_attention_q4

    n, d, page = 1, 32, 4
    table = jnp.asarray([[0]], jnp.int32)
    q, kq, vq, ks, vs = _build_case(
        jax.random.key(8), n, 2, 2, d, page, 1, table)
    with pytest.raises(ValueError, match="multiple of 8"):
        paged_decode_attention_q4(
            q, kq, vq, ks, vs, table, jnp.asarray([2]), backend="pallas")


# -- engine level --------------------------------------------------------------


class TestEngineInt4KV:
    @pytest.fixture(scope="class")
    def setup(self):
        cfg = LlamaConfig.tiny()
        params = llama.init(cfg, jax.random.key(7))
        return cfg, params

    def test_int4_serving_is_deterministic_and_leak_free(self, setup):
        """Greedy int4 serving is token-plausible: deterministic across
        runs, right count, in-vocab — and the pool accounting stays clean
        after mixed traffic (prefix reuse + slot churn)."""
        cfg, params = setup
        from gofr_tpu.testutil import assert_paged_pool_consistent

        eng = GenerateEngine(llama, cfg, params, new_mock_container(),
                             slots=4, max_len=64, max_prefill_batch=2,
                             kv_layout="paged", page_size=8,
                             kv_quantize="int4")
        try:
            assert isinstance(eng.kv_cache, Q4PagedKVCache)
            a = eng.generate([5, 3, 9], max_new_tokens=8, timeout=300)
            b = eng.generate([5, 3, 9], max_new_tokens=8, timeout=300)
            assert a["tokens"] == b["tokens"]
            assert len(a["tokens"]) == 8
            assert all(0 <= t < cfg.vocab_size for t in a["tokens"])
            c = eng.generate([2, 4], max_new_tokens=4, timeout=300)
            assert len(c["tokens"]) == 4
            assert_paged_pool_consistent(eng, slots_empty=True)

            # pool bytes: packed nibbles + bf16 scales vs an int8 pool of
            # the same geometry — strictly smaller, and the packed planes
            # alone are exactly half the int8 planes
            q4 = sum(x.size * x.dtype.itemsize
                     for x in (eng.kv_cache.k, eng.kv_cache.v,
                               eng.kv_cache.ks, eng.kv_cache.vs))
            q8pool = llama.make_paged_cache_q(
                cfg, eng.total_pages, eng.page_size)
            q8 = sum(x.size * x.dtype.itemsize
                     for x in (q8pool.k, q8pool.v, q8pool.ks, q8pool.vs))
            assert q4 < q8
            assert eng.kv_cache.k.nbytes * 2 == q8pool.k.nbytes
        finally:
            eng.stop()

    def test_build_engine_env_selects_int4(self, setup):
        """ENGINE_KV_DTYPE=int4 is the config-plane spelling: build_engine
        must materialize the packed pool and record kv_quantize='int4'
        (what /debug/engine and the handoff JOIN hello report)."""
        from gofr_tpu.tpu.engine import ModelSpec, build_engine

        cfg, _ = setup
        c = new_mock_container({"ENGINE_KV_DTYPE": "int4",
                                "ENGINE_KV_LAYOUT": "paged",
                                "ENGINE_PAGE_SIZE": "8"})
        spec = ModelSpec("llama", cfg, task="generate", dtype=jnp.float32)
        eng = build_engine(spec, c, slots=2, max_len=32)
        try:
            assert eng.kv_quantize == "int4"
            assert isinstance(eng.kv_cache, Q4PagedKVCache)
            out = eng.generate([1, 2, 3], max_new_tokens=2, timeout=300)
            assert len(out["tokens"]) == 2
        finally:
            eng.stop()

    def test_build_engine_rejects_bad_dtype_and_bf16_is_dense(self, setup):
        from gofr_tpu.tpu.engine import ModelSpec, build_engine

        cfg, _ = setup
        spec = ModelSpec("llama", cfg, task="generate", dtype=jnp.float32)
        with pytest.raises(ValueError, match="ENGINE_KV_DTYPE"):
            build_engine(spec, new_mock_container({"ENGINE_KV_DTYPE": "fp8"}),
                         slots=2, max_len=32)
        c = new_mock_container({"ENGINE_KV_DTYPE": "bf16",
                                "ENGINE_KV_LAYOUT": "paged",
                                "ENGINE_PAGE_SIZE": "8"})
        eng = build_engine(spec, c, slots=2, max_len=32)
        try:
            assert eng.kv_quantize == ""
        finally:
            eng.stop()

    def test_int4_requires_paged_layout(self, setup):
        cfg, params = setup
        with pytest.raises(ValueError, match="kv_quantize"):
            GenerateEngine(llama, cfg, params, new_mock_container(),
                           slots=2, max_len=32, kv_quantize="int4")
