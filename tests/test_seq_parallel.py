"""Sequence parallelism (ring / Ulysses) vs dense attention on the 8-device
CPU mesh — long-context support (SURVEY.md §5.7, new subsystem)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gofr_tpu.models import LlamaConfig, llama
from gofr_tpu.ops.attention import mha_attention
from gofr_tpu.parallel import build_mesh
from gofr_tpu.parallel.ring import make_seq_parallel_attn
from gofr_tpu.train import make_train_step


def _qkv(key, b, s, hq, hkv, d):
    kq, kk, kv = jax.random.split(key, 3)
    return (
        jax.random.normal(kq, (b, s, hq, d)),
        jax.random.normal(kk, (b, s, hkv, d)),
        jax.random.normal(kv, (b, s, hkv, d)),
    )


@pytest.mark.parametrize("strategy", ["ring", "ulysses"])
@pytest.mark.parametrize("mesh_spec", ["sp:8", "dp:2,sp:4"])
def test_matches_dense_causal(strategy, mesh_spec):
    mesh = build_mesh(mesh_spec)
    q, k, v = _qkv(jax.random.key(0), 2, 32, 8, 4, 16)
    lengths = jnp.array([32, 19], jnp.int32)
    want = mha_attention(q, k, v, causal=True, kv_lengths=lengths, backend="xla")
    attn = make_seq_parallel_attn(mesh, strategy=strategy)
    got = attn(q, k, v, causal=True, kv_lengths=lengths)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("strategy", ["ring", "ulysses"])
def test_matches_dense_non_causal(strategy):
    mesh = build_mesh("dp:2,sp:4")
    q, k, v = _qkv(jax.random.key(1), 2, 16, 4, 4, 8)
    want = mha_attention(q, k, v, causal=False, backend="xla")
    attn = make_seq_parallel_attn(mesh, strategy=strategy)
    got = attn(q, k, v, causal=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5, rtol=1e-5)


def test_ring_with_tp_sharded_heads():
    mesh = build_mesh("sp:2,tp:4")
    q, k, v = _qkv(jax.random.key(2), 2, 16, 8, 4, 8)
    want = mha_attention(q, k, v, causal=True, backend="xla")
    attn = make_seq_parallel_attn(mesh, strategy="ring")
    got = attn(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5, rtol=1e-5)


def test_ring_gradients_match_dense():
    mesh = build_mesh("dp:2,sp:4")
    q, k, v = _qkv(jax.random.key(3), 2, 16, 2, 2, 8)
    attn = make_seq_parallel_attn(mesh, strategy="ring")

    def loss_ring(q, k, v):
        return jnp.sum(attn(q, k, v, causal=True) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(mha_attention(q, k, v, causal=True, backend="xla") ** 2)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for gr, gd in zip(g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gd), atol=1e-4, rtol=1e-4)


def test_llama_forward_with_ring_attn():
    mesh = build_mesh("dp:2,sp:4")
    cfg = LlamaConfig.tiny()
    params = llama.init(cfg, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (2, 32), 0, cfg.vocab_size)
    lengths = jnp.array([32, 30], jnp.int32)
    want = llama.forward(cfg, params, tokens, lengths)
    attn = make_seq_parallel_attn(mesh, strategy="ring")
    got = llama.forward(cfg, params, tokens, lengths, attn)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-4, rtol=2e-4)


@pytest.mark.parametrize("strategy", ["ring", "ulysses"])
def test_train_step_seq_parallel(strategy):
    mesh = build_mesh("dp:2,sp:2,tp:2")
    cfg = LlamaConfig.tiny()
    init_fn, step_fn = make_train_step(cfg, llama, mesh, seq_parallel=strategy)
    state = init_fn(jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (4, 32), 0, cfg.vocab_size)
    lengths = jnp.full((4,), 32, jnp.int32)
    state, metrics = step_fn(state, tokens, lengths)
    l0 = float(metrics["loss"])
    assert np.isfinite(l0)
    for _ in range(3):
        state, metrics = step_fn(state, tokens, lengths)
    assert float(metrics["loss"]) < l0  # it learns


def test_seq_parallel_requires_sp_axis():
    mesh = build_mesh("dp:8")
    with pytest.raises(ValueError, match="sp"):
        make_train_step(LlamaConfig.tiny(), llama, mesh, seq_parallel="ring")
