"""Mesh / sharding-rules / collectives tests on the 8-device virtual CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from gofr_tpu.parallel import (
    MeshSpec,
    ShardingRules,
    build_mesh,
    collectives,
    local_mesh,
    logical_sharding,
    mesh_from_config,
    shard_pytree,
)
from gofr_tpu.config import DictConfig


def test_virtual_device_count():
    assert len(jax.devices()) == 8


class TestMeshSpec:
    def test_parse(self):
        spec = MeshSpec.parse("dp:2,tp:4")
        assert spec.axes == (("dp", 2), ("tp", 4))

    def test_parse_equals_and_fill(self):
        spec = MeshSpec.parse("tp=-1")
        assert spec.resolve(8) == (("tp", 8),)

    def test_fill_with_fixed(self):
        spec = MeshSpec.parse("dp:2,tp:-1")
        assert spec.resolve(8) == (("dp", 2), ("tp", 4))

    def test_bad_axis(self):
        with pytest.raises(ValueError, match="unknown mesh axis"):
            MeshSpec.parse("zz:2")

    def test_two_fills(self):
        with pytest.raises(ValueError, match="at most one"):
            MeshSpec.parse("dp:-1,tp:-1")

    def test_duplicate(self):
        with pytest.raises(ValueError, match="duplicate"):
            MeshSpec.parse("tp:2,tp:4")

    def test_indivisible(self):
        with pytest.raises(ValueError, match="divisible|needs"):
            MeshSpec.parse("dp:3").resolve(8)

    def test_build_mesh(self):
        mesh = build_mesh("dp:2,tp:4")
        assert mesh.axis_names == ("dp", "tp")
        assert mesh.devices.shape == (2, 4)

    def test_mesh_from_config(self):
        mesh = mesh_from_config(DictConfig({"TPU_MESH": "dp:2,sp:2,tp:2"}))
        assert mesh.axis_names == ("dp", "sp", "tp")

    def test_mesh_from_config_default(self):
        mesh = mesh_from_config(DictConfig({}))
        assert mesh.axis_names == ("dp",)
        assert mesh.devices.shape == (8,)


class TestShardingRules:
    def test_spec_maps_logical_to_mesh(self):
        mesh = build_mesh("dp:2,tp:4")
        rules = ShardingRules()
        spec = rules.spec(("batch", "seq", "embed"), mesh)
        # batch → dp (fsdp absent from mesh), seq → sp absent → None
        assert spec == P("dp", None, None)
        spec2 = rules.spec(("embed", "mlp"), mesh)
        assert spec2 == P(None, "tp")

    def test_absent_axis_replicates(self):
        mesh = local_mesh(8, axis="dp")
        spec = ShardingRules().spec(("heads", "embed"), mesh)
        assert spec == P(None, None)

    def test_unknown_logical_raises(self):
        mesh = local_mesh(8)
        with pytest.raises(KeyError):
            ShardingRules().spec(("nonsense",), mesh)

    def test_overrides(self):
        mesh = build_mesh("fsdp:8")
        rules = ShardingRules().with_overrides(embed="fsdp")
        assert rules.spec(("embed", "mlp"), mesh) == P("fsdp", None)

    def test_shard_pytree(self):
        mesh = build_mesh("dp:2,tp:4")
        rules = ShardingRules()
        params = {"w": jnp.ones((8, 16)), "b": jnp.ones((16,))}
        axes = {"w": ("embed", "mlp"), "b": ("mlp",)}
        sharded = shard_pytree(params, axes, rules, mesh)
        assert sharded["w"].sharding == NamedSharding(mesh, P(None, "tp"))
        assert sharded["b"].sharding == NamedSharding(mesh, P("tp"))
        # value preserved
        np.testing.assert_array_equal(np.asarray(sharded["w"]), np.ones((8, 16)))


class TestCollectives:
    def test_psum_all_gather_under_shard_map(self):
        mesh = local_mesh(8, axis="tp")
        x = jnp.arange(8.0)

        @collectives.shard_map_over(mesh, in_specs=P("tp"), out_specs=P())
        def total(shard):
            return collectives.psum(jnp.sum(shard), "tp")

        assert float(total(x)) == 28.0

    def test_ring_permute(self):
        mesh = local_mesh(8, axis="sp")
        x = jnp.arange(8.0)

        @collectives.shard_map_over(mesh, in_specs=P("sp"), out_specs=P("sp"))
        def rotate(shard):
            return collectives.ring_permute(shard, "sp")

        out = rotate(x)
        # device i's value moves to device i+1 (wrap): result is roll by 1
        np.testing.assert_array_equal(np.asarray(out), np.roll(np.arange(8.0), 1))

    def test_reduce_scatter(self):
        mesh = local_mesh(4, axis="tp")
        x = jnp.ones((4, 8))

        @collectives.shard_map_over(mesh, in_specs=P("tp", None), out_specs=P("tp", None))
        def rs(shard):
            # each shard is (1, 8); psum_scatter over tp splits dim 1 → (1, 2) per device
            return collectives.reduce_scatter(shard, "tp", scatter_dim=1)

        out = rs(x)
        assert out.shape == (4, 2)
        np.testing.assert_array_equal(np.asarray(out), np.full((4, 2), 4.0))

    def test_axis_index_size(self):
        mesh = local_mesh(8, axis="dp")

        @collectives.shard_map_over(mesh, in_specs=(), out_specs=P("dp"))
        def idx():
            return (collectives.axis_index("dp") * 10 + collectives.axis_size("dp"))[None]

        out = idx()
        np.testing.assert_array_equal(np.asarray(out), np.arange(8) * 10 + 8)
