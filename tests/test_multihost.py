"""Multi-host (DCN) scaffolding: JAX_COORDINATOR config →
``jax.distributed.initialize`` in the TPU datasource (SURVEY §5.8).

Two REAL processes coordinate over localhost, each contributing 2 virtual
CPU devices; each builds the container's TPU datasource from config alone,
constructs the GLOBAL dp mesh, and runs a jitted psum across the process
boundary. This is the CPU stand-in for a v5e multi-slice job — the same
config keys drive real DCN bring-up.
"""

import os
import socket
import subprocess
import sys
import textwrap

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from jaxpin import child_env  # noqa: E402

# integration tier (CI `integration` job): multi-minute engine/process
# runs — excluded from the tier-1 gate via -m 'not slow' (docs/testing.md)
pytestmark = pytest.mark.slow

_WORKER = textwrap.dedent("""
    import os, sys
    sys.path.insert(0, {repo!r})
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from gofr_tpu.container import new_mock_container

    pid = int(sys.argv[1])
    c = new_mock_container({{
        "JAX_COORDINATOR": "127.0.0.1:{port}",
        "JAX_NUM_PROCESSES": "2",
        "JAX_PROCESS_ID": str(pid),
        "TPU_MESH": "dp:4",
    }})
    tpu = c.tpu
    assert tpu.distributed, "distributed init did not run"
    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 4, jax.devices()
    assert len(tpu.local_devices) == 2

    mesh = tpu.mesh
    from jax.sharding import NamedSharding, PartitionSpec as P

    @jax.jit
    def global_sum(x):
        return jax.lax.psum(x, "dp")

    from functools import partial
    @partial(jax.jit, out_shardings=NamedSharding(mesh, P()))
    def reduce_all(x):
        return jnp.sum(x)

    # a length-4 array sharded one element per global device; the jitted sum
    # crosses the process boundary
    x = jax.device_put(
        jnp.arange(4.0), NamedSharding(mesh, P("dp"))
    )
    total = reduce_all(x)
    assert float(total) == 6.0, float(total)
    health = tpu.health_check()
    assert health["status"] == "UP"
    print(f"MULTIHOST_OK pid={{pid}} devices={{len(jax.devices())}} total={{float(total)}}")
""")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_global_mesh():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    port = _free_port()
    src = _WORKER.format(repo=repo, port=port)
    env = child_env()
    env.pop("XLA_FLAGS", None)  # workers pin their own device count

    procs = [
        subprocess.Popen([sys.executable, "-c", src, str(pid)],
                         env=env, stdout=subprocess.PIPE,
                         stderr=subprocess.STDOUT, text=True)
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail(f"multi-host workers hung; partial output: {outs}")
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out[-3000:]}"
        assert "MULTIHOST_OK" in out, out[-3000:]
