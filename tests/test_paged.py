"""Paged KV cache: pool write/read semantics and the Pallas paged-decode
kernel vs the XLA gather path (hermetic CPU tests, SURVEY.md §4 analog)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gofr_tpu.ops.attention import decode_attention, paged_decode_attention
from gofr_tpu.ops.kvcache import append_tokens
from gofr_tpu.ops.paged import (
    PagedKVCache,
    append_tokens_paged,
    gather_kv,
    write_prompts_paged,
)


PAGE = 8  # small page for tests; engine default is 128


def _rand(key, shape, dtype=jnp.float32):
    return jax.random.normal(key, shape, dtype)


def test_write_prompts_paged_round_trip():
    """A prompt scattered through an arbitrary (non-contiguous) block table
    reads back identical to the slot-cache layout."""
    b, s, hkv, d = 2, 20, 2, 16
    pool_pages, maxp = 12, 4
    k_new = _rand(jax.random.key(0), (b, s, hkv, d))
    v_new = _rand(jax.random.key(1), (b, s, hkv, d))

    # deliberately shuffled, interleaved page assignment
    pages = jnp.array([[7, 2, 9, 11], [0, 5, 3, 1]], jnp.int32)
    k_layer = jnp.zeros((pool_pages, hkv, PAGE, d))
    v_layer = jnp.zeros((pool_pages, hkv, PAGE, d))
    k_layer, v_layer = write_prompts_paged(k_layer, v_layer, pages, k_new, v_new)

    k_view, v_view = gather_kv(k_layer, v_layer, pages)
    # logical view is [B, Hkv, maxp*PAGE, D]; positions 0..s hold the prompt
    np.testing.assert_allclose(k_view[:, :, :s], k_new.swapaxes(1, 2), rtol=1e-6)
    np.testing.assert_allclose(v_view[:, :, :s], v_new.swapaxes(1, 2), rtol=1e-6)


def test_oob_page_writes_dropped():
    """Padding rows point every logical page at P (out of bounds): their
    writes must vanish, leaving the pool untouched."""
    b, s, hkv, d = 2, PAGE, 2, 8
    pool_pages = 4
    k_new = _rand(jax.random.key(2), (b, s, hkv, d))
    pages = jnp.array([[1], [pool_pages]], jnp.int32)  # row 1 is padding
    k_layer = jnp.zeros((pool_pages, hkv, PAGE, d))
    v_layer = jnp.zeros((pool_pages, hkv, PAGE, d))
    k_layer, v_layer = write_prompts_paged(k_layer, v_layer, pages, k_new, k_new)
    # page 1 holds row 0's prompt; every other page still zero
    np.testing.assert_allclose(k_layer[1], k_new[0].swapaxes(0, 1), rtol=1e-6)
    assert float(jnp.abs(k_layer[jnp.array([0, 2, 3])]).sum()) == 0.0


def test_append_tokens_paged_matches_slot_semantics():
    """Appending tokens one at a time through block tables must equal the
    slot cache's contiguous append."""
    n, hkv, d = 3, 2, 8
    maxp = 3
    pool_pages = n * maxp
    # identity-ish table: slot i owns pages [3i, 3i+1, 3i+2]
    table = jnp.arange(pool_pages, dtype=jnp.int32).reshape(n, maxp)

    k_pool = jnp.zeros((pool_pages, hkv, PAGE, d))
    v_pool = jnp.zeros((pool_pages, hkv, PAGE, d))
    k_slot = jnp.zeros((n, hkv, maxp * PAGE, d))
    v_slot = jnp.zeros((n, hkv, maxp * PAGE, d))

    positions = jnp.array([0, PAGE - 1, PAGE], jnp.int32)  # page-boundary cases
    for step in range(4):
        kn = _rand(jax.random.key(10 + step), (n, hkv, d))
        vn = _rand(jax.random.key(20 + step), (n, hkv, d))
        pos = positions + step
        k_pool, v_pool = append_tokens_paged(k_pool, v_pool, table, pos, kn, vn)
        k_slot, v_slot = append_tokens(k_slot, v_slot, pos, kn, vn)

    k_view, v_view = gather_kv(k_pool, v_pool, table)
    np.testing.assert_allclose(k_view, k_slot, rtol=1e-6)
    np.testing.assert_allclose(v_view, v_slot, rtol=1e-6)


@pytest.mark.parametrize("hq,hkv", [(4, 4), (8, 2)])
def test_paged_decode_kernel_matches_gather_path(monkeypatch, hq, hkv):
    """Pallas paged-decode (scalar-prefetched block tables) vs the XLA
    gather fallback, with ragged lengths and shuffled tables."""
    n, d, maxp, pool_pages = 3, 32, 4, 16
    page = 16
    q = _rand(jax.random.key(0), (n, hq, d))
    k_pool = _rand(jax.random.key(1), (pool_pages, hkv, page, d))
    v_pool = _rand(jax.random.key(2), (pool_pages, hkv, page, d))
    rng = np.random.RandomState(0)
    perm = rng.permutation(pool_pages)[: n * maxp].reshape(n, maxp)
    table = jnp.asarray(perm, jnp.int32)
    # OOB-mark the unallocated tail of slot 2's table
    table = table.at[2, 2:].set(pool_pages)
    lengths = jnp.array([page * maxp, 19, page + 3], jnp.int32)

    want = paged_decode_attention(q, k_pool, v_pool, table, lengths, backend="xla")
    monkeypatch.setenv("GOFR_PALLAS_INTERPRET", "1")
    got = paged_decode_attention(q, k_pool, v_pool, table, lengths, backend="pallas")
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)


def test_paged_matches_dense_decode():
    """Paged attention over a contiguous table == dense decode over the
    equivalent [N, Hkv, Smax, D] cache."""
    n, hq, hkv, d, maxp = 2, 4, 2, 16, 3
    page = 8
    pool_pages = n * maxp
    table = jnp.arange(pool_pages, dtype=jnp.int32).reshape(n, maxp)
    q = _rand(jax.random.key(5), (n, hq, d))
    k_pool = _rand(jax.random.key(6), (pool_pages, hkv, page, d))
    v_pool = _rand(jax.random.key(7), (pool_pages, hkv, page, d))
    lengths = jnp.array([maxp * page, 11], jnp.int32)

    k_view, v_view = gather_kv(k_pool, v_pool, table)
    want = decode_attention(q, k_view, v_view, lengths, backend="xla")
    got = paged_decode_attention(q, k_pool, v_pool, table, lengths, backend="xla")
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)
