"""Feature-interaction matrix (VERDICT r4 #7): token-exactness over
{slot, paged} × {bf16, int8 KV} × {plain, spec, chunked-long-prompt} ×
{prefix on/off}, concurrent requests per cell, warm-hit replay on prefix
cells. Silent untested combinations are how token-exactness claims rot —
every combination either serves exactly the dense-reference tokens here
or is an explicit build-time ValueError (tested in the rejection class).
"""

import threading

import jax
import pytest

from gofr_tpu.container import new_mock_container
from gofr_tpu.models import llama
from gofr_tpu.testutil import greedy_reference, tiny_f32_llama
from gofr_tpu.tpu.engine import GenerateEngine

# prompts sized against max_len=64, prefill_buckets up to 16; the LONG
# prompt exceeds the top bucket to force the chunked path in 'chunked'
# cells. Shared leading tokens on the first two give prefix cells a warm
# hit on replay.
PROMPTS = [
    [3, 7, 11, 3, 7, 11, 9, 1],
    [3, 7, 11, 3, 7, 11, 2, 5, 8],
    [5, 2, 9, 4],
]
LONG_PROMPT = [(7 * i) % 150 + 1 for i in range(21)]
NEW = 7


@pytest.fixture(scope="module")
def setup():
    cfg, params = tiny_f32_llama()
    ref = greedy_reference(cfg, params)
    want = [ref(p, NEW) for p in PROMPTS]
    want_long = ref(LONG_PROMPT, NEW)
    return cfg, params, want, want_long


def _serve(eng, want, want_long, mode):
    prompts = list(PROMPTS)
    expect = list(want)
    if mode == "chunked":
        prompts = prompts + [LONG_PROMPT]
        expect = expect + [want_long]
    results = [None] * len(prompts)

    def worker(i):
        results[i] = eng.generate(prompts[i], max_new_tokens=NEW, timeout=300)

    ts = [threading.Thread(target=worker, args=(i,)) for i in range(len(prompts))]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert [r["tokens"] for r in results] == expect


@pytest.mark.parametrize("layout", ["slot", "paged"])
@pytest.mark.parametrize("kvq", ["", "int8"])
@pytest.mark.parametrize("mode", ["plain", "spec", "chunked"])
@pytest.mark.parametrize("prefix", [False, True])
def test_matrix_token_exact(setup, layout, kvq, mode, prefix):
    cfg, params, want, want_long = setup
    if prefix and layout == "slot":
        pytest.skip("prefix caching is paged-only (validated separately)")
    kw = dict(slots=4, max_len=64, max_prefill_batch=2, decode_chunk=4,
              prefill_buckets=[8, 16], kv_layout=layout,
              kv_quantize=kvq, prefix_cache=prefix)
    if layout == "paged":
        kw.update(page_size=8)
    if mode == "spec":
        kw.update(spec_tokens=2)
    eng = GenerateEngine(llama, cfg, params, new_mock_container(), **kw)
    try:
        _serve(eng, want, want_long, mode)
        if prefix:
            # replay: shared prefixes now HIT the cache — tokens must not move
            _serve(eng, want, want_long, mode)
    finally:
        eng.stop()


class TestRejectedCombinations:
    """Deliberately-unsupported combinations must fail at BUILD time with
    a clear error, never serve silently-wrong tokens."""

    def test_prefix_cache_needs_paged(self, setup):
        cfg, params, _, _ = setup
        # slot + prefix_cache=True is accepted but inert by design:
        # the engine records no prefix state on the slot layout
        eng = GenerateEngine(llama, cfg, params, new_mock_container(),
                             slots=2, max_len=64, prefix_cache=True)
        try:
            assert eng._prefix is None
        finally:
            eng.stop()

    def test_spec_draft_rejects_paged(self, setup):
        cfg, params, _, _ = setup
        with pytest.raises(ValueError, match="slot-layout only"):
            GenerateEngine(llama, cfg, params, new_mock_container(),
                           slots=2, max_len=64, kv_layout="paged",
                           spec_tokens=2, spec_draft=(llama, cfg, params))

    def test_paged_spec_serves_sampling(self, setup):
        # both layouts serve sampled requests through rejection sampling
        # (round 5; distribution tests in test_spec_decode)
        cfg, params, _, _ = setup
        eng = GenerateEngine(llama, cfg, params, new_mock_container(),
                             slots=2, max_len=64, kv_layout="paged",
                             page_size=8, spec_tokens=2)
        try:
            out = eng.generate([3, 7, 9], max_new_tokens=6, temperature=0.7,
                               timeout=300)
            assert len(out["tokens"]) == 6
        finally:
            eng.stop()

    def test_bad_layout_and_quantize_values(self, setup):
        cfg, params, _, _ = setup
        with pytest.raises(ValueError, match="kv_layout"):
            GenerateEngine(llama, cfg, params, new_mock_container(),
                           slots=2, max_len=64, kv_layout="ragged")
        with pytest.raises(ValueError, match="kv_quantize"):
            GenerateEngine(llama, cfg, params, new_mock_container(),
                           slots=2, max_len=64, kv_quantize="int4")
