"""CI-config rehearsal (VERDICT r4 #9): a clean runner installs ONLY its
OWN job's pip lines, so every job that runs pytest must cover every
third-party module its collection can import — including the transitive
anchor (tests/conftest.py -> jaxpin -> jax, and gofr_tpu/__init__ ->
app -> aiohttp) that EVERY pytest job pays regardless of target files.
Checked PER JOB (a union across jobs would hide exactly the per-job gap
this exists to prevent). Grep/ast-generated so the pip lines can't drift
as imports are added.
"""

import pathlib
import sys

import pytest
import yaml

pytestmark = pytest.mark.quick

REPO = pathlib.Path(__file__).resolve().parents[1]

# import name -> pip distribution name, for the names that differ
DIST = {
    "jax": "jax", "flax": "flax", "optax": "optax", "chex": "chex",
    "einops": "einops", "numpy": "numpy", "aiohttp": "aiohttp",
    "httpx": "httpx", "pytest": "pytest", "transformers": "transformers",
    "orbax": "orbax-checkpoint", "grpc": "grpcio", "google": "protobuf",
    "kafka": "kafka-python", "paho": "paho-mqtt", "pymysql": "pymysql",
    "psycopg2": "psycopg2-binary", "yaml": "pyyaml",
    "cryptography": "cryptography",
}
IN_REPO = {"gofr_tpu", "jaxpin", "tests", "examples", "conftest"}

# imports that only exist inside function bodies but are REQUIRED at test
# runtime when the matching marker appears in the job's run lines (lazy
# imports the ast scan below skips): cryptography whenever the auth suite
# can run; kafka only when the job wires a real broker (the client import
# is env-gated behind REAL_KAFKA_BROKER)
RUNTIME_LAZY = (
    (lambda r: "test_auth_jwt" in r or " tests/ " in r or r.strip().endswith("tests/"),
     {"cryptography"}),
    (lambda r: "REAL_KAFKA_BROKER" in r, {"kafka"}),
)


def _top_level_imports(path: pathlib.Path) -> set:
    """Module-level (non-lazy) imports only: lazy client imports inside
    functions are config-gated and legitimately absent on a clean runner."""
    import ast

    out = set()
    try:
        tree = ast.parse(path.read_text(errors="ignore"))
    except SyntaxError:
        return out
    for node in tree.body:  # module level only — nested defs excluded
        if isinstance(node, ast.Import):
            out.update(a.name.split(".")[0] for a in node.names)
        elif isinstance(node, ast.ImportFrom) and node.module and not node.level:
            out.add(node.module.split(".")[0])
    return out


def _repo_needed() -> set:
    """Every module a pytest collection can pull in transitively: any test
    file plus the whole package (conftest imports gofr_tpu before
    selection filters apply, and gofr_tpu/__init__ imports app/aiohttp)."""
    needed = set()
    for base in (REPO / "tests", REPO / "gofr_tpu"):
        for p in base.rglob("*.py"):
            needed.update(_top_level_imports(p))
    needed.update(_top_level_imports(REPO / "jaxpin.py"))
    needed -= set(sys.stdlib_module_names)
    needed -= IN_REPO
    return needed


# -- quick-tier marker coverage (VERDICT r5 #8) --------------------------------
#
# `-m quick` is the <2-minute smoke tier (docs/testing.md). Every test
# module must either carry at least one @pytest.mark.quick test or appear
# here with a reason — so a NEW test module cannot silently land in no
# tier. Grep-based on purpose (same philosophy as the pip-line check):
# the list can't drift from what's actually marked.
QUICK_EXEMPT = {
    # engine/model tiers: jit compiles dominate — minutes, not seconds
    "test_70b_scale.py", "test_engine.py", "test_engine_stress.py",
    "test_kv_quant.py", "test_matrix.py", "test_mesh_serving.py",
    "test_models.py", "test_moe.py", "test_ops.py", "test_paged.py",
    "test_pallas.py", "test_parallel.py", "test_pipeline.py",
    "test_prefix.py", "test_quant.py", "test_seq_parallel.py",
    "test_spec_decode.py", "test_tokenizer.py", "test_train.py",
    "test_tpu_device.py", "test_native.py",
    # multi-process spawns / real servers / whole-app integration
    "test_examples.py", "test_http_server.py", "test_lockstep.py",
    "test_multihost.py", "test_pubsub_clients.py", "test_real_brokers.py",
    "test_real_checkpoint.py", "test_serve_integration.py",
    "test_service_client.py", "test_datasource_plugins.py",
    # needs `cryptography`, absent from minimal local envs
    "test_auth_jwt.py",
}


def test_quick_tier_marker_coverage():
    tests_dir = REPO / "tests"
    modules = sorted(p.name for p in tests_dir.glob("test_*.py"))
    unmarked = [
        name for name in modules
        if name not in QUICK_EXEMPT
        and "mark.quick" not in (tests_dir / name).read_text(errors="ignore")
    ]
    assert not unmarked, (
        f"test modules in no tier: {unmarked} — add a @pytest.mark.quick "
        "test (or `pytestmark = pytest.mark.quick`) or list them in "
        "QUICK_EXEMPT with a reason"
    )
    stale = sorted(n for n in QUICK_EXEMPT if not (tests_dir / n).exists())
    assert not stale, f"QUICK_EXEMPT entries for deleted modules: {stale}"
    # the tier must stay meaningful: several modules actually in it
    marked = [n for n in modules if n not in QUICK_EXEMPT]
    assert len(marked) >= 5, f"quick tier shrank to {marked}"


def test_kernel_autotune_suite_is_in_quick_tier():
    """ISSUE 6 satellite: the fused int8 paged-decode parity tests and the
    autotuner units (tests/test_autotune.py) must ride the `-m quick` CI
    job on every push — interpreter-mode parity and fake-timer units are
    CPU-safe by construction, so exemption would be a coverage hole."""
    path = REPO / "tests" / "test_autotune.py"
    assert path.exists(), "tests/test_autotune.py missing"
    text = path.read_text()
    assert "pytestmark = pytest.mark.quick" in text, (
        "test_autotune.py must be quick-marked module-wide"
    )
    assert "test_autotune.py" not in QUICK_EXEMPT, (
        "test_autotune.py must not be exempted from the quick tier"
    )
    # the two halves of ISSUE 6 are both present: kernel parity + autotuner
    assert "paged_decode_q" in text and "Autotuner" in text


def test_router_suite_is_in_quick_tier():
    """ISSUE 7 satellite: the router units — stable chain keys (subprocess
    PYTHONHASHSEED regression), ring, registry state machine, routing
    plans — are CPU-trivial and must ride the `-m quick` CI job; the
    multi-replica drills stay in the process tier (unmarked, tier-1)."""
    path = REPO / "tests" / "test_router.py"
    assert path.exists(), "tests/test_router.py missing"
    text = path.read_text()
    assert "pytest.mark.quick" in text, "router units must be quick-marked"
    assert "test_router.py" not in QUICK_EXEMPT, (
        "test_router.py must not be exempted from the quick tier"
    )
    # both halves are present: the stable-key regression and the drills
    assert "PYTHONHASHSEED" in text and "chain_key" in text
    assert "def test_two_replica" in text and "def test_replica_kill" in text


def test_slo_suite_is_in_quick_tier():
    """ISSUE 9 satellite: the SLO plane — window/burn arithmetic, the
    federation merge semantics (never average percentiles), the capture
    rate limit (fake clocks), and the two-replica federation drill — is
    pure bookkeeping over injectable clocks, CPU-trivial by construction,
    and must ride the `-m quick` CI job on every push."""
    path = REPO / "tests" / "test_slo.py"
    assert path.exists(), "tests/test_slo.py missing"
    text = path.read_text()
    assert "pytest.mark.quick" in text, "SLO units must be quick-marked"
    assert "test_slo.py" not in QUICK_EXEMPT, (
        "test_slo.py must not be exempted from the quick tier"
    )
    # the tentpole's three pieces are all covered: burn math + health,
    # router-side federation, and the rate-limited anomaly capture
    assert "burn" in text and "federation" in text
    assert "CaptureWatcher" in text and "def test_two_replica" in text


def test_resilience_suite_is_in_quick_tier():
    """ISSUE 10 satellite: the request-lifetime plane — deadline wire
    form + per-hop shrink, the Request future's constructed-deadline
    bound, retry-budget math (fake clock), Retry jitter/Retry-After/
    deadline interplay (stub transport), router deadline shed +
    budget-gated spill + hedged dispatch — is CPU-trivial by
    construction and must ride the `-m quick` CI job on every push;
    the paged-engine cancellation drills stay in tier-1 (unmarked)."""
    path = REPO / "tests" / "test_resilience.py"
    assert path.exists(), "tests/test_resilience.py missing"
    text = path.read_text()
    assert "pytest.mark.quick" in text, "resilience units must be quick-marked"
    assert "test_resilience.py" not in QUICK_EXEMPT, (
        "test_resilience.py must not be exempted from the quick tier"
    )
    # the tentpole's pieces are all covered: deadline propagation,
    # budgeted retries, hedging, and cooperative cancellation
    assert "RetryBudget" in text and "hedge" in text
    assert "assert_page_refs_consistent" in text
    assert "cancel_mid_decode" in text and "DEADLINE_HEADER" in text


def test_autoscaler_suite_is_in_quick_tier():
    """ISSUE 11 satellite: the elastic-fleet units — ScaleDecider
    hysteresis/cooldown/clamp on fake clocks, spawn-retry and drain-abort
    chaos handling, registry draining transitions, zero-drop requeue —
    are CPU-trivial and must ride the `-m quick` CI job on every push;
    the real-engine drain drills stay in tier-1 (unmarked)."""
    path = REPO / "tests" / "test_autoscaler.py"
    assert path.exists(), "tests/test_autoscaler.py missing"
    text = path.read_text()
    assert "pytest.mark.quick" in text, "autoscaler units must be quick-marked"
    assert "test_autoscaler.py" not in QUICK_EXEMPT, (
        "test_autoscaler.py must not be exempted from the quick tier"
    )
    # the tentpole's pieces are all covered: decision math, chaos drills,
    # draining membership, requeue, and the token-exact drain drill
    assert "ScaleDecider" in text and "autoscale.spawn" in text
    assert "replica.drain" in text and "draining" in text
    assert "requeue" in text and "assert_page_refs_consistent" in text


def test_ci_runs_the_diurnal_smoke():
    """ISSUE 11 satellite: CI must run the trace-driven diurnal harness
    (60s-compressed, autoscaler live) as an EXPLICIT CPU run and assert
    the elastic-vs-static verdict lands in extra.autoscale — otherwise
    the judging harness itself can rot between TPU bench rounds."""
    ci = yaml.safe_load((REPO / ".github" / "workflows" / "ci.yml").read_text())
    smoke_runs = [
        step.get("run", "")
        for job in ci["jobs"].values()
        for step in job.get("steps", [])
        if "GOFR_BENCH_DIURNAL=1" in step.get("run", "")
    ]
    assert smoke_runs, "ci.yml has no job running the GOFR_BENCH_DIURNAL smoke"
    joined = " ".join(smoke_runs)
    # explicit CPU label (the fail-loud guard rejects silent fallbacks)
    assert "GOFR_BENCH_PLATFORM=cpu" in joined
    assert "bench.py" in joined


def test_handoff_suite_is_in_quick_tier():
    """ISSUE 12 satellite: the disaggregated-serving suite — KV wire
    codec round trips, token-exact P→D handoff vs a colocated engine
    (bf16 AND int8 paged KV), the deadline-plane handoff shed, the
    chaos-severed zero-leak drill on both workers, and the router's
    stage-aware planning — runs on the CPU mesh in seconds and must ride
    the `-m quick` CI job on every push."""
    path = REPO / "tests" / "test_handoff.py"
    assert path.exists(), "tests/test_handoff.py missing"
    text = path.read_text()
    assert "pytestmark = pytest.mark.quick" in text, (
        "test_handoff.py must be quick-marked module-wide"
    )
    assert "test_handoff.py" not in QUICK_EXEMPT, (
        "test_handoff.py must not be exempted from the quick tier"
    )
    # the tentpole's acceptance pieces are all covered: token-exactness
    # on both KV dtypes, the deadline shed, the severed-transfer leak
    # check, and role-aware routing
    assert "token_exact_bf16" in text and "token_exact_int8" in text
    assert "kv.handoff" in text and "assert_page_refs_consistent" in text
    assert "deadline" in text and "stage" in text
    # ISSUE 18: the GOFR-HANDOFF2 streaming units ride the same quick
    # tier — chunk sequencing across streams, out-of-order reassembly,
    # the mid-stream deadline shed, the mixed-version (blob fallback)
    # pair, and the stream-granular chaos sever drills
    assert "ACK_OK_STREAM" in text, "v2 negotiation units missing"
    assert "test_out_of_order_multistream_reassembly" in text
    assert "test_deadline_expiry_mid_stream_sheds_504" in text
    assert "test_mixed_version_pair_token_exact" in text
    assert "kv.handoff.chunk" in text and "kv.handoff.midchunk" in text
    assert "kv.handoff.hello" in text


def test_ci_runs_the_disagg_smoke():
    """ISSUE 12 satellite: CI must run the prefill/decode A/B as an
    EXPLICIT CPU run and assert both arms archive TTFT/TPOT percentiles
    plus the role-split arm's handoff transfer stats in extra.disagg —
    otherwise the disaggregation harness can rot between TPU rounds."""
    ci = yaml.safe_load((REPO / ".github" / "workflows" / "ci.yml").read_text())
    smoke_runs = [
        step.get("run", "")
        for job in ci["jobs"].values()
        for step in job.get("steps", [])
        if "GOFR_BENCH_DISAGG=1" in step.get("run", "")
    ]
    assert smoke_runs, "ci.yml has no job running the GOFR_BENCH_DISAGG smoke"
    joined = " ".join(smoke_runs)
    assert "GOFR_BENCH_PLATFORM=cpu" in joined
    assert "bench.py" in joined
    # the verdict step must actually check the archived structure
    checks = " ".join(
        step.get("run", "")
        for job in ci["jobs"].values()
        for step in job.get("steps", [])
        if "disagg" in step.get("run", ""))
    assert "tpot" in checks and "handoff" in checks and "token_exact" in checks


def test_ci_runs_the_handoff_stream_smoke():
    """ISSUE 18 satellite: CI must run the blob-vs-streaming handoff A/B
    as an explicit CPU run and assert the tentpole perf claim from the
    archive — the streaming arm's decode-side TTFT slope strictly below
    the blob arm's, its longest/shortest flatness ratio bounded, a
    nonzero overlap ratio, and token-exact serving."""
    ci = yaml.safe_load((REPO / ".github" / "workflows" / "ci.yml").read_text())
    smoke_runs = [
        step.get("run", "")
        for job in ci["jobs"].values()
        for step in job.get("steps", [])
        if "GOFR_BENCH_HANDOFF_STREAM=1" in step.get("run", "")
    ]
    assert smoke_runs, (
        "ci.yml has no job running the GOFR_BENCH_HANDOFF_STREAM smoke")
    joined = " ".join(smoke_runs)
    assert "GOFR_BENCH_PLATFORM=cpu" in joined
    assert "bench.py" in joined
    # the verdict step must assert the flattening, not just presence
    checks = " ".join(
        step.get("run", "")
        for job in ci["jobs"].values()
        for step in job.get("steps", [])
        if "handoff_stream" in step.get("run", ""))
    assert "slope_s_per_page" in checks and "flatness_p50" in checks
    assert "overlap_ratio" in checks and "token_exact" in checks


def test_kv_int4_suite_is_in_quick_tier():
    """ISSUE 13 satellite: the packed-int4 KV suite — nibble pack/unpack
    round trips, pool write/append/gather, fused-kernel vs gathered-XLA
    parity under the interpreter, and int4 engine plausibility — runs on
    CPU in seconds and must ride the `-m quick` CI job on every push."""
    path = REPO / "tests" / "test_kv_int4.py"
    assert path.exists(), "tests/test_kv_int4.py missing"
    text = path.read_text()
    assert "pytestmark = pytest.mark.quick" in text, (
        "test_kv_int4.py must be quick-marked module-wide"
    )
    assert "test_kv_int4.py" not in QUICK_EXEMPT, (
        "test_kv_int4.py must not be exempted from the quick tier"
    )
    # the tentpole's acceptance pieces: lossless packing, kernel parity
    # against the gather reference, the ENGINE_KV_DTYPE config plane, and
    # clean page accounting on the int4 engine
    assert "pack_int4" in text and "unpack_int4" in text
    assert "paged_decode_attention_q4" in text and 'backend="xla"' in text
    assert "ENGINE_KV_DTYPE" in text
    assert "assert_paged_pool_consistent" in text


def test_spec_pipeline_suite_is_in_quick_tier():
    """ISSUE 13 satellite: the spec-in-the-pipeline suite — the queue-spy
    proof that paged spec rounds dispatch while older entries are still in
    flight, the depth-1 synchronous escape hatch, and the over-claim/trim
    page-lifecycle drills (cancel mid-round, tight-pool preemption) — is
    CPU-fast and must ride the `-m quick` CI job."""
    path = REPO / "tests" / "test_spec_pipeline.py"
    assert path.exists(), "tests/test_spec_pipeline.py missing"
    text = path.read_text()
    assert "pytestmark = pytest.mark.quick" in text, (
        "test_spec_pipeline.py must be quick-marked module-wide"
    )
    assert "test_spec_pipeline.py" not in QUICK_EXEMPT, (
        "test_spec_pipeline.py must not be exempted from the quick tier"
    )
    assert "_dq" in text and "spec" in text
    assert "cancel" in text and "assert_paged_pool_consistent" in text


def test_ci_runs_the_kvdtype_smoke():
    """ISSUE 13 satellite: CI must run the bf16/int8/int4 paged-pool A/B
    as an EXPLICIT CPU run and assert the archive carries all three arms
    with strictly decreasing pool bytes per decode token plus the
    token_exact/parity correctness fields — otherwise the decode-bandwidth
    harness can rot between TPU rounds."""
    ci = yaml.safe_load((REPO / ".github" / "workflows" / "ci.yml").read_text())
    smoke_runs = [
        step.get("run", "")
        for job in ci["jobs"].values()
        for step in job.get("steps", [])
        if "GOFR_BENCH_KVDTYPE=1" in step.get("run", "")
    ]
    assert smoke_runs, "ci.yml has no job running the GOFR_BENCH_KVDTYPE smoke"
    joined = " ".join(smoke_runs)
    assert "GOFR_BENCH_PLATFORM=cpu" in joined
    assert "bench.py" in joined
    # the verdict step must actually check the archived structure
    checks = " ".join(
        step.get("run", "")
        for job in ci["jobs"].values()
        for step in job.get("steps", [])
        if "kvdtype" in step.get("run", ""))
    assert "kv_bytes_per_decode_token" in checks
    assert "token_exact" in checks and "parity" in checks
    for arm in ("bf16", "int8", "int4"):
        assert arm in checks, f"verdict step never mentions the {arm} arm"


def test_perf_plane_suite_is_in_quick_tier():
    """ISSUE 14 satellite: the live-perf-plane suite — cost model vs
    hand-computed FLOPs/bytes for every step kind and all three KV dtype
    planes, fake-clock bubble accounting, GOFR_DEVICE_PEAKS resolution,
    sum-of-parts federation merges, and the capture/debug surfaces — is
    CPU-fast and must ride the `-m quick` CI job on every push."""
    path = REPO / "tests" / "test_perf_plane.py"
    assert path.exists(), "tests/test_perf_plane.py missing"
    text = path.read_text()
    assert "pytestmark = pytest.mark.quick" in text, (
        "test_perf_plane.py must be quick-marked module-wide"
    )
    assert "test_perf_plane.py" not in QUICK_EXEMPT, (
        "test_perf_plane.py must not be exempted from the quick tier"
    )
    # the tentpole's acceptance pieces: per-dtype plane widths, bubble
    # semantics, peak overrides, exact merges, and the joined surfaces
    assert "kv_plane_bytes_per_position" in text
    assert "mark_no_work" in text and "GOFR_DEVICE_PEAKS" in text
    assert "merge_totals" in text and "aggregate_perf" in text
    assert "_debug_perf_handler" in text and "CaptureWatcher" in text
    assert "app_tpu_mbu" in text


def test_ci_runs_the_perf_smoke():
    """ISSUE 14 satellite: CI must run a short CPU-labelled bench and
    assert the archive carries the per-kind roofline breakdown
    (extra.perf) AND that the headline mbu_decode_lb matches a bit-for-bit
    recomputation from the shared estimator — the one-estimator contract
    between bench and the live serving plane cannot rot silently."""
    ci = yaml.safe_load((REPO / ".github" / "workflows" / "ci.yml").read_text())
    job = ci["jobs"].get("bench-perf-smoke")
    assert job, "ci.yml has no bench-perf-smoke job"
    runs = " ".join(step.get("run", "") for step in job.get("steps", []))
    assert "GOFR_BENCH_PLATFORM=cpu" in runs
    assert "bench.py" in runs
    # the verdict step recomputes through the SHARED module and checks
    # the structure the round archives ride on
    assert "perf.mbu_decode_lb" in runs
    assert "mbu_decode_lb_params" in runs
    assert "peaks_nominal" in runs
    for kind in ("prefill", "decode"):
        assert kind in runs, f"verdict step never checks the {kind} kind"


def test_quality_suite_is_in_quick_tier():
    """ISSUE 17 satellite: the quality-plane suite — divergence-report
    math, teacher-forced determinism, the shadow-off/on token-identity
    contract on both KV layouts with spec on/off, metric label routing,
    sum-never-average federation, the chaos → burn → bundle → replay
    round trip, and the preemption/page-refs drill — is CPU-fast and must
    ride the `-m quick` CI job on every push."""
    path = REPO / "tests" / "test_quality.py"
    assert path.exists(), "tests/test_quality.py missing"
    text = path.read_text()
    assert "pytestmark = pytest.mark.quick" in text, (
        "test_quality.py must be quick-marked module-wide"
    )
    assert "test_quality.py" not in QUICK_EXEMPT, (
        "test_quality.py must not be exempted from the quick tier"
    )
    # the tentpole's acceptance pieces: deterministic scoring, the
    # off-is-free contract, the full anomaly loop, and pool hygiene
    assert "teacher_forced_rows" in text and "divergence_report" in text
    assert "quality_shadow_rate" in text and "_quality is None" in text
    assert "quality.corrupt" in text and "replay_bundle" in text
    assert "observe_quality" in text and "DIGEST_COUNTERS" in text
    assert "assert_page_refs_consistent" in text
    assert "app_tpu_spec_accept_ratio" in text


def test_ci_runs_the_quality_smoke():
    """ISSUE 17 satellite: CI must run the quality drill as an EXPLICIT
    CPU run and assert BOTH verdicts — clean arms at every KV dtype close
    breach-free, and the chaos-corrupted arm burns, bundles, and replays
    offline — otherwise the divergence harness can rot between TPU
    rounds."""
    ci = yaml.safe_load((REPO / ".github" / "workflows" / "ci.yml").read_text())
    job = ci["jobs"].get("bench-quality-smoke")
    assert job, "ci.yml has no bench-quality-smoke job"
    runs = " ".join(step.get("run", "") for step in job.get("steps", []))
    assert "GOFR_BENCH_PLATFORM=cpu" in runs
    assert "GOFR_BENCH_QUALITY=1" in runs
    assert "bench.py" in runs
    # the verdict step must check both halves of the drill
    assert "top1_agree_mean" in runs and "quality_breaches" in runs
    assert "replay_reproduced" in runs and "bundle" in runs
    for arm in ("bf16", "int8", "int4", "corrupt_int8"):
        assert arm in runs, f"verdict step never mentions the {arm} arm"


def test_tp_paged_suite_is_in_quick_tier():
    """ISSUE 19 satellite: the tensor-parallel paged-pool suite — token
    exactness sharded-vs-single-device on all three KV dtypes, spec rounds
    + preemption + host-tier swap-in on the sharded pool, per-device byte
    accounting, the sharding-preserved-after-serving check, and the
    ENGINE_KV_SHARD resolution gates — runs on the conftest-forced 8-CPU-
    device mesh and must ride the `-m quick` CI job on every push."""
    path = REPO / "tests" / "test_tp_paged.py"
    assert path.exists(), "tests/test_tp_paged.py missing"
    text = path.read_text()
    assert "pytestmark = pytest.mark.quick" in text, (
        "test_tp_paged.py must be quick-marked module-wide"
    )
    assert "test_tp_paged.py" not in QUICK_EXEMPT, (
        "test_tp_paged.py must not be exempted from the quick tier"
    )
    # the tentpole's acceptance pieces: exactness on every dtype, the
    # hard serving paths on the sharded pool, and honest accounting
    assert "int8" in text and "int4" in text
    assert "ENGINE_KV_SHARD" in text and "kv_shards" in text
    assert "spec_tokens" in text and "app_tpu_preemptions" in text
    assert "prefix_host_mb" in text and "swapin" in text
    assert "kv_plane_bytes_per_position" in text
    assert "pool_bytes_device" in text and "addressable_shards" in text
    assert "assert_page_refs_consistent" in text


def test_ci_runs_the_tp_smoke():
    """ISSUE 19 judge: CI must run the replicated-vs-sharded pool A/B on a
    forced 8-device host mesh and assert ALL THREE verdicts — token
    exactness on both arms, per-device pool bytes ≈ 1/tp, and strictly
    more pool pages at equal per-device HBM budget — otherwise the
    capacity claim can rot between TPU rounds."""
    ci = yaml.safe_load((REPO / ".github" / "workflows" / "ci.yml").read_text())
    job = ci["jobs"].get("bench-tp-smoke")
    assert job, "ci.yml has no bench-tp-smoke job"
    runs = " ".join(step.get("run", "") for step in job.get("steps", []))
    assert "GOFR_BENCH_PLATFORM=cpu" in runs
    assert "GOFR_BENCH_TP=1" in runs
    assert "xla_force_host_platform_device_count=8" in runs
    assert "bench.py" in runs
    # the verdict step must check all three halves of the claim
    assert "token_exact" in runs
    assert "device_bytes_shrink_ok" in runs
    assert "sharded_gt" in runs
    for arm in ("replicated", "sharded"):
        assert arm in runs, f"verdict step never mentions the {arm} arm"


def test_ci_has_py310_compat_gate():
    """A py3.10 interpreter must compile the whole tree in CI: 3.12-only
    syntax (same-quote nested f-strings) passes every 3.12 job silently and
    then breaks collection for anyone on the oldest supported interpreter
    (PR 1 lost most of the suite to exactly this)."""
    ci = yaml.safe_load((REPO / ".github" / "workflows" / "ci.yml").read_text())
    gates = [
        name for name, job in ci["jobs"].items()
        if any("compileall" in step.get("run", "") for step in job.get("steps", []))
        and any(str(step.get("with", {}).get("python-version", "")) == "3.10"
                for step in job.get("steps", []))
    ]
    assert gates, (
        "ci.yml has no job compiling the tree under python 3.10 "
        "(compileall on a setup-python 3.10 runner)"
    )
    # the gate must cover the package AND the test tree — a 3.12-only
    # f-string in tests/ is how the original regression landed
    for name in gates:
        runs = " ".join(s.get("run", "") for s in ci["jobs"][name]["steps"])
        assert "gofr_tpu" in runs and "tests" in runs


def test_ci_builds_the_serving_image():
    """The root Dockerfile (serving runtime; libtpu/jaxlib pinning docs live
    in its header) must exist and be built by a CI job — image breakage is
    deploy breakage and no pytest tier would catch it."""
    dockerfile = REPO / "Dockerfile"
    assert dockerfile.exists(), "root Dockerfile missing"
    text = dockerfile.read_text()
    # the pinning contract the satellite documents: jax version + libtpu
    # release index as build args, never floating installs
    assert "JAX_VERSION" in text and "libtpu" in text.lower()
    ci = yaml.safe_load((REPO / ".github" / "workflows" / "ci.yml").read_text())
    builds = [
        name for name, job in ci["jobs"].items()
        if any("docker build" in step.get("run", "") for step in job.get("steps", []))
    ]
    assert builds, "ci.yml has no job running `docker build` on the root Dockerfile"


def test_ci_runs_the_quick_tier():
    ci = yaml.safe_load((REPO / ".github" / "workflows" / "ci.yml").read_text())
    quick_runs = [
        step.get("run", "")
        for job in ci["jobs"].values()
        for step in job.get("steps", [])
        if "pytest" in step.get("run", "") and "-m quick" in step.get("run", "")
    ]
    assert quick_runs, "ci.yml has no job running `pytest -m quick`"


def test_every_pytest_job_installs_what_collection_imports():
    ci = yaml.safe_load((REPO / ".github" / "workflows" / "ci.yml").read_text())
    base_needed = _repo_needed()
    checked = 0
    for job_name, job in ci["jobs"].items():
        runs = [step.get("run", "") for step in job.get("steps", [])]
        if not any("pytest" in r for r in runs):
            continue
        checked += 1
        installed = set()
        for r in runs:
            if "pip install" in r:
                installed.update(r.replace("pip install", "").split())
        needed = set(base_needed)
        for r in runs:
            if "pytest" not in r:
                continue
            for match, extra in RUNTIME_LAZY:
                if match(r):
                    needed.update(extra)
        missing = sorted(m for m in needed if DIST.get(m, m) not in installed)
        assert not missing, (
            f"CI job {job_name!r} runs pytest but its pip lines lack "
            f"{missing} (map import->dist in tests/test_ci_config.py DIST)"
        )
    assert checked >= 3, f"expected >=3 pytest jobs in ci.yml, found {checked}"


def test_adapter_suite_is_in_quick_tier():
    """PR 16 satellite: the multi-LoRA multiplexing suite — registry/pool
    units, adapter_id=None token-exactness on both KV layouts with spec
    on and off, the mixed-adapter-batch-vs-isolation drill, per-adapter
    perf attribution, the zero-drop live hot-swap drill, and the
    adapter-cache eviction consistency check — runs on the CPU mesh and
    must ride the `-m quick` CI job on every push."""
    path = REPO / "tests" / "test_adapters.py"
    assert path.exists(), "tests/test_adapters.py missing"
    text = path.read_text()
    assert "pytestmark = pytest.mark.quick" in text, (
        "test_adapters.py must be quick-marked module-wide"
    )
    assert "test_adapters.py" not in QUICK_EXEMPT, (
        "test_adapters.py must not be exempted from the quick tier"
    )
    # the tentpole's acceptance pieces are all covered: base-lane
    # exactness, mixed-batch isolation equivalence, the hot-swap drill,
    # and the eviction-vs-page-pool consistency check
    assert "token_exact" in text and "isolation" in text
    assert "adopt_weights" in text and "zero_drop" in text
    assert "assert_page_refs_consistent" in text
    assert "epoch_of" in text  # the router-gossip epoch bump is asserted


def test_control_suite_is_in_quick_tier():
    """ISSUE 20 satellite: the online-controller suite — the extracted
    HysteresisGate units plus the ScaleDecider-delegates proof, the
    StepController trial loop on fake clocks (commit/revert/backoff,
    oscillation freeze, stand-down, starved-window accumulation, pin
    persistence + resume), the engine knob seams (boot-envelope clamps,
    per-g spec handle swap), the mid-stream token-exactness drill, and
    the metric-registration lint — is CPU-fast by construction and must
    ride the `-m quick` CI job on every push."""
    path = REPO / "tests" / "test_control.py"
    assert path.exists(), "tests/test_control.py missing"
    text = path.read_text()
    assert "pytestmark = pytest.mark.quick" in text, (
        "test_control.py must be quick-marked module-wide"
    )
    assert "test_control.py" not in QUICK_EXEMPT, (
        "test_control.py must not be exempted from the quick tier"
    )
    # the tentpole's acceptance pieces are all covered: the shared damping
    # core, the bounded trial loop with every failure edge, the safe-seam
    # actuation contract, and the never-change-tokens invariant
    assert "HysteresisGate" in text and "ScaleDecider" in text
    assert "oscillat" in text and "standdown" in text
    assert "no-evidence" in text and "resume" in text
    assert "request_knobs" in text and "_apply_pending_knobs" in text
    assert "token_exact" in text and "band_totals" in text
    assert "never_registered" in text or "is_registered" in text


def test_ci_runs_the_controller_smoke():
    """ISSUE 20 judge: CI must run the controller-vs-static A/B as an
    EXPLICIT CPU run and assert the closed-loop verdicts from the archive
    — the controller arm starting from a pessimal knob vector meets the
    best static arm within tolerance, its decision ring is non-empty, and
    serving stays token-exact across every arm AND with the controller
    off — otherwise the actuation harness can rot between TPU rounds."""
    ci = yaml.safe_load((REPO / ".github" / "workflows" / "ci.yml").read_text())
    job = ci["jobs"].get("bench-controller-smoke")
    assert job, "ci.yml has no bench-controller-smoke job"
    runs = " ".join(step.get("run", "") for step in job.get("steps", []))
    assert "GOFR_BENCH_PLATFORM=cpu" in runs
    assert "GOFR_BENCH_CONTROLLER=1" in runs
    assert "bench.py" in runs
    # the verdict step must check every half of the closed-loop claim
    assert "meets_statics" in runs
    assert "token_exact" in runs and "control_off_token_exact" in runs
    assert "decisions" in runs
    assert "bubble_ratio" in runs
