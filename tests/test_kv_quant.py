"""int8 KV cache (kvcache.QSlotKVCache): quantization error bounds, the
q-attention contraction algebra, and end-to-end serving through the engine.

Unlike weight-only int8 (exact same tokens — dequant is a reparameterized
matmul), KV int8 perturbs attention scores, so token equality with bf16 is
NOT a contract; the tests bound the numeric error and prove the serving
path (prefill→decode→finish) is self-consistent."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gofr_tpu.container import new_mock_container
from gofr_tpu.models import LlamaConfig, llama
from gofr_tpu.ops.attention import decode_attention, decode_attention_q
from gofr_tpu.ops.kvcache import (
    QSlotKVCache,
    append_tokens,
    append_tokens_q,
    dequantize_view,
    quantize_row,
    write_prompts,
    write_prompts_q,
)
from gofr_tpu.tpu.engine import GenerateEngine


def test_quantize_row_error_bound():
    x = jax.random.normal(jax.random.key(0), (4, 2, 64))
    q, s = quantize_row(x)
    deq = q.astype(jnp.float32) * s[..., None]
    # symmetric int8: |err| <= scale/2 = absmax/254 per row
    bound = np.asarray(jnp.max(jnp.abs(x), axis=-1) / 254.0 + 1e-6)
    err = np.asarray(jnp.max(jnp.abs(deq - x), axis=-1))
    assert (err <= bound).all()


def test_append_and_write_oob_dropped():
    n, hkv, smax, d = 3, 2, 16, 8
    cq = jnp.zeros((n, hkv, smax, d), jnp.int8)
    cs = jnp.zeros((n, hkv, smax), jnp.bfloat16)
    new = jax.random.normal(jax.random.key(1), (n, hkv, d))
    pos = jnp.array([0, 5, smax], jnp.int32)  # row 2 OOB -> dropped
    cq, cs = append_tokens_q(cq, cs, pos, new)
    assert int(jnp.abs(cq[2].astype(jnp.int32)).sum()) == 0
    deq = dequantize_view(cq, cs, jnp.float32)
    np.testing.assert_allclose(np.asarray(deq[0, :, 0]), np.asarray(new[0]),
                               rtol=0.02, atol=0.02)
    np.testing.assert_allclose(np.asarray(deq[1, :, 5]), np.asarray(new[1]),
                               rtol=0.02, atol=0.02)


def test_decode_attention_q_matches_dequantized_dense():
    """The folded-scale algebra must equal explicitly dequantizing the
    cache and running the plain kernel — bit-for-bit up to dtype."""
    b, hq, hkv, smax, d = 2, 4, 2, 32, 16
    key = jax.random.key(2)
    q = jax.random.normal(jax.random.fold_in(key, 0), (b, hq, d))
    k = jax.random.normal(jax.random.fold_in(key, 1), (b, hkv, smax, d))
    v = jax.random.normal(jax.random.fold_in(key, 2), (b, hkv, smax, d))
    kq, ks = quantize_row(k)
    vq, vs = quantize_row(v)
    lengths = jnp.array([smax, 11], jnp.int32)

    got = decode_attention_q(q, kq, vq, ks.astype(jnp.bfloat16),
                             vs.astype(jnp.bfloat16), lengths)
    want = decode_attention(
        q, dequantize_view(kq, ks.astype(jnp.bfloat16), q.dtype),
        dequantize_view(vq, vs.astype(jnp.bfloat16), q.dtype), lengths,
        backend="xla")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-2, atol=2e-2)


def test_decode_attention_q_close_to_fp():
    b, hq, hkv, smax, d = 2, 4, 2, 32, 16
    key = jax.random.key(3)
    q = jax.random.normal(jax.random.fold_in(key, 0), (b, hq, d))
    k = jax.random.normal(jax.random.fold_in(key, 1), (b, hkv, smax, d))
    v = jax.random.normal(jax.random.fold_in(key, 2), (b, hkv, smax, d))
    kq, ks = quantize_row(k)
    vq, vs = quantize_row(v)
    lengths = jnp.array([smax, 20], jnp.int32)
    got = decode_attention_q(q, kq, vq, ks.astype(jnp.bfloat16),
                             vs.astype(jnp.bfloat16), lengths)
    want = decode_attention(q, k, v, lengths, backend="xla")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=0.08, atol=0.08)


class TestEngineInt8KV:
    @pytest.fixture(scope="class")
    def setup(self):
        cfg = LlamaConfig.tiny()
        params = llama.init(cfg, jax.random.key(7))

        def ref(prompt, n_new):
            seq = list(prompt)
            for _ in range(n_new):
                logits = llama.forward(cfg, params, jnp.asarray([seq], jnp.int32))
                seq.append(int(jnp.argmax(logits[0, -1])))
            return seq[len(prompt):]

        return cfg, params, ref

    def test_serving_runs_and_matches_reference(self, setup):
        """f32 tiny model: int8 KV perturbations are far below the argmax
        margins at this scale, so greedy tokens still match the dense
        reference (a tie-flip here would indicate a real bug, not noise)."""
        cfg, params, ref = setup
        eng = GenerateEngine(llama, cfg, params, new_mock_container(),
                             slots=4, max_len=64, max_prefill_batch=2,
                             kv_quantize="int8")
        try:
            assert isinstance(eng.cache, QSlotKVCache)
            out = eng.generate([5, 3, 9], max_new_tokens=8, timeout=120)
            assert out["tokens"] == ref([5, 3, 9], 8)
            # cache bytes roughly halve vs bf16 (int8 + bf16 scales)
            qbytes = sum(x.size * x.dtype.itemsize for x in
                         (eng.cache.k, eng.cache.v, eng.cache.ks, eng.cache.vs))
            dense = llama.make_cache(cfg, 4, eng._cache_len)
            dbytes = sum(x.size * x.dtype.itemsize for x in (dense.k, dense.v))
            # tiny cfg is f32; against its own dtype the ratio is ~0.28,
            # against bf16 serving it is ~0.56 — assert the bf16 ratio
            assert qbytes <= 0.6 * dbytes / (dense.k.dtype.itemsize / 2)
        finally:
            eng.stop()

    def test_chunked_prefill_int8(self, setup):
        cfg, params, ref = setup
        eng = GenerateEngine(llama, cfg, params, new_mock_container(),
                             slots=2, max_len=64, max_prefill_batch=1,
                             prefill_buckets=[8], kv_quantize="int8")
        long_prompt = [(7 * i) % 190 + 1 for i in range(21)]
        try:
            out = eng.generate(long_prompt, max_new_tokens=6, timeout=300)
            assert out["tokens"] == ref(long_prompt, 6)
        finally:
            eng.stop()

    def test_paged_int8_serving_matches_reference(self, setup):
        """Paged int8 pool (QPagedKVCache): batched prefill, chunked
        prefill, decode, and the prefix cache all run quantized and still
        match dense greedy on the f32 tiny model."""
        cfg, params, ref = setup
        from gofr_tpu.ops.paged import QPagedKVCache
        from gofr_tpu.testutil import assert_paged_pool_consistent

        eng = GenerateEngine(llama, cfg, params, new_mock_container(),
                             slots=4, max_len=64, max_prefill_batch=2,
                             kv_layout="paged", page_size=8,
                             kv_quantize="int8")
        try:
            assert isinstance(eng.cache, QPagedKVCache)
            prompt = [(11 * i) % 190 + 1 for i in range(20)]  # 2 full pages
            assert eng.generate(prompt, max_new_tokens=6, timeout=120)["tokens"] == ref(prompt, 6)
            # second pass hits the prefix cache on QUANTIZED pages
            assert eng.generate(prompt, max_new_tokens=6, timeout=120)["tokens"] == ref(prompt, 6)
            hits = eng.metrics.get("app_tpu_prefix_hit_tokens")
            assert sum(hits._values.values()) == 16
            # long prompt exercises the quantized chunked-prefill path
            lp = [(7 * i) % 150 + 1 for i in range(21)]
            eng2 = GenerateEngine(llama, cfg, params, new_mock_container(),
                                  slots=2, max_len=64, max_prefill_batch=1,
                                  prefill_buckets=[8], kv_layout="paged",
                                  page_size=8, kv_quantize="int8")
            try:
                assert eng2.generate(lp, max_new_tokens=4, timeout=300)["tokens"] == ref(lp, 4)
            finally:
                eng2.stop()
            assert_paged_pool_consistent(eng, slots_empty=True)
        finally:
            eng.stop()

    def test_spec_decode_with_int8_kv(self, setup):
        """Speculation verifies against the SAME int8 cache it decodes
        from, so acceptance stays self-consistent and exact vs the int8
        greedy path (both run the identical quantized target)."""
        cfg, params, _ = setup
        kw = dict(slots=2, max_len=64, max_prefill_batch=1, kv_quantize="int8")
        plain = GenerateEngine(llama, cfg, params, new_mock_container(), **kw)
        spec = GenerateEngine(llama, cfg, params, new_mock_container(),
                              spec_tokens=3, decode_chunk=4, **kw)
        try:
            want = plain.generate([5, 3, 9], max_new_tokens=16, timeout=120)
            got = spec.generate([5, 3, 9], max_new_tokens=16, timeout=120)
            assert got["tokens"] == want["tokens"]
        finally:
            plain.stop()
            spec.stop()
