"""TPU device datasource tests (container.tpu) on the virtual CPU mesh."""


from gofr_tpu.config import DictConfig
from gofr_tpu.container import new_mock_container
from gofr_tpu.logging import MockLogger
from gofr_tpu.metrics import Registry
from gofr_tpu.tpu.device import TPUDevices


def _registry() -> Registry:
    """Registry with the framework's app_tpu_* metrics registered (names
    unknown to the registry are silently ignored, gofr-style)."""
    return new_mock_container().metrics


def make(conf=None):
    return TPUDevices(DictConfig(conf or {}), MockLogger(), _registry())


def test_defaults_all_devices_on_dp():
    t = make()
    assert len(t.devices) == 8
    assert t.mesh.axis_names == ("dp",)


def test_mesh_from_config():
    t = make({"TPU_MESH": "dp:2,tp:4"})
    assert t.mesh.devices.shape == (2, 4)
    assert t.mesh.axis_names == ("dp", "tp")


def test_device_cap():
    t = make({"TPU_DEVICES": "4", "TPU_MESH": "tp:4"})
    assert len(t.devices) == 4


def test_health_check_up():
    t = make({"TPU_MESH": "tp:-1"})
    h = t.health_check()
    assert h["status"] == "UP"
    assert h["details"]["devices"] == 8
    assert h["details"]["mesh"] == {"tp": 8}
    assert set(h["details"]["memory"]) == {str(d.id) for d in t.devices}


def test_compile_counter():
    reg = _registry()
    t = TPUDevices(DictConfig({}), MockLogger(), reg)
    t.record_compile()
    t.record_compile()
    assert t.compile_count == 2
    assert reg.get("app_tpu_compile_total").value() == 2


def test_container_lazily_wires_tpu():
    c = new_mock_container()
    assert not c.tpu_wired
    tpu = c.tpu
    assert c.tpu_wired
    assert tpu is c.tpu  # cached
    assert c.health()["services"]["tpu"]["status"] == "UP"


def test_device_count_gauge():
    reg = _registry()
    TPUDevices(DictConfig({}), MockLogger(), reg)
    assert reg.get("app_tpu_device_count").value() == 8
