"""JWT verification + JWKS tests (real RSA keys via cryptography)."""

import base64
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from gofr_tpu.http.middleware.auth import JWKSCache, verify_jwt


def _b64url(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


@pytest.fixture(scope="module")
def rsa_key():
    from cryptography.hazmat.primitives.asymmetric import rsa

    return rsa.generate_private_key(public_exponent=65537, key_size=2048)


def make_rs256(private_key, claims: dict, kid: str = "k1") -> str:
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import padding

    header = _b64url(json.dumps({"alg": "RS256", "typ": "JWT", "kid": kid}).encode())
    payload = _b64url(json.dumps(claims).encode())
    signing_input = f"{header}.{payload}".encode()
    sig = private_key.sign(signing_input, padding.PKCS1v15(), hashes.SHA256())
    return f"{header}.{payload}.{_b64url(sig)}"


def make_hs256(secret: bytes, claims: dict) -> str:
    import hashlib
    import hmac

    header = _b64url(json.dumps({"alg": "HS256", "typ": "JWT"}).encode())
    payload = _b64url(json.dumps(claims).encode())
    sig = hmac.new(secret, f"{header}.{payload}".encode(), hashlib.sha256).digest()
    return f"{header}.{payload}.{_b64url(sig)}"


@pytest.fixture
def jwks_server(rsa_key):
    pub = rsa_key.public_key().public_numbers()

    def int_b64(n: int) -> str:
        return _b64url(n.to_bytes((n.bit_length() + 7) // 8, "big"))

    jwks = {"keys": [{"kty": "RSA", "kid": "k1", "n": int_b64(pub.n), "e": int_b64(pub.e)}]}

    class H(BaseHTTPRequestHandler):
        def do_GET(self):
            body = json.dumps(jwks).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    srv = HTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield f"http://127.0.0.1:{srv.server_address[1]}/jwks"
    srv.shutdown()


def test_rs256_via_jwks(rsa_key, jwks_server):
    cache = JWKSCache(jwks_server)
    cache.refresh()
    token = make_rs256(rsa_key, {"sub": "alice", "exp": time.time() + 300})
    claims = verify_jwt(token, jwks=cache)
    assert claims["sub"] == "alice"


def test_rs256_bad_signature_rejected(rsa_key, jwks_server):
    cache = JWKSCache(jwks_server)
    cache.refresh()
    token = make_rs256(rsa_key, {"sub": "alice"})
    tampered = token[:-6] + "aaaaaa"
    with pytest.raises(ValueError):
        verify_jwt(tampered, jwks=cache)


def test_expired_token_rejected(rsa_key, jwks_server):
    cache = JWKSCache(jwks_server)
    cache.refresh()
    token = make_rs256(rsa_key, {"sub": "a", "exp": time.time() - 600})
    with pytest.raises(ValueError, match="expired"):
        verify_jwt(token, jwks=cache)


def test_audience_issuer_checks(rsa_key, jwks_server):
    cache = JWKSCache(jwks_server)
    cache.refresh()
    token = make_rs256(rsa_key, {"sub": "a", "aud": "api", "iss": "me"})
    assert verify_jwt(token, jwks=cache, audience="api", issuer="me")["iss"] == "me"
    with pytest.raises(ValueError, match="audience"):
        verify_jwt(token, jwks=cache, audience="other")
    with pytest.raises(ValueError, match="issuer"):
        verify_jwt(token, jwks=cache, issuer="them")


def test_hs256_roundtrip():
    token = make_hs256(b"secret", {"sub": "svc"})
    assert verify_jwt(token, hs_secret=b"secret")["sub"] == "svc"
    with pytest.raises(ValueError):
        verify_jwt(token, hs_secret=b"wrong")


def test_malformed_tokens_rejected():
    for bad in ("", "a.b", "a.b.c.d", "!!!.@@@.###"):
        with pytest.raises(ValueError):
            verify_jwt(bad, hs_secret=b"s")


def test_unknown_alg_rejected():
    header = _b64url(json.dumps({"alg": "none"}).encode())
    payload = _b64url(json.dumps({"sub": "x"}).encode())
    with pytest.raises(ValueError, match="unsupported alg"):
        verify_jwt(f"{header}.{payload}.", hs_secret=b"s")


class TestJWKSRotation:
    """Key-rotation behavior of the background-refresh cache (reference
    oauth.go:53-71): new kids become verifiable after refresh, stale kids
    stop, and a FAILING fetch must keep serving the last good key set
    (availability over freshness, same as the reference's ticker)."""

    def _server(self, jwks_box):
        class H(BaseHTTPRequestHandler):
            def do_GET(self):
                if jwks_box.get("fail"):
                    self.send_response(500)
                    self.end_headers()
                    return
                body = json.dumps(jwks_box["jwks"]).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        srv = HTTPServer(("127.0.0.1", 0), H)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        return srv, f"http://127.0.0.1:{srv.server_address[1]}/jwks"

    @staticmethod
    def _jwk(key, kid):
        pub = key.public_key().public_numbers()

        def int_b64(n):
            return _b64url(n.to_bytes((n.bit_length() + 7) // 8, "big"))

        return {"kty": "RSA", "kid": kid, "n": int_b64(pub.n), "e": int_b64(pub.e)}

    def test_rotation_and_stale_keys_on_failure(self, rsa_key):
        from cryptography.hazmat.primitives.asymmetric import rsa

        key_b = rsa.generate_private_key(public_exponent=65537, key_size=2048)
        box = {"jwks": {"keys": [self._jwk(rsa_key, "kid-a")]}}
        srv, url = self._server(box)
        try:
            cache = JWKSCache(url, refresh_interval=3600)
            cache.refresh()
            tok_a = make_rs256(rsa_key, {"sub": "x"}, kid="kid-a")
            tok_b = make_rs256(key_b, {"sub": "y"}, kid="kid-b")
            assert verify_jwt(tok_a, jwks=cache)["sub"] == "x"
            with pytest.raises(ValueError):
                verify_jwt(tok_b, jwks=cache)

            # rotate: kid-a retired, kid-b published
            box["jwks"] = {"keys": [self._jwk(key_b, "kid-b")]}
            cache.refresh()
            assert verify_jwt(tok_b, jwks=cache)["sub"] == "y"
            with pytest.raises(ValueError):
                verify_jwt(tok_a, jwks=cache)

            # endpoint down: the last good key set keeps serving
            box["fail"] = True
            cache.refresh()
            assert verify_jwt(tok_b, jwks=cache)["sub"] == "y"
        finally:
            srv.shutdown()

    def test_concurrent_verify_during_rotation_never_errors_spuriously(self, rsa_key):
        """Verifiers racing refresh() must always see a CONSISTENT key set
        (the whole dict swaps under the lock): a token signed by the
        currently-published key verifies, never a KeyError/partial state."""
        box = {"jwks": {"keys": [self._jwk(rsa_key, "kid-a")]}}
        srv, url = self._server(box)
        try:
            cache = JWKSCache(url, refresh_interval=3600)
            cache.refresh()
            tok = make_rs256(rsa_key, {"sub": "x"}, kid="kid-a")
            stop = threading.Event()
            errors = []

            def churn():
                while not stop.is_set():
                    cache.refresh()

            def verify_loop():
                while not stop.is_set():
                    try:
                        assert verify_jwt(tok, jwks=cache)["sub"] == "x"
                    except Exception as e:  # noqa: BLE001
                        errors.append(e)
                        return

            ts = [threading.Thread(target=churn)] + [
                threading.Thread(target=verify_loop) for _ in range(3)]
            for t in ts:
                t.start()
            time.sleep(1.0)
            stop.set()
            for t in ts:
                t.join()
            assert not errors, errors
        finally:
            srv.shutdown()
