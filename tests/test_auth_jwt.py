"""JWT verification + JWKS tests (real RSA keys via cryptography)."""

import base64
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from gofr_tpu.http.middleware.auth import JWKSCache, verify_jwt


def _b64url(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


@pytest.fixture(scope="module")
def rsa_key():
    from cryptography.hazmat.primitives.asymmetric import rsa

    return rsa.generate_private_key(public_exponent=65537, key_size=2048)


def make_rs256(private_key, claims: dict, kid: str = "k1") -> str:
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import padding

    header = _b64url(json.dumps({"alg": "RS256", "typ": "JWT", "kid": kid}).encode())
    payload = _b64url(json.dumps(claims).encode())
    signing_input = f"{header}.{payload}".encode()
    sig = private_key.sign(signing_input, padding.PKCS1v15(), hashes.SHA256())
    return f"{header}.{payload}.{_b64url(sig)}"


def make_hs256(secret: bytes, claims: dict) -> str:
    import hashlib
    import hmac

    header = _b64url(json.dumps({"alg": "HS256", "typ": "JWT"}).encode())
    payload = _b64url(json.dumps(claims).encode())
    sig = hmac.new(secret, f"{header}.{payload}".encode(), hashlib.sha256).digest()
    return f"{header}.{payload}.{_b64url(sig)}"


@pytest.fixture
def jwks_server(rsa_key):
    pub = rsa_key.public_key().public_numbers()

    def int_b64(n: int) -> str:
        return _b64url(n.to_bytes((n.bit_length() + 7) // 8, "big"))

    jwks = {"keys": [{"kty": "RSA", "kid": "k1", "n": int_b64(pub.n), "e": int_b64(pub.e)}]}

    class H(BaseHTTPRequestHandler):
        def do_GET(self):
            body = json.dumps(jwks).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    srv = HTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield f"http://127.0.0.1:{srv.server_address[1]}/jwks"
    srv.shutdown()


def test_rs256_via_jwks(rsa_key, jwks_server):
    cache = JWKSCache(jwks_server)
    cache.refresh()
    token = make_rs256(rsa_key, {"sub": "alice", "exp": time.time() + 300})
    claims = verify_jwt(token, jwks=cache)
    assert claims["sub"] == "alice"


def test_rs256_bad_signature_rejected(rsa_key, jwks_server):
    cache = JWKSCache(jwks_server)
    cache.refresh()
    token = make_rs256(rsa_key, {"sub": "alice"})
    tampered = token[:-6] + "aaaaaa"
    with pytest.raises(ValueError):
        verify_jwt(tampered, jwks=cache)


def test_expired_token_rejected(rsa_key, jwks_server):
    cache = JWKSCache(jwks_server)
    cache.refresh()
    token = make_rs256(rsa_key, {"sub": "a", "exp": time.time() - 600})
    with pytest.raises(ValueError, match="expired"):
        verify_jwt(token, jwks=cache)


def test_audience_issuer_checks(rsa_key, jwks_server):
    cache = JWKSCache(jwks_server)
    cache.refresh()
    token = make_rs256(rsa_key, {"sub": "a", "aud": "api", "iss": "me"})
    assert verify_jwt(token, jwks=cache, audience="api", issuer="me")["iss"] == "me"
    with pytest.raises(ValueError, match="audience"):
        verify_jwt(token, jwks=cache, audience="other")
    with pytest.raises(ValueError, match="issuer"):
        verify_jwt(token, jwks=cache, issuer="them")


def test_hs256_roundtrip():
    token = make_hs256(b"secret", {"sub": "svc"})
    assert verify_jwt(token, hs_secret=b"secret")["sub"] == "svc"
    with pytest.raises(ValueError):
        verify_jwt(token, hs_secret=b"wrong")


def test_malformed_tokens_rejected():
    for bad in ("", "a.b", "a.b.c.d", "!!!.@@@.###"):
        with pytest.raises(ValueError):
            verify_jwt(bad, hs_secret=b"s")


def test_unknown_alg_rejected():
    header = _b64url(json.dumps({"alg": "none"}).encode())
    payload = _b64url(json.dumps({"sub": "x"}).encode())
    with pytest.raises(ValueError, match="unsupported alg"):
        verify_jwt(f"{header}.{payload}.", hs_secret=b"s")
