"""Model-family tests: shapes, cache-consistency, sharding, and HF oracles.

The HF cross-checks build tiny *random* transformers models on CPU torch,
convert their weights (gofr_tpu.models.convert), and require logits to
match — the strongest correctness evidence available without golden files.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gofr_tpu.models import LlamaConfig, BertConfig, ViTConfig, llama, bert, vit, param_count
from gofr_tpu.parallel import ShardingRules, build_mesh, shard_pytree


class TestLlama:
    cfg = LlamaConfig.tiny()

    def test_forward_shapes(self):
        params = llama.init(self.cfg, jax.random.key(0))
        tokens = jnp.ones((2, 10), jnp.int32)
        logits = llama.forward(self.cfg, params, tokens)
        assert logits.shape == (2, 10, self.cfg.vocab_size)
        assert logits.dtype == jnp.float32

    def test_causality(self):
        """Changing a future token must not change past logits."""
        params = llama.init(self.cfg, jax.random.key(0))
        t1 = jnp.array([[5, 6, 7, 8]], jnp.int32)
        t2 = t1.at[0, 3].set(99)
        l1 = llama.forward(self.cfg, params, t1)
        l2 = llama.forward(self.cfg, params, t2)
        np.testing.assert_allclose(np.asarray(l1[0, :3]), np.asarray(l2[0, :3]), rtol=1e-5)
        assert not np.allclose(np.asarray(l1[0, 3]), np.asarray(l2[0, 3]))

    def test_prefill_matches_forward(self):
        params = llama.init(self.cfg, jax.random.key(0))
        tokens = jax.random.randint(jax.random.key(1), (2, 6), 0, 256)
        lengths = jnp.array([6, 4])
        cache = llama.make_cache(self.cfg, slots=4, max_len=32)
        logits, cache = llama.prefill(self.cfg, params, tokens, lengths, cache, jnp.array([0, 2]))
        full = llama.forward(self.cfg, params, tokens, lengths)
        np.testing.assert_allclose(
            np.asarray(logits[0]), np.asarray(full[0, 5]), rtol=2e-4, atol=2e-4
        )
        np.testing.assert_allclose(
            np.asarray(logits[1]), np.asarray(full[1, 3]), rtol=2e-4, atol=2e-4
        )

    def test_decode_matches_forward(self):
        """Prefill + N decode steps == full forward on the whole sequence."""
        params = llama.init(self.cfg, jax.random.key(0))
        seq = jax.random.randint(jax.random.key(1), (1, 8), 0, 256)
        prompt_len = 5
        cache = llama.make_cache(self.cfg, slots=2, max_len=32)
        logits, cache = llama.prefill(
            self.cfg, params, seq[:, :prompt_len], jnp.array([prompt_len]), cache, jnp.array([0])
        )
        full = llama.forward(self.cfg, params, seq)
        np.testing.assert_allclose(
            np.asarray(logits[0]), np.asarray(full[0, prompt_len - 1]), rtol=2e-4, atol=2e-4
        )
        # decode the remaining tokens one at a time in slot 0 (slot 1 idle)
        for i in range(prompt_len, 8):
            tok = jnp.array([seq[0, i], 0], jnp.int32)
            pos = jnp.array([i, 0], jnp.int32)
            step_logits, cache = llama.decode_step(self.cfg, params, tok, pos, cache)
            np.testing.assert_allclose(
                np.asarray(step_logits[0]), np.asarray(full[0, i]), rtol=2e-4, atol=2e-4
            )

    def test_paged_cache_matches_forward(self):
        """Paged prefill + decode through a shuffled block table must match
        the full forward pass (and therefore the dense slot cache)."""
        params = llama.init(self.cfg, jax.random.key(0))
        seq = jax.random.randint(jax.random.key(1), (1, 8), 0, 256)
        prompt_len = 5
        page_size, maxp, pool = 8, 4, 12
        cache = llama.make_paged_cache(self.cfg, pages=pool, page_size=page_size)
        # slot 0 owns shuffled, non-contiguous pages; slot 1 unallocated
        table = jnp.array([[3, 7, 1, 5], [pool, pool, pool, pool]], jnp.int32)
        logits, cache = llama.prefill_paged(
            self.cfg, params, seq[:, :prompt_len], jnp.array([prompt_len]),
            cache, table[:1],
        )
        full = llama.forward(self.cfg, params, seq)
        np.testing.assert_allclose(
            np.asarray(logits[0]), np.asarray(full[0, prompt_len - 1]), rtol=2e-4, atol=2e-4
        )
        for i in range(prompt_len, 8):
            tok = jnp.array([seq[0, i], 0], jnp.int32)
            pos = jnp.array([i, 0], jnp.int32)
            step_logits, cache = llama.decode_step_paged(
                self.cfg, params, tok, pos, cache, table
            )
            np.testing.assert_allclose(
                np.asarray(step_logits[0]), np.asarray(full[0, i]), rtol=2e-4, atol=2e-4
            )

    def test_paged_chunked_prefill_matches_forward(self):
        """Two prefill chunks (the second at a nonzero offset attending to
        the first through the block table) == one whole-prompt prefill."""
        params = llama.init(self.cfg, jax.random.key(0))
        seq = jax.random.randint(jax.random.key(2), (1, 16), 0, 256)
        page_size, pool = 8, 6
        cache = llama.make_paged_cache(self.cfg, pages=pool, page_size=page_size)
        table = jnp.array([[4, 1, 2]], jnp.int32)
        # chunk 1: positions 0..8 (whole-page), chunk 2: positions 8..16
        _, cache = llama.prefill_paged(
            self.cfg, params, seq[:, :8], jnp.array([8]), cache, table,
        )
        logits, cache = llama.prefill_paged(
            self.cfg, params, seq[:, 8:], jnp.array([8]), cache, table,
            offsets=jnp.array([8], jnp.int32),
        )
        full = llama.forward(self.cfg, params, seq)
        np.testing.assert_allclose(
            np.asarray(logits[0]), np.asarray(full[0, 15]), rtol=2e-4, atol=2e-4
        )

    def test_tied_embeddings(self):
        cfg = LlamaConfig.tiny(tie_embeddings=True)
        params = llama.init(cfg, jax.random.key(0))
        assert "lm_head" not in params
        logits = llama.forward(cfg, params, jnp.ones((1, 4), jnp.int32))
        assert logits.shape == (1, 4, cfg.vocab_size)

    def test_untied_lm_head_is_independent(self):
        params = llama.init(self.cfg, jax.random.key(0))
        assert not np.allclose(
            np.asarray(params["embed"]).ravel(), np.asarray(params["lm_head"]).ravel()
        )

    def test_param_axes_match_params(self):
        params = llama.init(self.cfg, jax.random.key(0))
        axes = llama.param_axes(self.cfg)
        flat_p = jax.tree.leaves(params)
        flat_a = jax.tree.leaves(axes, is_leaf=lambda x: isinstance(x, tuple))
        assert len(flat_p) == len(flat_a)
        for p, a in zip(flat_p, flat_a):
            assert p.ndim == len(a), f"{p.shape} vs {a}"

    def test_tp_sharding_preserves_numerics(self):
        """Forward on a tp=4 mesh must equal the single-device result."""
        mesh = build_mesh("dp:2,tp:4")
        params = llama.init(self.cfg, jax.random.key(0))
        tokens = jax.random.randint(jax.random.key(1), (2, 6), 0, 256)
        want = llama.forward(self.cfg, params, tokens)
        sharded = shard_pytree(params, llama.param_axes(self.cfg), ShardingRules(), mesh)
        got = llama.forward(self.cfg, sharded, tokens)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4)

    def test_hf_numerics_oracle(self):
        torch = pytest.importorskip("torch")
        from transformers import LlamaConfig as HFConfig, LlamaForCausalLM

        hf_cfg = HFConfig(
            vocab_size=128, hidden_size=32, intermediate_size=64,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
            max_position_embeddings=64, rms_norm_eps=1e-5, tie_word_embeddings=False,
        )
        torch.manual_seed(0)
        hf = LlamaForCausalLM(hf_cfg).eval()
        from gofr_tpu.models.convert import llama_from_hf

        cfg, params = llama_from_hf(hf, dtype=jnp.float32)
        tokens = np.random.RandomState(0).randint(0, 128, (2, 9))
        with torch.no_grad():
            want = hf(torch.tensor(tokens)).logits.numpy()
        got = np.asarray(llama.forward(cfg, params, jnp.asarray(tokens)))
        np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)


class TestBert:
    def test_embed_shapes_and_norm(self):
        cfg = BertConfig.tiny()
        params = bert.init(cfg, jax.random.key(0))
        tokens = jnp.ones((3, 12), jnp.int32)
        emb = bert.embed_pooled(cfg, params, tokens, jnp.array([12, 5, 1]))
        assert emb.shape == (3, cfg.hidden_size)
        np.testing.assert_allclose(np.linalg.norm(np.asarray(emb), axis=-1), 1.0, rtol=1e-5)

    def test_padding_invariance(self):
        """Extra padding must not change the pooled embedding."""
        cfg = BertConfig.tiny()
        params = bert.init(cfg, jax.random.key(0))
        t = jax.random.randint(jax.random.key(1), (1, 6), 0, 256)
        short = bert.embed_pooled(cfg, params, t, jnp.array([6]))
        padded = bert.embed_pooled(
            cfg, params, jnp.pad(t, ((0, 0), (0, 10))), jnp.array([6])
        )
        np.testing.assert_allclose(np.asarray(short), np.asarray(padded), rtol=1e-4, atol=1e-5)

    def test_hf_numerics_oracle(self):
        torch = pytest.importorskip("torch")
        from transformers import BertConfig as HFConfig, BertModel

        hf_cfg = HFConfig(
            vocab_size=128, hidden_size=32, intermediate_size=64,
            num_hidden_layers=2, num_attention_heads=4, max_position_embeddings=64,
        )
        torch.manual_seed(0)
        hf = BertModel(hf_cfg).eval()
        from gofr_tpu.models.convert import bert_from_hf

        cfg, params = bert_from_hf(hf)
        tokens = np.random.RandomState(1).randint(0, 128, (2, 7))
        with torch.no_grad():
            want = hf(torch.tensor(tokens)).last_hidden_state.numpy()
        got = np.asarray(bert.encode(cfg, params, jnp.asarray(tokens)))
        np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)


class TestViT:
    def test_forward_shapes(self):
        cfg = ViTConfig.tiny()
        params = vit.init(cfg, jax.random.key(0))
        images = jax.random.normal(jax.random.key(1), (2, 32, 32, 3))
        logits = vit.forward(cfg, params, images)
        assert logits.shape == (2, 10)

    def test_no_head_returns_embedding(self):
        cfg = ViTConfig.tiny(num_classes=0)
        params = vit.init(cfg, jax.random.key(0))
        out = vit.forward(cfg, params, jnp.zeros((1, 32, 32, 3)))
        assert out.shape == (1, cfg.hidden_size)

    def test_hf_numerics_oracle(self):
        torch = pytest.importorskip("torch")
        from transformers import ViTConfig as HFConfig, ViTForImageClassification

        hf_cfg = HFConfig(
            image_size=32, patch_size=8, num_channels=3, hidden_size=32,
            intermediate_size=64, num_hidden_layers=2, num_attention_heads=4,
            num_labels=10,
        )
        torch.manual_seed(0)
        hf = ViTForImageClassification(hf_cfg).eval()
        from gofr_tpu.models.convert import vit_from_hf

        cfg, params = vit_from_hf(hf)
        images = np.random.RandomState(2).randn(2, 3, 32, 32).astype(np.float32)
        with torch.no_grad():
            want = hf(torch.tensor(images)).logits.numpy()
        # ours is channels-last
        got = np.asarray(vit.forward(cfg, params, jnp.asarray(images.transpose(0, 2, 3, 1))))
        np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)


def test_param_count_sanity():
    assert param_count(llama.init(LlamaConfig.tiny(), jax.random.key(0))) > 50_000


class TestGPT2:
    def test_prefill_decode_matches_forward(self):
        """Greedy via prefill+decode_step must equal argmax of incremental
        dense forward — the engine-contract parity every family needs."""
        from gofr_tpu.models import GPT2Config, gpt2

        cfg = GPT2Config.tiny()
        params = gpt2.init(cfg, jax.random.key(5))
        prompt = [7, 3, 11, 20]
        n_new = 6

        seq = list(prompt)
        for _ in range(n_new):
            logits = gpt2.forward(cfg, params, jnp.asarray([seq], jnp.int32))
            seq.append(int(jnp.argmax(logits[0, -1])))
        want = seq[len(prompt):]

        cache = gpt2.make_cache(cfg, 2, 32)
        toks = jnp.asarray([prompt], jnp.int32)
        logits, cache = gpt2.prefill(cfg, params, toks, jnp.array([4]), cache, jnp.array([0]))
        got = [int(jnp.argmax(logits[0]))]
        pos = len(prompt)
        while len(got) < n_new:
            tokens = jnp.array([got[-1], 0], jnp.int32)
            positions = jnp.array([pos, 0], jnp.int32)
            logits, cache = gpt2.decode_step(cfg, params, tokens, positions, cache)
            got.append(int(jnp.argmax(logits[0])))
            pos += 1
        assert got == want

    def test_engine_serves_gpt2(self):
        from gofr_tpu.container import new_mock_container
        from gofr_tpu.models import GPT2Config, ModelSpec
        from gofr_tpu.tpu.engine import build_engine

        cfg = GPT2Config.tiny()
        eng = build_engine(ModelSpec(family="gpt2", task="generate", config=cfg),
                           new_mock_container(), seed=5, slots=2, max_len=48,
                           max_prefill_batch=2, quantize="int8")
        try:
            out = eng.generate([7, 3, 11], max_new_tokens=5, timeout=120)
            assert len(out["tokens"]) == 5 and out["finish_reason"] == "length"
        finally:
            eng.stop()

    def test_hf_numerics_oracle(self):
        torch = pytest.importorskip("torch")
        from transformers import GPT2Config as HFConfig, GPT2LMHeadModel

        hf_cfg = HFConfig(
            vocab_size=128, n_embd=32, n_layer=2, n_head=4, n_positions=64,
        )
        torch.manual_seed(0)
        hf = GPT2LMHeadModel(hf_cfg).eval()
        from gofr_tpu.models import gpt2
        from gofr_tpu.models.convert import gpt2_from_hf

        cfg, params = gpt2_from_hf(hf, dtype=jnp.float32)
        tokens = np.random.RandomState(2).randint(0, 128, (2, 9))
        with torch.no_grad():
            want = hf(torch.tensor(tokens)).logits.numpy()
        got = np.asarray(gpt2.forward(cfg, params, jnp.asarray(tokens)))
        np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)
