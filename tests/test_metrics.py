import pytest

from gofr_tpu.logging import MockLogger
from gofr_tpu.metrics import Registry, sample_runtime_metrics

pytestmark = pytest.mark.quick


def test_counter_inc_and_expose():
    reg = Registry()
    c = reg.new_counter("app_requests_total", "total requests")
    c.inc()
    c.inc(2, path="/a")
    text = reg.expose_text()
    assert "# TYPE app_requests_total counter" in text
    assert "app_requests_total 1" in text
    assert 'app_requests_total{path="/a"} 2' in text


def test_histogram_buckets():
    reg = Registry()
    h = reg.new_histogram("lat", "latency", buckets=[0.1, 1.0])
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    text = reg.expose_text()
    assert 'lat_bucket{le="0.1"} 1' in text
    assert 'lat_bucket{le="1"} 2' in text
    assert 'lat_bucket{le="+Inf"} 3' in text
    assert "lat_count 3" in text
    assert h.count() == 3
    assert abs(h.sum() - 5.55) < 1e-9


def test_gauge_set():
    reg = Registry()
    g = reg.new_gauge("hbm", "hbm bytes")
    g.set(1024, device="0")
    assert 'hbm{device="0"} 1024' in reg.expose_text()


def test_register_idempotent_and_type_conflict():
    reg = Registry()
    a = reg.new_counter("x")
    b = reg.new_counter("x")
    assert a is b
    try:
        reg.new_gauge("x")
        raise AssertionError("expected ValueError")
    except ValueError:
        pass


def test_record_by_name():
    reg = Registry()
    reg.new_counter("c")
    reg.new_histogram("h")
    reg.new_gauge("g")
    reg.increment_counter("c", 3)
    reg.record_histogram("h", 0.01)
    reg.set_gauge("g", 7)
    text = reg.expose_text()
    assert "c 3" in text and "g 7" in text and "h_count 1" in text
    # unknown names are silently ignored (feature-off ergonomics)
    reg.increment_counter("missing")


def test_cardinality_warning():
    log = MockLogger()
    reg = Registry(logger=log)
    reg.new_counter("many")
    for i in range(25):
        reg.increment_counter("many", 1, k=str(i))
    assert any("cardinality" in r.get("message", "") for r in log.records)


def test_runtime_collect_hook():
    reg = Registry()
    reg.add_collect_hook(sample_runtime_metrics)
    text = reg.expose_text()
    assert "app_threads" in text
    assert "app_sys_memory_rss_bytes" in text
