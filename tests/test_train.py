"""Sharded train-step tests on the virtual 8-device mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gofr_tpu.models import LlamaConfig, llama
from gofr_tpu.parallel import ShardingRules, build_mesh
from gofr_tpu.parallel.sharding import fsdp_rules
from gofr_tpu.train import TrainState, cross_entropy_loss, make_train_step


def test_cross_entropy_uniform():
    logits = jnp.zeros((1, 3, 7))
    targets = jnp.array([[1, 2, 3]])
    loss = cross_entropy_loss(logits, targets)
    np.testing.assert_allclose(float(loss), np.log(7), rtol=1e-5)


def test_cross_entropy_mask():
    logits = jnp.zeros((1, 2, 4))
    # second position hugely wrong but masked out
    logits = logits.at[0, 1, 0].set(100.0)
    targets = jnp.array([[1, 1]])
    loss = cross_entropy_loss(logits, targets, mask=jnp.array([[1, 0]]))
    np.testing.assert_allclose(float(loss), np.log(4), rtol=1e-5)


@pytest.mark.parametrize("mesh_spec,rules", [
    ("dp:2,tp:4", ShardingRules()),
    ("dp:2,fsdp:2,tp:2", fsdp_rules()),
])
def test_train_step_loss_decreases(mesh_spec, rules):
    cfg = LlamaConfig.tiny()
    mesh = build_mesh(mesh_spec)
    init_fn, step_fn = make_train_step(cfg, llama, mesh, rules=rules)
    state = init_fn(jax.random.key(0))
    assert int(state.step) == 0

    tokens = jax.random.randint(jax.random.key(1), (8, 16), 0, cfg.vocab_size)
    lengths = jnp.full((8,), 16, jnp.int32)
    losses = []
    for _ in range(5):
        state, metrics = step_fn(state, tokens, lengths)
        losses.append(float(metrics["loss"]))
    assert int(state.step) == 5
    assert losses[-1] < losses[0], losses
    assert all(np.isfinite(losses))
    assert float(metrics["grad_norm"]) > 0


def test_sharded_matches_single_device():
    """One train step on the mesh == one step on a single device."""
    cfg = LlamaConfig.tiny()
    tokens = jax.random.randint(jax.random.key(1), (4, 8), 0, cfg.vocab_size)
    lengths = jnp.full((4,), 8, jnp.int32)

    mesh1 = build_mesh("dp:1", devices=jax.devices()[:1])
    init1, step1 = make_train_step(cfg, llama, mesh1)
    s1, m1 = step1(init1(jax.random.key(0)), tokens, lengths)

    mesh8 = build_mesh("dp:2,fsdp:2,tp:2")
    init8, step8 = make_train_step(cfg, llama, mesh8, rules=fsdp_rules())
    s8, m8 = step8(init8(jax.random.key(0)), tokens, lengths)

    np.testing.assert_allclose(float(m1["loss"]), float(m8["loss"]), rtol=1e-4)
    # spot-check a param leaf after the update
    np.testing.assert_allclose(
        np.asarray(s1.params["final_norm"]), np.asarray(s8.params["final_norm"]), rtol=1e-4, atol=1e-5
    )


def test_remat_matches_no_remat():
    cfg = LlamaConfig.tiny()
    mesh = build_mesh("dp:2,tp:4")
    tokens = jax.random.randint(jax.random.key(2), (4, 8), 0, cfg.vocab_size)
    lengths = jnp.full((4,), 8, jnp.int32)
    init_a, step_a = make_train_step(cfg, llama, mesh)
    init_b, step_b = make_train_step(cfg, llama, mesh, remat=True)
    _, ma = step_a(init_a(jax.random.key(0)), tokens, lengths)
    _, mb = step_b(init_b(jax.random.key(0)), tokens, lengths)
    np.testing.assert_allclose(float(ma["loss"]), float(mb["loss"]), rtol=1e-5)


def test_padding_masked_out_of_loss():
    cfg = LlamaConfig.tiny()
    mesh = build_mesh("dp:8")
    init_fn, step_fn = make_train_step(cfg, llama, mesh)
    tokens = jax.random.randint(jax.random.key(3), (8, 12), 0, cfg.vocab_size)
    lengths = jnp.full((8,), 6, jnp.int32)
    # corrupt the padding region; loss must not change
    state = init_fn(jax.random.key(0))
    _, m1 = step_fn(state, tokens, lengths)
    corrupted = tokens.at[:, 7:].set(1)
    state2 = init_fn(jax.random.key(0))
    _, m2 = step_fn(state2, corrupted, lengths)
    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-5)
