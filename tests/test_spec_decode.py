"""Speculative decoding (VERDICT r3 #6): prompt-lookup drafts + one-forward
verification must be BIT-IDENTICAL to plain greedy decode — acceptance rate
only changes how many device rounds it takes, never the tokens."""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gofr_tpu.container import new_mock_container
from gofr_tpu.models import LlamaConfig, llama
from gofr_tpu.tpu.engine import GenerateEngine


@pytest.fixture(scope="module")
def setup():
    cfg = LlamaConfig.tiny()
    params = llama.init(cfg, jax.random.key(7))

    def ref(prompt, n_new):
        seq = list(prompt)
        for _ in range(n_new):
            logits = llama.forward(cfg, params, jnp.asarray([seq], jnp.int32))
            seq.append(int(jnp.argmax(logits[0, -1])))
        return seq[len(prompt):]

    return cfg, params, ref


def make_engine(cfg, params, **kw):
    kw.setdefault("slots", 4)
    kw.setdefault("max_len", 64)
    kw.setdefault("max_prefill_batch", 2)
    kw.setdefault("decode_chunk", 4)
    kw.setdefault("spec_tokens", 3)
    return GenerateEngine(llama, cfg, params, new_mock_container(), **kw)


def _counter(eng, name):
    m = eng.metrics.get(name)
    return sum(m._values.values()) if m is not None else 0


def test_verify_step_matches_sequential_decode(setup):
    """llama.verify_step over [input, d1, d2] must produce the same
    next-token logits as running decode_step on each token sequentially,
    and leave an equivalent cache behind."""
    cfg, params, _ = setup
    prompt = [5, 3, 9, 11]
    seq_cache = llama.make_cache(cfg, 2, 32)
    ver_cache = llama.make_cache(cfg, 2, 32)
    logits, seq_cache = llama.prefill(
        cfg, params, jnp.asarray([prompt, prompt], jnp.int32),
        jnp.asarray([4, 4], jnp.int32), seq_cache, jnp.asarray([0, 1], jnp.int32))
    _, ver_cache = llama.prefill(
        cfg, params, jnp.asarray([prompt, prompt], jnp.int32),
        jnp.asarray([4, 4], jnp.int32), ver_cache, jnp.asarray([0, 1], jnp.int32))
    t0 = int(jnp.argmax(logits[0]))

    # sequential: three decode steps
    toks, seq_logits = [t0], []
    pos = 4
    for _ in range(3):
        lg, seq_cache = llama.decode_step(
            cfg, params, jnp.asarray([toks[-1]] * 2, jnp.int32),
            jnp.asarray([pos, pos], jnp.int32), seq_cache)
        seq_logits.append(np.asarray(lg[0]))
        toks.append(int(jnp.argmax(lg[0])))
        pos += 1

    # verification: one forward over the same three tokens
    ver_logits, ver_cache = llama.verify_step(
        cfg, params, jnp.asarray([toks[:3], toks[:3]], jnp.int32),
        jnp.asarray([4, 4], jnp.int32), ver_cache)
    for j in range(3):
        np.testing.assert_allclose(
            np.asarray(ver_logits[0, j]), seq_logits[j], rtol=2e-4, atol=2e-4)


class TestSpecEngine:
    def test_single_request_matches_reference(self, setup):
        cfg, params, ref = setup
        eng = make_engine(cfg, params)
        try:
            out = eng.generate([5, 3, 9], max_new_tokens=12, timeout=120)
            assert out["tokens"] == ref([5, 3, 9], 12)
            assert out["finish_reason"] == "length"
            assert _counter(eng, "app_tpu_spec_proposed") > 0
        finally:
            eng.stop()

    def test_concurrent_requests_match_reference(self, setup):
        cfg, params, ref = setup
        eng = make_engine(cfg, params)
        prompts = [[i + 1, (2 * i) % 200 + 1, (7 * i) % 150] for i in range(8)]
        want = [ref(p, 8) for p in prompts]
        results = [None] * len(prompts)

        def worker(i):
            results[i] = eng.generate(prompts[i], max_new_tokens=8, timeout=300)

        try:
            threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=300)
            for i, r in enumerate(results):
                assert r is not None, f"request {i} did not complete"
                assert r["tokens"] == want[i], f"request {i} diverged under speculation"
        finally:
            eng.stop()

    def test_acceptance_happens_on_cyclic_output(self, setup):
        """Greedy decode from random weights falls into cycles, so the
        prompt-lookup draft must land real acceptances (the premise behind
        the throughput win; measured 35-50%% on this model class)."""
        cfg, params, ref = setup
        eng = make_engine(cfg, params)
        try:
            out = eng.generate([5, 3, 9], max_new_tokens=40, timeout=300)
            assert out["tokens"] == ref([5, 3, 9], 40)
            assert _counter(eng, "app_tpu_spec_accepted") > 0, (
                "no draft token ever accepted over a 40-token cyclic generation"
            )
        finally:
            eng.stop()

    def test_eos_mid_round_truncates(self, setup):
        cfg, params, ref = setup
        want = ref([5, 3, 9], 20)
        eos = want[5]  # force a stop partway through
        eng = make_engine(cfg, params, eos_token_id=eos)
        try:
            out = eng.generate([5, 3, 9], max_new_tokens=20, timeout=120)
            assert out["finish_reason"] == "stop"
            assert out["tokens"] == want[:5]
        finally:
            eng.stop()

    @pytest.mark.parametrize("layout_kw", [
        {}, {"kv_layout": "paged", "page_size": 8}, {"top_k": 5},
        {"top_p": 0.9}, {"kv_layout": "paged", "page_size": 8, "top_k": 5},
    ])
    def test_sampled_requests_served(self, setup, layout_kw):
        """Round 5: spec serves SAMPLED requests on BOTH layouts through
        distribution-exact rejection sampling (speculative_sample),
        composing with top_k/top_p (p and q truncated identically)."""
        cfg, params, ref = setup
        eng = make_engine(cfg, params, **layout_kw)
        try:
            out = eng.generate([5, 3, 9], max_new_tokens=12, temperature=0.8,
                               timeout=300)
            assert len(out["tokens"]) == 12
            # greedy and sampled requests mix in the same engine — and
            # greedy stays BIT-EXACT alongside (truncation keeps top-1)
            out2 = eng.generate([5, 3, 9], max_new_tokens=6, timeout=300)
            assert out2["tokens"] == ref([5, 3, 9], 6)
        finally:
            eng.stop()

    def test_paged_layout_matches_reference(self, setup):
        """Speculation on the PAGED layout (llama's default): verification
        writes route through block tables, pages for the worst-case span
        are allocated before each round, and greedy stays bit-exact — with
        the prefix cache active alongside."""
        cfg, params, ref = setup
        eng = make_engine(cfg, params, kv_layout="paged", page_size=8)
        try:
            prompt = [(11 * i) % 190 + 1 for i in range(20)]
            out = eng.generate(prompt, max_new_tokens=12, timeout=120)
            assert out["tokens"] == ref(prompt, 12)
            # again through a prefix hit; spec + prefix must compose
            out2 = eng.generate(prompt, max_new_tokens=12, timeout=120)
            assert out2["tokens"] == ref(prompt, 12)
            assert _counter(eng, "app_tpu_prefix_hit_tokens") > 0
            from gofr_tpu.testutil import assert_paged_pool_consistent

            assert_paged_pool_consistent(eng, slots_empty=True)
        finally:
            eng.stop()

    def test_paged_spec_pool_pressure(self, setup):
        """Worst-case-span page allocation under a tight pool: preemption
        and speculation interleave without diverging or leaking pages."""
        cfg, params, ref = setup
        eng = make_engine(cfg, params, kv_layout="paged", page_size=8,
                          total_pages=14, slots=4)
        prompts = [[i + 1, (3 * i) % 200 + 1, (5 * i) % 150] for i in range(4)]
        want = [ref(p, 12) for p in prompts]
        results = [None] * 4

        def worker(i):
            results[i] = eng.generate(prompts[i], max_new_tokens=12, timeout=300)

        try:
            threads = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=300)
            for i, r in enumerate(results):
                assert r is not None, f"request {i} did not complete"
                assert r["tokens"] == want[i], f"request {i} diverged"
            from gofr_tpu.testutil import assert_paged_pool_consistent

            assert_paged_pool_consistent(eng, slots_empty=True)
        finally:
            eng.stop()


def test_gpt2_spec_decode_matches_reference():
    """verify_step parity beyond llama: gpt2 (learned positional
    embeddings, fused-qkv biases) speculates bit-exactly too."""
    from gofr_tpu.models import GPT2Config, gpt2

    cfg = GPT2Config.tiny()
    params = gpt2.init(cfg, jax.random.key(5))

    def ref(prompt, n_new):
        seq = list(prompt)
        for _ in range(n_new):
            logits = gpt2.forward(cfg, params, jnp.asarray([seq], jnp.int32))
            seq.append(int(jnp.argmax(logits[0, -1])))
        return seq[len(prompt):]

    eng = GenerateEngine(gpt2, cfg, params, new_mock_container(),
                         slots=2, max_len=64, max_prefill_batch=1,
                         decode_chunk=4, spec_tokens=3)
    try:
        out = eng.generate([5, 3, 9], max_new_tokens=12, timeout=120)
        assert out["tokens"] == ref([5, 3, 9], 12)
    finally:
        eng.stop()


class TestPipelinedSpec:
    """Slot-layout spec rounds ride the pipelined dispatch queue (round 5):
    spec state — (token, hlen) carry and the token history — is device-
    resident, so chunk t+1 dispatches before chunk t's readback. Tokens
    must stay bit-identical to plain greedy decode at every depth."""

    def test_depth2_matches_depth1_and_reference(self, setup):
        cfg, params, ref = setup
        prompts = [[i + 2, (3 * i) % 180 + 1, (11 * i) % 90 + 1] for i in range(6)]
        want = [ref(p, 10) for p in prompts]
        for depth in (1, 2):
            eng = make_engine(cfg, params, decode_pipeline=depth)
            try:
                results = [None] * len(prompts)

                def worker(i):
                    results[i] = eng.generate(prompts[i], max_new_tokens=10, timeout=300)

                ts = [threading.Thread(target=worker, args=(i,)) for i in range(len(prompts))]
                for t in ts:
                    t.start()
                for t in ts:
                    t.join()
                assert [r["tokens"] for r in results] == want, f"depth={depth}"
            finally:
                eng.stop()

    def test_chunked_prefill_seeds_device_history(self, setup):
        """A prompt longer than the largest prefill bucket goes through
        chunked prefill, whose offset writes must seed the device-resident
        history correctly (tpu/programs.py _seed_hist with offsets) — a
        wrong hist row would change prompt-lookup drafts but NOT the
        verified output (bit-exactness), so assert acceptances still land
        AND tokens match."""
        cfg, params, ref = setup
        eng = make_engine(cfg, params, max_len=64,
                          prefill_buckets=[8], slots=2, max_prefill_batch=1)
        try:
            prompt = [7, 3, 7, 3, 7, 3, 7, 3, 7, 3, 7, 3]  # > bucket 8, cyclic
            out = eng.generate(prompt, max_new_tokens=12, timeout=300)
            assert out["tokens"] == ref(prompt, 12)
            assert _counter(eng, "app_tpu_spec_accepted") > 0
        finally:
            eng.stop()

    def test_mixed_lengths_mask_and_rejoin(self, setup):
        """Lanes with different max_total hit the worst-case masking bound
        (pos + chunk_span*inflight >= max_total) at different times; every
        request must still match the reference exactly."""
        cfg, params, ref = setup
        eng = make_engine(cfg, params, decode_pipeline=2, decode_chunk=2,
                          spec_tokens=2)
        try:
            prompts = [[9, 4, 9, 4], [5, 5, 5], [8, 1, 2, 3], [6, 6]]
            budgets = [3, 17, 9, 24]
            want = [ref(p, b) for p, b in zip(prompts, budgets)]
            results = [None] * len(prompts)

            def worker(i):
                results[i] = eng.generate(
                    prompts[i], max_new_tokens=budgets[i], timeout=300)

            ts = [threading.Thread(target=worker, args=(i,)) for i in range(len(prompts))]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            assert [r["tokens"] for r in results] == want
        finally:
            eng.stop()


class TestDraftModelSpec:
    """Draft-MODEL speculative decoding (round 5): g autoregressive steps
    of a small draft model on device propose the continuation, the target
    verifies in one forward. Verification is unchanged, so tokens are
    bit-identical to plain greedy decode REGARDLESS of the draft — only
    the acceptance rate moves."""

    def test_self_draft_accepts_everything(self, setup):
        """With the target as its own draft, every proposal matches the
        target's greedy choice: acceptance must be 100% and tokens exact."""
        cfg, params, ref = setup
        eng = make_engine(cfg, params, spec_draft=(llama, cfg, params))
        try:
            prompt = [5, 3, 9, 2]
            out = eng.generate(prompt, max_new_tokens=10, timeout=300)
            assert out["tokens"] == ref(prompt, 10)
            prop = _counter(eng, "app_tpu_spec_proposed")
            acc = _counter(eng, "app_tpu_spec_accepted")
            assert prop > 0
            # only whole-round padding (lanes idle in the fixed-shape
            # program) and end-of-generation truncation separate the two
            assert acc >= 0.5 * prop
        finally:
            eng.stop()

    def test_random_draft_still_bit_exact(self, setup):
        """A randomly-initialized draft proposes near-garbage; the verify
        forward must reject it and still emit exactly the reference."""
        cfg, params, ref = setup
        dparams = llama.init(cfg, jax.random.key(99))
        eng = make_engine(cfg, params, spec_draft=(llama, cfg, dparams))
        try:
            prompts = [[i + 2, (5 * i) % 170 + 1, (9 * i) % 110 + 1] for i in range(5)]
            want = [ref(p, 9) for p in prompts]
            results = [None] * len(prompts)

            def worker(i):
                results[i] = eng.generate(prompts[i], max_new_tokens=9, timeout=300)

            ts = [threading.Thread(target=worker, args=(i,)) for i in range(len(prompts))]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            assert [r["tokens"] for r in results] == want
        finally:
            eng.stop()

    def test_chunked_prefill_seeds_draft_cache(self, setup):
        """Long prompts stream through chunked prefill; the draft cache
        must be prefilled chunk-by-chunk too (offset writes) or its
        proposals would diverge silently — bit-exactness still holds
        either way, so ALSO require perfect acceptance with a self-draft."""
        cfg, params, ref = setup
        eng = make_engine(cfg, params, max_len=64, prefill_buckets=[8],
                          slots=2, max_prefill_batch=1,
                          spec_draft=(llama, cfg, params))
        try:
            prompt = [(7 * i) % 150 + 1 for i in range(13)]  # > bucket 8
            out = eng.generate(prompt, max_new_tokens=10, timeout=300)
            assert out["tokens"] == ref(prompt, 10)
            prop = _counter(eng, "app_tpu_spec_proposed")
            acc = _counter(eng, "app_tpu_spec_accepted")
            assert prop > 0 and acc >= 0.5 * prop
        finally:
            eng.stop()

    def test_draft_requires_spec_tokens(self, setup):
        # (paged rejection is covered in test_matrix.TestRejectedCombinations)
        cfg, params, _ = setup
        from gofr_tpu.container import new_mock_container
        with pytest.raises(ValueError, match="spec_tokens"):
            GenerateEngine(llama, cfg, params, new_mock_container(),
                           slots=2, max_len=64,
                           spec_draft=(llama, cfg, params))


def test_gpt2_draft_model_spec():
    """The draft path is family-protocol-generic: gpt2 drafting for a gpt2
    target (self-draft => full agreement) stays bit-exact and accepts."""
    from gofr_tpu.models import GPT2Config, gpt2

    cfg = GPT2Config.tiny()
    params = gpt2.init(cfg, jax.random.key(5))

    def ref(prompt, n_new):
        seq = list(prompt)
        for _ in range(n_new):
            logits = gpt2.forward(cfg, params, jnp.asarray([seq], jnp.int32))
            seq.append(int(jnp.argmax(logits[0, -1])))
        return seq[len(prompt):]

    eng = GenerateEngine(gpt2, cfg, params, new_mock_container(),
                         slots=2, max_len=64, max_prefill_batch=1,
                         decode_chunk=4, spec_tokens=3,
                         spec_draft=(gpt2, cfg, params))
    try:
        out = eng.generate([5, 3, 9], max_new_tokens=12, timeout=300)
        assert out["tokens"] == ref([5, 3, 9], 12)
        assert _counter(eng, "app_tpu_spec_accepted") > 0
    finally:
        eng.stop()


def test_cancel_and_timeout_mid_pipelined_spec(setup):
    """Requests cancelled/expired while spec rounds are IN FLIGHT must
    complete with their error, free their slots for reuse, and leave
    survivors bit-exact (slot-identity discard under the pipelined queue)."""
    cfg, params, ref = setup
    eng = make_engine(cfg, params, decode_pipeline=2, decode_chunk=2,
                      spec_tokens=2, slots=4)
    try:
        victim1 = eng.submit([9, 9, 9], max_new_tokens=40)
        victim2 = eng.submit([8, 8, 8], max_new_tokens=40, timeout=0.05)
        survivor = eng.submit([5, 3, 9, 2], max_new_tokens=12)
        time.sleep(0.2)
        victim1.cancel()
        out = survivor.result(timeout=300)
        assert out["tokens"] == ref([5, 3, 9, 2], 12)
        for v in (victim1, victim2):
            with pytest.raises(Exception):
                v.result(timeout=60)
        # slots all free again; a fresh request is exact
        out2 = eng.generate([2, 4, 6], max_new_tokens=8, timeout=300)
        assert out2["tokens"] == ref([2, 4, 6], 8)
    finally:
        eng.stop()


class TestSpeculativeSample:
    """Distribution guarantee of the rejection-sampling core
    (tpu/programs.speculative_sample): position-0 output must be
    distributed exactly as the target softmax, for both deterministic
    (one-hot q) and draft-model proposals — and T<=0 rows must reduce
    bit-exactly to greedy."""

    V = 11

    def _marginal(self, p_logits, drafts, temps, q_logits, n_keys=20000):
        from gofr_tpu.tpu.programs import speculative_sample

        keys = jax.random.split(jax.random.key(0), n_keys)
        outs, _ = jax.vmap(
            lambda k: speculative_sample(k, p_logits, drafts, temps, q_logits)
        )(keys)
        first = np.asarray(outs[:, 0, 0])  # lane 0, position 0
        return np.bincount(first, minlength=self.V) / n_keys

    def test_lookup_proposal_marginal_matches_target(self):
        p_logits = jax.random.normal(jax.random.key(3), (1, 3, self.V)) * 2.0
        drafts = jnp.asarray([[4, 7]], jnp.int32)
        temps = jnp.asarray([1.0], jnp.float32)
        want = np.asarray(jax.nn.softmax(p_logits[0, 0]))
        got = self._marginal(p_logits, drafts, temps, None)
        assert np.abs(got - want).sum() < 0.05, (got, want)

    def test_draft_model_proposal_marginal_matches_target(self):
        """The guarantee holds when proposals are SAMPLED from q (as the
        spec program does) — the combined draw+accept+correct pipeline's
        output must be distributed as the target softmax even though q is
        a very different distribution."""
        from gofr_tpu.tpu.programs import speculative_sample

        p_logits = jax.random.normal(jax.random.key(5), (1, 3, self.V)) * 2.0
        q_logits = jax.random.normal(jax.random.key(6), (1, 2, self.V)) * 2.0
        temps = jnp.asarray([0.7], jnp.float32)

        def one(k):
            kd, ks = jax.random.split(k)
            drafts = jax.random.categorical(
                kd, q_logits[0] / 0.7, axis=-1).astype(jnp.int32)[None, :]
            out, acc = speculative_sample(ks, p_logits, drafts, temps, q_logits)
            return out

        n_keys = 20000
        keys = jax.random.split(jax.random.key(0), n_keys)
        outs = jax.vmap(one)(keys)
        got = np.bincount(np.asarray(outs[:, 0, 0]), minlength=self.V) / n_keys
        want = np.asarray(jax.nn.softmax(p_logits[0, 0] / 0.7))
        assert np.abs(got - want).sum() < 0.05, (got, want)

    def test_greedy_rows_reduce_to_argmax(self):
        from gofr_tpu.tpu.programs import speculative_sample

        p_logits = jax.random.normal(jax.random.key(9), (2, 4, self.V))
        am = np.asarray(jnp.argmax(p_logits, -1))  # [2, 4]
        # lane 0: drafts follow the argmax chain -> all accepted + bonus;
        # lane 1: first draft wrong -> correction at position 0
        drafts = jnp.asarray([[am[0, 0], am[0, 1], am[0, 2]],
                              [(am[1, 0] + 1) % self.V, am[1, 1], am[1, 2]]],
                             jnp.int32)
        temps = jnp.zeros((2,), jnp.float32)
        out, acc = speculative_sample(
            jax.random.key(1), p_logits, drafts, temps, None)
        out, acc = np.asarray(out), np.asarray(acc)
        assert acc.tolist() == [3, 0]
        assert out[0, :4].tolist() == am[0].tolist()  # drafts + bonus
        assert out[1, 0] == am[1, 0]  # correction = the argmax


    def test_truncated_marginal_matches_truncated_target(self):
        """With top_k, the emitted marginal must equal the TRUNCATED
        target softmax — the same distribution plain top_k sampling
        serves — for the deterministic-proposal case."""
        from gofr_tpu.ops.sampling import truncate_logits
        from gofr_tpu.tpu.programs import speculative_sample

        p_logits = jax.random.normal(jax.random.key(12), (1, 3, self.V)) * 2.0
        drafts = jnp.asarray([[4, 7]], jnp.int32)
        temps = jnp.asarray([0.9], jnp.float32)
        n_keys = 20000
        keys = jax.random.split(jax.random.key(2), n_keys)
        outs, _ = jax.vmap(
            lambda k: speculative_sample(k, p_logits, drafts, temps, None,
                                         top_k=3)
        )(keys)
        got = np.bincount(np.asarray(outs[:, 0, 0]), minlength=self.V) / n_keys
        want = np.asarray(jax.nn.softmax(
            truncate_logits(p_logits[0, 0] / 0.9, top_k=3)))
        assert np.abs(got - want).sum() < 0.05, (got, want)
        assert (got[want < 1e-6] == 0).all(), "mass outside the top-k set"
