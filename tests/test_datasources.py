from dataclasses import dataclass

import pytest

from gofr_tpu.config import DictConfig
from gofr_tpu.container import Container, new_mock_container
from gofr_tpu.datasource import DatasourceError
from gofr_tpu.datasource.file import LocalFileSystem
from gofr_tpu.datasource.kv import KVStore
from gofr_tpu.datasource.sql import connect_sql, insert_query, update_query
from gofr_tpu.logging import MockLogger
from gofr_tpu.metrics import Registry

pytestmark = pytest.mark.quick
from gofr_tpu.migration import Migration, run_migrations
from gofr_tpu.pubsub.inmemory import InMemoryBroker


def make_db():
    reg = Registry()
    reg.new_histogram("app_sql_stats")
    return connect_sql(DictConfig({"DB_DIALECT": "sqlite"}), MockLogger(), reg), reg


def test_sql_query_exec_and_metrics():
    db, reg = make_db()
    db.execute("CREATE TABLE t (id INTEGER PRIMARY KEY, name TEXT)")
    db.execute("INSERT INTO t (id, name) VALUES (?, ?)", (1, "a"))
    rows = db.query("SELECT * FROM t")
    assert rows[0].name == "a"
    assert reg.get("app_sql_stats").count(type="exec") == 2
    assert reg.get("app_sql_stats").count(type="query") == 1
    assert db.health_check()["status"] == "UP"


def test_sql_select_into_dataclass():
    db, _ = make_db()
    db.execute("CREATE TABLE u (id INTEGER, name TEXT, extra TEXT)")
    db.execute("INSERT INTO u VALUES (1, 'x', 'ignored')")

    @dataclass
    class U:
        id: int
        name: str

    users = db.select_into(U, "SELECT * FROM u")
    assert users == [U(1, "x")]


def test_sql_transaction_rollback():
    db, _ = make_db()
    db.execute("CREATE TABLE t (id INTEGER PRIMARY KEY)")
    with pytest.raises(RuntimeError):
        with db.begin() as tx:
            tx.execute("INSERT INTO t VALUES (1)")
            raise RuntimeError("abort")
    assert db.query("SELECT COUNT(*) AS n FROM t")[0].n == 0
    with db.begin() as tx:
        tx.execute("INSERT INTO t VALUES (2)")
    assert db.query("SELECT COUNT(*) AS n FROM t")[0].n == 1


def test_sql_error_wrapped():
    db, _ = make_db()
    with pytest.raises(DatasourceError):
        db.query("SELECT * FROM missing_table")


def test_query_builder_quoting():
    assert insert_query("users", ["id", "name"], "sqlite") == 'INSERT INTO "users" ("id", "name") VALUES (?, ?)'
    assert insert_query("users", ["id"], "mysql") == "INSERT INTO `users` (`id`) VALUES (?)"
    # injection attempt is stripped from identifiers
    assert '"userssemicolon"' not in update_query('users;drop', ["a"], "id", "sqlite")
    assert "drop" in update_query("usersdrop", ["a"], "id", "sqlite")  # sanity


def test_migrations_apply_once_and_rollback():
    c = new_mock_container()
    c.sql, _ = make_db()
    ran = []
    migrations = {
        1: Migration(up=lambda d: (d.sql.execute("CREATE TABLE m1 (x INTEGER)"), ran.append(1))),
        2: Migration(up=lambda d: ran.append(2)),
    }
    assert run_migrations(migrations, c) == [1, 2]
    # idempotent second run
    assert run_migrations(migrations, c) == []
    assert ran == [1, 2]

    def bad(d):
        d.sql.execute("INSERT INTO m1 VALUES (9)")
        raise RuntimeError("migration fails")

    with pytest.raises(RuntimeError):
        run_migrations({3: Migration(up=bad)}, c)
    # rolled back: the insert from the failed migration is gone
    assert c.sql.query("SELECT COUNT(*) AS n FROM m1")[0].n == 0
    # version 3 not recorded
    assert c.sql.query_row("SELECT MAX(version) AS v FROM gofr_migrations")["v"] == 2


class FakeRedis:
    """Dict-backed stand-in honoring the wire shapes the migration RedisTx
    relies on: pipeline() buffers commands and applies them on execute()
    (MULTI/EXEC markers included, like a real server's transaction)."""

    def __init__(self):
        self.data: dict[str, object] = {}
        self.executed_pipelines = 0

    def get(self, key):
        v = self.data.get(key)
        return v if v is None else (v if isinstance(v, bytes) else str(v).encode())

    def hget(self, key, field):
        h = self.data.get(key) or {}
        v = h.get(field)
        return v if v is None else str(v).encode()

    def hgetall(self, key):
        h = self.data.get(key) or {}
        return {k.encode(): str(v).encode() for k, v in h.items()}

    def keys(self, pattern="*"):
        return [k.encode() for k in self.data]

    def _apply(self, parts):
        cmd = str(parts[0]).upper()
        if cmd in ("MULTI", "EXEC"):
            return
        if cmd == "SET":
            self.data[parts[1]] = parts[2]
        elif cmd == "DEL":
            for k in parts[1:]:
                self.data.pop(k, None)
        elif cmd == "HSET":
            self.data.setdefault(parts[1], {})[parts[2]] = parts[3]
        elif cmd == "LPUSH":
            self.data.setdefault(parts[1], []).extend(parts[2:])
        elif cmd == "INCR":
            self.data[parts[1]] = int(self.data.get(parts[1], 0)) + 1
        elif cmd == "EXPIRE":
            pass
        else:
            raise AssertionError(f"FakeRedis: unhandled command {cmd}")

    def pipeline(self):
        fake = self

        class _Pipe:
            def __init__(self):
                self.commands = []

            def command(self, *args):
                self.commands.append(args)
                return self

            def execute(self):
                for parts in self.commands:
                    fake._apply(parts)
                fake.executed_pipelines += 1
                self.commands = []
                return []

        return _Pipe()


def test_failing_migration_leaves_no_partial_redis_state():
    """VERDICT r3 missing #1: a migration that writes Redis then fails must
    leave NOTHING behind — writes buffer in a RedisTx and only ship as one
    MULTI/EXEC at commit (reference redis.go:78-127 TxPipeline semantics)."""
    c = new_mock_container()
    c.sql, _ = make_db()
    c.redis = FakeRedis()

    def bad(d):
        d.redis.set("feature_flag", "on")
        d.redis.hset("settings", "mode", "new")
        d.sql.execute("CREATE TABLE mr (x INTEGER)")
        raise RuntimeError("boom after redis writes")

    with pytest.raises(RuntimeError):
        run_migrations({1: Migration(up=bad)}, c)
    assert c.redis.data == {}, "failed migration leaked partial Redis state"
    assert c.redis.executed_pipelines == 0
    # SQL side also rolled back and unrecorded
    assert c.sql.query_row("SELECT MAX(version) AS v FROM gofr_migrations")["v"] is None


def test_migration_redis_writes_commit_atomically_with_record():
    c = new_mock_container()
    c.sql, _ = make_db()
    c.redis = FakeRedis()

    def up(d):
        d.redis.set("greeting", "hi")
        d.redis.hset("settings", "mode", "new")
        d.pubsub.create_topic("orders")  # broker topic migration (interface.go:28-31)

    assert run_migrations({1: Migration(up=up)}, c) == [1]
    assert c.redis.data["greeting"] == "hi"
    assert c.redis.data["settings"] == {"mode": "new"}
    assert c.redis.executed_pipelines == 1, "writes + record must ship as ONE pipeline"
    assert "1" in c.redis.data["gofr_migrations"]
    assert "orders" in c.pubsub.topics()
    # second run: version 1 skipped on BOTH bookkeeping sources
    assert run_migrations({1: Migration(up=up)}, c) == []


def test_redis_only_migrations_run_without_sql():
    """The reference runs migrations with any transactional datasource
    wired (migration.go:110-155); SQL must not be mandatory."""
    c = new_mock_container()
    c.sql = None
    c.redis = FakeRedis()
    ran = []
    assert run_migrations({1: Migration(up=lambda d: (d.redis.incr("n"), ran.append(1)))}, c) == [1]
    assert run_migrations({1: Migration(up=lambda d: ran.append("again"))}, c) == []
    assert ran == [1]
    assert c.redis.data["n"] == 1


class FakeDBAPIConn:
    """Cursor-style DBAPI stand-in (pymysql/psycopg2 shape) capturing the
    SQL actually sent, so the mysql/postgres dialect plumbing — '%s'
    placeholder normalization through the _DBAPIAdapter — is exercised
    without a server (VERDICT r3 weak #9)."""

    def __init__(self):
        self.executed: list[tuple[str, tuple]] = []
        self.commits = 0
        self.rollbacks = 0

    def cursor(self):
        conn = self

        class _Cur:
            description = [("n",)]
            rowcount = 1

            def execute(self, q, params=()):
                conn.executed.append((q, tuple(params)))
                if "boom" in q:
                    raise RuntimeError("server error")

            def executemany(self, q, seq):
                for p in seq:
                    conn.executed.append((q, tuple(p)))

            def fetchall(self):
                return [(1,)]

        return _Cur()

    def commit(self):
        self.commits += 1

    def rollback(self):
        self.rollbacks += 1


@pytest.mark.parametrize("dialect", ["mysql", "postgres"])
def test_dbapi_dialects_normalize_placeholders(dialect):
    from gofr_tpu.datasource.sql import DB, _DBAPIAdapter

    conn = FakeDBAPIConn()
    db = DB(_DBAPIAdapter(conn), dialect, MockLogger(), None, placeholder="%s")
    db.execute("INSERT INTO t (a, b) VALUES (?, ?)", (1, "x"))
    assert conn.executed[-1] == ("INSERT INTO t (a, b) VALUES (%s, %s)", (1, "x"))
    assert conn.commits == 1

    rows = db.query("SELECT n FROM t WHERE a = ?", (1,))
    assert rows[0].n == 1
    assert conn.executed[-1][0] == "SELECT n FROM t WHERE a = %s"

    db.execute_many("INSERT INTO t (a) VALUES (?)", [(1,), (2,)])
    assert conn.executed[-1] == ("INSERT INTO t (a) VALUES (%s)", (2,))

    with pytest.raises(DatasourceError):
        db.execute("boom")
    assert conn.rollbacks == 1  # failed exec clears transaction state

    # dialect-aware CRUD quoting flows through the same builder
    q = insert_query("t", ["a"], dialect)
    assert q == ("INSERT INTO `t` (`a`) VALUES (?)" if dialect == "mysql"
                 else 'INSERT INTO "t" ("a") VALUES (?)')


def test_connect_sql_missing_driver_warns_not_raises():
    """Reference semantics: unreachable/unconfigured datasources log and
    stay unwired instead of failing the app (sql.go:43-46)."""
    from gofr_tpu.datasource.sql import connect_sql

    logger = MockLogger()
    reg = Registry()
    reg.new_histogram("app_sql_stats")
    assert connect_sql(DictConfig({"DB_DIALECT": "mysql"}), logger, reg) is None
    assert connect_sql(DictConfig({"DB_DIALECT": "nosuchdb"}), logger, reg) is None


def test_kv_store_roundtrip(tmp_path):
    kv = KVStore(str(tmp_path / "kv.db"))
    kv.set("a", b"1")
    kv.set("a", "2")
    assert kv.get("a") == b"2"
    assert kv.get("missing") is None
    kv.delete("a")
    assert kv.get("a") is None
    assert kv.health_check()["status"] == "UP"


def test_file_datasource_row_readers(tmp_path):
    fs = LocalFileSystem(str(tmp_path))
    fs.create("data.json", b'[{"a": 1}, {"a": 2}]')
    assert list(fs.read_rows("data.json")) == [{"a": 1}, {"a": 2}]
    fs.create("data.csv", b"x,y\n1,2\n3,4\n")
    assert list(fs.read_rows("data.csv")) == [{"x": "1", "y": "2"}, {"x": "3", "y": "4"}]
    fs.create("data.jsonl", b'{"b": 1}\n{"b": 2}\n')
    assert list(fs.read_rows("data.jsonl")) == [{"b": 1}, {"b": 2}]
    fs.create("plain.txt", b"l1\nl2\n")
    assert list(fs.read_rows("plain.txt")) == ["l1", "l2"]
    fs.mkdir_all("sub/dir")
    assert fs.exists("sub/dir")
    fs.rename("plain.txt", "renamed.txt")
    assert fs.exists("renamed.txt") and not fs.exists("plain.txt")


def test_inmemory_broker_at_least_once():
    b = InMemoryBroker()
    b.publish("t", {"n": 1})
    b.publish("t", {"n": 2})
    m1 = b.subscribe("t", "g", timeout=0.1)
    assert m1.bind(dict) == {"n": 1}
    # not committed → rewind redelivers
    b.rewind_uncommitted("t", "g")
    m1b = b.subscribe("t", "g", timeout=0.1)
    assert m1b.bind(dict) == {"n": 1}
    m1b.commit()
    m2 = b.subscribe("t", "g", timeout=0.1)
    assert m2.bind(dict) == {"n": 2}
    # different group sees everything from the start
    mg2 = b.subscribe("t", "other", timeout=0.1)
    assert mg2.bind(dict) == {"n": 1}
    # empty → timeout returns None
    assert b.subscribe("empty", "g", timeout=0.05) is None


def test_container_health_aggregation():
    c = new_mock_container()
    c.sql, _ = make_db()

    class DownDS:
        def health_check(self):
            return {"status": "DOWN", "details": {}}

    c.redis = DownDS()
    h = c.health()
    assert h["status"] == "DEGRADED"
    assert h["services"]["sql"]["status"] == "UP"
    assert h["services"]["redis"]["status"] == "DOWN"


def test_container_config_gating():
    c = Container.create(DictConfig({}))
    assert c.sql is None and c.redis is None and c.pubsub is None and c.kv is None
    assert c.file is not None  # always wired (container.go:123)
    c2 = Container.create(DictConfig({"DB_DIALECT": "sqlite"}))
    assert c2.sql is not None


def test_inmemory_broker_concurrent_commit_keeps_at_least_once():
    """With concurrent consumer workers, a fast worker's higher-offset commit
    must NOT acknowledge a slower worker's uncommitted message: the group
    offset advances only across the contiguous committed prefix, so a rewind
    (crash/restart) redelivers the gap."""
    from gofr_tpu.pubsub.inmemory import InMemoryBroker

    b = InMemoryBroker()
    for i in range(3):
        b.publish("t", {"n": i})
    m0 = b.subscribe("t", group="g", timeout=1)   # worker A takes offset 0
    m1 = b.subscribe("t", group="g", timeout=1)   # worker B takes offset 1
    m2 = b.subscribe("t", group="g", timeout=1)
    assert [m.bind()["n"] for m in (m0, m1, m2)] == [0, 1, 2]
    m1.commit()   # B succeeds first (out of order)
    m2.commit()
    # A's handler failed: never commits. Offset must still sit at 0.
    b.rewind_uncommitted("t", group="g")
    redelivered = b.subscribe("t", group="g", timeout=1)
    assert redelivered is not None and redelivered.bind()["n"] == 0, (
        "failed message was lost — at-least-once violated"
    )
    redelivered.commit()
    # prefix now complete: 0,1,2 all committed — nothing left to redeliver
    b.rewind_uncommitted("t", group="g")
    assert b.subscribe("t", group="g", timeout=0.1) is None
    b.close()


def test_redis_exec_failure_leaves_durable_pending_marker():
    """ADVICE r4: when SQL commits but the Redis EXEC dies, the version
    must stay marked UP:redis-pending (a durable SQL marker), and the
    NEXT run_migrations must refuse to start — never silently skip the
    version's Redis writes forever."""
    c = new_mock_container()
    c.sql, _ = make_db()

    class ExplodingPipeRedis(FakeRedis):
        def pipeline(self):
            class _Boom:
                def command(self, *a):
                    return self

                def execute(self):
                    raise ConnectionError("redis died at EXEC")

            return _Boom()

    c.redis = ExplodingPipeRedis()

    def up(d):
        d.sql.execute("CREATE TABLE pend (x INTEGER)")
        d.redis.set("flag", "on")

    with pytest.raises(ConnectionError):
        run_migrations({1: Migration(up=up)}, c)
    row = c.sql.query_row("SELECT method FROM gofr_migrations WHERE version = 1")
    assert row["method"] == "UP:redis-pending"

    # rerun refuses loudly instead of skipping the lost Redis writes
    with pytest.raises(RuntimeError, match="redis-pending"):
        run_migrations({2: Migration(up=lambda d: None)}, c)

    # operator replays + clears the marker -> runs proceed
    c.sql.execute("UPDATE gofr_migrations SET method = 'UP' WHERE version = 1")
    c.redis = FakeRedis()
    assert run_migrations({2: Migration(up=lambda d: d.redis.set("k", "v"))}, c) == [2]
    assert c.sql.query_row(
        "SELECT method FROM gofr_migrations WHERE version = 2")["method"] == "UP"


def test_file_provider_seam_wires_hooks_and_health():
    """FileSystemProvider seam (reference `file/file.go:69-78`): a remote-FS
    provider swapped in via add_file_store gets the plugin wiring
    (use_logger/use_metrics/connect, in that contract order), replaces
    container.file for handlers, and joins health aggregation."""
    from gofr_tpu.datasource.file import FileSystemProvider, InMemoryFileSystem

    c = new_mock_container()
    fs = InMemoryFileSystem(bucket="b1")
    assert isinstance(fs, FileSystemProvider)
    assert isinstance(LocalFileSystem("."), FileSystemProvider)
    assert fs.health_check()["status"] == "DOWN"  # remote client pre-connect

    c.add_file_store(fs)
    assert c.file is fs
    assert fs.connected and fs.logger is c.logger and fs.metrics is c.metrics
    assert c.health()["services"]["file"]["status"] == "UP"


def test_inmemory_file_provider_full_surface():
    from gofr_tpu.datasource.file import InMemoryFileSystem

    fs = InMemoryFileSystem()
    fs.connect()
    fs.mkdir("data")
    with pytest.raises(FileExistsError):
        fs.mkdir("data")
    fs.mkdir_all("a/b/c")
    assert fs.exists("a/b")
    fs.create("data/rows.jsonl", b'{"a": 1}\n{"a": 2}\n')
    assert list(fs.read_rows("data/rows.jsonl")) == [{"a": 1}, {"a": 2}]
    fs.create("data/notes.txt", b"x\ny\n")
    assert list(fs.read_rows("data/notes.txt")) == ["x", "y"]
    assert fs.list("data") == ["notes.txt", "rows.jsonl"]
    assert fs.open("data/notes.txt").read() == b"x\ny\n"
    assert fs.stat("data/notes.txt").st_size == 4
    fs.rename("data/notes.txt", "data/notes2.txt")
    assert fs.exists("data/notes2.txt") and not fs.exists("data/notes.txt")
    fs.remove("data/notes2.txt")
    with pytest.raises(FileNotFoundError):
        fs.read("data/notes2.txt")
    with pytest.raises(FileNotFoundError):
        fs.create("nodir/x.txt", b"")  # parent must exist, like a real FS
    fs.remove_all("data")
    assert not fs.exists("data") and not fs.exists("data/rows.jsonl")
    # dotfile names survive normalization intact (".env" is a FILE NAME,
    # not path structure) and stay distinct from their dotless sibling
    fs.create(".env", b"A=1\n")
    fs.create("env", b"other\n")
    assert fs.read(".env") == b"A=1\n" and fs.read("env") == b"other\n"
    assert sorted(n for n in fs.list(".") if "env" in n) == [".env", "env"]
    # traversal above the root is clipped, like the local provider's chroot
    assert fs.read("../.env") == b"A=1\n"
