"""Speculative rounds INSIDE the unified pipeline (ISSUE 13 tentpole b):
paged spec_rounds no longer run synchronously — ``dispatch_spec_paged``
enqueues each round onto the engine's one bounded in-flight queue
(``engine._dq``) with worst-case page over-claim at dispatch and surplus
trim at fold. This module proves the OVERLAP (a spec round is dispatched
while an older entry is still in flight), and drills the allocator edges
the over-claim creates: cancellation mid-round, preemption under a tight
pool, and trim-at-fold accounting — zero page leaks throughout
(testutil.assert_page_refs_consistent). Token exactness of paged spec vs
plain greedy lives in tests/test_spec_decode.py; this file is about the
queue discipline and page lifecycle."""

import collections
import threading
import time

import jax
import pytest

from gofr_tpu.container import new_mock_container
from gofr_tpu.models import LlamaConfig, llama
from gofr_tpu.testutil import (
    assert_page_refs_consistent,
    assert_paged_pool_consistent,
)
from gofr_tpu.tpu.engine import GenerateEngine

pytestmark = pytest.mark.quick


@pytest.fixture(scope="module")
def setup():
    cfg = LlamaConfig.tiny()
    params = llama.init(cfg, jax.random.key(7))

    def ref(prompt, n_new):
        import jax.numpy as jnp

        seq = list(prompt)
        for _ in range(n_new):
            logits = llama.forward(cfg, params, jnp.asarray([seq], jnp.int32))
            seq.append(int(jnp.argmax(logits[0, -1])))
        return seq[len(prompt):]

    return cfg, params, ref


def make_engine(cfg, params, **kw):
    kw.setdefault("slots", 4)
    kw.setdefault("max_len", 64)
    kw.setdefault("max_prefill_batch", 2)
    kw.setdefault("decode_chunk", 2)
    kw.setdefault("spec_tokens", 2)
    kw.setdefault("kv_layout", "paged")
    kw.setdefault("page_size", 8)
    return GenerateEngine(llama, cfg, params, new_mock_container(), **kw)


class _SpyDeque(collections.deque):
    """Drop-in _dq that records, at every dispatch, what kind of entry
    went in and how deep the queue already was — the direct witness that
    spec rounds ride the pipelined queue instead of serializing."""

    def __init__(self):
        super().__init__()
        self.events = []  # (kind, depth_before_append)

    def append(self, entry):
        self.events.append((entry[0], len(self)))
        super().append(entry)


class _QueueSpy:
    def __init__(self, eng):
        self._eng = eng

    def __enter__(self):
        spy = _SpyDeque()
        spy.extend(self._eng._dq)
        self._eng._dq = spy
        self.events = spy.events
        return self

    def __exit__(self, *exc):
        pass  # the spy stays a perfectly good deque


def test_spec_rounds_ride_the_inflight_queue(setup):
    """With pipeline depth 2, some spec round must be APPENDED while an
    older entry is still un-processed (depth_before >= 1): speculation is
    pipelined, not a synchronous side-channel. Tokens stay exact."""
    cfg, params, ref = setup
    eng = make_engine(cfg, params, decode_pipeline=2)
    prompts = [[i + 1, (3 * i) % 200 + 1, (5 * i) % 150 + 1] for i in range(4)]
    want = [ref(p, 12) for p in prompts]
    results = [None] * 4
    try:
        with _QueueSpy(eng) as spy:
            threads = [
                threading.Thread(
                    target=lambda i=i: results.__setitem__(
                        i, eng.generate(prompts[i], max_new_tokens=12,
                                        timeout=300)))
                for i in range(4)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=300)
        for i, r in enumerate(results):
            assert r is not None and r["tokens"] == want[i], f"request {i}"
        kinds = {k for k, _ in spy.events}
        assert "spec" in kinds, f"no spec round ever dispatched: {kinds}"
        assert any(k == "spec" and depth >= 1 for k, depth in spy.events), (
            "every spec round was dispatched against an empty queue — "
            f"speculation is NOT overlapping readback: {spy.events[:20]}")
        assert_page_refs_consistent(eng)
    finally:
        eng.stop()


def test_depth_one_keeps_spec_synchronous(setup):
    """ENGINE_PIPELINE=1 is the debugging escape hatch: every spec round
    must see an EMPTY queue at dispatch (fully synchronous), and tokens
    still match the reference."""
    cfg, params, ref = setup
    eng = make_engine(cfg, params, decode_pipeline=1)
    try:
        with _QueueSpy(eng) as spy:
            out = eng.generate([5, 3, 9], max_new_tokens=10, timeout=300)
        assert out["tokens"] == ref([5, 3, 9], 10)
        spec_depths = [d for k, d in spy.events if k == "spec"]
        assert spec_depths and max(spec_depths) == 0, spec_depths
    finally:
        eng.stop()


def test_cancel_mid_spec_round_releases_overclaimed_pages(setup):
    """Cancel a request while its spec rounds (and their over-claimed
    pages) are in flight: the victim completes with its error, the
    surplus pages return to the free list, and a survivor stays exact."""
    cfg, params, ref = setup
    eng = make_engine(cfg, params, decode_pipeline=2)
    try:
        victim = eng.submit([9, 9, 9], max_new_tokens=40)
        survivor = eng.submit([5, 3, 9, 2], max_new_tokens=12)
        time.sleep(0.2)
        victim.cancel()
        out = survivor.result(timeout=300)
        assert out["tokens"] == ref([5, 3, 9, 2], 12)
        with pytest.raises(Exception):
            victim.result(timeout=60)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            with eng._state_lock:
                if all(s is None for s in eng.slots) and not eng._dq:
                    break
            time.sleep(0.02)
        assert_paged_pool_consistent(eng, slots_empty=True)
    finally:
        eng.stop()


def test_overclaim_trims_to_actual_position_at_fold(setup):
    """After a generation finishes, no lane may keep pages beyond what its
    final position needs: the dispatch-time worst-case claim
    (pos + chunk_span * (inflight + 1) - 1) must have been trimmed back by
    the fold (engine._trim_lane_pages). With the engine idle, every
    non-prefix-cached page is back on the free list."""
    cfg, params, _ = setup
    eng = make_engine(cfg, params, decode_pipeline=2, prefix_cache=False)
    try:
        eng.generate([7, 1, 4], max_new_tokens=9, timeout=300)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            with eng._state_lock:
                if all(s is None for s in eng.slots) and not eng._dq:
                    break
            time.sleep(0.02)
        with eng._state_lock:
            held = int(eng._page_refs[eng._page_sink:].sum())
        assert held == 0, f"{held} pages leaked past the fold's trim"
        assert_paged_pool_consistent(eng, slots_empty=True)
    finally:
        eng.stop()


def test_preemption_under_tight_pool_with_pipelined_spec(setup):
    """Worst-case-span over-claim against a pool that cannot hold every
    lane's worst case at once: preemption, speculation, and the pipelined
    queue interleave without deadlock, divergence, or page leaks."""
    cfg, params, ref = setup
    eng = make_engine(cfg, params, total_pages=14, decode_pipeline=2)
    prompts = [[i + 1, (3 * i) % 200 + 1, (5 * i) % 150 + 1] for i in range(4)]
    want = [ref(p, 12) for p in prompts]
    results = [None] * 4
    try:
        threads = [
            threading.Thread(
                target=lambda i=i: results.__setitem__(
                    i, eng.generate(prompts[i], max_new_tokens=12,
                                    timeout=300)))
            for i in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        for i, r in enumerate(results):
            assert r is not None and r["tokens"] == want[i], f"request {i}"
        assert_paged_pool_consistent(eng, slots_empty=True)
    finally:
        eng.stop()
