"""MoE routing op + Mixtral family: routing invariants, decode/prefill
parity with the full forward, expert-parallel training on the CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np

from gofr_tpu.models import MixtralConfig, mixtral
from gofr_tpu.ops.moe import default_capacity, moe_ffn, route_topk
from gofr_tpu.parallel import ShardingRules, build_mesh, shard_pytree
from gofr_tpu.train import make_train_step


class TestRouting:
    def test_dispatch_combine_shapes_and_mass(self):
        t, e, k, cap = 16, 4, 2, 16
        logits = jax.random.normal(jax.random.key(0), (t, e))
        r = route_topk(logits, k=k, capacity=cap)
        assert r.dispatch.shape == (t, e, cap)
        # with ample capacity every token keeps k slots, combine sums to 1
        np.testing.assert_allclose(np.asarray(jnp.sum(r.dispatch, axis=(1, 2))), np.full(t, k))
        np.testing.assert_allclose(
            np.asarray(jnp.sum(r.combine, axis=(1, 2))), np.ones(t), atol=1e-6
        )

    def test_each_slot_holds_at_most_one_token(self):
        logits = jax.random.normal(jax.random.key(1), (32, 4))
        r = route_topk(logits, k=2, capacity=4)
        per_slot = np.asarray(jnp.sum(r.dispatch, axis=0))  # [E, C]
        assert per_slot.max() <= 1.0 + 1e-6

    def test_capacity_drops_overflow(self):
        # all tokens prefer expert 0 → only `cap` survive
        logits = jnp.tile(jnp.array([[10.0, 0.0, 0.0, 0.0]]), (16, 1))
        r = route_topk(logits, k=1, capacity=4)
        assert float(jnp.sum(r.dispatch[:, 0])) == 4.0
        # dropped tokens have zero combine mass
        kept = np.asarray(jnp.sum(r.combine, axis=(1, 2)))
        assert (kept[:4] > 0.9).all() and (kept[4:] < 1e-6).all()

    def test_aux_loss_uniform_is_one(self):
        # perfectly uniform router → aux == 1 (its minimum)
        logits = jnp.zeros((64, 8))
        r = route_topk(logits, k=2, capacity=32)
        np.testing.assert_allclose(float(r.aux_loss), 1.0, atol=1e-5)

    def test_capacity_formula(self):
        assert default_capacity(64, 8, 2, 1.0) == 16
        assert default_capacity(1, 8, 1, 1.25) == 1


class TestMoeFFN:
    def test_output_finite_and_differentiable(self):
        key = jax.random.key(0)
        t, d, e, m = 8, 16, 4, 32
        x = jax.random.normal(key, (t, d))
        ks = jax.random.split(key, 4)
        router = jax.random.normal(ks[0], (d, e)) * 0.1
        wg = jax.random.normal(ks[1], (e, d, m)) * 0.1
        wu = jax.random.normal(ks[2], (e, d, m)) * 0.1
        wd = jax.random.normal(ks[3], (e, m, d)) * 0.1

        def f(x):
            y, aux = moe_ffn(x, router, wg, wu, wd, k=2)
            return jnp.sum(y**2) + aux

        g = jax.grad(f)(x)
        assert np.isfinite(np.asarray(g)).all()


class TestMixtral:
    cfg = MixtralConfig.tiny()

    def test_forward_shapes(self):
        params = mixtral.init(self.cfg, jax.random.key(0))
        tokens = jax.random.randint(jax.random.key(1), (2, 16), 0, self.cfg.vocab_size)
        logits = mixtral.forward(self.cfg, params, tokens, jnp.array([16, 10], jnp.int32))
        assert logits.shape == (2, 16, self.cfg.vocab_size)
        assert np.isfinite(np.asarray(logits)).all()

    def test_prefill_decode_matches_forward(self):
        """Greedy generate via cache == argmax of the full forward re-run."""
        cfg = self.cfg
        params = mixtral.init(cfg, jax.random.key(0))
        prompt = [3, 11, 7, 1]
        toks = list(prompt)
        for _ in range(3):
            t = jnp.array([toks], jnp.int32)
            lg = mixtral.forward(cfg, params, t, jnp.array([len(toks)], jnp.int32))
            toks.append(int(jnp.argmax(lg[0, len(toks) - 1])))
        want = toks[len(prompt):]

        cache = mixtral.make_cache(cfg, slots=2, max_len=32)
        lg, cache = mixtral.prefill(
            cfg, params, jnp.array([prompt], jnp.int32), jnp.array([4], jnp.int32),
            cache, jnp.array([0], jnp.int32),
        )
        got = [int(jnp.argmax(lg[0]))]
        pos = len(prompt)
        tok_v = jnp.zeros((2,), jnp.int32)
        pos_v = jnp.zeros((2,), jnp.int32)
        for _ in range(2):
            tok_v = tok_v.at[0].set(got[-1])
            pos_v = pos_v.at[0].set(pos)
            lg2, cache = mixtral.decode_step(cfg, params, tok_v, pos_v, cache)
            got.append(int(jnp.argmax(lg2[0])))
            pos += 1
        assert got == want

    def test_expert_parallel_matches_single(self):
        """Same forward, ep-sharded params vs unsharded — GSPMD numerics."""
        cfg = self.cfg
        params = mixtral.init(cfg, jax.random.key(0))
        tokens = jax.random.randint(jax.random.key(1), (2, 16), 0, cfg.vocab_size)
        lengths = jnp.array([16, 16], jnp.int32)
        want = mixtral.forward(cfg, params, tokens, lengths)

        mesh = build_mesh("ep:4,tp:2")
        sharded = shard_pytree(params, mixtral.param_axes(cfg), ShardingRules(), mesh)
        got = mixtral.forward(cfg, sharded, tokens, lengths)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-4, rtol=2e-4)

    def test_train_step_ep(self):
        mesh = build_mesh("dp:2,ep:2,tp:2")
        cfg = self.cfg
        init_fn, step_fn = make_train_step(cfg, mixtral, mesh)
        state = init_fn(jax.random.key(0))
        tokens = jax.random.randint(jax.random.key(1), (4, 16), 0, cfg.vocab_size)
        lengths = jnp.full((4,), 16, jnp.int32)
        state, metrics = step_fn(state, tokens, lengths)
        l0 = float(metrics["loss"])
        assert np.isfinite(l0)
        for _ in range(3):
            state, metrics = step_fn(state, tokens, lengths)
        assert float(metrics["loss"]) < l0


class TestMixtralServing:
    def test_generate_engine_serves_mixtral(self):
        """The continuous-batching engine is family-generic: a registered MoE
        family serves through the same GenerateEngine as llama."""
        from gofr_tpu.container import new_mock_container
        from gofr_tpu.tpu.engine import GenerateEngine

        # ample capacity: parity with the dense forward needs no drops
        cfg = MixtralConfig.tiny(capacity_factor=4.0)
        params = mixtral.init(cfg, jax.random.key(3))
        eng = GenerateEngine(mixtral, cfg, params, new_mock_container(),
                             slots=2, max_len=32, max_prefill_batch=2)
        try:
            want = []
            seq = [4, 9, 2]
            for _ in range(4):
                lg = mixtral.forward(cfg, params, jnp.asarray([seq], jnp.int32))
                seq.append(int(jnp.argmax(lg[0, -1])))
                want.append(seq[-1])
            out = eng.generate([4, 9, 2], max_new_tokens=4, timeout=120)
            assert out["tokens"] == want
            assert out["finish_reason"] == "length"
        finally:
            eng.stop()
