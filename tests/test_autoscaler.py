"""The elastic-fleet contracts (ISSUE 11, docs/resilience.md):

- quick tier — the ScaleDecider's pure decision math (hysteresis, cooldown,
  clamp, stale-signal freeze) with fake clocks, the spawn retry/backoff and
  drain-abort chaos handling at the Autoscaler level, the registry's
  ``draining`` membership transitions, and the zero-drop requeue helper;
- engine tier — the scale-in drain drill on real paged engines: a replica
  put into ``draining`` mid-stream finishes the stream token-exact, a
  queued request requeues to a peer and completes, page accounting balances
  after retire, and death-mid-drain chaos re-admits the victim with the
  fleet routable and the control loop live.
"""

from __future__ import annotations

import time

import pytest

from gofr_tpu.fleet import chaos
from gofr_tpu.fleet.autoscaler import (
    AutoscalePolicy,
    Autoscaler,
    FleetSignals,
    LocalEngineFleet,
    ScaleDecider,
    requeue,
)
from gofr_tpu.http.errors import RequestTimeout, ServiceUnavailable

# -- fakes ---------------------------------------------------------------------


def _sig(burn=None, wait=0.0, replicas=1, age=0.0):
    return FleetSignals(burn=burn, predicted_wait_s=wait,
                        replicas=replicas, age_s=age)


POLICY = AutoscalePolicy(
    min_replicas=1, max_replicas=3, burn_out=2.0, burn_in=1.0,
    wait_out_s=2.0, wait_in_s=0.25, sustain_s=3.0, idle_s=10.0,
    cooldown_out_s=5.0, cooldown_in_s=20.0, stale_s=5.0)


class FakeDriver:
    def __init__(self, n=1, fail_spawns=0, fail_drain=False):
        self.n = n
        self.fail_spawns = fail_spawns
        self.fail_drain = fail_drain
        self.spawned: list[str] = []
        self.readmitted: list[str] = []
        self.retired: list[str] = []

    def count(self):
        return self.n

    def spawn(self):
        if self.fail_spawns > 0:
            self.fail_spawns -= 1
            raise RuntimeError("spawn failed")
        self.n += 1
        name = f"rep{self.n}"
        self.spawned.append(name)
        return name

    def pick_victim(self):
        return f"rep{self.n}" if self.n > 1 else None

    def drain(self, name, timeout_s):
        if self.fail_drain:
            raise RuntimeError("replica died mid-drain")
        return True

    def readmit(self, name):
        self.readmitted.append(name)

    def retire(self, name):
        self.n -= 1
        self.retired.append(name)


def _autoscaler(driver, policy=POLICY, signals=None, clock=None):
    sleeps: list[float] = []
    t = {"now": 0.0}
    return Autoscaler(
        driver, policy,
        signals=signals or (lambda: _sig()),
        now=(clock or (lambda: t["now"])),
        sleep=sleeps.append), sleeps


# -- quick tier: decision math -------------------------------------------------


@pytest.mark.quick
class TestScaleDecider:
    def test_scale_out_requires_sustained_pressure(self):
        d = ScaleDecider(POLICY)
        assert d.decide(_sig(burn=5.0), 0.0) == "hold"   # just got hot
        assert d.decide(_sig(burn=5.0), 2.9) == "hold"   # not sustained yet
        assert d.decide(_sig(burn=5.0), 3.0) == "out"    # sustain_s reached

    def test_predicted_wait_is_an_independent_pressure_signal(self):
        d = ScaleDecider(POLICY)
        assert d.decide(_sig(wait=9.0), 0.0) == "hold"
        assert d.decide(_sig(wait=9.0), 3.5) == "out"

    def test_pressure_blip_resets_the_sustain_clock(self):
        d = ScaleDecider(POLICY)
        d.decide(_sig(burn=5.0), 0.0)
        d.decide(_sig(burn=0.1, wait=0.0), 1.0)          # calm blip
        assert d.decide(_sig(burn=5.0), 2.0) == "hold"   # clock restarted
        assert d.decide(_sig(burn=5.0), 5.0) == "out"

    def test_hysteresis_band_never_acts(self):
        # burn between burn_in and burn_out, wait between wait_in and
        # wait_out: neither hot nor calm, so neither streak accumulates
        d = ScaleDecider(POLICY)
        for t in range(0, 100, 2):
            assert d.decide(_sig(burn=1.5, wait=1.0, replicas=2), float(t)) == "hold"

    def test_cooldown_blocks_consecutive_scale_outs(self):
        d = ScaleDecider(POLICY)
        assert d.decide(_sig(burn=5.0), 3.0) == "hold"
        assert d.decide(_sig(burn=5.0), 6.5) == "out"
        d.note_action(6.5)
        # still hot, sustain re-accumulates from the action; cooldown_out_s
        # (5) < sustain_s re-accumulation (3) from 6.5 → out again at 9.5+
        assert d.decide(_sig(burn=5.0, replicas=2), 7.0) == "hold"
        assert d.decide(_sig(burn=5.0, replicas=2), 9.9) == "hold"
        assert d.decide(_sig(burn=5.0, replicas=2), 11.6) == "out"

    def test_clamp_holds_at_max_and_min(self):
        d = ScaleDecider(POLICY)
        d.decide(_sig(burn=5.0, replicas=3), 0.0)
        assert d.decide(_sig(burn=5.0, replicas=3), 10.0) == "hold"  # at max
        d2 = ScaleDecider(POLICY)
        d2.decide(_sig(replicas=1), 0.0)
        assert d2.decide(_sig(replicas=1), 50.0) == "hold"           # at min

    def test_scale_in_requires_sustained_idle_and_long_cooldown(self):
        d = ScaleDecider(POLICY)
        assert d.decide(_sig(replicas=2), 0.0) == "hold"
        assert d.decide(_sig(replicas=2), 9.0) == "hold"
        assert d.decide(_sig(replicas=2), 25.0) == "in"
        d.note_action(25.0)
        assert d.decide(_sig(replicas=2), 30.0) == "hold"  # cooldown_in_s=20
        assert d.decide(_sig(replicas=2), 46.0) == "in"

    def test_stale_signals_freeze_and_clear_streaks(self):
        d = ScaleDecider(POLICY)
        d.decide(_sig(burn=5.0), 0.0)
        d.decide(_sig(burn=5.0), 2.9)
        # gossip silence: no decision on fiction, and the pressure streak
        # must NOT survive the gap (it may be a different world after)
        assert d.decide(_sig(burn=5.0, age=6.0), 3.0) == "freeze"
        assert d.decide(_sig(burn=5.0), 4.0) == "hold"
        assert d.decide(_sig(burn=5.0), 6.9) == "hold"
        assert d.decide(_sig(burn=5.0), 7.1) == "out"

    def test_no_burn_evidence_plus_empty_queue_is_calm(self):
        # an idle fleet has no latency samples at all (burn=None): with the
        # queue empty too, that IS calm — otherwise a quiet fleet could
        # never scale in
        d = ScaleDecider(POLICY)
        d.decide(_sig(burn=None, wait=0.0, replicas=2), 0.0)
        assert d.decide(_sig(burn=None, wait=0.0, replicas=2), 25.0) == "in"

    def test_inverted_thresholds_rejected(self):
        with pytest.raises(ValueError, match="scale-in"):
            AutoscalePolicy(burn_out=1.0, burn_in=2.0)
        with pytest.raises(ValueError, match="FLEET_AUTOSCALE_MAX"):
            AutoscalePolicy(min_replicas=3, max_replicas=2)


@pytest.mark.quick
class TestAutoscalerChaos:
    def test_spawn_chaos_retries_with_backoff_then_succeeds(self):
        drv = FakeDriver(n=1)
        a, sleeps = _autoscaler(drv)
        with chaos.override("autoscale.spawn:raise,nth=1"):
            assert a._scale_out() is not None
        assert drv.n == 2
        assert sleeps == [a.policy.spawn_backoff_s]  # one backoff, then won

    def test_permanent_spawn_failure_leaves_loop_live_and_cooled(self):
        drv = FakeDriver(n=1)
        a, sleeps = _autoscaler(drv)
        with chaos.override("autoscale.spawn:raise"):
            assert a._scale_out() is None  # gave up this tick, no raise
        assert drv.n == 1
        assert len(sleeps) == a.policy.spawn_retries - 1
        # cooldown engaged even though nothing spawned: the next hot tick
        # must not hammer the failing driver
        assert a.decider._last_action_at == 0.0
        assert a.step(now=0.1) == "hold"

    def test_drain_abort_readmits_victim(self):
        drv = FakeDriver(n=2, fail_drain=True)
        a, _ = _autoscaler(drv)
        assert a._scale_in() is None
        assert drv.readmitted == ["rep2"]
        assert drv.retired == []
        assert drv.n == 2  # fleet unchanged, still routable

    def test_clean_drain_retires(self):
        drv = FakeDriver(n=2)
        a, _ = _autoscaler(drv)
        assert a._scale_in() == "rep2"
        assert drv.retired == ["rep2"]
        assert drv.n == 1

    def test_signal_source_failure_freezes(self):
        drv = FakeDriver(n=1)

        def bad_signals():
            raise RuntimeError("gossip silent")

        a, _ = _autoscaler(drv, signals=bad_signals)
        assert a.step(now=0.0) == "freeze"
        assert a.step(now=100.0) == "freeze"  # still live, still frozen

    def test_step_counts_decisions(self):
        from gofr_tpu.container import new_mock_container

        c = new_mock_container()
        drv = FakeDriver(n=1)
        a = Autoscaler(drv, POLICY, signals=lambda: _sig(),
                       metrics=c.metrics, now=lambda: 0.0, sleep=lambda s: None)
        a.step(now=0.0)
        m = c.metrics.get("app_fleet_autoscale_decisions_total")
        assert m.value(decision="hold") == 1


# -- quick tier: registry draining transitions ---------------------------------


@pytest.mark.quick
class TestRegistryDraining:
    def _registry(self):
        from gofr_tpu.router.registry import ReplicaRegistry
        from gofr_tpu.router.ring import HashRing

        t = {"now": 0.0}
        reg = ReplicaRegistry(HashRing(), ttl_s=0.0, jitter_s=0.0,
                              now=lambda: t["now"])
        return reg, t

    def test_draining_leaves_both_rings(self):
        reg, _ = self._registry()
        for name in ("a", "b"):
            reg.observe({"replica": name, "url": f"http://{name}", "epoch": 1})
        assert set(reg.ring.members()) == {"a", "b"}
        reg.observe({"replica": "a", "epoch": 1, "draining": True})
        r = reg.get("a")
        assert not r.in_ring and r.drop_reason == "draining"
        # unlike a restart window, the FULL ring gives the keys up too:
        # every class migrates to the successor, nothing sheds
        assert reg.ring.members() == ["b"]
        assert reg.full.members() == ["b"]

    def test_drain_abort_readmits_without_epoch_bump(self):
        reg, _ = self._registry()
        reg.observe({"replica": "a", "epoch": 4})
        reg.observe({"replica": "a", "epoch": 4, "draining": True})
        assert not reg.get("a").in_ring
        # device state was never torn down, so the SAME epoch re-admits
        # (the strict bump gate is for restart windows only)
        reg.observe({"replica": "a", "epoch": 4, "draining": False})
        assert reg.get("a").in_ring

    def test_terminal_down_after_drain_stays_out(self):
        reg, _ = self._registry()
        reg.observe({"replica": "a", "epoch": 1})
        reg.observe({"replica": "a", "epoch": 1, "draining": True})
        reg.observe({"replica": "a", "epoch": 1, "status": "DOWN"})
        assert not reg.get("a").in_ring
        assert reg.full.members() == []

    def test_snapshot_carries_draining(self):
        reg, _ = self._registry()
        reg.observe({"replica": "a", "epoch": 1, "draining": True})
        assert reg.snapshot()[0]["draining"] is True

    def test_gossip_snapshot_reports_engine_drain(self):
        from gofr_tpu.container import new_mock_container
        from gofr_tpu.router.gossip import GossipReporter

        class _Eng:
            _draining = True
            _restarting = False
            _restarts = 0

            def health_check(self):
                return {"status": "UP", "details": {}}

        c = new_mock_container()
        c.register_engine("gen", _Eng())
        snap = GossipReporter(c, name="rep-a").snapshot()
        assert snap["draining"] is True
        assert snap["status"] == "UP"


# -- quick tier: zero-drop requeue ---------------------------------------------


@pytest.mark.quick
class TestRequeue:
    def _req(self, timeout=30.0, stream=False):
        from gofr_tpu.tpu.engine import Request

        return Request([1, 2, 3], {}, timeout, stream)

    class _Peer:
        metrics = None

        def __init__(self):
            import queue

            self._queue = queue.Queue()

    def test_moves_request_objects_to_peer(self):
        peer = self._Peer()
        reqs = [self._req(), self._req()]
        assert requeue(reqs, peer) == 2
        assert peer._queue.qsize() == 2
        assert peer._queue.get_nowait() is reqs[0]  # the OBJECT moved

    def test_cancelled_and_expired_complete_instead_of_travelling(self):
        peer = self._Peer()
        dead = self._req()
        dead.cancel("client_disconnect")
        spent = self._req(timeout=0.000001)
        time.sleep(0.01)
        assert requeue([dead, spent], peer) == 0
        assert peer._queue.qsize() == 0
        with pytest.raises(RequestTimeout):
            dead.result(1.0)

    def test_no_peer_sheds_retryable(self):
        req = self._req()
        assert requeue([req], None) == 0
        with pytest.raises(ServiceUnavailable):
            req.result(1.0)


# -- engine tier: the drain drill on real paged engines ------------------------


@pytest.fixture(scope="module")
def tiny():
    from gofr_tpu.testutil import greedy_reference, tiny_f32_llama

    cfg, params = tiny_f32_llama()
    return cfg, params, greedy_reference(cfg, params)


def _fleet(cfg, params, *, slots=2, registry=None):
    from gofr_tpu.container import new_mock_container
    from gofr_tpu.models import llama
    from gofr_tpu.tpu.engine import GenerateEngine

    cont = new_mock_container()

    def factory(name):
        eng = GenerateEngine(llama, cfg, params, cont, slots=slots,
                             max_len=64, kv_layout="paged", page_size=8,
                             prefill_buckets=[16])
        eng.start()
        return eng

    return LocalEngineFleet(factory, registry=registry), cont


class TestDrainDrill:
    PROMPT = [3, 7, 11, 3, 7, 11, 9, 1]
    QUEUED = [5, 2, 9, 4]
    NEW = 7

    def test_drain_finishes_stream_token_exact_and_requeues_queued(self, tiny):
        from gofr_tpu.router.registry import ReplicaRegistry
        from gofr_tpu.router.ring import HashRing
        from gofr_tpu.testutil import assert_page_refs_consistent

        cfg, params, ref = tiny
        reg = ReplicaRegistry(HashRing(), jitter_s=0.0)
        fleet, _ = _fleet(cfg, params, slots=1, registry=reg)
        try:
            victim, peer = fleet.spawn(), fleet.spawn()
            veng = fleet.engine(victim)
            # one stream mid-flight on the only slot, one request queued
            # behind it — the drain must finish the first token-exact on
            # the victim and move the second, as an OBJECT, to the peer
            streaming = veng.submit(self.PROMPT, max_new_tokens=self.NEW,
                                    timeout=60.0, stream=True)
            queued = veng.submit(self.QUEUED, max_new_tokens=self.NEW,
                                 timeout=60.0)
            deadline = time.monotonic() + 30.0
            while streaming.kw.get("_slot") is None and time.monotonic() < deadline:
                time.sleep(0.01)  # wait until the stream actually holds the slot
            assert streaming.kw.get("_slot") is not None
            assert fleet.drain(victim, timeout_s=60.0)
            assert streaming.result(60.0)["tokens"] == ref(self.PROMPT, self.NEW)
            assert queued.result(60.0)["tokens"] == ref(self.QUEUED, self.NEW)
            assert veng.drained()
            # zero-leak bar, not "mostly freed": page accounting must
            # balance exactly on the drained replica before it retires
            assert_page_refs_consistent(veng)
            fleet.retire(victim)
            assert not reg.get(victim).in_ring
            assert reg.get(victim).status == "DOWN"
            # the surviving fleet is routable: same prompt, same tokens
            assert (fleet.engine(peer).generate(
                self.PROMPT, max_new_tokens=self.NEW, timeout=60.0)["tokens"]
                == ref(self.PROMPT, self.NEW))
        finally:
            fleet.stop_all()

    def test_draining_engine_sheds_new_arrivals_retryable(self, tiny):
        cfg, params, _ = tiny
        fleet, _ = _fleet(cfg, params)
        try:
            name = fleet.spawn()
            eng = fleet.engine(name)
            eng.begin_drain()
            with pytest.raises(ServiceUnavailable):
                eng.submit(self.PROMPT, max_new_tokens=2, timeout=30.0)
            eng.abort_drain()
            out = eng.generate(self.PROMPT, max_new_tokens=2, timeout=60.0)
            assert len(out["tokens"]) == 2
        finally:
            fleet.stop_all()

    def test_death_mid_drain_readmits_and_fleet_stays_routable(self, tiny):
        from gofr_tpu.router.registry import ReplicaRegistry
        from gofr_tpu.router.ring import HashRing

        cfg, params, ref = tiny
        reg = ReplicaRegistry(HashRing(), jitter_s=0.0)
        fleet, _ = _fleet(cfg, params, registry=reg)
        a = Autoscaler(fleet, AutoscalePolicy(min_replicas=1, max_replicas=3),
                       signals=lambda: _sig(replicas=fleet.count()))
        try:
            fleet.spawn(), fleet.spawn()
            victim = fleet.pick_victim()
            with chaos.override("replica.drain:raise"):
                assert a._scale_in() is None  # chaos fault → abort, no raise
            # re-admitted: engine flag cleared, registry UP and in-ring,
            # and the replica actually serves again
            assert fleet.count() == 2
            assert not fleet.engine(victim)._draining
            assert reg.get(victim).in_ring
            assert (fleet.engine(victim).generate(
                self.PROMPT, max_new_tokens=self.NEW, timeout=60.0)["tokens"]
                == ref(self.PROMPT, self.NEW))
            # the control loop survived: a clean scale-in still works
            assert a._scale_in() is not None
            assert fleet.count() == 1
        finally:
            fleet.stop_all()

    def test_burn_pressure_spawns_warm_spare(self, tiny):
        """The elastic drill's scale-out half: drive real traffic past a
        class's TTFT objective so the live SLO plane reports fast-window
        burn, and verify the control loop turns that burn into a spawned
        spare the fleet then serves from."""
        from gofr_tpu.container import new_mock_container
        from gofr_tpu.models import llama
        from gofr_tpu.tpu.engine import GenerateEngine

        cfg, params, _ = tiny
        # an unmeetable TTFT objective + tiny min_samples: every request
        # burns, so pressure is deterministic on any machine speed
        cont = new_mock_container({
            "SLO_INTERACTIVE_TTFT_MS": "0.001", "SLO_MIN_SAMPLES": "3",
            "SLO_FAST_WINDOW_S": "60"})

        def factory(name):
            eng = GenerateEngine(llama, cfg, params, cont, slots=2,
                                 max_len=64, kv_layout="paged", page_size=8,
                                 prefill_buckets=[16])
            eng.start()
            return eng

        fleet = LocalEngineFleet(factory)
        policy = AutoscalePolicy(min_replicas=1, max_replicas=2,
                                 burn_out=1.5, sustain_s=0.0,
                                 cooldown_out_s=0.0)

        def signals():
            pr = cont.slo.pressure()
            return FleetSignals(burn=pr["burn"], predicted_wait_s=0.0,
                                replicas=fleet.count())

        a = Autoscaler(fleet, policy, signals=signals)
        try:
            first = fleet.spawn()
            for _ in range(4):
                fleet.engine(first).generate([3, 7, 9], max_new_tokens=2,
                                             timeout=60.0,
                                             qos_class="interactive")
            assert cont.slo.pressure()["burn"] >= policy.burn_out
            assert a.step() == "out"
            assert fleet.count() == 2
            spare = [n for n in fleet.names() if n != first][0]
            out = fleet.engine(spare).generate([3, 7, 9], max_new_tokens=2,
                                               timeout=60.0)
            assert len(out["tokens"]) == 2
        finally:
            fleet.stop_all()
