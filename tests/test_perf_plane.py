"""Live engine performance plane (metrics/perf.py): analytical cost
model vs hand-computed FLOPs/bytes for every step kind and all three KV
dtype planes, the shared bench/engine decode-MBU estimator, peak-table
resolution (GOFR_DEVICE_PEAKS / GOFR_TPU_PEAK_* overrides, unknown
silicon degrades to None), fake-clock ``_dq`` bubble accounting
(saturated pipeline ~0, forced stall rises, ``mark_no_work`` keeps true
idleness out), exact sum-of-parts merges (container + fleet federation —
never averaged ratios), the capture-bundle and ``/debug/perf`` surfaces,
and a live tiny-engine end-to-end check: ``/metrics`` exposes a non-zero
decode MBU and the bf16/int8/int4 plane widths order strictly."""

import asyncio
import json
from types import SimpleNamespace

import jax
import pytest

from gofr_tpu.container import new_mock_container
from gofr_tpu.metrics import federation, perf
from gofr_tpu.models import LlamaConfig, llama
from gofr_tpu.ops.paged import kv_plane_bytes_per_position
from gofr_tpu.tpu.engine import GenerateEngine

pytestmark = pytest.mark.quick


# -- cost model: hand-computed FLOPs/bytes per step kind -----------------------


def _model(**kw):
    base = dict(n_params=1000, weight_bytes=500.0, kv_bytes_per_pos=8.0,
                page_bytes=64.0, page_size=8, kv_dtype="bf16")
    base.update(kw)
    return perf.CostModel(**base)


class TestCostModel:
    def test_prefill(self):
        m = _model()
        flops, bytes_ = m.prefill(10)
        assert flops == 2 * 1000 * 10
        assert bytes_ == 500 + 10 * 8  # one weight pass + every KV write

    def test_chunk_pays_the_re_read(self):
        m = _model()
        flops, bytes_ = m.chunk(4, offset=6)
        assert flops == 2 * 1000 * 4
        # weights + attention re-read of offset+chunk cached positions + writes
        assert bytes_ == 500 + (6 + 4) * 8 + 4 * 8

    def test_decode(self):
        m = _model()
        flops, bytes_ = m.decode(lanes=3, k=2, hist_positions=30)
        assert flops == 2 * 1000 * 3 * 2
        assert bytes_ == 2 * 500 + 2 * 30 * 8 + 3 * 2 * 8

    def test_spec_counts_every_proposed_position(self):
        m = _model()
        flops, bytes_ = m.spec(lanes=2, k=2, g=3, hist_positions=20)
        # g drafts + 1 bonus verified per lane per micro-step, accepted or not
        assert flops == 2 * 1000 * 2 * 2 * (3 + 1)
        assert bytes_ == 2 * 500 + 2 * 20 * 8 + 2 * 2 * 4 * 8

    def test_transfers_have_no_flops(self):
        m = _model()
        assert m.swapin(999.0) == (0.0, 999.0)
        assert m.handoff_export(3) == (0.0, 3 * 64.0)

    @pytest.mark.parametrize("dtype,want", [
        ("bf16", 2 * 2 * (2 * 16 * 4)),       # dense fp32 (CPU promotion)
        ("int8", 2 * 2 * (2 * 16 + 4)),       # int8 k+v + bf16 scales
        ("int4", 2 * 2 * (2 * (16 // 2) + 4)),  # packed nibbles + scales
    ])
    def test_plane_widths_match_archived_accounting(self, dtype, want):
        """The analytic widths reproduce the archived 512/144/80 numbers
        for the tiny CPU config (layers=2, kv_heads=2, head_dim=16)."""
        got = kv_plane_bytes_per_position(2, 2, 16, kv_dtype=dtype,
                                          dense_bytes=4)
        assert got == want
        assert want in (512, 144, 80)

    def test_cost_model_uses_each_dtype_width(self):
        """Same step, three planes: bytes order int4 < int8 < dense —
        the whole point of the kv-dtype A/B, now visible per step."""
        outs = {}
        for dtype in ("bf16", "int8", "int4"):
            w = kv_plane_bytes_per_position(2, 2, 16, kv_dtype=dtype,
                                            dense_bytes=4)
            m = _model(kv_bytes_per_pos=float(w), kv_dtype=dtype)
            outs[dtype] = m.decode(lanes=2, k=4, hist_positions=64)[1]
        assert outs["int4"] < outs["int8"] < outs["bf16"]


# -- the shared bench/engine estimator -----------------------------------------


class TestSharedEstimator:
    def test_decode_lb_bytes_terms(self):
        got = perf.decode_lb_bytes(weight_bytes=1000.0, new_tokens=20,
                                   slots=4, kv_bytes_per_pos=10.0, hist_len=7)
        assert got == 1000.0 * (20 / 4) + 20 * 7 * 10.0 + 20 * 10.0

    def test_mbu_decode_lb_is_bytes_over_capacity(self):
        kw = dict(weight_bytes=1000.0, new_tokens=20, slots=4,
                  kv_bytes_per_pos=10.0, hist_len=7)
        lb = perf.decode_lb_bytes(**kw)
        got = perf.mbu_decode_lb(**kw, elapsed_s=2.0, peak_bw=500.0)
        assert got == pytest.approx(lb / 2.0 / 500.0)

    def test_params_variant_is_the_legacy_weights_only_bound(self):
        got = perf.mbu_decode_lb_params(weight_bytes=1000.0, new_tokens=20,
                                        slots=4, elapsed_s=2.0, peak_bw=500.0)
        assert got == pytest.approx(1000.0 * 20 / 4 / 2.0 / 500.0)
        # folding KV bytes in strictly raises the bound
        assert perf.mbu_decode_lb(
            weight_bytes=1000.0, new_tokens=20, slots=4, kv_bytes_per_pos=10.0,
            hist_len=7, elapsed_s=2.0, peak_bw=500.0) > got


# -- peak resolution -----------------------------------------------------------


class TestDevicePeaks:
    @pytest.fixture(autouse=True)
    def _clean_env(self, monkeypatch):
        for var in ("GOFR_DEVICE_PEAKS", "GOFR_TPU_PEAK_TFLOPS",
                    "GOFR_TPU_PEAK_GBS"):
            monkeypatch.delenv(var, raising=False)

    def test_builtin_table_substring_match(self):
        assert perf.device_peaks("TPU v5e") == (197e12, 819e9)
        assert perf.device_peaks("TPU v5p") == (459e12, 2765e9)  # not "v5"
        assert perf.device_peaks("cpu")[0] == 1e12  # nominal envelope

    def test_unknown_device_degrades_to_none(self):
        assert perf.device_peaks("quantum-annealer-9000") is None
        assert perf.device_peaks("") is None

    def test_gofr_device_peaks_json_override(self, monkeypatch):
        monkeypatch.setenv("GOFR_DEVICE_PEAKS",
                           json.dumps({"weird-silicon": [100, 1000]}))
        assert perf.device_peaks("weird-silicon mk2") == (100e12, 1000e9)
        # an override can also re-spec a builtin kind
        monkeypatch.setenv("GOFR_DEVICE_PEAKS", json.dumps({"v5e": [2, 3]}))
        assert perf.device_peaks("TPU v5e") == (2e12, 3e9)

    def test_component_env_override_wins_over_table(self, monkeypatch):
        monkeypatch.setenv("GOFR_TPU_PEAK_TFLOPS", "5")
        assert perf.device_peaks("TPU v5e") == (5e12, 819e9)
        monkeypatch.setenv("GOFR_TPU_PEAK_GBS", "100")
        assert perf.device_peaks("TPU v5e") == (5e12, 100e9)
        # env alone cannot complete an unknown kind's missing component
        monkeypatch.delenv("GOFR_TPU_PEAK_GBS")
        assert perf.device_peaks("quantum") is None

    def test_malformed_json_is_ignored(self, monkeypatch):
        monkeypatch.setenv("GOFR_DEVICE_PEAKS", "{not json")
        assert perf.device_peaks("TPU v5e") == (197e12, 819e9)


# -- bubble accounting on a fake clock -----------------------------------------


def _plane(device_kind="TPU v5e", **kw):
    return perf.PerfPlane(_model(**kw), device_kind)


class TestBubbleAccounting:
    def test_saturated_pipeline_has_no_bubble(self):
        """Entry t+1 dispatched before entry t folds: residency tiles the
        device timeline (no double count) and the bubble stays ~0."""
        p = _plane()
        s1 = p.step_decode(2, 4, 10, t0=100.0)
        s1.t_ready = 100.5
        p.note(s1, 100.5)
        s2 = p.step_decode(2, 4, 10, t0=100.2)  # overlapped dispatch
        s2.t_ready = 101.0
        p.note(s2, 101.0)
        assert s1.device_s == pytest.approx(0.5)
        assert s2.bubble_s == 0.0
        assert s2.device_s == pytest.approx(0.5)  # clipped to floor=100.5
        tot = p.window_totals(101.0)
        assert tot["bubble"]["bubble_s"] == pytest.approx(0.0)
        assert tot["bubble"]["busy_s"] == pytest.approx(1.0)
        snap = p.snapshot(101.0)
        assert snap["bubble"]["ratio"] == pytest.approx(0.0)

    def test_forced_stall_raises_the_ratio(self):
        """Work existed (no mark_no_work) but the next dispatch came 2s
        after the previous fold — that gap is pipeline bubble."""
        p = _plane()
        s1 = p.step_decode(1, 1, 4, t0=100.0)
        s1.t_ready = 101.0
        p.note(s1, 101.0)
        s2 = p.step_decode(1, 1, 4, t0=103.0)  # 2s device-idle gap
        s2.t_ready = 104.0
        p.note(s2, 104.0)
        assert s2.bubble_s == pytest.approx(2.0)
        snap = p.snapshot(104.0)
        assert snap["bubble"]["ratio"] == pytest.approx(2.0 / (2.0 + 2.0))

    def test_mark_no_work_keeps_idleness_out(self):
        """The engine loop's idle branch advances the floor: a genuinely
        empty queue must not read as pipeline bubble."""
        p = _plane()
        s1 = p.step_decode(1, 1, 4, t0=100.0)
        s1.t_ready = 101.0
        p.note(s1, 101.0)
        p.mark_no_work(103.0)  # queue was empty 101 -> 103
        s2 = p.step_decode(1, 1, 4, t0=103.5)
        s2.t_ready = 104.0
        p.note(s2, 104.0)
        assert s2.bubble_s == pytest.approx(0.5)  # only 103.0 -> 103.5

    def test_note_external_never_moves_the_floor(self):
        p = _plane()
        s1 = p.step_decode(1, 1, 4, t0=100.0)
        s1.t_ready = 101.0
        p.note(s1, 101.0)
        p.note_external("handoff_export", 5.0, 0.0, 4096.0, 110.0)
        s2 = p.step_decode(1, 1, 4, t0=101.5)
        s2.t_ready = 102.0
        p.note(s2, 102.0)
        assert s2.bubble_s == pytest.approx(0.5)  # floor still 101.0
        tot = p.window_totals(110.0)
        key = "handoff_export|bf16"
        assert tot["kinds"][key]["bytes"] == pytest.approx(4096.0)
        assert tot["kinds"][key]["device_s"] == pytest.approx(5.0)

    def test_window_totals_caps_and_snapshot_utilization(self):
        # model sized so the utilization survives the snapshot's 6-decimal
        # rounding (a toy 1000-param model at v5e peaks rounds to 0.0)
        p = _plane(n_params=1e12, weight_bytes=2e12, kv_bytes_per_pos=1e9)
        s = p.step_decode(2, 4, 10, t0=50.0)
        s.t_ready = 52.0
        p.note(s, 52.0)
        tot = p.window_totals(52.0)
        rec = tot["kinds"]["decode|bf16"]
        flops, bytes_ = p.model.decode(2, 4, 10)
        assert rec["flops"] == pytest.approx(flops)
        assert rec["bytes"] == pytest.approx(bytes_)
        assert rec["flops_cap"] == pytest.approx(rec["device_s"] * 197e12)
        assert rec["bytes_cap"] == pytest.approx(rec["device_s"] * 819e9)
        snap = p.snapshot(52.0)
        k = snap["kinds"]["decode"]
        assert k["mfu"] == pytest.approx(flops / rec["flops_cap"], rel=1e-4)
        assert k["mbu"] == pytest.approx(bytes_ / rec["bytes_cap"], rel=1e-4)

    def test_unknown_device_reports_raw_sums_but_no_utilization(self, monkeypatch):
        for var in ("GOFR_DEVICE_PEAKS", "GOFR_TPU_PEAK_TFLOPS",
                    "GOFR_TPU_PEAK_GBS"):
            monkeypatch.delenv(var, raising=False)
        p = _plane(device_kind="mystery-chip")
        s = p.step_prefill(16, t0=10.0)
        s.t_ready = 11.0
        p.note(s, 11.0)
        tot = p.window_totals(11.0)
        rec = tot["kinds"]["prefill|bf16"]
        assert rec["flops"] > 0 and rec["flops_cap"] == 0.0
        snap = p.snapshot(11.0)
        assert snap["peaks"]["flops"] is None
        assert snap["kinds"]["prefill"]["mfu"] is None
        assert snap["kinds"]["prefill"]["mbu"] is None


# -- exact merges: container and fleet ----------------------------------------


def _part(flops, bytes_, device_s, fcap, bcap, bubble=0.0, busy=1.0,
          key="decode|bf16"):
    return {"v": 1, "window_s": 60.0,
            "kinds": {key: {"flops": flops, "bytes": bytes_,
                            "device_s": device_s, "steps": 1.0,
                            "flops_cap": fcap, "bytes_cap": bcap}},
            "bubble": {"bubble_s": bubble, "busy_s": busy}}


class TestMerges:
    def test_merge_is_sum_of_parts_never_an_average(self):
        a = _part(100.0, 1000.0, 1.0, 1e3, 2e3)    # mbu 0.5
        b = _part(300.0, 200.0, 3.0, 9e3, 4e3)     # mbu 0.05
        merged = perf.merge_totals([a, b])
        d = perf.derive(merged)
        assert d["mbu"]["decode|bf16"] == pytest.approx(1200.0 / 6000.0)
        averaged = (0.5 + 0.05) / 2
        assert d["mbu"]["decode|bf16"] != pytest.approx(averaged)
        assert d["mfu"]["decode|bf16"] == pytest.approx(400.0 / 10e3)

    def test_merge_is_associative_and_skips_junk(self):
        a = _part(1.0, 2.0, 1.0, 10.0, 10.0)
        b = _part(3.0, 4.0, 1.0, 10.0, 10.0)
        c = _part(5.0, 6.0, 1.0, 10.0, 10.0, key="prefill|int8")
        left = perf.merge_totals([perf.merge_totals([a, b]), c])
        flat = perf.merge_totals([a, b, c, None, {"not": "perf"}])
        assert left["kinds"] == flat["kinds"]
        assert left["bubble"] == flat["bubble"]
        assert set(flat["kinds"]) == {"decode|bf16", "prefill|int8"}

    def test_bubble_ratio_merges_from_sums(self):
        a = _part(1.0, 1.0, 1.0, 0.0, 0.0, bubble=2.0, busy=2.0)  # 0.5
        b = _part(1.0, 1.0, 1.0, 0.0, 0.0, bubble=0.0, busy=6.0)  # 0.0
        d = perf.derive(perf.merge_totals([a, b]))
        assert d["bubble_ratio"] == pytest.approx(2.0 / 10.0)  # not 0.25

    def test_aggregate_perf_matches_direct_merge(self):
        a = _part(100.0, 1000.0, 1.0, 1e3, 2e3)
        b = _part(300.0, 200.0, 3.0, 9e3, 4e3)
        digests = {"r0": {"perf": a}, "r1": {"perf": b}, "r2": {}}
        fleet = federation.aggregate_perf(digests)
        assert fleet["kinds"] == perf.merge_totals([a, b])["kinds"]

    def test_digest_carries_perf_and_fleet_text_exposes_it(self):
        c = new_mock_container()
        a = _part(100.0, 1000.0, 1.0, 1e3, 2e3)
        b = _part(300.0, 200.0, 3.0, 9e3, 4e3)
        d0 = federation.digest(c.metrics, perf=a)
        assert d0["perf"] == a
        assert "perf" not in federation.digest(c.metrics)
        text = federation.fleet_text({"r0": d0,
                                      "r1": federation.digest(c.metrics, perf=b)})
        agg = [ln for ln in text.splitlines()
               if ln.startswith("app_tpu_mbu{") and "replica" not in ln]
        assert len(agg) == 1
        assert float(agg[0].rsplit(" ", 1)[1]) == pytest.approx(0.2)
        per = [ln for ln in text.splitlines()
               if ln.startswith("app_tpu_mbu{") and 'replica="r0"' in ln]
        assert per and float(per[0].rsplit(" ", 1)[1]) == pytest.approx(0.5)


# -- capture bundle + /debug/perf surfaces ------------------------------------


def _fake_engine(plane, decisions=None):
    rep = ({"decisions": decisions} if decisions else None)
    return SimpleNamespace(
        perf=plane, autotune_report=lambda: rep,
        health_check=lambda: {"status": "UP"})


class TestSurfaces:
    def _lively_plane(self):
        import time as _t

        p = _plane()
        now = _t.monotonic()
        s = p.step_decode(2, 4, 10, t0=now - 0.5)
        s.t_ready = now
        p.note(s, now)
        return p

    def test_capture_bundle_contains_perf_state(self, tmp_path):
        from gofr_tpu.metrics.slo import CaptureWatcher

        c = new_mock_container()
        c.register_engine("lm", _fake_engine(self._lively_plane()))
        w = CaptureWatcher(c, SimpleNamespace(snapshot=dict),
                           out_dir=str(tmp_path))
        path = w.on_breach([{"class": "c", "objective": "ttft"}])
        bundle = json.loads(open(f"{path}/bundle.json").read())
        assert bundle["perf"]["engines"]["lm"]["kinds"]["decode"]["steps"] >= 1
        assert "decode|bf16" in bundle["perf"]["totals"]["kinds"]

    def test_debug_perf_joins_autotune_pins(self):
        from tests.test_http_server import make_app

        app = make_app({"APP_ENV": "DEBUG"})
        pins = {"decode": {"backend": "xla", "source": "measured"}}
        app.container.register_engine(
            "lm", _fake_engine(self._lively_plane(), decisions=pins))
        resp = asyncio.run(app._debug_perf_handler(None))
        data = json.loads(resp.body)["data"]
        snap = data["engines"]["lm"]
        assert snap["kinds"]["decode"]["mbu"] is not None
        joined = snap["autotune"]["decode"]
        assert joined["pin"]["backend"] == "xla"
        assert joined["roofline"]["decode"]["steps"] >= 1
        assert data["rollup"]["mbu"]["decode|bf16"] is not None


# -- live engine end to end ----------------------------------------------------


class TestLiveEngine:
    @pytest.fixture(scope="class")
    def setup(self):
        cfg = LlamaConfig.tiny()
        return cfg, llama.init(cfg, jax.random.key(3))

    def test_serving_lights_up_the_plane_and_planes_order(self, setup):
        """Acceptance: after live traffic the engine's decode MBU is
        non-zero on /metrics (CPU nominal peaks), flight steps carry the
        roofline fields, requests carry per-phase device totals, and the
        bf16/int8/int4 byte numerators order strictly (512/144/80
        plane accounting)."""
        cfg, params = setup
        bytes_by_dtype = {}
        for dtype in ("", "int8", "int4"):
            c = new_mock_container()
            kw = dict(slots=2, max_len=32, max_prefill_batch=2,
                      kv_layout="paged", page_size=8)
            if dtype:
                kw["kv_quantize"] = dtype
            eng = GenerateEngine(llama, cfg, params, c, **kw)
            c.register_engine("lm", eng)
            try:
                assert eng.perf is not None
                out = eng.generate([5, 3, 9], max_new_tokens=6, timeout=300)
                assert len(out["tokens"]) == 6
                import time as _t

                snap = eng.perf.snapshot(_t.monotonic())
                assert snap["kinds"]["decode"]["steps"] >= 1
                assert snap["kinds"]["prefill"]["steps"] >= 1
                bytes_by_dtype[dtype or "bf16"] = snap["kinds"]["decode"]["bytes"]
                # exact pool accounting matches the analytic plane width
                dense = eng.kv_cache.k.dtype.itemsize if not dtype else 2
                want = kv_plane_bytes_per_position(
                    cfg.num_layers, cfg.num_kv_heads, cfg.head_size,
                    kv_dtype=dtype or "bf16", dense_bytes=dense)
                assert snap["model"]["kv_bytes_per_pos"] == pytest.approx(want)
                # scrape surfaces container-merged gauges
                text = c.metrics.expose_text()
                mbu = [ln for ln in text.splitlines()
                       if ln.startswith("app_tpu_mbu{") and 'kind="decode"' in ln]
                assert mbu, text[:2000]
                assert float(mbu[0].rsplit(" ", 1)[1]) > 0.0
                assert "app_tpu_kv_pool_occupancy" in text
                # flight recorder step + request roofline fields
                steps = c.flight.steps()
                dec = [s for s in steps if s["kind"] == "decode"]
                assert dec and {"device_s", "bytes", "flops", "bubble"} <= set(dec[0])
                reqs = c.flight.requests()
                assert reqs and "device" in reqs[0]
                assert reqs[0]["device"].get("decode_s", 0) > 0
            finally:
                eng.stop()
        assert (bytes_by_dtype["int4"] < bytes_by_dtype["int8"]
                < bytes_by_dtype["bf16"])

    def test_spec_waste_counters_registered(self):
        c = new_mock_container()
        assert c.metrics.get("app_tpu_spec_pages_trimmed_total") is not None
        assert c.metrics.get("app_tpu_spec_tokens_rejected_total") is not None
        assert c.metrics.get("app_tpu_step_device_seconds") is not None


# -- per-adapter attribution (multi-LoRA multiplexing) -------------------------


def _part_ad(adapters, key="decode|bf16", flops=0.0, bytes_=0.0, device_s=0.0):
    """A replica totals payload whose adapter rows are given directly."""
    return {"v": 1, "window_s": 60.0,
            "kinds": {key: {"flops": flops, "bytes": bytes_,
                            "device_s": device_s, "steps": 1.0,
                            "flops_cap": 0.0, "bytes_cap": 0.0}},
            "adapters": {aid: dict(rec) for aid, rec in adapters.items()},
            "bubble": {"bubble_s": 0.0, "busy_s": 1.0}}


class TestAdapterAttribution:
    def test_note_adapters_is_an_exact_partition(self):
        """Adapter rows partition each step: summed over adapters they
        equal the step's own flops/bytes/device_s — the invariant that
        keeps per-tenant COGS (device_s per adapter) sum-of-parts."""
        p = _plane()
        s = p.step_decode(4, 8, 16, t0=100.0)
        s.t_ready = 100.5
        p.note(s, 100.5)
        p.note_adapters(["fr", "fr", None, "de"], s, 100.5)
        tot = p.window_totals(100.5)
        ads = tot["adapters"]
        assert set(ads) == {"fr", "de", "base"}
        for field in ("flops", "bytes", "device_s"):
            whole = sum(rec[field] for rec in tot["kinds"].values())
            part = sum(rec[field] for rec in ads.values())
            assert part == pytest.approx(whole, rel=1e-12)
        # proportional by lane count: fr had 2 of 4 lanes
        assert ads["fr"]["device_s"] == pytest.approx(s.device_s * 0.5)
        assert ads["base"]["device_s"] == pytest.approx(s.device_s * 0.25)

    def test_adapter_rows_never_leak_into_kinds(self):
        p = _plane()
        s = p.step_decode(1, 1, 4, t0=100.0)
        s.t_ready = 100.2
        p.note(s, 100.2)
        p.note_adapters(["solo"], s, 100.2)
        tot = p.window_totals(100.2)
        assert all(not k.startswith("ad.") for k in tot["kinds"])
        assert "solo" in tot["adapters"]

    def test_merge_totals_sums_adapter_rows_exactly(self):
        """Fleet rollup: adapter rows merge as exact sums across replicas
        — never averaged — and replicas without the section still merge."""
        a = _part_ad({"fr": {"flops": 10.0, "bytes": 100.0, "device_s": 1.0,
                             "steps": 1.0, "flops_cap": 40.0,
                             "bytes_cap": 400.0}})
        b = _part_ad({"fr": {"flops": 30.0, "bytes": 300.0, "device_s": 3.0,
                             "steps": 1.0, "flops_cap": 160.0,
                             "bytes_cap": 1600.0},
                      "de": {"flops": 5.0, "bytes": 50.0, "device_s": 0.5,
                             "steps": 1.0, "flops_cap": 20.0,
                             "bytes_cap": 200.0}})
        legacy = _part(1.0, 2.0, 1.0, 10.0, 10.0)  # pre-adapter replica
        merged = perf.merge_totals([a, b, legacy])
        fr = merged["adapters"]["fr"]
        assert fr["flops"] == 40.0 and fr["device_s"] == 4.0
        assert fr["flops_cap"] == 200.0
        assert merged["adapters"]["de"]["bytes"] == 50.0
        d = perf.derive(merged)
        # fleet MFU per adapter is ratio-of-sums (0.2), not mean-of-ratios
        assert d["adapters"]["fr"]["mfu"] == pytest.approx(40.0 / 200.0)
        assert d["adapters"]["fr"]["mfu"] != pytest.approx(
            (10.0 / 40.0 + 30.0 / 160.0) / 2)
        assert d["adapters"]["de"]["device_s"] == pytest.approx(0.5)

    def test_fleet_text_exposes_adapter_rollup_and_replica_rows(self):
        c = new_mock_container()
        a = _part_ad({"fr": {"flops": 10.0, "bytes": 100.0, "device_s": 1.0,
                             "steps": 1.0, "flops_cap": 40.0,
                             "bytes_cap": 400.0}})
        b = _part_ad({"fr": {"flops": 30.0, "bytes": 300.0, "device_s": 3.0,
                             "steps": 1.0, "flops_cap": 160.0,
                             "bytes_cap": 1600.0}})
        text = federation.fleet_text({
            "r0": federation.digest(c.metrics, perf=a),
            "r1": federation.digest(c.metrics, perf=b)})
        dev = [ln for ln in text.splitlines()
               if ln.startswith("app_tpu_adapter_device_seconds{")]
        fleet = [ln for ln in dev if "replica" not in ln]
        per = [ln for ln in dev if "replica" in ln]
        assert len(fleet) == 1 and 'adapter="fr"' in fleet[0]
        assert float(fleet[0].rsplit(" ", 1)[1]) == pytest.approx(4.0)
        # fleet device-seconds is EXACTLY the sum of the replica rows
        assert sum(float(ln.rsplit(" ", 1)[1]) for ln in per) == \
            pytest.approx(float(fleet[0].rsplit(" ", 1)[1]), rel=1e-12)
        mfu = [ln for ln in text.splitlines()
               if ln.startswith("app_tpu_adapter_mfu{") and "replica" not in ln]
        assert mfu and float(mfu[0].rsplit(" ", 1)[1]) == pytest.approx(0.2)
