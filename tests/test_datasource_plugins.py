"""External datasource plugins (mongo / cassandra / clickhouse), extra
pubsub backends (mqtt / google), and orbax checkpoint/resume — the
reference's separate-module tier (SURVEY.md §2.4) and §5.4 analog."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gofr_tpu.config import EnvConfig
from gofr_tpu.container import new_mock_container
from gofr_tpu.datasource.cassandra import in_memory_cassandra
from gofr_tpu.datasource.clickhouse import in_memory_clickhouse
from gofr_tpu.datasource.mongo import in_memory_mongo


def wire(container, plugin, add):
    getattr(container, add)(plugin)
    return plugin


class TestMongo:
    def test_crud_roundtrip(self):
        c = new_mock_container()
        m = wire(c, in_memory_mongo(), "add_mongo")
        m.insert_one("users", {"name": "ada", "age": 36})
        m.insert_many("users", [{"name": "bob"}, {"name": "eve"}])
        assert m.count_documents("users") == 3
        assert m.find_one("users", {"name": "ada"})["age"] == 36
        m.update_one("users", {"name": "ada"}, {"$set": {"age": 37}})
        assert m.find_one("users", {"name": "ada"})["age"] == 37
        m.update_by_id("users", 2, {"name": "bobby"})
        assert m.find_one("users", {"_id": 2})["name"] == "bobby"
        assert m.delete_one("users", {"name": "eve"}) == 1
        assert m.count_documents("users") == 2
        assert c.mongo is m
        assert c.health()["services"]["mongo"]["status"] == "UP"

    def test_metrics_recorded(self):
        c = new_mock_container()
        m = wire(c, in_memory_mongo(), "add_mongo")
        m.insert_one("t", {"a": 1})
        text = c.metrics.expose_text()
        assert "app_mongo_stats" in text


class TestCassandra:
    def test_exec_query_bind(self):
        c = new_mock_container()
        cass = wire(c, in_memory_cassandra(), "add_cassandra")
        cass.exec("CREATE TABLE users (id int PRIMARY KEY, name text)")
        cass.exec("INSERT INTO users (id, name) VALUES (?, ?)", 1, "ada")
        cass.exec("INSERT INTO users (id, name) VALUES (?, ?)", 2, "bob")

        rows = cass.query(dict, "SELECT * FROM users")
        assert len(rows) == 2

        @dataclasses.dataclass
        class User:
            id: int
            name: str

        u = cass.query_one(User, "SELECT id, name FROM users WHERE id = ?", 1)
        assert u == User(id=1, name="ada")
        assert c.health()["services"]["cassandra"]["status"] == "UP"

    def test_exec_cas_lightweight_tx(self):
        cass = in_memory_cassandra()
        cass.connect()
        cass.exec("CREATE TABLE locks (name text PRIMARY KEY)")
        assert cass.exec_cas("INSERT INTO locks (name) VALUES (?) IF NOT EXISTS", "a") is True
        assert cass.exec_cas("INSERT INTO locks (name) VALUES (?) IF NOT EXISTS", "a") is False


class TestClickhouse:
    def test_exec_select_async_insert(self):
        c = new_mock_container()
        ch = wire(c, in_memory_clickhouse(), "add_clickhouse")
        ch.exec("CREATE TABLE events (id INTEGER, kind TEXT)")
        ch.async_insert("events", [{"id": 1, "kind": "a"}, {"id": 2, "kind": "b"}])
        rows = ch.select("SELECT * FROM events ORDER BY id")
        assert rows == [{"id": 1, "kind": "a"}, {"id": 2, "kind": "b"}]
        assert c.health()["services"]["clickhouse"]["status"] == "UP"


class TestMqttBackend:
    def test_pub_sub_roundtrip(self):
        from gofr_tpu.pubsub.mqtt import FakeMqttClient, MqttBroker

        c = new_mock_container()
        conf = EnvConfig(environ={"MQTT_QOS": "1"})
        broker = MqttBroker(conf, c.logger, c.metrics, client_factory=FakeMqttClient)
        broker.create_topic("orders")
        broker.publish("orders", {"id": 7})
        msg = broker.subscribe("orders", timeout=1.0)
        assert msg is not None and msg.bind(dict) == {"id": 7}
        assert broker.health_check()["status"] == "UP"
        broker.close()
        assert broker.health_check()["status"] == "DOWN"

    def test_subscribe_with_function(self):
        import threading

        from gofr_tpu.pubsub.mqtt import FakeMqttClient, MqttBroker

        c = new_mock_container()
        broker = MqttBroker(EnvConfig(environ={}), c.logger, c.metrics,
                            client_factory=FakeMqttClient)
        got = []
        done = threading.Event()
        broker.subscribe_with_function("t", lambda m: (got.append(m.bind(str)), done.set()))
        import time

        time.sleep(0.05)  # let the subscriber thread register the topic queue
        broker.publish("t", "hi")
        assert done.wait(5.0) and got == ["hi"]


class TestGoogleBackend:
    def test_pub_sub_ack_roundtrip(self):
        from gofr_tpu.pubsub.google import FakeGooglePubSub, GooglePubSubBroker

        c = new_mock_container()
        fake = FakeGooglePubSub()
        conf = EnvConfig(environ={"GOOGLE_PROJECT_ID": "proj"})
        broker = GooglePubSubBroker(conf, c.logger, c.metrics,
                                    client_factory=lambda: (fake, fake))
        broker.publish("orders", {"n": 1})
        msg = broker.subscribe("orders", group="g1")
        assert msg is not None and msg.bind(dict) == {"n": 1}
        msg.commit()
        assert broker.subscribe("orders", group="g1") is None
        assert broker.health_check()["status"] == "UP"

    def test_requires_project(self):
        c = new_mock_container()
        with pytest.raises(ValueError, match="GOOGLE_PROJECT_ID"):
            from gofr_tpu.pubsub.google import GooglePubSubBroker

            GooglePubSubBroker(EnvConfig(environ={}), c.logger, c.metrics,
                               client_factory=lambda: (None, None))


class TestCheckpoint:
    def test_train_state_save_restore(self, tmp_path):
        from gofr_tpu.models import LlamaConfig, llama
        from gofr_tpu.parallel import build_mesh
        from gofr_tpu.train import make_train_step
        from gofr_tpu.train.checkpoint import latest_step, restore_checkpoint, save_checkpoint

        cfg = LlamaConfig.tiny()
        mesh = build_mesh("dp:4,tp:2")
        init_fn, step_fn = make_train_step(cfg, llama, mesh)
        state = init_fn(jax.random.key(0))
        tokens = jax.random.randint(jax.random.key(1), (4, 16), 0, cfg.vocab_size)
        lengths = jnp.full((4,), 16, jnp.int32)
        state, _ = step_fn(state, tokens, lengths)

        ckpt = str(tmp_path / "run1")
        saved = save_checkpoint(ckpt, state)
        assert saved == 1 and latest_step(ckpt) == 1

        restored = restore_checkpoint(ckpt, jax.tree.map(lambda x: x, state))
        for a, b in zip(jax.tree.leaves(state.params), jax.tree.leaves(restored.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # resume: stepping the restored state works and matches
        s1, m1 = step_fn(state, tokens, lengths)
        s2, m2 = step_fn(restored, tokens, lengths)
        assert float(m1["loss"]) == pytest.approx(float(m2["loss"]), rel=1e-6)

    def test_engine_loads_checkpoint_weights(self, tmp_path):
        from gofr_tpu.models import LlamaConfig, ModelSpec, llama
        from gofr_tpu.tpu.engine import build_engine
        from gofr_tpu.train.checkpoint import save_params

        cfg = LlamaConfig.tiny()
        params = llama.init(cfg, jax.random.key(42))
        ckpt = str(tmp_path / "weights")
        save_params(ckpt, params)

        c = new_mock_container()
        spec = ModelSpec("llama", cfg, task="generate", weights=ckpt, dtype=jnp.float32)
        eng = build_engine(spec, c, slots=2, max_len=32)
        try:
            seq = [5, 3, 9]
            want = []
            for _ in range(3):
                lg = llama.forward(cfg, params, jnp.asarray([seq], jnp.int32))
                seq.append(int(jnp.argmax(lg[0, -1])))
                want.append(seq[-1])
            out = eng.generate([5, 3, 9], max_new_tokens=3, timeout=120)
            assert out["tokens"] == want  # saved weights, not random re-init
        finally:
            eng.stop()
