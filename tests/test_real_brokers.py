"""Real-service integration tier (VERDICT r4 missing #2): the from-scratch
RESP client (datasource/redis.py) and the kafka pub/sub client
(pubsub/kafka.py) against LIVE servers. Reference analog: CI service
containers in .github/workflows/go.yml:25-57.

Self-skipping: runs only when REAL_REDIS_HOST / REAL_KAFKA_BROKER are set
(the `services` CI job sets them against its containers), so every other
environment stays hermetic. These tests go through ``Container.create`` —
the same config-gated wiring an app boots with — not raw client classes.
"""

import os
import time
import uuid

import pytest

from gofr_tpu.config import DictConfig
from gofr_tpu.container import Container

REDIS_HOST = os.environ.get("REAL_REDIS_HOST")
KAFKA_BROKER = os.environ.get("REAL_KAFKA_BROKER")


@pytest.mark.skipif(not REDIS_HOST, reason="REAL_REDIS_HOST not set")
class TestRealRedis:
    def _container(self) -> Container:
        return Container.create(DictConfig({
            "REDIS_HOST": REDIS_HOST,
            "REDIS_PORT": os.environ.get("REAL_REDIS_PORT", "6379"),
            "LOG_LEVEL": "ERROR",
        }))

    def test_roundtrip_types_and_pipeline(self):
        c = self._container()
        r = c.redis
        assert r is not None, "config-gated wiring did not connect redis"
        key = f"gofr-ci-{uuid.uuid4().hex}"
        try:
            assert r.ping()
            assert r.set(key, "v1") is True
            assert r.get(key) == b"v1"
            assert r.incr(key + ":n") == 1
            assert r.incr(key + ":n") == 2
            r.hset(key + ":h", "f", "x")
            assert r.hget(key + ":h", "f") == b"x"
            assert set(r.hgetall(key + ":h")) == {"f"}
            # MULTI/EXEC through the pipeline — the exact wire shape the
            # transactional migrations rely on (migration/__init__.py)
            p = r.pipeline()
            p.command("MULTI")
            p.command("SET", key + ":p", "in-tx")
            p.command("EXEC")
            p.execute()
            assert r.get(key + ":p") == b"in-tx"
            assert r.health_check()["status"] == "UP"
        finally:
            r.delete(key, key + ":n", key + ":h", key + ":p")
            r.close()

    def test_migration_runs_against_real_redis(self):
        from gofr_tpu.migration import Migration, run_migrations

        c = self._container()
        mark = f"gofr-ci-mig-{uuid.uuid4().hex}"
        # unique version per run: the CI redis may persist across jobs
        version = int(time.time())
        try:
            applied = run_migrations(
                {version: Migration(up=lambda d: d.redis.set(mark, "done"))}, c)
            assert applied == [version]
            assert c.redis.get(mark) == b"done"
            # recorded in the completion hash -> second run skips it
            assert run_migrations(
                {version: Migration(up=lambda d: d.redis.set(mark, "AGAIN"))},
                c) == []
            assert c.redis.get(mark) == b"done"
        finally:
            c.redis.delete(mark)
            c.redis.command("HDEL", "gofr_migrations", str(version))
            c.redis.close()


@pytest.mark.skipif(not KAFKA_BROKER, reason="REAL_KAFKA_BROKER not set")
class TestRealKafka:
    def test_publish_subscribe_health(self):
        c = Container.create(DictConfig({
            "PUBSUB_BACKEND": "kafka",
            "PUBSUB_BROKER": KAFKA_BROKER,
            "LOG_LEVEL": "ERROR",
        }))
        ps = c.pubsub
        assert ps is not None, "config-gated wiring did not connect kafka"
        # fresh group per run (passed to subscribe — that is the group
        # API); auto_offset_reset=earliest in the client means the
        # pre-subscribe publish below is still delivered to the new group
        group = f"gofr-ci-{uuid.uuid4().hex[:8]}"
        topic = f"gofr-ci-{uuid.uuid4().hex[:12]}"
        payload = f"hello-{time.time()}".encode()
        ps.publish(topic, payload)
        deadline = time.time() + 60
        got = None
        while time.time() < deadline and got is None:
            msg = ps.subscribe(topic, group=group, timeout=5.0)
            if msg is not None and bytes(msg.value) == payload:
                got = msg
                msg.commit()
        assert got is not None, "message never arrived from the real broker"
        assert ps.health_check()["status"] == "UP"
        ps.close()
