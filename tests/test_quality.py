"""Numerics & quality plane (ISSUE 17): online divergence shadowing against
a reference path, and deterministic anomaly replay bundles.

Unit tier: the pure scoring helpers — teacher-forced determinism, the
divergence report math, the serving-arm attention/head hooks — and the
QualityPlane state machine on a stub model (sampling, drop-oldest
backpressure, error isolation, metric label routing, SLO wiring).

Engine tier (CPU, tiny config): the OFF-is-free contract (rate 0 never
constructs the plane and rate 1 never changes emitted tokens — asserted on
both KV layouts with spec rounds on and off), the spec-acceptance gauge,
and the full anomaly loop: a chaos-corrupted int8 engine must diverge from
the reference, burn the quality SLO, write an enriched capture bundle, and
``scripts/replay_bundle.py`` must reproduce the exact per-token divergence
offline. A tight-pool preemption drill proves shadow scoring captures
per-life emitted tokens and leaks no pages (assert_page_refs_consistent).

Federation: the quality counters ride the gossip digest and merge as SUMS
(fleet agreement = sum(good)/sum(total), never an average of ratios).
"""

import glob
import json
import os
import time
from types import SimpleNamespace

import jax
import numpy as np
import pytest

from gofr_tpu.container import new_mock_container
from gofr_tpu.fleet import chaos
from gofr_tpu.metrics import Registry
from gofr_tpu.metrics import federation
from gofr_tpu.metrics.quality import (
    QualityPlane,
    divergence_report,
    make_adapter_head_fn,
    make_serving_attn_fn,
    teacher_forced_rows,
)
from gofr_tpu.metrics.slo import CaptureWatcher
from gofr_tpu.models import LlamaConfig, llama
from gofr_tpu.testutil import assert_page_refs_consistent
from gofr_tpu.tpu.engine import GenerateEngine

pytestmark = pytest.mark.quick


@pytest.fixture(scope="module")
def setup():
    cfg = LlamaConfig.tiny()
    params = llama.init(cfg, jax.random.key(7))
    return cfg, params


# -- divergence report math ----------------------------------------------------


def _rows(seed: int, t: int = 4, vocab: int = 11) -> np.ndarray:
    return np.random.RandomState(seed).randn(t, vocab).astype(np.float32)


class TestDivergenceReport:
    def test_identical_rows_full_agreement(self):
        rows = _rows(0)
        emitted = rows.argmax(axis=-1)  # engine emitted the ref argmax
        r = divergence_report(rows, rows.copy(), emitted)
        assert r["tokens"] == 4
        assert r["logprob_delta_mean_abs"] == 0.0
        assert r["logprob_delta_max_abs"] == 0.0
        assert r["kl_mean"] == 0.0 and r["kl_max"] == 0.0
        assert r["top1_agree"] == 1.0
        assert r["first_divergence"] == -1
        assert r["agree"] == [1, 1, 1, 1]

    def test_disagreement_indexes_first_divergent_token(self):
        ref = _rows(1)
        emitted = ref.argmax(axis=-1).copy()
        # live engine emitted something else from position 2 on
        emitted[2] = (emitted[2] + 1) % ref.shape[1]
        emitted[3] = (emitted[3] + 3) % ref.shape[1]
        r = divergence_report(_rows(2), ref, emitted)
        assert r["top1_agree"] == 0.5
        assert r["first_divergence"] == 2
        assert r["agree"] == [1, 1, 0, 0]
        # different distributions: KL strictly positive, never negative
        assert r["kl_max"] >= r["kl_mean"] > 0.0

    def test_top1_compares_reference_argmax_to_emitted(self):
        # the serving re-score arm agreeing with itself must NOT mask a
        # live-path corruption: agreement is ref-argmax vs EMITTED token
        ref = _rows(3)
        serving = ref.copy()  # arms identical (corruption lives off-path)
        emitted = (ref.argmax(axis=-1) + 1) % ref.shape[1]
        r = divergence_report(serving, ref, emitted)
        assert r["kl_mean"] == 0.0  # arms agree with each other...
        assert r["top1_agree"] == 0.0  # ...but the live output diverged
        assert r["first_divergence"] == 0


# -- teacher-forced scoring ----------------------------------------------------


class TestTeacherForced:
    def test_deterministic_and_shaped(self, setup):
        cfg, params = setup
        prompt, emitted = [2, 5, 7, 11], [3, 4, 9]
        r1 = teacher_forced_rows(llama, cfg, params, prompt, emitted)
        r2 = teacher_forced_rows(llama, cfg, params, prompt, emitted)
        assert r1.shape == (3, cfg.vocab_size)
        assert r1.dtype == np.float32
        assert (r1 == r2).all(), "teacher-forced re-score must be bitwise stable"

    def test_rejects_empty_sides(self, setup):
        cfg, params = setup
        with pytest.raises(ValueError):
            teacher_forced_rows(llama, cfg, params, [], [1])
        with pytest.raises(ValueError):
            teacher_forced_rows(llama, cfg, params, [1], [])

    def test_serving_attn_fn_cached_and_dense_is_none(self):
        assert make_serving_attn_fn("bf16") is None
        assert make_serving_attn_fn("") is None
        f1 = make_serving_attn_fn("int8")
        assert f1 is make_serving_attn_fn("int8"), (
            "attn_fn must be one cached object per dtype or jit retraces "
            "the shadow forward on every sample"
        )
        with pytest.raises(ValueError):
            make_serving_attn_fn("fp8")

    def test_int8_arm_differs_from_reference(self, setup):
        cfg, params = setup
        prompt, emitted = [2, 5, 7, 11], [3, 4, 9]
        ref = teacher_forced_rows(llama, cfg, params, prompt, emitted)
        srv = teacher_forced_rows(llama, cfg, params, prompt, emitted,
                                  attn_fn=make_serving_attn_fn("int8"))
        assert srv.shape == ref.shape
        assert not (srv == ref).all(), "fake-quant KV must perturb the rows"
        # ...but only slightly: the report over the real arms stays sane
        r = divergence_report(srv, ref, emitted)
        assert r["kl_mean"] < 1.0

    def test_zero_lora_delta_is_identity(self, setup):
        cfg, params = setup
        prompt, emitted = [2, 5], [3, 4]
        rank = 2
        a = np.zeros((cfg.hidden_size, rank), np.float32)
        b = np.zeros((rank, cfg.vocab_size), np.float32)
        base = teacher_forced_rows(llama, cfg, params, prompt, emitted)
        hooked = teacher_forced_rows(llama, cfg, params, prompt, emitted,
                                     head_fn=make_adapter_head_fn(a, b, 2.0))
        assert (base == hooked).all(), (
            "zero LoRA factors through the head hook must be bit-identical"
        )


# -- QualityPlane state machine (stub model, no jit) ---------------------------


def _stub_family(vocab: int = 8, fail: bool = False):
    """family.forward stand-in: logits favour token (position % vocab)."""

    def forward(cfg, params, tokens, lengths, attn_fn=None, head_fn=None):
        if fail:
            raise RuntimeError("injected scorer fault")
        b, s = np.asarray(tokens).shape
        out = np.zeros((b, s, vocab), np.float32)
        out[:, np.arange(s), np.arange(s) % vocab] = 5.0
        if attn_fn is not None:  # the "serving" arm: nudge, don't flip
            out = out + 0.01
        return out

    return SimpleNamespace(forward=forward)


def _mk_plane(**kw):
    defaults = dict(
        family=_stub_family(), cfg=SimpleNamespace(max_seq_len=64),
        params_fn=lambda: None, rate=1.0, seed=3, kv_dtype="bf16")
    defaults.update(kw)
    return QualityPlane(**defaults)


class TestQualityPlane:
    def test_rate_zero_never_samples(self):
        p = _mk_plane(rate=0.0)
        assert p.maybe_capture([1, 2], [3, 4]) is False
        assert p.pending == 0 and p.step() is False

    def test_drop_oldest_bounded_backpressure(self):
        p = _mk_plane(max_pending=2)
        for i in range(5):
            assert p.maybe_capture([1, 2], [3, 4], request_id=f"r{i}")
        assert p.pending == 2 and p.dropped == 3
        # the two newest survive the eviction
        while p.step():
            pass
        ids = [e["request_id"] for e in p.snapshot()["recent"]]
        assert ids == ["r3", "r4"]

    def test_step_scores_one_arm_per_call(self):
        p = _mk_plane()
        p.maybe_capture([1, 2], [3, 4], qos_class="batch")
        assert p.step() is True  # serving arm
        assert p.samples == 0 and p.pending == 1  # still inflight
        assert p.step() is True  # reference arm + finalize
        assert p.samples == 1 and p.pending == 0
        assert p.step() is False  # idle again

    def test_scorer_faults_counted_never_raised(self):
        p = _mk_plane(family=_stub_family(fail=True))
        p.maybe_capture([1, 2], [3, 4])
        assert p.step() is True
        assert p.errors == 1 and p.samples == 0 and p.pending == 0

    def test_metric_label_routing_and_slo_wiring(self):
        reg = Registry()
        reg.new_histogram("app_tpu_quality_logprob_delta")
        reg.new_histogram("app_tpu_quality_kl")
        reg.new_gauge("app_tpu_quality_top1_agree")
        reg.new_histogram("app_tpu_quality_first_divergence_token")
        reg.new_counter("app_tpu_quality_samples_total")
        reg.new_counter("app_tpu_quality_good_total")
        seen = []
        slo = SimpleNamespace(
            observe_quality=lambda cls_name, ok: seen.append((cls_name, ok)))
        p = _mk_plane(metrics=reg, slo=slo, kv_dtype="int8",
                      backend_fn=lambda: "pallas")
        # the stub scores position j as token j%vocab; emitted rows cover
        # absolute positions 1..2, so [1, 2] agrees with the "reference"
        p.maybe_capture([1, 2], [1, 2], qos_class="interactive")
        while p.step():
            pass
        assert p.samples == 1
        (ls, v), = reg.get("app_tpu_quality_samples_total").series()
        assert v == 1.0
        labels = dict(ls)
        assert labels == {"kv_dtype": "int8", "backend": "pallas",
                          "adapter": "base"}
        assert seen == [("interactive", True)]
        # good rides the same label set so the fleet ratio divides cleanly
        (ls_g, v_g), = reg.get("app_tpu_quality_good_total").series()
        assert ls_g == ls and v_g == 1.0

    def test_snapshot_replay_payload_trimmable(self):
        p = _mk_plane()
        p.maybe_capture([1, 2, 3], [4, 5], request_id="r0")
        while p.step():
            pass
        full = p.snapshot()["recent"][0]
        assert full["prompt"] == [1, 2, 3] and full["emitted"] == [4, 5]
        assert full["report"]["tokens"] == 2
        slim = p.snapshot(replay=False)["recent"][0]
        assert "prompt" not in slim and "emitted" not in slim
        assert slim["report"]["tokens"] == 2  # the stats stay


# -- federation: sums, never averages ------------------------------------------


def test_quality_counters_federate_as_sums():
    for name in ("app_tpu_quality_samples_total", "app_tpu_quality_good_total"):
        assert name in federation.DIGEST_COUNTERS, (
            f"{name} must ride the gossip digest")
    # unevenly loaded replicas: r1 scored 100 samples at 90% agreement,
    # r2 scored 10 at 10% — the fleet number must be 91/110, not the
    # traffic-blind average of ratios (0.5)
    digests = {}
    for replica, (good, total) in (("r1", (90, 100)), ("r2", (1, 10))):
        reg = Registry()
        reg.new_counter("app_tpu_quality_samples_total")
        reg.new_counter("app_tpu_quality_good_total")
        reg.increment_counter("app_tpu_quality_samples_total", total,
                              kv_dtype="int8", backend="xla", adapter="base")
        reg.increment_counter("app_tpu_quality_good_total", good,
                              kv_dtype="int8", backend="xla", adapter="base")
        digests[replica] = federation.digest(reg)
    agg_total, _ = federation._merge_counters(
        "app_tpu_quality_samples_total", digests)
    agg_good, _ = federation._merge_counters(
        "app_tpu_quality_good_total", digests)
    (ls, total), = agg_total.items()
    assert total == 110.0 and agg_good[ls] == 91.0
    assert dict(ls)["kv_dtype"] == "int8"
    fleet = agg_good[ls] / total
    assert fleet == pytest.approx(91 / 110)
    assert abs(fleet - (0.9 + 0.1) / 2) > 0.3


# -- chaos spec round trip -----------------------------------------------------


def test_chaos_active_spec_reserializes_overrides():
    assert chaos.active_spec() == ""
    with chaos.override("quality.corrupt:drop,factor=8"):
        assert chaos.active_spec() == "quality.corrupt:drop,factor=8"
    assert chaos.active_spec() == ""


# -- engine tier ---------------------------------------------------------------


PROMPTS = [[2, 5, 7, 11], [3, 4, 9], [1, 8, 6, 2, 9]]


def _serve(engine, n_new=6):
    out = []
    for p in PROMPTS:
        out.append(engine.generate(p, max_new_tokens=n_new, temperature=0.0,
                                   timeout=120)["tokens"])
    return out


@pytest.mark.parametrize("layout_kw,spec", [
    (dict(), 0),
    (dict(), 2),
    (dict(kv_layout="paged", page_size=8, total_pages=64, kv_quantize="int8"), 0),
    (dict(kv_layout="paged", page_size=8, total_pages=64, kv_quantize="int8"), 2),
], ids=["slot-bf16", "slot-bf16-spec", "paged-int8", "paged-int8-spec"])
def test_shadow_off_is_free_and_on_is_invisible(setup, layout_kw, spec):
    """rate=0: the plane is never constructed (one branch on the idle loop,
    bit-identical engine). rate=1: shadow scoring must not perturb a single
    emitted token — it is teacher-forced on idle capacity, never sampling."""
    cfg, params = setup
    kw = dict(slots=2, max_len=64, spec_tokens=spec, **layout_kw)
    off = GenerateEngine(llama, cfg, params, new_mock_container(), **kw)
    try:
        want = _serve(off)
        assert off._quality is None, "rate 0 must not construct the plane"
    finally:
        off.stop()
    on = GenerateEngine(llama, cfg, params, new_mock_container(),
                        quality_shadow_rate=1.0, **kw)
    try:
        got = _serve(on)
        assert got == want, "shadow-on run emitted different tokens"
        assert on._quality.drain(120), "idle loop never scored the backlog"
        snap = on.quality_snapshot()
        assert snap["samples"] == len(PROMPTS) and snap["errors"] == 0
        assert snap["kv_dtype"] == layout_kw.get("kv_quantize", "bf16")
        for e in snap["recent"]:
            assert e["report"]["tokens"] >= 1
    finally:
        on.stop()


def test_spec_accept_ratio_gauge_samples_at_scrape(setup):
    cfg, params = setup
    cont = new_mock_container()
    eng = GenerateEngine(llama, cfg, params, cont, slots=2, max_len=64,
                         kv_layout="paged", page_size=8, total_pages=64,
                         kv_quantize="int8", spec_tokens=2)
    cont.register_engine("lm", eng)
    try:
        _serve(eng)
        totals = eng.spec_accept_totals()
        (adapter, (acc, prop)), = totals.items()
        assert adapter == "base" and prop > 0 and 0 <= acc <= prop
        text = cont.metrics.expose_text()  # scrape: collect hooks run here
        line = [ln for ln in text.splitlines()
                if ln.startswith("app_tpu_spec_accept_ratio{")]
        assert line and 'adapter="base"' in line[0]
        ratio = float(line[0].rsplit(" ", 1)[1])
        assert ratio == pytest.approx(acc / prop)
    finally:
        eng.stop()


def test_chaos_corruption_burns_bundles_and_replays(setup, tmp_path):
    """The whole anomaly loop on one engine: quality.corrupt perturbs the
    int8 dequant scales inside the compiled gather, the shadow scorer sees
    reference/emitted disagreement, the quality SLO burns, the capture
    bundle carries the replay payload, and the offline replayer reproduces
    the exact per-token divergence (and the exact tokens) from the bundle
    alone."""
    cfg, params = setup
    cap = str(tmp_path / "cap")
    conf = {
        "SLO_DEFAULT_QUALITY": "0.99", "SLO_MIN_SAMPLES": "2",
        "SLO_BURN_THRESHOLD": "2", "SLO_CHECK_INTERVAL_S": "0",
        "SLO_CAPTURE": "true", "SLO_CAPTURE_DIR": cap,
        "SLO_CAPTURE_MIN_INTERVAL_S": "0.01", "SLO_CAPTURE_BURST": "4",
    }
    with chaos.override("quality.corrupt:drop,factor=8"):
        cont = new_mock_container(dict(conf))
        eng = GenerateEngine(llama, cfg, params, cont, slots=2, max_len=64,
                             kv_layout="paged", page_size=8, total_pages=64,
                             kv_quantize="int8", quality_shadow_rate=1.0)
        cont.register_engine("lm", eng)
        try:
            _serve(eng)
            assert eng._quality.drain(120)
            snap = eng.quality_snapshot()
        finally:
            eng.stop()
    assert snap["samples"] == len(PROMPTS)
    assert snap["good"] < snap["samples"], "corruption must fail thresholds"
    assert any(e["report"]["top1_agree"] < 0.9 for e in snap["recent"])
    assert any(e["report"]["first_divergence"] >= 0 for e in snap["recent"])
    # the snapshot records everything replay needs, including the armed spec
    assert snap["replay"]["chaos"] == "quality.corrupt:drop,factor=8"
    assert snap["replay"]["seed"] == eng._seed
    assert "adapter_digest" in snap["replay"] and "fingerprint" in snap["replay"]
    qb = [b for b in cont.slo.breaches() if b.get("objective") == "quality"]
    assert qb, "quality burn never fired"
    bundles = sorted(glob.glob(os.path.join(cap, "slo-capture-*")))
    assert bundles, "burn fired but no capture bundle was written"
    with open(os.path.join(bundles[-1], "bundle.json")) as f:
        bundle = json.load(f)
    assert "quality" in bundle and "lm" in bundle["quality"]
    assert bundle["quality"]["lm"]["recent"], "bundle lost the replay payload"

    import importlib
    import sys as _sys
    _sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts"))
    try:
        replay_bundle = importlib.import_module("replay_bundle")
    finally:
        _sys.path.pop(0)
    res = replay_bundle.replay(bundles[-1], run_engine=True, params=params,
                               max_samples=2)
    assert res["reproduced"] is True, res
    rows = res["engines"]["lm"]["samples"]
    assert rows and all(r["tokens_match"] for r in rows)
    assert all(r["divergence_match"] for r in rows)
    # replay must diff against the RECORDED report, not trivially agree
    assert any(r["recorded"]["first_divergence"] >= 0 for r in rows)


def test_preemption_keeps_shadow_consistent(setup):
    """Minimum-legal paged pool so preemption-by-recompute fires mid-run:
    shadow capture must record each request's per-life emitted tokens (a
    contiguous run of the final output — the requeued prompt already
    carries prior generations), score them all without errors, and leave
    the page refcounts consistent (the plane claims no pool state)."""
    cfg, params = setup
    rngs = np.random.RandomState(11)
    prompts = []
    for i in range(10):  # every 3rd arrival long enough to contend the pool
        n = 17 + (i % 2) * 4 if i % 3 == 2 else 2 + i % 4
        prompts.append([int(x) for x in rngs.randint(1, 200, size=n)])
    eng = GenerateEngine(llama, cfg, params, new_mock_container(),
                         slots=3, max_len=64, max_prefill_batch=2,
                         prefill_buckets=[8], kv_layout="paged",
                         page_size=8, total_pages=9,
                         quality_shadow_rate=1.0, quality_max_pending=16)
    try:
        reqs = []
        for p in prompts:  # paced arrivals, not one up-front burst
            time.sleep(0.01)
            reqs.append(eng.submit(p, max_new_tokens=16, timeout=300))
        results = [r.result(300) for r in reqs]
        pre = eng.metrics.get("app_tpu_preemptions")
        assert pre is not None and sum(pre._values.values()) >= 1, (
            "pool was not small enough to exercise preemption")
        assert eng._quality.drain(300)
        snap = eng.quality_snapshot()
        assert snap["samples"] == len(prompts) and snap["errors"] == 0
        by_id = {r.id: res["tokens"] for r, res in zip(reqs, results)}
        matched = 0
        for e in snap["recent"]:
            toks = by_id.get(e["request_id"])
            assert toks is not None, "sample keyed by unknown request id"
            matched += 1
            em, n = e["emitted"], len(e["emitted"])
            assert any(toks[i:i + n] == em
                       for i in range(len(toks) - n + 1)), (
                "captured emitted tokens are not a contiguous run of the "
                f"request output: {em} vs {toks}")
        assert matched == len(prompts)
        assert_page_refs_consistent(eng)
    finally:
        eng.stop()


# -- capture retention ---------------------------------------------------------


def test_capture_retention_sweeps_oldest(tmp_path):
    cont = new_mock_container({"SLO_CAPTURE": "true",
                               "SLO_CAPTURE_DIR": str(tmp_path),
                               "SLO_CAPTURE_MAX_BUNDLES": "2"})
    w = cont.slo_capture
    assert isinstance(w, CaptureWatcher) and w.max_bundles == 2
    for i in range(5):
        d = tmp_path / f"slo-capture-20260807-00000{i}-000"
        d.mkdir()
        (d / "bundle.json").write_text("{}")
    keeper = tmp_path / "not-a-capture"
    keeper.mkdir()
    w._sweep()
    left = sorted(p.name for p in tmp_path.iterdir())
    assert left == ["not-a-capture",
                    "slo-capture-20260807-000003-000",
                    "slo-capture-20260807-000004-000"], left
    # 0 disables retention entirely (the pre-retention behavior)
    w.max_bundles = 0
    w._sweep()
    assert sorted(p.name for p in tmp_path.iterdir()) == left
