# Serving runtime image for gofr_tpu (built by the `docker` CI job;
# asserted by tests/test_ci_config.py).
#
# Pinning discipline for TPU hosts: jax, jaxlib, and libtpu MUST move in
# lockstep — a libtpu from a different release than jaxlib produces
# undefined runtime behavior, not a clean error. The pins live in the two
# build args below; bump them TOGETHER and only to combinations published
# on the jax release matrix:
#
#   JAX_VERSION    the jax/jaxlib release (e.g. 0.4.38)
#   JAX_EXTRAS     ""      → CPU-only image (CI builds this: hermetic,
#                            no TPU wheel downloads)
#                  "[tpu]" → pulls the matching libtpu via the release
#                            index (requires network access to
#                            storage.googleapis.com at build time)
#
# On a TPU VM, run with --privileged --net=host (the TPU driver is host-
# side; /dev/accel* must be visible) and set TPU_MESH for the topology.
#
#   docker build -t gofr-tpu-serving .
#   docker build -t gofr-tpu-serving --build-arg JAX_EXTRAS="[tpu]" .
#   docker run --rm -p 8000:8000 -p 2121:2121 gofr-tpu-serving

FROM python:3.12-slim

ARG JAX_VERSION=0.4.38
ARG JAX_EXTRAS=""
# the libtpu release index the [tpu] extra resolves against; pinned so an
# image rebuild months later still gets the SAME libtpu for this jaxlib
ARG LIBTPU_INDEX=https://storage.googleapis.com/jax-releases/libtpu_releases.html

WORKDIR /srv/gofr_tpu

RUN pip install --no-cache-dir \
        "jax${JAX_EXTRAS}==${JAX_VERSION}" \
        -f "${LIBTPU_INDEX}" \
        flax optax orbax-checkpoint chex einops numpy \
        aiohttp httpx transformers grpcio protobuf cryptography pyyaml

COPY gofr_tpu ./gofr_tpu
COPY examples ./examples
COPY jaxpin.py pyproject.toml ./

ENV PYTHONUNBUFFERED=1
# HTTP / metrics / gRPC (docs/configs.md)
EXPOSE 8000 2121 9000

# default entrypoint: the LLM serving example (random-init dev weights);
# real deployments override CMD with their own app module
CMD ["python", "examples/serving-llm/main.py"]
