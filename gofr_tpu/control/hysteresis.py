"""The flap-damping decision core shared by every control loop.

Extracted verbatim from the PR 11 elastic-fleet ``ScaleDecider``
(fleet/autoscaler.py), which now delegates here: a pure state machine
over an explicit ``now`` — no threads, no wall clock — so quick-tier
units drive it with fake clocks. The step-level ``StepController``
(control/controller.py) reuses the same machine to gate knob trials,
which is the point of the extraction: replica scaling and knob tuning
damp oscillation with ONE proven set of semantics instead of two
subtly-different reimplementations.

Semantics (unchanged from the autoscaler):

- **hysteresis band**: the caller classifies each reading as ``hot``,
  ``calm``, or neither. Inside the band neither streak accumulates —
  a signal oscillating around one threshold can never trigger.
- **sustain**: ``hot`` must persist ``sustain_s`` before the gate fires
  hot; ``calm`` must persist ``idle_s`` before it fires calm. A single
  contrary reading resets the opposing streak (a blip restarts the
  clock).
- **cooldown**: after the caller reports an executed action via
  :meth:`note_action`, the gate holds for ``cooldown_hot_s`` /
  ``cooldown_calm_s`` (per direction) measured from the ACTION, not
  from the decision — what actually happened anchors the lockout.
- **stale freeze**: readings older than ``stale_s`` freeze the gate AND
  forget both streaks — after a signal-plane gap the world may have
  changed, so evidence restarts from scratch.
"""

from __future__ import annotations

__all__ = ["HysteresisGate"]


class HysteresisGate:
    """Pure hysteresis + sustain + cooldown over an explicit clock.

    :meth:`decide` returns one of ``"hot" | "calm" | "hold" | "freeze"``;
    the caller maps hot/calm onto its own actions (scale out/in, try a
    knob move, ...) and reports executed actions back via
    :meth:`note_action` so cooldowns anchor on reality.
    """

    def __init__(self, *, sustain_s: float, idle_s: float,
                 cooldown_hot_s: float, cooldown_calm_s: float,
                 stale_s: float):
        self.sustain_s = float(sustain_s)
        self.idle_s = float(idle_s)
        self.cooldown_hot_s = float(cooldown_hot_s)
        self.cooldown_calm_s = float(cooldown_calm_s)
        self.stale_s = float(stale_s)
        self._pressure_since: float | None = None
        self._calm_since: float | None = None
        self.last_action_at = float("-inf")

    def note_action(self, now: float) -> None:
        """An action was EXECUTED: anchor cooldowns here and restart both
        evidence streaks (the action changed the world the streaks
        measured)."""
        self.last_action_at = now
        self._pressure_since = None
        self._calm_since = None

    def decide(self, *, hot: bool, calm: bool, now: float,
               age_s: float = 0.0) -> str:
        if age_s > self.stale_s:
            # signal plane went silent: no decision on fiction, and the
            # streaks must not survive the gap
            self._pressure_since = None
            self._calm_since = None
            return "freeze"
        if hot:
            self._calm_since = None
            if self._pressure_since is None:
                self._pressure_since = now
        elif calm:
            self._pressure_since = None
            if self._calm_since is None:
                self._calm_since = now
        else:
            # inside the hysteresis band: neither streak accumulates
            self._pressure_since = None
            self._calm_since = None
        if (hot and now - self._pressure_since >= self.sustain_s
                and now - self.last_action_at >= self.cooldown_hot_s):
            return "hot"
        if (calm and now - self._calm_since >= self.idle_s
                and now - self.last_action_at >= self.cooldown_calm_s):
            return "calm"
        return "hold"

    def state(self) -> dict:
        """JSON-safe gate internals for debug endpoints."""
        return {
            "pressure_since": self._pressure_since,
            "calm_since": self._calm_since,
            "last_action_at": (None if self.last_action_at == float("-inf")
                               else self.last_action_at),
        }
