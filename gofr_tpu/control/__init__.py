"""Online step-level control plane (ROADMAP O2; docs/serving.md § "Online
controller").

PR 14's perf plane made every device step priced and every pipeline gap
accounted; this package promotes that measurement to ACTUATION. Two
pieces:

- :mod:`gofr_tpu.control.hysteresis` — the sustain/idle/cooldown/stale
  decision core extracted from the PR 11 elastic-fleet ``ScaleDecider``
  (fleet/autoscaler.py), shared verbatim between fleet-level replica
  scaling and step-level knob tuning so both planes damp flapping the
  same proven way;
- :mod:`gofr_tpu.control.controller` — the per-engine ``StepController``
  that bucketizes live perf samples per (step kind, kv dtype, occupancy
  band) and proposes bounded single-knob moves for pipeline depth,
  chunked-prefill chunk size, speculative round length and admission
  batch width, judged by measured roofline attainment and ``_dq``
  bubble ratio, with decisions pinned/persisted like autotune so a
  restarted fleet resumes tuned.

The thesis is PAPERS.md 1605.08695 applied at the step level: the
system adapts to the workload, not the workload to the system.
"""

from gofr_tpu.control.controller import (
    ControlPolicy,
    Decision,
    KnobSpec,
    StepController,
)
from gofr_tpu.control.hysteresis import HysteresisGate

__all__ = [
    "ControlPolicy",
    "Decision",
    "HysteresisGate",
    "KnobSpec",
    "StepController",
]
