"""Online step-level knob controller: the perf plane closed into a loop.

``StepController`` turns the PR 14 measurement plane into actuation. It
is deliberately engine-agnostic — knobs are ``KnobSpec`` records with
injected ``read``/``apply`` callables, evidence comes from an injected
``window_fn`` (the engine passes ``PerfPlane.band_totals``), and the
clock is injectable — so the quick-tier units drive the whole state
machine with fake clocks and synthetic windows, no engine required.

The loop, once per ``CONTROL_INTERVAL_S`` tick (driven from the engine's
device loop at the loop-top safe seam):

1. **sense** — read the band-labeled perf window accumulated since the
   last consumed tick: per (step kind, kv dtype, occupancy band) FLOPs,
   bytes, device-seconds, steps, and the ``_dq`` bubble in front of each
   step. The tick is skipped (evidence carries over) below
   ``CONTROL_MIN_STEPS``.
2. **judge** — roofline attainment ``max(MFU, MBU)`` over the window and
   the bubble ratio combine into one score, ``attainment * (1 - bubble
   ratio)``: a knob move only wins by making the device do the same
   priced work in less busy time or with fewer bubbles. Hot/calm
   classification feeds the shared :class:`HysteresisGate` (the PR 11
   ScaleDecider core), so proposals need SUSTAINED pressure and respect
   per-direction cooldowns.
3. **act** — one bounded single-knob move at a time, as a TRIAL: apply
   the neighbor value, measure the next evidence window, then COMMIT
   (pin + persist) if the score improved by at least
   ``CONTROL_EPSILON``, else REVERT and back off that (knob, direction)
   with doubling delay. A knob whose committed values alternate is
   flagged ``oscillating`` and frozen — the damping the fleet decider
   proved.

Commits are pinned per (knob, kv dtype, occupancy band, device kind,
shard) and persisted autotune-style: versioned JSON, read-merge-write of
our own keys only, atomic replace — a restarted or scaled-out replica
resumes tuned instead of re-exploring (``CONTROL_CACHE``).

Stand-down: like the autotuner, the controller disables itself where
acting would be wrong — an injected ``standdown_fn`` returning a reason
(the engine wires lockstep roles here: leader-only knob moves would
desync followers) parks the controller with one recorded decision.
"""

from __future__ import annotations

import collections
import json
import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable

from gofr_tpu.control.hysteresis import HysteresisGate

__all__ = ["ControlPolicy", "Decision", "KnobSpec", "StepController",
           "FORMAT_VERSION", "entry_key"]

FORMAT_VERSION = 1

# the knobs this plane knows how to move, in round-robin proposal order
KNOB_NAMES = ("pipeline_depth", "prefill_chunk", "spec_tokens",
              "prefill_batch")


def entry_key(knob: str, band: str, *, kv_dtype: str, device_kind: str,
              shard: str) -> str:
    """Persisted-pin key: one decision per (knob, kv dtype, occupancy
    band, device kind, shard) — the same dimensions autotune keys its
    kernel pins by, because a knob that wins on int4/v5e/tp4 can lose on
    bf16/cpu/tp1."""
    return (f"{knob}|kv={kv_dtype}|band={band}|dev={device_kind}"
            f"|shard={shard}")


@dataclass
class KnobSpec:
    """One tunable knob: its allowed values (ascending; bounded by the
    operator's boot configuration — the controller explores WITHIN what
    was provisioned, never past it) and the engine's read/apply seams."""

    name: str
    values: tuple
    read: Callable[[], int]
    apply: Callable[[int], None]

    def neighbor(self, current, direction: int):
        """The next allowed value in ``direction`` (+1/-1), or None at
        the range edge. A current value outside ``values`` (legacy boot
        config) snaps to the nearest allowed one first."""
        if not self.values:
            return None
        vals = self.values
        if current in vals:
            i = vals.index(current)
        else:
            i = min(range(len(vals)), key=lambda j: abs(vals[j] - current))
            # snapping IS the move: propose the nearest legal value
            return vals[i]
        j = i + (1 if direction > 0 else -1)
        if 0 <= j < len(vals):
            return vals[j]
        return None


@dataclass
class ControlPolicy:
    """CONTROL_* configuration (docs/configs.md)."""

    interval_s: float = 5.0        # evidence tick
    sustain_s: float = 10.0        # pressure persistence before a trial
    idle_s: float = 60.0           # calm persistence (gate symmetry)
    cooldown_s: float = 15.0       # lockout after a committed/reverted move
    stale_s: float = 120.0         # evidence silence that freezes the gate
    epsilon: float = 0.03          # relative score gain a commit requires
    bubble_hi: float = 0.15        # bubble ratio counting as pressure
    bubble_lo: float = 0.05        # bubble ratio below which we're calm
    attain_lo: float = 0.30        # attainment below which we're hot
    attain_hi: float = 0.60        # attainment above which we're calm
    min_steps: int = 8             # evidence floor per judged window
    max_trial_ticks: int = 3       # evidence-less ticks before a trial aborts
    backoff_s: float = 60.0        # first revert backoff (doubles, capped)
    backoff_cap_s: float = 960.0
    decisions_keep: int = 128      # decision ring depth
    cache_path: str = ""           # pin persistence ("" = in-memory only)
    knobs: tuple = KNOB_NAMES      # which knobs this replica may move

    def __post_init__(self) -> None:
        if self.bubble_lo > self.bubble_hi or self.attain_hi < self.attain_lo:
            # an inverted band would make one window simultaneously hot
            # and calm — flap by construction (AutoscalePolicy's rule)
            raise ValueError(
                "CONTROL hysteresis bands inverted: *_lo must sit at or "
                "below *_hi")
        if self.interval_s <= 0:
            raise ValueError("CONTROL_INTERVAL_S must be > 0")

    @classmethod
    def from_config(cls, conf) -> "ControlPolicy":
        interval = conf.get_float("CONTROL_INTERVAL_S", 5.0)
        knobs_csv = conf.get_or_default("CONTROL_KNOBS", "") or ""
        knobs = tuple(k.strip() for k in knobs_csv.split(",")
                      if k.strip()) or KNOB_NAMES
        return cls(
            interval_s=interval,
            sustain_s=conf.get_float("CONTROL_SUSTAIN_S", 2.0 * interval),
            idle_s=conf.get_float("CONTROL_IDLE_S", 12.0 * interval),
            cooldown_s=conf.get_float("CONTROL_COOLDOWN_S", 3.0 * interval),
            stale_s=conf.get_float("CONTROL_STALE_S", 24.0 * interval),
            epsilon=conf.get_float("CONTROL_EPSILON", 0.03),
            bubble_hi=conf.get_float("CONTROL_BUBBLE_HI", 0.15),
            bubble_lo=conf.get_float("CONTROL_BUBBLE_LO", 0.05),
            attain_lo=conf.get_float("CONTROL_ATTAIN_LO", 0.30),
            attain_hi=conf.get_float("CONTROL_ATTAIN_HI", 0.60),
            min_steps=conf.get_int("CONTROL_MIN_STEPS", 8),
            max_trial_ticks=conf.get_int("CONTROL_MAX_TRIAL_TICKS", 3),
            backoff_s=conf.get_float("CONTROL_BACKOFF_S", 12.0 * interval),
            backoff_cap_s=conf.get_float("CONTROL_BACKOFF_CAP_S",
                                         192.0 * interval),
            decisions_keep=conf.get_int("CONTROL_DECISIONS_KEEP", 128),
            cache_path=conf.get_or_default("CONTROL_CACHE", "") or "",
            knobs=knobs,
        )


@dataclass
class Decision:
    """One controller decision, as recorded in the flight ring."""

    at: float
    verdict: str               # try | commit | revert | resume | standdown
    knob: str = ""
    frm: Any = None
    to: Any = None
    band: str = ""
    score: float | None = None
    baseline: float | None = None
    evidence: dict = field(default_factory=dict)
    reason: str = ""

    def to_dict(self) -> dict[str, Any]:
        out = {"at": round(self.at, 3), "verdict": self.verdict}
        if self.knob:
            out.update(knob=self.knob, **{"from": self.frm, "to": self.to},
                       band=self.band)
        if self.score is not None:
            out["score"] = round(self.score, 6)
        if self.baseline is not None:
            out["baseline"] = round(self.baseline, 6)
        if self.evidence:
            out["evidence"] = self.evidence
        if self.reason:
            out["reason"] = self.reason
        return out


def _load_cache(path: str) -> dict[str, Any]:
    """Autotune's loading discipline: a missing, corrupt, or
    version-mismatched cache is an EMPTY cache, never an error."""
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
        if (isinstance(data, dict)
                and data.get("version") == FORMAT_VERSION
                and isinstance(data.get("entries"), dict)):
            return dict(data["entries"])
    except (OSError, ValueError):
        pass
    return {}


class StepController:
    """Per-engine online knob controller. Single-threaded by contract:
    every method is called from the engine's device loop (or a test's
    fake loop) — applies land at the loop-top safe seam by construction,
    so no knob ever changes under an in-flight dispatch's feet."""

    def __init__(self, policy: ControlPolicy, knobs: Iterable[KnobSpec], *,
                 kv_dtype: str = "bf16", device_kind: str = "cpu",
                 shard: str = "tp1",
                 window_fn: Callable[[float, float | None], dict] | None = None,
                 standdown_fn: Callable[[], str | None] | None = None,
                 on_decision: Callable[[Decision], None] | None = None,
                 clock: Callable[[], float] = time.monotonic,
                 logger=None):
        self.policy = policy
        self.knobs = {k.name: k for k in knobs if k.name in policy.knobs}
        self.kv_dtype = str(kv_dtype)
        self.device_kind = str(device_kind)
        self.shard = str(shard)
        self._window_fn = window_fn or (lambda now, since: {})
        self._standdown_fn = standdown_fn or (lambda: None)
        self._on_decision = on_decision
        self._clock = clock
        self._log = logger
        self.gate = HysteresisGate(
            sustain_s=policy.sustain_s, idle_s=policy.idle_s,
            cooldown_hot_s=policy.cooldown_s, cooldown_calm_s=policy.cooldown_s,
            stale_s=policy.stale_s)
        now = clock()
        self._last_tick = now
        self._since: float | None = now     # evidence window start
        self._last_evidence_at = now
        self._trial: dict[str, Any] | None = None
        self._rr = 0                        # round-robin proposal cursor
        self._backoff: dict[tuple[str, int], tuple[float, float]] = {}
        self._commits: dict[str, collections.deque] = {}
        self._frozen: set[str] = set()
        self._resumed: set[tuple[str, str]] = set()
        self.oscillating = False
        self.standdown: str | None = None
        self.decisions: collections.deque[Decision] = collections.deque(
            maxlen=max(1, policy.decisions_keep))
        self._pins: dict[str, Any] = (
            _load_cache(policy.cache_path) if policy.cache_path else {})
        self._last_evidence: dict[str, Any] = {}

    # -- persistence (the autotune read-merge-write discipline) -------------

    def _key(self, knob: str, band: str) -> str:
        return entry_key(knob, band, kv_dtype=self.kv_dtype,
                         device_kind=self.device_kind, shard=self.shard)

    def _persist(self, key: str, value, score: float | None) -> None:
        self._pins[key] = {"value": value, "at": time.time(),
                           "score": round(score, 6) if score is not None
                           else None}
        path = self.policy.cache_path
        if not path:
            return
        try:
            merged = _load_cache(path)
            merged[key] = self._pins[key]
            tmp = f"{path}.tmp.{os.getpid()}"
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump({"version": FORMAT_VERSION, "entries": merged},
                          f, indent=1, sort_keys=True)
            os.replace(tmp, path)
        except OSError as e:  # cache loss must never gate serving
            if self._log is not None:
                self._log.warnf("control pin persist failed: %r", e)

    def pin_for(self, knob: str, band: str):
        ent = self._pins.get(self._key(knob, band))
        return ent.get("value") if isinstance(ent, dict) else None

    # -- evidence ------------------------------------------------------------

    @staticmethod
    def _summarize(bands: dict[str, dict[str, float]]) -> dict[str, Any]:
        """Collapse a band_totals payload into one judged window: total
        priced work vs capacity (attainment), total bubble vs busy, and
        the dominant occupancy band by device-seconds share."""
        steps = busy = bubble = flops = bytes_ = fcap = bcap = 0.0
        per_band: dict[str, float] = {}
        for key, rec in bands.items():
            band = key.rsplit("|", 1)[1]
            steps += rec.get("steps", 0.0)
            busy += rec.get("device_s", 0.0)
            bubble += rec.get("bubble_s", 0.0)
            flops += rec.get("flops", 0.0)
            bytes_ += rec.get("bytes", 0.0)
            fcap += rec.get("flops_cap", 0.0)
            bcap += rec.get("bytes_cap", 0.0)
            per_band[band] = per_band.get(band, 0.0) + rec.get("device_s", 0.0)
        attain = max(flops / fcap if fcap else 0.0,
                     bytes_ / bcap if bcap else 0.0)
        denom = bubble + busy
        bubble_ratio = bubble / denom if denom else 0.0
        band = max(per_band, key=per_band.get) if per_band else "lo"
        return {
            "steps": int(steps), "device_s": busy, "attainment": attain,
            "bubble_ratio": bubble_ratio, "band": band,
            "score": attain * (1.0 - bubble_ratio),
        }

    # -- the tick ------------------------------------------------------------

    def maybe_tick(self, now: float | None = None) -> Decision | None:
        """Cheap per-iteration entry point: no-op between ticks."""
        now = self._clock() if now is None else now
        reason = self._standdown_fn()
        if reason:
            if self.standdown != reason:
                self.standdown = reason
                return self._record(Decision(
                    at=now, verdict="standdown", reason=reason))
            return None
        self.standdown = None
        if now - self._last_tick < self.policy.interval_s:
            return None
        self._last_tick = now
        return self._tick(now)

    def _record(self, d: Decision) -> Decision:
        self.decisions.append(d)
        if self._on_decision is not None:
            try:
                self._on_decision(d)
            except Exception:  # noqa: BLE001 - observers never gate control
                pass
        return d

    def _note_commit(self, knob: str, value) -> None:
        hist = self._commits.setdefault(knob, collections.deque(maxlen=4))
        hist.append(value)
        if len(hist) >= 3 and hist[-1] == hist[-3] and hist[-1] != hist[-2]:
            # a->b->a committed: the score signal is flapping faster than
            # the workload — freeze this knob and raise the flag
            self.oscillating = True
            self._frozen.add(knob)
            if self._log is not None:
                self._log.warnf("control knob %s oscillating (%r); frozen",
                                knob, list(hist))

    def _tick(self, now: float) -> Decision | None:
        p = self.policy
        ev = self._summarize(self._window_fn(now, self._since))
        if ev["steps"] >= p.min_steps:
            self._last_evidence_at = now
            self._last_evidence = ev
        if self._trial is not None:
            return self._judge_trial(now, ev)
        if ev["steps"] < p.min_steps:
            # starved window: leave _since where it is so evidence
            # accumulates across ticks instead of being discarded
            return None
        self._since = now
        band = ev["band"]
        resumed = self._resume(now, band)
        if resumed is not None:
            return resumed
        hot = (ev["bubble_ratio"] >= p.bubble_hi
               or ev["attainment"] <= p.attain_lo)
        calm = (ev["bubble_ratio"] <= p.bubble_lo
                and ev["attainment"] >= p.attain_hi)
        verdict = self.gate.decide(hot=hot, calm=calm, now=now,
                                   age_s=now - self._last_evidence_at)
        if verdict != "hot":
            return None
        return self._propose(now, ev)

    def _resume(self, now: float, band: str) -> Decision | None:
        """A persisted pin for the dominant band overrides the boot value
        once, without a trial — the restarted-fleet-resumes-tuned path."""
        for name, spec in self.knobs.items():
            if name in self._frozen or (name, band) in self._resumed:
                continue
            pin = self.pin_for(name, band)
            if pin is None or pin not in spec.values:
                continue
            cur = spec.read()
            self._resumed.add((name, band))
            if pin == cur:
                continue
            spec.apply(pin)
            self.gate.note_action(now)
            return self._record(Decision(
                at=now, verdict="resume", knob=name, frm=cur, to=pin,
                band=band))
        return None

    def _propose(self, now: float, ev: dict[str, Any]) -> Decision | None:
        """One bounded single-knob move, round-robin over the knob set.
        Bubble pressure prefers the move that adds overlap or work per
        dispatch (+1 toward deeper/wider/bigger); attainment pressure
        with a quiet pipeline tries the same direction first but will
        take -1 when +1 is exhausted or backed off."""
        p = self.policy
        names = [n for n in self.knobs if n not in self._frozen]
        if not names:
            return None
        order = names[self._rr % len(names):] + names[:self._rr % len(names)]
        self._rr += 1
        for name in order:
            spec = self.knobs[name]
            cur = spec.read()
            for direction in (1, -1):
                until, _delay = self._backoff.get((name, direction),
                                                  (float("-inf"), p.backoff_s))
                if now < until:
                    continue
                to = spec.neighbor(cur, direction)
                if to is None or to == cur:
                    continue
                spec.apply(to)
                self._trial = {"knob": name, "frm": cur, "to": to,
                               "band": ev["band"], "baseline": ev["score"],
                               "direction": direction, "ticks": 0}
                return self._record(Decision(
                    at=now, verdict="try", knob=name, frm=cur, to=to,
                    band=ev["band"], baseline=ev["score"],
                    evidence={"steps": ev["steps"],
                              "attainment": round(ev["attainment"], 6),
                              "bubble_ratio": round(ev["bubble_ratio"], 6)}))
        return None

    def _judge_trial(self, now: float, ev: dict[str, Any]) -> Decision | None:
        p = self.policy
        t = self._trial
        if ev["steps"] < p.min_steps:
            t["ticks"] += 1
            if t["ticks"] < p.max_trial_ticks:
                return None  # keep measuring; evidence accumulates
            # the workload dried up under the trial: revert without
            # judging — an unjudged knob must not linger
            return self._finish_trial(now, ev, commit=False,
                                      reason="no-evidence")
        self._since = now
        improved = ev["score"] >= t["baseline"] * (1.0 + p.epsilon)
        return self._finish_trial(now, ev, commit=improved)

    def _finish_trial(self, now: float, ev: dict[str, Any], *, commit: bool,
                      reason: str = "") -> Decision:
        p = self.policy
        t, self._trial = self._trial, None
        name, spec = t["knob"], self.knobs[t["knob"]]
        self.gate.note_action(now)
        evidence = {"steps": ev["steps"],
                    "attainment": round(ev["attainment"], 6),
                    "bubble_ratio": round(ev["bubble_ratio"], 6)}
        if commit:
            self._backoff.pop((name, t["direction"]), None)
            self._persist(self._key(name, t["band"]), t["to"], ev["score"])
            self._note_commit(name, t["to"])
            return self._record(Decision(
                at=now, verdict="commit", knob=name, frm=t["frm"],
                to=t["to"], band=t["band"], score=ev["score"],
                baseline=t["baseline"], evidence=evidence))
        spec.apply(t["frm"])
        _until, delay = self._backoff.get((name, t["direction"]),
                                          (float("-inf"), p.backoff_s))
        self._backoff[(name, t["direction"])] = (
            now + delay, min(delay * 2.0, p.backoff_cap_s))
        return self._record(Decision(
            at=now, verdict="revert", knob=name, frm=t["to"], to=t["frm"],
            band=t["band"], score=ev["score"], baseline=t["baseline"],
            evidence=evidence, reason=reason))

    # -- operator view -------------------------------------------------------

    def report(self) -> dict[str, Any]:
        """JSON-safe /debug/control payload."""
        return {
            "enabled": True,
            "standdown": self.standdown,
            "interval_s": self.policy.interval_s,
            "oscillating": self.oscillating,
            "knobs": {
                name: {"value": spec.read(),
                       "allowed": list(spec.values),
                       "frozen": name in self._frozen}
                for name, spec in self.knobs.items()},
            "pins": {k: v for k, v in self._pins.items()
                     if k.endswith(f"|dev={self.device_kind}"
                                   f"|shard={self.shard}")
                     and f"|kv={self.kv_dtype}|" in k},
            "trial": ({k: v for k, v in self._trial.items()}
                      if self._trial else None),
            "gate": self.gate.state(),
            "evidence": self._last_evidence,
            "decisions": [d.to_dict() for d in reversed(self.decisions)],
        }
