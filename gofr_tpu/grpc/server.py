"""gRPC server with logging/tracing/panic-recovery interceptor.

Parity with gofr `pkg/gofr/grpc.go:22-27` (chained interceptors: recovery +
logging/tracing) and `pkg/gofr/grpc/log.go` (per-RPC span + structured RPCLog
with method/status/µs). Servicers are generated-protobuf classes registered via
``app.register_grpc_service(add_fn, servicer)``.
"""

from __future__ import annotations

import contextvars
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any

import grpc

from gofr_tpu.context import Context

_grpc_ctx: contextvars.ContextVar[Context | None] = contextvars.ContextVar("gofr_grpc_ctx", default=None)


def current_grpc_context() -> Context | None:
    """The framework Context for the in-flight RPC (same surface as HTTP
    handlers get — closes the reference's gRPC asymmetry)."""
    return _grpc_ctx.get()


class RPCLog:
    def __init__(self, method: str, status_code: int, duration_us: int, trace_id: str,
                 messages: int | None = None):
        self.method = method
        self.status_code = status_code
        self.duration_us = duration_us
        self.trace_id = trace_id
        self.messages = messages  # response count for streaming RPCs

    def to_log_dict(self) -> dict[str, Any]:
        out = {
            "message": "rpc",
            "method": self.method,
            "status_code": self.status_code,
            "duration_us": self.duration_us,
            "trace_id": self.trace_id,
        }
        if self.messages is not None:
            out["messages"] = self.messages
        return out

    def pretty_print(self, w) -> None:
        extra = f" msgs={self.messages}" if self.messages is not None else ""
        w.write(f"  RPC {self.method} status={self.status_code} {self.duration_us}µs{extra}\n")


class GofrGrpcInterceptor(grpc.ServerInterceptor):
    """Recovery + span + RPCLog for ALL four RPC kinds — the reference
    intercepts only unary calls (`grpc.go:24`); here streaming RPCs get the
    same treatment so a streaming handler crash becomes INTERNAL with a
    logged span instead of a bare connection reset."""

    def __init__(self, container):
        self._container = container

    def intercept_service(self, continuation, handler_call_details):
        handler = continuation(handler_call_details)
        if handler is None:
            return handler
        method = handler_call_details.method
        metadata = dict(handler_call_details.invocation_metadata or ())

        dispatch = (
            ("unary_unary", self._wrap_unary, grpc.unary_unary_rpc_method_handler),
            ("unary_stream", self._wrap_stream, grpc.unary_stream_rpc_method_handler),
            ("stream_unary", self._wrap_unary, grpc.stream_unary_rpc_method_handler),
            ("stream_stream", self._wrap_stream, grpc.stream_stream_rpc_method_handler),
        )
        for attr, wrap, factory in dispatch:
            inner = getattr(handler, attr)
            if inner:
                return factory(
                    wrap(inner, method, metadata),
                    request_deserializer=handler.request_deserializer,
                    response_serializer=handler.response_serializer,
                )
        return handler

    def _begin(self, request, method: str, metadata: dict[str, str],
               servicer_context=None):
        container = self._container
        span = container.tracer.start_span(
            f"grpc {method}", traceparent=metadata.get("traceparent"), kind="SERVER",
            set_current=False,
        )
        span.set_attribute("rpc.method", method)
        adapter = _GRPCRequestAdapter(request, metadata)
        if servicer_context is not None:
            # the client's RPC deadline joins the request-lifetime plane
            # (docs/resilience.md): stored as a monotonic deadline on the
            # request context, Context folds the remaining budget into the
            # engine timeout — DEADLINE_EXCEEDED then reflects the CLIENT's
            # budget, not only the server default
            try:
                tr = servicer_context.time_remaining()
            except Exception:  # noqa: BLE001 - non-standard test doubles
                tr = None
            if tr is not None and tr < 3600 * 24 * 365:
                from gofr_tpu import deadline as _deadline

                _deadline.set_deadline(adapter.context(),
                                       time.monotonic() + max(0.0, tr))
        ctx = Context(adapter, container, span=span)
        token = _grpc_ctx.set(ctx)
        return span, token

    def _end(self, span, token, method: str, status: int, start: float,
             messages: int | None = None) -> None:
        try:
            _grpc_ctx.reset(token)
        except ValueError:
            # a cancelled stream generator can be finalized by the GC on a
            # different thread; the token belongs to the serving thread's
            # context then. The span/log below must still run.
            pass
        span.set_attribute("rpc.status_code", status)
        if messages is not None:
            span.set_attribute("rpc.messages", messages)
        span.finish()
        self._container.logger.info(
            RPCLog(method, status, int((time.perf_counter() - start) * 1e6),
                   span.trace_id, messages=messages)
        )

    def _wrap_unary(self, inner, method: str, metadata: dict[str, str]):
        container = self._container

        def wrapped(request, servicer_context):
            span, token = self._begin(request, method, metadata, servicer_context)
            start = time.perf_counter()
            status = 0
            try:
                return inner(request, servicer_context)
            except Exception as e:  # noqa: BLE001 - panic recovery → typed code or INTERNAL
                code = _grpc_code_of(e)
                status = code.value[0]
                span.set_status("ERROR")
                if code is grpc.StatusCode.INTERNAL:
                    container.logger.log_exception(e, f"grpc handler {method}")
                    servicer_context.abort(grpc.StatusCode.INTERNAL, "internal error")
                else:
                    # typed (QoS/timeout) rejection: retryable status + hint,
                    # no stack spam — rejection under load is not a fault
                    _abort_typed(servicer_context, e, code)
            finally:
                self._end(span, token, method, status, start)

        return wrapped

    def _wrap_stream(self, inner, method: str, metadata: dict[str, str]):
        container = self._container

        def wrapped(request, servicer_context):
            span, token = self._begin(request, method, metadata, servicer_context)
            start = time.perf_counter()
            status = 0
            sent = 0
            try:
                for item in inner(request, servicer_context):
                    sent += 1
                    yield item
            except GeneratorExit:
                # client cancelled mid-stream — log it as CANCELLED, not OK,
                # so cancellation storms are visible in logs/traces
                status = 1  # grpc CANCELLED
                span.set_status("CANCELLED")
                raise
            except Exception as e:  # noqa: BLE001 - panic recovery → typed code or INTERNAL
                code = _grpc_code_of(e)
                status = code.value[0]
                span.set_status("ERROR")
                if code is grpc.StatusCode.INTERNAL:
                    container.logger.log_exception(e, f"grpc stream handler {method}")
                    servicer_context.abort(grpc.StatusCode.INTERNAL, "internal error")
                else:
                    _abort_typed(servicer_context, e, code)
            finally:
                self._end(span, token, method, status, start, messages=sent)

        return wrapped


def _grpc_code_of(e: Exception) -> grpc.StatusCode:
    """Map typed HTTP errors to gRPC codes so QoS rejections raised inside
    handlers (engine admission: 429/503) surface as retryable statuses
    instead of INTERNAL."""
    sc = getattr(e, "status_code", None)
    if sc == 429:
        return grpc.StatusCode.RESOURCE_EXHAUSTED
    if sc == 503:
        return grpc.StatusCode.UNAVAILABLE
    if sc in (408, 504):
        # 408 = server-side timeout, 504 = the client's propagated deadline
        # was unmeetable (sheds with reason deadline_exceeded) — both are
        # DEADLINE_EXCEEDED on the wire
        return grpc.StatusCode.DEADLINE_EXCEEDED
    return grpc.StatusCode.INTERNAL


def _abort_typed(servicer_context, e: Exception, code: grpc.StatusCode) -> None:
    from gofr_tpu.http.errors import retry_after_hint

    retry_after = getattr(e, "retry_after", None)
    if retry_after is not None:
        servicer_context.set_trailing_metadata(
            (("retry-after", retry_after_hint(retry_after)),))
    servicer_context.abort(code, str(e) or code.name.lower().replace("_", " "))


class QoSGrpcInterceptor(grpc.ServerInterceptor):
    """Transport-edge admission control for gRPC (the 429/503 analog):
    over-rate traffic aborts RESOURCE_EXHAUSTED, backlog shedding aborts
    UNAVAILABLE, both with ``retry-after`` trailing metadata — the request
    never reaches the servicer or the model engine. Ordered OUTSIDE the
    Gofr interceptor so a rejection is not re-wrapped into INTERNAL."""

    def __init__(self, container):
        self._container = container

    def intercept_service(self, continuation, handler_call_details):
        handler = continuation(handler_call_details)
        controller = getattr(self._container, "qos", None)
        if handler is None or controller is None:
            return handler
        method = handler_call_details.method
        metadata = dict(handler_call_details.invocation_metadata or ())

        def check(servicer_context) -> None:
            decision = controller.admit_transport(
                route=method,
                api_key=metadata.get("x-api-key", ""),
                tenant=metadata.get(controller.policy.tenant_header.lower(), ""),
                cls_name=metadata.get(controller.policy.class_header.lower()),
            )
            if not decision.allowed:
                from gofr_tpu.http.errors import retry_after_hint

                servicer_context.set_trailing_metadata(
                    (("retry-after", retry_after_hint(decision.retry_after)),))
                code = (grpc.StatusCode.RESOURCE_EXHAUSTED if decision.status == 429
                        else grpc.StatusCode.UNAVAILABLE)
                servicer_context.abort(code, decision.message)

        def wrap_unary(inner):
            def wrapped(request, servicer_context):
                check(servicer_context)
                return inner(request, servicer_context)
            return wrapped

        def wrap_stream(inner):
            def wrapped(request, servicer_context):
                check(servicer_context)
                yield from inner(request, servicer_context)
            return wrapped

        dispatch = (
            ("unary_unary", wrap_unary, grpc.unary_unary_rpc_method_handler),
            ("unary_stream", wrap_stream, grpc.unary_stream_rpc_method_handler),
            ("stream_unary", wrap_unary, grpc.stream_unary_rpc_method_handler),
            ("stream_stream", wrap_stream, grpc.stream_stream_rpc_method_handler),
        )
        for attr, wrap, factory in dispatch:
            inner = getattr(handler, attr)
            if inner:
                return factory(
                    wrap(inner),
                    request_deserializer=handler.request_deserializer,
                    response_serializer=handler.response_serializer,
                )
        return handler


class _GRPCRequestAdapter:
    """Request-interface adapter over a protobuf message."""

    def __init__(self, message, metadata: dict[str, str]):
        self.message = message
        self.metadata = metadata
        self._ctx: dict[str, Any] = {}

    def param(self, key: str) -> str:
        return str(self.metadata.get(key, ""))

    def params(self, key: str) -> list[str]:
        v = self.param(key)
        return [v] if v else []

    def path_param(self, key: str) -> str:
        # gRPC has no path; metadata is the closest analog, so handlers
        # written against the HTTP Context shape still resolve something
        return str(self.metadata.get(key, ""))

    def bind(self, target: Any = None) -> Any:
        """No target → the raw message (protobuf or decoded JSON); a
        JSON-shaped message coerces through the SAME binder as the HTTP
        path (`http/request.py:95`), so dataclass/annotated-class targets
        behave identically across transports."""
        if target is None:
            return self.message
        if isinstance(self.message, (dict, list, str, int, float, bool)):
            from gofr_tpu.utils import bind as binder

            return binder.bind(self.message, target)
        return self.message  # protobuf message: handler works with it directly

    def host_name(self) -> str:
        return "grpc"

    def context(self) -> dict[str, Any]:
        return self._ctx


def start_grpc_server(app) -> grpc.Server:
    interceptors: list[grpc.ServerInterceptor] = []
    if getattr(app.container, "qos", None) is not None:
        # outermost: a QoS rejection aborts before the Gofr wrapper (which
        # would log it as INTERNAL) or the servicer ever runs
        interceptors.append(QoSGrpcInterceptor(app.container))
    interceptors.append(GofrGrpcInterceptor(app.container))
    server = grpc.server(
        ThreadPoolExecutor(max_workers=app.config.get_int("GRPC_THREADS", 16),
                           thread_name_prefix="gofr-grpc"),
        interceptors=interceptors,
    )
    for adder, servicer in app._grpc_services:
        if servicer is not None:
            adder(servicer, server)
        elif callable(adder):
            adder(server)
    server.add_insecure_port(f"[::]:{app.grpc_port}")
    server.start()
    return server
