"""gRPC server with logging/tracing/panic-recovery interceptor.

Parity with gofr `pkg/gofr/grpc.go:22-27` (chained interceptors: recovery +
logging/tracing) and `pkg/gofr/grpc/log.go` (per-RPC span + structured RPCLog
with method/status/µs). Servicers are generated-protobuf classes registered via
``app.register_grpc_service(add_fn, servicer)``.
"""

from __future__ import annotations

import contextvars
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any

import grpc

from gofr_tpu.context import Context

_grpc_ctx: contextvars.ContextVar[Context | None] = contextvars.ContextVar("gofr_grpc_ctx", default=None)


def current_grpc_context() -> Context | None:
    """The framework Context for the in-flight RPC (same surface as HTTP
    handlers get — closes the reference's gRPC asymmetry)."""
    return _grpc_ctx.get()


class RPCLog:
    def __init__(self, method: str, status_code: int, duration_us: int, trace_id: str):
        self.method = method
        self.status_code = status_code
        self.duration_us = duration_us
        self.trace_id = trace_id

    def to_log_dict(self) -> dict[str, Any]:
        return {
            "message": "rpc",
            "method": self.method,
            "status_code": self.status_code,
            "duration_us": self.duration_us,
            "trace_id": self.trace_id,
        }

    def pretty_print(self, w) -> None:
        w.write(f"  RPC {self.method} status={self.status_code} {self.duration_us}µs\n")


class GofrGrpcInterceptor(grpc.ServerInterceptor):
    def __init__(self, container):
        self._container = container

    def intercept_service(self, continuation, handler_call_details):
        handler = continuation(handler_call_details)
        if handler is None or not handler.unary_unary:
            return handler
        container = self._container
        method = handler_call_details.method
        metadata = dict(handler_call_details.invocation_metadata or ())
        inner = handler.unary_unary

        def wrapped(request, servicer_context):
            span = container.tracer.start_span(
                f"grpc {method}", traceparent=metadata.get("traceparent"), kind="SERVER",
                set_current=False,
            )
            ctx = Context(_GRPCRequestAdapter(request, metadata), container, span=span)
            token = _grpc_ctx.set(ctx)
            start = time.perf_counter()
            status = 0
            try:
                return inner(request, servicer_context)
            except Exception as e:  # noqa: BLE001 - panic recovery → INTERNAL
                status = 13  # grpc INTERNAL
                span.set_status("ERROR")
                container.logger.log_exception(e, f"grpc handler {method}")
                servicer_context.abort(grpc.StatusCode.INTERNAL, "internal error")
            finally:
                _grpc_ctx.reset(token)
                span.finish()
                container.logger.info(
                    RPCLog(method, status, int((time.perf_counter() - start) * 1e6), span.trace_id)
                )

        return grpc.unary_unary_rpc_method_handler(
            wrapped,
            request_deserializer=handler.request_deserializer,
            response_serializer=handler.response_serializer,
        )


class _GRPCRequestAdapter:
    """Request-interface adapter over a protobuf message."""

    def __init__(self, message, metadata: dict[str, str]):
        self.message = message
        self.metadata = metadata
        self._ctx: dict[str, Any] = {}

    def param(self, key: str) -> str:
        return str(self.metadata.get(key, ""))

    def params(self, key: str) -> list[str]:
        v = self.param(key)
        return [v] if v else []

    def path_param(self, key: str) -> str:
        return ""

    def bind(self, target: Any = None) -> Any:
        return self.message

    def host_name(self) -> str:
        return "grpc"

    def context(self) -> dict[str, Any]:
        return self._ctx


def start_grpc_server(app) -> grpc.Server:
    server = grpc.server(
        ThreadPoolExecutor(max_workers=app.config.get_int("GRPC_THREADS", 16),
                           thread_name_prefix="gofr-grpc"),
        interceptors=[GofrGrpcInterceptor(app.container)],
    )
    for adder, servicer in app._grpc_services:
        if servicer is not None:
            adder(servicer, server)
        elif callable(adder):
            adder(server)
    server.add_insecure_port(f"[::]:{app.grpc_port}")
    server.start()
    return server
