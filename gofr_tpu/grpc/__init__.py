"""gRPC entrypoint (gofr `pkg/gofr/grpc.go` + `pkg/gofr/grpc/log.go`).

Unlike the reference — where gRPC handlers bypass the framework Context
(SURVEY.md §3.3 notes the asymmetry) — servicers registered here can access the
full Context: the logging interceptor opens a span and exposes
``current_grpc_context()`` carrying the container, so gRPC methods get the same
datasource/tracing/inference surface as HTTP handlers.
"""

from gofr_tpu.grpc.server import GofrGrpcInterceptor, current_grpc_context, start_grpc_server

__all__ = ["start_grpc_server", "GofrGrpcInterceptor", "current_grpc_context"]
