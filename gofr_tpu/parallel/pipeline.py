"""SPMD pipeline parallelism over a ``pp`` mesh axis.

GPipe-style schedule expressed as ONE program on every device (no
per-stage programs, no host orchestration — the TPU way): stage s owns a
contiguous slab of layers (the stacked [L, ...] block params shard over
``pp`` on their leading dim), microbatches march through the ring via
``ppermute``, and a ``lax.scan`` over M + P - 1 ticks runs the whole
schedule inside one jit. Bubble ticks compute on garbage and are discarded
— uniform work keeps the program static (same discipline as the serving
engine's inactive slots, gofr_tpu/tpu/engine.py).

The reference has no model execution at all (SURVEY.md §2.9); this is the
pp entry in the dp/fsdp/tp/sp/ep/pp axis set (parallel.mesh.AXES).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


def spmd_pipeline(
    stage_fn: Callable[[Any, Any], Any],
    stage_params: Any,
    inputs: Any,
    *,
    axis: str = "pp",
    microbatches: int,
):
    """Run inside ``shard_map``: pipeline ``inputs`` (pytree, leading dim =
    ``microbatches``) through P ring stages.

    ``stage_fn(stage_params, act) -> act`` must preserve the activation
    pytree's structure and shapes (pass-through leaves like per-microbatch
    lengths just return unchanged). Returns outputs shaped like ``inputs``,
    replicated over the axis (psum-broadcast from the last stage).

    Stateless: bubble ticks compute on zeros and their outputs are
    discarded by the schedule, so no dropped-write convention is needed —
    the one-line delegation to the stateful variant keeps the tick
    schedule (feed/out index clipping, drain re-feed, psum broadcast) in
    exactly one place."""
    outs, _ = spmd_pipeline_stateful(
        lambda params, st, act: (st, stage_fn(params, act)),
        stage_params, None, inputs,
        axis=axis, microbatches=microbatches,
        init_act=jax.tree.map(lambda x: jnp.zeros_like(x[0]), inputs),
    )
    return outs


def spmd_pipeline_stateful(
    stage_fn: Callable[[Any, Any, Any], tuple[Any, Any]],
    stage_params: Any,
    state: Any,
    inputs: Any,
    *,
    axis: str = "pp",
    microbatches: int,
    init_act: Any,
):
    """``spmd_pipeline`` with per-stage local STATE threaded through every
    tick — the serving shape, where each stage owns the KV-cache layers of
    its slab and updates them as microbatches of slots stream past.

    ``stage_fn(stage_params, state, act) -> (state, act)``. Bubble ticks
    still run stage_fn, on ``init_act``-shaped garbage — which is why
    ``init_act`` is REQUIRED: the caller must bake out-of-bounds positions /
    slot ids into it so bubble-tick state writes are dropped (the engine's
    padding-row convention, engine._admit docstring); a zeros default would
    write bubble garbage into real index-0 state. Stage 0 re-feeds the last
    microbatch during drain ticks; its state writes recompute identical
    values, so they are harmless by construction. Returns ``(outs, state)``
    with outs replicated over the axis."""
    p = lax.axis_size(axis)
    stage = lax.axis_index(axis)
    m = microbatches
    perm = [(i, (i + 1) % p) for i in range(p)]

    act0 = init_act
    outs0 = jax.tree.map(jnp.zeros_like, inputs)

    def tick(carry, t):
        outs, act, st = carry
        feed_idx = jnp.minimum(t, m - 1)
        feed = jax.tree.map(
            lambda x: lax.dynamic_index_in_dim(x, feed_idx, 0, keepdims=False), inputs)
        cur = jax.tree.map(lambda f, a: jnp.where(stage == 0, f, a), feed, act)
        st, out = stage_fn(stage_params, st, cur)
        out_idx = jnp.clip(t - (p - 1), 0, m - 1)
        write = jnp.logical_and(stage == p - 1, t >= p - 1)
        outs = jax.tree.map(
            lambda o_all, o: jnp.where(
                write, lax.dynamic_update_index_in_dim(o_all, o, out_idx, 0), o_all
            ),
            outs, out,
        )
        act = jax.tree.map(lambda o: lax.ppermute(o, axis, perm), out)
        return (outs, act, st), None

    (outs, _, state), _ = lax.scan(tick, (outs0, act0, state), jnp.arange(m + p - 1))
    outs = jax.tree.map(
        lambda o: lax.psum(jnp.where(stage == p - 1, o, jnp.zeros_like(o)), axis), outs
    )
    return outs, state


def make_pipeline_forward(
    mesh: Mesh,
    *,
    microbatches: int = 4,
    axis: str = "pp",
    batch_axes=("dp", "fsdp"),
    param_specs: Any | None = None,
):
    """Bind a mesh to a pipelined model forward.

    Returns ``pp_forward(stage_fn, block_params, x, lengths)`` where
    ``block_params`` leaves have a leading layers dim (sharded over ``axis``)
    and ``stage_fn(local_blocks, x, lengths) -> x`` runs one stage's layers.
    The global batch B is cut into ``microbatches``; B % (microbatches *
    dp-shards) must be 0.

    ``param_specs`` (pytree of PartitionSpec matching ``block_params``)
    keeps other axes of the stage weights sharded inside the region — e.g.
    P('pp', None, 'tp') for a [L, E, H*D] projection — so pp composes with
    tp instead of all-gathering the stage weights; the stage_fn is then
    responsible for the matching manual psums.
    """
    if axis not in mesh.axis_names:
        raise ValueError(f"mesh has no {axis!r} axis: {mesh.axis_names}")
    batch = tuple(a for a in batch_axes if a in mesh.axis_names)
    bspec = batch if batch else None

    def pp_forward(stage_fn, block_params, x, lengths):
        b, s, e = x.shape
        if b % microbatches:
            raise ValueError(f"batch {b} not divisible by {microbatches} microbatches")
        mb = b // microbatches
        xm = x.reshape(microbatches, mb, s, e)
        lm = lengths.reshape(microbatches, mb)
        specs = param_specs if param_specs is not None else jax.tree.map(
            lambda _: P(axis), block_params
        )

        @partial(
            jax.shard_map,
            mesh=mesh,
            in_specs=(specs, P(None, bspec), P(None, bspec)),
            out_specs=(P(None, bspec), P(None, bspec)),
            check_vma=False,
        )
        def run(blocks_local, xm, lm):
            def fn(params, act):
                xa, la = act
                return stage_fn(params, xa, la), la

            return spmd_pipeline(fn, blocks_local, (xm, lm), axis=axis, microbatches=microbatches)

        ym, _ = run(block_params, xm, lm)
        return ym.reshape(b, s, e)

    return pp_forward
