"""Parallelism layer: device mesh, sharding rules, collectives.

This is the "distributed communication backend" of the framework — the
TPU-native equivalent of the reference's transport stack (SURVEY.md §2.9,
§5.8). Where GoFr selects a pub/sub backend by config
(`pkg/gofr/container/container.go:95-122`), we select a mesh topology by
config (``TPU_MESH=dp:2,tp:4``) and let XLA insert ICI/DCN collectives from
sharding annotations (GSPMD), instead of hand-written NCCL/MPI calls.

Axis vocabulary (fixed across the framework):

- ``dp``   data parallel (replica groups; DCN-friendly outermost axis)
- ``fsdp`` fully-sharded data parallel (weights sharded over the data axis)
- ``pp``   pipeline stages
- ``tp``   tensor parallel (ICI; heads / mlp sharding)
- ``sp``   sequence / context parallel (ring attention)
- ``ep``   expert parallel (MoE)
"""

from gofr_tpu.parallel.mesh import (
    AXES,
    MeshSpec,
    build_mesh,
    local_mesh,
    mesh_from_config,
)
from gofr_tpu.parallel.sharding import (
    ShardingRules,
    logical_sharding,
    logical_spec,
    shard_pytree,
    with_sharding_constraint,
)
from gofr_tpu.parallel import collectives

__all__ = [
    "AXES",
    "MeshSpec",
    "build_mesh",
    "local_mesh",
    "mesh_from_config",
    "ShardingRules",
    "logical_sharding",
    "logical_spec",
    "shard_pytree",
    "with_sharding_constraint",
    "collectives",
]
