"""Logical-axis sharding rules.

Models annotate every parameter/activation with *logical* axis names
(``("embed", "mlp")``); ShardingRules map logical names to mesh axes. This
decouples model code from topology: the same Llama forward runs 1-chip
(all rules → None), TP-8 (heads/mlp → "tp"), or FSDP+TP, purely by swapping
rules — the framework's analog of GoFr wiring datasources by config rather
than code (`container/container.go:66-124`).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# A logical axis annotation is a tuple of logical names (or None for
# unsharded), one entry per array dimension.
LogicalAxes = tuple[str | None, ...]


@dataclass(frozen=True)
class ShardingRules:
    """Map logical axis name → mesh axis name(s) (or None = replicate).

    The default rules implement the standard serving/training layout:
    batch over (dp, fsdp); attention heads and mlp hidden over tp; sequence
    over sp (ring attention); experts over ep; layers over pp.
    """

    rules: tuple[tuple[str, Any], ...] = (
        ("batch", ("dp", "fsdp")),
        ("seq", "sp"),
        ("heads", "tp"),
        ("kv_heads", "tp"),
        ("embed", None),
        ("mlp", "tp"),
        ("vocab", "tp"),
        ("expert", "ep"),
        ("layers", None),
        ("stage", "pp"),
    )

    def lookup(self, logical: str | None, mesh_axes: tuple[str, ...]):
        if logical is None:
            return None
        mapping = dict(self.rules)
        if logical not in mapping:
            raise KeyError(f"no sharding rule for logical axis {logical!r}")
        target = mapping[logical]
        if target is None:
            return None
        if isinstance(target, str):
            return target if target in mesh_axes else None
        # tuple of mesh axes: keep only those present in the mesh
        present = tuple(t for t in target if t in mesh_axes)
        if not present:
            return None
        return present if len(present) > 1 else present[0]

    def spec(self, logical_axes: LogicalAxes, mesh: Mesh) -> P:
        return P(*(self.lookup(name, mesh.axis_names) for name in logical_axes))

    def with_overrides(self, **overrides: Any) -> "ShardingRules":
        mapping = dict(self.rules)
        mapping.update(overrides)
        return ShardingRules(rules=tuple(mapping.items()))


def fsdp_rules() -> ShardingRules:
    """Rules for FSDP training: shard the embed dimension of weights over
    the fsdp axis so parameters are fully sharded across data replicas."""
    return ShardingRules().with_overrides(embed="fsdp")


def logical_spec(rules: ShardingRules, logical_axes: LogicalAxes, mesh: Mesh) -> P:
    return rules.spec(logical_axes, mesh)


def logical_sharding(rules: ShardingRules, logical_axes: LogicalAxes, mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, rules.spec(logical_axes, mesh))


def shard_pytree(tree: Any, axes_tree: Any, rules: ShardingRules, mesh: Mesh) -> Any:
    """Device-put every leaf of ``tree`` with the sharding derived from the
    matching leaf of ``axes_tree`` (a pytree of LogicalAxes tuples)."""

    def _put(leaf, axes):
        return jax.device_put(leaf, logical_sharding(rules, axes, mesh))

    return jax.tree.map(_put, tree, axes_tree, is_leaf=lambda x: x is None)


def sharding_tree(axes_tree: Any, rules: ShardingRules, mesh: Mesh) -> Any:
    """Pytree of NamedShardings matching ``axes_tree`` — feed to jit
    in_shardings/out_shardings."""
    return jax.tree.map(
        lambda axes: logical_sharding(rules, axes, mesh),
        axes_tree,
        is_leaf=lambda x: isinstance(x, tuple) and all(isinstance(e, (str, type(None))) for e in x),
    )


def with_sharding_constraint(x: Any, logical_axes: LogicalAxes, rules: ShardingRules, mesh: Mesh) -> Any:
    """Constrain an intermediate activation inside jit (GSPMD hint). Outside
    a mesh/jit context this is the identity."""
    try:
        return jax.lax.with_sharding_constraint(x, logical_sharding(rules, logical_axes, mesh))
    except (ValueError, RuntimeError):
        return x
