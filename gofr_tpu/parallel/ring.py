"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

Long-context first-class support (SURVEY.md §5.7 — absent in the reference,
sourced from PAPERS.md): shard the SEQUENCE dimension of activations over a
mesh axis (``sp``) so context length scales with chips.

Two strategies, both running inside ``shard_map`` so the collectives are
explicit and ride ICI:

- **Ring attention**: queries stay put; K/V chunks rotate around the ``sp``
  ring via ``ppermute`` while each device folds every visiting chunk into a
  blockwise online-softmax accumulator (same recurrence as the Pallas flash
  kernel, one ring hop = one kv block). Memory per device stays O(S/n);
  comm overlaps with the next block's compute in XLA's scheduler.
- **Ulysses**: ``all_to_all`` swaps the shard axis from sequence to heads
  ([B, S/n, H, D] → [B, S, H/n, D]), runs ordinary dense attention locally
  (which on TPU dispatches to the Pallas flash kernel), and swaps back.
  Cheaper comm at moderate S; requires heads % sp == 0.

``make_seq_parallel_attn`` binds either strategy to a mesh as a drop-in
``attn_fn`` for the model forwards (gofr_tpu.models.llama.forward).
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -1e30


def _block_scores(q, k, scale, q_off, kv_off, causal, kv_lengths, chunk_kv):
    """Masked f32 scores for one (local q, visiting kv) block.

    q [B, Cq, Hkv, G, D] grouped; k [B, Ckv, Hkv, D] → s [B, Hkv, G, Cq, Ckv].
    Positions are global: q_off/kv_off are the chunks' global start offsets.
    """
    s = jnp.einsum("bskgd,btkd->bkgst", q, k).astype(jnp.float32) * scale
    kv_pos = kv_off + jnp.arange(chunk_kv)  # [Ckv]
    mask = None
    if causal:
        q_pos = q_off + jnp.arange(q.shape[1])  # [Cq]
        mask = q_pos[:, None] >= kv_pos[None, :]  # [Cq, Ckv]
        mask = mask[None, None, None]
    if kv_lengths is not None:
        lmask = kv_pos[None, :] < kv_lengths[:, None]  # [B, Ckv]
        lmask = lmask[:, None, None, None]
        mask = lmask if mask is None else (mask & lmask)
    if mask is not None:
        s = jnp.where(mask, s, NEG_INF)
    return s


def _online_update(carry, s, v):
    """Fold one block's scores/values into the (m, l, acc) accumulator.
    s [B, K, G, Cq, Ckv] f32; v [B, Ckv, K, D]; acc [B, K, G, Cq, D] f32."""
    m, l, acc = carry
    m_next = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    m_safe = jnp.where(m_next > NEG_INF / 2, m_next, 0.0)
    p = jnp.exp(s - m_safe)
    alpha = jnp.exp(m - m_safe)
    l_next = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
    pv = jnp.einsum("bkgst,btkd->bkgsd", p.astype(v.dtype), v).astype(jnp.float32)
    acc_next = acc * alpha + pv
    return m_next, l_next, acc_next


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    axis: str = "sp",
    causal: bool = True,
    kv_lengths: jnp.ndarray | None = None,
    scale: float | None = None,
) -> jnp.ndarray:
    """Ring attention over sequence chunks. MUST run inside ``shard_map``
    with the sequence dim of q/k/v sharded over ``axis``.

    q [B, C, Hq, D], k/v [B, C, Hkv, D] local chunks of a global sequence
    S = C * axis_size; ``kv_lengths`` [B] are GLOBAL lengths. Chunk i holds
    global positions [i*C, (i+1)*C).
    """
    n = lax.axis_size(axis)
    idx = lax.axis_index(axis)
    b, c, hq, d = q.shape
    hkv = k.shape[2]
    g = hq // hkv
    scale = scale if scale is not None else 1.0 / (d**0.5)
    qg = q.reshape(b, c, hkv, g, d)  # grouped [B, Cq, K, G, D]

    q_off = idx * c
    m = jnp.full((b, hkv, g, c, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((b, hkv, g, c, 1), jnp.float32)
    acc = jnp.zeros((b, hkv, g, c, d), jnp.float32)

    def step(carry, i):
        k_cur, v_cur, m, l, acc = carry
        # after i forward rotations we hold the chunk of device (idx - i) % n
        kv_off = ((idx - i) % n) * c
        s = _block_scores(qg, k_cur, scale, q_off, kv_off, causal, kv_lengths, c)
        m, l, acc = _online_update((m, l, acc), s, v_cur)
        perm = [(j, (j + 1) % n) for j in range(n)]
        k_nxt = lax.ppermute(k_cur, axis, perm)
        v_nxt = lax.ppermute(v_cur, axis, perm)
        return (k_nxt, v_nxt, m, l, acc), None

    (k, v, m, l, acc), _ = lax.scan(step, (k, v, m, l, acc), jnp.arange(n))
    out = acc / jnp.maximum(l, 1e-20)  # [B, K, G, Cq, D]
    return out.transpose(0, 3, 1, 2, 4).reshape(b, c, hq, d).astype(q.dtype)


def ulysses_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    axis: str = "sp",
    causal: bool = True,
    kv_lengths: jnp.ndarray | None = None,
    scale: float | None = None,
    inner: Callable | None = None,
) -> jnp.ndarray:
    """Ulysses sequence parallelism. MUST run inside ``shard_map`` with the
    sequence dim sharded over ``axis``; requires Hq and Hkv divisible by the
    axis size. ``inner`` is the dense attention to run after the swap
    (default: gofr_tpu.ops.mha_attention, i.e. Pallas flash on TPU)."""
    from gofr_tpu.ops.attention import mha_attention

    inner = inner or mha_attention
    n = lax.axis_size(axis)
    hq, hkv = q.shape[2], k.shape[2]
    if hq % n != 0:
        raise ValueError(f"ulysses needs query heads ({hq}) divisible by sp axis size ({n})")
    if hkv % n != 0:
        # GQA with fewer kv heads than the axis: expand kv to the query-head
        # count so both scatter identically (head blocks stay aligned).
        k = jnp.repeat(k, hq // hkv, axis=2)
        v = jnp.repeat(v, hq // hkv, axis=2)
    # [B, C, H, D] → gather seq, scatter heads → [B, S, H/n, D]
    qh = lax.all_to_all(q, axis, split_axis=2, concat_axis=1, tiled=True)
    kh = lax.all_to_all(k, axis, split_axis=2, concat_axis=1, tiled=True)
    vh = lax.all_to_all(v, axis, split_axis=2, concat_axis=1, tiled=True)
    out = inner(qh, kh, vh, causal=causal, kv_lengths=kv_lengths, scale=scale)
    # back: gather heads, scatter seq → [B, C, H, D]
    return lax.all_to_all(out, axis, split_axis=1, concat_axis=2, tiled=True)


def make_seq_parallel_attn(
    mesh: Mesh,
    *,
    strategy: str = "ring",
    axis: str = "sp",
    batch_axes=("dp", "fsdp"),
    head_axis: str = "tp",
):
    """Bind ring/ulysses attention to ``mesh`` as a drop-in ``attn_fn`` for
    model forwards: takes GLOBAL [B, S, H, D] activations (GSPMD-sharded),
    runs the strategy under ``shard_map`` with seq sharded over ``axis`` and
    heads over ``head_axis``, returns global output.
    """
    if strategy not in ("ring", "ulysses"):
        raise ValueError(f"unknown sequence-parallel strategy {strategy!r}")
    batch = tuple(a for a in batch_axes if a in mesh.axis_names)
    bspec = batch if batch else None
    head = head_axis if head_axis in mesh.axis_names else None
    qkv_spec = P(bspec, axis, head, None)
    len_spec = P(bspec)

    fn = ring_attention if strategy == "ring" else ulysses_attention

    def attn_fn(q, k, v, *, causal=True, kv_lengths=None, scale=None, **_):
        if kv_lengths is None:
            kv_lengths = jnp.full((q.shape[0],), q.shape[1], jnp.int32)

        @partial(
            jax.shard_map,
            mesh=mesh,
            in_specs=(qkv_spec, qkv_spec, qkv_spec, len_spec),
            out_specs=qkv_spec,
            check_vma=False,
        )
        def run(ql, kl, vl, lens):
            return fn(ql, kl, vl, axis=axis, causal=causal, kv_lengths=lens, scale=scale)

        return run(q, k, v, kv_lengths)

    return attn_fn
