"""Device mesh construction.

The mesh is the framework's unit of distribution: every sharded computation
(serving engine, train step, collectives) runs inside one
``jax.sharding.Mesh``. Topology comes from config, keeping GoFr's
"backend selected by config" ergonomics (`container/container.go:95-122`):

    TPU_MESH=dp:2,tp:4        # explicit
    TPU_MESH=tp:-1            # -1 = fill with remaining devices
    (unset)                   # all devices on the ``dp`` axis

Axis order in the spec is physical-layout order: later axes are placed on
adjacent devices (innermost), so put the bandwidth-hungry axes (``tp``,
``sp``) last to keep their collectives on ICI and ``dp``/``pp`` first so
replica traffic can cross DCN.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import numpy as np
from jax.sharding import Mesh

# canonical axis names, in recommended outer→inner physical order
AXES = ("dp", "fsdp", "pp", "ep", "sp", "tp")


@dataclass(frozen=True)
class MeshSpec:
    """An ordered mapping of mesh axis name → size. Size ``-1`` means "fill
    with whatever devices remain" (at most one axis may be -1)."""

    axes: tuple[tuple[str, int], ...] = (("dp", -1),)

    @classmethod
    def parse(cls, text: str) -> "MeshSpec":
        """Parse ``"dp:2,tp:4"`` / ``"tp=4"`` / ``"tp:-1"``."""
        pairs: list[tuple[str, int]] = []
        for part in text.replace("=", ":").split(","):
            part = part.strip()
            if not part:
                continue
            name, _, size_s = part.partition(":")
            name = name.strip()
            if name not in AXES:
                raise ValueError(f"unknown mesh axis {name!r}; valid: {AXES}")
            try:
                size = int(size_s)
            except ValueError:
                raise ValueError(f"bad mesh axis size in {part!r}") from None
            pairs.append((name, size))
        if not pairs:
            raise ValueError(f"empty mesh spec {text!r}")
        names = [n for n, _ in pairs]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate axis in mesh spec {text!r}")
        if sum(1 for _, s in pairs if s == -1) > 1:
            raise ValueError(f"at most one -1 axis allowed: {text!r}")
        return cls(axes=tuple(pairs))

    def resolve(self, n_devices: int) -> tuple[tuple[str, int], ...]:
        """Fill the -1 axis (if any) and validate the product divides into
        ``n_devices`` exactly."""
        fixed = math.prod(s for _, s in self.axes if s != -1)
        if fixed <= 0:
            raise ValueError(f"mesh axis sizes must be positive: {self.axes}")
        resolved = []
        for name, size in self.axes:
            if size == -1:
                if n_devices % fixed != 0:
                    raise ValueError(
                        f"cannot fill axis {name!r}: {n_devices} devices not divisible by {fixed}"
                    )
                size = n_devices // fixed
            resolved.append((name, size))
        total = math.prod(s for _, s in resolved)
        if total != n_devices:
            raise ValueError(
                f"mesh {dict(resolved)} needs {total} devices, have {n_devices}"
            )
        return tuple(resolved)

    @property
    def names(self) -> tuple[str, ...]:
        return tuple(n for n, _ in self.axes)


def build_mesh(spec: MeshSpec | str | None = None, devices=None) -> Mesh:
    """Build a ``jax.sharding.Mesh`` from a spec over ``devices`` (default:
    all visible devices)."""
    if devices is None:
        devices = jax.devices()
    if spec is None:
        spec = MeshSpec()
    elif isinstance(spec, str):
        spec = MeshSpec.parse(spec)
    resolved = spec.resolve(len(devices))
    shape = tuple(s for _, s in resolved)
    names = tuple(n for n, _ in resolved)
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, axis_names=names)


def mesh_from_config(config, devices=None) -> Mesh:
    """Mesh from the ``TPU_MESH`` config key (default: all devices on dp)."""
    text = config.get("TPU_MESH") if hasattr(config, "get") else None
    return build_mesh(MeshSpec.parse(text) if text else None, devices=devices)


def local_mesh(n: int | None = None, axis: str = "dp") -> Mesh:
    """A trivial mesh over the first ``n`` local devices on one axis —
    convenience for single-axis tests and single-chip serving."""
    devices = jax.devices()[: n or len(jax.devices())]
    return Mesh(np.asarray(devices), axis_names=(axis,))
