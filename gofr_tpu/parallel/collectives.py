"""Collective communication wrappers (the east-west layer the reference
lacks — SURVEY.md §5.8).

All collectives are XLA primitives (`jax.lax.psum` etc.) that GSPMD lowers
onto ICI within a slice and DCN across slices; use them inside
``jax.shard_map`` / pjit over a mesh. The helpers here add the framework's
axis vocabulary and the ring-permutation used by ring attention.
"""

from __future__ import annotations

from typing import Any

import jax
from jax import lax
from jax.sharding import Mesh


def psum(x: Any, axis: str):
    return lax.psum(x, axis_name=axis)


def pmean(x: Any, axis: str):
    return lax.pmean(x, axis_name=axis)

def pmax(x: Any, axis: str):
    return lax.pmax(x, axis_name=axis)


def all_gather(x: Any, axis: str, *, tiled: bool = True, gather_dim: int = 0):
    return lax.all_gather(x, axis_name=axis, axis=gather_dim, tiled=tiled)


def reduce_scatter(x: Any, axis: str, *, scatter_dim: int = 0):
    return lax.psum_scatter(x, axis_name=axis, scatter_dimension=scatter_dim, tiled=True)


def all_to_all(x: Any, axis: str, *, split_dim: int, concat_dim: int):
    return lax.all_to_all(x, axis_name=axis, split_axis=split_dim, concat_axis=concat_dim, tiled=True)


def axis_index(axis: str):
    return lax.axis_index(axis_name=axis)


def axis_size(axis: str):
    return lax.axis_size(axis_name=axis)


def ring_permute(x: Any, axis: str, *, shift: int = 1):
    """Send this shard to the next device on ``axis`` (wrap-around ring) and
    receive from the previous one. The building block of ring attention:
    on TPU the ring maps directly onto ICI neighbor links."""
    n = lax.axis_size(axis_name=axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name=axis, perm=perm)


def shard_map_over(mesh: Mesh, in_specs: Any, out_specs: Any, *, check_vma: bool = False):
    """Decorator: run a per-shard function under ``jax.shard_map`` on
    ``mesh``. Thin sugar so call sites read like the reference's
    "register handler on transport" style."""

    def wrap(fn):
        return jax.shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=check_vma)

    return wrap
