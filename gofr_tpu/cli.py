"""CLI runtime: subcommand apps sharing the same Context/handler model.

Parity with gofr `pkg/gofr/cmd.go` + `pkg/gofr/cmd/`: ``new_cmd()`` apps route
on the first non-flag argument (regex match supported, `cmd.go:92-107`), flags
``-k=v`` / ``--k=v`` / ``-k v`` become params (`cmd/request.go:25-67`),
``bind`` maps flags into dataclasses (`cmd/request.go:90-117`), ``-h/--help``
output is generated from registered descriptions (`cmd.go:137-151`), and
results/errors print to stdout/stderr (`cmd/responder.go`).
"""

from __future__ import annotations

import re
import sys
from typing import Any, Callable

from gofr_tpu.utils import bind as binder


class CmdRequest:
    """Request implementation over argv."""

    def __init__(self, argv: list[str]):
        self.argv = argv
        self.subcommand = ""
        self._params: dict[str, list[str]] = {}
        self._positional: list[str] = []
        self._parse(argv)
        self._ctx: dict[str, Any] = {}

    def _parse(self, argv: list[str]) -> None:
        i = 0
        while i < len(argv):
            arg = argv[i]
            if arg.startswith("-"):
                name = arg.lstrip("-")
                if "=" in name:
                    key, _, value = name.partition("=")
                    self._params.setdefault(key, []).append(value)
                elif i + 1 < len(argv) and not argv[i + 1].startswith("-"):
                    self._params.setdefault(name, []).append(argv[i + 1])
                    i += 1
                else:
                    self._params.setdefault(name, []).append("true")
            elif not self.subcommand:
                self.subcommand = arg
            else:
                self._positional.append(arg)
            i += 1

    def param(self, key: str) -> str:
        values = self._params.get(key)
        return values[0] if values else ""

    def params(self, key: str) -> list[str]:
        return list(self._params.get(key, []))

    def path_param(self, key: str) -> str:
        if key == "subcommand":
            return self.subcommand
        try:
            return self._positional[int(key)]
        except (ValueError, IndexError):
            return ""

    @property
    def positional(self) -> list[str]:
        return list(self._positional)

    def bind(self, target: Any = dict) -> Any:
        flat = {k: v[0] if len(v) == 1 else v for k, v in self._params.items()}
        return binder.bind(flat, target)

    def host_name(self) -> str:
        return "cli"

    def context(self) -> dict[str, Any]:
        return self._ctx


class CmdResponder:
    def __init__(self, out=None, err=None):
        self._out = out or sys.stdout
        self._err = err or sys.stderr

    def write(self, *args: Any) -> None:
        self._out.write(" ".join(str(a) for a in args) + "\n")

    def respond(self, result: Any, err: BaseException | None) -> int:
        if err is not None:
            self._err.write(f"error: {err}\n")
            return 1
        if result is not None:
            self._out.write(f"{result}\n")
        return 0


class Route:
    def __init__(self, pattern: str, handler: Callable, description: str = "", help_text: str = ""):
        self.pattern = pattern
        self.handler = handler
        self.description = description
        self.help_text = help_text

    def matches(self, subcommand: str) -> bool:
        return re.fullmatch(self.pattern, subcommand) is not None


class CmdApp:
    """The CLI entrypoint runtime; created via ``gofr_tpu.new_cmd()``."""

    def __init__(self, container):
        self.container = container
        self._routes: list[Route] = []

    def sub_command(self, pattern: str, handler: Callable, description: str = "", help_text: str = "") -> None:
        self._routes.append(Route(pattern, handler, description, help_text))

    def run(self, argv: list[str] | None = None, out=None, err=None) -> int:
        from gofr_tpu.context import Context

        argv = list(sys.argv[1:] if argv is None else argv)
        responder = CmdResponder(out, err)
        request = CmdRequest(argv)

        if request.subcommand in ("", "help") or request.param("h") or request.param("help"):
            responder.write(self._help())
            return 0

        route = next((r for r in self._routes if r.matches(request.subcommand)), None)
        if route is None:
            responder._err.write(f"unknown subcommand {request.subcommand!r}\n\n{self._help()}\n")
            return 1

        span = self.container.tracer.start_span(f"cmd {request.subcommand}", set_current=False)
        ctx = Context(request, self.container, responder=responder, span=span)
        try:
            result = route.handler(ctx)
            span.finish()
            return responder.respond(result, None)
        except Exception as e:  # noqa: BLE001
            span.set_status("ERROR")
            span.finish()
            return responder.respond(None, e)

    def _help(self) -> str:
        lines = ["Available commands:"]
        for r in self._routes:
            desc = f"  {r.pattern:<20} {r.description}".rstrip()
            lines.append(desc)
            if r.help_text:
                lines.append(f"{'':<24}{r.help_text}")
        return "\n".join(lines)
