"""gofr_tpu: a TPU-native application framework with GoFr's capabilities.

GoFr's shape — one App, one Container, one transport-neutral Context,
handlers as plain functions, everything config-gated — with a JAX/XLA
serving runtime underneath: models served behind continuous-batching
engines on a sharded device mesh, reachable from any handler via
``ctx.infer`` / ``ctx.generate``.

    import gofr_tpu
    from gofr_tpu.models import ModelSpec, LlamaConfig

    app = gofr_tpu.new()
    app.serve_model("lm", ModelSpec("llama", LlamaConfig.llama3_8b(),
                                    weights="/ckpt/llama3-8b", task="generate"))

    def generate(ctx):
        return ctx.generate("lm", ctx.bind()["prompt"], max_new_tokens=128)

    app.post("/generate", generate)
    app.run()
"""

from gofr_tpu.app import App, new, new_cmd, new_testing
from gofr_tpu.context import Context
from gofr_tpu.models.base import ModelSpec
from gofr_tpu import version

__version__ = version.FRAMEWORK
__all__ = ["App", "Context", "ModelSpec", "new", "new_cmd", "new_testing"]
