"""Container: the dependency-injection hub (gofr `pkg/gofr/container/container.go`).

One Container per App. It materializes every infrastructure dependency from
config at boot — logger (with remote level polling), metrics registry, tracer,
datasources, inter-service HTTP clients — and exposes them through narrow
attributes. Everything is config-gated: an unset host/backend means the feature
is simply not wired (`container.go:91-122` semantics).

TPU-first: the device mesh is itself a datasource (``container.tpu``), exactly
parallel to how the reference wraps a Redis pool — created lazily, health-checked,
surfaced in metrics.
"""

from __future__ import annotations

import threading
from typing import Any

from gofr_tpu.config import DictConfig
from gofr_tpu.logging import Level, Logger, MockLogger, new_logger
from gofr_tpu.metrics import Registry, sample_runtime_metrics
from gofr_tpu.metrics.flight import FlightRecorder
from gofr_tpu.tracing import Tracer, tracer_from_config
from gofr_tpu import version


class Container:
    def __init__(self, config, logger: Logger | None = None):
        self.config = config
        self.app_name = config.get_or_default("APP_NAME", "gofr-tpu-app")
        self.app_version = config.get_or_default("APP_VERSION", "dev")

        self.logger: Logger = logger or new_logger(config.get_or_default("LOG_LEVEL", "INFO"))
        self.metrics: Registry = Registry(logger=self.logger)
        self.tracer: Tracer = Tracer()
        # always-on ring of recent request timelines + engine steps
        # (docs/observability.md; served at /debug/requests, /debug/engine)
        self.flight = FlightRecorder(
            max_requests=config.get_int("FLIGHT_REQUESTS", 256),
            max_steps=config.get_int("FLIGHT_STEPS", 512),
        )

        # datasource slots (None = not wired; config decides)
        self.sql = None
        self.redis = None
        self.mongo = None
        self.cassandra = None
        self.clickhouse = None
        self.kv = None
        self.file = None
        self.pubsub = None
        self._tpu = None
        self._tpu_lock = threading.Lock()
        self.services: dict[str, Any] = {}
        self._engines: dict[str, Any] = {}
        self.qos = None  # AdmissionController once App.enable_qos runs
        self.slo = None  # SLOEngine once _maybe_slo runs (SLO_ENABLED)
        self.slo_capture = None  # CaptureWatcher once SLO_CAPTURE opts in
        self._remote_level_poller = None
        self._pubsub_hdr_support: tuple[Any, bool] | None = None  # per-broker probe cache

    # -- boot ------------------------------------------------------------------

    @classmethod
    def create(cls, config) -> "Container":
        c = cls(config)
        c._register_framework_metrics()
        c.metrics.add_collect_hook(sample_runtime_metrics)
        c.metrics.add_collect_hook(c._sample_tpu_metrics)
        c.tracer = tracer_from_config(config, c.logger, c.app_name)
        c._maybe_remote_log_level()
        c._maybe_slo()
        c._maybe_sql()
        c._maybe_redis()
        c._maybe_pubsub()
        c._wire_file()
        c._maybe_kv()
        return c

    def _register_framework_metrics(self) -> None:
        m = self.metrics
        g = m.new_gauge("app_info", "application info")
        g.set(1, app=self.app_name, version=self.app_version, framework=f"gofr_tpu-{version.FRAMEWORK}")
        m.new_histogram("app_http_response", "HTTP handler latency (s)")
        m.new_histogram("app_http_service_response", "outbound HTTP client latency (s)")
        m.new_histogram("app_sql_stats", "SQL query latency (s)")
        m.new_histogram("app_redis_stats", "redis command latency (s)")
        m.new_histogram("app_kv_stats", "kv store op latency (s)")
        m.new_counter("app_pubsub_publish_total_count", "pubsub publish attempts")
        m.new_counter("app_pubsub_publish_success_count", "pubsub publish successes")
        m.new_counter("app_pubsub_subscribe_total_count", "pubsub messages received")
        m.new_counter("app_pubsub_subscribe_success_count", "pubsub messages handled ok")
        # TPU serving metrics (north-star observability: HBM + compile cache + batching)
        m.new_gauge("app_tpu_device_count", "visible TPU devices")
        m.new_gauge("app_tpu_hbm_used_bytes", "per-device HBM in use")
        m.new_gauge("app_tpu_hbm_limit_bytes", "per-device HBM capacity")
        m.new_counter("app_tpu_compile_total", "XLA compilations triggered")
        m.new_counter("app_tpu_compile_cache_hits", "batch steps served from compile cache")
        m.new_histogram("app_tpu_batch_occupancy", "occupied fraction of each device batch",
                        buckets=[0.1, 0.25, 0.5, 0.75, 0.9, 1.0])
        m.new_histogram("app_tpu_step_seconds", "device step wall time (s)")
        m.new_gauge("app_tpu_queue_depth", "requests waiting for a device step")
        m.new_counter("app_tpu_tokens_total", "tokens processed (prefill+decode)")
        m.new_gauge("app_tpu_kv_pages_free", "free pages in the paged KV pool")
        m.new_counter("app_tpu_preemptions", "slots preempted under KV pool pressure")
        m.new_counter("app_tpu_engine_restarts", "engine device-thread restarts")
        # hierarchical prefix cache (tpu/prefix.py, docs/serving.md): hit
        # tokens carry a tier label (hbm = pages already in the pool,
        # host = pages swapped back in from the host-DRAM spill tier)
        m.new_counter("app_tpu_prefix_hit_tokens", "prompt tokens served from the prefix cache (by tier)")
        m.new_counter("app_tpu_prefix_lookup_total", "prefix-cache lookups at admission")
        m.new_counter("app_tpu_prefix_miss_total", "prefix-cache lookups that hit nothing")
        m.new_gauge("app_tpu_prefix_cached_pages", "KV pages held by the prefix cache in HBM")
        m.new_gauge("app_tpu_prefix_host_pages", "KV pages held by the host-DRAM cache tier")
        m.new_gauge("app_tpu_prefix_host_bytes", "bytes held by the host-DRAM cache tier")
        m.new_counter("app_tpu_prefix_evicted_pages_total",
                      "prefix-cache pages evicted (tier: hbm = left the pool, host = dropped from host DRAM)")
        m.new_counter("app_tpu_prefix_swapin_pages_total",
                      "host-tier pages swapped back into the device pool")
        m.new_histogram("app_tpu_prefix_swapin_seconds",
                        "host->device page swap-in latency, dispatch to fold (s)")
        m.new_histogram("app_tpu_prefix_swapin_bytes",
                        "bytes uploaded per host->device swap-in",
                        buckets=[2 ** 14, 2 ** 17, 2 ** 20, 2 ** 23, 2 ** 26, 2 ** 29])
        # elastic fleet (gofr_tpu.fleet; docs/parallelism.md): epoch is the
        # membership generation — it only moves when the fleet changes
        m.new_gauge("app_fleet_epoch", "current fleet epoch (membership generation)")
        m.new_gauge("app_fleet_followers", "followers active on the fleet announce channel")
        m.new_counter("app_fleet_rejoins_total",
                      "followers admitted at an epoch bump (leader side) / successful "
                      "redials after leader loss (follower side)")
        m.new_counter("app_fleet_followers_lost_total",
                      "followers dropped from the announce fan-out mid-stream")
        m.new_counter("app_fleet_supervisor_restarts_total",
                      "fleet member processes restarted by fleet.Supervisor")
        # SLO-driven autoscaler (fleet/autoscaler.py, docs/resilience.md)
        m.new_gauge("app_fleet_replicas", "replicas the autoscaler's driver manages")
        m.new_counter("app_fleet_autoscale_decisions_total",
                      "autoscaler control-loop ticks (by decision: out/in/hold/freeze)")
        m.new_counter("app_fleet_autoscale_spawn_failures_total",
                      "warm-spare spawn attempts that failed (retried with backoff)")
        m.new_counter("app_fleet_autoscale_drain_aborts_total",
                      "scale-in drains aborted (victim re-admitted to the ring)")
        m.new_counter("app_fleet_requeued_total",
                      "requests moved from a draining replica onto a peer")
        m.new_gauge("app_tpu_draining", "1 while the engine is in its scale-in drain")
        m.new_counter("app_tpu_drain_shed_total",
                      "requests shed 503 because they arrived during a drain")
        # kernel-backend autotuner (ops/autotune.py, docs/kernels.md):
        # info-style gauge — 1 on the (op, backend) pair the warmup
        # autotuner pinned for 'auto' resolution, 0 on the loser
        m.new_gauge("app_tpu_kernel_backend",
                    "pinned attention-kernel backend per op (1 = op resolves "
                    "backend='auto' to this backend; labels: op, backend)")
        # data-plane router (gofr_tpu.router, docs/routing.md): the
        # front-end tier's routing/spillover/shed accounting — affinity hit
        # ratio = routed_total{affinity="home"} / requests_total
        m.new_counter("app_router_requests_total",
                      "requests entering the router data plane (by qos_class)")
        m.new_counter("app_router_routed_total",
                      "requests proxied to a replica (replica; affinity = home|spill)")
        m.new_counter("app_router_spilled_total",
                      "requests that LANDED off their home replica (replica = home "
                      "it left; reason: shedding/restart/down = plan-time exclusion, "
                      "busy/error = the home's own 429/5xx/transport answer)")
        m.new_counter("app_router_shed_total",
                      "requests shed AT the router (qos_class; reason)")
        m.new_gauge("app_router_ring_size",
                    "replicas currently in the consistent-hash ring")
        m.new_gauge("app_router_replicas_known",
                    "replicas known to the router registry, any state")
        m.new_counter("app_tpu_spec_proposed", "draft tokens proposed by speculative decoding")
        m.new_counter("app_tpu_spec_accepted", "draft tokens accepted by target verification")
        # SLO latency family (docs/observability.md): recorded by the engine
        # device loop / completion path regardless of QoS or tracing state
        m.new_histogram("app_tpu_queue_wait_seconds",
                        "enqueue-to-admission wait before the device loop picked the request")
        m.new_histogram("app_tpu_ttft_seconds", "time to first token (s)")
        m.new_histogram("app_tpu_tpot_seconds",
                        "time per output token after the first (s)",
                        buckets=[0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0])
        m.new_histogram("app_tpu_e2e_seconds",
                        "end-to-end request latency, submit to completion (by qos_class)")
        m.new_gauge("app_tpu_inflight_requests", "requests submitted but not yet complete")
        # QoS / admission control (gofr_tpu.qos; all zero while QoS is off)
        m.new_counter("app_qos_admitted_total", "requests admitted by QoS")
        m.new_counter("app_qos_rejected_total",
                      "requests rejected by QoS (reason: rate/route_rate/key_rate/"
                      "tenant_rate/queue/deadline_exceeded/capacity/restart/slo_burn)")
        m.new_counter("app_qos_shed_total", "requests shed under overload (503s)")
        m.new_gauge("app_qos_queue_depth", "queued requests per priority class")
        m.new_gauge("app_qos_predicted_wait_seconds",
                    "estimated queue wait per engine (EWMA step x backlog)")
        m.new_histogram("app_qos_queue_wait_seconds",
                        "time requests spent queued before reaching the device loop")
        # SLO plane (metrics/slo.py, docs/observability.md): attainment and
        # Google-SRE error-budget burn per (class, objective); refreshed by
        # the SLOEngine collect hook on every scrape
        m.new_gauge("app_slo_attainment",
                    "fraction of samples meeting the objective (class, objective, window)")
        m.new_gauge("app_slo_burn_rate",
                    "error-budget burn rate; 1.0 = sustainable pace (class, objective, window)")
        m.new_gauge("app_slo_budget_remaining",
                    "slow-window error budget left, clamped to [0,1] (class, objective)")
        m.new_counter("app_slo_captures_total",
                      "anomaly bundles written by the burn-breach capture watcher")
        m.new_counter("app_slo_captures_suppressed_total",
                      "burn-breach captures suppressed by the token-bucket rate limit")
        # router decision metrics (ISSUE 9 satellite: the affinity hit ratio
        # used to live only in the /debug/router JSON view)
        m.new_counter("app_router_decisions_total",
                      "router routing decisions (replica; decision = home|spill|shed|error)")
        m.new_gauge("app_router_affinity_hit_ratio",
                    "home-replica hit fraction of routed requests since router start")
        # request-lifetime plane (ISSUE 10, docs/resilience.md): deadline
        # propagation, retry budgets, and hedged dispatch
        m.new_counter("app_request_deadline_exceeded_total",
                      "requests shed because their deadline could not be met "
                      "(where = edge|qos|engine|router)")
        m.new_counter("app_retry_budget_spent_total",
                      "retries granted by the shared Envoy-style retry budget")
        m.new_counter("app_retry_budget_exhausted_total",
                      "retries DENIED because the budget window was spent")
        m.new_counter("app_router_hedged_total",
                      "hedged dispatches fired by the router "
                      "(winner = primary|hedge|none)")
        # live performance plane (metrics/perf.py, docs/observability.md):
        # windowed roofline utilization per step kind, derived at scrape
        # time from the engines' exact numerator/denominator sums — never
        # set per engine (the _sample_tpu_metrics discipline)
        m.new_gauge("app_tpu_mfu",
                    "windowed model-FLOPs utilization vs device peak "
                    "(kind, kv_dtype; absent while peaks are unknown)")
        m.new_gauge("app_tpu_mbu",
                    "windowed HBM-bandwidth utilization vs device peak "
                    "(kind, kv_dtype; absent while peaks are unknown)")
        m.new_gauge("app_tpu_perf_flops_window",
                    "analytical FLOPs folded in the perf window (kind, kv_dtype)")
        m.new_gauge("app_tpu_perf_bytes_window",
                    "analytical HBM bytes folded in the perf window (kind, kv_dtype)")
        m.new_gauge("app_tpu_perf_device_seconds_window",
                    "device-queue residency folded in the perf window (kind, kv_dtype)")
        m.new_histogram("app_tpu_step_device_seconds",
                        "per-step device-queue residency, pipeline overlap "
                        "deduplicated (kind)",
                        buckets=[0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
                                 0.05, 0.1, 0.25, 1.0])
        m.new_gauge("app_tpu_pipeline_bubble_ratio",
                    "device-idle-while-work-queued fraction of the perf window")
        # per-adapter attribution (multi-LoRA multiplexing; docs/serving.md):
        # proportional share of each mixed-adapter step's roofline terms,
        # an exact partition — summed over adapters they equal the step's
        m.new_gauge("app_tpu_adapter_mfu",
                    "windowed MFU share attributed to one adapter (adapter)")
        m.new_gauge("app_tpu_adapter_mbu",
                    "windowed MBU share attributed to one adapter (adapter)")
        m.new_gauge("app_tpu_adapter_device_seconds",
                    "windowed device-seconds attributed to one adapter "
                    "(adapter) — the per-tenant COGS meter")
        m.new_gauge("app_tpu_weights_epoch",
                    "live base-weight epoch (bumped by every hot-swap "
                    "adoption; engine.adopt_weights)")
        m.new_counter("app_tpu_weight_swaps_total",
                      "full-model live weight adoptions (zero-drop hot-swap)")
        m.new_gauge("app_tpu_adapters_registered",
                    "adapters resident in the host registry tier")
        m.new_counter("app_tpu_spec_pages_trimmed_total",
                      "KV pages claimed for spec over-claim and released at fold")
        m.new_counter("app_tpu_spec_tokens_rejected_total",
                      "spec draft tokens the target verification rejected")
        m.new_gauge("app_tpu_kv_pool_occupancy",
                    "allocated fraction of the paged KV pool (engine)")
        m.new_gauge("app_tpu_kv_pool_fragmentation",
                    "claimed-but-unwritten fraction of slot-held pages (engine)")
        m.new_gauge("app_tpu_kv_pool_device_bytes",
                    "shard-local paged-KV pool bytes resident per device "
                    "(engine, kv_shards) — fleet rollups sum, never average")
        # quality plane (metrics/quality.py; docs/observability.md): shadow
        # re-score divergence vs the reference configuration, keyed by what
        # the serving path actually used (kv_dtype, backend, adapter)
        m.new_histogram("app_tpu_quality_logprob_delta",
                        "mean |serving - reference| log-prob of the emitted "
                        "tokens, per shadow sample (kv_dtype, backend, adapter)",
                        buckets=[0.001, 0.005, 0.01, 0.05, 0.1, 0.5,
                                 1.0, 2.0, 5.0])
        m.new_histogram("app_tpu_quality_kl",
                        "mean per-token KL(serving || reference), per shadow "
                        "sample (kv_dtype, backend, adapter)",
                        buckets=[0.0001, 0.001, 0.01, 0.05, 0.1, 0.5,
                                 1.0, 5.0])
        m.new_gauge("app_tpu_quality_top1_agree",
                    "fraction of emitted tokens matching the reference "
                    "argmax, last shadow sample (kv_dtype, backend, adapter)")
        m.new_histogram("app_tpu_quality_first_divergence_token",
                        "token index of the first reference-argmax "
                        "disagreement (diverged samples only)",
                        buckets=[0, 1, 2, 4, 8, 16, 32, 64, 128])
        m.new_counter("app_tpu_quality_samples_total",
                      "shadow-scored requests (kv_dtype, backend, adapter) — "
                      "rides the gossip digest for exact fleet rollups")
        m.new_counter("app_tpu_quality_good_total",
                      "shadow samples within divergence thresholds "
                      "(kv_dtype, backend, adapter)")
        m.new_counter("app_tpu_quality_shadow_dropped_total",
                      "sampled requests evicted from the bounded shadow "
                      "queue before scoring (back-pressure, never blocking)")
        m.new_gauge("app_tpu_spec_accept_ratio",
                    "lifetime speculative-decode acceptance ratio (adapter) "
                    "— the cheapest always-on quality proxy")
        # online step controller (gofr_tpu.control; docs/serving.md): the
        # perf plane closed into actuation — decisions counted by verdict,
        # the live knob vector exported per knob so dashboards can overlay
        # knob moves on the MFU/bubble timelines they were judged by
        m.new_counter("app_tpu_control_decisions_total",
                      "step-controller decisions (verdict: try|commit|"
                      "revert|resume|standdown)")
        m.new_gauge("app_tpu_control_knob",
                    "live value of one engine tuning knob (engine, knob)")
        m.new_gauge("app_tpu_control_active",
                    "1 when the engine's step controller is constructed and "
                    "not stood down (engine)")

    def _sample_tpu_metrics(self, _registry=None) -> None:
        """Collect hook: live HBM gauges on every /metrics scrape (the
        reference pushes pool gauges on a ticker, sql.go:190-203). Only if
        the TPU datasource is already materialized — a scrape must never be
        the thing that initializes a device backend."""
        tpu = self._tpu
        if tpu is not None:
            try:
                tpu._push_memory_gauges()
            except Exception:  # noqa: BLE001 - scrape must not fail on device hiccup
                pass
        # summed HERE rather than set by each engine: a per-engine write to
        # the shared gauge would report whichever engine completed last
        self.metrics.set_gauge(
            "app_tpu_inflight_requests",
            sum(getattr(e, "_inflight_requests", 0) for e in self._engines.values()))
        # spec-decode acceptance, divided at scrape time from raw
        # per-adapter (accepted, proposed) numerators summed across engines
        # — never an average of per-engine ratios
        spec: dict[str, list[float]] = {}
        for e in self._engines.values():
            totals_fn = getattr(e, "spec_accept_totals", None)
            if not callable(totals_fn):
                continue
            for adapter, (acc, prop) in totals_fn().items():
                tot = spec.setdefault(adapter, [0.0, 0.0])
                tot[0] += acc
                tot[1] += prop
        for adapter, (acc, prop) in spec.items():
            if prop > 0:
                self.metrics.set_gauge("app_tpu_spec_accept_ratio",
                                       acc / prop, adapter=adapter)
        # online-controller surface: knob vectors are engine attributes, so
        # sampling them at scrape time (like the pool gauges) keeps the
        # device loop free of metrics writes on the knob-apply path
        for name, e in self._engines.items():
            kv_fn = getattr(e, "knob_vector", None)
            if not callable(kv_fn):
                continue
            for knob, value in kv_fn().items():
                self.metrics.set_gauge("app_tpu_control_knob", value,
                                       engine=name, knob=knob)
            ctl = getattr(e, "_control", None)
            self.metrics.set_gauge(
                "app_tpu_control_active",
                1 if (ctl is not None and ctl.standdown is None) else 0,
                engine=name)
        self._sample_perf_metrics()

    def perf_totals(self) -> dict | None:
        """Exact sum-of-parts merge of every registered engine's perf
        window (metrics/perf.py payload shape) — the one rollup the
        scrape gauges, the gossip digest, and capture bundles all share.
        None when no engine carries a perf plane."""
        planes = [e.perf for e in self._engines.values()
                  if getattr(e, "perf", None) is not None]
        if not planes:
            return None
        import time

        from gofr_tpu.metrics import perf as perf_mod

        now = time.monotonic()
        return perf_mod.merge_totals(p.window_totals(now) for p in planes)

    def knob_vectors(self) -> dict | None:
        """Per-engine live tuning-knob vectors (engine.knob_vector), with a
        ``_controlled`` marker where an online controller is actually
        driving them — rides the gossip digest so /debug/fleet shows who
        runs which tuning. None when no engine exposes knobs."""
        out: dict = {}
        for name, e in self._engines.items():
            kv_fn = getattr(e, "knob_vector", None)
            if not callable(kv_fn):
                continue
            vec = kv_fn()
            ctl = getattr(e, "_control", None)
            if ctl is not None and ctl.standdown is None:
                vec["_controlled"] = 1
            out[name] = vec
        return out or None

    def _sample_perf_metrics(self) -> None:
        """Roofline gauges from the merged engine windows: numerators and
        capacity denominators are summed exactly across engines, the
        ratios derived once here (never averaged)."""
        totals = self.perf_totals()
        if totals is None:
            return
        from gofr_tpu.metrics import perf as perf_mod

        for key, rec in totals["kinds"].items():
            kind, _, dtype = key.partition("|")
            labels = {"kind": kind, "kv_dtype": dtype}
            self.metrics.set_gauge(
                "app_tpu_perf_flops_window", rec["flops"], **labels)
            self.metrics.set_gauge(
                "app_tpu_perf_bytes_window", rec["bytes"], **labels)
            self.metrics.set_gauge(
                "app_tpu_perf_device_seconds_window", rec["device_s"], **labels)
            if rec["flops_cap"]:
                self.metrics.set_gauge(
                    "app_tpu_mfu", rec["flops"] / rec["flops_cap"], **labels)
            if rec["bytes_cap"]:
                self.metrics.set_gauge(
                    "app_tpu_mbu", rec["bytes"] / rec["bytes_cap"], **labels)
        derived = perf_mod.derive(totals)
        for aid, rec in derived.get("adapters", {}).items():
            labels = {"adapter": aid}
            self.metrics.set_gauge(
                "app_tpu_adapter_device_seconds", rec["device_s"], **labels)
            if rec.get("mfu") is not None:
                self.metrics.set_gauge("app_tpu_adapter_mfu", rec["mfu"],
                                       **labels)
            if rec.get("mbu") is not None:
                self.metrics.set_gauge("app_tpu_adapter_mbu", rec["mbu"],
                                       **labels)
        ratio = derived["bubble_ratio"]
        if ratio is not None:
            self.metrics.set_gauge("app_tpu_pipeline_bubble_ratio", ratio)
        for name, e in self._engines.items():
            stats_fn = getattr(e, "page_pool_stats", None)
            stats = stats_fn() if callable(stats_fn) else None
            if stats:
                # occupancy/fragmentation are page-count ratios — identical
                # on every shard of a tp-sharded pool, so one gauge per
                # engine IS the shard-local reading; the byte gauge is the
                # per-DEVICE slice (engine.page_pool_stats), so a fleet
                # sum-of-parts rollup over devices stays exact
                self.metrics.set_gauge(
                    "app_tpu_kv_pool_occupancy", stats["occupancy"], engine=name)
                self.metrics.set_gauge(
                    "app_tpu_kv_pool_fragmentation", stats["fragmentation"],
                    engine=name)
                if "pool_bytes_device" in stats:
                    self.metrics.set_gauge(
                        "app_tpu_kv_pool_device_bytes",
                        stats["pool_bytes_device"], engine=name,
                        kv_shards=str(stats.get("kv_shards", 1)))

    def _maybe_remote_log_level(self) -> None:
        url = self.config.get("REMOTE_LOG_URL")
        if not url:
            return
        from gofr_tpu.logging.remote import RemoteLevelPoller

        interval = self.config.get_float("REMOTE_LOG_FETCH_INTERVAL", 15.0)
        self._remote_level_poller = RemoteLevelPoller(self.logger, url, interval)
        self._remote_level_poller.start()

    def _maybe_slo(self) -> None:
        """Wire the SLO engine (on by default — it is pure bookkeeping over
        samples the engines already record) and, only when the app opts in
        via SLO_CAPTURE, the burn-breach anomaly capture watcher."""
        if not self.config.get_bool("SLO_ENABLED", True):
            return
        from gofr_tpu.metrics.slo import CaptureWatcher, SLOEngine

        self.slo = SLOEngine.from_config(
            self.config, metrics=self.metrics, logger=self.logger)
        self.metrics.add_collect_hook(self.slo.sample_gauges)
        if self.config.get_bool("SLO_CAPTURE"):
            self.slo_capture = CaptureWatcher.from_config(
                self.config, self, self.slo)
            self.slo.add_breach_listener(self.slo_capture.on_breach)

    def _maybe_sql(self) -> None:
        dialect = (self.config.get("DB_DIALECT") or "").lower()
        host = self.config.get("DB_HOST")
        if not dialect and not host:
            return
        from gofr_tpu.datasource.sql import connect_sql

        self.sql = connect_sql(self.config, self.logger, self.metrics)

    def _maybe_redis(self) -> None:
        host = self.config.get("REDIS_HOST")
        if not host:
            return
        from gofr_tpu.datasource.redis import connect_redis

        self.redis = connect_redis(self.config, self.logger, self.metrics)

    def _maybe_pubsub(self) -> None:
        backend = (self.config.get("PUBSUB_BACKEND") or "").lower()
        if not backend:
            return
        from gofr_tpu.pubsub import connect_pubsub

        self.pubsub = connect_pubsub(backend, self.config, self.logger, self.metrics)

    def _wire_file(self) -> None:
        from gofr_tpu.datasource.file import LocalFileSystem

        self.file = LocalFileSystem()

    def _maybe_kv(self) -> None:
        path = self.config.get("KV_PATH")
        if not path:
            return
        from gofr_tpu.datasource.kv import KVStore

        self.kv = KVStore(path, self.logger, self.metrics)

    # -- external-plugin injection (gofr `external_db.go` pattern) -------------

    def add_mongo(self, client: Any) -> None:
        self.mongo = self._wire_plugin(client)

    def add_cassandra(self, client: Any) -> None:
        self.cassandra = self._wire_plugin(client)

    def add_clickhouse(self, client: Any) -> None:
        self.clickhouse = self._wire_plugin(client)

    def add_kv_store(self, client: Any) -> None:
        self.kv = self._wire_plugin(client)

    def add_file_store(self, client: Any) -> None:
        """Replace the default local filesystem with a remote-FS provider
        (datasource/file.py ``FileSystemProvider``; gofr `file.go:69-78`)."""
        self.file = self._wire_plugin(client)

    def _wire_plugin(self, client: Any) -> Any:
        if hasattr(client, "use_logger"):
            client.use_logger(self.logger)
        if hasattr(client, "use_metrics"):
            client.use_metrics(self.metrics)
        if hasattr(client, "connect"):
            client.connect()
        return client

    # -- TPU device datasource (lazy; a feature like any other) ----------------

    @property
    def tpu(self):
        if self._tpu is None:
            with self._tpu_lock:
                if self._tpu is None:
                    from gofr_tpu.tpu.device import TPUDevices

                    self._tpu = TPUDevices(self.config, self.logger, self.metrics)
        return self._tpu

    @property
    def tpu_wired(self) -> bool:
        return self._tpu is not None

    # -- QoS / admission control -----------------------------------------------

    def register_qos(self, controller: Any) -> None:
        """Install the app-wide AdmissionController (App.enable_qos): binds
        every already-served engine, exports the per-class gauges on each
        scrape, and joins health aggregation (DEGRADED while shedding).
        Re-registering (QOS_ENABLED auto-enable followed by a programmatic
        enable_qos) replaces the old controller entirely — its scrape hook
        included, so a stale sampler can't keep writing gauges."""
        if self.qos is not None:
            self.metrics.remove_collect_hook(self.qos.sample_gauges)
        self.qos = controller
        self.metrics.add_collect_hook(controller.sample_gauges)
        for name, engine in self._engines.items():
            controller.bind_engine(name, engine)

    # -- model engines ---------------------------------------------------------

    def register_engine(self, name: str, engine: Any) -> None:
        self._engines[name] = engine
        if self.qos is not None:
            self.qos.bind_engine(name, engine)

    def engine(self, name: str):
        try:
            return self._engines[name]
        except KeyError:
            raise KeyError(
                f"no model {name!r} served; registered: {sorted(self._engines)}"
            ) from None

    @property
    def engines(self) -> dict[str, Any]:
        return dict(self._engines)

    def infer(self, model: str, inputs: Any, **kw: Any):
        return self.engine(model).infer(inputs, **kw)

    def generate(self, model: str, prompt: Any, **kw: Any):
        return self.engine(model).generate(prompt, **kw)

    # -- inter-service HTTP clients -------------------------------------------

    def register_service(self, name: str, client: Any) -> None:
        self.services[name] = client

    def http_service(self, name: str):
        try:
            return self.services[name]
        except KeyError:
            raise KeyError(f"no HTTP service registered as {name!r}") from None

    # -- pubsub convenience ----------------------------------------------------

    def _pubsub_supports_headers(self) -> bool:
        """Signature-probed once per broker object (NOT try/except TypeError
        around the send — that would conflate 'no headers parameter' with a
        genuine TypeError inside a headers-capable broker and re-publish)."""
        ps = self.pubsub
        cached = self._pubsub_hdr_support
        if cached is not None and cached[0] is ps:
            return cached[1]
        import inspect

        try:
            ok = "headers" in inspect.signature(ps.publish).parameters
        except (TypeError, ValueError):  # builtins/C extensions: no signature
            ok = False
        self._pubsub_hdr_support = (ps, ok)
        return ok

    def publish(self, topic: str, payload: Any, headers: dict[str, str] | None = None) -> None:
        if self.pubsub is None:
            raise RuntimeError("no pubsub backend configured (set PUBSUB_BACKEND)")
        self.metrics.increment_counter("app_pubsub_publish_total_count", 1, topic=topic)
        if headers and self._pubsub_supports_headers():
            # trace context (W3C traceparent) rides as message headers so
            # subscribe handlers join the publisher's trace; an external
            # plugin broker without header support still gets the message
            self.pubsub.publish(topic, payload, headers=headers)
        else:
            self.pubsub.publish(topic, payload)
        self.metrics.increment_counter("app_pubsub_publish_success_count", 1, topic=topic)

    # -- health aggregation (gofr `container/health.go`) -----------------------

    def health(self) -> dict[str, Any]:
        services: dict[str, Any] = {}
        down = 0

        def check(name: str, obj: Any) -> None:
            nonlocal down
            if obj is None:
                return
            try:
                h = obj.health_check() if hasattr(obj, "health_check") else {"status": "UP"}
            except Exception as e:  # noqa: BLE001
                h = {"status": "DOWN", "details": {"error": str(e)}}
            services[name] = h
            if h.get("status") != "UP":
                down += 1

        check("sql", self.sql)
        check("redis", self.redis)
        check("pubsub", self.pubsub)
        check("kv", self.kv)
        check("file", self.file)
        check("mongo", self.mongo)
        check("cassandra", self.cassandra)
        check("clickhouse", self.clickhouse)
        check("tpu", self._tpu)
        check("qos", self.qos)
        check("slo", self.slo)
        for name, engine in self._engines.items():
            check(f"model:{name}", engine)
        for name, svc in self.services.items():
            check(f"service:{name}", svc)

        status = "UP" if down == 0 else ("DEGRADED" if down < max(len(services), 1) else "DOWN")
        return {
            "status": status,
            "name": self.app_name,
            "version": self.app_version,
            "services": services,
        }

    # -- shutdown --------------------------------------------------------------

    def close(self) -> None:
        if self._remote_level_poller is not None:
            self._remote_level_poller.stop()
        for engine in self._engines.values():
            if hasattr(engine, "stop"):
                engine.stop()
        for ds in (self.sql, self.redis, self.pubsub, self.kv, self.mongo, self.cassandra, self.clickhouse):
            if ds is not None and hasattr(ds, "close"):
                try:
                    ds.close()
                except Exception:  # noqa: BLE001
                    pass
        self.tracer.shutdown()


def new_mock_container(config: dict[str, str] | None = None) -> Container:
    """Hermetic container for handler tests (gofr `NewMockContainer`): mock
    logger, real metrics registry, no datasources wired, in-memory pubsub."""
    from gofr_tpu.pubsub.inmemory import InMemoryBroker

    c = Container(DictConfig(config or {}), logger=MockLogger(level=Level.DEBUG))
    c._register_framework_metrics()
    c.metrics.add_collect_hook(c._sample_tpu_metrics)
    c._maybe_slo()  # mock containers skip create(); SLO must still wire
    c.pubsub = InMemoryBroker()
    return c
