"""Test utilities — the reference's `pkg/gofr/testutil` analog (SURVEY §2.7),
extended with the TPU build's own needs: shared mesh-serving correctness
checks used by both the pytest tier and the driver's multichip dryrun, so
the two can't silently drift apart.
"""

from __future__ import annotations

import threading
from typing import Callable

import jax
import jax.numpy as jnp


def tiny_f32_llama():
    """A tiny FLOAT32 llama config + params for cross-sharding greedy-token
    comparisons. f32 matters: sharded matmul reduction order differs from
    the dense single-device order, and on a random bf16 model near-tie
    argmaxes flip — which would test numerics, not the serving path."""
    from gofr_tpu.models import LlamaConfig, llama

    cfg = LlamaConfig(
        vocab_size=512, hidden_size=64, intermediate_size=160,
        num_layers=2, num_heads=8, num_kv_heads=4, max_seq_len=128,
        dtype=jnp.float32,
    )
    params = llama.init(cfg, jax.random.key(3))
    return cfg, params


def greedy_reference(cfg, params) -> Callable[[list[int], int], list[int]]:
    """Single-device incremental-forward greedy decoder (the ground truth
    every engine/sharding path must reproduce token-for-token)."""
    from gofr_tpu.models import llama

    def ref(prompt: list[int], n: int) -> list[int]:
        seq = list(prompt)
        for _ in range(n):
            logits = llama.forward(cfg, params, jnp.asarray([seq], jnp.int32))
            seq.append(int(jnp.argmax(logits[0, -1])))
        return seq[len(prompt):]

    return ref


def assert_paged_pool_consistent(engine, slots_empty: bool = False) -> None:
    """Paged-pool accounting invariant: every page is free XOR held, and
    ``_page_refs`` equals the true holder count (slot block tables + the
    prefix cache's DEVICE tier — host-resident nodes hold no pool pages).
    With ``slots_empty`` (end-of-test quiescence) additionally require that
    only the prefix cache still holds pages — the old "everything is free"
    assertion generalized for prefix retention."""
    import numpy as np

    refs = np.zeros(engine.total_pages, np.int64)
    for pages in engine._slot_pages:
        for p in pages:
            refs[p] += 1
    if slots_empty:
        assert not refs.any(), "a vacated slot still holds pages"
    if engine._prefix is not None:
        for node in engine._prefix._nodes.values():
            if node.page_id >= 0:
                refs[node.page_id] += 1
    assert (refs == engine._page_refs).all(), "refcounts diverge from holders"
    free = set(engine._free_pages)
    assert len(free) == len(engine._free_pages), "free list holds duplicates"
    for p in range(getattr(engine, "_page_sink", 0), engine.total_pages):
        assert (p in free) == (refs[p] == 0), f"page {p}: free/held mismatch"


def assert_page_refs_consistent(engine) -> None:
    """Full paged-cache accounting cross-check, safe to call at any point
    (takes the engine state lock): ``_page_refs`` vs the true holders (slot
    page lists + device-tier prefix nodes), free-list/refcount duality,
    block-table rows vs ``_slot_pages``, and both prefix-cache tiers'
    internal invariants (host nodes carry payloads and no page; device
    nodes carry a page and no payload; host byte/page accounting matches
    the stored payloads). No-op on slot-layout engines — used as a shared
    teardown by tests/test_prefix.py and tests/test_async_pipeline.py."""
    if getattr(engine, "kv_layout", "slot") != "paged":
        return
    import numpy as np

    with engine._state_lock:
        assert_paged_pool_consistent(engine)
        for i, pages in enumerate(engine._slot_pages):
            row = engine._table[i]
            assert list(row[: len(pages)]) == list(pages), (
                f"slot {i}: block table row diverges from _slot_pages")
            assert (row[len(pages):] == engine.total_pages).all(), (
                f"slot {i}: table rows past the owned pages must be OOB")
            if engine.slots[i] is None:
                assert not pages, f"empty lane {i} still owns pages"
        cache = engine._prefix
        if cache is None:
            return
        dev = host = 0
        host_bytes = 0
        for key, node in cache._nodes.items():
            if node.page_id >= 0:
                dev += 1
                assert node.host is None and node.host_nbytes == 0, (
                    "device-tier node still holds a host payload")
            else:
                host += 1
                assert node.host is not None, "host-tier node lost its payload"
                assert not node.pending, "host-tier node marked upload-pending"
                host_bytes += node.host_nbytes
        # child counters: recompute from parent links across both tiers
        children = {k: [0, 0] for k in cache._nodes}
        for node in cache._nodes.values():
            ent = children.get(node.parent_key)
            if ent is not None:
                ent[0] += 1
                if node.page_id >= 0:
                    ent[1] += 1
        for key, node in cache._nodes.items():
            want_all, want_dev = children[key]
            assert node.children == want_all, (
                f"node {key}: children counter {node.children} != {want_all}")
            assert node.dev_children == want_dev, (
                f"node {key}: dev_children counter {node.dev_children} != {want_dev}")
        assert len(cache) == dev, "device-tier count diverges"
        assert cache.host_pages == host, "host-tier count diverges"
        assert cache.host_bytes == host_bytes, "host byte accounting diverges"
        assert np.all(engine._page_refs >= 0), "negative page refcount"


def check_mesh_serving(config: dict[str, str], *, n_requests: int = 6,
                       max_new: int = 5, timeout: float = 600.0,
                       **engine_kw) -> None:
    """Build an engine on a mesh container (per ``config``, e.g.
    ``{"TPU_MESH": "dp:2,tp:4"}``), serve ``n_requests`` concurrent greedy
    requests, and require token-exact agreement with single-device decoding.
    Raises AssertionError on divergence."""
    from gofr_tpu.container import new_mock_container
    from gofr_tpu.models import ModelSpec
    from gofr_tpu.tpu.engine import build_engine

    cfg, params = tiny_f32_llama()
    ref = greedy_reference(cfg, params)

    container = new_mock_container(config)
    engine_kw.setdefault("slots", 4)
    engine_kw.setdefault("max_len", 64)
    engine_kw.setdefault("max_prefill_batch", 2)
    if engine_kw.pop("spec_self_draft", False):
        # draft-model speculation with the target as its own draft: the
        # sharded draft path compiles/executes, every proposal is accepted,
        # and tokens must still match the single-device reference. The
        # draft params must be the ENGINE's sharded tree, so rebuild from
        # the same seed the engine will use.
        from gofr_tpu.models import llama as _llama

        engine_kw["spec_draft"] = (_llama, cfg, _llama.init(cfg, jax.random.key(3)))
    eng = build_engine(ModelSpec(family="llama", task="generate", config=cfg),
                       container, seed=3, **engine_kw)
    prompts = [[i + 1, (2 * i) % 200 + 1, (7 * i) % 150 + 1] for i in range(n_requests)]
    want = [ref(p, max_new) for p in prompts]
    results: list = [None] * len(prompts)

    def worker(i):
        results[i] = eng.generate(prompts[i], max_new_tokens=max_new, timeout=timeout)

    try:
        threads = [threading.Thread(target=worker, args=(i,)) for i in range(len(prompts))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=timeout)
        for i, r in enumerate(results):
            assert r is not None, f"request {i} did not complete"
            assert r["tokens"] == want[i], (
                f"request {i} diverged on mesh {config.get('TPU_MESH')}: "
                f"{r['tokens']} != {want[i]}"
            )
    finally:
        eng.stop()


def assert_lane_sets_consistent(engine) -> None:
    """The incrementally-maintained lane sets (engine._free_lanes /
    _prefill_lanes / _decode_lanes) must always agree with a fresh rescan
    of ``engine.slots`` — they replace the per-iteration O(num_slots)
    sweeps, so drift would silently corrupt admission/decode masking."""
    with engine._state_lock:
        free = {i for i, s in enumerate(engine.slots) if s is None}
        prefill = {i for i, s in enumerate(engine.slots)
                   if s is not None and s.last_token is None}
        decode = {i for i, s in enumerate(engine.slots)
                  if s is not None and s.last_token is not None}
        assert engine._free_lanes == free, (engine._free_lanes, free)
        assert engine._prefill_lanes == prefill, (engine._prefill_lanes, prefill)
        assert engine._decode_lanes == decode, (engine._decode_lanes, decode)
