"""Deterministic chaos injection (docs/testing.md chaos-point catalog).

Production code declares *named fault points* — ``engine.step``,
``engine.restart``, ``lockstep.announce``, ``pubsub.commit``,
``client.disconnect`` (drop = sever the response stream mid-flight so the
cooperative-cancellation path must reclaim the slot/pages),
``replica.slow`` (delay = stall ``_submit`` to widen hedge windows) — and the
fault that fires there is injected from the outside via the ``GOFR_CHAOS``
environment variable (or :func:`override` inside a test process). This is
how the app-tier failure contracts are *proven* rather than asserted:
the same binary that serves traffic can be told "kill the device loop on
its 5th step" and the test observes the recovery path.

Spec grammar (``;``-separated points)::

    GOFR_CHAOS="engine.step:raise,nth=5;lockstep.announce:delay,ms=50,every=3"

    point   dotted fault-point name (the catalog lives in docs/testing.md)
    action  raise        raise ChaosFault at the point (crash that code path)
            exit         hard-exit the process (code=N, default 1)
            drop         return True — the call site discards the operation
            delay        sleep ms=N milliseconds, then continue
            hold         block until file=PATH exists (timeout=N seconds,
                         default 30) — the deterministic latch tests use to
                         pin a window open (no sleeps-as-synchronization)
    gates   nth=N        fire on the Nth hit of this point only
            every=N      fire on every Nth hit
            after=N      fire on every hit once more than N hits happened
            at_step=N    fire ONCE, the first time the call site's
                         ``step=`` context reaches N — gating on engine
                         state (the device-step counter) instead of hit
                         counts, so "kill the device loop mid-generation"
                         is exact under any loop-iteration timing
            p=F          fire with probability F — SEEDED per point from
                         GOFR_CHAOS_SEED, so a given seed replays the same
                         fault schedule every run
            (no gate)    fire on every hit

Determinism: gating is by per-point hit COUNTERS (and a seeded PRNG for
``p=``), never by wall clock, so a fault schedule is a pure function of
the spec + seed + call sequence.

Zero cost when off: ``hook(point)`` returns ``None`` unless a spec
targets the point — call sites bind it once and pay a single branch
(the ``Tracer.enabled`` discipline); ``fire(point)`` short-circuits on an
empty table for call sites that can't pre-bind.
"""

from __future__ import annotations

import os
import random
import threading
import time
import zlib
from typing import Any


class ChaosFault(RuntimeError):
    """The injected failure (action ``raise``). Deliberately a RuntimeError:
    fault points sit on paths whose real faults are runtime errors, and the
    recovery machinery under test must not special-case chaos."""


class ChaosPoint:
    """One armed fault point. Calling it applies the gate and, when it
    fires, performs the action; returns True when the call site should
    DROP the guarded operation (action ``drop``)."""

    def __init__(self, name: str, action: str, params: dict[str, str], seed: int):
        self.name = name
        self.action = action
        self.params = params
        self._hits = 0
        self._fired = False
        self._lock = threading.Lock()
        self._rng = random.Random(seed ^ zlib.crc32(name.encode())) \
            if "p" in params else None

    def _gate(self, ctx: dict[str, Any]) -> bool:
        with self._lock:
            self._hits += 1
            hits = self._hits
        at_step = self.params.get("at_step")
        if at_step is not None:
            with self._lock:
                if self._fired or int(ctx.get("step", -1)) < int(at_step):
                    return False
                self._fired = True
                return True
        nth = self.params.get("nth")
        if nth is not None:
            return hits == int(nth)
        every = self.params.get("every")
        if every is not None:
            return hits % int(every) == 0
        after = self.params.get("after")
        if after is not None:
            return hits > int(after)
        p = self.params.get("p")
        if p is not None:
            with self._lock:  # PRNG state is shared mutable state
                return self._rng.random() < float(p)
        return True

    def __call__(self, **ctx: Any) -> bool:
        if not self._gate(ctx):
            return False
        if self.action == "drop":
            return True
        if self.action == "delay":
            time.sleep(float(self.params.get("ms", "10")) / 1000.0)
            return False
        if self.action == "hold":
            path = self.params.get("file", "")
            deadline = time.monotonic() + float(self.params.get("timeout", "30"))
            while path and not os.path.exists(path):
                if time.monotonic() > deadline:
                    break
                time.sleep(0.01)
            return False
        if self.action == "exit":
            os._exit(int(self.params.get("code", "1")))
        raise ChaosFault(
            f"chaos: injected fault at {self.name!r} "
            f"(hit {self._hits}, ctx {ctx or '{}'})"
        )


_TABLE: dict[str, ChaosPoint] | None = None  # None = env not parsed yet
_TABLE_LOCK = threading.Lock()


def _parse(spec: str, seed: int) -> dict[str, ChaosPoint]:
    table: dict[str, ChaosPoint] = {}
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        point, _, rest = part.partition(":")
        bits = [b.strip() for b in rest.split(",")] if rest else []
        action = bits[0] if bits and "=" not in bits[0] else "raise"
        params: dict[str, str] = {}
        for b in bits:
            if "=" in b:
                k, _, v = b.partition("=")
                params[k.strip()] = v.strip()
        table[point.strip()] = ChaosPoint(point.strip(), action, params, seed)
    return table


def _table() -> dict[str, ChaosPoint]:
    global _TABLE
    if _TABLE is None:
        with _TABLE_LOCK:
            if _TABLE is None:
                spec = os.environ.get("GOFR_CHAOS", "")
                seed = int(os.environ.get("GOFR_CHAOS_SEED", "0"))
                _TABLE = _parse(spec, seed) if spec else {}
    return _TABLE


def active() -> bool:
    return bool(_table())


def active_spec() -> str:
    """The armed spec re-serialized from the LIVE table — env-parsed or
    :class:`override`-installed alike. Diagnostics record this (capture
    bundles, engine replay configs) so an offline replay can re-arm the
    exact fault schedule; reading GOFR_CHAOS alone would miss overrides."""
    parts = []
    for name, pt in sorted(_table().items()):
        bits = [pt.action] + [f"{k}={v}" for k, v in sorted(pt.params.items())]
        parts.append(f"{name}:{','.join(bits)}")
    return ";".join(parts)


def hook(point: str) -> ChaosPoint | None:
    """The armed ChaosPoint for ``point``, or None (the common case) —
    bind at construction time and guard with one truthiness branch."""
    return _table().get(point)


def fire(point: str, **ctx: Any) -> bool:
    """Dynamic-lookup spelling of :func:`hook` for call sites that cannot
    pre-bind (e.g. the subscriber loop, where tests install an override
    after the app object exists). True = drop the guarded operation."""
    table = _table()
    if not table:
        return False
    p = table.get(point)
    return p(**ctx) if p is not None else False


# Fault points consulted at TRACE time (the fault bakes into the compiled
# program rather than firing per call). Arming or disarming one of these
# must invalidate the in-process jit cache: the persistent cache is safe
# (the corruption changes the HLO), but the in-memory cache keys on python
# callables + static args + shapes only, so an identically-shaped program
# compiled clean would be silently reused by the "corrupted" engine — and,
# worse, a corrupted program would outlive the override into clean code.
_TRACE_TIME_POINTS = ("quality.corrupt",)


def _flush_traces(*tables: dict[str, ChaosPoint] | None) -> None:
    if not any(t and any(n in t for n in _TRACE_TIME_POINTS) for t in tables):
        return
    try:
        import jax

        jax.clear_caches()
    except Exception:  # noqa: BLE001 — jax absent or too old: nothing cached
        pass


class override:
    """Context manager installing a chaos spec for in-process tests::

        with chaos.override("pubsub.commit:raise,nth=1"):
            ...

    Counters start fresh on entry; the previous table (usually empty) is
    restored on exit. Trace-time points (see ``_TRACE_TIME_POINTS``) flush
    the jit cache on both edges so the fault schedule actually recompiles
    in and back out."""

    def __init__(self, spec: str, seed: int = 0):
        self.spec = spec
        self.seed = seed
        self._prev: dict[str, ChaosPoint] | None = None

    def __enter__(self) -> "override":
        global _TABLE
        with _TABLE_LOCK:
            self._prev = _TABLE
            _TABLE = _parse(self.spec, self.seed)
            _flush_traces(self._prev, _TABLE)
        return self

    def __exit__(self, *exc) -> None:
        global _TABLE
        with _TABLE_LOCK:
            _flush_traces(self._prev, _TABLE)
            _TABLE = self._prev


def reset() -> None:
    """Forget the parsed table so the next use re-reads GOFR_CHAOS (tests
    that mutate the environment)."""
    global _TABLE
    with _TABLE_LOCK:
        _TABLE = None
