"""Host-side (DCN) announce transport for N-process fleet lockstep.

The collective transport in ``tpu/lockstep.py`` rides the device fabric:
announces ARE collectives, so any process death wedges every peer inside
an unfinishable collective and the only recovery is full group teardown
(v1 semantics, preserved there). This module is the recoverable
alternative: announces ride plain TCP, followers execute the announced
programs on their own process-local mesh, and membership changes —
leader restart, follower restart, follower loss — are handled OUTSIDE
the compiled programs (GSPMD's rule for scaling SPMD past one process).

Topology: followers DIAL the leader (the leader's listen port is the
fleet's well-known endpoint, exactly like a coordinator). The handshake
carries the engine-config fingerprint — a follower built from different
config is rejected outright, never silently desynchronized — and every
accepted follower parks in a *pending* set until the leader's device
loop admits it at a step boundary with a ``TAG_EPOCH`` frame (the fleet
epoch bump; ``tpu/lockstep.py`` docs the follower side).

Wire format, little-endian, one frame per announce::

    int32[4] header  (tag, a, b, epoch)
    int32    nbytes  payload byte length (0 = header-only frame)
    bytes    payload the packed int32 array, C order

Failure semantics:

- leader death (process kill or socket close) → follower ``recv`` raises
  :class:`ChannelClosed`; the follower resets per-epoch state and redials
  until ``rejoin_timeout_s`` (then it is leader-lost: exit 17 territory);
- follower death → the leader's ``send`` to it fails; the follower is
  dropped from the active set (counted, logged) and serving continues —
  a restarted follower redials into *pending* and rejoins at the next
  epoch bump;
- partial frames (leader's device thread died mid-``send``) are resolved
  by reconnection, never by in-band resync: a rejoining socket starts at
  a frame boundary by construction.
"""

from __future__ import annotations

import socket
import struct
import threading
import time
from typing import Any

import numpy as np

_MAGIC = b"GOFR-FLEET1\n"
_HEADER = struct.Struct("<4i")
_NBYTES = struct.Struct("<i")

# Frame-size sanity cap, both directions. A corrupt or hostile length
# prefix would otherwise make _recv_exact allocate the advertised bytes
# outright (silent multi-GB OOM); an oversized send is a caller bug that
# must fail loudly, not wedge every follower mid-frame. Generous: the
# largest legitimate frames are multi-MB KV-page payloads (tpu/handoff.py
# rides the same framing), far below 256 MiB.
MAX_FRAME_BYTES = 256 << 20


class ChannelClosed(Exception):
    """The peer went away mid-stream (EOF, reset, or local abort). For
    rejoin-capable channels this is the *recoverable* signal."""


class FleetProtocolError(RuntimeError):
    """Unrecoverable protocol violation (fingerprint mismatch, garbage
    frame): the process must not keep serving."""


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        try:
            chunk = sock.recv(n - len(buf))
        except OSError as e:
            raise ChannelClosed(str(e)) from e
        if not chunk:
            raise ChannelClosed("peer closed the connection")
        buf.extend(chunk)
    return bytes(buf)


# sendmsg takes at most IOV_MAX iovecs per call (1024 on Linux); batching
# below it keeps one syscall per chunk without ever tripping EMSGSIZE
_IOV_BATCH = 64


def sendmsg_all(sock: socket.socket, parts) -> int:
    """Vectored (scatter-gather) send: write every buffer in ``parts`` in
    order WITHOUT joining them into one bytes object — the ``sendmsg``
    analog of ``sendall``, handling partial writes by slicing memoryviews
    rather than re-packing. This is the zero-repack framing path shared by
    the fleet announce channel and the KV handoff streams (tpu/handoff.py):
    a multi-MB page frame goes out as [header, meta, plane, plane, ...]
    views over the original arrays, never as one concatenated copy.
    Returns the total bytes written."""
    bufs = []
    for p in parts:
        mv = p if isinstance(p, memoryview) else memoryview(p)
        if mv.nbytes:
            bufs.append(mv.cast("B") if mv.format != "B" or mv.ndim != 1 else mv)
    total = sum(b.nbytes for b in bufs)
    while bufs:
        try:
            sent = sock.sendmsg(bufs[:_IOV_BATCH])
        except AttributeError:  # platform without sendmsg: degrade loudly-simple
            for b in bufs:
                sock.sendall(b)
            return total
        while sent:
            if sent >= bufs[0].nbytes:
                sent -= bufs[0].nbytes
                bufs.pop(0)
            else:
                bufs[0] = bufs[0][sent:]
                sent = 0
    return total


class FleetLeaderChannel:
    """Leader end: listens for follower dials, fans every announce out to
    the active follower set. ``send`` runs on the engine's device thread
    only; the listener thread touches only the pending set."""

    supports_rejoin = True

    def __init__(self, port: int, *, fingerprint: str, host: str = "0.0.0.0",
                 logger=None, metrics=None, bind_timeout_s: float = 5.0,
                 send_timeout_s: float = 10.0):
        self.fingerprint = fingerprint
        self.send_timeout_s = send_timeout_s
        self.logger = logger
        self.metrics = metrics
        self._lock = threading.Lock()
        self._active: list[socket.socket] = []
        self._pending: list[socket.socket] = []
        self._closed = False
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        # bind retries: a supervisor-restarted leader rebinds the fleet's
        # well-known port while the dead life's connections may still be
        # draining out of the kernel — EADDRINUSE for a moment is part of
        # the restart path, not an error
        deadline = time.monotonic() + bind_timeout_s
        while True:
            try:
                self._srv.bind((host, port))
                break
            except OSError:
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.05)
        self._srv.listen(64)
        # the accept loop polls on a short timeout instead of blocking
        # forever: a close() must be able to JOIN the thread before the fd
        # is released — a thread still blocked in accept() on a closed fd
        # would steal connections the moment the fd number is reused (e.g.
        # by the next leader life's listener)
        self._srv.settimeout(0.25)
        self.port = self._srv.getsockname()[1]
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="fleet-accept", daemon=True)
        self._accept_thread.start()

    # -- listener thread -------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                conn, addr = self._srv.accept()
            except socket.timeout:
                continue  # poll tick: re-check _closed
            except OSError:
                return  # listener closed
            try:
                conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                conn.settimeout(10.0)
                join = _recv_exact(conn, len(_MAGIC))
                if join != _MAGIC:
                    raise FleetProtocolError(f"bad join magic from {addr}")
                (flen,) = _NBYTES.unpack(_recv_exact(conn, _NBYTES.size))
                fp = _recv_exact(conn, min(max(flen, 0), 4096)).decode()
                if fp != self.fingerprint:
                    # config mismatch is FATAL for the joiner, not for us:
                    # a follower built from different config would replay
                    # our programs against different state and silently
                    # diverge — reject it at the door (tag -1).
                    conn.sendall(_HEADER.pack(-1, 0, 0, 0) + _NBYTES.pack(0))
                    conn.close()
                    if self.logger is not None:
                        self.logger.warn(
                            f"fleet: rejected follower {addr}: config "
                            f"fingerprint {fp!r} != leader {self.fingerprint!r}")
                    continue
                # finite SEND timeout for the serving phase: a stalled-but-
                # alive follower (SIGSTOP, livelock — socket open, never
                # reading) would otherwise wedge the leader's device thread
                # in sendall once the kernel buffers fill, stalling the
                # whole fleet. socket.timeout is an OSError, so send()'s
                # drop-the-follower path handles slow exactly like dead;
                # the torn frame is resolved by reconnection as usual.
                conn.settimeout(self.send_timeout_s)
            except (ChannelClosed, FleetProtocolError, OSError) as e:
                try:
                    conn.close()
                except OSError:
                    pass
                if self.logger is not None:
                    self.logger.warn(f"fleet: follower join from {addr} failed: {e}")
                continue
            with self._lock:
                self._pending.append(conn)
            if self.logger is not None:
                self.logger.info(f"fleet: follower {addr} joined (pending admission)")

    # -- device-thread API -----------------------------------------------------

    def has_pending(self) -> bool:
        with self._lock:
            return bool(self._pending)

    def admit_pending(self, epoch: int) -> int:
        """Move pending followers into the active set and frame the new
        epoch to EVERYONE (TAG_EPOCH; rejoiners and survivors alike reset
        per-epoch state on it). Device thread only, at a step boundary —
        the caller has already reset its own per-epoch engine state."""
        with self._lock:
            fresh, self._pending = self._pending, []
            self._active.extend(fresh)
        from gofr_tpu.tpu.lockstep import TAG_EPOCH

        self.send(np.array([TAG_EPOCH, 0, 0, epoch], np.int32), None)
        return len(fresh)

    def wait_ready(self, expect: int, epoch: int, timeout_s: float) -> int:
        """Initial bring-up: block until ``expect`` followers joined, then
        admit them at the starting epoch. Raises on timeout — a fleet
        configured for N followers must not silently serve with fewer."""
        deadline = time.monotonic() + timeout_s
        while True:
            with self._lock:
                n = len(self._pending) + len(self._active)
            if n >= expect:
                break
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"fleet: only {n}/{expect} followers joined within {timeout_s:.0f}s")
            time.sleep(0.02)
        self.admit_pending(epoch)
        return expect

    def send(self, header: np.ndarray, payload: np.ndarray | None) -> None:
        """Fan one frame out to every active follower. A failing follower
        is dropped (counted + logged) and serving continues — its
        supervisor restarts it into the pending set."""
        head = _HEADER.pack(*(int(x) for x in header))
        if payload is None:
            head += _NBYTES.pack(0)
            body = None
        else:
            # zero-copy payload path: the header+length go out as one small
            # bytes object, the payload as a memoryview over the (already
            # contiguous) array — multi-MB KV-page frames no longer pay a
            # tobytes() copy plus a second header+payload concat copy
            arr = np.ascontiguousarray(payload, np.int32)
            if arr.nbytes > MAX_FRAME_BYTES:
                raise FleetProtocolError(
                    f"fleet: refusing to send a {arr.nbytes}-byte frame "
                    f"(cap {MAX_FRAME_BYTES}); payload shape {arr.shape}")
            head += _NBYTES.pack(arr.nbytes)
            body = memoryview(arr).cast("B")
        with self._lock:
            conns = list(self._active)
        lost = []
        for conn in conns:
            try:
                # one vectored write per follower: header + payload go out
                # in a single syscall instead of two sendalls (the small
                # head would otherwise ride its own TCP segment)
                if body is not None:
                    sendmsg_all(conn, (head, body))
                else:
                    conn.sendall(head)
            except OSError as e:
                lost.append(conn)
                if self.logger is not None:
                    self.logger.warn(f"fleet: follower lost mid-stream: {e}")
        if lost:
            with self._lock:
                for conn in lost:
                    if conn in self._active:
                        self._active.remove(conn)
                    try:
                        conn.close()
                    except OSError:
                        pass
                remaining = len(self._active)
            if self.metrics is not None:
                self.metrics.increment_counter(
                    "app_fleet_followers_lost_total", len(lost))
                # keep the active-follower gauge truthful between epoch
                # bumps: a for-good loss never reaches _fleet_admit
                self.metrics.set_gauge("app_fleet_followers", remaining)

    def follower_count(self) -> int:
        with self._lock:
            return len(self._active)

    def reset_connections(self) -> None:
        """Close every active follower socket (leader device-loop restart:
        a mid-``send`` crash may have left partial frames on the wire, and
        reconnection is the only framing resync). Followers see EOF, reset
        per-epoch state, and redial into pending."""
        with self._lock:
            conns, self._active = self._active, []
        for conn in conns:
            try:
                conn.close()
            except OSError:
                pass

    def close(self) -> None:
        self._closed = True
        # join BEFORE closing the fd (see the settimeout note in __init__)
        self._accept_thread.join(timeout=2.0)
        try:
            self._srv.close()
        except OSError:
            pass
        with self._lock:
            conns = self._active + self._pending
            self._active, self._pending = [], []
        for conn in conns:
            try:
                conn.close()
            except OSError:
                pass


class FleetFollowerChannel:
    """Follower end: dials the leader, receives frames. ``recv_header``/
    ``recv_payload`` run on the follower's replay thread; ``abort()`` is
    the thread-safe poke that releases a blocked recv (liveness watchdog)."""

    supports_rejoin = True

    def __init__(self, leader: str, *, fingerprint: str,
                 connect_timeout_s: float = 60.0, rejoin_timeout_s: float = 30.0,
                 logger=None):
        host, _, port = leader.rpartition(":")
        self.addr = (host or "127.0.0.1", int(port))
        self.fingerprint = fingerprint
        self.connect_timeout_s = connect_timeout_s
        self.rejoin_timeout_s = rejoin_timeout_s
        self.logger = logger
        self._sock: socket.socket | None = None
        self._lock = threading.Lock()
        self._pending_nbytes = 0

    def _dial(self, timeout_s: float) -> None:
        deadline = time.monotonic() + timeout_s
        delay = 0.05
        while True:
            try:
                sock = socket.create_connection(self.addr, timeout=5.0)
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                fp = self.fingerprint.encode()
                sock.sendall(_MAGIC + _NBYTES.pack(len(fp)) + fp)
                sock.settimeout(None)
                with self._lock:
                    self._sock = sock
                return
            except OSError as e:
                if time.monotonic() > deadline:
                    raise ChannelClosed(
                        f"fleet: no leader at {self.addr} within {timeout_s:.0f}s: {e}"
                    ) from e
                time.sleep(delay)
                delay = min(delay * 2, 1.0)

    def connect(self) -> None:
        self._dial(self.connect_timeout_s)

    def rejoin(self) -> None:
        """Leader went away: drop the dead socket and redial until the
        rejoin deadline (a restarted leader with the same config accepts
        the same fingerprint). Raises ChannelClosed when the deadline
        expires — the caller maps that to leader-lost (exit 17)."""
        self.abort()
        self._pending_nbytes = 0
        self._dial(self.rejoin_timeout_s)

    def recv_header(self) -> np.ndarray:
        sock = self._sock
        if sock is None:
            raise ChannelClosed("not connected")
        raw = _recv_exact(sock, _HEADER.size)
        header = np.frombuffer(raw, np.int32).copy()
        if int(header[0]) == -1:
            raise FleetProtocolError(
                "fleet: leader rejected this follower (engine config "
                "fingerprint mismatch — rebuild with the leader's config)")
        (self._pending_nbytes,) = _NBYTES.unpack(_recv_exact(sock, _NBYTES.size))
        if not 0 <= self._pending_nbytes <= MAX_FRAME_BYTES:
            raise FleetProtocolError(
                f"fleet: frame advertises {self._pending_nbytes} payload "
                f"bytes (cap {MAX_FRAME_BYTES}) — corrupt stream")
        return header

    def recv_payload(self, shape: tuple[int, ...]) -> np.ndarray:
        sock = self._sock  # abort() can null it between header and payload
        if sock is None:
            raise ChannelClosed("not connected")
        n = self._pending_nbytes
        self._pending_nbytes = 0
        want = int(np.prod(shape)) * 4
        if n != want:
            raise FleetProtocolError(
                f"fleet: payload size {n} != expected {want} for shape {shape}")
        raw = _recv_exact(sock, n)
        return np.frombuffer(raw, np.int32).reshape(shape).copy()

    def abort(self) -> None:
        """Thread-safe close releasing any blocked recv with ChannelClosed
        (the liveness watchdog's lever — silence past the deadline is
        treated exactly like leader death: reset and redial)."""
        with self._lock:
            sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass

    def close(self) -> None:
        self.abort()


class CollectiveChannel:
    """The device-fabric transport (``multihost_utils.broadcast_one_to_all``)
    wrapped in the channel interface — the v1 lockstep data plane for
    global-mesh (ICI-sharded) deployments. No rejoin: an announce IS a
    collective, so membership is fixed for the group's lifetime and any
    process death is group-fatal (tpu/lockstep.py module docs)."""

    supports_rejoin = False

    @staticmethod
    def _broadcast(value):
        from jax.experimental import multihost_utils

        return multihost_utils.broadcast_one_to_all(value)

    def send(self, header: np.ndarray, payload: np.ndarray | None) -> None:
        self._broadcast(np.asarray(header, np.int32))
        if payload is not None:
            self._broadcast(np.asarray(payload, np.int32))

    def recv_header(self) -> np.ndarray:
        from gofr_tpu.tpu.lockstep import _HEADER_LEN

        return np.asarray(self._broadcast(np.zeros(_HEADER_LEN, np.int32)))

    def recv_payload(self, shape: tuple[int, ...]) -> np.ndarray:
        return np.asarray(self._broadcast(np.zeros(shape, np.int32)))

    def close(self) -> None:
        pass


def fingerprint_of(*parts: Any) -> str:
    """Stable config fingerprint: a fleet only forms between processes
    whose engines were built identically (same model config, seed, slot
    geometry, layout...). 16 hex chars of sha256 over the reprs."""
    import hashlib

    h = hashlib.sha256()
    for p in parts:
        h.update(repr(p).encode())
        h.update(b"\x00")
    return h.hexdigest()[:16]
