"""SLO-driven elastic-fleet control loop: the fleet breathes with traffic
(ROADMAP O2; docs/resilience.md "Autoscaler runbook").

The pieces PRs 7-10 built are composed here into one closed loop:

- **pressure** comes from the PR 9 burn-rate plane (``SLOEngine.pressure()``
  — the worst fast-window burn across every tracked class/objective) and
  the QoS predicted-wait estimator
  (``AdmissionController.max_predicted_wait()``);
- **scale-out** spawns a warm spare through the driver: weights are
  pre-loaded by the replica factory and autotune pins are reused from
  ``GOFR_AUTOTUNE_CACHE``, so warmup is near-free. Gossip admits the
  spare at a bumped epoch and the PR 7 ring moves only the keys it takes;
- **scale-in** puts a cooling replica into the ``draining`` registry
  state (router/registry.py: out of BOTH rings, keys migrate to ring
  successors), lets its in-flight streams finish via the engine drain
  entrypoint (tpu/engine.py ``GenerateEngine.drain``), requeues its
  queued work onto a peer (:func:`requeue` — the Request OBJECTS move,
  so caller handles, stream queues and deadlines survive), and retires
  it with a terminal DOWN;
- **robustness core**: decisions pass through a pure, fake-clock-testable
  :class:`ScaleDecider` with hysteresis (pressure/calm must be
  *sustained*), per-direction cooldown windows, and a min/max replica
  clamp — the fleet never flaps. Spawn failure retries with backoff
  (chaos point ``autoscale.spawn``); replica death mid-drain aborts the
  drain and re-admits the replica (chaos point ``replica.drain`` fires
  inside the engine drain); stale signals (gossip silence) FREEZE the
  decision loop instead of acting on fiction.

Config (``AutoscalePolicy.from_config``, docs/configs.md):

    FLEET_AUTOSCALE_MIN / _MAX        replica clamp (default 1 / 4)
    FLEET_AUTOSCALE_BURN_OUT          fast-window burn that counts as
                                      pressure (default 2.0; 1.0 = exactly
                                      sustainable burn)
    FLEET_AUTOSCALE_BURN_IN           burn below which the fleet is calm
                                      (default 1.0 — the hysteresis band)
    FLEET_AUTOSCALE_WAIT_OUT_S / _IN_S  predicted-wait pressure/calm bounds
    FLEET_AUTOSCALE_SUSTAIN_S         pressure must persist this long
    FLEET_AUTOSCALE_IDLE_S            calm must persist this long
    FLEET_AUTOSCALE_COOLDOWN_OUT_S / _IN_S  lockout after ANY scale action
    FLEET_AUTOSCALE_STALE_S           signal age that freezes decisions
    FLEET_AUTOSCALE_INTERVAL_S        control-loop tick
    FLEET_AUTOSCALE_SPAWN_RETRIES     spawn attempts before giving up a tick
    FLEET_AUTOSCALE_SPAWN_BACKOFF_S   first retry delay (doubles, capped)
    FLEET_AUTOSCALE_DRAIN_TIMEOUT_S   in-flight settle budget at scale-in

Driver protocol (duck-typed): ``count() -> int``, ``spawn() -> name``,
``pick_victim() -> name | None``, ``drain(name, timeout_s) -> bool``,
``readmit(name)``, ``retire(name)``. :class:`LocalEngineFleet` is the
in-process implementation (one warmed ``GenerateEngine`` per replica,
membership mirrored into a ``ReplicaRegistry`` exactly as gossip would)
used by the diurnal bench and the drill tests; the process tier wires the
same protocol over ``fleet/supervisor.py`` ``FleetSupervisor`` members.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Any, Callable

from gofr_tpu.fleet import chaos

__all__ = [
    "AutoscalePolicy",
    "Autoscaler",
    "FleetSignals",
    "LocalEngineFleet",
    "ScaleDecider",
    "requeue",
]


@dataclass
class AutoscalePolicy:
    min_replicas: int = 1
    max_replicas: int = 4
    burn_out: float = 2.0          # fast-window burn counting as pressure
    burn_in: float = 1.0           # burn below which the fleet is calm
    wait_out_s: float = 2.0        # predicted wait counting as pressure
    wait_in_s: float = 0.25        # predicted wait below which it's calm
    sustain_s: float = 3.0         # pressure persistence before scale-out
    idle_s: float = 10.0           # calm persistence before scale-in
    cooldown_out_s: float = 5.0    # post-action lockout for scale-out
    cooldown_in_s: float = 20.0    # post-action lockout for scale-in
    stale_s: float = 5.0           # signal age that freezes decisions
    interval_s: float = 1.0        # control-loop tick
    spawn_retries: int = 3
    spawn_backoff_s: float = 0.2
    spawn_backoff_cap_s: float = 2.0
    drain_timeout_s: float = 30.0

    def __post_init__(self) -> None:
        if self.min_replicas < 1:
            raise ValueError("FLEET_AUTOSCALE_MIN must be >= 1")
        if self.max_replicas < self.min_replicas:
            raise ValueError("FLEET_AUTOSCALE_MAX must be >= FLEET_AUTOSCALE_MIN")
        if self.burn_in > self.burn_out or self.wait_in_s > self.wait_out_s:
            # an inverted hysteresis band would make one signal reading
            # simultaneously "pressure" and "calm" — flap by construction
            raise ValueError("scale-in thresholds must sit at or below scale-out")

    @classmethod
    def from_config(cls, conf) -> "AutoscalePolicy":
        return cls(
            min_replicas=conf.get_int("FLEET_AUTOSCALE_MIN", 1),
            max_replicas=conf.get_int("FLEET_AUTOSCALE_MAX", 4),
            burn_out=conf.get_float("FLEET_AUTOSCALE_BURN_OUT", 2.0),
            burn_in=conf.get_float("FLEET_AUTOSCALE_BURN_IN", 1.0),
            wait_out_s=conf.get_float("FLEET_AUTOSCALE_WAIT_OUT_S", 2.0),
            wait_in_s=conf.get_float("FLEET_AUTOSCALE_WAIT_IN_S", 0.25),
            sustain_s=conf.get_float("FLEET_AUTOSCALE_SUSTAIN_S", 3.0),
            idle_s=conf.get_float("FLEET_AUTOSCALE_IDLE_S", 10.0),
            cooldown_out_s=conf.get_float("FLEET_AUTOSCALE_COOLDOWN_OUT_S", 5.0),
            cooldown_in_s=conf.get_float("FLEET_AUTOSCALE_COOLDOWN_IN_S", 20.0),
            stale_s=conf.get_float("FLEET_AUTOSCALE_STALE_S", 5.0),
            interval_s=conf.get_float("FLEET_AUTOSCALE_INTERVAL_S", 1.0),
            spawn_retries=conf.get_int("FLEET_AUTOSCALE_SPAWN_RETRIES", 3),
            spawn_backoff_s=conf.get_float("FLEET_AUTOSCALE_SPAWN_BACKOFF_S", 0.2),
            spawn_backoff_cap_s=conf.get_float(
                "FLEET_AUTOSCALE_SPAWN_BACKOFF_CAP_S", 2.0),
            drain_timeout_s=conf.get_float("FLEET_AUTOSCALE_DRAIN_TIMEOUT_S", 30.0),
        )


@dataclass
class FleetSignals:
    """One pressure reading. ``burn`` is the worst fast-window burn across
    tracked (class, objective) pairs (None = not enough samples anywhere —
    an IDLE fleet, which together with an empty queue reads as calm, so a
    quiet fleet can still scale in); ``predicted_wait_s`` is the worst QoS
    queue-wait estimate across replicas; ``age_s`` is how stale the reading
    is — the *signal plane going silent* (gossip loss, dead scraper) shows
    up here and freezes the decider rather than letting it act on
    fiction."""

    burn: float | None
    predicted_wait_s: float
    replicas: int
    age_s: float = 0.0


class ScaleDecider:
    """Pure decision math — hysteresis + cooldowns + clamp — over an
    explicit ``now`` so the quick-tier units drive it with fake clocks.
    Returns one of ``"out" | "in" | "hold" | "freeze"``; the executor
    reports actions back via :meth:`note_action` so cooldowns anchor on
    what actually happened, not on what was decided.

    The hysteresis/sustain/cooldown/stale core lives in
    :class:`gofr_tpu.control.hysteresis.HysteresisGate` (extracted from
    here so the step-level knob controller damps flapping with the same
    semantics); this class keeps only what is fleet-specific — the
    hot/calm signal classification and the replica clamp."""

    def __init__(self, policy: AutoscalePolicy):
        from gofr_tpu.control.hysteresis import HysteresisGate

        self.policy = policy
        self._gate = HysteresisGate(
            sustain_s=policy.sustain_s, idle_s=policy.idle_s,
            cooldown_hot_s=policy.cooldown_out_s,
            cooldown_calm_s=policy.cooldown_in_s,
            stale_s=policy.stale_s)

    @property
    def _last_action_at(self) -> float:
        # pre-extraction attribute, still read by drills/operators
        return self._gate.last_action_at

    def note_action(self, now: float) -> None:
        self._gate.note_action(now)

    def decide(self, sig: FleetSignals, now: float) -> str:
        p = self.policy
        hot = ((sig.burn is not None and sig.burn >= p.burn_out)
               or sig.predicted_wait_s >= p.wait_out_s)
        calm = ((sig.burn is None or sig.burn <= p.burn_in)
                and sig.predicted_wait_s <= p.wait_in_s)
        verdict = self._gate.decide(hot=hot, calm=calm, now=now,
                                    age_s=sig.age_s)
        if verdict == "freeze":
            return "freeze"
        if verdict == "hot":
            return "out" if sig.replicas < p.max_replicas else "hold"
        if verdict == "calm":
            return "in" if sig.replicas > p.min_replicas else "hold"
        return "hold"


class Autoscaler:
    """The control loop: read signals, decide, execute through the driver.

    ``signals()`` returns a :class:`FleetSignals`; a raising signal source
    is treated exactly like stale gossip (freeze). Every chaos contract
    lives here or one call below:

    - ``autoscale.spawn`` fires before each spawn attempt — an injected
      raise is a spawn failure, answered with bounded retry-with-backoff
      (and the cooldown still engages, so a permanently failing spawn
      can't hammer the driver every tick);
    - ``replica.drain`` fires inside the engine drain path — an injected
      raise (or real replica death mid-drain) aborts the drain and
      RE-ADMITS the victim, leaving the fleet routable and the loop live.
    """

    def __init__(self, driver, policy: AutoscalePolicy | None = None, *,
                 signals: Callable[[], FleetSignals], logger=None,
                 metrics=None, now: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep):
        self.driver = driver
        self.policy = policy or AutoscalePolicy()
        self.decider = ScaleDecider(self.policy)
        self._signals = signals
        self.logger = logger
        self.metrics = metrics
        self._now = now
        self._sleep = sleep
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- logging/metrics helpers ----------------------------------------------

    def _log(self, msg: str) -> None:
        if self.logger is not None:
            self.logger.warn(f"autoscaler: {msg}")

    def _count(self, name: str, **labels: Any) -> None:
        if self.metrics is not None:
            self.metrics.increment_counter(name, 1, **labels)

    # -- one tick --------------------------------------------------------------

    def step(self, now: float | None = None) -> str:
        """One decision tick. Safe to call directly (the fake-clock tests
        and the trace-driven bench do); ``run()`` just calls it on a
        timer. Returns the decision taken."""
        t = self._now() if now is None else now
        try:
            sig = self._signals()
        except Exception as e:  # noqa: BLE001 - dead signal source == stale
            self._log(f"signal source failed ({e!r}); freezing decisions")
            sig = FleetSignals(burn=None, predicted_wait_s=0.0,
                               replicas=self.driver.count(),
                               age_s=self.policy.stale_s + 1.0)
        decision = self.decider.decide(sig, t)
        self._count("app_fleet_autoscale_decisions_total", decision=decision)
        if decision == "out":
            self._scale_out()
        elif decision == "in":
            self._scale_in()
        if self.metrics is not None:
            self.metrics.set_gauge("app_fleet_replicas", self.driver.count())
        return decision

    def _scale_out(self) -> str | None:
        p = self.policy
        delay = p.spawn_backoff_s
        try:
            for attempt in range(1, max(1, p.spawn_retries) + 1):
                try:
                    chaos.fire("autoscale.spawn", attempt=attempt)
                    name = self.driver.spawn()
                    self._log(f"scaled out: spawned {name} "
                              f"({self.driver.count()} replicas)")
                    return name
                except Exception as e:  # noqa: BLE001 - injected or real
                    self._count("app_fleet_autoscale_spawn_failures_total")
                    self._log(f"spawn attempt {attempt}/{p.spawn_retries} "
                              f"failed: {e!r}")
                    if attempt >= p.spawn_retries:
                        return None
                    self._sleep(min(delay, p.spawn_backoff_cap_s))
                    delay *= 2
            return None
        finally:
            # cooldown engages whether or not the spawn landed: a driver
            # whose spawns keep failing must not be hammered every tick
            self.decider.note_action(self._now())

    def _scale_in(self) -> str | None:
        victim = self.driver.pick_victim()
        if victim is None:
            return None
        try:
            ok = self.driver.drain(victim, self.policy.drain_timeout_s)
        except Exception as e:  # noqa: BLE001 - chaos or real death mid-drain
            ok = False
            self._log(f"drain of {victim} aborted ({e!r}); re-admitting")
        if not ok:
            self._count("app_fleet_autoscale_drain_aborts_total")
            try:
                self.driver.readmit(victim)
            except Exception as e:  # noqa: BLE001 - replica truly gone
                self._log(f"re-admit of {victim} failed: {e!r}")
            self.decider.note_action(self._now())
            return None
        self.driver.retire(victim)
        self._log(f"scaled in: retired {victim} "
                  f"({self.driver.count()} replicas)")
        self.decider.note_action(self._now())
        return victim

    # -- loop lifecycle --------------------------------------------------------

    def run(self) -> None:
        while not self._stop.is_set():
            try:
                self.step()
            except Exception as e:  # noqa: BLE001 - the loop must stay live
                self._log(f"tick failed: {e!r}")
            self._stop.wait(self.policy.interval_s)

    def start(self) -> "Autoscaler":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self.run, name="gofr-autoscaler", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2 * self.policy.interval_s + 1.0)


# -- zero-drop requeue ----------------------------------------------------------


def requeue(requests, peer) -> int:
    """Move drained-but-unserved Requests onto ``peer``'s queue — the
    Request OBJECTS move, so caller handles, stream queues, deadlines and
    accumulated kw (QoS class, preemption history) all survive; tokens
    start flowing from the peer the moment it admits them. Cancelled or
    already-expired requests complete immediately instead of travelling;
    with no peer everything left completes with a retryable 503 (shed, not
    dropped: the caller gets a definitive answer either way)."""
    from gofr_tpu.http.errors import RequestTimeout, ServiceUnavailable

    now = time.monotonic()
    moved = 0
    for req in requests:
        if req.cancelled or req.expired(now):
            req.complete(error=RequestTimeout())
        elif peer is None:
            req.complete(error=ServiceUnavailable(
                "replica drained with no peer to requeue to", retry_after=1.0))
        else:
            peer._queue.put(req)
            moved += 1
    if moved and peer is not None and getattr(peer, "metrics", None) is not None:
        peer.metrics.increment_counter("app_fleet_requeued_total", moved)
    return moved


# -- in-process driver -----------------------------------------------------------


class LocalEngineFleet:
    """In-process replica set: one warmed ``GenerateEngine`` per replica,
    built by ``factory(name)`` (the factory pre-loads weights and warms
    against the shared ``GOFR_AUTOTUNE_CACHE``, which is what makes the
    spare *warm*). Membership transitions are mirrored into an optional
    ``ReplicaRegistry`` with the SAME observe() messages gossip would
    carry — UP at a bumped epoch on spawn, ``draining`` during scale-in,
    terminal DOWN on retire — so the PR 7 ring moves keys exactly as it
    would across processes. The process tier swaps this driver for
    ``FleetSupervisor`` members without touching the control loop."""

    def __init__(self, factory: Callable[[str], Any], *, registry=None,
                 name_prefix: str = "rep", logger=None):
        self.factory = factory
        self.registry = registry
        self.logger = logger
        self.name_prefix = name_prefix
        self.replicas: dict[str, Any] = {}
        self._counter = 0
        self._epoch = 0
        self._lock = threading.Lock()

    # -- registry mirroring ----------------------------------------------------

    def _observe(self, name: str, **over: Any) -> None:
        if self.registry is None:
            return
        msg = {"replica": name, "url": f"local://{name}", "status": "UP",
               "epoch": self._epoch, "ts": time.time()}
        msg.update(over)
        self.registry.observe(msg)

    # -- driver protocol -------------------------------------------------------

    def count(self) -> int:
        with self._lock:
            return len(self.replicas)

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self.replicas)

    def engine(self, name: str):
        with self._lock:
            return self.replicas[name]

    def engines(self) -> list[Any]:
        with self._lock:
            return list(self.replicas.values())

    def spawn(self) -> str:
        with self._lock:
            name = f"{self.name_prefix}{self._counter}"
            self._counter += 1
        eng = self.factory(name)  # warm: weights + autotune pins pre-loaded
        with self._lock:
            self.replicas[name] = eng
            self._epoch += 1  # gossip admits the spare at a bumped epoch
        self._observe(name)
        return name

    def pick_victim(self) -> str | None:
        """The cooling replica: the LIGHTEST backlog loses its slot —
        draining it strands the least in-flight work, and ties break to
        the newest name so the fleet contracts in spawn order."""
        with self._lock:
            if len(self.replicas) <= 1:
                return None
            return min(sorted(self.replicas, reverse=True),
                       key=lambda n: self.replicas[n]._backlog())

    def drain(self, name: str, timeout_s: float) -> bool:
        """Registry first (router stops routing new work), then the engine
        drain (in-flight streams finish; queued work comes back), then the
        zero-drop requeue onto a surviving peer."""
        eng = self.engine(name)
        self._observe(name, draining=True)
        pending = eng.drain(timeout_s=timeout_s)  # chaos "replica.drain" fires inside
        peers = [e for n, e in self.replicas.items() if n != name]
        requeue(pending, peers[0] if peers else None)
        return True

    def readmit(self, name: str) -> None:
        """Drain abort (death-mid-drain chaos, or a drain that failed):
        the replica goes back to serving — engine flag cleared, registry
        told it is UP and not draining."""
        eng = self.replicas.get(name)
        if eng is not None and hasattr(eng, "abort_drain"):
            eng.abort_drain()
        self._observe(name, draining=False)

    def retire(self, name: str) -> None:
        with self._lock:
            eng = self.replicas.pop(name, None)
        if eng is not None:
            eng.stop()
        self._observe(name, status="DOWN")

    def stop_all(self) -> None:
        for name in self.names():
            self.retire(name)

    # -- signal helpers --------------------------------------------------------

    def max_predicted_wait(self, qos=None) -> float:
        """Worst queue-wait estimate across replicas: through the bound
        AdmissionController when QoS is wired, else a backlog-only
        estimate (steps of work per lane x a nominal step)."""
        worst = 0.0
        for eng in self.engines():
            ctl = qos or getattr(eng, "qos", None)
            if ctl is not None:
                worst = max(worst, ctl.predicted_wait(eng))
            else:
                lanes = max(1, int(getattr(eng, "num_slots", 1)))
                import math

                worst = max(worst, 0.05 * math.ceil(eng._backlog() / lanes))
        return worst
