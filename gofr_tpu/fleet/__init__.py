"""Elastic fleet subsystem: N-process lockstep serving that survives
member death (docs/parallelism.md "Fleet" section).

Pieces:

- :mod:`gofr_tpu.fleet.channel` — the host-side (DCN) announce transport:
  followers dial the leader, frames carry the fleet epoch, membership
  changes happen at step boundaries outside the compiled programs;
- :mod:`gofr_tpu.fleet.supervisor` — the watchdog→restart→warm-rejoin
  loop for one fleet process (exit-17 aware, sliding-window restart
  budget), plus :class:`FleetSupervisor`'s fleet-wide monotonic
  generation counter;
- :mod:`gofr_tpu.fleet.autoscaler` — the SLO-driven elastic control
  loop (burn-rate/predicted-wait pressure → warm-spare spawn; calm →
  zero-drop drain + retire) with hysteresis, cooldowns and a replica
  clamp (``FLEET_AUTOSCALE_*``, docs/resilience.md);
- :mod:`gofr_tpu.fleet.chaos` — deterministic fault injection at named
  points (``GOFR_CHAOS``), used by the failure-contract tests only and
  zero-cost when unset.

Config (docs/configs.md):

    FLEET_LISTEN             leader: TCP port followers dial (role=leader)
    FLEET_LEADER             follower: leader host:port (role=follower)
    FLEET_FOLLOWERS          leader: follower count to wait for at bring-up
    FLEET_EPOCH              starting epoch (a supervisor passes the
                             process generation here so every life starts
                             at a fresh epoch base)
    FLEET_READY_TIMEOUT_S    leader bring-up wait for followers (default 60)
    FLEET_CONNECT_TIMEOUT_S  follower initial dial window (default 60)
    FLEET_REJOIN_S           follower redial window after leader loss
                             (default 30; expiry = leader-lost, exit 17)

The engine wires itself into a fleet when these keys are set
(tpu/engine.py ``build_engine``); the collective (device-fabric) lockstep
keeps its v1 group-fatal semantics and ignores this module entirely.
"""

from __future__ import annotations

from dataclasses import dataclass

from gofr_tpu.fleet.channel import (
    ChannelClosed,
    CollectiveChannel,
    FleetFollowerChannel,
    FleetLeaderChannel,
    FleetProtocolError,
    fingerprint_of,
)
from gofr_tpu.fleet.autoscaler import (
    AutoscalePolicy,
    Autoscaler,
    FleetSignals,
    LocalEngineFleet,
    ScaleDecider,
    requeue,
)
from gofr_tpu.fleet.supervisor import FleetSupervisor, Supervisor

__all__ = [
    "AutoscalePolicy",
    "Autoscaler",
    "ChannelClosed",
    "CollectiveChannel",
    "FleetConfig",
    "FleetFollowerChannel",
    "FleetLeaderChannel",
    "FleetProtocolError",
    "FleetSignals",
    "FleetSupervisor",
    "LocalEngineFleet",
    "ScaleDecider",
    "Supervisor",
    "epoch_of",
    "fingerprint_of",
    "requeue",
]


def epoch_of(engine) -> int:
    """Membership/restart generation of ``engine``, for the router's
    health/epoch gossip (router/gossip.py): the fleet epoch when the engine
    fronts a fleet (leader lockstep epoch — bumped at every membership
    change and every warm rejoin), else its device-loop restart count.
    Both move exactly when the replica's per-epoch device state was
    rebuilt, which is what ring re-admission keys on (router/registry.py:
    a replica dropped during its restart window must come back at a
    strictly bumped epoch). Max of the two on a fleet leader: a restart IS
    an epoch bump there, but the counters can briefly disagree mid-window.
    Live weight hot-swaps (engine.adopt_weights) ADD their own counter on
    top: an adoption rebuilds per-epoch device state the same way, and the
    router must see a strictly bumped epoch so it never keeps routing a
    sticky (tenant, adapter) ring slot across mismatched weights."""
    ls = getattr(engine, "_ls", None)
    epoch = int(getattr(ls, "epoch", 0) or 0)
    base = max(epoch, int(getattr(engine, "_restarts", 0) or 0))
    return base + int(getattr(engine, "weights_epoch", 0) or 0)


@dataclass
class FleetConfig:
    """Resolved ``FLEET_*`` config for one process (None = not a fleet)."""

    role: str                       # "leader" | "follower"
    listen: int = 0                 # leader listen port (0 = ephemeral)
    leader: str = ""                # follower: leader host:port
    followers: int = 0              # leader: bring-up expectation
    epoch: int = 0
    ready_timeout_s: float = 60.0
    connect_timeout_s: float = 60.0
    rejoin_timeout_s: float = 30.0

    @classmethod
    def from_config(cls, conf) -> "FleetConfig | None":
        listen = conf.get("FLEET_LISTEN")
        leader = conf.get("FLEET_LEADER")
        if not listen and not leader:
            return None
        if listen and leader:
            raise ValueError(
                "FLEET_LISTEN and FLEET_LEADER are mutually exclusive: a "
                "process is the leader (listens) or a follower (dials)")
        return cls(
            role="leader" if listen else "follower",
            listen=int(listen) if listen else 0,
            leader=leader or "",
            followers=conf.get_int("FLEET_FOLLOWERS", 0),
            epoch=conf.get_int("FLEET_EPOCH", 0),
            ready_timeout_s=conf.get_float("FLEET_READY_TIMEOUT_S", 60.0),
            connect_timeout_s=conf.get_float("FLEET_CONNECT_TIMEOUT_S", 60.0),
            rejoin_timeout_s=conf.get_float("FLEET_REJOIN_S", 30.0),
        )
