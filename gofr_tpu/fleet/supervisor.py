"""Process supervision for fleet members: the VERDICT #4 runbook as code.

A fleet process dies in one of three recognizable ways:

- **exit 0** — clean stop (leader announced STOP, follower drained): done;
- **exit 17** (``LOCKSTEP_EXIT_CODE``) — the follower's liveness watchdog
  declared the leader dead after the rejoin deadline. The right response
  is a *restart into rejoin-wait*: the fresh follower redials the
  leader's endpoint and joins the next epoch (fleet/channel.py);
- **any other code / signal** — a crash (device fault, OOM, kill -9).
  Restart with the same config; the channel handshake plus the epoch
  bump make the rejoin safe without state transfer (weights re-init from
  the same seed; the announce channel is the only state that matters).

The restart budget is windowed like the engine's device-loop budget:
only crashes inside the trailing ``window_s`` count against it — the
give-up exists for crash LOOPS, not lifetime fault totals. The budget is
a true sliding window (a deque of crash timestamps pruned to the
window), not a reset-on-gap counter: a slow steady drip of isolated
faults each a few minutes apart never exhausts it, because no single
window ever holds more than a couple of crashes. Each respawn passes
the new generation number to ``spawn`` so the process can derive its
base fleet epoch (``FLEET_EPOCH``) and logs can correlate lives;
:class:`FleetSupervisor` hands all members ONE shared monotonic counter
so rapid kill/rejoin across different members can never reuse an epoch.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Callable, Iterable

# == tpu.lockstep.LOCKSTEP_EXIT_CODE; literal here because lockstep imports
# the fleet package (chaos hooks) and this module must stay import-light
LOCKSTEP_EXIT_CODE = 17


class Supervisor:
    """Supervise ONE fleet process (leader or follower).

    ``spawn(generation) -> Popen-like`` starts the process; the returned
    object needs ``wait(timeout)``/``poll()``/``returncode`` and
    ``terminate()``/``kill()`` (subprocess.Popen satisfies all of it).
    ``run()`` blocks until the process exits cleanly, the budget is
    exhausted, or ``stop()`` is called; it returns the last exit code.
    """

    def __init__(self, spawn: Callable[[int], Any], *, name: str = "fleet-proc",
                 max_restarts: int = 3, window_s: float = 300.0,
                 backoff_s: float = 0.5, backoff_cap_s: float = 10.0,
                 restart_on: Callable[[int], bool] | None = None,
                 logger=None, metrics=None,
                 now: Callable[[], float] = time.monotonic):
        self.spawn = spawn
        self.name = name
        self.max_restarts = max_restarts
        self.window_s = window_s
        self.backoff_s = backoff_s
        self.backoff_cap_s = backoff_cap_s
        self.restart_on = restart_on or (lambda rc: rc != 0)
        self.logger = logger
        self.metrics = metrics
        self.generation = 0
        self.restarts = 0
        self.proc: Any = None
        self._stop = threading.Event()
        self._now = now
        self._crashes: collections.deque[float] = collections.deque()

    def _crashes_in_window(self, now: float) -> int:
        """Record a crash at ``now`` and return how many crashes the
        trailing window holds (sliding, not reset-on-gap: see module doc)."""
        self._crashes.append(now)
        while self._crashes and now - self._crashes[0] > self.window_s:
            self._crashes.popleft()
        return len(self._crashes)

    # -- lifecycle -------------------------------------------------------------

    def _log(self, msg: str) -> None:
        if self.logger is not None:
            self.logger.warn(f"supervisor[{self.name}]: {msg}")

    def run(self) -> int:
        """The watchdog→restart→warm-rejoin loop. Returns the supervised
        process's final exit code (0 = clean stop)."""
        self.proc = self.spawn(self.generation)
        while True:
            while self.proc.poll() is None:
                if self._stop.wait(0.05):
                    self._log("stop requested; terminating child")
                    self.proc.terminate()
                    try:
                        self.proc.wait(timeout=10)
                    except Exception:  # noqa: BLE001 - unkillable child
                        self.proc.kill()
                        self.proc.wait()
                    return int(self.proc.returncode or 0)
            rc = int(self.proc.returncode)
            if rc == 0:
                self._log(f"generation {self.generation} exited cleanly")
                return 0
            if not self.restart_on(rc):
                self._log(f"generation {self.generation} exited {rc}; policy says no restart")
                return rc
            in_window = self._crashes_in_window(self._now())
            if in_window > self.max_restarts:
                self._log(
                    f"generation {self.generation} exited {rc}; restart budget "
                    f"({self.max_restarts} within {self.window_s:.0f}s) exhausted — giving up")
                return rc
            self.restarts = in_window
            why = ("liveness watchdog: leader presumed dead — restarting into rejoin-wait"
                   if rc == LOCKSTEP_EXIT_CODE else f"crash (exit {rc})")
            delay = min(self.backoff_s * (2 ** (self.restarts - 1)), self.backoff_cap_s)
            self._log(
                f"generation {self.generation} died: {why}; restart "
                f"{self.restarts}/{self.max_restarts} in {delay:.2f}s")
            if self._stop.wait(delay):
                return rc
            if self.metrics is not None:
                self.metrics.increment_counter("app_fleet_supervisor_restarts_total", 1)
            self.generation += 1
            self.proc = self.spawn(self.generation)

    def start(self) -> threading.Thread:
        """Run the supervision loop on a daemon thread (the in-app shape);
        the returned thread's liveness is the fleet member's liveness."""
        t = threading.Thread(target=self.run, name=f"supervisor-{self.name}", daemon=True)
        t.start()
        return t

    def stop(self) -> None:
        self._stop.set()


class FleetSupervisor:
    """Supervise N named fleet members with ONE shared, lock-protected,
    strictly monotonic generation counter. Every spawn — initial bring-up
    or post-crash respawn of ANY member — draws the next number, so the
    ``FLEET_EPOCH`` base derived from it can never be reused even under
    rapid kill/rejoin across different members (two replicas crashing in
    the same window get distinct, ordered generations; a ring re-admission
    gate keyed on a strictly bumped epoch therefore always passes for the
    newer life and never for a stale one).

    ``spawn_member(name, generation) -> Popen-like`` starts one member;
    the autoscaler drives the same protocol at a higher level, and each
    member individually keeps the windowed restart budget of
    :class:`Supervisor`.
    """

    def __init__(self, spawn_member: Callable[[str, int], Any], *,
                 members: Iterable[str], logger=None, metrics=None,
                 now: Callable[[], float] = time.monotonic, **supervisor_kw):
        self.spawn_member = spawn_member
        self._lock = threading.Lock()
        self._generation = 0
        self.members: dict[str, Supervisor] = {}
        for name in members:
            self.members[name] = Supervisor(
                self._spawner(name), name=name, logger=logger,
                metrics=metrics, now=now, **supervisor_kw)

    @property
    def generation(self) -> int:
        with self._lock:
            return self._generation

    def next_generation(self) -> int:
        with self._lock:
            self._generation += 1
            return self._generation

    def _spawner(self, name: str) -> Callable[[int], Any]:
        # the member Supervisor's own per-life counter is ignored on
        # purpose: the FLEET-WIDE counter is the monotonicity contract
        def spawn(_local_generation: int) -> Any:
            return self.spawn_member(name, self.next_generation())
        return spawn

    def start(self) -> dict[str, threading.Thread]:
        return {name: sup.start() for name, sup in self.members.items()}

    def stop(self) -> None:
        for sup in self.members.values():
            sup.stop()
