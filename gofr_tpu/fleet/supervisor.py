"""Process supervision for fleet members: the VERDICT #4 runbook as code.

A fleet process dies in one of three recognizable ways:

- **exit 0** — clean stop (leader announced STOP, follower drained): done;
- **exit 17** (``LOCKSTEP_EXIT_CODE``) — the follower's liveness watchdog
  declared the leader dead after the rejoin deadline. The right response
  is a *restart into rejoin-wait*: the fresh follower redials the
  leader's endpoint and joins the next epoch (fleet/channel.py);
- **any other code / signal** — a crash (device fault, OOM, kill -9).
  Restart with the same config; the channel handshake plus the epoch
  bump make the rejoin safe without state transfer (weights re-init from
  the same seed; the announce channel is the only state that matters).

The restart budget is windowed like the engine's device-loop budget:
crashes further apart than ``window_s`` don't count against it — the
give-up exists for crash LOOPS, not lifetime fault totals. Each respawn
passes the new generation number to ``spawn`` so the process can derive
its base fleet epoch (``FLEET_EPOCH``) and logs can correlate lives.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable

# == tpu.lockstep.LOCKSTEP_EXIT_CODE; literal here because lockstep imports
# the fleet package (chaos hooks) and this module must stay import-light
LOCKSTEP_EXIT_CODE = 17


class Supervisor:
    """Supervise ONE fleet process (leader or follower).

    ``spawn(generation) -> Popen-like`` starts the process; the returned
    object needs ``wait(timeout)``/``poll()``/``returncode`` and
    ``terminate()``/``kill()`` (subprocess.Popen satisfies all of it).
    ``run()`` blocks until the process exits cleanly, the budget is
    exhausted, or ``stop()`` is called; it returns the last exit code.
    """

    def __init__(self, spawn: Callable[[int], Any], *, name: str = "fleet-proc",
                 max_restarts: int = 3, window_s: float = 300.0,
                 backoff_s: float = 0.5, backoff_cap_s: float = 10.0,
                 restart_on: Callable[[int], bool] | None = None,
                 logger=None, metrics=None):
        self.spawn = spawn
        self.name = name
        self.max_restarts = max_restarts
        self.window_s = window_s
        self.backoff_s = backoff_s
        self.backoff_cap_s = backoff_cap_s
        self.restart_on = restart_on or (lambda rc: rc != 0)
        self.logger = logger
        self.metrics = metrics
        self.generation = 0
        self.restarts = 0
        self.proc: Any = None
        self._stop = threading.Event()
        self._last_crash_at = 0.0

    # -- lifecycle -------------------------------------------------------------

    def _log(self, msg: str) -> None:
        if self.logger is not None:
            self.logger.warn(f"supervisor[{self.name}]: {msg}")

    def run(self) -> int:
        """The watchdog→restart→warm-rejoin loop. Returns the supervised
        process's final exit code (0 = clean stop)."""
        self.proc = self.spawn(self.generation)
        while True:
            while self.proc.poll() is None:
                if self._stop.wait(0.05):
                    self._log("stop requested; terminating child")
                    self.proc.terminate()
                    try:
                        self.proc.wait(timeout=10)
                    except Exception:  # noqa: BLE001 - unkillable child
                        self.proc.kill()
                        self.proc.wait()
                    return int(self.proc.returncode or 0)
            rc = int(self.proc.returncode)
            if rc == 0:
                self._log(f"generation {self.generation} exited cleanly")
                return 0
            if not self.restart_on(rc):
                self._log(f"generation {self.generation} exited {rc}; policy says no restart")
                return rc
            now = time.monotonic()
            if now - self._last_crash_at > self.window_s:
                self.restarts = 0  # isolated fault, not a crash loop
            self._last_crash_at = now
            if self.restarts >= self.max_restarts:
                self._log(
                    f"generation {self.generation} exited {rc}; restart budget "
                    f"({self.max_restarts} within {self.window_s:.0f}s) exhausted — giving up")
                return rc
            self.restarts += 1
            why = ("liveness watchdog: leader presumed dead — restarting into rejoin-wait"
                   if rc == LOCKSTEP_EXIT_CODE else f"crash (exit {rc})")
            delay = min(self.backoff_s * (2 ** (self.restarts - 1)), self.backoff_cap_s)
            self._log(
                f"generation {self.generation} died: {why}; restart "
                f"{self.restarts}/{self.max_restarts} in {delay:.2f}s")
            if self._stop.wait(delay):
                return rc
            if self.metrics is not None:
                self.metrics.increment_counter("app_fleet_supervisor_restarts_total", 1)
            self.generation += 1
            self.proc = self.spawn(self.generation)

    def start(self) -> threading.Thread:
        """Run the supervision loop on a daemon thread (the in-app shape);
        the returned thread's liveness is the fleet member's liveness."""
        t = threading.Thread(target=self.run, name=f"supervisor-{self.name}", daemon=True)
        t.start()
        return t

    def stop(self) -> None:
        self._stop.set()
