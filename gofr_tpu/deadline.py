"""Request-lifetime plane: one absolute deadline carried end to end.

A client (or the router on its behalf) stamps an absolute wall-clock
deadline on the request as ``X-Request-Deadline-Ms`` (unix epoch
milliseconds); gRPC callers get the same effect from the native RPC
deadline. Every tier converts the wire form ONCE at ingress to a
*monotonic* deadline — immune to wall-clock steps — and hands the
remaining budget down:

- the HTTP edge parses the header into the per-request context
  (``app._materialize``) and sheds already-expired work with 504;
- the gRPC edge reads ``servicer_context.time_remaining()`` into the
  same context slot;
- the router re-stamps the header shrunk by ``DEADLINE_HOP_MARGIN_MS``
  before proxying, so a replica never starts work its caller cannot
  wait for;
- ``Context`` folds the remaining budget into the engine timeout, so
  the QoS predicted-wait check sheds doomed work with
  504/``deadline_exceeded`` before it ever takes a slot.

See docs/resilience.md for the full model.
"""

from __future__ import annotations

import time
from typing import Any

# absolute deadline, unix epoch milliseconds
DEADLINE_HEADER = "X-Request-Deadline-Ms"

# per-request context slot: monotonic seconds (time.monotonic() domain)
CTX_KEY = "deadline_at"


def parse_deadline_ms(value: Any) -> float | None:
    """Wire form (absolute epoch ms) -> monotonic deadline in seconds,
    or None when absent or malformed. A garbage deadline must never 500
    the request — it degrades to 'no deadline'."""
    if value is None or value == "":
        return None
    try:
        wall_remaining = float(value) / 1000.0 - time.time()
    except (TypeError, ValueError):
        return None
    return time.monotonic() + wall_remaining


def header_value(deadline_at: float, margin_s: float = 0.0) -> str:
    """Monotonic deadline -> the absolute epoch-ms wire form, shrunk by
    ``margin_s`` (the router's per-hop safety margin: the upstream must
    answer early enough for the proxy to still relay the response)."""
    wall = time.time() + (deadline_at - time.monotonic()) - margin_s
    return str(int(wall * 1000.0))


def set_deadline(ctx: dict, deadline_at: float | None) -> None:
    """Record a monotonic deadline on a per-request context dict."""
    if deadline_at is not None:
        ctx[CTX_KEY] = float(deadline_at)


def deadline_of(ctx: dict) -> float | None:
    return ctx.get(CTX_KEY)


def remaining(ctx: dict, now: float | None = None) -> float | None:
    """Remaining budget in seconds (can be <= 0 once expired); None when
    the request carries no deadline."""
    at = ctx.get(CTX_KEY)
    if at is None:
        return None
    return at - (time.monotonic() if now is None else now)
