"""Checkpoint / resume (orbax-backed).

The reference's only durable-progress machinery is the versioned migration
table (SURVEY.md §5.4); the TPU build needs real state checkpointing:

- training: save/restore the full TrainState (params + optimizer moments +
  step) with shardings preserved — restore places every leaf back on the
  same mesh layout, so resume works across process restarts on the same
  topology (and across topologies by passing different shardings).
- serving: ``save_params`` / ``load_params`` let ModelSpec.weights point at
  a checkpoint directory instead of an HF id (engine.build_engine).

Layout: ``<dir>/<step>/state`` via orbax CheckpointManager — idempotent
re-run semantics like the migration runner (skip ≤ last applied;
`migration.go:55-62` analog: ``latest_step`` + ``restore``).
"""

from __future__ import annotations

import os
from typing import Any

import jax
import orbax.checkpoint as ocp


def _manager(directory: str, max_to_keep: int | None = 3) -> ocp.CheckpointManager:
    return ocp.CheckpointManager(
        os.path.abspath(directory),
        options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep, create=True),
    )


def save_checkpoint(directory: str, state: Any, step: int | None = None,
                    max_to_keep: int | None = 3) -> int:
    """Save a pytree (e.g. TrainState) at ``step`` (default: state.step).
    Returns the step saved. Blocks until the write is durable."""
    if step is None:
        step = int(jax.device_get(getattr(state, "step", 0)))
    with _manager(directory, max_to_keep) as mgr:
        mgr.save(step, args=ocp.args.StandardSave(state))
        mgr.wait_until_finished()
    return step


def latest_step(directory: str) -> int | None:
    """Newest saved step, or None when the directory holds no checkpoints."""
    if not os.path.isdir(directory):
        return None
    with _manager(directory, None) as mgr:
        return mgr.latest_step()


def restore_checkpoint(directory: str, target: Any, step: int | None = None) -> Any:
    """Restore into the structure/shardings of ``target`` (a concrete pytree
    or jax.eval_shape result with shardings). ``step`` defaults to latest."""
    with _manager(directory, None) as mgr:
        if step is None:
            step = mgr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {directory!r}")
        return mgr.restore(step, args=ocp.args.StandardRestore(target))


def save_params(directory: str, params: Any) -> None:
    """Serving-weights save: bare param pytree at step 0."""
    save_checkpoint(directory, params, step=0, max_to_keep=1)


def load_params(directory: str, like: Any) -> Any:
    """Serving-weights load shaped/sharded like ``like`` (an abstract or
    concrete param pytree)."""
    return restore_checkpoint(directory, like)


def is_checkpoint_dir(path: str) -> bool:
    """Heuristic used by build_engine to tell a checkpoint directory from an
    HF model id: a local dir containing at least one numeric step DIRECTORY
    that itself holds orbax items. A bare numeric file (e.g. in a local HF
    snapshot) must not divert weights away from the HF converter (ADVICE.md)."""
    if not os.path.isdir(path):
        return False
    for name in os.listdir(path):
        step_dir = os.path.join(path, name)
        if name.isdigit() and os.path.isdir(step_dir) and os.listdir(step_dir):
            return True
    return False
