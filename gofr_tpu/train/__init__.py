"""Distributed training: sharded train step over the device mesh.

The reference is a serving framework with no training surface (SURVEY.md
§2.9) — this subsystem exists so gofr_tpu models can be fine-tuned /
trained on the same mesh they serve from. One ``make_train_step`` builds a
pjit-style compiled step with explicit in/out shardings derived from the
model's logical param axes: dp/fsdp shard the batch (and weights, for
fsdp), tp shards heads/mlp/vocab — XLA inserts the ICI collectives
(psum for grads over dp, all-gathers for fsdp params) per GSPMD.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from gofr_tpu.parallel import ShardingRules, logical_sharding
from gofr_tpu.parallel.sharding import sharding_tree


def cross_entropy_loss(logits: jnp.ndarray, targets: jnp.ndarray,
                       mask: jnp.ndarray | None = None) -> jnp.ndarray:
    """Mean next-token cross entropy. logits [B,S,V] (f32), targets [B,S],
    mask [B,S] (1 = count this position)."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    if mask is None:
        return jnp.mean(nll)
    mask = mask.astype(jnp.float32)
    return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)


@jax.tree_util.register_dataclass
@dataclass
class TrainState:
    params: Any
    opt_state: Any
    step: jnp.ndarray


def make_train_step(
    cfg: Any,
    family: Any,
    mesh: Mesh,
    rules: ShardingRules | None = None,
    optimizer: optax.GradientTransformation | None = None,
    remat: bool = False,
):
    """Build ``(init_fn, step_fn)`` compiled over ``mesh``.

    - ``init_fn(key) -> TrainState`` with every leaf placed per the
      model's logical axes (params AND optimizer moments shard alike).
    - ``step_fn(state, tokens, lengths) -> (state, metrics)`` — next-token
      LM loss on ``tokens`` [B,S]; batch dim sharded over (dp, fsdp).

    ``remat=True`` wraps the forward in ``jax.checkpoint`` to trade FLOPs
    for HBM (rematerialize activations in the backward pass).
    """
    rules = rules or ShardingRules()
    optimizer = optimizer or optax.adamw(3e-4, weight_decay=0.01)
    axes = family.param_axes(cfg)
    param_sh = sharding_tree(axes, rules, mesh)
    batch_spec = rules.spec(("batch", None), mesh)
    batch_sh = NamedSharding(mesh, batch_spec)
    len_sh = NamedSharding(mesh, P(batch_spec[0]))
    scalar_sh = NamedSharding(mesh, P())

    def fwd(params, tokens, lengths):
        return family.forward(cfg, params, tokens, lengths)

    if remat:
        fwd = jax.checkpoint(fwd)

    def loss_fn(params, tokens, lengths):
        logits = fwd(params, tokens, lengths)
        mask = (jnp.arange(tokens.shape[1])[None] < lengths[:, None] - 1).astype(jnp.float32)
        # predict token t+1 from position t
        return cross_entropy_loss(logits[:, :-1], tokens[:, 1:], mask[:, : tokens.shape[1] - 1])

    def _init(key):
        params = family.init(cfg, key)
        return TrainState(params=params, opt_state=optimizer.init(params), step=jnp.zeros((), jnp.int32))

    # opt_state mirrors params leaf-for-leaf (adam moments) plus scalar
    # counters — derive its shardings by shape-matching against params.
    state_shape = jax.eval_shape(_init, jax.random.key(0))

    flat_param_sh = jax.tree.leaves(param_sh)
    flat_param_shapes = [tuple(x.shape) for x in jax.tree.leaves(state_shape.params)]
    shape_to_sh = {}
    for shp, sh in zip(flat_param_shapes, flat_param_sh):
        shape_to_sh.setdefault(shp, sh)

    def leaf_sharding(leaf):
        return shape_to_sh.get(tuple(leaf.shape), scalar_sh)

    opt_sh = jax.tree.map(leaf_sharding, state_shape.opt_state)
    state_sh = TrainState(params=param_sh, opt_state=opt_sh, step=scalar_sh)

    init_fn = jax.jit(_init, out_shardings=state_sh)

    def _step(state: TrainState, tokens, lengths):
        loss, grads = jax.value_and_grad(loss_fn)(state.params, tokens, lengths)
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        gnorm = optax.global_norm(grads)
        new_state = TrainState(params=params, opt_state=opt_state, step=state.step + 1)
        return new_state, {"loss": loss, "grad_norm": gnorm}

    step_fn = jax.jit(
        _step,
        in_shardings=(state_sh, batch_sh, len_sh),
        out_shardings=(state_sh, {"loss": scalar_sh, "grad_norm": scalar_sh}),
        donate_argnums=0,
    )
    return init_fn, step_fn
