"""Distributed training: sharded train step over the device mesh.

The reference is a serving framework with no training surface (SURVEY.md
§2.9) — this subsystem exists so gofr_tpu models can be fine-tuned /
trained on the same mesh they serve from. One ``make_train_step`` builds a
pjit-style compiled step with explicit in/out shardings derived from the
model's logical param axes: dp/fsdp shard the batch (and weights, for
fsdp), tp shards heads/mlp/vocab — XLA inserts the ICI collectives
(psum for grads over dp, all-gathers for fsdp params) per GSPMD.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from gofr_tpu.parallel import ShardingRules
from gofr_tpu.parallel.sharding import sharding_tree


def cross_entropy_loss(logits: jnp.ndarray, targets: jnp.ndarray,
                       mask: jnp.ndarray | None = None) -> jnp.ndarray:
    """Mean next-token cross entropy. logits [B,S,V] (f32), targets [B,S],
    mask [B,S] (1 = count this position)."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    if mask is None:
        return jnp.mean(nll)
    mask = mask.astype(jnp.float32)
    return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)


@jax.tree_util.register_dataclass
@dataclass
class TrainState:
    params: Any
    opt_state: Any
    step: jnp.ndarray


def make_train_step(
    cfg: Any,
    family: Any,
    mesh: Mesh,
    rules: ShardingRules | None = None,
    optimizer: optax.GradientTransformation | None = None,
    remat: bool = False,
    seq_parallel: str | None = None,
    pipeline_microbatches: int | None = None,
):
    """Build ``(init_fn, step_fn)`` compiled over ``mesh``.

    - ``init_fn(key) -> TrainState`` with every leaf placed per the
      model's logical axes (params AND optimizer moments shard alike).
    - ``step_fn(state, tokens, lengths) -> (state, metrics)`` — next-token
      LM loss on ``tokens`` [B,S]; batch dim sharded over (dp, fsdp).

    ``remat=True`` wraps the forward in ``jax.checkpoint`` to trade FLOPs
    for HBM (rematerialize activations in the backward pass).

    ``seq_parallel='ring'|'ulysses'`` shards the sequence dimension of
    attention over the mesh's ``sp`` axis (gofr_tpu.parallel.ring) — the
    long-context path; the model family must accept an ``attn_fn``.

    ``pipeline_microbatches=M`` runs the blocks pipeline-parallel over the
    mesh's ``pp`` axis (family must expose ``forward_pipelined``); the
    layers dim of block params shards over pp.
    """
    rules = rules or ShardingRules()
    if pipeline_microbatches and seq_parallel:
        raise ValueError(
            "seq_parallel and pipeline_microbatches cannot be combined yet: "
            "the pipelined stages run dense attention"
        )
    if pipeline_microbatches:
        if "pp" not in mesh.axis_names or mesh.shape["pp"] <= 1:
            raise ValueError("pipeline_microbatches needs a 'pp' mesh axis > 1")
        if not hasattr(family, "forward_pipelined"):
            raise ValueError(f"{family.__name__} does not support pipeline parallelism")
        rules = rules.with_overrides(layers="pp")
    optimizer = optimizer or optax.adamw(3e-4, weight_decay=0.01)
    axes = family.param_axes(cfg)
    param_sh = sharding_tree(axes, rules, mesh)
    batch_spec = rules.spec(("batch", None), mesh)
    batch_sh = NamedSharding(mesh, batch_spec)
    len_sh = NamedSharding(mesh, P(batch_spec[0]))
    scalar_sh = NamedSharding(mesh, P())

    attn_fn = None
    if seq_parallel:
        if "sp" not in mesh.axis_names or mesh.shape["sp"] <= 1:
            raise ValueError(f"seq_parallel={seq_parallel!r} needs an 'sp' mesh axis > 1")
        from gofr_tpu.parallel.ring import make_seq_parallel_attn

        attn_fn = make_seq_parallel_attn(mesh, strategy=seq_parallel)

    # MoE families expose forward_with_aux; the router load-balance term
    # joins the loss scaled by cfg.router_aux_coef.
    with_aux = getattr(family, "forward_with_aux", None)
    aux_coef = float(getattr(cfg, "router_aux_coef", 0.0)) if with_aux else 0.0

    def fwd(params, tokens, lengths):
        if pipeline_microbatches:
            return family.forward_pipelined(
                cfg, params, tokens, lengths, mesh, pipeline_microbatches
            ), {}
        if with_aux is not None:
            return with_aux(cfg, params, tokens, lengths, attn_fn)
        if attn_fn is not None:
            return family.forward(cfg, params, tokens, lengths, attn_fn), {}
        return family.forward(cfg, params, tokens, lengths), {}

    if remat:
        fwd = jax.checkpoint(fwd)

    def loss_fn(params, tokens, lengths):
        logits, aux = fwd(params, tokens, lengths)
        mask = (jnp.arange(tokens.shape[1])[None] < lengths[:, None] - 1).astype(jnp.float32)
        # predict token t+1 from position t
        loss = cross_entropy_loss(logits[:, :-1], tokens[:, 1:], mask[:, : tokens.shape[1] - 1])
        if aux_coef and "load_balance" in aux:
            loss = loss + aux_coef * aux["load_balance"]
        return loss

    def _init(key):
        params = family.init(cfg, key)
        return TrainState(params=params, opt_state=optimizer.init(params), step=jnp.zeros((), jnp.int32))

    # opt_state mirrors params leaf-for-leaf (adam moments) plus scalar
    # counters — derive its shardings by shape-matching against params.
    state_shape = jax.eval_shape(_init, jax.random.key(0))

    flat_param_sh = jax.tree.leaves(param_sh)
    flat_param_shapes = [tuple(x.shape) for x in jax.tree.leaves(state_shape.params)]
    shape_to_sh = {}
    for shp, sh in zip(flat_param_shapes, flat_param_sh):
        shape_to_sh.setdefault(shp, sh)

    def leaf_sharding(leaf):
        return shape_to_sh.get(tuple(leaf.shape), scalar_sh)

    opt_sh = jax.tree.map(leaf_sharding, state_shape.opt_state)
    state_sh = TrainState(params=param_sh, opt_state=opt_sh, step=scalar_sh)

    platform = mesh.devices.flat[0].platform

    def _hinted(f):
        """Trace under the mesh's platform so kernel-backend resolution sees
        where the step actually runs (not jax.default_backend())."""

        def g(*a):
            from gofr_tpu.ops.pallas import platform_hint

            with platform_hint(platform):
                return f(*a)

        return g

    init_fn = _hinted(jax.jit(_init, out_shardings=state_sh))

    def _step(state: TrainState, tokens, lengths):
        loss, grads = jax.value_and_grad(loss_fn)(state.params, tokens, lengths)
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        gnorm = optax.global_norm(grads)
        new_state = TrainState(params=params, opt_state=opt_state, step=state.step + 1)
        return new_state, {"loss": loss, "grad_norm": gnorm}

    step_fn = _hinted(jax.jit(
        _step,
        in_shardings=(state_sh, batch_sh, len_sh),
        out_shardings=(state_sh, {"loss": scalar_sh, "grad_norm": scalar_sh}),
        donate_argnums=0,
    ))
    return init_fn, step_fn
