"""HTTP request wrapper: the HTTP implementation of the transport-neutral
Request interface (gofr `pkg/gofr/http/request.go`).

The server materializes the body BEFORE the handler runs, so ``Request`` is
fully synchronous and safe to hand to sync handlers running in worker threads —
the transport-neutral analog of the reference buffering/re-buffering the body
(`request.go:86-95`).
"""

from __future__ import annotations

import json
from typing import Any, Mapping
from urllib.parse import parse_qs

from gofr_tpu.http.errors import InvalidParam
from gofr_tpu.utils import bind as binder


class Request:
    """Transport-neutral request interface (gofr `pkg/gofr/gofr.go` Request).

    Implementations: HTTPRequest (here), cmd.Request, pubsub.Message,
    websocket.Connection.
    """

    def param(self, key: str) -> str:
        return ""

    def params(self, key: str) -> list[str]:
        return []

    def path_param(self, key: str) -> str:
        return ""

    def bind(self, target: Any) -> Any:
        raise NotImplementedError

    def host_name(self) -> str:
        return ""

    def context(self) -> dict[str, Any]:
        """Per-request values injected by middleware (auth claims etc.)."""
        return {}


class HTTPRequest(Request):
    def __init__(
        self,
        method: str,
        path: str,
        query_string: str = "",
        headers: Mapping[str, str] | None = None,
        body: bytes = b"",
        path_params: Mapping[str, str] | None = None,
        remote: str = "",
        route_template: str = "",
    ):
        self.method = method
        self.path = path
        self.headers = _CIDict(headers or {})
        self.body = body
        self.remote = remote
        self.route_template = route_template or path
        # kept verbatim alongside the parsed form: a proxy tier (router
        # data plane) must forward the query string byte-identical
        self.query_string = query_string
        self._query = parse_qs(query_string, keep_blank_values=True)
        self._path_params = dict(path_params or {})
        self._ctx: dict[str, Any] = {}

    # -- Request interface -----------------------------------------------------

    def param(self, key: str) -> str:
        values = self._query.get(key)
        return values[0] if values else ""

    def params(self, key: str) -> list[str]:
        # comma-split multi-values like the reference's query params
        out: list[str] = []
        for v in self._query.get(key, []):
            out.extend(p for p in v.split(",") if p != "")
        return out

    def path_param(self, key: str) -> str:
        return self._path_params.get(key, "")

    def bind(self, target: Any = dict) -> Any:
        content_type = (self.headers.get("Content-Type") or "").split(";")[0].strip().lower()
        if content_type in ("", "application/json"):
            if not self.body:
                data: Any = {}
            else:
                try:
                    data = json.loads(self.body)
                except json.JSONDecodeError as e:
                    raise InvalidParam("body") from e
            return binder.bind(data, target)
        if content_type == "application/x-www-form-urlencoded":
            form = {k: v[0] if len(v) == 1 else v for k, v in parse_qs(self.body.decode(), keep_blank_values=True).items()}
            return binder.bind(form, target)
        if content_type.startswith("text/"):
            if target in (str, bytes):
                return self.body.decode() if target is str else self.body
            return binder.bind(self.body.decode(), target)
        if content_type == "multipart/form-data":
            from gofr_tpu.http.multipart import bind_multipart

            return bind_multipart(self.headers.get("Content-Type", ""), self.body, target)
        raise InvalidParam("Content-Type")

    def host_name(self) -> str:
        proto = self.headers.get("X-Forwarded-Proto") or "http"
        host = self.headers.get("Host") or ""
        return f"{proto}://{host}" if host else ""

    def context(self) -> dict[str, Any]:
        return self._ctx

    # -- extras ----------------------------------------------------------------

    @property
    def client_ip(self) -> str:
        fwd = self.headers.get("X-Forwarded-For")
        if fwd:
            return fwd.split(",")[0].strip()
        return self.remote


class _CIDict(dict):
    """Case-insensitive header map."""

    def __init__(self, data: Mapping[str, str]):
        super().__init__()
        for k, v in data.items():
            self[k] = v

    def __setitem__(self, key: str, value: str) -> None:
        super().__setitem__(key.lower(), value)

    def __getitem__(self, key: str) -> str:
        return super().__getitem__(key.lower())

    def get(self, key: str, default: str | None = None) -> str | None:  # type: ignore[override]
        return super().get(key.lower(), default)

    def __contains__(self, key: object) -> bool:
        return super().__contains__(str(key).lower())
